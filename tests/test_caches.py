"""Device-residency + assembly caches (the BlockManager/.cache() analog):
identity semantics, weakref lifetime, byte bounds, kill switch."""

import gc

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import VectorAssembler
from sntc_tpu.feature.vector_assembler import _ASSEMBLE_CACHE
from sntc_tpu.parallel.collectives import (
    _DEVICE_CACHE,
    pad_rows,
    shard_batch,
)


def test_pad_rows_buckets_nearby_sizes(monkeypatch):
    # fold-sized datasets land in one bucket -> one compiled program
    a, b = pad_rows(200_000, 8), pad_rows(199_000, 8)
    assert a == b
    # far-apart sizes differ
    assert pad_rows(100_000, 8) != pad_rows(200_000, 8)
    # small inputs are exact (no bucket waste)
    assert pad_rows(100, 4) == 100
    monkeypatch.setenv("SNTC_SHAPE_BUCKETS", "0")
    assert pad_rows(200_001, 8) == 200_008


def test_shard_batch_device_cache_identity(mesh8):
    X = np.random.default_rng(0).normal(size=(5000, 60)).astype(np.float32)
    xs1, _ = shard_batch(mesh8, X)
    xs2, _ = shard_batch(mesh8, X)          # same object -> same buffer
    assert xs1 is xs2
    xs3, _ = shard_batch(mesh8, X.copy())   # equal content, new object
    assert xs3 is not xs1


def test_shard_batch_cache_entry_dies_with_array(mesh8):
    X = np.random.default_rng(1).normal(size=(5000, 60)).astype(np.float32)
    shard_batch(mesh8, X)
    key_count = len(_DEVICE_CACHE)
    assert key_count >= 1
    del X
    gc.collect()
    # next call sweeps dead entries
    Y = np.random.default_rng(2).normal(size=(4000, 60)).astype(np.float32)
    shard_batch(mesh8, Y)
    assert all(e[0]() is not None for e in _DEVICE_CACHE.values())


def test_device_cache_kill_switch(mesh8, monkeypatch):
    monkeypatch.setenv("SNTC_DEVICE_CACHE_MB", "0")
    X = np.random.default_rng(3).normal(size=(5000, 60)).astype(np.float32)
    xs1, _ = shard_batch(mesh8, X)
    xs2, _ = shard_batch(mesh8, X)
    assert xs1 is not xs2


def test_assembler_memo_reuses_stack(monkeypatch):
    # the memo only engages for fit-scale stacks (serving micro-batches
    # skip it); drop the floor so the tiny test frames qualify
    import sntc_tpu.feature.vector_assembler as va_mod

    monkeypatch.setattr(va_mod, "_ASSEMBLE_MEMO_MIN_BYTES", 0)
    cols = {
        "a": np.arange(1000.0, dtype=np.float64),
        "b": np.arange(1000.0, dtype=np.float64) * 2,
    }
    f1 = Frame(cols)
    f2 = f1.with_column("extra", np.zeros(1000))  # shares a/b arrays
    va = VectorAssembler(inputCols=["a", "b"], outputCol="v",
                         handleInvalid="keep")
    X1 = va.transform(f1)["v"]
    X2 = va.transform(f2)["v"]
    assert X1 is X2  # identical column objects -> one stack
    monkeypatch.setenv("SNTC_DEVICE_CACHE_MB", "0")
    X3 = va.transform(f1)["v"]
    assert X3 is not X1


def test_assembler_memo_sweeps_dead_columns(monkeypatch):
    import sntc_tpu.feature.vector_assembler as va_mod

    monkeypatch.setattr(va_mod, "_ASSEMBLE_MEMO_MIN_BYTES", 0)
    big = np.random.default_rng(4).normal(size=(2000,)).astype(np.float64)
    f = Frame({"a": big, "b": big.copy()})
    va = VectorAssembler(inputCols=["a", "b"], outputCol="v",
                         handleInvalid="keep")
    va.transform(f)
    del f, big
    gc.collect()
    va2 = VectorAssembler(inputCols=["a", "b"], outputCol="v",
                          handleInvalid="keep")
    f2 = Frame({"a": np.ones(10), "b": np.ones(10)})
    va2.transform(f2)
    assert all(
        all(r() is not None for r in e[0]) for e in _ASSEMBLE_CACHE.values()
    )
