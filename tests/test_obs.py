"""Unified telemetry substrate (r13, ``sntc_tpu.obs``): metrics
registry semantics (labels, cardinality cap, histogram bucket edges,
snapshot under concurrent writes, exposition), span-tracer ring
behavior and Chrome-trace export, the event→metrics bridge, the
per-engine transfer-ledger attribution, the end-to-end agreement of
one serve run's Prometheus snapshot with the legacy ledger views, and
the metric-name drift check (tier-1 wiring of check_metric_names)."""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Pipeline, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import MinMaxScaler, VectorAssembler
from sntc_tpu.models import LogisticRegression
from sntc_tpu.obs import SpanTracer, disable_tracing, enable_tracing
from sntc_tpu.obs import span as obs_span
from sntc_tpu.obs import tracer as obs_tracer
from sntc_tpu.obs.bridge import split_tenant_site
from sntc_tpu.obs.metrics import CATALOG, MetricsRegistry, registry
from sntc_tpu.serve import (
    MemorySink,
    MemorySource,
    ServeDaemon,
    TenantSpec,
)
from sntc_tpu.utils.profiling import (
    TransferLedger,
    active_ledgers,
    ledger_scope,
    transfer_ledger,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()
    disable_tracing()


def _get(name, **labels):
    return registry().get(name, **labels) or 0


# ---------------------------------------------------------------------------
# MetricsRegistry unit semantics
# ---------------------------------------------------------------------------


def test_counter_gauge_and_labels():
    r = MetricsRegistry()
    r.inc("sntc_rows_committed_total", 5)
    r.inc("sntc_rows_committed_total", 2)
    r.inc("sntc_rows_committed_total", 3, tenant="a")
    r.set_gauge("sntc_health_state", 2, component="engine")
    r.set_gauge("sntc_health_state", 1, component="engine")
    assert r.get("sntc_rows_committed_total") == 7
    assert r.get("sntc_rows_committed_total", tenant="a") == 3
    assert r.get("sntc_health_state", component="engine") == 1
    assert r.get("sntc_rows_committed_total", tenant="nope") is None


def test_registry_rejects_undeclared_names_and_labels():
    r = MetricsRegistry()
    with pytest.raises(KeyError, match="CATALOG"):
        r.inc("sntc_made_up_total")
    with pytest.raises(KeyError, match="label"):
        r.inc("sntc_rows_committed_total", 1, flavor="x")
    with pytest.raises(KeyError, match="histogram"):
        r.observe("sntc_rows_committed_total", 1.0)


def test_label_cardinality_cap_collapses_to_overflow():
    r = MetricsRegistry(max_label_sets=4)
    for i in range(9):
        r.inc("sntc_rows_committed_total", 1, tenant=f"t{i}")
    # first 4 label sets kept; the 5 surplus collapse into overflow
    assert r.label_overflows() == 5
    assert r.get("sntc_rows_committed_total", overflow="true") == 5
    for i in range(4):
        assert r.get("sntc_rows_committed_total", tenant=f"t{i}") == 1
    snap = r.snapshot()["sntc_rows_committed_total"]
    assert len(snap["series"]) == 5  # 4 kept + overflow


def test_histogram_bucket_edges():
    spec = CATALOG["sntc_batch_duration_seconds"]
    bounds = spec["buckets"]
    r = MetricsRegistry()
    # exactly ON a bound counts into that bound's bucket (le semantics)
    r.observe("sntc_batch_duration_seconds", bounds[0])
    r.observe("sntc_batch_duration_seconds", bounds[0] * 1.0001)
    r.observe("sntc_batch_duration_seconds", 1e9)  # +Inf bucket
    s = r.snapshot()["sntc_batch_duration_seconds"]["series"][0]
    assert s["buckets"][0] == 1
    assert s["buckets"][1] == 1
    assert s["buckets"][-1] == 1
    assert s["count"] == 3
    text = r.to_prometheus()
    assert f'sntc_batch_duration_seconds_bucket{{le="{bounds[0]}"}} 1' in text
    # cumulative: the second bucket line includes the first's count
    assert f'sntc_batch_duration_seconds_bucket{{le="{bounds[1]}"}} 2' in text
    assert 'sntc_batch_duration_seconds_bucket{le="+Inf"} 3' in text
    assert "sntc_batch_duration_seconds_count 3" in text


def test_snapshot_under_concurrent_writes():
    r = MetricsRegistry()
    N_THREADS, N_INC = 8, 2_000
    stop = threading.Event()
    snaps = []

    def writer(i):
        for _ in range(N_INC):
            r.inc("sntc_rows_committed_total", 1, tenant=f"w{i % 3}")
            r.observe("sntc_batch_duration_seconds", 0.01)

    def reader():
        while not stop.is_set():
            snaps.append(r.snapshot())
            r.to_prometheus()

    threads = [
        threading.Thread(target=writer, args=(i,))
        for i in range(N_THREADS)
    ]
    rt = threading.Thread(target=reader)
    rt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join()
    total = sum(
        r.get("sntc_rows_committed_total", tenant=f"w{k}")
        for k in range(3)
    )
    assert total == N_THREADS * N_INC  # no lost increments
    s = r.snapshot()["sntc_batch_duration_seconds"]["series"][0]
    assert s["count"] == N_THREADS * N_INC
    assert snaps, "reader never snapshotted"
    # monotone non-decreasing totals across the reader's snapshots
    last = -1
    for snap in snaps:
        rows = snap.get("sntc_rows_committed_total")
        tot = sum(x["value"] for x in rows["series"]) if rows else 0
        assert tot >= last
        last = tot


def test_jsonl_exposition_deterministic_clock(tmp_path):
    r = MetricsRegistry(clock=lambda: 123.5, mono=lambda: 7.25)
    r.inc("sntc_daemon_ticks_total", 3)
    path = str(tmp_path / "m.jsonl")
    rec = r.write_jsonl(path)
    assert (rec["ts"], rec["mono"], rec["seq"]) == (123.5, 7.25, 0)
    r.write_jsonl(path)
    with open(path) as f:
        lines = [json.loads(line) for line in f]
    assert [r_["seq"] for r_ in lines] == [0, 1]
    assert (
        lines[0]["metrics"]["sntc_daemon_ticks_total"]["series"][0][
            "value"
        ]
        == 3
    )


def test_write_prometheus_atomic(tmp_path):
    r = MetricsRegistry()
    r.inc("sntc_daemon_ticks_total")
    path = str(tmp_path / "m.prom")
    r.write_prometheus(path)
    with open(path) as f:
        text = f.read()
    assert "# TYPE sntc_daemon_ticks_total counter" in text
    assert "sntc_daemon_ticks_total 1" in text
    assert not os.path.exists(path + ".tmp")


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_span_ring_overflow_drops_oldest_and_counts():
    t = SpanTracer(capacity=4)
    for i in range(7):
        with t.span("s", i=i):
            pass
    spans = t.spans()
    assert len(spans) == 4
    assert [s["attrs"]["i"] for s in spans] == [3, 4, 5, 6]
    assert t.dropped == 3
    assert t.stats() == {"spans": 4, "capacity": 4, "dropped": 3}


def test_span_records_on_exception_and_clocks():
    base = {"t": 0.0}
    t = SpanTracer(clock=lambda: base["t"], wall=lambda: 1000 + base["t"])
    with pytest.raises(ValueError):
        with t.span("boom"):
            base["t"] = 2.5
            raise ValueError("x")
    (s,) = t.spans()
    assert s["name"] == "boom"
    assert s["dur_s"] == 2.5
    assert s["wall"] == 1000.0


def test_module_span_noop_when_disabled_records_when_enabled():
    assert obs_tracer() is None
    with obs_span("ignored", k=1):
        pass  # no tracer: shared null context
    t = enable_tracing(capacity=16)
    assert obs_tracer() is t
    with obs_span("live", k=2):
        pass
    assert [s["name"] for s in t.spans()] == ["live"]
    assert disable_tracing() is t
    with obs_span("ignored-again"):
        pass
    assert [s["name"] for s in t.spans()] == ["live"]


def test_chrome_trace_export_loadable(tmp_path):
    t = SpanTracer(capacity=16)
    with t.span("outer", batch=1):
        with t.span("inner"):
            pass
    path = t.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in events} == {"outer", "inner"}
    for e in events:
        assert e["dur"] >= 0 and "ts" in e and "tid" in e
        assert "wall_ts" in e["args"]
    assert events[1]["args"]["batch"] == 1  # ring order: inner first
    assert any(e["ph"] == "M" for e in doc["traceEvents"])  # thread names


# ---------------------------------------------------------------------------
# the event→metrics bridge + event timestamps
# ---------------------------------------------------------------------------


def test_events_carry_wall_and_monotonic_timestamps():
    rec = R.emit_event(event="retry", site="stream.read", attempt=1)
    assert rec["ts"] > 0 and rec["mono"] > 0
    (tail,) = R.recent_events(event="retry")[-1:]
    assert tail["ts"] == rec["ts"] and tail["mono"] == rec["mono"]


def test_bridge_counts_events_and_splits_tenant_sites():
    assert split_tenant_site(
        {"site": "tenant/a/sink.write"}
    ) == ("sink.write", "a")
    assert split_tenant_site(
        {"site": "stream.read", "tenant": "b"}
    ) == ("stream.read", "b")
    before = _get(
        "sntc_events_total", event="retry", site="sink.write", tenant="z"
    )
    R.emit_event(event="retry", site="tenant/z/sink.write", attempt=1)
    assert (
        _get("sntc_events_total", event="retry", site="sink.write",
             tenant="z")
        == before + 1
    )


def test_bridge_rows_rejected_reasons_and_shed_offsets():
    before_nf = _get(
        "sntc_rows_rejected_total", reason="non_finite", tenant="q"
    )
    before_shed = _get("sntc_shed_offsets_total", tenant="q")
    R.emit_event(
        event="rows_rejected", site="tenant/q/source.parse", count=3,
        reasons={"non_finite": 2, "ragged_row": 1}, tenant="q",
    )
    R.emit_event(
        event="load_shed", site="tenant/q/stream.read", tenant="q",
        policy="oldest", offsets_shed=7, start=0, end=7,
    )
    assert (
        _get("sntc_rows_rejected_total", reason="non_finite", tenant="q")
        == before_nf + 2
    )
    assert (
        _get("sntc_shed_offsets_total", tenant="q") == before_shed + 7
    )


def test_health_report_mirrors_gauge_and_snapshot_has_both_clocks():
    h = R.HealthMonitor(clock=lambda: 42.0)
    h.report("mycomp", R.HealthState.DEGRADED, "testing")
    assert _get("sntc_health_state", component="mycomp") == 1
    entry = h.snapshot()["components"]["mycomp"]
    assert entry["since"] == 42.0
    assert entry["since_wall"] > 0
    h.report("mycomp", R.HealthState.OK)
    assert _get("sntc_health_state", component="mycomp") == 0


def test_breaker_transitions_set_state_gauge():
    br = R.CircuitBreaker(
        "obs.test.site", window=4, min_calls=2, failure_threshold=0.5,
        cooldown_s=0.0, clock=lambda: 0.0,
    )
    br.record_failure()
    br.record_failure()
    assert br.state == "half_open"  # cooldown 0: open → half_open
    # the OPEN transition wrote 2, the half_open probe window wrote 1
    assert _get("sntc_breaker_state", site="obs.test.site") == 1


# ---------------------------------------------------------------------------
# per-engine transfer-ledger attribution (satellite: the bare
# process-global conflated tenants)
# ---------------------------------------------------------------------------


def test_ledger_scope_attribution_and_metrics_mirror():
    glob = transfer_ledger()
    g0 = glob.snapshot()
    a = TransferLedger(tenant="ledger-a")
    b = TransferLedger(tenant="ledger-b")
    assert active_ledgers() == (glob,)
    with ledger_scope(a):
        assert active_ledgers() == (glob, a)
        for led in active_ledgers():
            led.record_uploads(2, 100)
    with ledger_scope(b):
        for led in active_ledgers():
            led.record_uploads(1, 50)
            led.record_downloads(1, 10)
    assert active_ledgers() == (glob,)
    # per-engine ledgers saw only their own scope's transfers
    assert (a.uploads, a.downloads) == (2, 0)
    assert (b.uploads, b.downloads) == (1, 1)
    # the global saw everything (the default process-wide view)
    g1 = glob.snapshot()
    assert g1["uploads"] - g0["uploads"] == 3
    assert g1["download_bytes"] - g0["download_bytes"] == 10
    # tenant-labeled metric series mirror the per-engine ledgers exactly
    assert _get("sntc_transfer_uploads_total", tenant="ledger-a") == 2
    assert _get("sntc_transfer_uploads_total", tenant="ledger-b") == 1
    assert (
        _get("sntc_transfer_download_bytes_total", tenant="ledger-b")
        == 10
    )
    # anonymous engine ledgers do NOT mirror (the unlabeled series must
    # stay exactly the global ledger)
    anon = TransferLedger()
    unlabeled0 = _get("sntc_transfer_uploads_total")
    anon.record_uploads(5, 5)
    assert _get("sntc_transfer_uploads_total") == unlabeled0


def test_nested_scopes_record_to_both():
    a = TransferLedger()
    b = TransferLedger()
    with ledger_scope(a), ledger_scope(b):
        for led in active_ledgers()[1:]:
            led.record_downloads(1)
    assert a.downloads == 1 and b.downloads == 1


# ---------------------------------------------------------------------------
# end-to-end: one serve run's Prometheus snapshot agrees with the
# legacy ledger views (compile / transfer / shed / tenant series)
# ---------------------------------------------------------------------------


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _fused_served_model(mesh):
    """A tiny fitted pipeline with a real fused segment (assembler runs
    eagerly per the single-upload rule; scaler+LR fuse)."""
    rng = np.random.default_rng(0)
    cols = {
        f"c{i}": np.abs(rng.normal(3, 2, 240)).astype(np.float32)
        for i in range(4)
    }
    cols["label"] = (cols["c0"] > 3.0).astype(np.float64)
    f = Frame(cols)
    pm = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(4)],
                        outputCol="features"),
        MinMaxScaler(inputCol="features", outputCol="scaled"),
        LogisticRegression(mesh=mesh, featuresCol="scaled", maxIter=15),
    ]).fit(f)
    from sntc_tpu.fuse import compile_pipeline, fused_segments

    fused = compile_pipeline(pm)
    assert fused_segments(fused)
    serve_frames = [
        Frame({
            f"c{i}": np.abs(rng.normal(3, 2, 16)).astype(np.float32)
            for i in range(4)
        })
        for _ in range(4)
    ]
    return fused, serve_frames


def test_e2e_prometheus_snapshot_agrees_with_legacy_ledgers(
    mesh8, tmp_path
):
    fused, frames_a = _fused_served_model(mesh8)
    _, frames_b = _fused_served_model(mesh8)
    from sntc_tpu.serve.transform import BatchPredictor

    pred = BatchPredictor(fused, bucket_rows=8)
    spec_a = TenantSpec(
        tenant_id="obs-a", model=pred,
        source=MemorySource(frames_a), sink=MemorySink(),
    )
    # tenant b sheds: backlog of 6 one-offset batches over a cap of 2
    spec_b = TenantSpec(
        tenant_id="obs-b", model=pred,
        source=MemorySource(frames_b + frames_b[:2]),
        sink=MemorySink(),
        max_pending_batches=2, shed_policy="oldest",
    )
    before = {
        "compile": _get("sntc_predict_compile_events_total"),
        "fuse_compile": _get("sntc_fuse_compile_events_total"),
        "up_global": _get("sntc_transfer_uploads_total"),
        "down_global": _get("sntc_transfer_downloads_total"),
        "shed_b": _get("sntc_shed_offsets_total", tenant="obs-b"),
        "rows_a": _get("sntc_rows_committed_total", tenant="obs-a"),
        "rows_b": _get("sntc_rows_committed_total", tenant="obs-b"),
        "ticks": _get("sntc_daemon_ticks_total"),
    }
    glob0 = transfer_ledger().snapshot()
    compile0 = pred.compile_events
    daemon = ServeDaemon(
        [spec_a, spec_b], str(tmp_path / "root"), shape_buckets=8
    )
    try:
        daemon.process_available()
        status = daemon.status()
        ta = daemon._by_id["obs-a"]
        tb = daemon._by_id["obs-b"]
        # tenant rows: registry series == the daemon's own accounting
        assert (
            _get("sntc_rows_committed_total", tenant="obs-a")
            - before["rows_a"]
            == ta.rows_done
        )
        assert (
            _get("sntc_rows_committed_total", tenant="obs-b")
            - before["rows_b"]
            == tb.rows_done
        )
        assert (
            _get("sntc_batches_committed_total", tenant="obs-a")
            == ta.batches_done
        )
        # shed: registry series == the tenant's journaled shed ledger
        assert tb.shed_total_offsets > 0
        assert (
            _get("sntc_shed_offsets_total", tenant="obs-b")
            - before["shed_b"]
            == tb.shed_total_offsets
        )
        # compile ledger: registry delta == the shared predictor's delta
        assert (
            _get("sntc_predict_compile_events_total") - before["compile"]
            == pred.compile_events - compile0
        )
        assert status["recompiles_after_warmup"] is None  # not marked
        # transfers: the unlabeled series delta == the global ledger
        # delta, and the per-tenant series sum to it (every dispatch in
        # this window came from the two scoped engines)
        glob1 = transfer_ledger().snapshot()
        up_delta = _get("sntc_transfer_uploads_total") - before[
            "up_global"
        ]
        assert up_delta == glob1["uploads"] - glob0["uploads"]
        assert up_delta > 0
        assert (
            _get("sntc_transfer_uploads_total", tenant="obs-a")
            + _get("sntc_transfer_uploads_total", tenant="obs-b")
            >= up_delta
        )
        # per-engine ledgers ride pipeline_stats as the legacy-style view
        ledger_a = ta.query.pipeline_stats()["transfers"]
        assert ledger_a["uploads"] == _get(
            "sntc_transfer_uploads_total", tenant="obs-a"
        )
        assert _get("sntc_daemon_ticks_total") > before["ticks"]
        # the exposition carries all of it
        prom = registry().to_prometheus()
        assert 'sntc_rows_committed_total{tenant="obs-a"}' in prom
        assert 'sntc_shed_offsets_total{tenant="obs-b"}' in prom
        assert "sntc_predict_compile_events_total" in prom
        assert 'sntc_tenant_state{tenant="obs-a"} 0' in prom
    finally:
        daemon.close()


def test_engine_transfer_ledger_not_conflated_across_tenants(
    mesh8, tmp_path
):
    """THE satellite regression: two tenant streams on one shared fused
    predictor used to conflate upload/download counts in the one
    process-global ledger; per-engine ledgers attribute them."""
    fused, frames = _fused_served_model(mesh8)
    from sntc_tpu.serve.transform import BatchPredictor

    pred = BatchPredictor(fused)
    specs = [
        TenantSpec(tenant_id=tid, model=pred,
                   source=MemorySource(list(frames[:n])),
                   sink=MemorySink())
        for tid, n in (("conf-a", 3), ("conf-b", 1))
    ]
    daemon = ServeDaemon(specs, str(tmp_path / "root"))
    try:
        daemon.process_available()
        la = daemon._by_id["conf-a"].query.transfer
        lb = daemon._by_id["conf-b"].query.transfer
        assert la.dispatches == 3 and lb.dispatches == 1
        assert la.uploads > lb.uploads  # 3 batches vs 1, attributed
        assert la.tenant == "conf-a" and lb.tenant == "conf-b"
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# single-tenant engine: per-batch metrics without labels
# ---------------------------------------------------------------------------


def test_single_tenant_engine_emits_unlabeled_series(tmp_path):
    from sntc_tpu.serve import StreamingQuery

    frames = [Frame({"x": np.arange(6.0)}) for _ in range(2)]
    b0 = _get("sntc_batches_committed_total")
    r0 = _get("sntc_rows_committed_total")
    q = StreamingQuery(
        _Identity(), MemorySource(frames), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
    )
    assert q.process_available() == 2
    assert _get("sntc_batches_committed_total") - b0 == 2
    assert _get("sntc_rows_committed_total") - r0 == 12
    assert q.pipeline_stats()["transfers"]["dispatches"] == 0  # unfused


# ---------------------------------------------------------------------------
# metric-name drift check (tier-1 wiring of check_metric_names)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metric_names_consistent_code_catalog_docs():
    checker = _load_script("check_metric_names")
    assert checker.check() == []
