"""GeneralizedLinearRegression oracle tests — coefficient-level parity
with sklearn's unpenalized GLMs (Poisson/Gamma/Tweedie lbfgs MLE) and
with our own exact linear/logistic fits for the gaussian/binomial
families."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import (
    GeneralizedLinearRegression,
    GeneralizedLinearRegressionModel,
    LinearRegression,
    LogisticRegression,
)


def _design(n=4000, d=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32) * 0.5
    beta = np.array([0.8, -0.5, 0.3, 0.0])[:d]
    eta = X @ beta + 0.2
    return X, beta, eta, rng


def test_gaussian_identity_matches_linear_regression(mesh8):
    X, beta, eta, rng = _design()
    y = eta + 0.1 * rng.normal(size=len(eta))
    f = Frame({"features": X, "label": y})
    glr = GeneralizedLinearRegression(mesh=mesh8).fit(f)
    lin = LinearRegression(mesh=mesh8, solver="normal").fit(f)
    np.testing.assert_allclose(
        glr.coefficients, lin.coefficients, atol=1e-4
    )
    assert glr.intercept == pytest.approx(lin.intercept, abs=1e-4)
    assert glr.summary.totalIterations <= 3  # identity link: one solve
    # deviance for gaussian = SSE
    resid = y - glr.predict(X)
    assert glr.summary.deviance == pytest.approx(
        float((resid**2).sum()), rel=1e-3
    )
    assert glr.summary.nullDeviance > glr.summary.deviance


def test_binomial_logit_matches_logistic_regression(mesh8):
    X, beta, eta, rng = _design(seed=1)
    y = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(np.float64)
    f = Frame({"features": X, "label": y})
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="binomial", maxIter=50
    ).fit(f)
    lr = LogisticRegression(mesh=mesh8, maxIter=200, tol=1e-10).fit(f)
    np.testing.assert_allclose(
        glr.coefficients, lr.coefficients, atol=2e-3
    )
    assert glr.intercept == pytest.approx(lr.intercept, abs=2e-3)
    # predictions are probabilities
    mu = glr.predict(X)
    assert np.all((mu > 0) & (mu < 1))
    assert glr.summary.dispersion == 1.0


def test_poisson_log_matches_sklearn(mesh8):
    from sklearn.linear_model import PoissonRegressor

    X, beta, eta, rng = _design(seed=2)
    y = rng.poisson(np.exp(eta)).astype(np.float64)
    f = Frame({"features": X, "label": y})
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="poisson", maxIter=50
    ).fit(f)
    sk = PoissonRegressor(alpha=0.0, max_iter=500, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(glr.coefficients, sk.coef_, atol=2e-3)
    assert glr.intercept == pytest.approx(sk.intercept_, abs=2e-3)


def test_gamma_log_matches_sklearn(mesh8):
    from sklearn.linear_model import GammaRegressor

    X, beta, eta, rng = _design(seed=3)
    mu = np.exp(eta)
    y = rng.gamma(shape=5.0, scale=mu / 5.0).astype(np.float64)
    f = Frame({"features": X, "label": y})
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="gamma", link="log", maxIter=50
    ).fit(f)
    sk = GammaRegressor(alpha=0.0, max_iter=500, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(glr.coefficients, sk.coef_, atol=3e-3)
    assert glr.intercept == pytest.approx(sk.intercept_, abs=3e-3)
    # gamma dispersion estimated from Pearson chi^2 / dof (~1/shape)
    assert glr.summary.dispersion == pytest.approx(1 / 5.0, rel=0.25)


def test_poisson_l2_matches_sklearn_alpha(mesh8):
    """regParam applies to the weight-AVERAGED Gram (Spark
    WeightedLeastSquares convention), which maps 1:1 onto sklearn's
    ``alpha`` against the mean deviance."""
    from sklearn.linear_model import PoissonRegressor

    X, beta, eta, rng = _design(seed=7)
    y = rng.poisson(np.exp(eta)).astype(np.float64)
    f = Frame({"features": X, "label": y})
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="poisson", regParam=0.5, maxIter=50
    ).fit(f)
    sk = PoissonRegressor(alpha=0.5, max_iter=500, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(glr.coefficients, sk.coef_, atol=2e-3)
    assert glr.intercept == pytest.approx(sk.intercept_, abs=2e-3)
    # and the penalty actually bites
    un = GeneralizedLinearRegression(
        mesh=mesh8, family="poisson", maxIter=50
    ).fit(f)
    assert np.linalg.norm(glr.coefficients) < np.linalg.norm(
        un.coefficients
    )


def test_weight_col_equals_replication(mesh8):
    """Integer weights ≡ row replication (the GLM weighted-likelihood
    contract)."""
    X, beta, eta, rng = _design(n=800, seed=4)
    y = rng.poisson(np.exp(eta)).astype(np.float64)
    w = rng.integers(1, 4, size=len(y)).astype(np.float64)
    f_w = Frame({"features": X, "label": y, "w": w})
    rep = np.repeat(np.arange(len(y)), w.astype(int))
    f_rep = Frame({"features": X[rep], "label": y[rep]})
    kw = dict(mesh=mesh8, family="poisson", maxIter=50)
    m_w = GeneralizedLinearRegression(weightCol="w", **kw).fit(f_w)
    m_rep = GeneralizedLinearRegression(**kw).fit(f_rep)
    np.testing.assert_allclose(
        m_w.coefficients, m_rep.coefficients, atol=1e-4
    )


def test_link_validation_and_transform_cols(mesh8):
    X, _, eta, rng = _design(n=500, seed=5)
    y = rng.poisson(np.exp(eta)).astype(np.float64)
    f = Frame({"features": X, "label": y})
    with pytest.raises(ValueError, match="not supported"):
        GeneralizedLinearRegression(
            mesh=mesh8, family="poisson", link="logit"
        ).fit(f)
    with pytest.raises(ValueError, match="non-negative"):
        GeneralizedLinearRegression(mesh=mesh8, family="poisson").fit(
            Frame({"features": X, "label": y - 10.0})
        )
    m = GeneralizedLinearRegression(
        mesh=mesh8, family="poisson", linkPredictionCol="eta"
    ).fit(f)
    out = m.transform(f)
    np.testing.assert_allclose(
        np.exp(out["eta"]), out["prediction"], rtol=1e-5
    )


def test_glm_save_load_roundtrip(mesh8, tmp_path):
    X, _, eta, rng = _design(n=600, seed=6)
    y = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(np.float64)
    f = Frame({"features": X, "label": y})
    m = GeneralizedLinearRegression(
        mesh=mesh8, family="binomial", link="probit", maxIter=50
    ).fit(f)
    m2 = load_model(save_model(m, str(tmp_path / "glm")))
    assert isinstance(m2, GeneralizedLinearRegressionModel)
    assert m2.getLink() == "probit"  # the RESOLVED link persists
    np.testing.assert_allclose(
        m2.transform(f)["prediction"], m.transform(f)["prediction"],
        rtol=1e-6,
    )


def test_tweedie_matches_sklearn(mesh8):
    """family='tweedie' vs sklearn TweedieRegressor (alpha=0 MLE, log
    link) — the same GLM, independent optimizer."""
    from sklearn.linear_model import TweedieRegressor

    from sntc_tpu.models import GeneralizedLinearRegression

    rng = np.random.default_rng(8)
    n, d = 4000, 3
    X = rng.normal(size=(n, d)).astype(np.float32) * 0.5
    beta = np.array([0.6, -0.3, 0.2])
    mu = np.exp(X @ beta + 0.8)
    # compound-poisson-ish targets: gamma noise with exact zeros mixed in
    y = (mu * rng.gamma(2.0, 0.5, size=n)).astype(np.float32)
    y[rng.random(n) < 0.1] = 0.0

    m = GeneralizedLinearRegression(
        family="tweedie", variancePower=1.5, linkPower=0.0, maxIter=50,
    ).fit(Frame({"features": X, "label": y}))
    sk = TweedieRegressor(
        power=1.5, alpha=0.0, link="log", max_iter=500, tol=1e-8
    ).fit(X.astype(np.float64), y.astype(np.float64))
    np.testing.assert_allclose(m.coefficients, sk.coef_, atol=5e-3)
    assert m.intercept == pytest.approx(sk.intercept_, abs=5e-3)
    # deviance improves on the null model and dispersion is finite
    assert m.summary.deviance < m.summary.nullDeviance
    assert np.isfinite(m.summary.dispersion)


def test_tweedie_default_link_power_and_validation(mesh8):
    from sntc_tpu.models import GeneralizedLinearRegression

    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 2)).astype(np.float32)
    y = np.exp(0.3 * X[:, 0] + 1.0).astype(np.float32)
    # default linkPower = 1 - variancePower = -1 (inverse-ish power link)
    m = GeneralizedLinearRegression(
        family="tweedie", variancePower=2.0, maxIter=40,
    ).fit(Frame({"features": X, "label": y}))
    assert m.getLink() == "power:-1.0"
    assert np.isfinite(m.transform(Frame({"features": X}))["prediction"]).all()
    with pytest.raises(ValueError, match="linkPower"):
        GeneralizedLinearRegression(
            family="tweedie", link="log"
        ).fit(Frame({"features": X, "label": y}))
    with pytest.raises(ValueError, match="strictly"):
        GeneralizedLinearRegression(
            family="tweedie", variancePower=2.5
        ).fit(Frame({"features": X, "label": np.zeros(300, np.float32)}))
    with pytest.raises(ValueError):
        GeneralizedLinearRegression(family="tweedie", variancePower=0.5)


def test_tweedie_clone_params_refit(mesh8):
    """GeneralizedLinearRegression(**fitted_model.paramValues()).fit —
    the clone-and-refit idiom — must work for tweedie (the persisted
    'power:<lp>' link passes through resolution)."""
    from sntc_tpu.models import GeneralizedLinearRegression

    rng = np.random.default_rng(5)
    X = rng.normal(size=(400, 2)).astype(np.float32)
    y = np.exp(0.4 * X[:, 0] + 0.5).astype(np.float32)
    f = Frame({"features": X, "label": y})
    m = GeneralizedLinearRegression(
        family="tweedie", variancePower=1.5, linkPower=0.0, maxIter=30,
    ).fit(f)
    clone = GeneralizedLinearRegression(
        **{k: v for k, v in m.paramValues().items()
           if GeneralizedLinearRegression().hasParam(k)}
    )
    m2 = clone.fit(f)
    np.testing.assert_allclose(m2.coefficients, m.coefficients, atol=1e-6)
    with pytest.raises(ValueError, match="failed validation"):
        GeneralizedLinearRegression(family="tweedie", linkPower="log")


# ---------------------------------------------------------------------------
# AIC (r5): Spark GeneralizedLinearRegressionSummary.aic = family log-
# likelihood form + 2*rank, oracle-checked against scipy.stats
# ---------------------------------------------------------------------------


def test_aic_gaussian_closed_form(mesh8):
    X, beta, eta, rng = _design(n=800, seed=7)
    y = eta + 0.1 * rng.normal(size=len(eta))
    glr = GeneralizedLinearRegression(mesh=mesh8).fit(
        Frame({"features": X, "label": y})
    )
    n, rank = len(y), X.shape[1] + 1
    dev = glr.summary.deviance
    oracle = n * (np.log(2 * np.pi * dev / n) + 1) + 2 + 2 * rank
    assert glr.summary.aic == pytest.approx(oracle, rel=1e-6)


def test_aic_poisson_matches_scipy(mesh8):
    from scipy.stats import poisson as sp_poisson

    X, beta, eta, rng = _design(n=800, seed=8)
    y = rng.poisson(np.exp(eta)).astype(np.float64)
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="poisson", maxIter=50
    ).fit(Frame({"features": X, "label": y}))
    mu = glr.predict(X)
    oracle = -2.0 * sp_poisson.logpmf(y.astype(int), mu).sum() + 2 * (
        X.shape[1] + 1
    )
    assert glr.summary.aic == pytest.approx(oracle, rel=1e-5)


def test_aic_binomial_weighted_trials_matches_scipy(mesh8):
    """Spark treats weightCol as Binomial trial counts: y is the success
    FRACTION, round(y*w) the successes."""
    from scipy.stats import binom as sp_binom

    rng = np.random.default_rng(9)
    X = rng.normal(size=(600, 3)).astype(np.float32) * 0.5
    eta = X @ np.array([0.8, -0.5, 0.3]) + 0.1
    p = 1 / (1 + np.exp(-eta))
    w = rng.integers(1, 6, size=600).astype(np.float64)
    succ = rng.binomial(w.astype(int), p).astype(np.float64)
    y = succ / w
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="binomial", weightCol="w", maxIter=50
    ).fit(Frame({"features": X, "label": y, "w": w}))
    mu = glr.predict(X)
    oracle = -2.0 * sp_binom.logpmf(
        np.round(y * w).astype(int), w.astype(int), mu
    ).sum() + 2 * (X.shape[1] + 1)
    assert glr.summary.aic == pytest.approx(oracle, rel=1e-5)


def test_aic_binomial_half_integer_weights_scala_rounding(mesh8):
    """ADVICE r5: Scala math.round is half-UP; np.round is banker's.
    Half-integer trial weights (w=2.5 -> 3 trials, not 2) must follow
    Spark's floor(x + 0.5)."""
    from scipy.stats import binom as sp_binom

    rng = np.random.default_rng(21)
    X = rng.normal(size=(400, 2)).astype(np.float32) * 0.5
    eta = X @ np.array([0.7, -0.4]) + 0.2
    p = 1 / (1 + np.exp(-eta))
    # all weights half-integers: every row hits the rounding difference
    w = rng.integers(1, 5, size=400).astype(np.float64) + 0.5
    trials_scala = np.floor(w + 0.5)  # half-up, Scala math.round
    succ = rng.binomial(trials_scala.astype(int), p).astype(np.float64)
    y = np.clip(succ / w, 0.0, 1.0)
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="binomial", weightCol="w", maxIter=50
    ).fit(Frame({"features": X, "label": y, "w": w}))
    mu = glr.predict(X)
    r_scala = np.floor(y * w + 0.5)
    oracle = -2.0 * sp_binom.logpmf(
        r_scala.astype(int), trials_scala.astype(int), mu
    ).sum() + 2 * (X.shape[1] + 1)
    assert glr.summary.aic == pytest.approx(oracle, rel=1e-5)
    # and it must NOT match the banker's-rounding oracle (np.round(2.5)
    # == 2): the two differ on every row here
    oracle_bankers = -2.0 * sp_binom.logpmf(
        np.round(y * w).astype(int), np.round(w).astype(int), mu
    ).sum() + 2 * (X.shape[1] + 1)
    assert abs(oracle - oracle_bankers) > 1.0
    assert glr.summary.aic != pytest.approx(oracle_bankers, rel=1e-5)


def test_aic_gamma_matches_scipy(mesh8):
    from scipy.stats import gamma as sp_gamma

    X, beta, eta, rng = _design(n=800, seed=10)
    mu_true = np.exp(eta)
    y = rng.gamma(shape=5.0, scale=mu_true / 5.0).astype(np.float64)
    glr = GeneralizedLinearRegression(
        mesh=mesh8, family="gamma", link="log", maxIter=50
    ).fit(Frame({"features": X, "label": y}))
    mu = glr.predict(X)
    disp = glr.summary.deviance / len(y)
    oracle = (
        -2.0 * sp_gamma.logpdf(y, a=1.0 / disp, scale=mu * disp).sum()
        + 2.0
        + 2 * (X.shape[1] + 1)
    )
    assert glr.summary.aic == pytest.approx(oracle, rel=1e-5)


def test_aic_tweedie_raises(mesh8):
    rng = np.random.default_rng(12)
    X = rng.normal(size=(300, 2)).astype(np.float32)
    y = np.exp(0.3 * X[:, 0] + 1.0).astype(np.float32)
    m = GeneralizedLinearRegression(
        family="tweedie", variancePower=1.5, maxIter=20
    ).fit(Frame({"features": X, "label": y}))
    with pytest.raises(ValueError, match="tweedie"):
        m.summary.aic
