"""KMeans + ClusteringEvaluator oracle tests vs sklearn."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.evaluation import ClusteringEvaluator
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import KMeans


def _blobs(seed=0, n=3000, k=3, d=5, scale=6.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * scale
    y = rng.integers(0, k, size=n)
    X = (centers[y] + rng.normal(size=(n, d))).astype(np.float32)
    return Frame({"features": X}), X, y, centers


def _cluster_match(pred, truth, k):
    """Best-permutation agreement between cluster labelings."""
    from itertools import permutations

    best = 0.0
    for perm in permutations(range(k)):
        mapped = np.asarray(perm)[pred.astype(int)]
        best = max(best, (mapped == truth).mean())
    return best


def test_kmeans_recovers_blobs_and_matches_sklearn_cost(mesh8):
    from sklearn.cluster import KMeans as SkKM

    f, X, y, _ = _blobs()
    m = KMeans(mesh=mesh8, k=3, seed=1, maxIter=30).fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    assert _cluster_match(pred, y, 3) > 0.98
    sk = SkKM(n_clusters=3, n_init=5, random_state=0).fit(X)
    # same inertia to within 1% (same optimum on separated blobs)
    assert m.summary.trainingCost == pytest.approx(sk.inertia_, rel=0.01)
    assert m.clusterCenters.shape == (3, 5)


def test_kmeans_init_modes_tol_and_save_load(mesh8, tmp_path):
    f, X, y, _ = _blobs(seed=3)
    r = KMeans(mesh=mesh8, k=3, seed=5, initMode="random", maxIter=50).fit(f)
    # random init (no restarts, as in Spark) can land in a local optimum;
    # k-means|| on the same data must do at least as well
    r_match = _cluster_match(np.asarray(r.transform(f)["prediction"]), y, 3)
    pp = KMeans(mesh=mesh8, k=3, seed=5, maxIter=50).fit(f)
    pp_match = _cluster_match(np.asarray(pp.transform(f)["prediction"]), y, 3)
    assert pp_match > 0.98 and pp_match >= r_match
    # deterministic under a fixed seed
    r2 = KMeans(mesh=mesh8, k=3, seed=5, initMode="random", maxIter=50).fit(f)
    np.testing.assert_allclose(r.clusterCenters, r2.clusterCenters)
    save_model(r, str(tmp_path / "km"))
    m2 = load_model(str(tmp_path / "km"))
    np.testing.assert_allclose(m2.clusterCenters, r.clusterCenters)
    with pytest.raises(ValueError, match="exceeds the row count"):
        KMeans(mesh=mesh8, k=50).fit(
            Frame({"features": np.zeros((10, 2), np.float32)})
        )


def test_kmeans_cosine(mesh8):
    rng = np.random.default_rng(4)
    # two directions on the sphere, different magnitudes
    base = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    y = rng.integers(0, 2, size=1000)
    X = base[y] * rng.uniform(0.5, 5.0, size=(1000, 1)).astype(np.float32)
    X = X + 0.05 * rng.normal(size=X.shape).astype(np.float32)
    f = Frame({"features": X})
    m = KMeans(mesh=mesh8, k=2, seed=0, distanceMeasure="cosine").fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    assert _cluster_match(pred, y, 2) > 0.98


def test_silhouette_matches_sklearn(mesh8):
    from sklearn.metrics import silhouette_score

    f, X, y, _ = _blobs(seed=6, n=1500)
    m = KMeans(mesh=mesh8, k=3, seed=0).fit(f)
    out = m.transform(f)
    ours = ClusteringEvaluator().evaluate(out)
    # sklearn silhouette uses EUCLIDEAN distance; Spark's closed form is
    # SQUARED euclidean — compare against sklearn on squared distances
    sk = silhouette_score(
        X.astype(np.float64),
        np.asarray(out["prediction"]).astype(int),
        metric="sqeuclidean",
    )
    assert ours == pytest.approx(float(sk), abs=1e-6)
    cos = ClusteringEvaluator(distanceMeasure="cosine").evaluate(out)
    sk_cos = silhouette_score(
        X.astype(np.float64),
        np.asarray(out["prediction"]).astype(int),
        metric="cosine",
    )
    # cosine silhouette: Spark's mean-vector form vs sklearn's pairwise
    # differ slightly; directions agree
    assert abs(cos - float(sk_cos)) < 0.1
    assert ClusteringEvaluator().isLargerBetter()


def test_silhouette_ignores_empty_cluster_ids():
    """A never-predicted cluster id must not poison b(i) with a fake
    zero distance."""
    from sntc_tpu.evaluation.clustering import _silhouette

    X = np.array([[0.0, 0.0], [0.1, 0.0], [10.0, 0.0], [10.1, 0.0]])
    sparse = _silhouette(X, np.array([0, 0, 2, 2]), 3, cosine=False)
    dense = _silhouette(X, np.array([0, 0, 1, 1]), 2, cosine=False)
    assert sparse == pytest.approx(dense)
    assert sparse > 0.9
