"""LinearSVC oracle tests vs sklearn's LinearSVC/SVC(linear)."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import LinearSVC, OneVsRest


def _binary(seed=0, n=3000, d=6, margin=2.0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ w + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return Frame({"features": X, "label": y}), X, y


def test_matches_sklearn_accuracy_and_direction(mesh8):
    from sklearn.svm import LinearSVC as SkSVC

    f, X, y = _binary()
    m = LinearSVC(mesh=mesh8, regParam=0.01, maxIter=100).fit(f)
    # sklearn C = 1/(n*regParam) for the same objective scaling
    sk = SkSVC(C=1.0 / (len(y) * 0.01), loss="hinge", max_iter=20000).fit(X, y)
    out = m.transform(f)
    acc = (np.asarray(out["prediction"]) == y).mean()
    sk_acc = (sk.predict(X) == y).mean()
    # objective matches sklearn to ~1e-6 on this data; accuracy is
    # noise-bound (~0.91 for both), so parity — not absolute level —
    # is the assertion
    assert acc > 0.88
    assert abs(acc - sk_acc) < 0.005
    # same separating direction (cosine similarity)
    cos = np.dot(m.coefficients, sk.coef_[0]) / (
        np.linalg.norm(m.coefficients) * np.linalg.norm(sk.coef_[0])
    )
    assert cos > 0.99
    # raw = [-m, +m]; prediction thresholds raw at 0
    raw = np.asarray(out["rawPrediction"])
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1])
    np.testing.assert_array_equal(
        np.asarray(out["prediction"]), (raw[:, 1] > 0).astype(np.float64)
    )
    assert "probability" not in out.columns  # Spark: no probability col
    assert m.summary.totalIterations > 0


def test_threshold_and_weights(mesh8):
    f, X, y = _binary(seed=1)
    m = LinearSVC(mesh=mesh8, regParam=0.01).fit(f)
    hi = m.copy({"threshold": 1e9}).transform(f)
    assert np.asarray(hi["prediction"]).sum() == 0  # nothing clears it
    # zero-weighting the attack rows flips the fit toward all-benign
    w = (y == 0).astype(np.float32)
    fw = Frame({"features": X, "label": y, "w": w})
    mw = LinearSVC(mesh=mesh8, regParam=0.01, weightCol="w").fit(fw)
    assert np.asarray(mw.transform(f)["prediction"]).sum() < len(y) * 0.05


def test_multiclass_rejected_and_ovr_works(mesh8):
    rng = np.random.default_rng(2)
    X = rng.normal(size=(1500, 5)).astype(np.float32)
    y = np.argmax(X[:, :3], axis=1).astype(np.float64)
    f = Frame({"features": X, "label": y})
    with pytest.raises(ValueError, match="binary-only"):
        LinearSVC(mesh=mesh8).fit(f)
    ovr = OneVsRest(classifier=LinearSVC(mesh=mesh8, regParam=0.01), mesh=mesh8).fit(f)
    acc = (np.asarray(ovr.transform(f)["prediction"]) == y).mean()
    assert acc > 0.85
    # fused serving path engages for homogeneous SVC sub-models and
    # matches the per-model loop
    assert ovr._fused_raw() is not None
    fused = ovr._raw_predict(X)
    loop = np.stack([m._raw_predict(X)[:, 1] for m in ovr.models], axis=1)
    np.testing.assert_allclose(fused, loop, atol=1e-4)


def test_standardization_flag_and_save_load(mesh8, tmp_path):
    f, X, y = _binary(seed=3)
    # scale one feature: standardization should absorb it
    X2 = X.copy(); X2[:, 0] *= 1e4
    f2 = Frame({"features": X2, "label": y})
    m_std = LinearSVC(mesh=mesh8, regParam=0.1, standardization=True).fit(f2)
    m_raw = LinearSVC(mesh=mesh8, regParam=0.1, standardization=False).fit(f2)
    a_std = (np.asarray(m_std.transform(f2)["prediction"]) == y).mean()
    assert a_std > 0.9
    # different penalty spaces -> different coefficients
    assert not np.allclose(m_std.coefficients, m_raw.coefficients)
    save_model(m_std, str(tmp_path / "svc"))
    m2 = load_model(str(tmp_path / "svc"))
    np.testing.assert_allclose(m2.coefficients, m_std.coefficients)
    np.testing.assert_array_equal(
        np.asarray(m2.transform(f2)["prediction"]),
        np.asarray(m_std.transform(f2)["prediction"]),
    )


def test_standardization_survives_large_mean_features(mesh8):
    """mean ~1e6, std ~1 features: the pilot-shifted moments must not
    cancel the spread away (raw f32 sumsq estimated std = 0 here,
    silently skipping standardization)."""
    rng = np.random.default_rng(5)
    n = 4000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    X2 = X.copy()
    X2[:, 0] = X2[:, 0] + 1e6  # huge mean, std 1 — carries the signal
    f = Frame({"features": X2, "label": y})
    m = LinearSVC(mesh=mesh8, regParam=0.001).fit(f)
    acc = (m.predict(X2) == y).mean()
    assert acc > 0.9
    # predict() convenience works and matches transform
    out = m.transform(f)
    np.testing.assert_array_equal(np.asarray(out["prediction"]), m.predict(X2))
