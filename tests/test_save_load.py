"""Save/load round-trip contract for every stage (the DefaultReadWriteTest
analog, SURVEY.md §4 item 3)."""

import numpy as np
import pytest

from sntc_tpu.core.base import Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import (
    ChiSqSelector,
    ChiSqSelectorModel,
    IndexToString,
    StandardScaler,
    StringIndexer,
    VectorAssembler,
)
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import LogisticRegression
from sntc_tpu.models.logistic_regression import LogisticRegressionModel


def _roundtrip(stage, tmp_path, name):
    path = str(tmp_path / name)
    save_model(stage, path)
    loaded = load_model(path)
    assert type(loaded) is type(stage)
    got, want = loaded.paramValues(), stage.paramValues()
    got.pop("stages", None), want.pop("stages", None)  # objects; checked by caller
    assert got == want
    assert loaded.uid == stage.uid
    return loaded


def _frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    labels = np.where(y > 0, "attack", "benign").astype(object)
    return Frame({"features": X, "label": y, "labelStr": labels})


def test_transformer_roundtrips(tmp_path):
    _roundtrip(
        VectorAssembler(inputCols=["a", "b"], handleInvalid="skip"),
        tmp_path, "va",
    )
    _roundtrip(
        IndexToString(inputCol="p", outputCol="s", labels=["x", "y"]),
        tmp_path, "its",
    )


def test_fitted_model_roundtrips(tmp_path, mesh8):
    f = _frame()
    si = StringIndexer(inputCol="labelStr", outputCol="idx").fit(f)
    si2 = _roundtrip(si, tmp_path, "si")
    assert si2.labels == si.labels

    sc = StandardScaler(mesh=mesh8, inputCol="features", outputCol="sf").fit(f)
    sc2 = _roundtrip(sc, tmp_path, "sc")
    np.testing.assert_array_equal(sc2.mean, sc.mean)
    np.testing.assert_array_equal(sc2.std, sc.std)

    cs = ChiSqSelector(mesh=mesh8, numTopFeatures=2, labelCol="label").fit(f)
    cs2 = _roundtrip(cs, tmp_path, "cs")
    assert cs2.selected_features == cs.selected_features

    lr = LogisticRegression(mesh=mesh8, maxIter=20).fit(f)
    lr2 = _roundtrip(lr, tmp_path, "lr")
    assert isinstance(lr2, LogisticRegressionModel)
    np.testing.assert_array_equal(lr2.coefficientMatrix, lr.coefficientMatrix)
    out1, out2 = lr.transform(f), lr2.transform(f)
    np.testing.assert_array_equal(out1["prediction"], out2["prediction"])


def test_pipeline_model_roundtrip(tmp_path, mesh8):
    f = _frame(seed=1)
    pipe = Pipeline(stages=[
        StandardScaler(mesh=mesh8, inputCol="features", outputCol="scaled"),
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=20),
    ])
    model = pipe.fit(f)
    path = str(tmp_path / "pm")
    model.save(path)
    loaded = PipelineModel.load(path)
    np.testing.assert_allclose(
        loaded.transform(f)["prediction"], model.transform(f)["prediction"]
    )
    # unfitted Pipeline round-trips too
    p2 = _roundtrip(Pipeline(stages=[VectorAssembler(inputCols=["a"])]), tmp_path, "p")
    assert len(p2.getStages()) == 1


def test_load_rejects_foreign_class(tmp_path):
    import json, os
    path = str(tmp_path / "evil")
    os.makedirs(path)
    with open(os.path.join(path, "metadata.json"), "w") as fh:
        json.dump({"format_version": 1, "class": "os.path.join", "params": {}}, fh)
    with pytest.raises(ValueError, match="outside sntc_tpu"):
        load_model(path)


def test_orbax_payload_roundtrip(mesh8, tmp_path, monkeypatch):
    """SNTC_CHECKPOINT_FORMAT=orbax writes array payloads through the
    JAX-ecosystem checkpointer (SURVEY.md §5.4 names orbax/npz); loads
    auto-detect the format, so mixed-format repos interoperate."""
    import numpy as np

    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.feature import StandardScaler
    from sntc_tpu.models import LogisticRegression

    rng = np.random.default_rng(1)
    X = rng.normal(size=(400, 5)).astype(np.float32)
    y = (X[:, 1] > 0).astype(np.float64)
    f = Frame({"features": X, "label": y})
    model = Pipeline(stages=[
        StandardScaler(inputCol="features", outputCol="scaled",
                       withMean=True),
        LogisticRegression(featuresCol="scaled", maxIter=10),
    ]).fit(f)

    monkeypatch.setenv("SNTC_CHECKPOINT_FORMAT", "orbax")
    p = str(tmp_path / "orbax_pipe")
    save_model(model, p)
    # every stage dir carries the orbax payload, no npz anywhere
    import glob as _glob
    import os as _os

    assert not _glob.glob(p + "/**/data.npz", recursive=True)
    assert _glob.glob(p + "/**/data.orbax", recursive=True)

    monkeypatch.setenv("SNTC_CHECKPOINT_FORMAT", "npz")  # loads autodetect
    m2 = load_model(p)
    np.testing.assert_array_equal(
        np.asarray(m2.transform(f)["prediction"]),
        np.asarray(model.transform(f)["prediction"]),
    )
    with pytest.raises(ValueError, match="SNTC_CHECKPOINT_FORMAT"):
        monkeypatch.setenv("SNTC_CHECKPOINT_FORMAT", "zarr")
        save_model(model, str(tmp_path / "bad"))


def test_optimizer_checkpoint_orbax(tmp_path, monkeypatch):
    """SNTC_CHECKPOINT_FORMAT=orbax covers MID-FIT optimizer state too
    (same env var, same meaning as model payloads)."""
    import numpy as np

    from sntc_tpu.mlio.optimizer_checkpoint import (
        clear_state, load_state, save_state,
    )

    state = {"x": np.arange(6, dtype=np.float32), "k": np.int32(3)}
    fp = {"problem": "t", "d": 6}
    d = str(tmp_path / "ck")
    monkeypatch.setenv("SNTC_CHECKPOINT_FORMAT", "orbax")
    save_state(d, state, fp)
    back = load_state(d, fp)
    np.testing.assert_array_equal(back["x"], state["x"])
    assert int(back["k"]) == 3
    assert load_state(d, {"problem": "other"}) is None  # fingerprint gate
    # switching format and re-saving replaces the payload cleanly
    monkeypatch.setenv("SNTC_CHECKPOINT_FORMAT", "npz")
    save_state(d, {"x": state["x"] * 2, "k": np.int32(4)}, fp)
    back2 = load_state(d, fp)
    assert int(back2["k"]) == 4
    clear_state(d)
    assert load_state(d, fp) is None
