"""Save/load round-trip contract for every stage (the DefaultReadWriteTest
analog, SURVEY.md §4 item 3)."""

import numpy as np
import pytest

from sntc_tpu.core.base import Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import (
    ChiSqSelector,
    ChiSqSelectorModel,
    IndexToString,
    StandardScaler,
    StringIndexer,
    VectorAssembler,
)
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import LogisticRegression
from sntc_tpu.models.logistic_regression import LogisticRegressionModel


def _roundtrip(stage, tmp_path, name):
    path = str(tmp_path / name)
    save_model(stage, path)
    loaded = load_model(path)
    assert type(loaded) is type(stage)
    got, want = loaded.paramValues(), stage.paramValues()
    got.pop("stages", None), want.pop("stages", None)  # objects; checked by caller
    assert got == want
    assert loaded.uid == stage.uid
    return loaded


def _frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    labels = np.where(y > 0, "attack", "benign").astype(object)
    return Frame({"features": X, "label": y, "labelStr": labels})


def test_transformer_roundtrips(tmp_path):
    _roundtrip(
        VectorAssembler(inputCols=["a", "b"], handleInvalid="skip"),
        tmp_path, "va",
    )
    _roundtrip(
        IndexToString(inputCol="p", outputCol="s", labels=["x", "y"]),
        tmp_path, "its",
    )


def test_fitted_model_roundtrips(tmp_path, mesh8):
    f = _frame()
    si = StringIndexer(inputCol="labelStr", outputCol="idx").fit(f)
    si2 = _roundtrip(si, tmp_path, "si")
    assert si2.labels == si.labels

    sc = StandardScaler(mesh=mesh8, inputCol="features", outputCol="sf").fit(f)
    sc2 = _roundtrip(sc, tmp_path, "sc")
    np.testing.assert_array_equal(sc2.mean, sc.mean)
    np.testing.assert_array_equal(sc2.std, sc.std)

    cs = ChiSqSelector(mesh=mesh8, numTopFeatures=2, labelCol="label").fit(f)
    cs2 = _roundtrip(cs, tmp_path, "cs")
    assert cs2.selected_features == cs.selected_features

    lr = LogisticRegression(mesh=mesh8, maxIter=20).fit(f)
    lr2 = _roundtrip(lr, tmp_path, "lr")
    assert isinstance(lr2, LogisticRegressionModel)
    np.testing.assert_array_equal(lr2.coefficientMatrix, lr.coefficientMatrix)
    out1, out2 = lr.transform(f), lr2.transform(f)
    np.testing.assert_array_equal(out1["prediction"], out2["prediction"])


def test_pipeline_model_roundtrip(tmp_path, mesh8):
    f = _frame(seed=1)
    pipe = Pipeline(stages=[
        StandardScaler(mesh=mesh8, inputCol="features", outputCol="scaled"),
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=20),
    ])
    model = pipe.fit(f)
    path = str(tmp_path / "pm")
    model.save(path)
    loaded = PipelineModel.load(path)
    np.testing.assert_allclose(
        loaded.transform(f)["prediction"], model.transform(f)["prediction"]
    )
    # unfitted Pipeline round-trips too
    p2 = _roundtrip(Pipeline(stages=[VectorAssembler(inputCols=["a"])]), tmp_path, "p")
    assert len(p2.getStages()) == 1


def test_load_rejects_foreign_class(tmp_path):
    import json, os
    path = str(tmp_path / "evil")
    os.makedirs(path)
    with open(os.path.join(path, "metadata.json"), "w") as fh:
        json.dump({"format_version": 1, "class": "os.path.join", "params": {}}, fh)
    with pytest.raises(ValueError, match="outside sntc_tpu"):
        load_model(path)
