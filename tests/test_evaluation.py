"""Oracle: sklearn.metrics reproduces Spark's definitions for these cases."""

import numpy as np
import pytest
from sklearn.metrics import (
    accuracy_score,
    confusion_matrix,
    f1_score,
    precision_score,
    recall_score,
    roc_auc_score,
)

from sntc_tpu.core.frame import Frame
from sntc_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    MulticlassMetrics,
)
from sntc_tpu.evaluation.binary import area_under_pr, area_under_roc


def _pairs(n=3000, k=5, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, k, size=n).astype(np.float64)
    p = np.where(rng.random(n) < 0.7, y, rng.integers(0, k, size=n)).astype(np.float64)
    return y, p


def test_confusion_matrix_matches_sklearn(mesh8):
    y, p = _pairs()
    m = MulticlassMetrics(y, p, mesh=mesh8)
    np.testing.assert_array_equal(m.confusion, confusion_matrix(y, p))


def test_scalar_metrics_match_sklearn(mesh8):
    y, p = _pairs(seed=1)
    m = MulticlassMetrics(y, p, mesh=mesh8)
    assert m.accuracy == pytest.approx(accuracy_score(y, p))
    assert m.weighted_f_measure() == pytest.approx(
        f1_score(y, p, average="weighted"), abs=1e-12
    )
    assert m.macro_f1() == pytest.approx(f1_score(y, p, average="macro"), abs=1e-12)
    assert m.weighted_precision() == pytest.approx(
        precision_score(y, p, average="weighted", zero_division=0), abs=1e-12
    )
    assert m.weighted_recall() == pytest.approx(
        recall_score(y, p, average="weighted", zero_division=0), abs=1e-12
    )


def test_zero_division_convention(mesh8):
    # class 2 never predicted, class 3 never true -> 0/0 -> 0 (Spark)
    y = np.array([0, 0, 1, 2.0])
    p = np.array([0, 1, 1, 3.0])
    m = MulticlassMetrics(y, p, mesh=mesh8)
    assert m.precision_by_label()[2] == 0.0
    assert m.recall_by_label()[3] == 0.0
    assert m.f_measure_by_label()[2] == 0.0
    # macroF1 averages only over classes present in TRUE labels
    present_f1 = m.f_measure_by_label()[:3]
    assert m.macro_f1() == pytest.approx(present_f1.mean())


def test_evaluator_facade(mesh8):
    y, p = _pairs(seed=2)
    f = Frame({"label": y, "prediction": p})
    ev = MulticlassClassificationEvaluator(metricName="f1", mesh=mesh8)
    assert ev.evaluate(f) == pytest.approx(f1_score(y, p, average="weighted"))
    ev2 = MulticlassClassificationEvaluator(metricName="macroF1", mesh=mesh8)
    assert ev2.evaluate(f) == pytest.approx(f1_score(y, p, average="macro"))
    with pytest.raises(ValueError):
        MulticlassClassificationEvaluator(metricName="bogus")


def test_auc_matches_sklearn():
    rng = np.random.default_rng(3)
    y = rng.integers(0, 2, size=500).astype(np.float64)
    s = rng.normal(size=500) + y * 1.5
    assert area_under_roc(y, s) == pytest.approx(roc_auc_score(y, s), abs=1e-12)
    # with heavy score ties (grouped thresholds)
    s_tied = np.round(s)
    assert area_under_roc(y, s_tied) == pytest.approx(
        roc_auc_score(y, s_tied), abs=1e-12
    )


def test_auc_pr_known_value():
    # perfect ranking -> AUPR 1.0; random-ish score sanity bounds
    y = np.array([0, 0, 1, 1.0])
    s = np.array([0.1, 0.2, 0.8, 0.9])
    assert area_under_pr(y, s) == pytest.approx(1.0)
    assert area_under_roc(y, s) == pytest.approx(1.0)


def test_binary_evaluator_uses_raw_column():
    y = np.array([0, 1, 1, 0.0])
    raw = np.array([[0.6, -0.6], [-2.0, 2.0], [-1.0, 1.0], [0.5, -0.5]])
    f = Frame({"label": y, "rawPrediction": raw})
    ev = BinaryClassificationEvaluator()
    assert ev.evaluate(f) == pytest.approx(1.0)


def test_extended_multiclass_metrics_match_sklearn(mesh8):
    from sklearn.metrics import (
        hamming_loss as sk_hamming,
        log_loss as sk_logloss,
        precision_score,
        recall_score,
    )

    rng = np.random.default_rng(9)
    y = rng.integers(0, 4, size=500).astype(np.float64)
    p = rng.integers(0, 4, size=500).astype(np.float64)
    prob = rng.dirichlet(np.ones(4), size=500)
    f = Frame({"label": y, "prediction": p, "probability": prob})

    def ev(name, **kw):
        return MulticlassClassificationEvaluator(
            metricName=name, mesh=mesh8, **kw
        ).evaluate(f)

    assert ev("hammingLoss") == pytest.approx(sk_hamming(y, p))
    assert ev("logLoss") == pytest.approx(
        sk_logloss(y, prob, labels=[0, 1, 2, 3])
    )
    assert ev("precisionByLabel", metricLabel=2) == pytest.approx(
        precision_score(y, p, labels=[2], average="macro", zero_division=0)
    )
    assert ev("recallByLabel", metricLabel=3) == pytest.approx(
        recall_score(y, p, labels=[3], average="macro", zero_division=0)
    )
    assert ev("truePositiveRateByLabel", metricLabel=1) == pytest.approx(
        recall_score(y, p, labels=[1], average="macro", zero_division=0)
    )
    # FPR by label: FP / negatives, cross-checked by hand
    fp = ((p == 2) & (y != 2)).sum()
    assert ev("falsePositiveRateByLabel", metricLabel=2) == pytest.approx(
        fp / (y != 2).sum()
    )
    assert ev("weightedTruePositiveRate") == pytest.approx(
        ev("weightedRecall")
    )
    # smaller-is-better metrics invert the tuning direction
    assert not MulticlassClassificationEvaluator(
        metricName="logLoss"
    ).isLargerBetter()
    assert MulticlassClassificationEvaluator(metricName="f1").isLargerBetter()


def test_by_label_metric_absent_class_and_negative(mesh8):
    f = Frame({
        "label": np.array([0.0, 1.0, 1.0]),
        "prediction": np.array([0.0, 1.0, 0.0]),
    })
    # class 5 absent everywhere: 0/0 -> 0, not IndexError
    v = MulticlassClassificationEvaluator(
        metricName="recallByLabel", metricLabel=5, mesh=mesh8
    ).evaluate(f)
    assert v == 0.0
    with pytest.raises(ValueError, match="metricLabel"):
        MulticlassClassificationEvaluator(
            metricName="recallByLabel", metricLabel=-1
        )


def test_regression_evaluator_matches_sklearn():
    """rmse/mse/mae/r2 vs sklearn.metrics on random data, incl. weights;
    var = Spark explainedVariance (SS_reg/n about the label mean)."""
    from sklearn.metrics import (
        mean_absolute_error,
        mean_squared_error,
        r2_score,
    )

    from sntc_tpu.evaluation import RegressionEvaluator

    rng = np.random.default_rng(5)
    y = rng.normal(size=500) * 3 + 1
    pred = y + rng.normal(size=500) * 0.7
    w = rng.uniform(0.5, 2.0, size=500)
    f = Frame({"label": y, "prediction": pred, "w": w})

    def ev(name, weight=None):
        return RegressionEvaluator(
            metricName=name, weightCol=weight
        ).evaluate(f)

    assert ev("mse") == pytest.approx(mean_squared_error(y, pred))
    assert ev("rmse") == pytest.approx(np.sqrt(mean_squared_error(y, pred)))
    assert ev("mae") == pytest.approx(mean_absolute_error(y, pred))
    assert ev("r2") == pytest.approx(r2_score(y, pred))
    assert ev("mse", "w") == pytest.approx(
        mean_squared_error(y, pred, sample_weight=w)
    )
    assert ev("r2", "w") == pytest.approx(
        r2_score(y, pred, sample_weight=w)
    )
    ybar = np.average(y, weights=w)
    assert ev("var", "w") == pytest.approx(
        np.average((pred - ybar) ** 2, weights=w)
    )
    assert not RegressionEvaluator(metricName="rmse").isLargerBetter()
    assert RegressionEvaluator(metricName="r2").isLargerBetter()
    with pytest.raises(ValueError):
        RegressionEvaluator(metricName="nope")
