"""Backend-probe policy: requested platform wins, cpu-only in-process
pins skip the probe (no 3-minute stall in tests/embedders), disabled
probe trusts the backend."""

import jax

from sntc_tpu.utils.backend_probe import (
    probe_default_backend,
    resolve_platform,
)


def test_requested_platform_wins():
    assert resolve_platform("cpu") == "cpu"
    assert resolve_platform("tpu") == "tpu"


def test_cpu_only_pin_skips_probe():
    # conftest pins jax_platforms to cpu in-process: resolving must
    # return instantly (no subprocess probe) and trust the pin
    assert jax.config.jax_platforms and all(
        p.strip() == "cpu" for p in jax.config.jax_platforms.split(",")
    )
    assert resolve_platform(None) is None


def test_probe_disabled_trusts_backend():
    assert probe_default_backend(timeout_s=0) is True


def test_specific_env_overrides_generic(monkeypatch):
    monkeypatch.setenv("SNTC_PROBE_TIMEOUT_S", "180")
    monkeypatch.setenv("TOOL_PROBE_TIMEOUT_S", "0")
    # the tool-specific 0 must win -> probe disabled -> instant True
    assert (
        probe_default_backend(specific_env="TOOL_PROBE_TIMEOUT_S") is True
    )
