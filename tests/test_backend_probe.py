"""Backend-probe policy: requested platform wins, cpu-only in-process
pins skip the probe (no 3-minute stall in tests/embedders), disabled
probe trusts the backend."""

import jax

from sntc_tpu.utils.backend_probe import (
    _ok_marker,
    probe_default_backend,
    resolve_platform,
)


def test_requested_platform_wins():
    assert resolve_platform("cpu") == "cpu"
    assert resolve_platform("tpu") == "tpu"


def test_cpu_only_pin_skips_probe():
    # conftest pins jax_platforms to cpu in-process: resolving must
    # return instantly (no subprocess probe) and trust the pin
    assert jax.config.jax_platforms and all(
        p.strip() == "cpu" for p in jax.config.jax_platforms.split(",")
    )
    assert resolve_platform(None) is None


def test_probe_disabled_trusts_backend():
    assert probe_default_backend(timeout_s=0) is True


def test_specific_env_overrides_generic(monkeypatch):
    monkeypatch.setenv("SNTC_PROBE_TIMEOUT_S", "180")
    monkeypatch.setenv("TOOL_PROBE_TIMEOUT_S", "0")
    # the tool-specific 0 must win -> probe disabled -> instant True
    assert (
        probe_default_backend(specific_env="TOOL_PROBE_TIMEOUT_S") is True
    )


def test_malformed_timeout_env_falls_back(monkeypatch, capsys, tmp_path):
    # ADVICE r4: an empty/garbage timeout env must not crash startup.
    # The real probe subprocess would hang 180 s on this host class when
    # the tunnel is down (sitecustomize re-pins the platform regardless
    # of env) — stub it; the parse path is what's under test.  The marker
    # is redirected into tmp_path (a fake success WRITES the marker, so a
    # shared fixed path would leak a fresh marker into later runs).
    import subprocess as sp

    calls = {}

    def fake_run(cmd, timeout=None, **kw):
        calls["timeout"] = timeout
        return sp.CompletedProcess(cmd, 0)

    import sntc_tpu.utils.backend_probe as bp

    monkeypatch.setattr(bp.subprocess, "run", fake_run)
    marker = tmp_path / "probe-marker"
    monkeypatch.setattr(bp, "_ok_marker", lambda: str(marker))
    monkeypatch.setenv("SNTC_PROBE_TIMEOUT_S", "not-a-number")
    # single attempt so the total budget == per-attempt timeout (r6
    # splits the budget across SNTC_PROBE_ATTEMPTS)
    monkeypatch.setenv("SNTC_PROBE_ATTEMPTS", "1")
    assert probe_default_backend() is True
    assert calls["timeout"] == 180.0  # fell back to the default
    assert marker.exists()  # success cached — in tmp_path, not ~
    assert "malformed probe timeout" in capsys.readouterr().err


def test_ok_marker_keyed_on_platform_env(monkeypatch):
    # ADVICE r4: a success cached under JAX_PLATFORMS=cpu must not
    # suppress the probe for tunnel-default (unset) processes
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    cpu_marker = _ok_marker()
    monkeypatch.delenv("JAX_PLATFORMS")
    default_marker = _ok_marker()
    assert cpu_marker != default_marker
