"""Supervision layer (r7): circuit breakers, health & watchdog, load
shedding, preemption-safe drain, the bounded event ring, the strict
SNTC_FAULTS grammar, the fault-site drift check, and the kill-at-
fault-point chaos crash matrix.  Breaker/health/watchdog tests run on
injectable clocks — fully deterministic, no sleeps."""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    HealthMonitor,
    HealthState,
    QuerySupervisor,
)
from sntc_tpu.resilience.supervisor import DRAIN_MARKER

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _frames(n_batches, rows=8):
    return [
        Frame({"x": np.arange(rows, dtype=np.float64) + 100 * b})
        for b in range(n_batches)
    ]


def _query(tmp_path, src_frames, sink=None, **kw):
    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    src = MemorySource(src_frames)
    sink = sink if sink is not None else MemorySink()
    q = StreamingQuery(
        _Identity(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, **kw,
    )
    return q, sink


# ---------------------------------------------------------------------------
# circuit breaker: the state machine, on an injectable clock
# ---------------------------------------------------------------------------


def test_breaker_opens_on_failure_rate_window():
    clk = FakeClock()
    br = CircuitBreaker(
        "t.site", window=4, failure_threshold=0.5, min_calls=4,
        cooldown_s=10.0, clock=clk,
    )
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # 2 outcomes < min_calls
    br.record_success()
    br.record_failure()  # 4 outcomes, rate 0.75 >= 0.5
    assert br.state == "open"
    assert not br.allow()
    assert br.retry_after_s() == pytest.approx(10.0)
    opened = R.recent_events(site="t.site", event="breaker_open")
    assert len(opened) == 1 and opened[0]["failure_rate"] == 0.75


def test_breaker_half_open_probe_failure_reopens():
    clk = FakeClock()
    br = CircuitBreaker(
        "t.site", window=2, failure_threshold=1.0, min_calls=2,
        cooldown_s=5.0, clock=clk,
    )
    br.record_failure()
    br.record_failure()
    assert br.state == "open"
    clk.t = 5.0
    assert br.state == "half_open"
    assert R.recent_events(site="t.site", event="breaker_half_open")
    assert br.allow()       # the single probe slot
    assert not br.allow()   # no second probe
    br.record_failure()     # probe failed: fresh cooldown
    assert br.state == "open"
    clk.t = 9.9
    assert not br.allow()   # cooldown restarted at t=5
    clk.t = 10.0
    assert br.allow()
    br.record_success()
    assert br.state == "closed"
    assert R.recent_events(site="t.site", event="breaker_closed")


def test_breaker_call_wrapper_and_snapshot():
    clk = FakeClock()
    br = CircuitBreaker(
        "t.call", window=2, failure_threshold=1.0, min_calls=2,
        cooldown_s=30.0, clock=clk,
    )
    assert br.call(lambda: "ok") == "ok"
    for _ in range(2):
        with pytest.raises(ValueError):
            br.call(lambda: (_ for _ in ()).throw(ValueError("down")))
    with pytest.raises(CircuitOpenError) as ei:
        br.call(lambda: "never runs")
    assert ei.value.site == "t.call"
    snap = br.snapshot()
    assert snap["state"] == "open" and snap["open_count"] == 1
    assert snap["retry_after_s"] == pytest.approx(30.0)


def test_breaker_registry():
    a = R.breaker_for("reg.site", cooldown_s=1.0)
    assert R.breaker_for("reg.site") is a
    a.record_failure()
    assert "reg.site" in R.breakers_snapshot()
    R.reset_breakers()
    assert R.breakers_snapshot() == {}


# ---------------------------------------------------------------------------
# breaker wired into the streaming engine: defer, cool down, recover
# ---------------------------------------------------------------------------


def test_streaming_sink_breaker_defers_then_recovers(tmp_path):
    from sntc_tpu.serve import MemorySink

    class DownSink(MemorySink):
        def __init__(self):
            super().__init__()
            self.down = True
            self.calls = 0

        def add_batch(self, batch_id, frame):
            self.calls += 1
            if self.down:
                raise IOError("sink down")
            super().add_batch(batch_id, frame)

    clk = FakeClock()
    br = CircuitBreaker(
        "sink.write", window=4, failure_threshold=1.0, min_calls=2,
        cooldown_s=60.0, clock=clk,
    )
    q, sink = _query(
        tmp_path, _frames(3), sink=DownSink(),
        max_batch_failures=100, breakers={"sink.write": br},
    )
    assert q.process_available() == 0  # round 1 fails, defers
    assert q.process_available() == 0  # round 2 fails -> breaker opens
    assert br.state == "open"
    calls_when_open = sink.calls
    # while open the engine defers WITHOUT touching the sink
    assert q.process_available() == 0
    assert sink.calls == calls_when_open
    assert q.last_committed() == -1  # nothing skipped, batch still queued
    # dependency heals + cooldown elapses -> half-open probe commits
    sink.down = False
    clk.t = 60.0
    assert q.process_available() == 3
    assert br.state == "closed"
    assert [i for i, _ in sink.batches] == [0, 1, 2]


def test_streaming_predict_breaker_defers(tmp_path):
    class BoomModel(Transformer):
        def __init__(self):
            super().__init__()
            self.down = True

        def transform(self, frame):
            if self.down:
                raise RuntimeError("model down")
            return frame

    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    class CountingSource(MemorySource):
        def __init__(self, frames):
            super().__init__(frames)
            self.reads = 0

        def get_batch(self, start, end):
            self.reads += 1
            return super().get_batch(start, end)

    clk = FakeClock()
    br = CircuitBreaker(
        "predict.dispatch", window=4, failure_threshold=1.0, min_calls=2,
        cooldown_s=60.0, clock=clk,
    )
    model = BoomModel()
    src = CountingSource(_frames(2))
    sink = MemorySink()
    q = StreamingQuery(
        model, src, sink, str(tmp_path / "ckpt"), max_batch_offsets=1,
        max_batch_failures=100, breakers={"predict.dispatch": br},
    )
    assert q.process_available() == 0
    assert q.process_available() == 0
    assert br.state == "open"
    model.down = False
    # while OPEN the engine defers BEFORE reading: no wasted batch read
    # per poll tick during an outage
    reads_when_open = src.reads
    assert q.process_available() == 0
    assert src.reads == reads_when_open
    clk.t = 60.0
    assert q.process_available() == 2
    assert br.state == "closed"
    assert len(sink.frames) == 2


# ---------------------------------------------------------------------------
# health monitor + watchdog
# ---------------------------------------------------------------------------


def test_health_report_overall_and_changed_events():
    h = HealthMonitor()
    assert h.overall() == HealthState.OK
    h.report("sink.write", HealthState.DEGRADED, "flaky")
    h.report("engine", HealthState.OK)
    assert h.overall() == HealthState.DEGRADED
    h.report("sink.write", HealthState.UNHEALTHY, "dead")
    assert h.overall() == HealthState.UNHEALTHY
    snap = h.snapshot()
    assert snap["overall"] == "UNHEALTHY"
    assert snap["components"]["sink.write"]["reason"] == "dead"
    changed = R.recent_events(event="health_changed")
    assert [(e["component"], e["state"]) for e in changed] == [
        ("sink.write", "DEGRADED"), ("engine", "OK"),
        ("sink.write", "UNHEALTHY"),
    ]
    # unchanged state: no new event
    h.report("engine", HealthState.OK)
    assert len(R.recent_events(event="health_changed")) == 3


def test_health_aggregates_from_event_stream():
    h = HealthMonitor().attach()
    try:
        R.emit_event(event="retry", site="sink.write", attempt=1)
        assert h.state_of("sink.write") == HealthState.DEGRADED
        R.emit_event(event="retry_exhausted", site="sink.write", attempts=3)
        assert h.state_of("sink.write") == HealthState.UNHEALTHY
        R.emit_event(event="retry_success", site="sink.write", attempts=2)
        assert h.state_of("sink.write") == HealthState.OK
        assert h.overall() == HealthState.OK
    finally:
        h.detach()
    # detached: events no longer move health
    R.emit_event(event="quarantine", site="sink.write")
    assert h.state_of("sink.write") == HealthState.OK


def test_watchdog_flags_stalled_batch_once():
    clk = FakeClock()
    h = HealthMonitor(max_batch_wall_time=5.0, clock=clk)
    h.batch_started(7)
    clk.t = 4.0
    assert h.check_watchdog() == []
    clk.t = 6.0
    assert h.check_watchdog() == [7]
    assert h.state_of("engine") == HealthState.UNHEALTHY
    stalls = R.recent_events(event="watchdog_stall")
    assert len(stalls) == 1 and stalls[0]["batch_id"] == 7
    assert h.check_watchdog() == []  # one alarm per stalled batch
    h.batch_finished(7)
    assert h.check_watchdog() == []


# ---------------------------------------------------------------------------
# supervisor: load shedding
# ---------------------------------------------------------------------------


def test_shed_oldest_caps_backlog_and_journals(tmp_path):
    q, sink = _query(tmp_path, _frames(10))
    sup = QuerySupervisor(q, max_pending_batches=2, shed_policy="oldest")
    try:
        rec = sup.maybe_shed()
        assert rec["offsets_shed"] == 8
        assert rec["start"] == 0 and rec["end"] == 8
        assert sup.shed_total_offsets == 8
        # the freshest two offsets survive and commit
        assert q.process_available() == 2
        assert [int(f["x"][0]) for f in sink.frames] == [800, 900]
        # journaled evidence + structured event + degraded health
        shed_log = os.path.join(str(tmp_path / "ckpt"), "shed.jsonl")
        records = [json.loads(ln) for ln in open(shed_log)]
        assert len(records) == 1 and records[0]["policy"] == "oldest"
        assert R.recent_events(event="load_shed")
        assert sup.health.state_of("engine") == HealthState.DEGRADED
        # under the cap: no further shedding
        assert sup.maybe_shed() is None
    finally:
        sup.close()


def test_shed_sample_processes_backlog_at_stride(tmp_path):
    q, sink = _query(tmp_path, _frames(10))
    sup = QuerySupervisor(q, max_pending_batches=2, shed_policy="sample")
    try:
        rec = sup.maybe_shed()
        assert rec["sample_stride"] == 5  # ceil(10 pending / 2 kept)
        assert q.process_available() == 1  # ONE batch covers everything
        # 10 frames x 8 rows = 80 rows, stride 5 -> 16 rows survive
        assert sink.frames[0].num_rows == 16
        np.testing.assert_array_equal(
            sink.frames[0]["x"][:2], [0.0, 5.0]
        )
        # the stride is IN the committed intent -> a replay reproduces
        # the identical sample
        with open(
            os.path.join(str(tmp_path / "ckpt"), "commits", "0.json")
        ) as f:
            intent = json.load(f)
        assert intent["sample_stride"] == 5
        assert intent["start"] == 0 and intent["end"] == 10
    finally:
        sup.close()


def test_shed_sample_replays_identically_after_crash(tmp_path):
    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    frames = _frames(10)
    q, _ = _query(tmp_path, frames)
    sup = QuerySupervisor(q, max_pending_batches=2, shed_policy="sample")
    try:
        sup.maybe_shed()
        R.arm("stream.commit", times=1)  # crash post-sink, pre-commit
        with pytest.raises(R.InjectedFault):
            q.process_available()
    finally:
        R.clear()
        sup.close()
    # restart: the WAL'd sampled intent replays with the same stride
    sink2 = MemorySink()
    q2 = StreamingQuery(
        _Identity(), MemorySource(frames), sink2,
        str(tmp_path / "ckpt"), max_batch_offsets=1,
    )
    assert q2.process_available() == 1
    assert sink2.frames[0].num_rows == 16
    np.testing.assert_array_equal(
        sink2.frames[0]["x"], np.concatenate([f["x"] for f in frames])[::5]
    )


# ---------------------------------------------------------------------------
# supervisor: drain + run loop
# ---------------------------------------------------------------------------


def test_supervisor_drain_commits_in_flight_and_writes_marker(tmp_path):
    q, sink = _query(tmp_path, _frames(4), pipeline_depth=3)
    # dispatch two batches without retiring either (in flight at the
    # moment the preemption notice lands)
    assert q._dispatch_next() and q._dispatch_next()
    assert len(q._in_flight) == 2
    sup = QuerySupervisor(q)
    try:
        sup.request_drain("SIGTERM")
        status = sup.run(poll_interval=0.01)
    finally:
        sup.close()
    assert status["drained"] is True
    assert status["engine"]["in_flight"] == 0
    assert q.last_committed() == 1  # both in-flight batches committed
    marker_path = os.path.join(str(tmp_path / "ckpt"), DRAIN_MARKER)
    marker = json.load(open(marker_path))
    assert marker["reason"] == "SIGTERM"
    assert marker["last_committed"] == 1
    assert marker["in_flight_left"] == 0
    assert R.recent_events(event="drained")
    # restart resumes exactly-once: only the two undispatched batches
    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    sink2 = MemorySink()
    q2 = StreamingQuery(
        _Identity(), MemorySource(_frames(4)), sink2,
        str(tmp_path / "ckpt"), max_batch_offsets=1,
    )
    assert q2.process_available() == 2
    assert [i for i, _ in sink2.batches] == [2, 3]


def test_health_site_recovers_after_quarantine(tmp_path):
    """One poison batch must not pin sink.write UNHEALTHY for the life
    of the process: the next CLEAN commit proves the stage recovered
    (first-attempt successes never emit retry_success, so this is the
    only recovery signal)."""
    from sntc_tpu.serve import MemorySink

    class Poison0(MemorySink):
        def add_batch(self, batch_id, frame):
            if batch_id == 0:
                raise IOError("poison")
            super().add_batch(batch_id, frame)

    q, sink = _query(
        tmp_path, _frames(2), sink=Poison0(), max_batch_failures=1
    )
    sup = QuerySupervisor(q)
    try:
        assert sup.tick() == 1  # batch 0 quarantined + committed
        assert sup.health.state_of("sink.write") == HealthState.UNHEALTHY
        assert sup.tick() == 1  # batch 1 commits cleanly
        assert sup.health.state_of("sink.write") == HealthState.OK
        assert sup.health.overall() == HealthState.OK
    finally:
        sup.close()


def test_watchdog_flags_batch_deferring_across_ticks(tmp_path):
    """A batch that keeps DEFERRING (sink down, rounds below the
    quarantine threshold) ages across ticks: fast failing ticks must
    not reset the watchdog clock each round."""
    from sntc_tpu.serve import MemorySink

    class AlwaysDown(MemorySink):
        def add_batch(self, batch_id, frame):
            raise IOError("down")

    clk = FakeClock()
    q, _ = _query(
        tmp_path, _frames(1), sink=AlwaysDown(), max_batch_failures=100
    )
    sup = QuerySupervisor(q, max_batch_wall_time=5.0, clock=clk)
    try:
        assert sup.tick() == 0  # round 1 defers; batch 0 starts aging
        clk.t = 6.0
        assert sup.tick() == 0  # still deferring: original start kept
        assert sup.health.check_watchdog() == [0]
        assert sup.health.state_of("engine") == HealthState.UNHEALTHY
    finally:
        sup.close()


def test_watchdog_ignores_idle_stream(tmp_path):
    """No data and nothing in flight: the tick must not start aging a
    PHANTOM batch — an idle watch directory is healthy, not stalled."""
    clk = FakeClock()
    q, _ = _query(tmp_path, [])  # empty source
    sup = QuerySupervisor(q, max_batch_wall_time=5.0, clock=clk)
    try:
        assert sup.tick() == 0  # idle tick
        clk.t = 60.0
        assert sup.health.check_watchdog() == []
        assert sup.health.state_of("engine") != HealthState.UNHEALTHY
    finally:
        sup.close()


def test_shed_sample_not_rejournaled_while_pending(tmp_path):
    """A sample decision awaiting consumption (dispatch deferred) must
    not be re-journaled every poll tick."""
    q, sink = _query(tmp_path, _frames(10))
    sup = QuerySupervisor(q, max_pending_batches=2, shed_policy="sample")
    try:
        assert sup.maybe_shed() is not None
        for _ in range(5):  # breaker-open-style ticks: nothing consumed
            assert sup.maybe_shed() is None
        shed_log = os.path.join(str(tmp_path / "ckpt"), "shed.jsonl")
        assert len(open(shed_log).readlines()) == 1
        assert len(R.recent_events(event="load_shed")) == 1
        # once consumed, a NEW backlog decision is possible again
        assert q.process_available() == 1
        assert sup.maybe_shed() is None  # backlog drained
    finally:
        sup.close()


def test_engine_health_recovers_after_watchdog_stall(tmp_path):
    """UNHEALTHY from a past stall must not latch forever: the stalled
    batch finishing (a committing tick) is the recovery evidence."""
    q, sink = _query(tmp_path, _frames(2))
    sup = QuerySupervisor(q)
    try:
        sup.health.report(
            "engine", HealthState.UNHEALTHY, "batch 0 stalled"
        )
        assert sup.tick() == 1
        assert sup.health.state_of("engine") == HealthState.OK
    finally:
        sup.close()


def test_serve_cli_defaults_degrade_not_die(tmp_path, capsys):
    """The serve CLI arms retry + quarantine by default: a poison input
    file dead-letters and the drain exits 0 instead of the first error
    killing the supervised process (where breakers could never open)."""
    import csv
    import json as _json
    import threading

    from sntc_tpu.app import main

    watch = tmp_path / "in"
    watch.mkdir()
    for i, rows in enumerate([3, 3]):
        with open(watch / f"in_{i}.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["x"])
            for r in range(rows):
                w.writerow([i * 10 + r])
    (watch / "in_0.csv").write_text("x\nnot,a,valid,row\n1,2\n")  # torn

    from sntc_tpu.feature import Binarizer
    from sntc_tpu.mlio import save_model

    model_dir = str(tmp_path / "model")
    save_model(
        Binarizer(inputCol="x", outputCol="prediction", threshold=5.0),
        model_dir,
    )
    # run the REAL cmd_serve loop on a thread; drain via a timer
    from sntc_tpu.resilience import supervisor as sup_mod

    drained = threading.Event()
    orig_run = sup_mod.QuerySupervisor.run

    def run_and_capture(self, *a, **kw):
        threading.Timer(0.5, lambda: self.request_drain("test")).start()
        try:
            return orig_run(self, *a, **kw)
        finally:
            drained.set()

    sup_mod.QuerySupervisor.run = run_and_capture
    try:
        rc = main([
            "serve", "--model", model_dir, "--watch", str(watch),
            "--out", str(tmp_path / "out"), "--checkpoint",
            str(tmp_path / "ckpt"), "--max-files-per-batch", "1",
            "--poll-interval", "0.05", "--max-batch-failures", "1",
            "--batch-retry-attempts", "1", "--platform", "cpu",
        ])
    finally:
        sup_mod.QuerySupervisor.run = orig_run
    assert rc == 0 and drained.is_set()
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["drained"] is True
    assert out["batches"] == 2  # poison batch quarantined + committed
    dl = tmp_path / "ckpt" / "dead_letter" / "dead_letter.jsonl"
    assert dl.exists()  # the torn file's evidence
    assert (tmp_path / "ckpt" / "drain_marker.json").exists()


def test_supervisor_run_commits_and_writes_health_json(tmp_path):
    health_path = str(tmp_path / "health.json")
    q, sink = _query(tmp_path, _frames(3))
    sup = QuerySupervisor(q, health_json=health_path)
    try:
        status = sup.run(poll_interval=0.01, max_batches=3)
    finally:
        sup.close()
    assert status["engine"]["batches_done"] == 3
    assert len(sink.frames) == 3
    dump = json.load(open(health_path))
    assert dump["engine"]["last_committed"] == 2
    assert dump["health"]["overall"] == "OK"
    assert "breakers" in dump and "events_dropped" in dump
    assert not status["drained"]


# ---------------------------------------------------------------------------
# bounded, thread-safe event ring
# ---------------------------------------------------------------------------


def test_event_ring_bounded_with_drop_counter():
    for i in range(600):
        R.emit_event(event="ring_test", i=i)
    events = R.recent_events(event="ring_test")
    assert len(events) == 512  # hard cap
    assert R.events_dropped() == 88  # evictions counted, not silent
    assert events[0]["i"] == 88  # oldest records were the ones dropped
    assert events[-1]["i"] == 599
    R.clear_events()
    assert R.events_dropped() == 0


def test_event_ring_thread_safe():
    n_threads, per_thread = 8, 300
    errors = []

    def emit_and_read(tid):
        try:
            for i in range(per_thread):
                R.emit_event(event="mt_test", tid=tid, i=i)
                if i % 50 == 0:
                    R.recent_events(event="mt_test")
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=emit_and_read, args=(t,))
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    kept = len(R.recent_events(event="mt_test"))
    assert kept == 512
    assert kept + R.events_dropped() == n_threads * per_thread


# ---------------------------------------------------------------------------
# SNTC_FAULTS grammar: every failure mode names the offending segment
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("raw, match", [
    ("sink.write:exc:0.5:1:9", r"'sink.write:exc:0.5:1:9'.*at most 4"),
    (":exc", r"':exc'.*empty site"),
    ("sink.write:bogus", r"'sink.write:bogus'.*unknown kind 'bogus'"),
    ("sink.write:exc:zzz", r"'sink.write:exc:zzz'.*not a float"),
    ("sink.write:exc:1.5", r"'sink.write:exc:1.5'.*lie in \[0, 1\]"),
    ("sink.write:exc:0.5:xx", r"'sink.write:exc:0.5:xx'.*not an int"),
])
def test_parse_faults_env_names_offending_segment(raw, match):
    with pytest.raises(ValueError, match=match):
        R.parse_faults_env("stream.read," + raw)  # good specs unaffected


def test_parse_faults_env_accepts_kill_kind():
    assert R.parse_faults_env("sink.write:kill:1.0:3") == [
        {"site": "sink.write", "kind": "kill", "prob": 1.0, "seed": 3}
    ]


# ---------------------------------------------------------------------------
# fault-site drift check (the tier-1 wiring of scripts/check_fault_sites)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fault_sites_documented_and_declared():
    checker = _load_script("check_fault_sites")
    assert checker.check() == []
    # the checker itself must see every declared site wired
    assert checker.code_sites() == set(R.SITES)


# ---------------------------------------------------------------------------
# bench journaling: resilience evidence rides along
# ---------------------------------------------------------------------------


def test_bench_resilience_summary_counts_events_and_breakers():
    import sys

    sys.path.insert(0, REPO)
    import bench

    assert bench._resilience_summary() is None  # clean run: no field
    R.emit_event(event="retry", site="sink.write", attempt=1)
    R.emit_event(event="retry", site="sink.write", attempt=2)
    br = R.breaker_for("sink.write", min_calls=1, failure_threshold=1.0)
    br.record_failure()
    summary = bench._resilience_summary()
    assert summary["event_counts"]["retry"] == 2
    assert summary["breakers"]["sink.write"]["state"] == "open"
    assert summary["events_dropped"] == 0
    # the summary is a DELTA per journal record: a multi-config sweep
    # must not attribute config 1's retries to later configs
    R.reset_breakers()
    assert bench._resilience_summary() is None
    R.emit_event(event="retry", site="stream.read", attempt=1)
    assert bench._resilience_summary()["event_counts"] == {"retry": 1}


# ---------------------------------------------------------------------------
# chaos crash matrix: kill the engine at each protocol boundary in a
# REAL child process; restart must converge to the reference state
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos():
    return _load_script("chaos_crash_matrix")


@pytest.fixture(scope="module")
def chaos_reference(chaos, tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("chaos"))
    return workdir, chaos.run_reference(workdir)


def test_chaos_kill_matrix_exactly_once(chaos, chaos_reference):
    workdir, reference = chaos_reference
    # sanity on the reference itself: 4 input files -> 4 committed
    # single-offset batches, 6 rows each
    assert sorted(reference["commits"]) == [0, 1, 2, 3]
    assert set(reference["rows"].values()) == {6}
    for site in chaos.KILL_SITES:
        verdict = chaos.run_kill_scenario(workdir, site, reference)
        assert verdict["ok"], verdict


def test_chaos_sigterm_drains_and_exits_zero(chaos, chaos_reference):
    workdir, _ = chaos_reference
    verdict = chaos.run_drain_scenario(workdir)
    if not verdict["ok"]:
        # timing-sensitive subprocess scenario: under full-suite load
        # the SIGTERM/child-startup race can flake — retry ONCE with
        # the first verdict printed, never silently absorbed (the
        # bench rendezvous-retry pattern)
        print("first drain verdict:", json.dumps(verdict))
        verdict = chaos.run_drain_scenario(os.path.join(workdir, "retry"))
    assert verdict["ok"], verdict
    assert verdict["rc"] == 0
    assert verdict["marker"]["reason"] == "SIGTERM"


def test_chaos_kill_matrix_pipelined_exactly_once(chaos, chaos_reference):
    """r8 satellite: the kill matrix in PIPELINED mode (prefetching
    source + shape buckets + overlapped sink delivery) must converge to
    the SERIAL reference's commits and sink rows at every boundary."""
    workdir, reference = chaos_reference
    for site in chaos.KILL_SITES:
        verdict = chaos.run_kill_scenario(
            workdir, site, reference, pipelined=True
        )
        assert verdict["ok"], verdict
        assert verdict["pipelined"] is True


def test_chaos_sigterm_drains_pipelined(chaos, chaos_reference):
    """Drain scenario with the pipelined engine: SIGTERM must settle
    the delivery thread's in-air batch, commit, and exit 0."""
    workdir, _ = chaos_reference
    verdict = chaos.run_drain_scenario(workdir, pipelined=True)
    if not verdict["ok"]:
        # same timing-sensitive retry discipline as the serial drain
        print("first pipelined drain verdict:", json.dumps(verdict))
        verdict = chaos.run_drain_scenario(
            os.path.join(workdir, "retry_pipelined"), pipelined=True
        )
    assert verdict["ok"], verdict
    assert verdict["rc"] == 0


# ---------------------------------------------------------------------------
# chaos: kill mid-promotion (r11) — the model-lifecycle publish/swap
# protocol dies at each of its three boundaries in a REAL child
# process; the restart must converge to the reference commits with the
# CORRECT model (incumbent or promoted candidate) serving
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def promotion_reference(chaos, tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("chaos_promote"))
    return workdir, chaos.run_promotion_reference(workdir)


def test_chaos_promotion_reference_shape(chaos, promotion_reference):
    _, reference = promotion_reference
    # 2 batches under the incumbent (class 0), promotion, 2 under the
    # candidate (class 1) — and all four batches committed exactly once
    assert sorted(reference["commits"]) == [0, 1, 2, 3]
    assert reference["predictions"] == {
        "batch_000000.csv": [0.0], "batch_000001.csv": [0.0],
        "batch_000002.csv": [1.0], "batch_000003.csv": [1.0],
    }


def test_chaos_kill_mid_promotion_converges(chaos, promotion_reference):
    workdir, reference = promotion_reference
    for point in chaos.PROMOTE_KILL_POINTS:
        verdict = chaos.run_promotion_kill_scenario(
            workdir, point, reference
        )
        assert verdict["ok"], verdict
        # pre-publish: the incumbent keeps serving; once the publish
        # reached disk, the restart must serve the promoted candidate
        assert verdict["candidate_serves"] is (point != "pre_publish")
