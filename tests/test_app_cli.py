"""CLI entry-point tests — the reference's L0 script layer (SURVEY.md §2.1):
train/eval scripts and the streaming-inference script as `python -m
sntc_tpu` subcommands, driven end-to-end on synthetic day CSVs."""

import json
import os

import numpy as np
import pytest

from sntc_tpu.app import main


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory, mesh8):
    d = str(tmp_path_factory.mktemp("days"))
    assert main(["synth", "--out", d, "--rows", "6000", "--days", "3"]) == 0
    return d


def test_train_evaluate_roundtrip(data_dir, tmp_path, capsys):
    model_dir = str(tmp_path / "model")
    rc = main([
        "train", "--data", data_dir, "--estimator", "lr", "--binary",
        "--max-iter", "20", "--model-out", model_dir,
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["macroF1"] > 0.5 and out["fit_wall_clock_s"] > 0
    assert os.path.isdir(model_dir)

    rc = main(["evaluate", "--data", data_dir, "--model", model_dir,
               "--binary", "--metric", "accuracy"])
    assert rc == 0
    ev = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert ev["accuracy"] > 0.5


def test_train_rf_with_chisq(data_dir, tmp_path, capsys):
    rc = main([
        "train", "--data", data_dir, "--estimator", "rf",
        "--num-trees", "4", "--max-depth", "3", "--chisq-top", "20",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert 0.0 <= out["macroF1"] <= 1.0


def test_serve_once(data_dir, tmp_path, capsys):
    model_dir = str(tmp_path / "model")
    main(["train", "--data", data_dir, "--estimator", "lr", "--binary",
          "--max-iter", "15", "--model-out", model_dir])
    capsys.readouterr()
    out_dir = str(tmp_path / "out")
    rc = main([
        "serve", "--model", model_dir, "--watch", data_dir,
        "--out", out_dir, "--checkpoint", str(tmp_path / "ckpt"),
        "--max-files-per-batch", "1", "--once",
    ])
    assert rc == 0
    served = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert served["batches"] == 3
    outs = sorted(os.listdir(out_dir))
    assert len(outs) == 3
    # predictions come back as label STRINGS via the indexer's vocabulary
    with open(os.path.join(out_dir, outs[0])) as fh:
        header = fh.readline()
        first = fh.readline()
    assert "predictedLabel" in header
    assert any(lbl in first for lbl in ('"benign"', '"attack"', "benign", "attack"))
    # resume: nothing new -> zero batches
    rc = main([
        "serve", "--model", model_dir, "--watch", data_dir,
        "--out", out_dir, "--checkpoint", str(tmp_path / "ckpt"), "--once",
    ])
    served = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert served["batches"] == 0


def test_train_rf_default_and_chisq_mlp(data_dir, capsys):
    """rf/gbt without --chisq-top consume the assembler output; --chisq-top
    with the default mlp layers adapts the input layer width."""
    assert main(["train", "--data", data_dir, "--estimator", "rf",
                 "--num-trees", "3", "--max-depth", "2"]) == 0
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert main(["train", "--data", data_dir, "--estimator", "mlp",
                 "--chisq-top", "20", "--max-iter", "5"]) == 0
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    with pytest.raises(SystemExit):
        main(["train", "--data", data_dir, "--estimator", "mlp",
              "--chisq-top", "20", "--layers", "40,8,15"])


def test_train_new_estimators(data_dir, capsys):
    """dt/nb/svc ride the same train script surface."""
    for est, extra in (
        ("dt", ["--max-depth", "4"]),
        ("nb", []),
        ("svc", ["--binary", "--max-iter", "20"]),
    ):
        rc = main(["train", "--data", data_dir, "--estimator", est] + extra)
        assert rc == 0, est
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert 0.0 <= out["macroF1"] <= 1.0, est
