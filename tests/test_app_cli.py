"""CLI entry-point tests — the reference's L0 script layer (SURVEY.md §2.1):
train/eval scripts and the streaming-inference script as `python -m
sntc_tpu` subcommands, driven end-to-end on synthetic day CSVs."""

import json
import os

import numpy as np
import pytest

from sntc_tpu.app import main


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory, mesh8):
    d = str(tmp_path_factory.mktemp("days"))
    assert main(["synth", "--out", d, "--rows", "6000", "--days", "3"]) == 0
    return d


def test_train_evaluate_roundtrip(data_dir, tmp_path, capsys):
    model_dir = str(tmp_path / "model")
    rc = main([
        "train", "--data", data_dir, "--estimator", "lr", "--binary",
        "--max-iter", "20", "--model-out", model_dir,
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["macroF1"] > 0.5 and out["fit_wall_clock_s"] > 0
    assert os.path.isdir(model_dir)

    rc = main(["evaluate", "--data", data_dir, "--model", model_dir,
               "--binary", "--metric", "accuracy"])
    assert rc == 0
    ev = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert ev["accuracy"] > 0.5


def test_train_rf_with_chisq(data_dir, tmp_path, capsys):
    rc = main([
        "train", "--data", data_dir, "--estimator", "rf",
        "--num-trees", "4", "--max-depth", "3", "--chisq-top", "20",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert 0.0 <= out["macroF1"] <= 1.0


def test_serve_once(data_dir, tmp_path, capsys):
    model_dir = str(tmp_path / "model")
    main(["train", "--data", data_dir, "--estimator", "lr", "--binary",
          "--max-iter", "15", "--model-out", model_dir])
    capsys.readouterr()
    out_dir = str(tmp_path / "out")
    rc = main([
        "serve", "--model", model_dir, "--watch", data_dir,
        "--out", out_dir, "--checkpoint", str(tmp_path / "ckpt"),
        "--max-files-per-batch", "1", "--once",
    ])
    assert rc == 0
    served = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert served["batches"] == 3
    outs = sorted(os.listdir(out_dir))
    assert len(outs) == 3
    # predictions come back as label STRINGS via the indexer's vocabulary
    with open(os.path.join(out_dir, outs[0])) as fh:
        header = fh.readline()
        first = fh.readline()
    assert "predictedLabel" in header
    assert any(lbl in first for lbl in ('"benign"', '"attack"', "benign", "attack"))
    # resume: nothing new -> zero batches
    rc = main([
        "serve", "--model", model_dir, "--watch", data_dir,
        "--out", out_dir, "--checkpoint", str(tmp_path / "ckpt"), "--once",
    ])
    served = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert served["batches"] == 0


def test_train_rf_default_and_chisq_mlp(data_dir, capsys):
    """rf/gbt without --chisq-top consume the assembler output; --chisq-top
    with the default mlp layers adapts the input layer width."""
    assert main(["train", "--data", data_dir, "--estimator", "rf",
                 "--num-trees", "3", "--max-depth", "2"]) == 0
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert main(["train", "--data", data_dir, "--estimator", "mlp",
                 "--chisq-top", "20", "--max-iter", "5"]) == 0
    json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    with pytest.raises(SystemExit):
        main(["train", "--data", data_dir, "--estimator", "mlp",
              "--chisq-top", "20", "--layers", "40,8,15"])


def test_train_new_estimators(data_dir, capsys):
    """dt/nb/svc ride the same train script surface."""
    for est, extra in (
        ("dt", ["--max-depth", "4"]),
        ("nb", []),
        ("svc", ["--binary", "--max-iter", "20"]),
    ):
        rc = main(["train", "--data", data_dir, "--estimator", est] + extra)
        assert rc == 0, est
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert 0.0 <= out["macroF1"] <= 1.0, est


def test_serve_daemon_once_two_tenants_shared_checkpoint(
    data_dir, tmp_path, capsys
):
    """serve-daemon end-to-end: two tenants naming the SAME checkpoint
    share one served model (and so one predictor/program cache), each
    drains its own watch dir into its own out dir, and --once exits 0
    with per-tenant drain markers under the daemon root."""
    model_dir = str(tmp_path / "model")
    main(["train", "--data", data_dir, "--estimator", "lr", "--binary",
          "--max-iter", "15", "--model-out", model_dir])
    capsys.readouterr()
    for tid in ("acme", "beta"):
        in_dir = tmp_path / "in" / tid
        in_dir.mkdir(parents=True)
        for f in sorted(os.listdir(data_dir)):
            os.link(os.path.join(data_dir, f), str(in_dir / f))
    spec_path = tmp_path / "tenants.json"
    spec_path.write_text(json.dumps({"tenants": [
        {"id": "acme", "model": model_dir,
         "watch": str(tmp_path / "in" / "acme"),
         "out": str(tmp_path / "out" / "acme"), "weight": 2},
        {"id": "beta", "model": model_dir,
         "watch": str(tmp_path / "in" / "beta"),
         "out": str(tmp_path / "out" / "beta")},
    ]}))
    root = str(tmp_path / "root")
    metrics_out = str(tmp_path / "metrics.prom")
    trace_out = str(tmp_path / "trace.json")
    from sntc_tpu.obs import disable_tracing
    from sntc_tpu.obs.metrics import registry

    def _m(name, **labels):
        return registry().get(name, **labels) or 0

    rows_before = {
        tid: _m("sntc_rows_committed_total", tenant=tid)
        for tid in ("acme", "beta")
    }
    batches_before = {
        tid: _m("sntc_batches_committed_total", tenant=tid)
        for tid in ("acme", "beta")
    }
    try:
        rc = main([
            "serve-daemon", "--tenants", str(spec_path), "--root", root,
            "--max-files-per-batch", "1", "--once",
            "--metrics-out", metrics_out, "--trace-out", trace_out,
        ])
    finally:
        disable_tracing()
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["batches"] == 6  # 3 day files per tenant
    assert out["tenants"] == {"acme": "OK", "beta": "OK"}
    assert out["drained"] is True
    for tid in ("acme", "beta"):
        outs = sorted(os.listdir(tmp_path / "out" / tid))
        assert len(outs) == 3
        with open(tmp_path / "out" / tid / outs[0]) as fh:
            assert "predictedLabel" in fh.readline()
        marker = os.path.join(root, "tenant", tid, "drain_marker.json")
        with open(marker) as fh:
            assert json.load(fh)["tenant"] == tid
    assert os.path.exists(os.path.join(root, "daemon_drain_marker.json"))
    # --metrics-out: a Prometheus snapshot with per-tenant series whose
    # values agree with the daemon's own accounting (r13 acceptance)
    with open(metrics_out) as fh:
        prom = fh.read()
    assert "# TYPE sntc_rows_committed_total counter" in prom
    for tid in ("acme", "beta"):
        assert f'sntc_rows_committed_total{{tenant="{tid}"}}' in prom
        assert (
            _m("sntc_batches_committed_total", tenant=tid)
            - batches_before[tid]
            == 3
        )
        assert _m("sntc_rows_committed_total", tenant=tid) - rows_before[
            tid
        ] > 0
    # this pipeline folds fully (scaler→LR) so no fused segment exists
    # and no transfer series is expected — the per-engine transfer
    # ledger still rides pipeline_stats (tested with a real fused
    # segment in tests/test_obs.py); health/events series do appear
    assert "sntc_events_total" in prom
    # --trace-out: Perfetto-loadable Chrome trace with the hot-path spans
    with open(trace_out) as fh:
        doc = json.load(fh)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"daemon.tick", "stream.read", "predict.dispatch",
            "stream.commit", "sink.deliver"} <= names
    assert all(
        e["ph"] in ("X", "M") and "ts" in e or e["ph"] == "M"
        for e in doc["traceEvents"]
    )
