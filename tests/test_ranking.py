"""RankingEvaluator / MultilabelClassificationEvaluator: hand-computed
oracles on the classic mllib doc examples, plus an ALS integration."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame, object_column
from sntc_tpu.evaluation import (
    MultilabelClassificationEvaluator,
    RankingEvaluator,
)


def _rank_frame():
    preds = object_column([
        [1, 6, 2, 7, 8, 3, 9, 10, 4, 5],
        [4, 1, 5, 6, 2, 7, 3, 8, 9, 10],
        [1, 2, 3, 4, 5],
    ])
    labels = object_column([
        [1, 2, 3, 4, 5],
        [1, 2, 3],
        [1, 2],
    ])
    return Frame({"prediction": preds, "label": labels})


def test_mean_average_precision():
    f = _rank_frame()
    ev = RankingEvaluator(metricName="meanAveragePrecision")
    # query 1: hits at ranks 1,3,6,9,10 -> (1/1+2/3+3/6+4/9+5/10)/5
    q1 = (1 + 2 / 3 + 3 / 6 + 4 / 9 + 5 / 10) / 5
    # query 2: hits at 2,5,7 -> (1/2+2/5+3/7)/3
    q2 = (1 / 2 + 2 / 5 + 3 / 7) / 3
    # query 3: hits at 1,2 -> (1+1)/2
    q3 = 1.0
    assert ev.evaluate(f) == pytest.approx((q1 + q2 + q3) / 3)
    assert ev.isLargerBetter()


def test_precision_recall_at_k():
    f = _rank_frame()
    p3 = RankingEvaluator(metricName="precisionAtK", k=3)
    # q1: {1,2} of first 3 -> 2/3; q2: {1} -> 1/3; q3: {1,2} -> 2/3
    assert p3.evaluate(f) == pytest.approx((2 / 3 + 1 / 3 + 2 / 3) / 3)
    r3 = RankingEvaluator(metricName="recallAtK", k=3)
    assert r3.evaluate(f) == pytest.approx((2 / 5 + 1 / 3 + 2 / 2) / 3)


def test_ndcg_and_map_at_k():
    f = _rank_frame()
    nd = RankingEvaluator(metricName="ndcgAtK", k=3)
    inv = lambda i: 1.0 / np.log2(i + 2)  # noqa: E731
    q1 = (inv(0) + inv(2)) / (inv(0) + inv(1) + inv(2))
    q2 = inv(1) / (inv(0) + inv(1) + inv(2))
    q3 = (inv(0) + inv(1)) / (inv(0) + inv(1))
    assert nd.evaluate(f) == pytest.approx((q1 + q2 + q3) / 3)
    mapk = RankingEvaluator(metricName="meanAveragePrecisionAtK", k=2)
    # truncated at 2: q1 hit@1 -> (1/1)/2; q2 hit@2 -> (1/2)/2; q3 -> 1
    assert mapk.evaluate(f) == pytest.approx((0.5 + 0.25 + 1.0) / 3)


def test_ranking_evaluator_with_als():
    from sntc_tpu.models import ALS

    rng = np.random.default_rng(0)
    users, items = [], []
    for u in range(30):
        group = u % 2
        for _ in range(10):
            users.append(u)
            items.append(int(rng.integers(0, 10) + 10 * group))
    f = Frame({
        "user": np.array(users), "item": np.array(items),
        "rating": np.ones(len(users), np.float32),
    })
    m = ALS(rank=4, maxIter=8, implicitPrefs=True, alpha=5.0, seed=0).fit(f)
    rec = m.recommendForAllUsers(5)
    truth = {u: sorted({i for uu, i in zip(users, items) if uu == u})
             for u in range(30)}
    eval_f = Frame({
        "prediction": object_column(
            [list(r) for r in rec["recommendations"]]
        ),
        "label": object_column(
            [truth[int(u)] for u in rec["id"]]
        ),
    })
    ndcg = RankingEvaluator(metricName="ndcgAtK", k=5).evaluate(eval_f)
    assert ndcg > 0.8  # in-group items dominate the top of the ranking


def test_multilabel_metrics():
    # the classic mllib MultilabelMetrics doc example
    preds = object_column([
        [0.0, 1.0], [0.0, 2.0], [], [2.0], [2.0, 0.0], [0.0, 1.0, 2.0],
        [1.0],
    ])
    labels = object_column([
        [0.0, 1.0], [0.0, 2.0], [0.0], [2.0], [2.0, 0.0], [0.0, 1.0],
        [1.0, 2.0],
    ])
    f = Frame({"prediction": preds, "label": labels})
    ev = lambda name: MultilabelClassificationEvaluator(  # noqa: E731
        metricName=name
    ).evaluate(f)
    assert ev("subsetAccuracy") == pytest.approx(4 / 7)
    assert ev("accuracy") == pytest.approx(
        (1 + 1 + 0 + 1 + 1 + 2 / 3 + 1 / 2) / 7
    )
    assert ev("hammingLoss") == pytest.approx((0 + 0 + 1 + 0 + 0 + 1 + 1) / 21)
    assert ev("precision") == pytest.approx(
        (1 + 1 + 0 + 1 + 1 + 2 / 3 + 1) / 7
    )
    assert ev("recall") == pytest.approx((1 + 1 + 0 + 1 + 1 + 1 + 0.5) / 7)
    tp = 2 + 2 + 0 + 1 + 2 + 2 + 1  # per-doc intersections
    fp = 0 + 0 + 0 + 0 + 0 + 1 + 0
    fn = 0 + 0 + 1 + 0 + 0 + 0 + 1
    assert ev("microPrecision") == pytest.approx(tp / (tp + fp))
    assert ev("microRecall") == pytest.approx(tp / (tp + fn))
    assert ev("microF1Measure") == pytest.approx(
        2 * tp / (2 * tp + fp + fn)
    )
    assert not MultilabelClassificationEvaluator(
        metricName="hammingLoss"
    ).isLargerBetter()


def test_multilabel_accuracy_nan_on_both_empty_row():
    """Spark parity (r5): a row whose prediction AND label sets are both
    empty is a bare 0/0 in MultilabelMetrics.accuracy — NaN, which
    poisons the mean.  The other metrics stay finite on the same data."""
    import math

    f = Frame({
        "prediction": object_column([[1.0], []]),
        "label": object_column([[1.0], []]),
    })
    acc = MultilabelClassificationEvaluator(metricName="accuracy").evaluate(f)
    assert math.isnan(acc)
    sub = MultilabelClassificationEvaluator(
        metricName="subsetAccuracy"
    ).evaluate(f)
    assert sub == pytest.approx(1.0)


def test_text_pipeline_end_to_end_persisted(tmp_path):
    """The full text stack inside a Pipeline object, fitted, persisted,
    reloaded, and re-scored — the composition story for every new
    stage."""
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.feature import (
        CountVectorizer, IDF, StopWordsRemover, Tokenizer,
    )
    from sntc_tpu.models import NaiveBayes
    from sntc_tpu.mlio.save_load import load_model, save_model

    rng = np.random.default_rng(1)
    attack = ["syn flood attack burst", "scan probe attack vector",
              "flood probe syn storm"]
    benign = ["normal web get request", "benign web browse page",
              "normal page get fetch"]
    texts, ys = [], []
    for _ in range(120):
        a = rng.random() < 0.5
        texts.append((attack if a else benign)[rng.integers(3)])
        ys.append(1.0 if a else 0.0)
    f = Frame({"text": object_column(texts), "label": np.array(ys)})
    pipe = Pipeline(stages=[
        Tokenizer(inputCol="text", outputCol="tok"),
        StopWordsRemover(inputCol="tok", outputCol="filt"),
        CountVectorizer(inputCol="filt", outputCol="counts"),
        IDF(inputCol="counts", outputCol="features"),
        NaiveBayes(),
    ])
    model = pipe.fit(f)
    acc = float((model.transform(f)["prediction"] == f["label"]).mean())
    assert acc > 0.95
    save_model(model, str(tmp_path / "textpipe"))
    m2 = load_model(str(tmp_path / "textpipe"))
    np.testing.assert_array_equal(
        m2.transform(f)["prediction"], model.transform(f)["prediction"]
    )


def test_evaluators_are_params_stages(tmp_path):
    from sntc_tpu.mlio.save_load import load_model, save_model

    ev = RankingEvaluator(metricName="ndcgAtK", k=7)
    save_model(ev, str(tmp_path / "rank_ev"))
    ev2 = load_model(str(tmp_path / "rank_ev"))
    assert ev2.getK() == 7 and ev2.getMetricName() == "ndcgAtK"
