"""Multi-tenant serve front door (r12): shared predictor program
cache, weighted-deficit fair scheduling, rate quotas, the tenant
escalation ladder (OK → THROTTLED → QUARANTINED → STOPPED), per-tenant
namespacing of events/breakers/fault sites/journals, daemon drain, the
observer-leak and breaker-eviction regressions, the tenant-flags drift
check, and the multi-tenant chaos scenarios in a real child process.
Scheduler/quota/ladder tests run on injectable clocks — deterministic,
no sleeps."""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.resilience import HealthMonitor, HealthState, breaker_for
from sntc_tpu.serve import (
    MemorySink,
    MemorySource,
    ServeDaemon,
    StreamingQuery,
    TenantSpec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Identity(Transformer):
    def transform(self, frame):
        return frame


class _FailingSink(MemorySink):
    def add_batch(self, batch_id, frame):
        raise IOError("sink volume down")


def _frames(n_batches, rows=8, base=0):
    return [
        Frame({"x": np.arange(rows, dtype=np.float64) + 100 * b + base})
        for b in range(n_batches)
    ]


def _spec(tid, frames, sink=None, model=None, **kw):
    return TenantSpec(
        tenant_id=tid,
        model=model if model is not None else _Identity(),
        source=MemorySource(frames),
        sink=sink if sink is not None else MemorySink(),
        **kw,
    )


def _daemon(tmp_path, specs, **kw):
    return ServeDaemon(specs, str(tmp_path / "root"), **kw)


# ---------------------------------------------------------------------------
# satellite regressions: observer leak, breaker-registry eviction,
# tenant-tagged event stream
# ---------------------------------------------------------------------------


def test_observer_count_flat_across_50_monitor_lifecycles():
    base = R.event_observer_count()
    for _ in range(50):
        HealthMonitor().attach().close()
    assert R.event_observer_count() == base
    # attach is idempotent, close is too
    m = HealthMonitor().attach().attach()
    assert R.event_observer_count() == base + 1
    m.close()
    m.close()
    assert R.event_observer_count() == base


def test_daemon_close_detaches_monitor_and_strike_observer(tmp_path):
    base = R.event_observer_count()
    for _ in range(5):
        d = _daemon(tmp_path, [_spec("a", _frames(1))])
        assert R.event_observer_count() == base + 2  # health + strikes
        d.close()
    assert R.event_observer_count() == base


def test_daemon_init_failure_detaches_observer_and_evicts(tmp_path):
    """A bad spec raising out of __init__ must not leak the monitor
    observer the daemon had already attached (close() never runs) —
    nor the breakers an earlier-built GOOD tenant already registered."""
    base = R.event_observer_count()
    good = _spec("good", _frames(1))
    bad = TenantSpec(tenant_id="bad", model=_Identity())  # no source
    with pytest.raises(ValueError, match="source"):
        _daemon(tmp_path, [good, bad])
    assert R.event_observer_count() == base
    assert not any(
        site.startswith("tenant/good/")
        for site in R.breakers_snapshot()
    )


def test_deferring_tenant_banks_no_deficit(tmp_path):
    """DRR cap: credit a deferring tenant could not spend does not
    bank — on recovery one tick commits at most ~2 rounds' worth, not
    the whole deferral backlog ahead of its neighbors."""
    class _HealableSink(MemorySink):
        def __init__(self):
            super().__init__()
            self.broken = True

        def add_batch(self, batch_id, frame):
            if self.broken:
                raise IOError("sink volume down")
            super().add_batch(batch_id, frame)

    heal = _HealableSink()
    specs = [
        _spec("flaky", _frames(30), sink=heal, max_batch_offsets=1,
              max_batch_failures=None, quarantine_after=10_000),
        _spec("ok", _frames(30), max_batch_offsets=1),
    ]
    d = _daemon(tmp_path, specs, clock=FakeClock())
    try:
        flaky = d._by_id["flaky"]
        for _ in range(20):  # 20 deferring rounds
            d.tick()
        assert flaky.batches_done == 0
        assert flaky.deficit <= flaky.spec.weight * d.quantum
        heal.broken = False
        d.tick()
        # one recovery tick: bounded by last round's cap + this
        # round's credit, NOT the 20 banked rounds
        assert flaky.batches_done <= 2
    finally:
        d.close()


def test_reset_breakers_prefix_evicts_only_namespace():
    breaker_for("tenant/a/sink.write")
    breaker_for("tenant/a/predict.dispatch")
    keep_b = breaker_for("tenant/b/sink.write")
    keep_g = breaker_for("collective.dispatch")
    R.reset_breakers(prefix="tenant/a/")
    snap = R.breakers_snapshot()
    assert set(snap) == {"tenant/b/sink.write", "collective.dispatch"}
    # survivors are the same instances; the evicted site rebuilds fresh
    assert breaker_for("tenant/b/sink.write") is keep_b
    assert breaker_for("collective.dispatch") is keep_g
    fresh = breaker_for("tenant/a/sink.write")
    assert fresh.snapshot()["window_calls"] == 0


def test_engine_events_tenant_tagged_and_site_namespaced(tmp_path):
    sink = _FailingSink()
    q = StreamingQuery(
        _Identity(), MemorySource(_frames(1)), sink,
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        max_batch_failures=1, tenant="acme",
    )
    assert q.process_available() == 1  # quarantined, committed
    events = R.recent_events(event="quarantine")
    assert len(events) == 1
    assert events[0]["site"] == "tenant/acme/sink.write"
    assert events[0]["tenant"] == "acme"
    # single-tenant engines stay untagged (allocation-free path)
    q2 = StreamingQuery(
        _Identity(), MemorySource(_frames(1)), _FailingSink(),
        str(tmp_path / "ckpt2"), max_batch_offsets=1,
        max_batch_failures=1,
    )
    q2.process_available()
    plain = R.recent_events(site="sink.write", event="quarantine")
    assert len(plain) == 1 and "tenant" not in plain[0]


def test_shed_journal_records_tenant(tmp_path):
    q = StreamingQuery(
        _Identity(), MemorySource(_frames(10)), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1, tenant="acme",
    )
    record = q.shed_backlog(2)
    assert record["tenant"] == "acme"
    with open(tmp_path / "ckpt" / "shed.jsonl") as f:
        assert json.loads(f.readline())["tenant"] == "acme"
    shed_events = R.recent_events(event="load_shed")
    assert shed_events[0]["tenant"] == "acme"
    assert shed_events[0]["site"] == "tenant/acme/stream.read"


def test_events_dropped_per_tenant_breakdown():
    for _ in range(600):
        R.emit_event(event="retry", site="x", tenant="noisy")
    for _ in range(30):
        R.emit_event(event="retry", site="x")
    by_tenant = R.events_dropped(by_tenant=True)
    total = R.events_dropped()
    assert total == 600 + 30 - 512
    # the first 512-30=... evictions were all noisy's records; the
    # untagged records count only against the int total
    assert by_tenant["noisy"] >= 600 - 512
    assert set(by_tenant) == {"noisy"}
    R.clear_events()
    assert R.events_dropped(by_tenant=True) == {}


def test_fault_point_tenant_namespacing(tmp_path):
    R.arm("tenant/a/stream.read", times=None)
    qa = StreamingQuery(
        _Identity(), MemorySource(_frames(1)), MemorySink(),
        str(tmp_path / "a"), tenant="a",
    )
    qb = StreamingQuery(
        _Identity(), MemorySource(_frames(1)), MemorySink(),
        str(tmp_path / "b"), tenant="b",
    )
    with pytest.raises(R.InjectedFault):
        qa.process_available()
    assert qb.process_available() == 1  # b never sees a's fault
    # a bare-site fault is the shared-environment failure: hits b too
    R.clear()
    R.arm("stream.read")
    qb2 = StreamingQuery(
        _Identity(), MemorySource(_frames(1)), MemorySink(),
        str(tmp_path / "b2"), tenant="b",
    )
    with pytest.raises(R.InjectedFault):
        qb2.process_available()


# ---------------------------------------------------------------------------
# shared program cache
# ---------------------------------------------------------------------------


def test_shared_predictor_and_flat_ledger_across_tenants(tmp_path):
    model = _Identity()
    sinks = {t: MemorySink() for t in ("a", "b", "c")}
    # ragged per-tenant batch sizes that all fall into buckets {4, 8}
    frames = {
        "a": _frames(2, rows=3), "b": _frames(2, rows=5),
        "c": _frames(2, rows=7),
    }
    specs = [
        _spec(t, frames[t], sink=sinks[t], model=model,
              max_batch_offsets=1)
        for t in ("a", "b", "c")
    ]
    d = _daemon(tmp_path, specs, shape_buckets=4)
    try:
        # one model object -> ONE shared predictor for all three
        preds = {
            id(d.predictor_for(s.spec)) for s in d.tenants
        }
        assert len(preds) == 1
        d.process_available()
        d.mark_warm()
        # more traffic in the SAME shapes: zero new compiles, shared
        # bucket hits keep counting on the one ledger
        for t in ("a", "b", "c"):
            src = d._by_id[t].query.source
            for f in frames[t]:
                src.add(f)
        d.process_available()
        assert d.recompiles_after_warmup() == 0
        ledger = list(d.compile_ledger().values())
        assert len(ledger) == 1 and ledger[0]["compile_events"] == 2
        # every tenant's rows came through intact
        for t in ("a", "b", "c"):
            got = np.concatenate(
                [np.asarray(f["x"]) for f in sinks[t].frames]
            )
            want = np.concatenate(
                [np.asarray(f["x"]) for f in frames[t] * 2]
            )
            np.testing.assert_array_equal(got, want)
    finally:
        d.close()


# ---------------------------------------------------------------------------
# fair scheduling & quotas (injectable clock, steppable ticks)
# ---------------------------------------------------------------------------


def test_deficit_round_robin_honors_weights(tmp_path):
    sinks = {"heavy": MemorySink(), "light": MemorySink()}
    specs = [
        _spec("heavy", _frames(12), sink=sinks["heavy"], weight=3.0,
              max_batch_offsets=1),
        _spec("light", _frames(12), sink=sinks["light"], weight=1.0,
              max_batch_offsets=1),
    ]
    d = _daemon(tmp_path, specs, clock=FakeClock())
    try:
        for _ in range(4):
            d.tick()
        heavy, light = d._by_id["heavy"], d._by_id["light"]
        assert heavy.batches_done == 12  # 3 per round
        assert light.batches_done == 4  # 1 per round
    finally:
        d.close()


def test_rate_quota_throttles_then_time_refills(tmp_path):
    clk = FakeClock()
    sink = MemorySink()
    specs = [
        _spec("metered", _frames(6, rows=8), sink=sink,
              max_rows_per_sec=8.0, max_batch_offsets=1),
    ]
    d = _daemon(tmp_path, specs, clock=clk)
    try:
        t = d._by_id["metered"]
        assert d.tick() == 1  # burst = 1 s of quota = one 8-row batch
        assert t.allowance <= 0
        assert d.tick() == 0  # same instant: bucket empty
        assert t.state == "THROTTLED"
        assert d.process_available() == 0  # rounds don't refill, time does
        clk.t = 1.0
        assert d.tick() == 1
        assert t.state in ("OK", "THROTTLED")
        assert t.batches_done == 2
    finally:
        d.close()


def test_backlog_shed_is_journaled_per_tenant(tmp_path):
    sink = MemorySink()
    specs = [
        _spec("flood", _frames(10), sink=sink, max_pending_batches=2,
              max_batch_offsets=1),
    ]
    d = _daemon(tmp_path, specs, clock=FakeClock())
    try:
        d.process_available()
        t = d._by_id["flood"]
        assert t.shed_total_offsets > 0
        shed_path = os.path.join(
            d.tenant_dir("flood"), "ckpt", "shed.jsonl"
        )
        with open(shed_path) as f:
            rec = json.loads(f.readline())
        assert rec["tenant"] == "flood"
        # freshest data kept flowing: the sink got the post-shed tail
        assert len(sink.batches) > 0
    finally:
        d.close()


# ---------------------------------------------------------------------------
# escalation ladder & isolation
# ---------------------------------------------------------------------------


def test_noisy_tenant_walks_the_ladder_good_tenant_unaffected(tmp_path):
    clk = FakeClock()
    good_sink = MemorySink()
    specs = [
        _spec("good", _frames(6), sink=good_sink, max_batch_offsets=1,
              max_batch_failures=2),
        _spec("bad", _frames(8), sink=_FailingSink(),
              max_batch_offsets=1, max_batch_failures=2,
              quarantine_after=2, quarantine_cooldown_s=10.0,
              stop_after=2),
    ]
    d = _daemon(tmp_path, specs, clock=clk)
    try:
        bad = d._by_id["bad"]
        d.process_available()
        # every bad batch takes 2 failed rounds then quarantines (a
        # strike); 2 strikes -> episode 1 -> QUARANTINED
        assert bad.state == "QUARANTINED"
        assert bad.quarantine_episodes == 1
        assert R.recent_events(event="tenant_quarantined")
        # the good tenant never noticed
        good = d._by_id["good"]
        assert good.state == "OK" and good.batches_done == 6
        assert len(good_sink.batches) == 6
        assert d.tenant_health("good") == HealthState.OK
        # bad's evidence landed in bad's OWN namespace
        dead = os.path.join(
            d.tenant_dir("bad"), "ckpt", "dead_letter",
            "dead_letter.jsonl",
        )
        assert os.path.exists(dead)
        assert d.tenant_health("bad") == HealthState.UNHEALTHY
        # cooldown elapses -> probation: health reset, serving resumes
        clk.t = 10.0
        d.tick()
        assert bad.state != "QUARANTINED"
        assert d.tenant_health("bad") == HealthState.OK
        assert R.recent_events(event="tenant_released")
        # still failing -> second episode >= stop_after -> STOPPED,
        # breakers evicted from the process registry
        d.process_available()
        assert bad.state == "STOPPED"
        assert R.recent_events(event="tenant_stopped")
        assert not any(
            site.startswith("tenant/bad/")
            for site in R.breakers_snapshot()
        )
        # a stopped tenant's neighbors keep their breakers
        assert any(
            site.startswith("tenant/good/")
            for site in R.breakers_snapshot()
        )
        # daemon keeps scheduling the survivors
        d._by_id["good"].query.source.add(_frames(1)[0])
        assert d.process_available() == 1
    finally:
        d.close()


def test_strikes_attributed_by_namespaced_site_too(tmp_path):
    """Breaker / retry-executor events carry no ``tenant`` field —
    they fire against the tenant's namespaced site; the ladder must
    count them anyway (an open breaker IS escalation evidence)."""
    d = _daemon(tmp_path, [_spec("a", _frames(1)), _spec("b", [])],
                clock=FakeClock())
    try:
        R.emit_event(event="breaker_open", site="tenant/a/sink.write")
        R.emit_event(event="retry_exhausted",
                     site="tenant/a/sink.write", attempts=3)
        assert d._by_id["a"].strikes == 2
        assert d._by_id["b"].strikes == 0
        # untagged bare-site events attribute to nobody
        R.emit_event(event="breaker_open", site="sink.write")
        R.emit_event(event="breaker_open", site="tenant/unknown")
        assert d._by_id["a"].strikes == 2
    finally:
        d.close()


def test_engine_error_strikes_tenant_never_kills_daemon(tmp_path):
    class _ExplodingSource(MemorySource):
        def latest_offset(self):
            raise RuntimeError("source backend down")

    specs = [
        TenantSpec(tenant_id="boom", model=_Identity(),
                   source=_ExplodingSource(_frames(1)),
                   sink=MemorySink(), quarantine_after=99),
        _spec("ok", _frames(2), max_batch_offsets=1),
    ]
    d = _daemon(tmp_path, specs, clock=FakeClock())
    try:
        assert d.process_available() == 2  # the healthy tenant's batches
        assert d._by_id["boom"].strikes > 0
        assert R.recent_events(event="tenant_error")
        assert d._by_id["ok"].batches_done == 2
    finally:
        d.close()


# ---------------------------------------------------------------------------
# drain
# ---------------------------------------------------------------------------


def test_daemon_drain_settles_every_tenant_with_markers(tmp_path):
    sinks = {"a": MemorySink(), "b": MemorySink()}
    specs = [
        _spec(t, _frames(3), sink=sinks[t], max_batch_offsets=1)
        for t in ("a", "b")
    ]
    d = _daemon(tmp_path, specs, clock=FakeClock())
    try:
        d.request_drain("test")
        status = d.run(poll_interval=0.0)
        assert status["drained"] is True
        for t in ("a", "b"):
            marker = os.path.join(
                d.tenant_dir(t), "drain_marker.json"
            )
            with open(marker) as f:
                rec = json.load(f)
            assert rec["tenant"] == t and rec["in_flight_left"] == 0
        with open(
            os.path.join(str(tmp_path / "root"),
                         "daemon_drain_marker.json")
        ) as f:
            daemon_marker = json.load(f)
        assert daemon_marker["reason"] == "test"
        assert set(daemon_marker["tenants"]) == {"a", "b"}
        assert R.recent_events(event="daemon_drained")
    finally:
        d.close()


# ---------------------------------------------------------------------------
# spec hygiene
# ---------------------------------------------------------------------------


def test_tenant_spec_validation():
    with pytest.raises(ValueError, match="path-safe"):
        TenantSpec(tenant_id="a/b", model=_Identity())
    with pytest.raises(ValueError, match="weight"):
        TenantSpec(tenant_id="a", model=_Identity(), weight=0)
    with pytest.raises(ValueError, match="schema_contract"):
        TenantSpec(tenant_id="a", model=_Identity(),
                   row_policy="salvage")
    with pytest.raises(ValueError, match="unknown TenantSpec field"):
        TenantSpec.from_dict({"id": "a", "max_rows_per_second": 5})
    spec = TenantSpec.from_dict(
        {"id": "a", "weight": 2.0},
        defaults={"weight": 1.0, "max_rows_per_sec": 10.0,
                  "model": _Identity()},
    )
    assert spec.weight == 2.0 and spec.max_rows_per_sec == 10.0
    # the CLI's documented "0 = quarantine unarmed" normalizes to None
    # (a raw 0 would be rejected by StreamingQuery)
    zero = TenantSpec(tenant_id="z", model=_Identity(),
                      max_batch_failures=0)
    assert zero.max_batch_failures is None


def test_daemon_rejects_duplicate_tenants(tmp_path):
    with pytest.raises(ValueError, match="duplicate"):
        _daemon(tmp_path, [_spec("a", _frames(1)),
                           _spec("a", _frames(1))])


# ---------------------------------------------------------------------------
# tenant-flags drift check (the tier-1 wiring of check_tenant_flags)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tenant_flags_consistent_cli_spec_docs():
    checker = _load_script("check_tenant_flags")
    assert checker.check() == []


# ---------------------------------------------------------------------------
# multi-tenant chaos: one tenant's kill/fault in a REAL daemon process
# must not touch its neighbors (tier-1 wiring of the chaos matrix's
# r12 scenarios)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos():
    return _load_script("chaos_crash_matrix")


@pytest.fixture(scope="module")
def mt_reference(chaos, tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("mt_chaos"))
    return workdir, chaos.run_multi_tenant_reference(workdir)


def test_chaos_multi_tenant_kill_converges_every_tenant(
    chaos, mt_reference
):
    workdir, reference = mt_reference
    # reference sanity: 3 tenants x 4 single-file batches, 6 rows each
    for tid in chaos.TENANT_IDS:
        assert sorted(reference[tid]["commits"]) == [0, 1, 2, 3]
        assert set(reference[tid]["rows"].values()) == {6}
    verdict = chaos.run_multi_tenant_kill_scenario(workdir, reference)
    assert verdict["ok"], verdict


def test_chaos_tenant_fault_isolated_to_its_namespace(
    chaos, mt_reference
):
    workdir, reference = mt_reference
    verdict = chaos.run_tenant_isolation_scenario(workdir, reference)
    assert verdict["ok"], verdict
    assert verdict["tenant_states"]["t1"] in ("QUARANTINED", "STOPPED")


# ---------------------------------------------------------------------------
# concurrency smoke: the daemon schedules on one thread, but sharing a
# predictor ACROSS daemon + external thread must keep the ledger sane
# (the full bitwise two-engine contract lives in test_streaming.py)
# ---------------------------------------------------------------------------


def test_shared_predictor_ledger_thread_safe(tmp_path):
    from sntc_tpu.serve import BatchPredictor

    pred = BatchPredictor(_Identity(), bucket_rows=4)
    frames = _frames(40, rows=5)
    errs = []

    def worker(tid):
        try:
            q = StreamingQuery(
                pred, MemorySource(frames), MemorySink(),
                str(tmp_path / tid), max_batch_offsets=1, tenant=tid,
            )
            q.process_available()
        except Exception as e:  # pragma: no cover - failure evidence
            errs.append(e)

    threads = [
        threading.Thread(target=worker, args=(f"t{i}",))
        for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert pred.compile_events == 1  # one bucket shape, ever
    assert pred.bucket_hits == 3 * 40 - 1
