"""Parity oracle: sklearn (SURVEY.md §4.2) — coefficients within f32 slack."""

import numpy as np
import pytest
from sklearn.linear_model import LogisticRegression as SkLR

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import LogisticRegression


def _binary_data(n=4000, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32) * rng.uniform(0.5, 3.0, size=d)
    w = rng.normal(size=d)
    logits = X @ w - 0.5
    y = (logits + rng.logistic(size=n) > 0).astype(np.float64)
    return Frame({"features": X.astype(np.float32), "label": y}), X, y


def _multi_data(n=6000, d=6, k=4, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, k)) * 1.5
    logits = X @ W
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    y = np.array([rng.choice(k, p=p) for p in probs], dtype=np.float64)
    return Frame({"features": X, "label": y}), X, y


def test_binomial_no_reg_matches_sklearn(mesh8):
    f, X, y = _binary_data()
    model = LogisticRegression(mesh=mesh8, maxIter=200, tol=1e-9).fit(f)
    sk = SkLR(penalty=None, max_iter=2000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_[0], rtol=2e-3, atol=2e-3)
    assert model.intercept == pytest.approx(sk.intercept_[0], abs=5e-3)
    assert model.summary.totalIterations > 0
    # objectiveHistory decreases
    h = model.summary.objectiveHistory
    assert h[0] > h[-1]


def test_binomial_l2_matches_sklearn(mesh8):
    f, X, y = _binary_data(seed=2)
    reg = 0.1
    model = LogisticRegression(
        mesh=mesh8, regParam=reg, standardization=False, maxIter=200, tol=1e-9
    ).fit(f)
    sk = SkLR(C=1.0 / (len(y) * reg), max_iter=2000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_[0], rtol=2e-3, atol=2e-3)


def test_binomial_l1_sparsity_matches_sklearn(mesh8):
    f, X, y = _binary_data(n=2000, seed=3)
    reg = 0.05
    model = LogisticRegression(
        mesh=mesh8, regParam=reg, elasticNetParam=1.0, standardization=False,
        maxIter=300, tol=1e-9,
    ).fit(f)
    sk = SkLR(
        penalty="l1", solver="liblinear", C=1.0 / (len(y) * reg),
        max_iter=5000, tol=1e-10,
    ).fit(X, y)
    np.testing.assert_allclose(model.coefficients, sk.coef_[0], atol=2e-2)
    # same sparsity pattern
    assert np.array_equal(
        np.abs(model.coefficients) < 1e-4, np.abs(sk.coef_[0]) < 1e-4
    )


def test_multinomial_matches_sklearn(mesh8):
    f, X, y = _multi_data()
    reg = 0.01
    model = LogisticRegression(
        mesh=mesh8, regParam=reg, standardization=False, maxIter=300, tol=1e-10
    ).fit(f)
    sk = SkLR(C=1.0 / (len(y) * reg), max_iter=3000, tol=1e-12).fit(X, y)
    assert model.num_classes == 4
    # f32 leaves ~3e-2 slack in the softmax's weakly-determined directions
    # (SURVEY.md §7.2 item 2); behavioral parity is what matters:
    np.testing.assert_allclose(
        model.coefficientMatrix, sk.coef_, rtol=6e-2, atol=6e-2
    )
    # both solutions are unique only up to a uniform intercept shift
    np.testing.assert_allclose(
        model.interceptVector - model.interceptVector.mean(),
        sk.intercept_ - sk.intercept_.mean(),
        atol=6e-2,
    )
    out = model.transform(f)
    agree = (out["prediction"] == sk.predict(X)).mean()
    assert agree > 0.995
    np.testing.assert_allclose(
        out["probability"], sk.predict_proba(X), atol=2e-2
    )


def test_transform_columns_and_threshold(mesh8):
    f, X, y = _binary_data(n=500, seed=4)
    model = LogisticRegression(mesh=mesh8, maxIter=50).fit(f)
    out = model.transform(f)
    prob = out["probability"]
    raw = out["rawPrediction"]
    assert prob.shape == (500, 2) and raw.shape == (500, 2)
    np.testing.assert_allclose(prob.sum(1), 1.0, rtol=1e-5)
    # Spark binary raw margins are [-m, m]
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1], rtol=1e-5)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.85
    # threshold=1.0 -> everything class 0
    all0 = model.copy({"threshold": 1.0}).transform(f)["prediction"]
    assert (all0 == 0.0).all()


def test_weighted_rows_equal_duplication(mesh8):
    rng = np.random.default_rng(5)
    X = rng.normal(size=(200, 3)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    dup = np.concatenate([X, X[:50]]), np.concatenate([y, y[:50]])
    w = np.ones(200, np.float32)
    w[:50] = 2.0
    # small L2 keeps the (separable) solution finite and well-conditioned
    m_w = LogisticRegression(
        mesh=mesh8, weightCol="w", regParam=0.01, maxIter=100, tol=1e-9
    ).fit(Frame({"features": X, "label": y, "w": w}))
    m_d = LogisticRegression(mesh=mesh8, regParam=0.01, maxIter=100, tol=1e-9).fit(
        Frame({"features": dup[0], "label": dup[1]})
    )
    np.testing.assert_allclose(m_w.coefficients, m_d.coefficients, rtol=1e-3, atol=1e-3)


def test_family_validation(mesh8):
    f, _, _ = _multi_data(n=300)
    with pytest.raises(ValueError, match="binomial"):
        LogisticRegression(mesh=mesh8, family="binomial").fit(f)


def test_binomial_probability_is_sigmoid_of_margin(mesh8):
    """Pin Spark parity: probability = sigmoid(m), NOT sigmoid(2m) — softmax
    of the symmetrized rawPrediction [-m, +m] would silently double the
    logit.  Also pins fused (device) == two-step (numpy) paths and overflow
    safety on extreme margins."""
    f, X, y = _binary_data(n=400, seed=6)
    model = LogisticRegression(mesh=mesh8, maxIter=50).fit(f)
    out = model.transform(f)
    m = out["rawPrediction"][:, 1]
    expected_p1 = 1.0 / (1.0 + np.exp(-m))
    np.testing.assert_allclose(out["probability"][:, 1], expected_p1, rtol=1e-5)
    # two-step numpy path agrees with the fused device path
    raw = model._raw_predict(X)
    np.testing.assert_allclose(
        model._raw_to_probability(raw)[:, 1], expected_p1, rtol=1e-5
    )
    # extreme margins: no overflow warnings, saturate to {0, 1}
    huge = np.stack([-np.float64([1e4, -1e4]), np.float64([1e4, -1e4])], axis=1)
    with np.errstate(over="raise"):
        p = model._raw_to_probability(huge)
    np.testing.assert_allclose(p[:, 1], [1.0, 0.0], atol=1e-10)
