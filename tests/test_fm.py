"""Factorization-machine tests: FM must capture multiplicative feature
interactions a linear model cannot; save/load; determinism."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import (
    FMClassificationModel,
    FMClassifier,
    FMRegressionModel,
    FMRegressor,
    LinearRegression,
    LogisticRegression,
)


def _xor_data(n=4000, d=6, seed=0):
    """Label = sign of a product interaction — linearly inseparable."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float64)
    return Frame({"features": X, "label": y}), X, y


def test_fm_classifier_beats_linear_on_interactions(mesh8):
    f, X, y = _xor_data()
    lr_acc = (
        np.asarray(
            LogisticRegression(mesh=mesh8, maxIter=50)
            .fit(f).transform(f)["prediction"]
        )
        == y
    ).mean()
    fm = FMClassifier(
        mesh=mesh8, factorSize=4, maxIter=300, stepSize=0.1, seed=0
    ).fit(f)
    out = fm.transform(f)
    fm_acc = (np.asarray(out["prediction"]) == y).mean()
    assert lr_acc < 0.62  # interaction label defeats the linear model
    assert fm_acc > 0.85, fm_acc
    prob = out["probability"]
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, rtol=1e-6)
    assert fm.summary.totalIterations > 0
    assert fm.summary.areaUnderROC > 0.9


def test_fm_regressor_captures_products(mesh8):
    rng = np.random.default_rng(1)
    n = 4000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (2.0 * X[:, 0] * X[:, 1] + X[:, 2] + 0.05 * rng.normal(size=n))
    f = Frame({"features": X, "label": y})
    lin_rmse = float(np.sqrt(np.mean((
        np.asarray(
            LinearRegression(mesh=mesh8, solver="normal").fit(f)
            .transform(f)["prediction"]
        ) - y
    ) ** 2)))
    fm = FMRegressor(
        mesh=mesh8, factorSize=4, maxIter=400, stepSize=0.1, seed=0
    ).fit(f)
    fm_rmse = float(np.sqrt(np.mean(
        (np.asarray(fm.transform(f)["prediction"]) - y) ** 2
    )))
    assert fm_rmse < 0.5 * lin_rmse, (fm_rmse, lin_rmse)


def test_fm_switches_and_validation(mesh8):
    f, X, y = _xor_data(n=800, seed=2)
    m = FMClassifier(
        mesh=mesh8, factorSize=3, maxIter=30, fitLinear=False,
        fitIntercept=False, seed=0,
    ).fit(f)
    assert np.all(m.linear == 0.0) and m.intercept == 0.0
    with pytest.raises(ValueError, match="binary-only"):
        FMClassifier(mesh=mesh8, maxIter=5).fit(
            Frame({"features": X, "label": (y + 1.0)})
        )


def test_fm_determinism_and_save_load(mesh8, tmp_path):
    f, X, y = _xor_data(n=1200, seed=3)
    kw = dict(mesh=mesh8, factorSize=4, maxIter=60, stepSize=0.1, seed=7)
    m1 = FMClassifier(**kw).fit(f)
    m2 = FMClassifier(**kw).fit(f)
    np.testing.assert_array_equal(m1.factors, m2.factors)

    m3 = load_model(save_model(m1, str(tmp_path / "fmc")))
    assert isinstance(m3, FMClassificationModel)
    np.testing.assert_array_equal(
        m3.transform(f)["prediction"], m1.transform(f)["prediction"]
    )

    rng = np.random.default_rng(4)
    yr = (X[:, 0] * X[:, 1]).astype(np.float64)
    fr = Frame({"features": X, "label": yr})
    r1 = FMRegressor(mesh=mesh8, factorSize=3, maxIter=50, seed=0).fit(fr)
    r2 = load_model(save_model(r1, str(tmp_path / "fmr")))
    assert isinstance(r2, FMRegressionModel)
    np.testing.assert_allclose(
        r2.transform(fr)["prediction"], r1.transform(fr)["prediction"],
        rtol=1e-6,
    )
