"""GBT validated boosting — Spark ``runWithValidation`` stop semantics
(SURVEY.md §2.3 upstream ``ml/tree/impl/GradientBoostedTrees.scala`` [U]):
boosting halts when the validation-loss improvement falls below
``validationTol * max(err, 0.01)`` and the model keeps ``best_m < maxIter``
trees.
"""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models.one_vs_rest import OneVsRest
from sntc_tpu.models.tree.gbt import (
    GBTClassifier,
    _ValidationTracker,
    _validation_error,
)


def _binary_frame(n=4000, seed=0, n_val=1000):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    # easy separable signal: plateaus after a few trees
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    flip = rng.random(n) < 0.05
    y[flip] = 1.0 - y[flip]
    is_val = np.zeros(n, bool)
    is_val[rng.choice(n, size=n_val, replace=False)] = True
    return Frame({"features": X, "label": y, "isVal": is_val})


def test_tracker_spark_semantics():
    t = _ValidationTracker(tol=0.1)
    assert not t.update(0, 1.0)
    assert t.best_m[0] == 1
    # big improvement -> new best
    assert not t.update(1, 0.5)
    assert t.best_m[0] == 2
    # improvement below tol*max(err, 0.01) -> stop, best_m unchanged
    assert t.update(2, 0.49)
    assert t.best_m[0] == 2


def test_validation_error_is_weighted_logloss():
    m = np.array([0.0, 10.0])
    ys = np.array([1.0, 1.0])
    w = np.array([1.0, 3.0])
    expect = (2.0 * np.log(2.0) * 1.0 + 2.0 * np.log1p(np.exp(-20.0)) * 3.0) / 4.0
    assert _validation_error(m, ys, w) == pytest.approx(expect)


def test_gbt_early_stop_sequential():
    frame = _binary_frame()
    gbt = GBTClassifier(
        maxIter=40, maxDepth=3, maxBins=16,
        validationIndicatorCol="isVal", validationTol=0.01, seed=7,
    )
    model = gbt.fit(frame)
    assert model.numTrees < 40
    assert model.forest.feature.shape[0] == model.numTrees
    assert len(model.treeWeights) == model.numTrees
    # still a working classifier on the held-out rows
    val = frame.filter(np.asarray(frame["isVal"]).astype(bool))
    pred = model.transform(val)["prediction"]
    acc = float((pred == val["label"]).mean())
    assert acc > 0.85


def test_gbt_no_validation_runs_all_rounds():
    frame = _binary_frame()
    model = GBTClassifier(maxIter=5, maxDepth=2, maxBins=16, seed=7).fit(frame)
    assert model.numTrees == 5


def _multiclass_frame(n=3000, k=3, seed=1, n_val=800):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.argmax(X[:, :k] + 0.3 * rng.normal(size=(n, k)), axis=1).astype(
        np.float64
    )
    is_val = np.zeros(n, bool)
    is_val[rng.choice(n, size=n_val, replace=False)] = True
    return Frame({"features": X, "label": y, "isVal": is_val})


def test_ovr_vectorized_early_stop_matches_sequential():
    frame = _multiclass_frame()
    gbt = GBTClassifier(
        maxIter=25, maxDepth=3, maxBins=16,
        validationIndicatorCol="isVal", validationTol=0.02, seed=3,
    )
    vec = OneVsRest(classifier=gbt).fit(frame)
    assert any(m.numTrees < 25 for m in vec.models)
    # sequential path: force it by setting a per-sub-fit weightCol gate off
    # via checkpointing gate (checkpointInterval>0 with dir unset keeps the
    # vectorized gate open), so instead relabel manually per class
    seq_models = []
    y = np.asarray(frame["label"])
    for c in range(3):
        sub = frame.with_column("bin", (y == c).astype(np.float64))
        seq_models.append(gbt.copy({"labelCol": "bin"}).fit(sub))
    for mv, ms in zip(vec.models, seq_models):
        assert mv.numTrees == ms.numTrees
        np.testing.assert_array_equal(mv.forest.feature, ms.forest.feature)
        np.testing.assert_allclose(
            mv.forest.threshold, ms.forest.threshold, rtol=1e-6
        )
        np.testing.assert_allclose(mv.treeWeights, ms.treeWeights)


def test_validation_requires_proper_subset():
    frame = _binary_frame(n=100, n_val=0)
    gbt = GBTClassifier(maxIter=3, validationIndicatorCol="isVal")
    with pytest.raises(ValueError, match="proper"):
        gbt.fit(frame)
