"""Word2Vec: co-occurrence-structure recovery (words that share contexts
embed closer than words that never do), exact transform averaging,
vocabulary/minCount semantics, save/load."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import Word2Vec


ANIMALS = ["cat", "dog", "horse", "sheep"]
TECH = ["cpu", "gpu", "ram", "disk"]


def _corpus(n=300, seed=0):
    """Sentences draw exclusively from one topic: animal words only ever
    co-occur with animal words."""
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(n):
        pool = ANIMALS if rng.random() < 0.5 else TECH
        docs.append(list(rng.choice(pool, size=6)))
    col = np.empty(len(docs), dtype=object)
    for i, d in enumerate(docs):
        col[i] = d
    return Frame({"tokens": col})


@pytest.fixture(scope="module")
def fitted():
    return Word2Vec(
        vectorSize=16, windowSize=3, minCount=1, maxIter=50, seed=3,
        stepSize=0.2,
    ).fit(_corpus())


def test_synonyms_respect_cooccurrence(fitted):
    syn = fitted.findSynonyms("cat", 3)
    top = list(syn["word"])
    assert set(top) <= set(ANIMALS) - {"cat"}, top
    # and across-topic similarity is lower than within-topic
    syn_all = fitted.findSynonyms("cat", 7)
    sims = dict(zip(syn_all["word"], syn_all["similarity"]))
    worst_animal = min(sims[w] for w in ANIMALS if w != "cat")
    best_tech = max(sims[w] for w in TECH)
    assert worst_animal > best_tech


def test_get_vectors_and_vocab(fitted):
    v = fitted.getVectors()
    assert set(v["word"]) == set(ANIMALS + TECH)
    assert v["vector"].shape == (8, 16)


def test_transform_is_mean_of_vectors(fitted):
    col = np.empty(2, dtype=object)
    col[0] = ["cat", "dog"]
    col[1] = ["unknownword"]
    out = fitted.transform(Frame({"tokens": col}))["wordVectors"]
    vecs = {w: x for w, x in zip(
        fitted.getVectors()["word"], fitted.getVectors()["vector"]
    )}
    np.testing.assert_allclose(
        out[0], (vecs["cat"] + vecs["dog"]) / 2.0, atol=1e-6
    )
    np.testing.assert_array_equal(out[1], np.zeros(16, np.float32))


def test_min_count_and_errors():
    col = np.empty(2, dtype=object)
    col[0] = ["rare", "word", "other"]
    col[1] = ["word", "other", "another"]
    f = Frame({"tokens": col})
    m = Word2Vec(vectorSize=4, minCount=2, maxIter=1, seed=0).fit(f)
    assert set(m.vocabulary) == {"word", "other"}
    assert "rare" not in m.vocabulary
    with pytest.raises(ValueError, match="empty vocabulary"):
        Word2Vec(minCount=10).fit(f)
    with pytest.raises(KeyError):
        m.findSynonyms("rare", 1)


def test_save_load(fitted, tmp_path):
    from sntc_tpu.mlio.save_load import load_model, save_model

    save_model(fitted, str(tmp_path / "w2v"))
    m2 = load_model(str(tmp_path / "w2v"))
    assert m2.vocabulary == fitted.vocabulary
    np.testing.assert_allclose(m2.vectors, fitted.vectors)
    syn1 = fitted.findSynonyms("dog", 2)
    syn2 = m2.findSynonyms("dog", 2)
    assert list(syn1["word"]) == list(syn2["word"])
