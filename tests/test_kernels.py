"""Kernel forge (r21): twin-equality matrix, poison ladder, drift check.

The serving kernel tier (``sntc_tpu/kernels/``) promises each Pallas
kernel is interchangeable with its lowered-jnp twin — bitwise in f64,
<=1e-5 rel in f32 (the registered tolerances; the traversal and pad
kernels are in fact bit-exact in both, by construction).  Tier-1 runs
the whole matrix in interpret mode on CPU:

* ``forest_traversal`` vs ``grower.forest_leaf_stats`` on random
  forests across depths/widths/stat shapes;
* rf/gbt/dt heads end-to-end through ``BatchPredictor`` — kernel tier
  vs kernels-off — across shape buckets and row-validity masks;
* ``pad_assemble`` vs ``Frame.pad_rows(...).with_column(VALID_COL)``;
* a forced ``kernel.compile`` fault proving the poison ladder serves
  bitwise on the XLA path with zero quarantines/strikes, host-level
  AND inside a fused trace (where the segment must recompile on pure
  XLA, not fall to the eager host path);
* ``tree_hist`` selection semantics preserved through the registry
  reroute (satellite: behavior-preserving);
* the registry ⇔ docs ⇔ tests drift check
  (``scripts/check_kernel_registry.py``) wired tier-1.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sntc_tpu.resilience.faults as R
from sntc_tpu.core.base import Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.fuse import compile_serving, fused_segments, fusion_stats
from sntc_tpu.kernels.assemble import (
    _pad_column_np,
    pad_assemble,
    pad_fits_pallas,
    pad_rows_pallas,
)
from sntc_tpu.kernels.forest import (
    forest_fits_pallas,
    forest_leaf_stats_pallas,
)
from sntc_tpu.kernels.registry import (
    clear_poisons,
    kernel_stats,
    registered_kernels,
    resolve_impl,
    resolve_serve_kernels,
)
from sntc_tpu.models.tree.grower import forest_leaf_stats
from sntc_tpu.resilience.device import DeviceFaultDomain
from sntc_tpu.serve.transform import VALID_COL, BatchPredictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _kernel_tier(monkeypatch):
    """Every test here runs the kernel tier in interpret mode with a
    clean poison ledger and disarmed faults."""
    monkeypatch.setenv("SNTC_SERVE_KERNELS", "interpret")
    clear_poisons()
    R.clear()
    yield
    R.clear()
    clear_poisons()


def _random_forest(rng, T, max_depth, F, S, dtype=np.float32):
    """A structurally valid random forest: internal nodes carry a
    feature/threshold, leaves carry stats, absent nodes are -2 (the
    grower's dense layout)."""
    M = 2 ** (max_depth + 1) - 1
    feat = np.full((T, M), -2, np.int32)
    thr = np.zeros((T, M), dtype)
    leaf = np.zeros((T, M, S), dtype)

    def build(t, node, depth):
        if depth < max_depth and rng.random() < 0.7:
            feat[t, node] = rng.integers(0, F)
            thr[t, node] = rng.normal()
            build(t, 2 * node + 1, depth + 1)
            build(t, 2 * node + 2, depth + 1)
        else:
            feat[t, node] = -1
            leaf[t, node] = rng.random(S).astype(dtype)

    for t in range(T):
        build(t, 0, 0)
    return feat, thr, leaf


@pytest.mark.parametrize(
    "T,N,F,S,max_depth",
    [
        (1, 5, 3, 2, 2),
        (3, 17, 7, 3, 4),
        (2, 128, 4, 5, 3),
        (4, 130, 6, 2, 5),
    ],
)
def test_forest_traversal_matches_twin_f32(T, N, F, S, max_depth):
    rng = np.random.default_rng(T * 1000 + N)
    feat, thr, leaf = _random_forest(rng, T, max_depth, F, S)
    X = rng.normal(size=(N, F)).astype(np.float32)
    ref = np.asarray(
        forest_leaf_stats(
            jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thr),
            jnp.asarray(leaf), max_depth=max_depth,
        )
    )
    out = np.asarray(
        forest_leaf_stats_pallas(
            jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thr),
            jnp.asarray(leaf), max_depth=max_depth, interpret=True,
        )
    )
    # documented tolerance <=1e-5 rel; the kernel is actually bit-exact
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=0)
    np.testing.assert_array_equal(out, ref)


def test_forest_traversal_matches_twin_f64_bitwise():
    with jax.experimental.enable_x64():
        rng = np.random.default_rng(7)
        feat, thr, leaf = _random_forest(rng, 3, 4, 5, 3, np.float64)
        X = rng.normal(size=(23, 5))
        ref = np.asarray(
            forest_leaf_stats(
                jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thr),
                jnp.asarray(leaf), max_depth=4,
            )
        )
        out = np.asarray(
            forest_leaf_stats_pallas(
                jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thr),
                jnp.asarray(leaf), max_depth=4, interpret=True,
            )
        )
    assert ref.dtype == np.float64
    np.testing.assert_array_equal(out, ref)


def test_pad_rows_kernel_bitwise():
    rng = np.random.default_rng(3)
    for n, c, target in [(5, 3, 8), (6, 1, 16), (130, 4, 256)]:
        a = rng.normal(size=(n, c)).astype(np.float32)
        out = np.asarray(
            pad_rows_pallas(jnp.asarray(a), target=target, interpret=True)
        )
        np.testing.assert_array_equal(out, _pad_column_np(a, target))


def test_pad_assemble_matches_frame_twin_all_dtypes():
    rng = np.random.default_rng(4)
    f = Frame({
        "x": rng.normal(size=(5, 4)).astype(np.float32),
        "y": rng.normal(size=5),  # f64: numpy twin without x64
        "i": np.arange(5),
        "s": np.array(list("abcde"), dtype=object),
    })
    valid = np.zeros(8, bool)
    valid[:5] = True
    out = pad_assemble(f, 8, valid)
    ref = f.pad_rows(8).with_column(VALID_COL, valid)
    assert out.columns == ref.columns
    for c in ref.columns:
        np.testing.assert_array_equal(np.asarray(out[c]), np.asarray(ref[c]))
        assert out[c].dtype == ref[c].dtype


def _head_pipeline(kind, rng):
    from sntc_tpu.feature import DCT, VectorAssembler
    from sntc_tpu.models.tree.decision_tree import DecisionTreeClassifier
    from sntc_tpu.models.tree.gbt import GBTClassifier
    from sntc_tpu.models.tree.random_forest import RandomForestClassifier

    D = 4
    X = np.abs(rng.normal(3.0, 2.0, size=(120, D))).astype(np.float32)
    cols = {f"c{i}": X[:, i].copy() for i in range(D)}
    cols["label"] = (X[:, 0] > 3.0).astype(np.float64)
    train = Frame(cols)
    head = {
        "rf": lambda: RandomForestClassifier(
            numTrees=3, maxDepth=3, seed=7, featuresCol="dct"
        ),
        "gbt": lambda: GBTClassifier(maxIter=3, maxDepth=2, featuresCol="dct"),
        "dt": lambda: DecisionTreeClassifier(maxDepth=3, featuresCol="dct"),
    }[kind]()
    pm = Pipeline(stages=[
        VectorAssembler(
            inputCols=[f"c{i}" for i in range(D)], outputCol="features"
        ),
        DCT(inputCol="features", outputCol="dct"),
        head,
    ]).fit(train)
    return pm, train.drop("label")


_SCORE_COLS = ("rawPrediction", "probability", "prediction")


@pytest.mark.parametrize("kind", ["rf", "gbt", "dt"])
@pytest.mark.parametrize("rows,mask", [
    (13, None),      # padded bucket
    (16, None),      # exact bucket
    (11, "partial"),  # row-validity mask + pad
])
def test_heads_kernel_tier_matches_xla(kind, rows, mask, monkeypatch):
    """The equality matrix: rf/gbt/dt heads × shape buckets ×
    row-validity masks, kernel tier (interpret) vs kernels-off, through
    the full fused BatchPredictor path."""
    rng = np.random.default_rng(11)
    pm, serve = _head_pipeline(kind, rng)
    frame = serve.slice(0, rows)
    row_valid = None
    if mask == "partial":
        row_valid = np.ones(rows, dtype=bool)
        row_valid[::3] = False

    monkeypatch.setenv("SNTC_SERVE_KERNELS", "off")
    ref = BatchPredictor(
        compile_serving(pm), bucket_rows=16
    ).predict_frame(frame, row_valid=row_valid)

    monkeypatch.setenv("SNTC_SERVE_KERNELS", "interpret")
    fused = compile_serving(pm)
    out = BatchPredictor(fused, bucket_rows=16).predict_frame(
        frame, row_valid=row_valid
    )
    for c in _SCORE_COLS:
        np.testing.assert_array_equal(
            np.asarray(out[c]), np.asarray(ref[c]), err_msg=f"{kind}:{c}"
        )
    assert fusion_stats(fused)["kernels"]["poisoned_signatures"] == 0


def test_host_level_kernel_compile_fault_serves_twin_bitwise():
    """Unfused head: an injected kernel.compile compile_error poisons
    exactly that (kernel, signature) and the batch serves on the XLA
    twin — bitwise, no exception, nothing reaches any fault domain."""
    from sntc_tpu.models.tree.random_forest import RandomForestClassifier

    rng = np.random.default_rng(0)
    X = rng.normal(size=(80, 5)).astype(np.float64)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    model = RandomForestClassifier(numTrees=3, maxDepth=3, seed=7).fit(
        Frame({"features": X, "label": y})
    )
    Xs = rng.normal(size=(33, 5)).astype(np.float32)
    R.arm("kernel.compile", kind="compile_error", times=1)
    out = np.asarray(model._predict_all_dev(Xs))
    R.clear()
    os.environ["SNTC_SERVE_KERNELS"] = "off"
    ref = np.asarray(model._predict_all_dev(Xs))
    np.testing.assert_array_equal(out, ref)
    st = kernel_stats()
    assert st["poisoned_signatures"] == 1
    reason = next(iter(st["poisoned"].values()))
    assert "kernel.compile" in reason
    # poisoned signature stays on the twin with the tier back on
    os.environ["SNTC_SERVE_KERNELS"] = "interpret"
    np.testing.assert_array_equal(
        np.asarray(model._predict_all_dev(Xs)), ref
    )


def test_forced_pallas_on_cpu_poisons_and_serves_twin():
    """``SNTC_SERVE_KERNELS=pallas`` forced on a CPU backend: the
    Pallas lowering failure is a plain ValueError (not XLA-shaped), yet
    the kernel-scope classifier treats it as a compile error — the
    signature poisons and the batch serves bitwise on the twin instead
    of striking the tenant (the silent-defer regression found driving
    the serve CLI on a chipless host)."""
    from sntc_tpu.models.tree.random_forest import RandomForestClassifier

    rng = np.random.default_rng(3)
    X = rng.normal(size=(64, 5)).astype(np.float64)
    y = (X[:, 0] - X[:, 2] > 0).astype(np.float64)
    model = RandomForestClassifier(numTrees=3, maxDepth=3, seed=5).fit(
        Frame({"features": X, "label": y})
    )
    Xs = rng.normal(size=(21, 5)).astype(np.float32)
    os.environ["SNTC_SERVE_KERNELS"] = "pallas"
    out = np.asarray(model._predict_all_dev(Xs))
    st = kernel_stats()
    assert st["poisoned_signatures"] == 1
    reason = next(iter(st["poisoned"].values()))
    assert "interpret mode" in reason.lower()
    os.environ["SNTC_SERVE_KERNELS"] = "off"
    ref = np.asarray(model._predict_all_dev(Xs))
    np.testing.assert_array_equal(out, ref)


def test_classify_kernel_error_scope():
    """The widened classifier recognizes Pallas/Mosaic lowering text
    and chained causes, defers to the strict device classifier for
    XLA-shaped errors, and stays None for arbitrary user errors."""
    from sntc_tpu.kernels.registry import classify_kernel_error

    assert classify_kernel_error(
        ValueError("Only interpret mode is supported on CPU backend.")
    ) == "compile_error"
    wrapped = RuntimeError("fused trace failed")
    wrapped.__cause__ = ValueError("Mosaic lowering failed: op")
    assert classify_kernel_error(wrapped) == "compile_error"
    assert classify_kernel_error(ValueError("bad user regex")) is None
    assert classify_kernel_error(None) is None


def test_fused_kernel_compile_fault_recompiles_on_xla_path():
    """Inside a fused trace: the kernel poisons, the SEGMENT survives —
    it recompiles the same signature on pure XLA (zero eager fallbacks,
    zero segment poisons, zero domain faults, zero quarantines) and the
    sink-visible outputs stay bitwise vs an unfaulted reference."""
    rng = np.random.default_rng(11)
    pm, serve = _head_pipeline("rf", rng)
    frame = serve.slice(0, 13)

    os.environ["SNTC_SERVE_KERNELS"] = "off"
    ref = BatchPredictor(
        compile_serving(pm), bucket_rows=16
    ).predict_frame(frame)

    os.environ["SNTC_SERVE_KERNELS"] = "interpret"
    fused = compile_serving(pm)
    dom = DeviceFaultDomain()
    bp = BatchPredictor(fused, bucket_rows=16, device_domain=dom)
    R.arm("kernel.compile", kind="compile_error", times=1)
    out = bp.predict_frame(frame)
    R.clear()
    for c in _SCORE_COLS:
        np.testing.assert_array_equal(np.asarray(out[c]), np.asarray(ref[c]))
    fs = fusion_stats(fused)
    assert fs["fallbacks"] == 0  # XLA path, NOT the eager host path
    assert fs["poisoned_signatures"] == 0  # the segment is not poisoned
    assert fs["kernels"]["poisoned_signatures"] >= 1
    assert dom.fault_count() == 0  # platform fault, zero strikes
    assert dom.stats()["state"] == "DEVICE_OK"
    seg = fused_segments(fused)[0]
    assert seg.poisoned_served == 0


def test_registry_selection_and_guards(monkeypatch):
    assert set(registered_kernels()) >= {
        "forest_traversal", "pad_assemble", "tree_hist",
    }
    monkeypatch.setenv("SNTC_SERVE_KERNELS", "off")
    assert resolve_serve_kernels() == "off"
    assert resolve_impl(
        "forest_traversal", n_nodes=7, n_features=3, n_stats=2
    ) == "xla"
    monkeypatch.setenv("SNTC_SERVE_KERNELS", "interpret")
    assert resolve_impl(
        "forest_traversal", n_nodes=7, n_features=3, n_stats=2
    ) == "interpret"
    # guard reject: a freak-width forest falls back to the XLA walk
    assert not forest_fits_pallas(1 << 22, 4, 2)
    assert resolve_impl(
        "forest_traversal", n_nodes=1 << 22, n_features=4, n_stats=2
    ) == "xla"
    assert pad_fits_pallas(64, 8)
    assert not pad_fits_pallas(1 << 20, 1 << 10)


def test_tree_hist_selection_preserved_through_registry(monkeypatch):
    """Satellite regression pin: routing SNTC_TREE_HIST through the
    registry must not change a single selection decision."""
    from sntc_tpu.ops.pallas_histogram import (
        _resolve_tree_hist,
        resolve_hist_impl,
    )

    cases = [(8, 32, None), (8, 32, object()), (1 << 14, 128, object())]
    for env in (None, "pallas", "segment"):
        if env is None:
            monkeypatch.delenv("SNTC_TREE_HIST", raising=False)
        else:
            monkeypatch.setenv("SNTC_TREE_HIST", env)
        for n_nodes, n_bins, mesh in cases:
            assert resolve_hist_impl(n_nodes, n_bins, mesh) == (
                _resolve_tree_hist(n_nodes, n_bins, mesh)
            )
    # on CPU the default stays segment; guard overflow forces segment
    monkeypatch.delenv("SNTC_TREE_HIST", raising=False)
    assert resolve_hist_impl(8, 32, object()) == "segment"
    monkeypatch.setenv("SNTC_TREE_HIST", "pallas")
    assert resolve_hist_impl(1 << 14, 128, object()) == "segment"
    assert resolve_hist_impl(8, 32, object()) == "pallas"


def test_probed_peaks_sources(monkeypatch):
    from sntc_tpu.utils.backend_probe import probed_peaks

    monkeypatch.delenv("SNTC_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("SNTC_PEAK_BW", raising=False)
    cpu = probed_peaks("cpu")
    assert cpu["peak_source"] == "estimate"  # honest CPU labeling
    tpu = probed_peaks("tpu")
    assert tpu["peak_source"] == "datasheet"
    assert tpu["flops"] > cpu["flops"]
    monkeypatch.setenv("SNTC_PEAK_FLOPS", "1e12")
    over = probed_peaks("cpu")
    assert over["flops"] == 1e12 and over["peak_source"] == "env"


def test_roofline_math():
    from sntc_tpu.obs.cost import roofline

    r = roofline(
        {"flops": 1e9, "bytes accessed": 1e8},
        seconds=2.0, invocations=4, platform="cpu",
    )
    assert r["arithmetic_intensity"] == pytest.approx(10.0)
    assert r["achieved_flops"] == pytest.approx(2e9)
    assert r["mfu"] == pytest.approx(2e9 / r["peak_flops"])
    assert r["peak_source"] == "estimate"
    assert roofline(None) is None
    warm = roofline({"flops": 1e9}, seconds=0.0, invocations=0)
    assert "mfu" not in warm and warm["flops"] == 1e9


# ---------------------------------------------------------------------------
# kernel-registry drift check (tier-1 wiring of check_kernel_registry)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_kernel_registry_consistent_code_docs_tests():
    checker = _load_script("check_kernel_registry")
    assert checker.check() == []
