"""Direct unit oracles for ops/lbfgs.py — the optimizer behind every
linear-family fit, tested on problems with KNOWN answers (closed-form
quadratic minima; scipy L-BFGS-B for the box-constrained path; the
soft-threshold fixed point for OWLQN)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sntc_tpu.ops.lbfgs import minimize_lbfgs


def _quadratic(seed, d=12, cond=50.0):
    rng = np.random.default_rng(seed)
    q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    eigs = np.linspace(1.0, cond, d)
    A = (q * eigs) @ q.T
    b = rng.normal(size=d)
    x_star = np.linalg.solve(A, b)
    A32 = jnp.asarray(A, jnp.float32)
    b32 = jnp.asarray(b, jnp.float32)

    def vg(x):
        def f(x):
            return 0.5 * x @ (A32 @ x) - b32 @ x

        return jax.value_and_grad(f)(x)

    return vg, x_star, A, b


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_quadratic_reaches_closed_form(seed):
    vg, x_star, _, _ = _quadratic(seed)
    res = minimize_lbfgs(
        vg, jnp.zeros(len(x_star), jnp.float32), max_iter=200, tol=1e-10
    )
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), x_star, atol=2e-3)
    # objective history is monotone non-increasing through the run
    hist = np.asarray(res.history)[: int(res.n_iters) + 1]
    assert (np.diff(hist) <= 1e-5).all()


def test_bounds_match_scipy_lbfgsb():
    from scipy.optimize import minimize as sp_min

    vg, _, A, b = _quadratic(3)
    d = len(b)
    lb = np.full(d, -0.2)
    ub = np.full(d, 0.3)
    res = minimize_lbfgs(
        vg, jnp.zeros(d, jnp.float32), max_iter=300, tol=1e-10,
        bounds=(jnp.asarray(lb, jnp.float32), jnp.asarray(ub, jnp.float32)),
    )
    ref = sp_min(
        lambda x: 0.5 * x @ (A @ x) - b @ x,
        np.zeros(d), jac=lambda x: A @ x - b,
        method="L-BFGS-B", bounds=list(zip(lb, ub)),
        options={"maxiter": 500, "ftol": 1e-15, "gtol": 1e-12},
    )
    ours = np.asarray(res.x, np.float64)
    assert (ours >= lb - 1e-6).all() and (ours <= ub + 1e-6).all()
    f_ours = 0.5 * ours @ (A @ ours) - b @ ours
    assert f_ours <= ref.fun + 1e-4  # same constrained optimum
    np.testing.assert_allclose(ours, ref.x, atol=5e-3)


def test_owlqn_diagonal_soft_threshold():
    """Diagonal quadratic + L1 has the exact soft-threshold solution
    x_i = sign(b_i/a_i)·max(|b_i|−λ, 0)/a_i — OWLQN must land on it,
    zeros included."""
    a = np.array([1.0, 2.0, 4.0, 0.5], np.float32)
    b = np.array([3.0, -0.1, 2.0, 0.05], np.float32)
    lam = 0.5
    x_star = np.sign(b) * np.maximum(np.abs(b) - lam, 0.0) / a

    def vg(x):
        def f(x):
            return jnp.sum(0.5 * a * x * x - b * x)

        return jax.value_and_grad(f)(x)

    res = minimize_lbfgs(
        vg, jnp.zeros(4, jnp.float32), max_iter=200, tol=1e-10,
        l1=jnp.full(4, lam, jnp.float32),
    )
    ours = np.asarray(res.x)
    np.testing.assert_allclose(ours, x_star, atol=1e-3)
    # exact zeros where soft-thresholding kills the coordinate
    assert ours[1] == 0.0 and ours[3] == 0.0


def test_resume_bit_identical():
    """init_state resume: stopping at iteration k and continuing must
    reproduce the uninterrupted trajectory EXACTLY (the SURVEY §5.4
    mid-fit checkpoint contract, at the optimizer level)."""
    vg, _, _, _ = _quadratic(5)
    full = minimize_lbfgs(
        vg, jnp.zeros(12, jnp.float32), max_iter=40, tol=0.0
    )
    _, half_state = minimize_lbfgs(
        vg, jnp.zeros(12, jnp.float32), max_iter=40, tol=0.0,
        iter_limit=17, return_state=True,
    )
    resumed, _ = minimize_lbfgs(
        vg, jnp.zeros(12, jnp.float32), max_iter=40, tol=0.0,
        init_state=half_state, return_state=True,
    )
    np.testing.assert_array_equal(
        np.asarray(full.x), np.asarray(resumed.x)
    )
