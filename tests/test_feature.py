import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import (
    ChiSqSelector,
    IndexToString,
    StandardScaler,
    StringIndexer,
    VectorAssembler,
)


# ---------------- StringIndexer ----------------

def _label_frame():
    labels = ["b"] * 5 + ["a"] * 5 + ["c"] * 3 + ["d"] * 1
    return Frame({"label": np.array(labels, dtype=object)})


def test_string_indexer_frequency_desc_tiebreak_alpha():
    # b and a tie at 5 -> alphabetical ascending breaks the tie (Spark parity)
    model = StringIndexer(inputCol="label", outputCol="idx").fit(_label_frame())
    assert model.labels == ["a", "b", "c", "d"]
    out = model.transform(_label_frame())
    assert out["idx"].dtype == np.float64
    assert out["idx"][0] == 1.0  # "b"


def test_string_indexer_order_types():
    f = _label_frame()
    assert StringIndexer(stringOrderType="alphabetAsc").fit(f).labels == ["a", "b", "c", "d"]
    assert StringIndexer(stringOrderType="alphabetDesc").fit(f).labels == ["d", "c", "b", "a"]
    assert StringIndexer(stringOrderType="frequencyAsc").fit(f).labels == ["d", "c", "a", "b"]


def test_string_indexer_handle_invalid():
    model = StringIndexer(inputCol="label", outputCol="idx").fit(_label_frame())
    unseen = Frame({"label": np.array(["a", "zz"], dtype=object)})
    with pytest.raises(ValueError, match="unseen"):
        model.transform(unseen)
    skipped = model.copy({"handleInvalid": "skip"}).transform(unseen)
    assert skipped.num_rows == 1
    kept = model.copy({"handleInvalid": "keep"}).transform(unseen)
    assert kept["idx"].tolist() == [0.0, 4.0]


def test_index_to_string_roundtrip():
    f = _label_frame()
    model = StringIndexer(inputCol="label", outputCol="idx").fit(f)
    out = model.transform(f)
    back = IndexToString(inputCol="idx", outputCol="orig", labels=model.labels).transform(out)
    assert list(back["orig"]) == list(f["label"])


# ---------------- VectorAssembler ----------------

def test_vector_assembler_stacks_in_order():
    f = Frame({
        "a": np.array([1.0, 2.0]),
        "b": np.array([[10.0, 20.0], [30.0, 40.0]]),
        "c": np.array([5.0, 6.0]),
    })
    out = VectorAssembler(inputCols=["a", "b", "c"]).transform(f)
    assert out["features"].dtype == np.float32
    np.testing.assert_array_equal(
        out["features"], [[1, 10, 20, 5], [2, 30, 40, 6]]
    )


def test_vector_assembler_n_by_1_columns_use_assign_path():
    """(N, 1) 2-D columns must NOT hit the all-1-D np.array fast path
    (np.array would stack them into 3-D)."""
    f = Frame({
        "a": np.array([[1.0], [2.0], [3.0]]),
        "b": np.array([[4.0], [5.0], [6.0]]),
    })
    out = VectorAssembler(inputCols=["a", "b"]).transform(f)
    assert out["features"].shape == (3, 2)
    np.testing.assert_array_equal(out["features"], [[1, 4], [2, 5], [3, 6]])


def test_vector_assembler_handle_invalid():
    f = Frame({"a": np.array([1.0, np.nan, 3.0])})
    with pytest.raises(ValueError, match="NaN/Inf"):
        VectorAssembler(inputCols=["a"]).transform(f)
    out = VectorAssembler(inputCols=["a"], handleInvalid="skip").transform(f)
    assert out.num_rows == 2
    out = VectorAssembler(inputCols=["a"], handleInvalid="keep").transform(f)
    assert out.num_rows == 3 and np.isnan(out["features"][1, 0])


# ---------------- StandardScaler ----------------

def test_standard_scaler_matches_numpy_unbiased(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(3.0, 2.0, size=(500, 6)).astype(np.float32)
    X[:, 5] = 7.0  # constant feature -> std 0 -> output 0 (Spark semantics)
    f = Frame({"features": X})
    model = StandardScaler(
        mesh=mesh8, inputCol="features", outputCol="scaled", withMean=True
    ).fit(f)
    np.testing.assert_allclose(model.mean, X.mean(0), rtol=1e-4)
    np.testing.assert_allclose(model.std[:5], X.std(0, ddof=1)[:5], rtol=1e-3)
    out = model.transform(f)["scaled"]
    np.testing.assert_allclose(out[:, :5].mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(out[:, :5].std(0, ddof=1), 1.0, rtol=1e-3)
    assert np.all(out[:, 5] == 0.0)


def test_standard_scaler_no_mean_default():
    X = np.array([[2.0], [4.0]], dtype=np.float32)
    model = StandardScaler(inputCol="features", outputCol="s").fit(
        Frame({"features": X})
    )
    out = model.transform(Frame({"features": X}))["s"]
    # withMean=False: scaled but not centered
    np.testing.assert_allclose(out.ravel(), X.ravel() / X.std(ddof=1), rtol=1e-5)


# ---------------- ChiSqSelector ----------------

def test_chi_square_matches_scipy():
    from scipy.stats import chi2_contingency

    from sntc_tpu.ops.histogram import chi_square

    rng = np.random.default_rng(1)
    observed = rng.integers(1, 50, size=(3, 4, 5)).astype(np.float64)
    stats, pvals, dofs = chi_square(observed)
    for j in range(3):
        ref = chi2_contingency(observed[j], correction=False)
        assert stats[j] == pytest.approx(ref.statistic, rel=1e-9)
        assert pvals[j] == pytest.approx(ref.pvalue, rel=1e-9)
        assert dofs[j] == ref.dof


def test_chisq_selector_picks_informative_features(mesh8):
    rng = np.random.default_rng(2)
    n = 2000
    y = rng.integers(0, 3, size=n)
    X = rng.normal(size=(n, 10)).astype(np.float32)
    # features 2 and 7 carry the label signal
    X[:, 2] += y * 2.0
    X[:, 7] -= y * 1.5
    f = Frame({"features": X, "label": y.astype(np.float64)})
    model = ChiSqSelector(
        mesh=mesh8, numTopFeatures=2, labelCol="label"
    ).fit(f)
    assert model.selected_features == [2, 7]
    out = model.transform(f)
    assert out["selectedFeatures"].shape == (n, 2)
    np.testing.assert_array_equal(out["selectedFeatures"][:, 0], X[:, 2])


def test_chisq_selector_fpr_mode(mesh8):
    rng = np.random.default_rng(3)
    n = 3000
    y = rng.integers(0, 2, size=n)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    X[:, 0] += y * 3.0
    model = ChiSqSelector(
        mesh=mesh8, selectorType="fpr", fpr=1e-6, labelCol="label"
    ).fit(Frame({"features": X, "label": y.astype(np.float64)}))
    assert model.selected_features == [0]



def test_chisq_selector_fdr_and_fwe_modes(mesh8):
    """fdr = Benjamini-Hochberg step-up on sorted p-values; fwe =
    Bonferroni p < fwe/F (Spark ChiSqSelector selectorType parity)."""
    rng = np.random.default_rng(4)
    n = 3000
    y = rng.integers(0, 2, size=n)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    X[:, 1] += y * 3.0
    X[:, 5] += y * 2.5
    f = Frame({"features": X, "label": y.astype(np.float64)})
    fdr_model = ChiSqSelector(
        mesh=mesh8, selectorType="fdr", fdr=1e-4, labelCol="label"
    ).fit(f)
    assert fdr_model.selected_features == [1, 5]
    fwe_model = ChiSqSelector(
        mesh=mesh8, selectorType="fwe", fwe=1e-4, labelCol="label"
    ).fit(f)
    assert fwe_model.selected_features == [1, 5]
    # BH with a loose budget keeps at least everything Bonferroni keeps
    loose = ChiSqSelector(
        mesh=mesh8, selectorType="fdr", fdr=0.5, labelCol="label"
    ).fit(f)
    assert set(loose.selected_features) >= {1, 5}


# ---------------- UnivariateFeatureSelector ----------------

def test_ufs_anova_matches_sklearn(mesh8):
    from sklearn.feature_selection import f_classif as sk_f_classif

    from sntc_tpu.feature import UnivariateFeatureSelector

    rng = np.random.default_rng(6)
    n = 4000
    y = rng.integers(0, 3, size=n)
    X = rng.normal(size=(n, 10)).astype(np.float32)
    X[:, 3] += y * 1.5
    X[:, 8] -= y * 2.0
    f = Frame({"features": X, "label": y.astype(np.float64)})
    sel = UnivariateFeatureSelector(
        mesh=mesh8, featureType="continuous", labelType="categorical",
        selectionMode="numTopFeatures", selectionThreshold=2,
    ).fit(f)
    assert sorted(sel.selected_features) == [3, 8]
    # statistic parity with sklearn's f_classif
    from sntc_tpu.feature.univariate_selector import (
        _anova_moments_agg,
        f_classif,
    )
    from sntc_tpu.parallel.collectives import shard_batch

    import jax.numpy as jnp

    xs, ys, w = shard_batch(mesh8, X, y.astype(np.int32))
    F, p = f_classif(_anova_moments_agg(mesh8, 3)(xs, ys, w, jnp.asarray(X[0])))
    F_sk, p_sk = sk_f_classif(X.astype(np.float64), y)
    np.testing.assert_allclose(F, F_sk, rtol=2e-3)
    out = sel.transform(f)
    assert out["selectedFeatures"].shape == (n, 2)


def test_ufs_f_regression_matches_sklearn(mesh8):
    from sklearn.feature_selection import f_regression as sk_f_regression

    from sntc_tpu.feature import UnivariateFeatureSelector

    rng = np.random.default_rng(7)
    n = 3000
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = 2.0 * X[:, 1] - 1.0 * X[:, 6] + 0.5 * rng.normal(size=n)
    f = Frame({"features": X, "label": y})
    sel = UnivariateFeatureSelector(
        mesh=mesh8, featureType="continuous", labelType="continuous",
        selectionMode="numTopFeatures", selectionThreshold=2,
    ).fit(f)
    assert sorted(sel.selected_features) == [1, 6]
    from sntc_tpu.feature.univariate_selector import (
        _regression_moments_agg,
        f_regression,
    )
    from sntc_tpu.parallel.collectives import shard_batch

    import jax.numpy as jnp

    xs, ys, w = shard_batch(mesh8, X, y.astype(np.float32))
    F, p = f_regression(
        _regression_moments_agg(mesh8)(
            xs, ys, w, jnp.asarray(X[0]), jnp.float32(y[0])
        )
    )
    F_sk, p_sk = sk_f_regression(X.astype(np.float64), y)
    np.testing.assert_allclose(F, F_sk, rtol=5e-3)


def test_ufs_chi2_mode_and_validation(mesh8):
    from sntc_tpu.feature import ChiSqSelector, UnivariateFeatureSelector

    rng = np.random.default_rng(8)
    n = 2500
    y = rng.integers(0, 2, size=n)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    X[:, 2] += y * 3.0
    f = Frame({"features": X, "label": y.astype(np.float64)})
    # categorical/categorical == ChiSqSelector's χ² (binned continuous)
    ufs = UnivariateFeatureSelector(
        mesh=mesh8, featureType="categorical", labelType="categorical",
        selectionMode="numTopFeatures", selectionThreshold=1,
    ).fit(f)
    chi = ChiSqSelector(mesh=mesh8, numTopFeatures=1).fit(f)
    assert ufs.selected_features == chi.selected_features == [2]
    with pytest.raises(ValueError, match="featureType and labelType"):
        UnivariateFeatureSelector(mesh=mesh8).fit(f)
    with pytest.raises(ValueError, match="no\\s+Spark score function"):
        UnivariateFeatureSelector(
            mesh=mesh8, featureType="categorical", labelType="continuous"
        ).fit(f)


def test_ufs_save_load(tmp_path, mesh8):
    from sntc_tpu.feature import UnivariateFeatureSelector
    from sntc_tpu.mlio import load_model, save_model

    rng = np.random.default_rng(9)
    X = rng.normal(size=(1000, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    X[:, 0] += 1.0
    f = Frame({"features": X, "label": y})
    m = UnivariateFeatureSelector(
        mesh=mesh8, featureType="continuous", labelType="categorical",
        selectionMode="fpr", selectionThreshold=1e-8,
    ).fit(f)
    save_model(m, str(tmp_path / "ufs"))
    m2 = load_model(str(tmp_path / "ufs"))
    assert m2.selected_features == m.selected_features == [0]


def test_ufs_threshold_validation(mesh8):
    from sntc_tpu.feature import UnivariateFeatureSelector

    rng = np.random.default_rng(10)
    f = Frame({
        "features": rng.normal(size=(200, 4)).astype(np.float32),
        "label": rng.integers(0, 2, 200).astype(np.float64),
    })
    with pytest.raises(ValueError, match="positive\\s+feature count"):
        UnivariateFeatureSelector(
            mesh=mesh8, featureType="continuous", labelType="categorical",
            selectionMode="numTopFeatures", selectionThreshold=-3,
        ).fit(f)
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        UnivariateFeatureSelector(
            mesh=mesh8, featureType="continuous", labelType="categorical",
            selectionMode="fpr", selectionThreshold=3.0,
        ).fit(f)


# ---------------- MinMax/MaxAbs/Normalizer/Binarizer/PCA ----------------

def test_minmax_scaler_matches_sklearn(mesh8):
    from sklearn.preprocessing import MinMaxScaler as SkMM

    from sntc_tpu.feature import MinMaxScaler

    rng = np.random.default_rng(12)
    X = rng.normal(size=(1000, 5)).astype(np.float32) * 3
    X[:, 4] = 2.0  # constant feature
    f = Frame({"features": X})
    m = MinMaxScaler(mesh=mesh8).fit(f)
    out = np.asarray(m.transform(f)["scaledFeatures"])
    sk = SkMM().fit_transform(X[:, :4])
    np.testing.assert_allclose(out[:, :4], sk, atol=1e-5)
    assert np.all(out[:, 4] == 0.5)  # Spark: constant -> midpoint
    m2 = MinMaxScaler(mesh=mesh8, min=-1.0, max=3.0).fit(f)
    out2 = np.asarray(m2.transform(f)["scaledFeatures"])
    np.testing.assert_allclose(out2[:, :4], sk * 4.0 - 1.0, atol=2e-4)
    assert np.all(out2[:, 4] == 1.0)
    with pytest.raises(ValueError, match="min must be"):
        MinMaxScaler(mesh=mesh8, min=2.0, max=1.0).fit(f)


def test_robust_scaler_matches_sklearn(mesh8):
    from sklearn.preprocessing import RobustScaler as SkRS

    from sntc_tpu.feature import RobustScaler

    rng = np.random.default_rng(21)
    X = rng.lognormal(1.0, 2.0, size=(1001, 4)).astype(np.float32)
    X[:, 3] = 7.0  # zero-IQR feature
    f = Frame({"features": X})
    # default: scale only, no centering (Spark's defaults)
    m = RobustScaler().fit(f)
    out = np.asarray(m.transform(f)["scaledFeatures"])
    sk = SkRS(with_centering=False).fit_transform(X[:, :3])
    np.testing.assert_allclose(out[:, :3], sk, rtol=2e-4)
    assert np.all(out[:, 3] == 0.0)  # zero range -> 0 (Spark std=0 rule)
    # centered + custom quantile range
    m2 = RobustScaler(
        withCentering=True, lower=0.1, upper=0.9
    ).fit(f)
    out2 = np.asarray(m2.transform(f)["scaledFeatures"])
    sk2 = SkRS(quantile_range=(10, 90)).fit_transform(
        X[:, :3].astype(np.float64)
    )
    np.testing.assert_allclose(out2[:, :3], sk2, atol=2e-3)
    with pytest.raises(ValueError, match="lower must be"):
        RobustScaler(lower=0.8, upper=0.2).fit(f)


def test_robust_scaler_save_load(mesh8, tmp_path):
    from sntc_tpu.feature import RobustScaler
    from sntc_tpu.mlio.save_load import load_model, save_model

    X = np.random.default_rng(3).normal(size=(256, 3)).astype(np.float32)
    f = Frame({"features": X})
    m = RobustScaler(withCentering=True).fit(f)
    save_model(m, str(tmp_path / "rs"))
    m2 = load_model(str(tmp_path / "rs"))
    np.testing.assert_allclose(m2.median, m.median)
    np.testing.assert_allclose(m2.range, m.range)
    np.testing.assert_allclose(
        m2.transform(f)["scaledFeatures"], m.transform(f)["scaledFeatures"]
    )


def test_maxabs_scaler(mesh8):
    from sntc_tpu.feature import MaxAbsScaler

    X = np.array([[2.0, -4.0, 0.0], [-1.0, 8.0, 0.0]], np.float32)
    m = MaxAbsScaler(mesh=mesh8).fit(Frame({"features": X}))
    np.testing.assert_allclose(m.maxAbs, [2.0, 8.0, 0.0])
    out = m.transform(Frame({"features": X}))["scaledFeatures"]
    np.testing.assert_allclose(
        out, [[1.0, -0.5, 0.0], [-0.5, 1.0, 0.0]], atol=1e-6
    )


def test_normalizer_and_binarizer():
    from sntc_tpu.feature import Binarizer, Normalizer

    X = np.array([[3.0, 4.0], [0.0, 0.0], [1.0, 1.0]], np.float32)
    f = Frame({"features": X})
    out = Normalizer(inputCol="features", outputCol="n").transform(f)["n"]
    np.testing.assert_allclose(out[0], [0.6, 0.8], atol=1e-6)
    np.testing.assert_allclose(out[1], [0.0, 0.0])  # zero row unchanged
    out1 = Normalizer(inputCol="features", outputCol="n", p=1.0).transform(f)["n"]
    np.testing.assert_allclose(out1[2], [0.5, 0.5], atol=1e-6)
    outi = Normalizer(
        inputCol="features", outputCol="n", p=float("inf")
    ).transform(f)["n"]
    np.testing.assert_allclose(outi[0], [0.75, 1.0], atol=1e-6)
    b = Binarizer(inputCol="features", outputCol="b", threshold=0.5).transform(f)
    np.testing.assert_array_equal(
        b["b"], [[1.0, 1.0], [0.0, 0.0], [1.0, 1.0]]
    )


def test_pca_matches_sklearn(mesh8):
    from sklearn.decomposition import PCA as SkPCA

    from sntc_tpu.feature import PCA
    from sntc_tpu.mlio import load_model, save_model

    rng = np.random.default_rng(13)
    base = rng.normal(size=(2000, 2)).astype(np.float32)
    mix = np.array([[1.0, 0.5, 0.1, 0.0], [0.0, 0.3, 1.0, 0.2]], np.float32)
    X = base @ mix + 0.01 * rng.normal(size=(2000, 4)).astype(np.float32)
    f = Frame({"features": X})
    m = PCA(mesh=mesh8, k=2).fit(f)
    sk = SkPCA(n_components=2).fit(X.astype(np.float64))
    # components match up to sign
    for j in range(2):
        dot = abs(np.dot(m.pc[:, j], sk.components_[j]))
        assert dot == pytest.approx(1.0, abs=1e-3)
    np.testing.assert_allclose(
        m.explainedVariance, sk.explained_variance_ratio_, atol=1e-4
    )
    # Spark projects raw (uncentered) vectors
    out = np.asarray(m.transform(f)["pcaFeatures"])
    np.testing.assert_allclose(out, X @ m.pc, atol=1e-4)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        save_model(m, d + "/pca")
        m2 = load_model(d + "/pca")
        np.testing.assert_allclose(m2.pc, m.pc)
    with pytest.raises(ValueError, match="exceeds the feature width"):
        PCA(mesh=mesh8, k=9).fit(f)


def test_pca_large_mean_stability(mesh8):
    """Covariance accumulates about a pilot row: large feature means must
    not destroy the components (f32 cancellation hazard)."""
    from sklearn.decomposition import PCA as SkPCA

    from sntc_tpu.feature import PCA

    rng = np.random.default_rng(14)
    base = rng.normal(size=(20000, 2)).astype(np.float32)
    mix = np.array([[1.0, 0.5, 0.1], [0.0, 0.3, 1.0]], np.float32)
    X = (base @ mix + np.array([1e3, 5e3, 2e3], np.float32)).astype(np.float32)
    m = PCA(mesh=mesh8, k=2).fit(Frame({"features": X}))
    sk = SkPCA(n_components=2).fit(X.astype(np.float64))
    for j in range(2):
        assert abs(np.dot(m.pc[:, j], sk.components_[j])) > 0.999
    np.testing.assert_allclose(
        m.explainedVariance, sk.explained_variance_ratio_, atol=2e-3
    )


def test_ufs_rejects_fractional_top_k(mesh8):
    from sntc_tpu.feature import UnivariateFeatureSelector

    f = Frame({
        "features": np.zeros((10, 3), np.float32),
        "label": np.zeros(10),
    })
    with pytest.raises(ValueError, match="integer\\s+feature count"):
        UnivariateFeatureSelector(
            mesh=mesh8, featureType="continuous", labelType="categorical",
            selectionMode="numTopFeatures", selectionThreshold=2.7,
        ).fit(f)


# ---------------- Bucketizer / QuantileDiscretizer / Imputer ----------------

def test_bucketizer_spark_semantics():
    from sntc_tpu.feature import Bucketizer

    f = Frame({"x": np.array([-1.0, 0.0, 0.5, 1.0, 2.0, 3.0])})
    b = Bucketizer(inputCol="x", outputCol="b", splits=[0.0, 1.0, 2.0, 3.0])
    # out-of-range ALWAYS errors regardless of handleInvalid (Spark)
    with pytest.raises(ValueError, match="outside the splits"):
        b.transform(f)
    with pytest.raises(ValueError, match="outside the splits"):
        b.copy({"handleInvalid": "keep"}).transform(f)
    fin = Frame({"x": np.array([0.0, 0.5, 1.0, 2.0, 3.0, np.nan])})
    with pytest.raises(ValueError, match="NaN"):
        b.transform(fin)
    kept = b.copy({"handleInvalid": "keep"}).transform(fin)
    # last bucket closed on the right: 3.0 -> bucket 2; NaN -> extra 3
    np.testing.assert_array_equal(kept["b"], [0.0, 0.0, 1.0, 2.0, 2.0, 3.0])
    skipped = b.copy({"handleInvalid": "skip"}).transform(fin)
    assert skipped.num_rows == 5
    with pytest.raises(ValueError, match="strictly increasing"):
        Bucketizer(inputCol="x", outputCol="b", splits=[0.0, 0.0, 1.0]).transform(f)


def test_quantile_discretizer_matches_quantiles():
    from sntc_tpu.feature import QuantileDiscretizer

    with pytest.raises(ValueError, match="no non-NaN values"):
        QuantileDiscretizer(inputCol="x", numBuckets=3).fit(
            Frame({"x": np.array([np.nan, np.nan])})
        )

    rng = np.random.default_rng(15)
    x = rng.normal(size=5000)
    f = Frame({"x": x})
    model = QuantileDiscretizer(
        inputCol="x", outputCol="q", numBuckets=4
    ).fit(f)
    out = model.transform(f)
    counts = np.bincount(np.asarray(out["q"], np.int64))
    # quartile buckets are balanced
    assert counts.size == 4 and counts.min() > 0.2 * len(x)
    # open ends: extreme values don't error
    far = model.transform(Frame({"x": np.array([-1e9, 1e9])}))
    np.testing.assert_array_equal(far["q"], [0.0, 3.0])


def test_imputer_mean_median_roundtrip(tmp_path):
    from sntc_tpu.feature import Imputer
    from sntc_tpu.mlio import load_model, save_model

    a = np.array([1.0, np.nan, 3.0, np.nan])
    b = np.array([10.0, 20.0, -1.0, 40.0])
    f = Frame({"a": a, "b": b})
    m = Imputer(inputCols=["a", "b"], outputCols=["a2", "b2"]).fit(f)
    out = m.transform(f)
    np.testing.assert_allclose(out["a2"], [1.0, 2.0, 3.0, 2.0])
    np.testing.assert_allclose(out["b2"], b)  # no NaN in b
    med = Imputer(
        inputCols=["b"], strategy="median", missingValue=-1.0
    ).fit(f)
    np.testing.assert_allclose(
        med.surrogates, [np.median([10.0, 20.0, 40.0])]
    )
    out2 = med.transform(f)
    assert out2["b"][2] == 20.0
    save_model(m, str(tmp_path / "imp"))
    m2 = load_model(str(tmp_path / "imp"))
    np.testing.assert_allclose(
        np.asarray(m2.transform(f)["a2"]), np.asarray(out["a2"])
    )


# ---------------- OneHotEncoder / VectorSlicer / ElementwiseProduct ---------

def test_one_hot_encoder_spark_semantics(tmp_path):
    from sntc_tpu.feature import OneHotEncoder
    from sntc_tpu.mlio import load_model, save_model

    f = Frame({"cat": np.array([0.0, 1.0, 2.0, 1.0])})
    m = OneHotEncoder(inputCols=["cat"]).fit(f)
    assert m.categorySizes == [3]
    out = m.transform(f)["cat_ohe"]
    # dropLast: category 2 encodes as all-zeros, width 2
    np.testing.assert_array_equal(
        out, [[1, 0], [0, 1], [0, 0], [0, 1]]
    )
    full = m.copy({"dropLast": False}).transform(f)["cat_ohe"]
    np.testing.assert_array_equal(
        full, [[1, 0, 0], [0, 1, 0], [0, 0, 1], [0, 1, 0]]
    )
    unseen = Frame({"cat": np.array([0.0, 5.0])})
    with pytest.raises(ValueError, match="outside"):
        m.transform(unseen)
    kept = m.copy({"handleInvalid": "keep", "dropLast": False}).transform(
        unseen
    )["cat_ohe"]
    # keep: extra invalid slot appended
    np.testing.assert_array_equal(kept, [[1, 0, 0, 0], [0, 0, 0, 1]])
    save_model(m, str(tmp_path / "ohe"))
    m2 = load_model(str(tmp_path / "ohe"))
    np.testing.assert_array_equal(
        np.asarray(m2.transform(f)["cat_ohe"]), np.asarray(out)
    )
    with pytest.raises(ValueError, match="non-negative"):
        OneHotEncoder(inputCols=["cat"]).fit(
            Frame({"cat": np.array([0.5, 1.0])})
        )


def test_vector_slicer_and_elementwise_product():
    from sntc_tpu.feature import ElementwiseProduct, VectorSlicer

    X = np.arange(12, dtype=np.float32).reshape(3, 4)
    f = Frame({"features": X})
    out = VectorSlicer(indices=[3, 0]).transform(f)["sliced"]
    np.testing.assert_array_equal(out, X[:, [3, 0]])
    with pytest.raises(ValueError, match="out of range"):
        VectorSlicer(indices=[9]).transform(f)
    ew = ElementwiseProduct(scalingVec=[1.0, 0.0, 2.0, -1.0]).transform(f)
    np.testing.assert_allclose(
        ew["scaled"], X * np.array([1.0, 0.0, 2.0, -1.0])
    )
    with pytest.raises(ValueError, match="length"):
        ElementwiseProduct(scalingVec=[1.0]).transform(f)


# ---------------- PolynomialExpansion / Interaction ----------------

def test_polynomial_expansion_spark_order():
    from sntc_tpu.feature import PolynomialExpansion
    from sntc_tpu.feature.expansion import _expansion_plan
    from math import comb

    # Spark's documented degree-2 order for [x1, x2]:
    # x1, x1², x2, x1x2, x2²
    f = Frame({"v": np.array([[2.0, 3.0], [1.0, -1.0]])})
    out = PolynomialExpansion(inputCol="v", outputCol="p").transform(f)["p"]
    np.testing.assert_allclose(
        out, [[2, 4, 3, 6, 9], [1, 1, -1, -1, 1]]
    )
    # width = C(n+d, d) - 1 for several shapes
    for n, d in ((3, 2), (4, 3), (5, 2)):
        assert len(_expansion_plan(n, d)) == comb(n + d, d) - 1
    # degree-3 prefix for one variable: x1, x1², x1³
    plan = _expansion_plan(2, 3)
    assert plan[:3] == ((0,), (0, 0), (0, 0, 0))


def test_interaction_layout_and_scalars():
    from sntc_tpu.feature import Interaction

    f = Frame({
        "a": np.array([2.0, 3.0]),
        "v": np.array([[1.0, 10.0], [2.0, 20.0]]),
        "w": np.array([[5.0, 7.0], [1.0, 1.0]]),
    })
    out = Interaction(inputCols=["a", "v", "w"], outputCol="i").transform(f)
    # width = 1*2*2; LAST input varies fastest
    np.testing.assert_allclose(
        out["i"],
        [[2 * 1 * 5, 2 * 1 * 7, 2 * 10 * 5, 2 * 10 * 7],
         [3 * 2 * 1, 3 * 2 * 1, 3 * 20 * 1, 3 * 20 * 1]],
    )
    with pytest.raises(ValueError, match="at least two"):
        Interaction(inputCols=["a"]).transform(f)
