"""sntc_tpu.stat vs scipy/sklearn oracles (SURVEY.md §4.2 oracle idiom:
every statistic checked against an independent reference implementation
on the same data)."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.stat import (
    ANOVATest,
    ChiSquareTest,
    Correlation,
    FValueTest,
    KolmogorovSmirnovTest,
    Summarizer,
)


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(7)
    n, f = 4_003, 6  # non-multiple of 8: exercises the padding path
    X = rng.lognormal(1.0, 1.5, size=(n, f)).astype(np.float32)
    X[:, 2] = rng.integers(0, 4, size=n)  # a categorical-ish column
    y = rng.integers(0, 3, size=n)
    X[:, 0] += 3.0 * y  # give ANOVA/χ² something to find
    return X, y


def test_pearson_matches_numpy(mesh8, xy):
    X, _ = xy
    m = Correlation.corr(Frame({"features": X}), "features")["pearson"]
    expected = np.corrcoef(X.astype(np.float64), rowvar=False)
    np.testing.assert_allclose(m, expected, atol=1e-4)
    assert m.shape == (X.shape[1], X.shape[1])


def test_pearson_constant_feature_nan(mesh8):
    X = np.ones((64, 2), dtype=np.float32)
    X[:, 1] = np.arange(64)
    m = Correlation.corr(Frame({"features": X}), "features")["pearson"]
    # Spark: zero-variance rows/cols are NaN, diagonal is 1
    assert np.isnan(m[0, 1]) and np.isnan(m[1, 0])
    np.testing.assert_allclose(np.diag(m), 1.0)


def test_spearman_matches_scipy(mesh8, xy):
    from scipy.stats import spearmanr

    X, _ = xy
    m = Correlation.corr(Frame({"features": X}), "features", "spearman")
    expected = spearmanr(X).statistic
    np.testing.assert_allclose(m["spearman"], expected, atol=1e-4)


def test_chisquare_matches_scipy(mesh8, xy):
    from scipy.stats import chi2_contingency

    X, y = xy
    cats = np.stack(
        [X[:, 2], (X[:, 0] > np.median(X[:, 0])).astype(np.float32)], axis=1
    )
    out = ChiSquareTest.test(Frame({"f": cats, "label": y}), "f", "label")
    for j in range(2):
        table = np.zeros((len(np.unique(cats[:, j])), 3))
        for v_i, v in enumerate(np.unique(cats[:, j])):
            for c in range(3):
                table[v_i, c] = ((cats[:, j] == v) & (y == c)).sum()
        ref = chi2_contingency(table, correction=False)
        assert out["statistics"][0, j] == pytest.approx(ref.statistic, rel=1e-6)
        assert out["pValues"][0, j] == pytest.approx(ref.pvalue, abs=1e-9)
        assert out["degreesOfFreedom"][0, j] == ref.dof


def test_chisquare_flatten_shape(mesh8, xy):
    X, y = xy
    out = ChiSquareTest.test(
        Frame({"f": X[:, 2], "label": y}), "f", "label", flatten=True
    )
    assert out.num_rows == 1
    assert set(out.columns) == {
        "featureIndex", "pValue", "degreesOfFreedom", "statistic",
    }


def test_chisquare_rejects_continuous(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=20_000).astype(np.float32)
    y = rng.integers(0, 2, size=20_000)
    with pytest.raises(ValueError, match="distinct"):
        ChiSquareTest.test(Frame({"f": X, "label": y}), "f", "label")


def test_anova_matches_sklearn(mesh8, xy):
    from sklearn.feature_selection import f_classif as sk_f_classif

    X, y = xy
    out = ANOVATest.test(Frame({"features": X, "label": y}), "features", "label")
    F_ref, p_ref = sk_f_classif(X.astype(np.float64), y)
    np.testing.assert_allclose(out["statistics"][0], F_ref, rtol=1e-3)
    np.testing.assert_allclose(out["pValues"][0], p_ref, atol=1e-6)


def test_fvalue_matches_sklearn(mesh8, xy):
    from sklearn.feature_selection import f_regression as sk_f_regression

    X, y = xy
    target = (X[:, 0] * 0.5 + np.random.default_rng(1).normal(size=len(y))).astype(
        np.float32
    )
    out = FValueTest.test(
        Frame({"features": X, "y": target}), "features", "y"
    )
    F_ref, p_ref = sk_f_regression(X.astype(np.float64), target.astype(np.float64))
    np.testing.assert_allclose(out["statistics"][0], F_ref, rtol=1e-3)
    np.testing.assert_allclose(out["pValues"][0], p_ref, atol=1e-6)


def test_ks_matches_scipy(mesh8):
    from scipy.stats import kstest

    rng = np.random.default_rng(3)
    x = rng.normal(2.0, 3.0, size=10_001)
    out = KolmogorovSmirnovTest.test(Frame({"s": x}), "s", "norm", 2.0, 3.0)
    ref = kstest(x, "norm", args=(2.0, 3.0))
    assert out["statistic"][0] == pytest.approx(ref.statistic, abs=1e-9)
    # scipy's default uses the exact distribution; ours is the asymptotic
    # Kolmogorov form (Spark/commons-math) — agree loosely at n=10k
    assert out["pValue"][0] == pytest.approx(ref.pvalue, abs=5e-3)
    out_bad = KolmogorovSmirnovTest.test(Frame({"s": x}), "s", "norm")
    assert out_bad["pValue"][0] < 1e-10  # wrong null → rejected


def test_summarizer_unweighted(mesh8, xy):
    X, _ = xy
    out = Summarizer.metrics(
        "mean", "variance", "count", "min", "max", "normL1", "normL2",
        "numNonZeros", "std", "sum", "weightSum",
    ).summary(Frame({"features": X}), "features")
    X64 = X.astype(np.float64)
    np.testing.assert_allclose(out["mean"][0], X64.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        out["variance"][0], X64.var(axis=0, ddof=1), rtol=1e-3
    )
    assert out["count"][0] == len(X)
    assert out["weightSum"][0] == pytest.approx(len(X))
    np.testing.assert_allclose(out["min"][0], X.min(axis=0), rtol=1e-6)
    np.testing.assert_allclose(out["max"][0], X.max(axis=0), rtol=1e-6)
    np.testing.assert_allclose(out["normL1"][0], np.abs(X64).sum(axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        out["normL2"][0], np.sqrt((X64**2).sum(axis=0)), rtol=1e-5
    )
    np.testing.assert_allclose(
        out["numNonZeros"][0], (X != 0).sum(axis=0), rtol=1e-6
    )


def test_summarizer_weighted_matches_replication(mesh8):
    """weightNorm="frequency": weightCol ≡ integer row replication — the
    weighted-stats contract the framework's FITS pin (e.g. GLM
    weightCol).  Kept as an opt-in extension; the default is Spark's
    reliability form (next test)."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(501, 3)).astype(np.float32)
    w = rng.integers(1, 4, size=501).astype(np.float32)
    rep = np.repeat(X, w.astype(int), axis=0)
    out_w = Summarizer.metrics("mean", "variance", "weightSum").summary(
        Frame({"features": X, "w": w}), "features", weightCol="w",
        weightNorm="frequency",
    )
    out_r = Summarizer.metrics("mean", "variance", "weightSum").summary(
        Frame({"features": rep}), "features"
    )
    np.testing.assert_allclose(out_w["mean"][0], out_r["mean"][0], atol=1e-5)
    np.testing.assert_allclose(
        out_w["variance"][0], out_r["variance"][0], rtol=1e-4
    )
    assert out_w["weightSum"][0] == pytest.approx(out_r["weightSum"][0])


def test_summarizer_reliability_variance_matches_spark(mesh8):
    """Default weighted variance = Spark ml.stat SummarizerBuffer's
    reliability-weight denominator Σw − Σw²/Σw (r5 closed the former
    frequency-denominator delta).  Hand-computed float64 oracle on
    NON-integer weights, where the two forms differ."""
    rng = np.random.default_rng(11)
    X = rng.normal(size=(257, 3)).astype(np.float32)
    w = rng.uniform(0.25, 2.75, size=257).astype(np.float32)
    out = Summarizer.metrics("variance").summary(
        Frame({"features": X, "w": w}), "features", weightCol="w"
    )
    X64, w64 = X.astype(np.float64), w.astype(np.float64)
    wsum = w64.sum()
    mean = (w64[:, None] * X64).sum(axis=0) / wsum
    num = (w64[:, None] * (X64 - mean) ** 2).sum(axis=0)
    oracle = num / (wsum - (w64**2).sum() / wsum)
    np.testing.assert_allclose(out["variance"][0], oracle, rtol=1e-3)
    # the frequency form must differ on this data (the delta was real)
    freq = num / (wsum - 1.0)
    assert not np.allclose(oracle, freq, rtol=1e-3)
    with pytest.raises(ValueError, match="weightNorm"):
        Summarizer.metrics("variance").summary(
            Frame({"features": X, "w": w}), "features", weightCol="w",
            weightNorm="bogus",
        )


def test_summarizer_zero_weight_rows_excluded(mesh8):
    """Spark's SummarizerBuffer skips weight-0 instances: they must not
    leak into extrema or count."""
    X = np.array([[100.0], [1.0], [2.0]], dtype=np.float32)
    w = np.array([0.0, 1.0, 1.0], dtype=np.float32)
    out = Summarizer.metrics("min", "max", "count", "mean").summary(
        Frame({"features": X, "w": w}), "features", weightCol="w"
    )
    assert out["max"][0, 0] == 2.0
    assert out["min"][0, 0] == 1.0
    assert out["count"][0] == 2
    assert out["mean"][0, 0] == pytest.approx(1.5)


def test_summarizer_single_metric_shorthand(mesh8, xy):
    X, _ = xy
    out = Summarizer.mean(Frame({"features": X}), "features")
    assert out.columns == ["mean"]
