"""Closed-loop SLO controller (r16): windowed percentile oracle,
TenantSpec SLO-field validation, the priority ladder end-to-end on a
3-tenant daemon, single-stream supervisor wiring, the no-oscillation
property over the UNION of serving + ingest knobs, the controller
drift check, and the two controller chaos scenarios in real child
processes.  Scheduler/controller tests run on injectable clocks —
deterministic, no sleeps."""

import importlib.util
import json
import math
import os

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.obs.metrics import observe
from sntc_tpu.resilience import QuerySupervisor
from sntc_tpu.resilience.control import ControlPolicy, Guardrails
from sntc_tpu.serve import (
    MemorySink,
    MemorySource,
    ServeController,
    ServeDaemon,
    SloPolicy,
    StreamingQuery,
    TenantSpec,
)
from sntc_tpu.serve.controller import SloSignal, window_percentile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _frames(n, rows=8, base=0):
    return [
        Frame({"x": np.arange(rows, dtype=np.float64) + 100 * b + base})
        for b in range(n)
    ]


def _spec(tid, frames, **kw):
    return TenantSpec(
        tenant_id=tid,
        model=_Identity(),
        source=MemorySource(frames),
        sink=MemorySink(),
        **kw,
    )


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# the windowed percentile + SLO signal path
# ---------------------------------------------------------------------------


def test_window_percentile_hand_oracle():
    """The upper-bound rule against hand-computed ranks."""
    bounds = (0.005, 0.01, 0.25)
    # 6 observations in the first bucket, 4 in the third, 0 overflow
    counts = [6, 0, 4, 0]
    # p50: rank ceil(0.5*10)=5, cum(0.005)=6 >= 5
    assert window_percentile(bounds, counts, 50) == 0.005
    # p99: rank ceil(0.99*10)=10, cum reaches 10 at 0.25
    assert window_percentile(bounds, counts, 99) == 0.25
    # p60: rank 6 still inside the first bucket
    assert window_percentile(bounds, counts, 60) == 0.005
    # empty window
    assert window_percentile(bounds, [0, 0, 0, 0], 99) is None
    # overflow bucket -> inf sentinel (caller substitutes the mean)
    assert math.isinf(window_percentile(bounds, [0, 0, 0, 3], 99))


def test_windowed_p99_from_registry_deltas_matches_oracle(tmp_path):
    """The controller's per-window p50/p99 must be computed from the
    REGISTRY BUCKET DELTAS — pre-existing observations (a previous
    window, another test) must not leak in — and must equal the
    hand-computed upper-bound oracle on an injectable clock."""
    clock = FakeClock()
    daemon = ServeDaemon(
        [_spec("a", [], slo_p99_ms=100.0)],
        str(tmp_path / "root"), clock=clock,
    )
    ctl = ServeController.for_daemon(
        daemon, policy=ControlPolicy(confirm=1, cooldown=0),
        ingest=False,
    )
    daemon.controller = ctl
    try:
        # noise BEFORE the baseline was primed is already absorbed;
        # now land a known distribution inside ONE window
        for v in [0.004] * 6 + [0.2] * 4:
            observe("sntc_batch_duration_seconds", v, tenant="a")
        clock.t += 2.0
        t = ctl.targets[0]
        sig = ctl._window_signal(t, clock.t)
        # oracle: p50 rank 5 of 10 -> bound 0.005 (5 ms); p99 rank 10
        # -> bound 0.25 (250 ms)
        assert sig.p50_ms == 5.0
        assert sig.p99_ms == 250.0
        # the NEXT window is empty -> no latency verdict
        clock.t += 2.0
        sig2 = ctl._window_signal(t, clock.t)
        assert sig2.p50_ms is None and sig2.p99_ms is None
    finally:
        daemon.close()


def test_remove_tenant_detaches_controller_target(tmp_path):
    """``ServeDaemon.remove_tenant`` must detach the tenant from an
    armed controller (the symmetric inverse of ``attach_tenant``):
    the target list must not keep a ghost whose ``controllable()``
    stays True — it would keep sampling the stopped engine, keep
    evaluating SLO windows, and could post a fleet request for a
    tenant another worker now owns."""
    clock = FakeClock()
    daemon = ServeDaemon(
        [_spec("a", _frames(2)), _spec("b", _frames(2))],
        str(tmp_path / "root"), clock=clock,
    )
    ctl = ServeController.for_daemon(
        daemon, policy=ControlPolicy(confirm=1, cooldown=0),
        ingest=False,
    )
    daemon.controller = ctl
    try:
        clock.t += 1.0
        daemon.tick()
        assert sorted(t.key for t in ctl.targets) == ["a", "b"]
        summary = daemon.remove_tenant("a", drain=True, reason="moved")
        assert summary["tenant"] == "a"
        assert [t.key for t in ctl.targets] == ["b"]
        assert not any(
            name.startswith("a/") for name in ctl.knob_values()
        )
        # the loop keeps running clean on the survivor alone
        clock.t += 2.0
        ctl.on_tick()
        daemon.tick()
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# TenantSpec SLO fields
# ---------------------------------------------------------------------------


def test_tenant_spec_slo_validation():
    # 0 normalizes to None, PR-7 style
    s = _spec("a", [], slo_p99_ms=0, slo_min_rows_per_sec=0.0,
              slo_max_shed_rate=0)
    assert s.slo_p99_ms is None
    assert s.slo_min_rows_per_sec is None
    assert s.slo_max_shed_rate is None
    with pytest.raises(ValueError, match="slo_p99_ms"):
        _spec("a", [], slo_p99_ms=-1.0)
    with pytest.raises(ValueError, match="fraction"):
        _spec("a", [], slo_max_shed_rate=1.5)
    # from_dict rejects unknown keys (a typo'd SLO must be loud)
    with pytest.raises(ValueError, match="slo_p99"):
        TenantSpec.from_dict({
            "id": "a", "model": _Identity(), "source": MemorySource([]),
            "sink": MemorySink(), "slo_p99": 100.0,
        })
    # and accepts the real fields
    s = TenantSpec.from_dict({
        "id": "a", "model": _Identity(), "source": MemorySource([]),
        "sink": MemorySink(), "slo_p99_ms": 250.0,
    })
    assert s.slo_p99_ms == 250.0


def test_slo_policy_normalization():
    p = SloPolicy(slo_p99_ms=0, slo_min_rows_per_sec=5.0)
    assert p.slo_p99_ms is None and p.slo_min_rows_per_sec == 5.0
    assert p.declared()
    assert not SloPolicy().declared()
    with pytest.raises(ValueError):
        SloPolicy(slo_min_rows_per_sec=-2)


# ---------------------------------------------------------------------------
# end-to-end: 3-tenant daemon, one violator
# ---------------------------------------------------------------------------


def test_controller_e2e_three_tenants_one_violator(tmp_path):
    """A throughput-violating tenant gets its own pipeline deepened
    (the local remedy) while the compliant neighbors' knobs are never
    touched; the status dump carries the slo + controller blocks and
    the drain markers record the final knob state."""
    clock = FakeClock()
    specs = [
        _spec("v", _frames(8), slo_min_rows_per_sec=1e9),
        _spec("n1", _frames(2), slo_p99_ms=60_000.0),
        _spec("n2", _frames(2)),
    ]
    daemon = ServeDaemon(specs, str(tmp_path / "root"), clock=clock)
    daemon.controller = ServeController.for_daemon(
        daemon, policy=ControlPolicy(confirm=1, cooldown=0),
        ingest=False,
    )
    try:
        for _ in range(8):
            clock.t += 1.0
            daemon.tick()
        st = daemon.status()
        assert st["slo"]["v"]["declared"]["slo_min_rows_per_sec"] == 1e9
        ctl = st["controller"]
        assert ctl["windows"] >= 6
        assert ctl["applied"] >= 1
        # the violator's depth moved; every neighbor knob is pristine
        assert ctl["knobs"]["v/pipeline_depth"] > 1
        for name, value in ctl["knobs"].items():
            if not name.startswith("v/"):
                assert value == daemon.controller._defaults[name]
        # journaled decisions name the violator only
        applied = [
            d for d in daemon.controller.guard.decisions
            if d["action"] == "applied"
        ]
        assert applied and all(
            d["knob"].startswith("v/") for d in applied
        )
        # neighbors stayed compliant on their declared axes
        assert st["slo"]["n1"]["compliant"] in (None, True)
        daemon.drain()
        marker = json.load(open(
            tmp_path / "root" / "tenant" / "v" / "drain_marker.json"
        ))
        assert marker["controller_knobs"]["pipeline_depth"] > 1
        dm = json.load(open(
            tmp_path / "root" / "daemon_drain_marker.json"
        ))
        assert dm["controller_knobs"]["v/pipeline_depth"] > 1
        # the durable journal parses and matches the in-memory count
        jpath = tmp_path / "root" / "controller.jsonl"
        records = [
            json.loads(line) for line in open(jpath) if line.strip()
        ]
        assert len([r for r in records if r["action"] == "applied"]) \
            == len(applied)
        assert all("knobs" in r for r in records)
    finally:
        daemon.close()


def test_controller_flooding_violator_walks_degradation_ladder(tmp_path):
    """A shed-rate violator is degraded on its OWN knobs in ladder
    order — quota first — driven synthetically through step() so the
    ladder is pinned without real shed machinery."""
    clock = FakeClock()
    daemon = ServeDaemon(
        [
            _spec("noisy", [], slo_max_shed_rate=0.05,
                  quarantine_after=2),
            _spec("quiet", [], slo_p99_ms=60_000.0),
        ],
        str(tmp_path / "root"), clock=clock,
    )
    ctl = ServeController.for_daemon(
        daemon, policy=ControlPolicy(confirm=1, cooldown=0),
        ingest=False,
    )
    daemon.controller = ctl
    flooding = SloSignal(batches=2, rows=16, rows_per_s=16.0,
                         shed_offsets=20, shed_rate=0.9, backlog=30,
                         elapsed_s=1.0)
    quiet = SloSignal(batches=2, rows=16, rows_per_s=16.0,
                      p99_ms=5.0, elapsed_s=1.0)
    try:
        seen = []
        for _ in range(24):
            rec = ctl.step({"noisy": flooding, "quiet": quiet})
            if rec is not None and rec["action"] == "applied":
                seen.append(rec["knob"])
        # ladder order: quota tightens fully, then shed, then escalate
        assert seen[0] == "noisy/quota"
        first_index = {k: seen.index(k) for k in dict.fromkeys(seen)}
        assert first_index["noisy/quota"] < first_index["noisy/shed"]
        assert first_index["noisy/shed"] < first_index["noisy/escalate"]
        # escalation issued REAL ladder strikes against the tenant
        assert ctl.escalations_total >= 1
        noisy = daemon._by_id["noisy"]
        assert noisy.strikes >= 1 or noisy.state != "OK"
        # the quiet tenant's knobs never moved
        assert all(k.startswith("noisy/") for k in seen)
        # and the live quota actually tightened (the token bucket)
        assert noisy.spec.max_rows_per_sec is not None
    finally:
        daemon.close()


def test_supervisor_single_stream_slo_wiring(tmp_path):
    """Any declared SLO arms the controller over the one supervised
    engine: status/health-json gain slo + controller blocks, the
    single-stream knob set (depth / buckets / shed) resolves, and the
    drain marker records the final knob state."""
    q = StreamingQuery(
        _Identity(), MemorySource(_frames(4)), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
    )
    clock = FakeClock()
    sup = QuerySupervisor(
        q, health_json=str(tmp_path / "health.json"),
        clock=clock, slo=SloPolicy(slo_min_rows_per_sec=1e9),
    )
    try:
        assert sup.controller is not None
        knobs = sup.controller.knob_values()
        assert set(knobs) == {"pipeline_depth", "shape_buckets", "shed"}
        for _ in range(6):
            clock.t += 1.0
            sup.tick()
        status = sup.status()
        assert status["slo"]["_"]["declared"]["slo_min_rows_per_sec"] \
            == 1e9
        assert status["controller"]["windows"] >= 4
        # throughput remedy on a single stream: deeper pipeline
        assert q.pipeline_depth > 2 or \
            status["controller"]["applied"] >= 1
        dumped = json.load(open(tmp_path / "health.json"))
        assert "slo" in dumped and "controller" in dumped
        final = sup.drain_now("test")
        assert final["drained"]
        marker = json.load(open(tmp_path / "ckpt" / "drain_marker.json"))
        assert marker["controller_knobs"] is not None
    finally:
        sup.close()


def test_controller_error_degrades_never_kills(tmp_path):
    """A controller that raises inside the daemon tick emits
    controller_error and the round still schedules batches."""
    clock = FakeClock()
    daemon = ServeDaemon(
        [_spec("a", _frames(3))], str(tmp_path / "root"), clock=clock,
    )
    daemon.controller = ServeController.for_daemon(daemon, ingest=False)

    def _boom():
        raise RuntimeError("controller bug")

    daemon.controller.on_tick = _boom
    try:
        clock.t += 1.0
        committed = daemon.tick()
        assert committed >= 1
        events = [
            e for e in R.recent_events()
            if e.get("event") == "controller_error"
        ]
        assert events and "controller bug" in events[-1]["error"]
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# THE no-oscillation property over the serving + ingest knob union
# ---------------------------------------------------------------------------


def test_no_oscillation_over_serving_and_ingest_knob_union(tmp_path):
    """A signal flapping between latency-violating and idle (the chaos
    profile) produces a BOUNDED number of knob changes across the
    UNION of the controller's serving knobs and its delegated ingest
    tuners' knobs — the analytic bound
    Σ_knobs (max_reversals + 1) × (hi − lo) — and the plane goes
    quiescent forever after (the contested knob freezes)."""
    import csv

    in_dir = tmp_path / "in" / "a"
    os.makedirs(in_dir)
    for i in range(3):
        with open(in_dir / f"in_{i:03d}.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["x"])
            w.writerow([i])
    clock = FakeClock()
    # a FileStreamSource-backed tenant so the delegated tuner has a
    # real live-setter action space (read_workers / prefetch)
    daemon = ServeDaemon(
        [TenantSpec(
            tenant_id="a", model=_Identity(), watch=str(in_dir),
            out=str(tmp_path / "out"), slo_p99_ms=100.0,
            slo_min_rows_per_sec=50.0,
        )],
        str(tmp_path / "root"), clock=clock,
    )
    policy = ControlPolicy(confirm=2, cooldown=1, max_reversals=2)
    ctl = ServeController.for_daemon(daemon, policy=policy)
    daemon.controller = ctl
    bad_latency = SloSignal(batches=3, rows=24, rows_per_s=200.0,
                            p99_ms=500.0, elapsed_s=1.0)
    starved = SloSignal(batches=0, rows=0, rows_per_s=0.0,
                        backlog=5, elapsed_s=1.0)
    idle = SloSignal(batches=2, rows=16, rows_per_s=200.0,
                     p99_ms=5.0, elapsed_s=1.0)
    phases = (bad_latency, starved, idle)
    changes_at = []

    def _applied_total():
        serving = len(ctl.guard.applied())
        ingest = sum(
            len(t.tuner.applied()) for t in ctl.targets
            if t.tuner is not None
        )
        return serving + ingest

    try:
        for w in range(600):
            clock.t += 1.0
            before = _applied_total()
            ctl.step({"a": phases[(w // 6) % len(phases)]})
            if _applied_total() != before:
                changes_at.append(w)
        knob_union = dict(ctl._knobs)
        for t in ctl.targets:
            if t.tuner is not None and t.tuner._knobs:
                for name, k in t.tuner._knobs.items():
                    knob_union[f"{t.key}/ingest/{name}"] = k
        bound = Guardrails.change_bound(
            knob_union, policy.max_reversals
        )
        assert len(changes_at) <= bound
        # quiescent: nothing moved in the last 300 windows
        assert not changes_at or changes_at[-1] < 300
        # and the flapping froze at least one contested serving knob
        # OR the plane simply ran out of legal moves — either way the
        # journal records every freeze
        if ctl.guard.frozen:
            frozen_recs = [
                d for d in ctl.guard.decisions
                if d["action"] == "frozen"
            ]
            assert frozen_recs
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# drift check + chaos scenarios (tier-1 wiring)
# ---------------------------------------------------------------------------


def test_controller_flags_drift_check():
    checker = _load_script("check_controller_flags")
    assert checker.check() == []


@pytest.fixture(scope="module")
def chaos():
    return _load_script("chaos_crash_matrix")


def test_chaos_controller_kill_mid_knob_apply(chaos, tmp_path_factory):
    """Kill the controller-armed daemon inside the SECOND ctl.apply;
    restart must converge every tenant to the controller-OFF
    reference and reconcile the journal (restart record + delta)."""
    workdir = str(tmp_path_factory.mktemp("ctl_kill"))
    ref = chaos.run_multi_tenant_reference(workdir)
    verdict = chaos.run_controller_kill_scenario(workdir, ref)
    assert verdict["ok"], verdict
    assert verdict["converged"] and verdict["journal_torn_lines"] == 0


def test_chaos_controller_noisy_neighbor(chaos, tmp_path_factory):
    """Controller-armed noisy-neighbor arc vs a controller-off
    reference on identical inputs: well-behaved sink bytes identical,
    the violator throttled via the journaled quota rung, zero
    decisions against the compliant tenants, quiescent at the end."""
    workdir = str(tmp_path_factory.mktemp("ctl_noisy"))
    verdict = chaos.run_controller_noisy_scenario(workdir)
    assert verdict["ok"], verdict
    assert verdict["clean_sinks_match"]
    assert any(
        k.endswith("quota") for k in verdict["t1_ladder_knobs"]
    )
    assert verdict["clean_tenant_decisions"] == 0
