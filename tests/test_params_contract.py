"""Universal Params contract sweep — the ``ParamsSuite`` analog
(SURVEY.md §4 substrate model): EVERY exported stage class must
construct with defaults, explain its params, copy with overrides,
round-trip its param values, and reject unknown params.  New stages are
covered automatically by being exported."""

import numpy as np
import pytest

import sntc_tpu.evaluation as E
import sntc_tpu.feature as F
import sntc_tpu.models as M
from sntc_tpu.core.params import Params

# classes that require constructor data (fitted models) — the sweep
# covers their ESTIMATOR side; model persistence is tested per-stage
_SKIP = {
    "StringIndexerModel", "StandardScalerModel", "ChiSqSelectorModel",
    "UnivariateFeatureSelectorModel", "MinMaxScalerModel",
    "MaxAbsScalerModel", "RobustScalerModel", "PCAModel", "ImputerModel",
    "OneHotEncoderModel", "CountVectorizerModel", "IDFModel",
    "Word2VecModel", "BucketedRandomProjectionLSHModel", "MinHashLSHModel",
    "VectorIndexerModel", "RFormulaModel", "VarianceThresholdSelectorModel",
    "LogisticRegressionModel", "MultilayerPerceptronClassificationModel",
    "RandomForestClassificationModel", "GBTClassificationModel",
    "DecisionTreeClassificationModel", "DecisionTreeRegressionModel",
    "GBTRegressionModel", "RandomForestRegressionModel",
    "IsotonicRegressionModel", "KMeansModel", "FMClassificationModel",
    "FMRegressionModel", "GaussianMixtureModel",
    "GeneralizedLinearRegressionModel", "LinearRegressionModel",
    "LinearSVCModel", "NaiveBayesModel", "OneVsRestModel",
    "AFTSurvivalRegressionModel", "ALSModel", "BisectingKMeansModel",
    "FPGrowthModel", "LDAModel", "MulticlassMetrics",
}


def _constructible(cls):
    """OneVsRest needs a base classifier — wrap it so the sweep still
    covers its params."""
    if cls.__name__ == "OneVsRest":
        from sntc_tpu.models import LogisticRegression

        return lambda **kw: cls(classifier=LogisticRegression(), **kw)
    return cls


def _stage_classes():
    out = []
    for mod in (F, M, E):
        for name in mod.__all__:
            if name in _SKIP:
                continue
            cls = getattr(mod, name)
            if isinstance(cls, type) and issubclass(cls, Params):
                out.append(cls)
    return out


@pytest.mark.parametrize(
    "cls", _stage_classes(), ids=lambda c: c.__name__
)
def test_params_contract(cls):
    make = _constructible(cls)
    stage = make()
    # every declared param is gettable and explained
    names = list(stage.params())
    assert names, f"{cls.__name__} declares no params"
    text = stage.explainParams()
    for n in names:
        assert n in text, f"{cls.__name__}.explainParams misses {n!r}"
    # paramValues round-trips through a fresh instance
    vals = stage.paramValues()
    clone = make(**vals)
    assert clone.paramValues() == vals
    # copy(extra) applies the override on the COPY without touching the
    # original (the CrossValidator grid-fit contract)
    str_params = [
        n for n in names
        if isinstance(stage.paramValues().get(n), str)
        and getattr(cls, n).validator is None  # "_x" must stay valid
    ]
    copied = stage.copy()
    assert copied is not stage
    assert copied.paramValues() == stage.paramValues()
    for n in str_params[:1]:
        before = stage.getOrDefault(n)
        overridden = stage.copy({n: before + "_x"})
        assert overridden.getOrDefault(n) == before + "_x"
        assert stage.getOrDefault(n) == before  # original untouched
    # unknown params are rejected, not silently absorbed
    with pytest.raises((ValueError, TypeError, AttributeError)):
        make(definitely_not_a_param=1)


@pytest.mark.parametrize(
    "cls", _stage_classes(), ids=lambda c: c.__name__
)
def test_validators_reject_garbage(cls):
    """EVERY param with a validator must reject an opaque object() at
    set time — eager validation, the Spark behavior.  (All in-repo
    validators are range/type/one_of checks; an object() passing one
    means the validator stopped validating.)"""
    stage = _constructible(cls)()
    for name in stage.params():
        p = getattr(cls, name)
        if p.validator is None:
            continue
        with pytest.raises((ValueError, TypeError)):
            stage.set(name, object())