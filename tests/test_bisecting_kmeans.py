"""BisectingKMeans: cluster-recovery oracles on separable blobs,
tree-descent prediction semantics, minDivisibleClusterSize gating,
save/load."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import BisectingKMeans
from sntc_tpu.mlio.save_load import load_model, save_model


def _blobs(n_per=400, centers=None, seed=0, spread=0.3):
    rng = np.random.default_rng(seed)
    centers = centers if centers is not None else np.array(
        [[0.0, 0.0], [8.0, 0.0], [0.0, 8.0], [8.0, 8.0]]
    )
    X = np.concatenate(
        [c + spread * rng.normal(size=(n_per, centers.shape[1]))
         for c in centers]
    ).astype(np.float32)
    y = np.repeat(np.arange(len(centers)), n_per)
    return X, y


def test_recovers_separated_blobs(mesh8):
    X, y = _blobs()
    m = BisectingKMeans(k=4, seed=1).fit(Frame({"features": X}))
    assert len(m.clusterCenters) == 4
    pred = m.predict(X).astype(int)
    # every true blob maps to one predicted cluster (perfect separation)
    for c in range(4):
        assert len(np.unique(pred[y == c])) == 1
    assert len(np.unique(pred)) == 4
    # centers match blob means: nearest found center per true center
    true = np.array([[0, 0], [8, 0], [0, 8], [8, 8]], np.float64)
    d = np.linalg.norm(
        true[:, None, :] - m.clusterCenters[None, :, :], axis=2
    )
    nearest = d.argmin(axis=1)
    assert len(np.unique(nearest)) == 4  # a distinct center per blob
    assert d[np.arange(4), nearest].max() < 0.15


def test_transform_and_cost(mesh8):
    X, _ = _blobs(n_per=100)
    f = Frame({"features": X})
    m = BisectingKMeans(k=4, seed=0).fit(f)
    out = m.transform(f)
    assert out["prediction"].shape == (400,)
    cost = m.computeCost(f)
    assert cost == pytest.approx(m.summary.trainingCost, rel=1e-9)
    # within-cluster sq distances of tight blobs: small vs total spread
    assert cost < 0.25 * ((X - X.mean(0)) ** 2).sum()


def test_min_divisible_cluster_size(mesh8):
    # one big and one tiny blob: with min size above the tiny blob, only
    # the big one may split, capping the leaf count below k
    rng = np.random.default_rng(2)
    X = np.concatenate([
        rng.normal(size=(900, 2)),
        np.array([[50.0, 50.0]]) + 0.01 * rng.normal(size=(60, 2)),
    ]).astype(np.float32)
    m = BisectingKMeans(
        k=6, minDivisibleClusterSize=100, seed=0
    ).fit(Frame({"features": X}))
    # the 60-row blob can never split; leaves over it stay at 1
    pred = m.predict(X).astype(int)
    tiny_clusters = np.unique(pred[900:])
    assert len(tiny_clusters) == 1
    m2 = BisectingKMeans(
        k=6, minDivisibleClusterSize=0.5, seed=0
    ).fit(Frame({"features": X}))
    # fraction 0.5 of 960 rows = 480: after the first split no leaf is
    # divisible except possibly the big side once — fewer than k leaves
    assert len(m2.clusterCenters) < 6


def test_fewer_than_k_on_degenerate_data(mesh8):
    X = np.ones((64, 3), np.float32)  # identical points can't split
    m = BisectingKMeans(k=4).fit(Frame({"features": X}))
    assert len(m.clusterCenters) == 1
    assert (m.predict(X) == 0).all()


def test_cosine_distance(mesh8):
    # rays from the origin: cosine clusters by direction, not magnitude
    rng = np.random.default_rng(5)
    dirs = np.array([[1.0, 0.0], [0.0, 1.0]])
    rows = []
    for d in dirs:
        scale = rng.uniform(0.5, 20.0, size=200)[:, None]
        rows.append(scale * (d + 0.02 * rng.normal(size=(200, 2))))
    X = np.concatenate(rows).astype(np.float32)
    m = BisectingKMeans(k=2, distanceMeasure="cosine", seed=0).fit(
        Frame({"features": X})
    )
    pred = m.predict(X).astype(int)
    assert len(np.unique(pred[:200])) == 1
    assert len(np.unique(pred[200:])) == 1
    assert pred[0] != pred[200]


def test_save_load(mesh8, tmp_path):
    X, _ = _blobs(n_per=50)
    f = Frame({"features": X})
    m = BisectingKMeans(k=3, seed=4).fit(f)
    save_model(m, str(tmp_path / "bkm"))
    m2 = load_model(str(tmp_path / "bkm"))
    np.testing.assert_allclose(m2.clusterCenters, m.clusterCenters)
    np.testing.assert_array_equal(m2.predict(X), m.predict(X))
