"""Seeded pipeline-composition fuzz: random-but-reproducible stage
chains over random frames must fit, transform, and round-trip through
persistence without error, and produce finite predictions.  The
cross-stage seams (column dtypes/shapes handed from stage to stage) are
where composition bugs live — single-stage oracles can't see them.
SURVEY.md §4's randomized-integration idiom."""

import numpy as np
import pytest

from sntc_tpu.core.base import Pipeline
from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio.save_load import load_model, save_model

N_TRIALS = 12


def _random_frame(rng, n):
    d = int(rng.integers(4, 9))
    X = rng.lognormal(0.5, 1.0, size=(n, d)).astype(np.float32)
    X[:, 0] = rng.integers(0, 3, size=n)  # a low-cardinality feature
    y_bin = (X[:, 1] > np.median(X[:, 1])).astype(np.float64)
    y_multi = rng.integers(0, 3, size=n).astype(np.float64)
    # correlate the multiclass label with a feature so fits have signal
    X[:, 2] += 2.0 * y_multi
    return Frame({"features": X, "label": y_bin, "multi": y_multi}), d


def _scaler_pool(rng):
    from sntc_tpu.feature import (
        MaxAbsScaler, MinMaxScaler, Normalizer, RobustScaler,
        StandardScaler,
    )

    return rng.choice([
        lambda: StandardScaler(inputCol="features", outputCol="f2",
                               withMean=True),
        lambda: MinMaxScaler(inputCol="features", outputCol="f2"),
        lambda: MaxAbsScaler(inputCol="features", outputCol="f2"),
        lambda: RobustScaler(inputCol="features", outputCol="f2"),
        lambda: Normalizer(inputCol="features", outputCol="f2"),
    ])()


def _middle_pool(rng, d):
    from sntc_tpu.feature import (
        Binarizer, PCA, PolynomialExpansion, VectorIndexer, VectorSlicer,
    )

    return rng.choice([
        lambda: PCA(inputCol="f2", outputCol="f3", k=min(3, d)),
        lambda: VectorSlicer(inputCol="f2", outputCol="f3",
                             indices=list(range(min(3, d)))),
        lambda: PolynomialExpansion(inputCol="f2", outputCol="f3",
                                    degree=2),
        lambda: Binarizer(inputCol="f2", outputCol="f3", threshold=0.1),
        lambda: VectorIndexer(inputCol="f2", outputCol="f3",
                              maxCategories=4, handleInvalid="keep"),
        lambda: None,
    ])()


def _estimator_pool(rng, label):
    from sntc_tpu.models import (
        DecisionTreeClassifier, LinearSVC, LogisticRegression,
        MultilayerPerceptronClassifier, NaiveBayes,
        RandomForestClassifier,
    )

    if label == "label":
        pool = [
            lambda: LogisticRegression(
                featuresCol="f3", labelCol=label, maxIter=15),
            lambda: LinearSVC(featuresCol="f3", labelCol=label, maxIter=15),
            lambda: DecisionTreeClassifier(
                featuresCol="f3", labelCol=label, maxDepth=3),
        ]
    else:
        pool = [
            lambda: LogisticRegression(
                featuresCol="f3", labelCol=label, maxIter=15),
            lambda: RandomForestClassifier(
                featuresCol="f3", labelCol=label, numTrees=3, maxDepth=3),
            lambda: NaiveBayes(featuresCol="f3", labelCol=label,
                               modelType="gaussian"),
            lambda: MultilayerPerceptronClassifier(
                featuresCol="f3", labelCol=label, maxIter=10),
        ]
    return rng.choice(pool)()


def _regressor_pool(rng):
    from sntc_tpu.models import (
        DecisionTreeRegressor, GBTRegressor, GeneralizedLinearRegression,
        LinearRegression, RandomForestRegressor,
    )

    return rng.choice([
        lambda: LinearRegression(featuresCol="f3", labelCol="target",
                                 maxIter=15),
        lambda: GeneralizedLinearRegression(
            featuresCol="f3", labelCol="target", family="gaussian",
            maxIter=10),
        lambda: DecisionTreeRegressor(featuresCol="f3", labelCol="target",
                                      maxDepth=3),
        lambda: RandomForestRegressor(featuresCol="f3", labelCol="target",
                                      numTrees=3, maxDepth=3),
        lambda: GBTRegressor(featuresCol="f3", labelCol="target",
                             maxIter=3, maxDepth=2),
    ])()


@pytest.mark.parametrize("trial", range(6))
def test_random_regression_pipeline(mesh8, tmp_path, trial):
    rng = np.random.default_rng(2000 + trial)
    f, d = _random_frame(rng, int(rng.integers(150, 300)))
    target = (
        np.asarray(f["features"])[:, 1] * 0.7
        + rng.normal(size=f.num_rows).astype(np.float32) * 0.1
    ).astype(np.float64)
    f = f.with_column("target", target)

    stages = [_scaler_pool(rng)]
    mid = _middle_pool(rng, d)
    if mid is None:
        from sntc_tpu.feature import VarianceThresholdSelector

        mid = VarianceThresholdSelector(
            featuresCol="f2", outputCol="f3", varianceThreshold=0.0
        )
    stages.extend([mid, _regressor_pool(rng)])

    model = Pipeline(stages=stages).fit(f)
    pred = np.asarray(model.transform(f)["prediction"], np.float64)
    assert pred.shape == (f.num_rows,)
    assert np.isfinite(pred).all(), f"non-finite predictions (trial {trial})"

    path = str(tmp_path / f"rpipe_{trial}")
    save_model(model, path)
    np.testing.assert_array_equal(
        np.asarray(load_model(path).transform(f)["prediction"]), pred,
        err_msg=f"persistence changed predictions (trial {trial})",
    )


@pytest.mark.parametrize("trial", range(N_TRIALS))
def test_random_pipeline_composition(mesh8, tmp_path, trial):
    rng = np.random.default_rng(1000 + trial)
    f, d = _random_frame(rng, int(rng.integers(150, 400)))
    label = str(rng.choice(["label", "multi"]))

    stages = [_scaler_pool(rng)]
    mid = _middle_pool(rng, d)
    if mid is None:
        from sntc_tpu.feature import VectorSlicer

        mid = VectorSlicer(inputCol="f2", outputCol="f3",
                           indices=list(range(d)))
    est = _estimator_pool(rng, label)
    # MLP needs declared layer sizes: probe the mid-stage output width
    if type(est).__name__ == "MultilayerPerceptronClassifier":
        scaled = (
            stages[0].fit(f).transform(f)
            if hasattr(stages[0], "fit") else stages[0].transform(f)
        )
        probe = (
            mid.fit(scaled).transform(scaled)
            if hasattr(mid, "fit") else mid.transform(scaled)
        )
        width = probe["f3"].shape[1]
        est.setParams(layers=[int(width), 8, 3])
    stages.extend([mid, est])

    model = Pipeline(stages=stages).fit(f)
    out = model.transform(f)
    pred = np.asarray(out["prediction"], np.float64)
    assert pred.shape == (f.num_rows,)
    assert np.isfinite(pred).all(), f"non-finite predictions (trial {trial})"

    path = str(tmp_path / f"pipe_{trial}")
    save_model(model, path)
    reloaded = load_model(path)
    np.testing.assert_array_equal(
        np.asarray(reloaded.transform(f)["prediction"]), pred,
        err_msg=f"persistence changed predictions (trial {trial})",
    )
