import numpy as np

from sntc_tpu.core.base import Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import StandardScaler, VectorAssembler
from sntc_tpu.models import (
    LogisticRegression,
    MultilayerPerceptronClassifier,
)
from sntc_tpu.serve.fuse import compile_serving


def _frame(n=800, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(3.0, 2.0, size=(n, d)).astype(np.float32)
    X[:, d - 1] = 5.0  # constant feature exercises the f=0 path
    y = (X[:, 0] > 3.0).astype(np.float64)
    return Frame({"features": X, "label": y})


def _pipeline(head, mesh):
    return Pipeline(stages=[
        StandardScaler(mesh=mesh, inputCol="features", outputCol="scaled",
                       withMean=True),
        head,
    ])


def test_fold_scaler_into_lr(mesh8):
    f = _frame()
    pm = _pipeline(
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=40), mesh8
    ).fit(f)
    fused = compile_serving(pm)
    assert len(fused.getStages()) == 1
    a, b = pm.transform(f), fused.transform(f)
    np.testing.assert_allclose(a["probability"], b["probability"], atol=1e-5)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_fold_scaler_into_mlp(mesh8):
    f = _frame(seed=1)
    pm = _pipeline(
        MultilayerPerceptronClassifier(
            mesh=mesh8, featuresCol="scaled", layers=[6, 8, 2], maxIter=40
        ),
        mesh8,
    ).fit(f)
    fused = compile_serving(pm)
    assert len(fused.getStages()) == 1
    a, b = pm.transform(f), fused.transform(f)
    np.testing.assert_allclose(a["probability"], b["probability"], atol=1e-4)
    agree = (a["prediction"] == b["prediction"]).mean()
    assert agree > 0.995  # tolerate a boundary flip within the 1e-4 drift


def test_non_matching_stages_untouched(mesh8):
    f = _frame(seed=2)
    # assembler ahead of scaler: assembler passes through, pair still fuses
    raw = Frame({f"c{i}": f["features"][:, i] for i in range(6)})
    raw = raw.with_column("label", f["label"])
    pm = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(6)], outputCol="features"),
        StandardScaler(mesh=mesh8, inputCol="features", outputCol="scaled"),
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=20),
    ]).fit(raw)
    fused = compile_serving(pm)
    assert len(fused.getStages()) == 2  # assembler + folded model
    np.testing.assert_array_equal(
        pm.transform(raw)["prediction"], fused.transform(raw)["prediction"]
    )
    # scaler NOT feeding the model -> untouched
    pm2 = PipelineModel(stages=[pm.getStages()[1]])
    assert len(compile_serving(pm2).getStages()) == 1


def test_no_fold_when_later_stage_consumes_scaled(mesh8, monkeypatch):
    """The scaler's OUTPUT must survive if another stage also reads it:
    the weight fold is blocked, and the planner instead fuses
    scaler+head into one segment that keeps 'scaled' materialized for
    the second consumer."""
    from sntc_tpu.fuse import FusedSegment

    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")  # staged = device path
    f = _frame(seed=3)
    pm = _pipeline(
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=30), mesh8
    ).fit(f)
    scaler, lr = pm.getStages()
    second = LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=30,
                                predictionCol="p2", rawPredictionCol="r2",
                                probabilityCol="pr2").fit(scaler.transform(f))
    pm3 = PipelineModel(stages=[scaler, lr, second])
    fused = compile_serving(pm3)
    stages = fused.getStages()
    assert len(stages) == 2  # [FusedSegment(scaler+lr), second]
    assert isinstance(stages[0], FusedSegment)
    assert "scaled" in stages[0]._live_writes  # 2nd consumer keeps it live
    a, b = pm3.transform(f), fused.transform(f)
    np.testing.assert_array_equal(a["scaled"], b["scaled"])
    np.testing.assert_array_equal(a["prediction"], b["prediction"])
    np.testing.assert_array_equal(a["p2"], b["p2"])
