"""Text stages: murmur3 vs sklearn's independent implementation,
CountVectorizer vs sklearn on identical token streams, IDF vs the Spark
formula recomputed in numpy, tokenizer/stopword/ngram semantics."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.feature.text import (
    CountVectorizer,
    HashingTF,
    IDF,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
    murmur3_32,
)
from sntc_tpu.mlio.save_load import load_model, save_model

DOCS = [
    "TCP syn flood detected from host alpha",
    "benign http GET from host beta",
    "udp scan scan scan from gamma",
    "",
]


def _tok_frame():
    return Tokenizer(inputCol="text", outputCol="tokens").transform(
        Frame({"text": np.array(DOCS, dtype=object)})
    )


def test_murmur3_matches_sklearn():
    from sklearn.utils.murmurhash import murmurhash3_32

    for term in ["", "a", "abc", "hello", "flow", "синтаксис", "長い語"]:
        for seed in (0, 42):
            ours = murmur3_32(term.encode("utf-8"), seed)
            ref = murmurhash3_32(term.encode("utf-8"), seed=seed,
                                 positive=True)
            assert ours == ref, (term, seed)


def test_tokenizer_and_regex():
    f = _tok_frame()
    assert f["tokens"][0] == [
        "tcp", "syn", "flood", "detected", "from", "host", "alpha",
    ]
    assert f["tokens"][3] == []
    rt = RegexTokenizer(
        inputCol="text", outputCol="tokens", pattern=r"[a-z]+",
        gaps=False, minTokenLength=4,
    ).transform(Frame({"text": np.array(DOCS, dtype=object)}))
    assert rt["tokens"][0] == ["flood", "detected", "from", "host", "alpha"]


def test_stopwords_and_ngram():
    f = _tok_frame()
    sw = StopWordsRemover(
        inputCol="tokens", outputCol="filtered"
    ).transform(f)
    assert "from" not in sw["filtered"][0]
    assert "tcp" in sw["filtered"][0]
    ng = NGram(inputCol="tokens", outputCol="ngrams", n=2).transform(f)
    assert ng["ngrams"][0][0] == "tcp syn"
    assert len(ng["ngrams"][0]) == 6
    assert ng["ngrams"][3] == []


def test_hashingtf_bucket_parity_and_counts():
    from sklearn.utils.murmurhash import murmurhash3_32

    f = _tok_frame()
    tf = HashingTF(inputCol="tokens", outputCol="tf", numFeatures=64)
    out = tf.transform(f)["tf"]
    assert out.shape == (4, 64)
    # Spark indexOf semantics: signed murmur3(seed 42), nonNegativeMod
    for term in ["tcp", "scan", "host"]:
        h = murmurhash3_32(term.encode("utf-8"), seed=42, positive=False)
        assert tf.indexOf(term) == ((h % 64) + 64) % 64
    assert out[2, tf.indexOf("scan")] == 3.0
    assert out[3].sum() == 0.0
    binary = HashingTF(
        inputCol="tokens", outputCol="tf", numFeatures=64, binary=True
    ).transform(f)["tf"]
    assert binary[2, tf.indexOf("scan")] == 1.0


def test_hashingtf_dense_guard():
    with pytest.raises(ValueError, match="dense"):
        HashingTF(
            inputCol="tokens", outputCol="tf", numFeatures=1 << 18
        ).transform(
            Frame({"tokens": np.array([["x"]] * 10_000, dtype=object)})
        )
    # the default width stays usable at realistic row counts
    out = HashingTF(inputCol="tokens", outputCol="tf").transform(
        Frame({"tokens": np.array([["x"]] * 10_000, dtype=object)})
    )
    assert out["tf"].shape == (10_000, 4096)


def test_count_vectorizer_matches_sklearn(mesh8):
    from sklearn.feature_extraction.text import CountVectorizer as SkCV

    f = _tok_frame()
    cv = CountVectorizer(inputCol="tokens", outputCol="counts").fit(f)
    out = cv.transform(f)["counts"]
    sk = SkCV(analyzer=lambda d: d)
    ref = sk.fit_transform([list(t) for t in f["tokens"]]).toarray()
    ref_vocab = sk.vocabulary_
    assert set(cv.vocabulary) == set(ref_vocab)
    for t, j in ref_vocab.items():
        np.testing.assert_array_equal(
            out[:, cv.vocabulary.index(t)], ref[:, j]
        )
    # frequency-desc, term-asc ordering is deterministic: 'scan' (3
    # occurrences) first, then 'from' (3) — tie broken by term
    assert cv.vocabulary[0] in ("from", "scan")
    assert sorted(cv.vocabulary[:2]) == cv.vocabulary[:2]


def test_count_vectorizer_df_bounds_and_mintf():
    f = _tok_frame()
    cv = CountVectorizer(
        inputCol="tokens", outputCol="counts", minDF=2.0
    ).fit(f)
    assert set(cv.vocabulary) == {"from", "host"}  # in ≥2 docs
    cv2 = CountVectorizer(
        inputCol="tokens", outputCol="counts", maxDF=2.0
    ).fit(f)
    assert "from" not in cv2.vocabulary  # df=3 > 2
    out = CountVectorizer(
        inputCol="tokens", outputCol="counts", minTF=2.0
    ).fit(f).transform(f)["counts"]
    # only 'scan' (count 3 in doc 2) survives minTF=2
    assert out.sum() == 3.0


def test_idf_matches_formula(mesh8):
    f = _tok_frame()
    counts = CountVectorizer(inputCol="tokens", outputCol="counts").fit(
        f
    ).transform(f)
    idf_model = IDF(inputCol="counts", outputCol="tfidf").fit(counts)
    X = counts["counts"]
    m = X.shape[0]
    df = (X > 0).sum(axis=0).astype(np.float64)
    np.testing.assert_allclose(
        idf_model.idf, np.log((m + 1.0) / (df + 1.0)), rtol=1e-12
    )
    out = idf_model.transform(counts)["tfidf"]
    np.testing.assert_allclose(
        out, X * idf_model.idf[None, :].astype(np.float32), rtol=1e-6
    )
    # minDocFreq zeroes rare terms
    idf2 = IDF(inputCol="counts", outputCol="tfidf", minDocFreq=2).fit(
        counts
    )
    assert (idf2.idf[df < 2] == 0).all()
    assert (idf2.idf[df >= 2] > 0).all()


def test_text_save_load(mesh8, tmp_path):
    f = _tok_frame()
    cv = CountVectorizer(inputCol="tokens", outputCol="counts").fit(f)
    save_model(cv, str(tmp_path / "cv"))
    cv2 = load_model(str(tmp_path / "cv"))
    assert cv2.vocabulary == cv.vocabulary
    np.testing.assert_array_equal(
        cv2.transform(f)["counts"], cv.transform(f)["counts"]
    )
    counts = cv.transform(f)
    idf = IDF(inputCol="counts", outputCol="tfidf").fit(counts)
    save_model(idf, str(tmp_path / "idf"))
    idf2 = load_model(str(tmp_path / "idf"))
    np.testing.assert_allclose(idf2.idf, idf.idf)
    assert idf2.numDocs == idf.numDocs
