"""NetFlow v5 native parser tests: C++ vs the pure-Python oracle, malformed
input, schema lifting, and the capture->stream->predict path [B:11]."""

import socket

import numpy as np
import pytest

from sntc_tpu.native import (
    NF5_FIELDS,
    make_datagram,
    netflow_to_flow_frame,
    parse_datagram,
    parse_stream,
    using_native,
)
from sntc_tpu.native.netflow import _parse_py, _parse_stream_py


def _records(n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        first = int(rng.integers(0, 1_000_000))
        out.append((
            int(rng.integers(0, 2**32)), int(rng.integers(0, 2**32)),
            int(rng.integers(0, 65536)), int(rng.integers(0, 65536)),
            6, int(rng.integers(0, 64)), 0,
            int(rng.integers(1, 10_000)), int(rng.integers(40, 10_000_000)),
            first, first + int(rng.integers(0, 60_000)),
            1, 2, 0, 0,
        ))
    return out


def test_native_compiles():
    assert using_native(), "g++ build of netflow.cpp failed"


def test_parse_matches_python_oracle():
    recs = _records(7)
    dg = make_datagram(recs)
    got = parse_datagram(dg)
    want = _parse_py(dg)
    assert got is not None and got.shape == (7, NF5_FIELDS)
    np.testing.assert_array_equal(got, want)
    # spot-check real fields
    assert got[0, 3] == recs[0][3]  # dstport
    assert got[0, 7] == recs[0][7]  # packets
    assert got[0, 15] == recs[0][10] - recs[0][9]  # duration


def test_malformed_rejected():
    assert parse_datagram(b"") is None
    assert parse_datagram(b"\x00" * 23) is None
    good = make_datagram(_records(2))
    assert parse_datagram(b"\x00\x09" + good[2:]) is None  # version 9
    with pytest.raises(ValueError):
        make_datagram(_records(31))


def test_truncated_datagram_salvages_valid_prefix():
    """r10: a datagram cut mid-record no longer vanishes into None —
    the records that fully fit parse, and the torn tail is reported as
    a structured ``parse_truncated`` event (docs/RESILIENCE.md
    "Data-plane admission")."""
    import sntc_tpu.resilience as R

    R.clear_events()
    good = make_datagram(_records(2))
    got = parse_datagram(good[:-10])  # second record torn
    assert got is not None and got.shape == (1, NF5_FIELDS)
    np.testing.assert_array_equal(got[0], _parse_py(good)[0])
    events = [
        e for e in R.recent_events() if e.get("event") == "parse_truncated"
    ]
    assert len(events) == 1
    assert events[0]["format"] == "netflow"
    assert events[0]["dropped_bytes"] == 48 - 10
    # header-only torn datagram: zero records, still no exception
    assert parse_datagram(good[:30]).shape == (0, NF5_FIELDS)


def test_parse_stream_concatenated():
    dgs = [make_datagram(_records(5, seed=i), seq=i) for i in range(4)]
    data = b"".join(dgs)
    got = parse_stream(data)
    want = _parse_stream_py(data)
    assert got.shape == (20, NF5_FIELDS)
    np.testing.assert_array_equal(got, want)
    # trailing garbage stops cleanly at the boundary
    got2 = parse_stream(data + b"\xff" * 10)
    assert got2.shape == (20, NF5_FIELDS)


def test_flow_frame_schema():
    from sntc_tpu.data import CICIDS2017_FEATURES

    recs = parse_datagram(make_datagram(_records(3)))
    f = netflow_to_flow_frame(recs)
    assert f.num_rows == 3
    assert set(f.columns) == set(CICIDS2017_FEATURES)
    assert (f["Flow Bytes/s"] > 0).all()
    syn = f["SYN Flag Count"]
    assert ((syn == 0) | (syn == 1)).all()


def test_udp_capture_to_streaming_prediction(tmp_path, mesh8):
    """Loopback UDP -> capture WAL -> NetFlowDirSource -> model.transform."""
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import MemorySink, StreamingQuery
    from sntc_tpu.serve.netflow_source import NetFlowDirSource, capture_udp
    from sntc_tpu.data import CICIDS2017_FEATURES

    # train a toy model on the full 78-col schema
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 78)).astype(np.float32) + 1.0
    y = (X[:, 0] > 1.0).astype(np.float64)
    model = LogisticRegression(mesh=mesh8, maxIter=10).fit(
        Frame({"features": X, "label": y})
    )

    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    port = recv.getsockname()[1]
    send = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    for i in range(3):
        send.sendto(make_datagram(_records(10, seed=i), seq=i),
                    ("127.0.0.1", port))
    send.close()

    cap_dir = str(tmp_path / "captures")
    n = capture_udp(port, cap_dir, max_datagrams=3, timeout_s=2.0, sock=recv)
    recv.close()
    assert n == 3

    # serving pipeline: assemble the schema columns -> predict
    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import VectorAssembler

    serve = PipelineModel(stages=[
        VectorAssembler(inputCols=CICIDS2017_FEATURES, outputCol="features"),
        model,
    ])
    sink = MemorySink()
    q = StreamingQuery(
        serve, NetFlowDirSource(cap_dir), sink, str(tmp_path / "ckpt")
    )
    assert q.process_available() == 1
    assert sink.frames[0].num_rows == 30
    assert "prediction" in sink.frames[0].columns
