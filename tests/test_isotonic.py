"""IsotonicRegression oracle tests vs sklearn (exact PAVA agreement)."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import IsotonicRegression, IsotonicRegressionModel


def test_matches_sklearn_pava():
    from sklearn.isotonic import IsotonicRegression as SkIso

    rng = np.random.default_rng(0)
    n = 2000
    x = rng.uniform(0, 10, size=n)
    y = np.sin(x / 3.5) * 3 + x * 0.4 + rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, size=n)
    f = Frame({"features": x, "label": y, "w": w})
    m = IsotonicRegression(weightCol="w").fit(f)
    ours = m.predict(x)
    sk = SkIso(out_of_bounds="clip").fit(x, y, sample_weight=w)
    np.testing.assert_allclose(ours, sk.predict(x), atol=1e-8)


def test_antitonic_and_vector_feature_index():
    rng = np.random.default_rng(1)
    n = 1000
    x = rng.uniform(0, 5, size=n)
    y = -2.0 * x + rng.normal(size=n)
    X = np.stack([rng.normal(size=n), x], axis=1)
    f = Frame({"features": X, "label": y})
    m = IsotonicRegression(isotonic=False, featureIndex=1).fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    assert np.corrcoef(pred, y)[0, 1] > 0.9
    # monotone decreasing in x
    order = np.argsort(x)
    assert np.all(np.diff(pred[order]) <= 1e-12)


def test_interpolation_and_clamp():
    f = Frame({
        "features": np.array([1.0, 2.0, 3.0, 4.0]),
        "label": np.array([1.0, 3.0, 3.0, 7.0]),
    })
    m = IsotonicRegression().fit(f)
    # between boundaries: linear; outside: clamped (Spark predict)
    assert m.predict(np.array([1.5]))[0] == pytest.approx(2.0)
    assert m.predict(np.array([0.0]))[0] == pytest.approx(1.0)
    assert m.predict(np.array([99.0]))[0] == pytest.approx(7.0)


def test_save_load(tmp_path):
    rng = np.random.default_rng(2)
    x = rng.uniform(0, 1, size=300)
    f = Frame({"features": x, "label": x + rng.normal(size=300) * 0.1})
    m = IsotonicRegression().fit(f)
    m2 = load_model(save_model(m, str(tmp_path / "iso")))
    assert isinstance(m2, IsotonicRegressionModel)
    np.testing.assert_allclose(m2.predict(x), m.predict(x))
