"""AFTSurvivalRegression: independent-optimizer oracle (the same Weibull
AFT negative log-likelihood minimized by scipy L-BFGS-B in float64),
parameter recovery on simulated data, censoring semantics, quantiles."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import AFTSurvivalRegression
from sntc_tpu.mlio.save_load import load_model, save_model


def _simulate(n=4000, seed=0, censor_frac=0.3):
    rng = np.random.default_rng(seed)
    d = 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([0.6, -0.4, 0.2])
    b, sigma = 1.5, 0.7
    # standard minimum extreme value: CDF 1 - exp(-e^x)
    g = np.log(-np.log(rng.uniform(size=n)))
    T = np.exp(X @ beta + b + sigma * g)
    cutoff = np.quantile(T, 1.0 - censor_frac)
    t_obs = np.minimum(T, cutoff)
    delta = (T <= cutoff).astype(np.float32)
    return X, t_obs, delta, (beta, b, sigma)


def _nll(theta, X, t, delta):
    d = X.shape[1]
    coef, b, log_s = theta[:d], theta[d], theta[d + 1]
    eps = (np.log(t) - X @ coef - b) / np.exp(log_s)
    ll = delta * (eps - log_s) - np.exp(eps)
    return -ll.mean()


def test_aft_matches_scipy_optimum(mesh8):
    from scipy.optimize import minimize

    X, t, delta, _ = _simulate()
    f = Frame({"features": X, "label": t, "censor": delta})
    m = AFTSurvivalRegression(maxIter=200, tol=1e-8).fit(f)
    ours = np.concatenate(
        [m.coefficients, [m.intercept, np.log(m.scale)]]
    )
    ref = minimize(
        _nll, np.zeros(X.shape[1] + 2),
        args=(X.astype(np.float64), t, delta.astype(np.float64)),
        method="L-BFGS-B", options={"maxiter": 500, "ftol": 1e-14},
    )
    # same objective optimum (the coefficient parametrizations differ by
    # the internal scaling, so compare achieved NLL, then coefficients)
    assert _nll(ours, X.astype(np.float64), t, delta) <= ref.fun + 1e-4
    np.testing.assert_allclose(ours, ref.x, atol=2e-2)


def test_aft_recovers_truth(mesh8):
    X, t, delta, (beta, b, sigma) = _simulate(n=20_000, seed=3)
    f = Frame({"features": X, "label": t, "censor": delta})
    m = AFTSurvivalRegression().fit(f)
    np.testing.assert_allclose(m.coefficients, beta, atol=0.05)
    assert m.intercept == pytest.approx(b, abs=0.05)
    assert m.scale == pytest.approx(sigma, abs=0.05)
    assert m.summary.totalIterations > 0
    assert m.summary.objectiveHistory[-1] < m.summary.objectiveHistory[0]


def test_aft_censoring_matters(mesh8):
    # treating censored rows as events biases the fit; the censored
    # likelihood must not
    X, t, delta, (beta, *_r) = _simulate(n=10_000, seed=5, censor_frac=0.5)
    f_cens = Frame({"features": X, "label": t, "censor": delta})
    f_naive = Frame(
        {"features": X, "label": t, "censor": np.ones_like(delta)}
    )
    m_c = AFTSurvivalRegression().fit(f_cens)
    m_n = AFTSurvivalRegression().fit(f_naive)
    err_c = np.abs(m_c.coefficients - beta).max()
    err_n = np.abs(m_n.coefficients - beta).max()
    assert err_c < err_n


def test_aft_quantiles_and_transform(mesh8):
    X, t, delta, _ = _simulate(n=2_000, seed=7)
    f = Frame({"features": X, "label": t, "censor": delta})
    m = AFTSurvivalRegression(
        quantilesCol="q", quantileProbabilities=(0.5,)
    ).fit(f)
    out = m.transform(f)
    assert out["prediction"].shape == (2_000,)
    # median = prediction * (ln 2)^sigma
    np.testing.assert_allclose(
        out["q"][:, 0],
        out["prediction"] * np.log(2.0) ** m.scale,
        rtol=1e-10,
    )


def test_aft_validation_errors(mesh8):
    X = np.ones((4, 2), np.float32)
    with pytest.raises(ValueError, match="> 0"):
        AFTSurvivalRegression().fit(
            Frame({"features": X, "label": np.array([1.0, -1, 1, 1]),
                   "censor": np.ones(4, np.float32)})
        )
    with pytest.raises(ValueError, match="censor"):
        AFTSurvivalRegression().fit(
            Frame({"features": X, "label": np.ones(4),
                   "censor": np.array([0.5, 1, 1, 1], np.float32)})
        )


def test_aft_save_load(mesh8, tmp_path):
    X, t, delta, _ = _simulate(n=1_000, seed=9)
    f = Frame({"features": X, "label": t, "censor": delta})
    m = AFTSurvivalRegression().fit(f)
    save_model(m, str(tmp_path / "aft"))
    m2 = load_model(str(tmp_path / "aft"))
    np.testing.assert_allclose(m2.coefficients, m.coefficients)
    assert m2.intercept == m.intercept and m2.scale == m.scale
    np.testing.assert_allclose(
        m2.transform(f)["prediction"], m.transform(f)["prediction"]
    )
