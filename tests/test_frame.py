import numpy as np
import pytest

from sntc_tpu.core.frame import Frame


def _frame(n=10):
    return Frame({
        "a": np.arange(n, dtype=np.float32),
        "vec": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
        "label": np.array([f"c{i % 3}" for i in range(n)], dtype=object),
    })


def test_construction_and_accessors():
    f = _frame()
    assert f.num_rows == 10 and len(f) == 10
    assert f.columns == ["a", "vec", "label"]
    assert f["vec"].shape == (10, 3)
    with pytest.raises(KeyError):
        f["missing"]


def test_row_count_mismatch_rejected():
    with pytest.raises(ValueError):
        Frame({"a": np.zeros(3), "b": np.zeros(4)})


def test_with_column_is_functional():
    f = _frame()
    g = f.with_column("b", np.ones(10))
    assert "b" in g and "b" not in f


def test_filter_take_slice_concat():
    f = _frame()
    assert f.filter(f["a"] < 5).num_rows == 5
    assert np.array_equal(f.take(np.array([2, 0]))["a"], [2.0, 0.0])
    assert f.slice(2, 6).num_rows == 4
    assert f.concat(f).num_rows == 20


def test_random_split_partitions_all_rows():
    f = _frame(100)
    a, b = f.random_split([0.8, 0.2], seed=1)
    assert a.num_rows + b.num_rows == 100
    assert abs(a.num_rows - 80) <= 1
    merged = sorted(np.concatenate([a["a"], b["a"]]).tolist())
    assert merged == sorted(f["a"].tolist())


def test_random_split_many_weights_drops_no_rows():
    f = _frame(1000)
    parts = f.random_split([0.1] * 10, seed=0)
    assert sum(p.num_rows for p in parts) == 1000


def test_concat_all():
    f = _frame(10)
    g = Frame.concat_all([f, f, f])
    assert g.num_rows == 30
    with pytest.raises(ValueError):
        Frame.concat_all([f, Frame({"z": np.zeros(2)})])


def test_arrow_roundtrip_with_vector_column():
    f = _frame()
    table = f.to_arrow()
    g = Frame.from_arrow(table)
    assert g.columns == f.columns
    assert np.array_equal(g["vec"], f["vec"])
    assert list(g["label"]) == list(f["label"])


def test_device_columns_round_trip():
    """jax.Array columns are held as-is (device residency) and materialize
    through to_arrow/to_pandas and numpy fallbacks."""
    import jax.numpy as jnp

    x = jnp.arange(12, dtype=jnp.float32).reshape(6, 2)
    f = Frame({"v": x, "s": np.arange(6.0)})
    assert f.num_rows == 6
    table = f.to_arrow()
    assert table.num_rows == 6
    np.testing.assert_array_equal(
        np.asarray(f["v"]), np.arange(12, dtype=np.float32).reshape(6, 2)
    )
    sliced = f.slice(1, 3)
    assert sliced.num_rows == 2
    filtered = f.filter(np.asarray(f["s"]) > 2.0)
    assert filtered.num_rows == 3


def test_take_boolean_mask_selects_consistently():
    """A boolean array passed to take() is a mask (numpy fancy-indexing
    semantics), not positions — row count must match the selection."""
    f = Frame({"a": np.arange(5.0), "b": np.arange(10.0).reshape(5, 2)})
    mask = np.array([True, False, True, False, False])
    g = f.take(mask)
    assert g.num_rows == 2
    assert np.array_equal(g["a"], np.array([0.0, 2.0]))
    assert len(g) == 2
    # derived frames keep consistent bookkeeping
    assert g.filter(np.array([True, False])).num_rows == 1


def test_derived_frames_keep_row_counts():
    f = Frame({"a": np.arange(7.0)})
    assert f.slice(2, 100).num_rows == 5
    assert f.slice(0, None).num_rows == 7
    assert f.take(np.array([6, 0, 3])).num_rows == 3
    assert f.select(["a"]).num_rows == 7
    assert f.drop("a").columns == []
    assert Frame.concat_all([f]) is f


def test_pad_rows_repeats_last_row_and_validates():
    import pytest

    f = Frame({
        "a": np.arange(3, dtype=np.float32),
        "v": np.arange(6, dtype=np.float32).reshape(3, 2),
        "s": np.array(["x", "y", "z"], dtype=object),
    })
    p = f.pad_rows(5)
    assert p.num_rows == 5
    np.testing.assert_array_equal(p["a"], [0, 1, 2, 2, 2])
    np.testing.assert_array_equal(p["v"][3:], [[4, 5], [4, 5]])
    assert list(p["s"]) == ["x", "y", "z", "z", "z"]
    assert f.pad_rows(3) is f  # no-op shares the immutable frame
    with pytest.raises(ValueError):
        f.pad_rows(2)
    with pytest.raises(ValueError):
        Frame({}).pad_rows(4)
