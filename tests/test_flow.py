"""Stateful flow-feature engine tests (sntc_tpu/flow, r14).

Golden-value window correctness against hand-computed references,
bitwise equality of windowed output vs the whole-capture oracle
(including out-of-order and session-gap cases), the late-record /
watermark-eviction state bounds, snapshot/restore and
snapshot-at-commit crash safety (in-process and via the real
process-kill chaos matrix), the `--from-capture` CLI path, and the
tier-1 wiring of scripts/check_flow_flags.py.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.data.synth import write_capture_stream
from sntc_tpu.flow import (
    FlowCaptureSource,
    FlowFeatureEngine,
    FlowStateCorruptError,
    FlowStateError,
    FlowStateStore,
    NetFlowMeter,
    PcapFlowMeter,
)
from sntc_tpu.native import (
    make_datagram,
    make_packet,
    make_pcap,
    netflow_to_flow_frame,
    packets_to_flow_frame,
    parse_pcap,
    parse_stream,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

A, B = 0x0A000001, 0x0A000002  # the golden flow's endpoints


def _pkts(spec):
    """[(ts, src, dst, sport, dport, payload)] -> parsed packet matrix
    (through the real pcap encode/decode round trip)."""
    cap = make_pcap([
        (ts, make_packet(s, d, sp, dp, proto=6, payload=pay))
        for ts, s, d, sp, dp, pay in spec
    ])
    out = parse_pcap(cap)
    assert out is not None and out.shape[0] == len(spec)
    return out


def _sentinel(ts):
    """One far-future packet on a reserved key: advances the watermark
    without joining any flow under test."""
    return _pkts([(ts, 0x01010101, 0x02020202, 9, 9, 8)])


def _engine(**kw):
    kw.setdefault("allowed_lateness", 0.5)
    meter = PcapFlowMeter(
        flow_timeout=kw.pop("flow_timeout", 2.0),
        activity_timeout=kw.pop("activity_timeout", 1.0),
    )
    return FlowFeatureEngine(meter, **kw)


def _rows(frame):
    """Canonical row matrix for order-free bitwise comparison."""
    arr = np.stack(
        [np.asarray(frame[c], np.float64) for c in frame.columns], 1
    )
    return arr[np.lexsort(arr.T[::-1])]


# ---------------------------------------------------------------------------
# golden-value window correctness
# ---------------------------------------------------------------------------


def test_golden_single_flow_hand_computed():
    eng = _engine()
    eng.consume(_pkts([
        (100.0, A, B, 1024, 80, 100),   # fwd
        (100.1, B, A, 80, 1024, 50),    # bwd
        (100.3, A, B, 1024, 80, 200),   # fwd
    ]))
    eng.consume(_sentinel(200.0))  # watermark far past the flow
    out = eng.poll()
    assert out.num_rows == 1
    got = {c: float(out[c][0]) for c in out.columns}
    assert got["Destination Port"] == 80.0
    assert got["Flow Duration"] == pytest.approx(300_000.0, rel=1e-6)
    assert got["Total Fwd Packets"] == 2.0
    assert got["Total Backward Packets"] == 1.0
    assert got["Total Length of Fwd Packets"] == 300.0
    assert got["Total Length of Bwd Packets"] == 50.0
    assert got["Fwd Packet Length Mean"] == 150.0
    assert got["Fwd Packet Length Max"] == 200.0
    assert got["Fwd Packet Length Min"] == 100.0
    # sample std of [100, 200]
    assert got["Fwd Packet Length Std"] == pytest.approx(
        70.7106781, rel=1e-6
    )
    assert got["Flow IAT Mean"] == pytest.approx(150_000.0, rel=1e-6)
    assert got["Flow IAT Max"] == pytest.approx(200_000.0, rel=1e-6)
    assert got["Flow IAT Min"] == pytest.approx(100_000.0, rel=1e-6)
    assert got["Flow Bytes/s"] == pytest.approx(350 / 0.3, rel=1e-5)
    assert got["Flow Packets/s"] == pytest.approx(3 / 0.3, rel=1e-5)
    assert got["Down/Up Ratio"] == 0.0
    assert eng.windows_emitted == 1
    assert eng.evictions == {"watermark": 1}


def test_session_gap_splits_into_two_windows():
    eng = _engine(flow_timeout=2.0)
    spec = [
        (10.0, A, B, 1024, 80, 100), (10.5, A, B, 1024, 80, 100),
        # quiet gap of 20s >> flow_timeout: a NEW session window
        (30.0, A, B, 1024, 80, 40), (30.2, A, B, 1024, 80, 40),
    ]
    eng.consume(_pkts(spec))
    eng.consume(_sentinel(100.0))
    out = eng.poll()
    assert out.num_rows == 2
    durs = sorted(np.asarray(out["Flow Duration"], np.float64))
    assert durs == pytest.approx([200_000.0, 500_000.0], rel=1e-6)


def test_out_of_order_within_lateness_is_bitwise_order_free():
    spec = [
        (10.0, A, B, 1024, 80, 100), (10.1, B, A, 80, 1024, 60),
        (10.2, A, B, 1024, 80, 80), (10.3, B, A, 80, 1024, 30),
    ]
    pkts = _pkts(spec)
    e1 = _engine(allowed_lateness=1.0)
    e1.consume(pkts)
    e1.consume(_sentinel(100.0))
    ref = e1.poll()
    # scrambled arrival over several consume calls, inside lateness
    e2 = _engine(allowed_lateness=1.0)
    e2.consume(pkts[[2]])
    e2.consume(pkts[[0, 3]])
    e2.consume(pkts[[1]])
    assert e2.out_of_order >= 2 and e2.late_records == 0
    e2.consume(_sentinel(100.0))
    out = e2.poll()
    assert np.array_equal(_rows(ref), _rows(out))  # bitwise


def test_late_record_drops_with_reason_code():
    from sntc_tpu.resilience import recent_events

    eng = _engine(allowed_lateness=0.5)
    eng.consume(_pkts([(50.0, A, B, 1024, 80, 100)]))
    # 40.0 < watermark 49.5: dropped, never joins any window
    eng.consume(_pkts([(40.0, A, B, 1024, 80, 999)]))
    assert eng.late_records == 1
    evs = [e for e in recent_events()
           if e.get("event") == "flow_late_records"]
    assert evs and evs[-1]["reason"] == "late_record"
    eng.consume(_sentinel(100.0))
    out = eng.poll()
    assert out.num_rows == 1
    assert float(out["Total Fwd Packets"][0]) == 1.0  # late pkt excluded


def test_watermark_eviction_bounds_state_on_out_of_order_replay():
    """The acceptance-criteria bound: on a long out-of-order replayed
    capture, buffered state stays a small constant (the watermark
    window) while total consumption grows without bound."""
    d = str(pytest.importorskip("tempfile").mkdtemp())
    info = write_capture_stream(
        d, n_files=20, flows_per_file=4, packets_per_flow=6,
        seed=5, defer_fraction=0.25, flush=False, file_gap_s=1.0,
    )
    src = FlowCaptureSource(
        d, format="pcap", flow_timeout=0.5, allowed_lateness=1.5,
    )
    peaks = []
    for i in range(src.latest_offset()):
        src.get_batch(i, i + 1)
        peaks.append(src.engine.state_size()["packets"])
    consumed = src.engine.records_consumed
    assert consumed >= 20 * 4 * 6 - info["n_flows"]  # ~everything
    # watermark window spans lateness (1.5) + timeout (0.5) + one file
    # of arrival skew: at most ~4 files' packets ever buffered
    per_file = 4 * 6
    assert max(peaks) <= 4 * per_file
    assert max(peaks) < consumed / 3  # state ≪ stream length
    assert src.engine.out_of_order > 0


def test_state_cap_force_evicts_oldest():
    eng = _engine(flow_timeout=1000.0, max_state_packets=8)
    # long-lived flows the watermark can never complete
    for i in range(6):
        s = 0x0B000000 + i
        eng.consume(_pkts([
            (10.0 + i, s, B, 2000 + i, 80, 10),
            (10.5 + i, s, B, 2000 + i, 80, 10),
        ]))
        eng.poll()
        assert eng.state_size()["packets"] <= 8
    assert eng.evictions.get("state_cap", 0) >= 1
    assert eng.windows_emitted >= 1


def test_snapshot_restore_replays_bitwise():
    d = str(pytest.importorskip("tempfile").mkdtemp())
    write_capture_stream(
        d, n_files=6, flows_per_file=3, packets_per_flow=6, seed=7,
        defer_fraction=0.2,
    )
    src1 = FlowCaptureSource(
        d, format="pcap", flow_timeout=0.5, allowed_lateness=1.2,
    )
    frames, snap = [], None
    for i in range(src1.latest_offset()):
        if i == 3:
            snap = src1.engine.snapshot()
        frames.append(src1.get_batch(i, i + 1))
    src2 = FlowCaptureSource(
        d, format="pcap", flow_timeout=0.5, allowed_lateness=1.2,
    )
    src2.engine.restore(snap)
    src2._consumed_end = 3
    for i in range(3, src2.latest_offset()):
        a, b = frames[i], src2.get_batch(i, i + 1)
        assert a.columns == b.columns
        for c in a.columns:
            assert np.array_equal(a[c], b[c]), c  # bitwise


def test_windowed_equals_whole_capture_oracle():
    d = str(pytest.importorskip("tempfile").mkdtemp())
    info = write_capture_stream(
        d, n_files=6, flows_per_file=3, packets_per_flow=6, seed=9,
        defer_fraction=0.2,
    )
    oracle = packets_to_flow_frame(
        info["packets"], flow_timeout=0.5, activity_timeout=0.2
    )
    src = FlowCaptureSource(
        d, format="pcap", flow_timeout=0.5, activity_timeout=0.2,
        allowed_lateness=5.0,
    )
    frames = [
        src.get_batch(i, i + 1) for i in range(src.latest_offset())
    ]
    emitted = Frame.concat_all(frames)
    # every real window emitted (the sentinel stays open in state)
    assert src.engine.state_size() == {"flows": 1, "packets": 1}
    assert np.array_equal(_rows(emitted), _rows(oracle))  # bitwise


# ---------------------------------------------------------------------------
# NetFlow windows
# ---------------------------------------------------------------------------


def test_netflow_merge_golden():
    # two exporter records of ONE flow, 100ms apart -> one window
    recs = [
        (A, B, 1024, 80, 6, 0x02, 0, 3, 300, 1000, 1040, 1, 2, 0, 0),
        (A, B, 1024, 80, 6, 0x18, 0, 2, 200, 1100, 1150, 1, 2, 0, 0),
        # a different flow
        (B, A, 443, 9999, 6, 0x10, 0, 1, 99, 1000, 1001, 1, 2, 0, 0),
    ]
    records = parse_stream(make_datagram(recs))
    out = NetFlowMeter(flow_timeout=10.0).emit(records)
    assert out.num_rows == 2
    i = int(np.asarray(out["Total Fwd Packets"]).argmax())
    assert float(out["Total Fwd Packets"][i]) == 5.0   # 3 + 2
    assert float(out["Total Length of Fwd Packets"][i]) == 500.0
    # duration: min(first)=1000 .. max(last)=1150 -> 150ms = 150000us
    assert float(out["Flow Duration"][i]) == pytest.approx(150_000.0)
    assert float(out["SYN Flag Count"][i]) == 1.0  # OR'd flags has 0x02
    assert float(out["PSH Flag Count"][i]) == 1.0  # ...and 0x08


def test_netflow_capture_source_end_to_end():
    d = str(pytest.importorskip("tempfile").mkdtemp())
    info = write_capture_stream(
        d, n_files=4, flows_per_file=3, packets_per_flow=4, seed=3,
        format="netflow", file_gap_s=1.0,
    )
    assert info["records"].shape[1] == 16
    src = FlowCaptureSource(
        d, format="netflow", flow_timeout=0.5, allowed_lateness=0.2,
    )
    frames = [
        src.get_batch(i, i + 1) for i in range(src.latest_offset())
    ]
    frames.append(src.flush_windows())
    total = sum(f.num_rows for f in frames)
    oracle = NetFlowMeter(flow_timeout=0.5).emit(info["records"])
    # +1: the flush sentinel record emits as its own window here
    assert total == oracle.num_rows + 1


# ---------------------------------------------------------------------------
# state store + source protocol
# ---------------------------------------------------------------------------


def test_state_store_roundtrip_retention_and_corruption(tmp_path):
    store = FlowStateStore(str(tmp_path / "st"))
    for end, payload in ((1, b"one"), (2, b"two"), (3, b"three")):
        store.publish(end, payload)
    assert store.ends() == [2, 3]  # keep=2 pruned offset 1
    assert store.load(3) == b"three"
    assert store.load(1) is None
    # torn payload -> loud integrity failure naming the file
    path = store._file(2)
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[:-1])
    with pytest.raises(FlowStateCorruptError):
        store.load(2)


def test_source_ordered_consumption_and_memoized_retry(tmp_path):
    d = str(tmp_path / "cap")
    write_capture_stream(d, n_files=3, flows_per_file=2,
                         packets_per_flow=4, seed=2)
    src = FlowCaptureSource(d, format="pcap", flow_timeout=0.5,
                            allowed_lateness=0.2)
    f0 = src.get_batch(0, 1)
    consumed = src.engine.records_consumed
    # engine read-retry of the SAME range: memoized, no double-consume
    again = src.get_batch(0, 1)
    assert again is f0 and src.engine.records_consumed == consumed
    # rewinding below the consumed offset is a contract violation
    src.get_batch(1, 2)
    with pytest.raises(ValueError, match="snapshot-at-commit"):
        src.get_batch(0, 1)


def test_on_restore_requires_matching_snapshot(tmp_path):
    d = str(tmp_path / "cap")
    write_capture_stream(d, n_files=3, flows_per_file=2,
                         packets_per_flow=4, seed=2)
    src = FlowCaptureSource(
        d, format="pcap", state_dir=str(tmp_path / "st")
    )
    src.on_restore(0)  # fresh state: fine
    with pytest.raises(FlowStateError, match="diverged"):
        src.on_restore(2)  # nonzero offset, no snapshot
    # and with NO store at all, a nonzero offset is unrecoverable
    src2 = FlowCaptureSource(d, format="pcap")
    with pytest.raises(FlowStateError, match="state_dir"):
        src2.on_restore(1)


def test_streaming_query_restart_converges_bitwise(tmp_path, mesh8):
    """In-process crash analog: a fresh StreamingQuery on the same
    checkpoint (uncommitted WAL intent pending) must replay to the
    uninterrupted reference's sink bytes."""
    import glob as _glob

    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import arm, clear
    from sntc_tpu.serve.streaming import CsvDirSink, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    cap = str(tmp_path / "cap")
    write_capture_stream(cap, n_files=5, flows_per_file=3,
                         packets_per_flow=6, seed=4, defer_fraction=0.2)
    cols = ["Destination Port", "Flow Duration", "Total Fwd Packets",
            "Flow IAT Mean", "Flow Bytes/s"]

    def engine(d):
        src = FlowCaptureSource(
            cap, format="pcap", flow_timeout=0.5,
            allowed_lateness=1.2,
            state_dir=os.path.join(d, "ckpt", "flow_state"),
        )
        return src, StreamingQuery(
            Identity(), src,
            CsvDirSink(os.path.join(d, "out"), columns=cols),
            os.path.join(d, "ckpt"), max_batch_offsets=1,
        )

    def sink_bytes(d):
        return {
            os.path.basename(p): open(p, "rb").read()
            for p in sorted(_glob.glob(
                os.path.join(d, "out", "batch_*.csv")
            ))
        }

    ref = str(tmp_path / "ref")
    _, q = engine(ref)
    n_ref = q.process_available()
    assert n_ref == 6

    crash = str(tmp_path / "crash")
    src, q = engine(crash)
    for _ in range(2):
        q._run_one_batch()
    arm("sink.write", kind="io", times=100)
    with pytest.raises(Exception):
        q._run_one_batch()
    clear()
    assert q.in_flight_count() > 0  # a WAL intent is pending, unsunk
    del q, src
    src2, q2 = engine(crash)  # restart: on_restore + WAL replay
    q2.process_available()
    assert sink_bytes(crash) == sink_bytes(ref)  # bitwise


@pytest.mark.parametrize("fault_site,fault_after", [
    ("flow.emit", 2),   # raises after the memo landed -> memo path
    ("flow.evict", 1),  # raises inside poll(), consume already folded
                        # the records -> the _pending resume path
])
def test_raising_flow_fault_retries_without_double_consume(
    tmp_path, mesh8, fault_site, fault_after,
):
    """A RAISING fault anywhere after the consume re-enters get_batch
    through the engine's retry — the records must never fold into
    keyed state twice, and the run must converge bitwise to a
    no-fault reference."""
    import glob as _glob

    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import RetryPolicy, arm, clear
    from sntc_tpu.serve.streaming import CsvDirSink, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    cap = str(tmp_path / "cap")
    write_capture_stream(cap, n_files=4, flows_per_file=3,
                         packets_per_flow=6, seed=17)
    cols = ["Destination Port", "Flow Duration", "Total Fwd Packets"]

    def run(name, faulted):
        d = str(tmp_path / name)
        src = FlowCaptureSource(
            cap, format="pcap", flow_timeout=0.5,
            allowed_lateness=0.2,
            state_dir=os.path.join(d, "ckpt", "flow_state"),
        )
        q = StreamingQuery(
            Identity(), src,
            CsvDirSink(os.path.join(d, "out"), columns=cols),
            os.path.join(d, "ckpt"), max_batch_offsets=1,
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0),
        )
        if faulted:
            # fire mid-stream: state mutated, then the fault raises
            # before the batch returns
            arm(fault_site, kind="exc", after=fault_after, times=1)
        try:
            q.process_available()
        finally:
            clear()
        consumed = src.engine.records_consumed
        return {
            os.path.basename(p): open(p, "rb").read()
            for p in sorted(_glob.glob(
                os.path.join(d, "out", "batch_*.csv")
            ))
        }, consumed

    ref_sink, ref_consumed = run("ref", faulted=False)
    got_sink, got_consumed = run("faulted", faulted=True)
    assert got_consumed == ref_consumed  # exactly-once consumption
    assert got_sink == ref_sink  # bitwise


def test_persistent_poll_failure_quarantines_without_poisoning_state(
    tmp_path, mesh8,
):
    """A poll that fails EVERY round exhausts the quarantine threshold;
    the quarantined range's records must be excised from keyed state
    (no cascade of the same failing eviction set into later batches)
    and the stream must keep emitting windows afterwards."""
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.resilience import RetryPolicy, arm, clear
    from sntc_tpu.serve.streaming import CsvDirSink, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    cap = str(tmp_path / "cap")
    write_capture_stream(cap, n_files=5, flows_per_file=3,
                         packets_per_flow=6, seed=21)
    src = FlowCaptureSource(
        cap, format="pcap", flow_timeout=0.5, allowed_lateness=0.2,
        state_dir=str(tmp_path / "ckpt" / "flow_state"),
    )
    q = StreamingQuery(
        Identity(), src,
        CsvDirSink(str(tmp_path / "out"),
                   columns=["Destination Port", "Flow Duration"]),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.0),
        max_batch_failures=2,
    )
    # 2 attempts/round x 2 rounds = 4 raising polls exhaust ONE batch's
    # quarantine threshold; afterwards the site is spent and the
    # stream must recover
    arm("flow.evict", kind="exc", after=1, times=4)
    try:
        for _ in range(10):
            q.process_available()
    finally:
        clear()
    quarantined = [p for p in q.recentProgress if p.get("quarantined")]
    assert len(quarantined) == 1, q.recentProgress
    assert q.last_committed() == 5  # every batch committed regardless
    # the cascade check: batches AFTER the quarantined one still
    # emitted windows (their polls did not inherit the poisoned set)
    after = [p for p in q.recentProgress
             if p["batchId"] > quarantined[0]["batchId"]]
    assert sum(p["numInputRows"] for p in after) > 0
    # and the quarantined range's packets are NOT in keyed state: the
    # residue is just the flush sentinel plus flows the watermark has
    # not completed yet — far fewer than a whole un-excised file
    assert src.engine.state_size()["packets"] < 18  # one file = 18+


def test_pipelined_engine_matches_serial_bitwise(tmp_path, mesh8):
    """The overlapped engine (prefetching source + delivery thread)
    over the stateful flow source: reads stay ordered on the engine
    thread, parse staging is stateless, and the sink output must be
    byte-identical to the serial engine's."""
    import glob as _glob

    from sntc_tpu.core.base import Transformer
    from sntc_tpu.serve.streaming import CsvDirSink, StreamingQuery

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    cap = str(tmp_path / "cap")
    write_capture_stream(cap, n_files=6, flows_per_file=3,
                         packets_per_flow=6, seed=13,
                         defer_fraction=0.2)
    cols = ["Destination Port", "Flow Duration", "Total Fwd Packets",
            "Flow IAT Mean"]

    def run(name, pipelined):
        d = str(tmp_path / name)
        src = FlowCaptureSource(
            cap, format="pcap", flow_timeout=0.5,
            allowed_lateness=1.2,
            state_dir=os.path.join(d, "ckpt", "flow_state"),
            prefetch_batches=2 if pipelined else 0,
        )
        q = StreamingQuery(
            Identity(), src,
            CsvDirSink(os.path.join(d, "out"), columns=cols),
            os.path.join(d, "ckpt"), max_batch_offsets=1,
            pipeline_depth=3 if pipelined else 1,
            overlap_sink=pipelined,
        )
        q.process_available()
        q.stop()
        src.close()
        return {
            os.path.basename(p): open(p, "rb").read()
            for p in sorted(_glob.glob(
                os.path.join(d, "out", "batch_*.csv")
            ))
        }

    assert run("serial", False) == run("pipe", True)


def test_serve_daemon_capture_tenants(tmp_path, mesh8):
    """Two raw-capture tenants on one ServeDaemon: each runs its own
    namespaced flow operator (state under tenant/<id>/ckpt/flow_state)
    and emits exactly its own capture's windows."""
    from sntc_tpu.core.base import Transformer
    from sntc_tpu.serve import ServeDaemon, TenantSpec

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    cols = ["Destination Port", "Flow Duration", "Total Fwd Packets"]
    expected = {}
    specs = []
    for k, tid in enumerate(("t0", "t1")):
        cap = str(tmp_path / "in" / tid)
        write_capture_stream(cap, n_files=3, flows_per_file=2,
                             packets_per_flow=4, seed=20 + k)
        ref = FlowCaptureSource(cap, format="pcap", flow_timeout=0.5,
                                allowed_lateness=0.2)
        expected[tid] = sum(
            ref.get_batch(i, i + 1).num_rows
            for i in range(ref.latest_offset())
        )
        specs.append(TenantSpec(
            tenant_id=tid, model=Identity(), watch=cap,
            out=str(tmp_path / "out" / tid), out_columns=cols,
            from_capture="pcap",
            flow_options={"flow_timeout": 0.5,
                          "allowed_lateness": 0.2},
        ))
    daemon = ServeDaemon(specs, str(tmp_path / "root"))
    try:
        daemon.process_available()
        snap = {t.spec.tenant_id: t.rows_done for t in daemon.tenants}
    finally:
        daemon.close()
    assert snap == expected and all(v > 0 for v in snap.values())
    for tid in ("t0", "t1"):
        assert os.path.isdir(
            str(tmp_path / "root" / "tenant" / tid / "ckpt"
                / "flow_state")
        )


# ---------------------------------------------------------------------------
# capture writer
# ---------------------------------------------------------------------------


def test_write_capture_stream_parses_and_reports_truth(tmp_path):
    d = str(tmp_path / "cap")
    info = write_capture_stream(
        d, n_files=4, flows_per_file=2, packets_per_flow=4, seed=0
    )
    assert len(info["files"]) >= 4 and info["flush_file"] is not None
    total = 0
    for p in info["files"]:
        with open(p, "rb") as f:
            pkts = parse_pcap(f.read())
        assert pkts is not None
        total += pkts.shape[0]
    # every ground-truth packet present exactly once, plus the sentinel
    assert total == info["packets"].shape[0] + 1
    assert info["n_flows"] == 8


# ---------------------------------------------------------------------------
# CLI: --from-capture end-to-end
# ---------------------------------------------------------------------------


def test_serve_from_capture_cli(tmp_path, mesh8, capsys):
    from sntc_tpu.app import main

    data = str(tmp_path / "days")
    assert main(["synth", "--out", data, "--rows", "4000",
                 "--days", "2"]) == 0
    model = str(tmp_path / "model")
    main(["train", "--data", data, "--estimator", "lr", "--binary",
          "--max-iter", "10", "--model-out", model])
    capsys.readouterr()
    cap = str(tmp_path / "caps")
    write_capture_stream(cap, n_files=4, flows_per_file=3,
                         packets_per_flow=6, seed=6)
    out_dir = str(tmp_path / "preds")
    ckpt = str(tmp_path / "ckpt")
    rc = main([
        "serve", "--model", model, "--watch", cap, "--out", out_dir,
        "--checkpoint", ckpt, "--from-capture", "pcap",
        "--flow-timeout", "0.5", "--flow-activity-timeout", "0.2",
        "--flow-lateness", "0.1", "--max-files-per-batch", "1",
        "--once",
    ])
    assert rc == 0
    served = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert served["batches"] == 5
    # crash-safe state landed under the checkpoint
    assert os.path.isdir(os.path.join(ckpt, "flow_state"))
    outs = sorted(os.listdir(out_dir))
    assert len(outs) == 5
    rows = 0
    for name in outs:
        with open(os.path.join(out_dir, name)) as fh:
            header = fh.readline()
            body = fh.read().strip()
        assert "predictedLabel" in header
        rows += len(body.splitlines()) if body else 0
    assert rows > 0  # live windows were classified to label strings
    # resume on the same checkpoint: nothing new -> zero batches
    rc = main([
        "serve", "--model", model, "--watch", cap, "--out", out_dir,
        "--checkpoint", ckpt, "--from-capture", "pcap",
        "--flow-timeout", "0.5", "--flow-lateness", "0.1", "--once",
    ])
    served = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert served["batches"] == 0


# ---------------------------------------------------------------------------
# drift checks (tier-1 wiring) + process-kill chaos
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_flow_flags_consistent():
    problems = _load_script("check_flow_flags").check()
    assert problems == [], "\n".join(problems)


def test_chaos_matrix_covers_flow_sites():
    checker = _load_script("check_fault_sites")
    assert checker.check_chaos_coverage() == []
    covered = checker.chaos_kill_sites()
    assert {"flow.emit", "flow.evict",
            "flow.state_snapshot"} <= covered


@pytest.fixture(scope="module")
def flow_chaos():
    return _load_script("chaos_crash_matrix")


@pytest.fixture(scope="module")
def flow_chaos_reference(flow_chaos, tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("flow_chaos"))
    return workdir, flow_chaos.run_flow_reference(workdir)


def test_flow_kill_matrix_bitwise(flow_chaos, flow_chaos_reference):
    """Real process kills mid-window at every flow.* fault site:
    restart must converge BITWISE to the reference sink bytes (the
    acceptance criterion: zero duplicated or lost windows)."""
    workdir, reference = flow_chaos_reference
    assert len(reference["sink"]) == 6
    for site in flow_chaos.FLOW_KILL_SITES:
        verdict = flow_chaos.run_flow_kill_scenario(
            workdir, site, reference
        )
        assert verdict["ok"], verdict
        assert verdict["sink_bitwise"], verdict
