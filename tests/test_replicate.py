"""Warm-standby disaster recovery (r23): the ReplicationPlane
ship/barrier protocol, promotion drills under the strict
loss-accounting law (committed == replicated_through_barrier +
counted_tail_loss), torn-ship quarantine, mid-arc restart equivalence
across WAL modes x lifecycle, anti-entropy fsck, and the four-layer
repl-flag drift check.  Chaos (kill INSIDE repl.ship / repl.apply /
repl.barrier) rides scripts/chaos_crash_matrix.py (REPL_KILL_SITES),
driven here in tier-1."""

import glob
import importlib.util
import json
import os

import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.obs.metrics import registry
from sntc_tpu.resilience import arm, storage
from sntc_tpu.resilience.replicate import (
    MANIFEST_NAME,
    ReplicationPlane,
    fsck_standby,
    last_barrier,
    promote_standby,
    replica_dir,
)
from sntc_tpu.resilience.storage import load_sealed_json
from sntc_tpu.serve import CsvDirSink, FileStreamSource, StreamingQuery

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    storage.reset_degradation()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()
    storage.reset_degradation()


def _get(name, **labels):
    return registry().get(name, **labels) or 0


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _write_inputs(watch, n=4, rows=6):
    os.makedirs(watch, exist_ok=True)
    for i in range(n):
        with open(os.path.join(watch, f"in_{i:03d}.csv"), "w") as f:
            f.write("x\n")
            for r in range(rows):
                f.write(f"{i * 1000 + r}\n")


def _sink_bytes(out):
    state = {}
    for p in sorted(glob.glob(os.path.join(out, "batch_*.csv"))):
        with open(p, "rb") as f:
            state[os.path.basename(p)] = f.read()
    return state


def _dirs(tmp_path):
    return tuple(
        str(tmp_path / d) for d in ("in", "out", "ckpt", "standby")
    )


def _engine(watch, out, ckpt, plane, **kw):
    return StreamingQuery(
        _Identity(), FileStreamSource(watch),
        CsvDirSink(out, columns=["x"]), ckpt, max_batch_offsets=1,
        commit_listener=plane.on_commit if plane else None, **kw,
    )


def _replicate(tmp_path, n=2):
    """n committed batches shipped to the standby; returns the dirs."""
    watch, out, ckpt, standby = _dirs(tmp_path)
    _write_inputs(watch, n=n)
    plane = ReplicationPlane(ckpt, standby, sink_dir=out)
    q = _engine(watch, out, ckpt, plane)
    assert q.process_available() == n
    q.stop()
    plane.close()
    return watch, out, ckpt, standby


# ---------------------------------------------------------------------------
# the ship/barrier protocol
# ---------------------------------------------------------------------------


def test_every_commit_ships_and_seals_a_barrier(tmp_path):
    """barrier_every=1: each durable engine commit produces one ship
    pass, one sealed manifest, and one sealed barrier whose batch/row
    accounting is exact; replica sink bytes mirror the primary's."""
    watch, out, ckpt, standby = _dirs(tmp_path)
    _write_inputs(watch, n=3)
    plane = ReplicationPlane(ckpt, standby, sink_dir=out)
    q = _engine(watch, out, ckpt, plane)
    assert q.process_available() == 3
    q.stop()
    st = plane.status()
    assert st["ships"] == 3 and st["barriers_sealed"] == 3
    assert st["ship_errors"] == 0 and st["pending_batches"] == 0
    bar = last_barrier(standby, "default")
    assert bar["batch_id"] == 2 and bar["batches_through"] == 3
    assert bar["rows_through"] == 18 and bar["rows_exact"] is True
    rep = replica_dir(standby, "default")
    man = load_sealed_json(os.path.join(rep, MANIFEST_NAME))
    assert "commits/2.json" in man["files"]
    assert "batch_000002.csv" in man["sink"]
    assert _sink_bytes(os.path.join(rep, "sink")) == _sink_bytes(out)
    assert _get("sntc_repl_barriers_sealed_total", tenant="default") == 3
    assert _get("sntc_repl_lag_batches", tenant="default") == 0


def test_ship_failure_degrades_and_catches_up(tmp_path):
    """An injected repl.ship fault never reaches the engine: the
    commit is counted + journaled as a replication error, and the NEXT
    commit's pass ships the backlog and seals a barrier covering both
    batches with exact rows."""
    watch, out, ckpt, standby = _dirs(tmp_path)
    _write_inputs(watch, n=2)
    plane = ReplicationPlane(ckpt, standby, sink_dir=out)
    q = _engine(watch, out, ckpt, plane)
    arm("repl.ship", kind="io", times=1)
    assert q.process_available() == 2  # the engine never sees the fault
    q.stop()
    st = plane.status()
    assert st["ship_errors"] == 1 and st["barriers_sealed"] == 1
    bar = last_barrier(standby, "default")
    assert bar["batches_through"] == 2 and bar["rows_through"] == 12
    assert bar["rows_exact"] is True
    assert _get("sntc_repl_ships_total", tenant="default",
                outcome="error") == 1
    assert R.recent_events(event="replication_error")


def test_plane_restart_reconciles_gap_rows_from_sink(tmp_path):
    """Commits that land while the plane is down (crash between commit
    and barrier) stay EXACT on the next barrier: batches by sequential
    id, rows recounted from the gap batches' sink files."""
    watch, out, ckpt, standby = _dirs(tmp_path)
    _write_inputs(watch, n=4)
    plane = ReplicationPlane(ckpt, standby, sink_dir=out)
    q1 = _engine(watch, out, ckpt, plane)
    q1.run(max_batches=2, poll_interval=0.01)
    q1.stop()
    plane.close()
    # batch 2 commits with NO plane attached (the plane is "down")
    q2 = _engine(watch, out, ckpt, None)
    q2.run(max_batches=1, poll_interval=0.01)
    q2.stop()
    # plane restart: adopts the replica, reconciles the gap
    plane2 = ReplicationPlane(ckpt, standby, sink_dir=out)
    q3 = _engine(watch, out, ckpt, plane2)
    assert q3.process_available() == 1
    q3.stop()
    bar = last_barrier(standby, "default")
    assert bar["batch_id"] == 3 and bar["batches_through"] == 4
    assert bar["rows_through"] == 24 and bar["rows_exact"] is True


# ---------------------------------------------------------------------------
# promotion drills
# ---------------------------------------------------------------------------


def test_torn_ship_stray_quarantines_and_never_promotes(tmp_path):
    """An immutable replica file the sealed manifest doesn't vouch for
    (the torn-ship shape) goes to .corrupt/ and is ABSENT from the
    promoted tree; the promotion itself still succeeds to the last
    sealed barrier."""
    _watch, out, ckpt, standby = _replicate(tmp_path)
    tree = os.path.join(replica_dir(standby, "default"), "tree")
    stray = os.path.join(tree, "commits", "99.json")
    with open(stray, "w") as f:
        json.dump({"batch_id": 99, "start": 0, "end": 0}, f)
    dest = str(tmp_path / "promoted")
    rep = promote_standby(
        standby, "default", os.path.join(dest, "ckpt"),
        dest_sink=os.path.join(dest, "out"),
        primary_root=ckpt, primary_sink=out,
    )
    assert rep["ok"] is True, rep
    assert any(q["rel"].endswith("99.json") for q in rep["quarantined"])
    assert not os.path.exists(
        os.path.join(dest, "ckpt", "commits", "99.json")
    )
    for q_rec in rep["quarantined"]:
        assert ".corrupt" in q_rec["to"]
        assert os.path.exists(q_rec["to"])
    assert rep["law_exact"] is True and rep["tail_loss_batches"] == 0


def test_diverged_replica_refuses_promotion_and_leaves_no_tree(tmp_path):
    """A replica file whose bytes diverge from the sealed manifest
    refuses promotion outright — ok=False never leaves a promoted
    tree behind — and the divergence is counted + journaled."""
    _watch, out, ckpt, standby = _replicate(tmp_path)
    tree = os.path.join(replica_dir(standby, "default"), "tree")
    with open(os.path.join(tree, "commits", "1.json"), "w") as f:
        json.dump({"batch_id": 1, "start": 0, "end": 999999}, f)
    dest = str(tmp_path / "promoted")
    rep = promote_standby(
        standby, "default", os.path.join(dest, "ckpt"),
        dest_sink=os.path.join(dest, "out"),
        primary_root=ckpt, primary_sink=out,
    )
    assert rep["ok"] is False
    assert "diverges" in rep["reason"]
    assert not glob.glob(
        os.path.join(dest, "**", "*"), recursive=True
    )
    assert _get("sntc_repl_promotions_total", outcome="failed") == 1
    assert _get("sntc_repl_divergence_total", tenant="default") >= 1
    assert R.recent_events(event="replica_diverged")


def test_promotion_refuses_without_sealed_manifest(tmp_path):
    _watch, out, ckpt, standby = _replicate(tmp_path)
    os.unlink(os.path.join(replica_dir(standby, "default"),
                           MANIFEST_NAME))
    dest = str(tmp_path / "promoted")
    rep = promote_standby(standby, "default", os.path.join(dest, "ckpt"))
    assert rep["ok"] is False
    assert "manifest" in rep["reason"]
    assert not glob.glob(os.path.join(dest, "**", "*"), recursive=True)


def test_torn_barrier_tail_is_skipped_not_trusted(tmp_path):
    """A torn (unsealed) final barrier line is ignored: promotion
    anchors on the last SEALED record."""
    _watch, out, ckpt, standby = _replicate(tmp_path)
    log = os.path.join(replica_dir(standby, "default"), "barriers.jsonl")
    with open(log, "a") as f:
        f.write('{"batch_id": 7, "batches_through": 8, "rows_t')
    bar = last_barrier(standby, "default")
    assert bar["batch_id"] == 1 and bar["batches_through"] == 2
    dest = str(tmp_path / "promoted")
    rep = promote_standby(
        standby, "default", os.path.join(dest, "ckpt"),
        dest_sink=os.path.join(dest, "out"), primary_root=ckpt,
    )
    assert rep["ok"] is True and rep["batches_through"] == 2


# ---------------------------------------------------------------------------
# satellite 3: mid-arc promotion, restart-equivalent across
# WAL modes x lifecycle
# ---------------------------------------------------------------------------


def _const_class_pipeline(positive):
    import numpy as np

    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import VectorAssembler
    from sntc_tpu.models.logistic_regression import (
        LogisticRegressionModel,
    )

    head = LogisticRegressionModel(
        coefficient_matrix=np.zeros((2, 1), np.float32),
        intercepts=np.asarray(
            [0.0, 50.0 if positive else -50.0], np.float32
        ),
        is_binomial=True,
    )
    return PipelineModel(stages=[
        VectorAssembler(inputCols=["x"], outputCol="features"),
        head,
    ])


def _lifecycle_arc(watch, out, ckpt, serving_path, *, plane=None,
                   stop_after=None, wal_kwargs=None):
    """Serve the arc with a REAL mid-arc model promotion (after batch
    1): incumbent (class 0) through batch 1, candidate (class 1)
    after.  ``stop_after`` stops the engine once that many batches
    committed (the replicated run's failure point)."""
    from sntc_tpu.lifecycle import LifecycleManager, ModelPromoter
    from sntc_tpu.mlio import load_model, save_model

    candidate_path = serving_path + ".candidate"
    if not os.path.isdir(serving_path):
        save_model(_const_class_pipeline(False), serving_path)
        save_model(_const_class_pipeline(True), candidate_path)
    model = load_model(serving_path)
    promoter = ModelPromoter(
        model, incumbent_raw=model, serving_path=serving_path,
        checkpoint_dir=ckpt, probation_batches=1,
    )
    q = StreamingQuery(
        model, FileStreamSource(watch),
        CsvDirSink(out, columns=["x", "prediction"]), ckpt,
        max_batch_offsets=1,
        lifecycle=LifecycleManager(promoter=promoter),
        commit_listener=plane.on_commit if plane else None,
        **(wal_kwargs or {}),
    )
    done = q.run(max_batches=2, poll_interval=0.01)
    promoter.load_candidate(candidate_path)
    promoter.promote()
    if stop_after is not None:
        done += q.run(max_batches=stop_after - done, poll_interval=0.01)
    else:
        done += q.process_available()
    q.stop()
    return done


def _plain_arc(watch, out, ckpt, *, plane=None, stop_after=None,
               wal_kwargs=None):
    q = _engine(watch, out, ckpt, plane, **(wal_kwargs or {}))
    if stop_after is not None:
        done = q.run(max_batches=stop_after, poll_interval=0.01)
    else:
        done = q.process_available()
    q.stop()
    return done


@pytest.mark.parametrize("wal_mode,lifecycle", [
    ("files", False),
    ("files", True),
    ("append", False),
    ("append", True),
], ids=["files", "files-lifecycle", "append", "append-lifecycle"])
def test_promote_standby_mid_arc_restart_equivalent(
    tmp_path, wal_mode, lifecycle,
):
    """The full drill, table-driven over WAL mode x lifecycle: the
    replicated primary dies mid-arc (after batch 3 of 6), the standby
    promotes to the last sealed barrier — promoted state bitwise equal
    to the unfailed reference's first four sink files, the loss law
    exact — and an engine RESTARTED on the promoted tree finishes the
    arc byte-for-byte identical to the unfailed reference."""
    wal_kwargs = (
        {"wal_mode": "append", "wal_compact_every": 2}
        if wal_mode == "append" else {}
    )
    arc = _lifecycle_arc if lifecycle else _plain_arc
    watch = str(tmp_path / "in")
    _write_inputs(watch, n=6)

    # unfailed reference
    ref = str(tmp_path / "ref")
    ref_args = ([os.path.join(ref, "model")] if lifecycle else [])
    assert arc(
        watch, os.path.join(ref, "out"), os.path.join(ref, "ckpt"),
        *ref_args, wal_kwargs=wal_kwargs,
    ) == 6
    ref_sink = _sink_bytes(os.path.join(ref, "out"))

    # replicated primary, killed mid-arc after batch 3
    pri = str(tmp_path / "pri")
    out, ckpt = os.path.join(pri, "out"), os.path.join(pri, "ckpt")
    standby = str(tmp_path / "standby")
    plane = ReplicationPlane(ckpt, standby, sink_dir=out)
    pri_args = ([os.path.join(pri, "model")] if lifecycle else [])
    assert arc(
        watch, out, ckpt, *pri_args, plane=plane, stop_after=4,
        wal_kwargs=wal_kwargs,
    ) == 4
    plane.close()

    # promote: barrier = batch 3, law exact, zero tail (clean stop)
    dest = str(tmp_path / "promoted")
    dest_out = os.path.join(dest, "out")
    dest_ckpt = os.path.join(dest, "ckpt")
    rep = promote_standby(
        standby, "default", dest_ckpt, dest_sink=dest_out,
        primary_root=ckpt, primary_sink=out,
    )
    assert rep["ok"] is True, rep
    assert rep["batches_through"] == 4 and rep["rows_through"] == 24
    assert rep["law_exact"] is True and rep["tail_loss_batches"] == 0
    assert rep["rows_exact"] is True
    # promoted sink bitwise == the reference's, up to the barrier
    assert _sink_bytes(dest_out) == {
        k: v for k, v in ref_sink.items()
        if k <= "batch_000003.csv"
    }
    if lifecycle:
        assert os.path.exists(
            os.path.join(dest_ckpt, "model_marker.json")
        )

    # restart ON the promoted tree: the arc finishes bitwise with the
    # unfailed reference (the promoted standby IS the new primary)
    if lifecycle:
        from sntc_tpu.mlio import load_model

        model = load_model(os.path.join(pri, "model"))
        q = StreamingQuery(
            model, FileStreamSource(watch),
            CsvDirSink(dest_out, columns=["x", "prediction"]),
            dest_ckpt, max_batch_offsets=1, **wal_kwargs,
        )
    else:
        q = _engine(watch, dest_out, dest_ckpt, None, **wal_kwargs)
    assert q.process_available() == 2
    q.stop()
    assert _sink_bytes(dest_out) == ref_sink


# ---------------------------------------------------------------------------
# anti-entropy: fsck --standby
# ---------------------------------------------------------------------------


def test_fsck_standby_detects_and_repairs_divergence(tmp_path):
    """Bit-rot on the replica is a journaled + counted divergence;
    repair quarantines the bad copy and the next ship pass re-seeds
    it, after which fsck is clean again."""
    _watch, out, ckpt, standby = _replicate(tmp_path)
    tree = os.path.join(replica_dir(standby, "default"), "tree")
    victim = os.path.join(tree, "commits", "0.json")
    with open(victim, "w") as f:
        f.write('{"rot": true}')
    rep = fsck_standby(standby, primary_root=ckpt)
    assert rep["ok"] is False
    div = rep["tenants"]["default"]["divergences"]
    assert any(d["kind"] == "hash" for d in div)
    assert _get("sntc_repl_divergence_total", tenant="default") >= 1
    assert R.recent_events(event="replica_diverged")
    # repair + re-ship heals it
    fsck_standby(standby, primary_root=ckpt, repair=True)
    assert not os.path.exists(victim)
    plane = ReplicationPlane(ckpt, standby, sink_dir=out)
    plane.sync()
    rep2 = fsck_standby(standby, primary_root=ckpt)
    assert rep2["ok"] is True, rep2


# ---------------------------------------------------------------------------
# drift checker
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repl_flags_consistent_across_layers():
    assert _load_script("check_repl_flags").main() == 0


# ---------------------------------------------------------------------------
# chaos: kill inside the replication protocol (child procs)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos():
    return _load_script("chaos_crash_matrix")


@pytest.fixture(scope="module")
def repl_reference(chaos, tmp_path_factory):
    return chaos.run_repl_reference(
        str(tmp_path_factory.mktemp("repl_ref"))
    )


def test_chaos_repl_apply_kill_torn_ship_quarantined(
    chaos, repl_reference, tmp_path
):
    """SIGKILL between the ship and the manifest publish: the torn
    standby still promotes to the last SEALED barrier with the loss
    law exact and every un-manifested stray quarantined (never
    promoted); the restarted primary converges bitwise."""
    v = chaos.run_repl_kill_scenario(
        str(tmp_path), "repl.apply", repl_reference
    )
    assert v["ok"], v
    assert v["torn_promotion"]["quarantined"] >= 1


@pytest.mark.slow
def test_chaos_repl_ship_kill_bitwise(chaos, repl_reference, tmp_path):
    v = chaos.run_repl_kill_scenario(
        str(tmp_path), "repl.ship", repl_reference
    )
    assert v["ok"], v


@pytest.mark.slow
def test_chaos_repl_barrier_kill_bitwise(
    chaos, repl_reference, tmp_path
):
    v = chaos.run_repl_kill_scenario(
        str(tmp_path), "repl.barrier", repl_reference
    )
    assert v["ok"], v
