"""FPGrowth: brute-force itemset enumeration oracle (itertools over the
small universe — exact), rule metrics recomputed by hand, transform
semantics, save/load."""

from itertools import combinations

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import FPGrowth
from sntc_tpu.mlio.save_load import load_model, save_model

BASKETS = [
    ["a", "b", "c"],
    ["a", "b"],
    ["a", "c"],
    ["a", "b", "c", "d"],
    ["b", "c"],
    ["a", "d"],
    ["c", "d"],
    ["a", "b", "c"],
]


def _ragged_frame():
    col = np.empty(len(BASKETS), dtype=object)
    for i, b in enumerate(BASKETS):
        col[i] = b
    return Frame({"items": col})


def _brute_force(min_support):
    universe = sorted({x for b in BASKETS for x in b})
    n = len(BASKETS)
    out = {}
    for k in range(1, len(universe) + 1):
        for combo in combinations(universe, k):
            freq = sum(1 for b in BASKETS if set(combo) <= set(b))
            if freq >= min_support * n:
                out[combo] = freq
    return out


@pytest.mark.parametrize("min_support", [0.25, 0.4, 0.6])
def test_itemsets_match_bruteforce(min_support):
    m = FPGrowth(minSupport=min_support).fit(_ragged_frame())
    fi = m.freqItemsets
    ours = {
        tuple(sorted(items)): int(freq)
        for items, freq in zip(fi["items"], fi["freq"])
    }
    assert ours == _brute_force(min_support)


def test_association_rules_metrics():
    m = FPGrowth(minSupport=0.25, minConfidence=0.6).fit(_ragged_frame())
    rules = m.associationRules
    n = len(BASKETS)
    freq = _brute_force(0.0)
    seen = 0
    for a, c, conf, lift, sup in zip(
        rules["antecedent"], rules["consequent"], rules["confidence"],
        rules["lift"], rules["support"],
    ):
        whole = tuple(sorted(list(a) + list(c)))
        fa = freq[tuple(sorted(a))]
        fc = freq[tuple(c)]
        assert conf == pytest.approx(freq[whole] / fa)
        assert lift == pytest.approx(conf / (fc / n))
        assert sup == pytest.approx(freq[whole] / n)
        assert conf >= 0.6
        seen += 1
    assert seen > 0
    # every qualifying rule is present: check one known rule by hand
    # {b} -> c: freq(bc)=4, freq(b)=5, conf 0.8
    pairs = {
        (tuple(a), c[0])
        for a, c in zip(rules["antecedent"], rules["consequent"])
    }
    assert (("b",), "c") in pairs


def test_transform_predicts_consequents():
    m = FPGrowth(minSupport=0.25, minConfidence=0.6).fit(_ragged_frame())
    out = m.transform(
        Frame({"items": np.array([["b"], ["a", "b", "c", "d"]], dtype=object)})
    )
    pred = out["prediction"]
    assert "c" in pred[0]  # {b} -> c holds at conf 0.8
    assert "b" not in pred[0]  # never predict an item already present
    assert pred[1] == []  # basket already holds everything


def test_duplicate_items_rejected():
    col = np.empty(1, dtype=object)
    col[0] = ["a", "a", "b"]
    with pytest.raises(ValueError, match="duplicate"):
        FPGrowth().fit(Frame({"items": col}))


def test_integer_items_roundtrip(tmp_path):
    """Itemset members must keep their types through save/load — int 1
    and str '1' are different items."""
    col = np.empty(4, dtype=object)
    for i, b in enumerate([[1, 2], [1, 2], [2, 3], [1]]):
        col[i] = b
    f = Frame({"items": col})
    m = FPGrowth(minSupport=0.4, minConfidence=0.5).fit(f)
    save_model(m, str(tmp_path / "fpint"))
    m2 = load_model(str(tmp_path / "fpint"))
    fi = m2.freqItemsets
    assert all(
        isinstance(x, int) for items in fi["items"] for x in items
    )
    out = m2.transform(f)
    assert 2 in out["prediction"][3]  # {1} -> 2 at conf 2/3


def test_rules_cache_tracks_min_confidence():
    m = FPGrowth(minSupport=0.25, minConfidence=0.9).fit(_ragged_frame())
    strict = m.associationRules.num_rows
    m2 = m.copy({"minConfidence": 0.3})
    assert m2.associationRules.num_rows > strict
    # the original is untouched but must also refresh if its own param
    # changes (the cache keys on the confidence it was built at)
    m.setParams(minConfidence=0.3)
    assert m.associationRules.num_rows == m2.associationRules.num_rows


def test_save_load(tmp_path):
    m = FPGrowth(minSupport=0.25, minConfidence=0.6).fit(_ragged_frame())
    save_model(m, str(tmp_path / "fp"))
    m2 = load_model(str(tmp_path / "fp"))
    fi1 = m.freqItemsets
    fi2 = m2.freqItemsets
    assert [list(v) for v in fi1["items"]] == [list(v) for v in fi2["items"]]
    np.testing.assert_array_equal(fi1["freq"], fi2["freq"])
    f = Frame({"items": np.array([["b"]], dtype=object)})
    assert list(m2.transform(f)["prediction"][0]) == list(
        m.transform(f)["prediction"][0]
    )
