"""Minimum end-to-end slice (SURVEY.md §7.1 step 2): config-1 [B:7]
StringIndexer + VectorAssembler + StandardScaler + binary LogisticRegression
on synthetic CICIDS2017-shaped data, evaluated with macro-F1 and AUC,
save/load round-tripped — every layer of the restack exercised once."""

import numpy as np

from sntc_tpu.core.base import Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.data import CICIDS2017_FEATURES, clean_flows, generate_frame
from sntc_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
)
from sntc_tpu.feature import StandardScaler, StringIndexer, VectorAssembler
from sntc_tpu.models import LogisticRegression


def test_config1_binary_pipeline(tmp_path, mesh8):
    raw = generate_frame(6000, seed=42)
    df = clean_flows(raw)
    # binary label: benign vs attack [B:7]
    is_attack = (df["Label"].astype(str) != "BENIGN").astype(object)
    df = df.with_column(
        "binLabel", np.where(is_attack.astype(bool), "attack", "benign").astype(object)
    )
    train, test = df.random_split([0.8, 0.2], seed=0)

    pipe = Pipeline(stages=[
        StringIndexer(inputCol="binLabel", outputCol="label"),
        VectorAssembler(inputCols=CICIDS2017_FEATURES, outputCol="rawFeatures"),
        StandardScaler(mesh=mesh8, inputCol="rawFeatures", outputCol="features",
                       withMean=True),
        LogisticRegression(mesh=mesh8, maxIter=60, regParam=1e-4),
    ])
    model = pipe.fit(train)

    out = model.transform(test)
    f1 = MulticlassClassificationEvaluator(
        metricName="macroF1", mesh=mesh8
    ).evaluate(out)
    auc = BinaryClassificationEvaluator().evaluate(out)
    # benign index 0 (majority), attack 1; mostly-separable synthetic data
    assert f1 > 0.85, f1
    assert auc > 0.95, auc

    # save / load serving parity
    path = str(tmp_path / "pipeline_model")
    model.save(path)
    loaded = PipelineModel.load(path)
    out2 = loaded.transform(test)
    np.testing.assert_array_equal(out["prediction"], out2["prediction"])
