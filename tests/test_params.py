import pytest

from sntc_tpu.core.params import NO_DEFAULT, Param, Params, validators


class Stage(Params):
    maxIter = Param("max iterations (> 0)", default=100, validator=validators.gt(0))
    regParam = Param("regularization (>= 0)", default=0.0, validator=validators.gteq(0))
    solver = Param("solver name", default="lbfgs", validator=validators.one_of("lbfgs", "owlqn"))
    labelCol = Param("label column", default="label")
    required = Param("no default")


class SubStage(Stage):
    maxIter = Param("overridden doc", default=50, validator=validators.gt(0))
    extra = Param("extra param", default=True, validator=validators.is_bool())


def test_defaults_and_generated_accessors():
    s = Stage()
    assert s.getMaxIter() == 100
    assert s.getRegParam() == 0.0
    assert s.getOrDefault("solver") == "lbfgs"
    assert s.getOrDefault(Stage.maxIter) == 100


def test_constructor_kwargs_and_chained_setters():
    s = Stage(maxIter=10).setRegParam(0.5).setSolver("owlqn")
    assert (s.getMaxIter(), s.getRegParam(), s.getSolver()) == (10, 0.5, "owlqn")


def test_validator_rejects():
    with pytest.raises(ValueError):
        Stage(maxIter=0)
    with pytest.raises(ValueError):
        Stage().setSolver("newton")


def test_unknown_param_rejected():
    with pytest.raises(AttributeError):
        Stage(bogus=1)


def test_no_default_raises_until_set():
    s = Stage()
    assert not s.isDefined("required")
    with pytest.raises(KeyError):
        s.getRequired()
    s.setRequired(7)
    assert s.getRequired() == 7


def test_inheritance_and_override():
    s = SubStage()
    assert s.getMaxIter() == 50
    assert s.getExtra() is True
    assert s.getRegParam() == 0.0
    assert set(SubStage.params()) == {
        "maxIter", "regParam", "solver", "labelCol", "required", "extra",
    }


def test_copy_with_extra_is_independent():
    s = Stage(maxIter=10)
    c = s.copy({"maxIter": 20})
    assert s.getMaxIter() == 10 and c.getMaxIter() == 20
    assert c.uid == s.uid  # Spark copy keeps the uid
    c.setRegParam(1.0)
    assert not s.isSet("regParam")


def test_explain_and_param_values():
    s = Stage(maxIter=5)
    text = s.explainParams()
    assert "maxIter" in text and "current: 5" in text
    vals = s.paramValues()
    assert vals["maxIter"] == 5 and vals["solver"] == "lbfgs"
    assert "required" not in vals  # undefined, no default
