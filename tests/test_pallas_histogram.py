"""Pallas histogram kernel vs the segment-sum reference (interpret mode on
CPU; the same kernel compiles for TPU via mosaic)."""

import jax.numpy as jnp
import numpy as np
import pytest

from sntc_tpu.ops.pallas_histogram import level_histogram_pallas


def _reference(binned, node_idx, stats, n_nodes, n_bins):
    import jax

    f = binned.shape[1]
    out = np.zeros((f, n_nodes * n_bins, stats.shape[1]), np.float32)
    for j in range(f):
        for i in range(binned.shape[0]):
            if node_idx[i] >= 0:
                out[j, node_idx[i] * n_bins + binned[i, j]] += stats[i]
    return out


@pytest.mark.parametrize("n,f,s,n_nodes,n_bins", [
    (300, 5, 3, 4, 8),
    (1000, 7, 15, 8, 32),
    (64, 2, 1, 1, 32),
])
def test_matches_reference(n, f, s, n_nodes, n_bins):
    rng = np.random.default_rng(0)
    binned = rng.integers(0, n_bins, size=(n, f)).astype(np.int32)
    node_idx = rng.integers(-1, n_nodes, size=n).astype(np.int32)
    stats = rng.normal(size=(n, s)).astype(np.float32)
    stats[node_idx < 0] = 0.0  # pre-masked, as the grower guarantees

    got = np.asarray(
        level_histogram_pallas(
            jnp.asarray(binned.T.copy()),
            jnp.asarray(node_idx),
            jnp.asarray(stats),
            n_nodes=n_nodes,
            n_bins=n_bins,
            tile_n=256,
            interpret=True,
        )
    )
    want = _reference(binned, node_idx, stats, n_nodes, n_bins)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rf_identical_forest_under_pallas_hist(mesh8, monkeypatch):
    """The grower must produce the SAME trees with either histogram impl."""
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.models import RandomForestClassifier

    rng = np.random.default_rng(3)
    X = rng.normal(size=(600, 5)).astype(np.float32)
    y = (X[:, 0] + X[:, 2] > 0).astype(np.float64)
    f = Frame({"features": X, "label": y})
    kw = dict(mesh=mesh8, numTrees=3, maxDepth=3, seed=0)

    monkeypatch.setenv("SNTC_TREE_HIST", "segment")
    m_seg = RandomForestClassifier(**kw).fit(f)
    monkeypatch.setenv("SNTC_TREE_HIST", "pallas")
    m_pal = RandomForestClassifier(**kw).fit(f)

    np.testing.assert_array_equal(m_pal.forest.feature, m_seg.forest.feature)
    np.testing.assert_allclose(
        m_pal.forest.leaf_stats, m_seg.forest.leaf_stats, rtol=1e-5, atol=1e-5
    )


def test_mixed_pallas_segment_levels_with_sibling(mesh8, monkeypatch):
    """Depth deep enough that the widest levels overflow the pallas VMEM
    gate and fall back to segment_sum while shallow levels keep the MXU
    kernel — the exact mixed regime a real chip hits — with sibling
    subtraction auto-gated per level (engages only where the NEXT level
    is pallas).  The grown forest must equal the all-segment one.

    Exact equality is safe, not flaky: Poisson bagging weights are
    integer-valued, so every histogram cell is an exact small-int f32
    sum on BOTH impls (the sibling subtraction parent − left is exact
    on integers), identical cells feed the identical split-eval code,
    and the gain argmaxes cannot diverge."""
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.models import RandomForestClassifier
    from sntc_tpu.ops.pallas_histogram import hist_fits_pallas

    # depth 9 → level 8 has 256 nodes; 256·32 bins overflows the kernel
    # budget, so levels 0–7 are pallas and level 8 is segment
    assert hist_fits_pallas(128, 32) and not hist_fits_pallas(256, 32)

    rng = np.random.default_rng(21)
    n = 800
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = ((X[:, 0] > 0) * 2 + (X[:, 3] > 0.2)).astype(np.float64)
    f = Frame({"features": X, "label": y})
    kw = dict(mesh=mesh8, numTrees=2, maxDepth=9, seed=0,
              featureSubsetStrategy="all")

    monkeypatch.setenv("SNTC_TREE_HIST", "segment")
    m_seg = RandomForestClassifier(**kw).fit(f)
    monkeypatch.setenv("SNTC_TREE_HIST", "pallas")
    m_mix = RandomForestClassifier(**kw).fit(f)

    np.testing.assert_array_equal(
        m_mix.forest.feature, m_seg.forest.feature
    )
    np.testing.assert_allclose(
        m_mix.forest.leaf_stats, m_seg.forest.leaf_stats,
        rtol=1e-5, atol=1e-5,
    )


def test_row_padding_contributes_zero():
    # n not a multiple of tile_n exercises the padding path
    n, f, s, n_nodes, n_bins = 130, 3, 2, 2, 4
    rng = np.random.default_rng(1)
    binned = rng.integers(0, n_bins, size=(n, f)).astype(np.int32)
    node_idx = rng.integers(0, n_nodes, size=n).astype(np.int32)
    stats = np.ones((n, s), np.float32)
    got = np.asarray(
        level_histogram_pallas(
            jnp.asarray(binned.T.copy()), jnp.asarray(node_idx),
            jnp.asarray(stats), n_nodes=n_nodes, n_bins=n_bins,
            tile_n=128, interpret=True,
        )
    )
    assert got.sum() == pytest.approx(n * s * f)
