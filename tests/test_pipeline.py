import numpy as np

from sntc_tpu.core.base import Estimator, Model, Pipeline, PipelineModel, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param


class AddConst(Transformer):
    value = Param("constant to add", default=1.0)
    inputCol = Param("input column", default="x")

    def transform(self, frame):
        col = self.getInputCol()
        return frame.with_column(col, frame[col] + self.getValue())


class MeanModel(Model):
    def __init__(self, mean, **kw):
        super().__init__(**kw)
        self.mean = mean

    def transform(self, frame):
        return frame.with_column("centered", frame["x"] - self.mean)


class MeanCenter(Estimator):
    def _fit(self, frame):
        return MeanModel(float(frame["x"].mean()))


def test_pipeline_fit_transform_order():
    f = Frame({"x": np.array([0.0, 2.0, 4.0])})
    pipe = Pipeline(stages=[AddConst(value=1.0), MeanCenter()])
    model = pipe.fit(f)
    assert isinstance(model, PipelineModel)
    # estimator saw the transformed column (mean of x+1 = 3)
    assert model.getStages()[1].mean == 3.0
    out = model.transform(f)
    assert np.allclose(out["centered"], [-2.0, 0.0, 2.0])


def test_fit_with_param_override_does_not_mutate():
    f = Frame({"x": np.array([1.0])})

    class Rec(Estimator):
        value = Param("v", default=0)

        def _fit(self, frame):
            m = MeanModel(self.getValue())
            return m

    e = Rec()
    m = e.fit(f, {"value": 9})
    assert m.mean == 9
    assert e.getValue() == 0
