"""ALS: normal-equation exactness (the fitted factors must satisfy the
ALS-WR stationary conditions they were solved for), low-rank recovery
with held-out RMSE, implicit preference ordering, cold-start handling,
recommend-top-k consistency, save/load."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import ALS
from sntc_tpu.mlio.save_load import load_model, save_model


def _low_rank_ratings(n_u=60, n_i=40, rank=4, frac=0.5, seed=0, noise=0.05):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_u, rank)) / np.sqrt(rank)
    V = rng.normal(size=(n_i, rank)) / np.sqrt(rank)
    R = U @ V.T + 2.0  # keep ratings positive-ish
    mask = rng.random((n_u, n_i)) < frac
    uu, ii = np.nonzero(mask)
    r = R[uu, ii] + noise * rng.normal(size=len(uu))
    # non-contiguous original ids to prove the lut round-trip
    return 10 * uu + 3, 7 * ii + 1, r.astype(np.float32), R, mask


@pytest.fixture(scope="module")
def fitted():
    users, items, r, R, mask = _low_rank_ratings()
    f = Frame({"user": users, "item": items, "rating": r})
    m = ALS(rank=6, maxIter=15, regParam=0.01, seed=2).fit(f)
    return m, users, items, r, R, mask


def test_heldout_rmse(fitted):
    m, users, items, r, R, mask = fitted
    # held-out cells: the unobserved entries of the true low-rank matrix
    hu, hi = np.nonzero(~mask)
    f_test = Frame({"user": 10 * hu + 3, "item": 7 * hi + 1})
    pred = m.transform(f_test)["prediction"]
    rmse = float(np.sqrt(np.mean((pred - R[hu, hi]) ** 2)))
    assert rmse < 0.15  # true noise level is 0.05; spread of R is ~1
    # and the training cells fit tightly
    pred_tr = m.transform(
        Frame({"user": users, "item": items})
    )["prediction"]
    assert float(np.sqrt(np.mean((pred_tr - r) ** 2))) < 0.1


def test_normal_equation_stationarity(fitted):
    """The ITEM half-step runs last, so each item factor must solve
    (Σ u uᵀ + λ n_i I) x = Σ r u exactly — the ALS-WR system [U]."""
    m, users, items, r, _, _ = fitted
    uf = {int(i): f for i, f in zip(m.userIds, np.asarray(m.userFactors["features"], np.float64))}
    vf = {int(i): f for i, f in zip(m.itemIds, np.asarray(m.itemFactors["features"], np.float64))}
    lam = 0.01
    for iid in list(vf)[:5]:
        rows = np.nonzero(items == iid)[0]
        U = np.stack([uf[int(users[j])] for j in rows])
        rr = r[rows].astype(np.float64)
        A = U.T @ U + lam * len(rows) * np.eye(m.rank)
        b = U.T @ rr
        np.testing.assert_allclose(A @ vf[iid], b, atol=5e-3)


def test_implicit_preference_ordering(mesh8):
    # two user groups each consuming a disjoint item set: implicit ALS
    # must score in-group items above out-group items
    rng = np.random.default_rng(4)
    users, items, counts = [], [], []
    for u in range(40):
        group = u % 2
        for _ in range(15):
            it = rng.integers(0, 20) + 20 * group
            users.append(u)
            items.append(it)
            counts.append(float(rng.integers(1, 5)))
    f = Frame({
        "user": np.array(users), "item": np.array(items),
        "rating": np.array(counts, np.float32),
    })
    m = ALS(
        rank=4, maxIter=10, regParam=0.05, implicitPrefs=True, alpha=10.0,
        seed=0,
    ).fit(f)
    rec = m.recommendForAllUsers(5)
    ids = np.asarray(rec["id"])
    recs = np.asarray(rec["recommendations"])
    for row, uid in enumerate(ids):
        group = int(uid) % 2
        in_group = ((recs[row] >= 20 * group) & (recs[row] < 20 * (group + 1)))
        assert in_group.mean() >= 0.8, (uid, recs[row])


def test_cold_start(fitted):
    m = fitted[0]
    f = Frame({"user": np.array([3, 99999]), "item": np.array([1, 1])})
    out_nan = m.transform(f)
    assert np.isnan(out_nan["prediction"][1])
    m2 = m.copy({"coldStartStrategy": "drop"})
    out_drop = m2.transform(f)
    assert out_drop.num_rows == 1


def test_recommend_consistency(fitted):
    m = fitted[0]
    rec = m.recommendForAllUsers(3)
    uid = int(np.asarray(rec["id"])[0])
    top_items = np.asarray(rec["recommendations"])[0]
    top_scores = np.asarray(rec["ratings"])[0]
    # scores descending and equal to transform() on the same pairs
    assert (np.diff(top_scores) <= 1e-6).all()
    f = Frame({
        "user": np.full(3, uid), "item": top_items.astype(np.int64),
    })
    pred = m.transform(f)["prediction"]
    np.testing.assert_allclose(pred, top_scores, atol=1e-5)
    # item-side API shape
    rec_i = m.recommendForAllItems(2)
    assert rec_i["recommendations"].shape == (len(m.itemIds), 2)


def test_validation_and_save_load(fitted, tmp_path):
    m = fitted[0]
    with pytest.raises(ValueError, match="non-negative"):
        ALS(implicitPrefs=True).fit(Frame({
            "user": np.array([0]), "item": np.array([0]),
            "rating": np.array([-1.0], np.float32),
        }))
    save_model(m, str(tmp_path / "als"))
    m2 = load_model(str(tmp_path / "als"))
    np.testing.assert_allclose(
        np.asarray(m2.userFactors["features"]),
        np.asarray(m.userFactors["features"]),
    )
    f = Frame({"user": np.array([3, 13]), "item": np.array([1, 8])})
    np.testing.assert_allclose(
        m2.transform(f)["prediction"], m.transform(f)["prediction"]
    )


def test_nonnegative_factors_and_kkt():
    """nonnegative=True must (a) produce factor matrices that are
    elementwise >= 0 and (b) land each item factor at the KKT point of
    its constrained ALS-WR system: free coordinates (x_j > 0) have zero
    gradient, bound coordinates (x_j = 0) have non-negative gradient —
    the defining optimality conditions of Spark's NNLS solves."""
    rng = np.random.default_rng(4)
    n_u, n_i, rank = 50, 35, 3
    U = np.abs(rng.normal(size=(n_u, rank))) / np.sqrt(rank)
    V = np.abs(rng.normal(size=(n_i, rank))) / np.sqrt(rank)
    R = U @ V.T
    mask = rng.random((n_u, n_i)) < 0.6
    uu, ii = np.nonzero(mask)
    r = (R[uu, ii] + 0.02 * rng.normal(size=len(uu))).astype(np.float32)
    f = Frame({"user": uu, "item": ii, "rating": r})
    lam = 0.02
    m = ALS(rank=4, maxIter=10, regParam=lam, nonnegative=True, seed=3).fit(f)

    uf = np.asarray(m.userFactors["features"], np.float64)
    vf = np.asarray(m.itemFactors["features"], np.float64)
    assert (uf >= 0).all() and (vf >= 0).all()

    # KKT of the final (item) half-step
    ulut = {int(i): j for j, i in enumerate(m.userIds)}
    for col, iid in enumerate(np.asarray(m.itemIds)[:8]):
        rows = np.nonzero(ii == iid)[0]
        Um = np.stack([uf[ulut[int(uu[j])]] for j in rows])
        A = Um.T @ Um + lam * len(rows) * np.eye(m.rank)
        b = Um.T @ r[rows].astype(np.float64)
        x = vf[col]
        g = A @ x - b
        assert (g[x > 1e-8] < 5e-3).all() and (g[x > 1e-8] > -5e-3).all()
        assert (g[x <= 1e-8] > -5e-3).all()

    # the constrained fit still reconstructs the planted nonneg matrix
    pred = m.transform(Frame({"user": uu, "item": ii}))["prediction"]
    assert float(np.sqrt(np.mean((pred - r) ** 2))) < 0.1
