"""LinearRegression oracle tests vs sklearn (same objective family)."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.evaluation import RegressionEvaluator
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import LinearRegression


def _data(seed=0, n=3000, d=8, noise=0.3):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32) * rng.uniform(0.5, 3, d)
    y = (X @ w + 1.7 + noise * rng.normal(size=n)).astype(np.float32)
    return Frame({"features": X, "label": y}), X, y, w


def test_ols_matches_sklearn_exactly(mesh8):
    from sklearn.linear_model import LinearRegression as SkOLS

    f, X, y, w = _data()
    m = LinearRegression(mesh=mesh8).fit(f)  # auto -> normal solver
    sk = SkOLS().fit(X, y)
    np.testing.assert_allclose(m.coefficients, sk.coef_, atol=1e-4)
    assert m.intercept == pytest.approx(sk.intercept_, abs=1e-3)
    pred = m.transform(f)
    r2 = RegressionEvaluator(metricName="r2").evaluate(pred)
    assert r2 > 0.98


def test_ridge_matches_sklearn(mesh8):
    from sklearn.linear_model import Ridge

    f, X, y, w = _data(seed=1)
    lam = 0.1
    m = LinearRegression(
        mesh=mesh8, regParam=lam, standardization=False
    ).fit(f)
    # Spark objective 1/(2n)||r||^2 + lam/2||w||^2  == sklearn Ridge with
    # alpha = n * lam on the same unscaled loss
    sk = Ridge(alpha=len(y) * lam).fit(X, y)
    np.testing.assert_allclose(m.coefficients, sk.coef_, rtol=1e-4, atol=1e-5)
    assert m.intercept == pytest.approx(sk.intercept_, abs=1e-3)


def test_elastic_net_matches_sklearn(mesh8):
    from sklearn.linear_model import ElasticNet

    f, X, y, w = _data(seed=2)
    lam, alpha = 0.05, 0.5
    m = LinearRegression(
        mesh=mesh8, regParam=lam, elasticNetParam=alpha,
        standardization=False, maxIter=300, tol=1e-9,
    ).fit(f)
    sk = ElasticNet(alpha=lam, l1_ratio=alpha, max_iter=50000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(m.coefficients, sk.coef_, atol=2e-3)
    assert m.intercept == pytest.approx(sk.intercept_, abs=5e-3)
    # lasso component produces genuine sparsity agreement
    assert np.array_equal(
        np.abs(m.coefficients) < 1e-6, np.abs(sk.coef_) < 1e-6
    )


def test_solver_rules_and_no_intercept(mesh8):
    from sklearn.linear_model import LinearRegression as SkOLS

    f, X, y, w = _data(seed=3)
    with pytest.raises(ValueError, match="no L1"):
        LinearRegression(
            mesh=mesh8, solver="normal", regParam=0.1, elasticNetParam=0.5
        ).fit(f)
    m = LinearRegression(mesh=mesh8, fitIntercept=False).fit(f)
    sk = SkOLS(fit_intercept=False).fit(X, y)
    np.testing.assert_allclose(m.coefficients, sk.coef_, atol=1e-3)
    assert m.intercept == 0.0
    # l-bfgs solver agrees with the normal solver
    m2 = LinearRegression(mesh=mesh8, solver="l-bfgs", maxIter=300).fit(f)
    mn = LinearRegression(mesh=mesh8, solver="normal").fit(f)
    np.testing.assert_allclose(m2.coefficients, mn.coefficients, atol=2e-3)


def test_weights_and_save_load(mesh8, tmp_path):
    from sklearn.linear_model import LinearRegression as SkOLS

    f, X, y, w_true = _data(seed=4)
    rng = np.random.default_rng(9)
    w = rng.uniform(0.2, 2.0, size=len(y)).astype(np.float32)
    fw = Frame({"features": X, "label": y, "w": w})
    m = LinearRegression(mesh=mesh8, weightCol="w").fit(fw)
    sk = SkOLS().fit(X, y, sample_weight=w)
    np.testing.assert_allclose(m.coefficients, sk.coef_, atol=1e-3)
    save_model(m, str(tmp_path / "lin"))
    m2 = load_model(str(tmp_path / "lin"))
    np.testing.assert_allclose(m2.coefficients, m.coefficients)
    np.testing.assert_allclose(
        np.asarray(m2.transform(f)["prediction"]),
        np.asarray(m.transform(f)["prediction"]),
    )


def test_singular_gram_falls_back_to_lstsq(mesh8):
    """Duplicated + constant features: the normal solver must not crash
    (minimum-norm lstsq fallback) and predictions stay accurate."""
    rng = np.random.default_rng(6)
    n = 2000
    a = rng.normal(size=n).astype(np.float32)
    X = np.stack([a, a, np.full(n, 7.0, np.float32)], axis=1)  # dup + const
    y = 2.0 * a + 1.0
    f = Frame({"features": X, "label": y.astype(np.float32)})
    m = LinearRegression(mesh=mesh8).fit(f)  # auto -> normal, singular
    pred = np.asarray(m.transform(f)["prediction"])
    assert np.sqrt(np.mean((pred - y) ** 2)) < 1e-2
    assert isinstance(m.summary.objectiveHistory, list)
