"""Training-summary parity (SURVEY.md §5.5) + evaluator Params system +
tuning-spec persistence (Spark ``CrossValidatorModel.save`` round-trip)."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
)
from sntc_tpu.models import LogisticRegression, MultilayerPerceptronClassifier
from sntc_tpu.models.summary import (
    BinaryClassificationTrainingSummary,
    ClassificationTrainingSummary,
)
from sntc_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)


@pytest.fixture(scope="module")
def binary_frame():
    rng = np.random.default_rng(0)
    n = 1200
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n) > 0).astype(
        np.float64
    )
    return Frame({"features": X, "label": y})


@pytest.fixture(scope="module")
def multi_frame():
    rng = np.random.default_rng(1)
    n = 1500
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = np.clip(np.floor(X[:, 0] * 1.5 + 1.5), 0, 2).astype(np.float64)
    return Frame({"features": X, "label": y})


def test_binary_lr_training_summary(mesh8, binary_frame):
    m = LogisticRegression(mesh=mesh8, maxIter=30).fit(binary_frame)
    s = m.summary
    assert isinstance(s, BinaryClassificationTrainingSummary)
    assert s.totalIterations > 0 and len(s.objectiveHistory) > 1
    # predictions frame: lazy, one per summary, carries the model's cols
    preds = s.predictions
    assert preds.num_rows == binary_frame.num_rows
    assert "prediction" in preds.columns and "probability" in preds.columns
    # per-class metrics agree with the evaluator on the same frame
    ev = MulticlassClassificationEvaluator(
        metricName="accuracy", mesh=mesh8
    )
    assert s.accuracy == pytest.approx(ev.evaluate(preds))
    assert s.precisionByLabel.shape == (2,)
    assert s.recallByLabel.shape == (2,)
    assert np.all(s.fMeasureByLabel() <= 1.0)
    assert s.weightedRecall == pytest.approx(s.accuracy)
    assert s.labels.tolist() == [0.0, 1.0]
    # threshold curves
    auc_ev = BinaryClassificationEvaluator().evaluate(preds)
    assert s.areaUnderROC == pytest.approx(auc_ev)
    roc = s.roc
    assert roc["FPR"][0] == 0.0 and roc["TPR"][-1] == 1.0
    assert np.all(np.diff(roc["FPR"]) >= -1e-12)
    pr = s.pr
    # roc carries both (0,0) and (1,1) anchors; pr prepends one point
    assert pr.num_rows == roc.num_rows - 1
    f_thr = s.fMeasureByThreshold()
    assert f_thr.num_rows > 1
    assert float(np.max(f_thr["metric"])) <= 1.0


def test_multinomial_lr_and_mlp_summary(mesh8, multi_frame):
    m = LogisticRegression(
        mesh=mesh8, maxIter=30, family="multinomial"
    ).fit(multi_frame)
    s = m.summary
    assert isinstance(s, ClassificationTrainingSummary)
    assert not isinstance(s, BinaryClassificationTrainingSummary)
    assert s.precisionByLabel.shape == (3,)
    assert 0.0 < s.accuracy <= 1.0

    mlp = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[5, 8, 3], maxIter=25, seed=0
    ).fit(multi_frame)
    s2 = mlp.summary
    assert isinstance(s2, ClassificationTrainingSummary)
    assert s2.totalIterations > 0
    assert s2.recallByLabel.shape == (3,)


def test_linear_svc_training_summary(mesh8, binary_frame):
    from sntc_tpu.models import LinearSVC

    m = LinearSVC(mesh=mesh8, maxIter=25).fit(binary_frame)
    s = m.summary
    assert isinstance(s, BinaryClassificationTrainingSummary)
    assert s.totalIterations > 0
    assert s.precisionByLabel.shape == (2,)
    assert 0.5 < s.areaUnderROC <= 1.0


def test_tree_classifier_summaries(mesh8, binary_frame, multi_frame):
    from sntc_tpu.models import GBTClassifier, RandomForestClassifier

    rf = RandomForestClassifier(
        mesh=mesh8, numTrees=4, maxDepth=4, seed=0
    ).fit(multi_frame)
    s = rf.summary
    assert isinstance(s, ClassificationTrainingSummary)
    assert s.objectiveHistory == [] and s.totalIterations == 0
    assert s.precisionByLabel.shape == (3,)
    assert 0.0 < s.accuracy <= 1.0

    gbt = GBTClassifier(
        mesh=mesh8, maxIter=5, maxDepth=3, seed=0
    ).fit(binary_frame)
    s2 = gbt.summary
    assert isinstance(s2, BinaryClassificationTrainingSummary)
    assert s2.totalIterations == 5
    assert 0.5 < s2.areaUnderROC <= 1.0


def test_model_evaluate(mesh8, binary_frame, multi_frame):
    m = LogisticRegression(mesh=mesh8, maxIter=20).fit(binary_frame)
    s = m.evaluate(binary_frame)
    assert not hasattr(s, "objectiveHistory")
    assert s.areaUnderROC == pytest.approx(m.summary.areaUnderROC)
    mlp = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[5, 6, 3], maxIter=15, seed=0
    ).fit(multi_frame)
    assert 0.0 < mlp.evaluate(multi_frame).accuracy <= 1.0


def test_evaluator_params_system():
    ev = MulticlassClassificationEvaluator(metricName="logLoss", beta=2.0)
    assert ev.getMetricName() == "logLoss"
    assert ev.getBeta() == 2.0
    assert "metricName" in ev.paramValues()
    assert "metricName" in ev.explainParams()
    with pytest.raises(ValueError):
        MulticlassClassificationEvaluator(metricName="nope")
    with pytest.raises(ValueError, match="metricLabel"):
        MulticlassClassificationEvaluator(metricLabel=-1.0)
    ev2 = ev.copy({"metricName": "accuracy"})
    assert ev2.getMetricName() == "accuracy"
    assert ev.getMetricName() == "logLoss"
    with pytest.raises(ValueError):
        BinaryClassificationEvaluator(metricName="nope")


def test_evaluator_save_load(tmp_path):
    from sntc_tpu.mlio import load_model, save_model

    ev = MulticlassClassificationEvaluator(
        metricName="fMeasureByLabel", metricLabel=2.0, beta=0.5,
        weightCol="w",
    )
    loaded = load_model(save_model(ev, str(tmp_path / "ev")))
    assert isinstance(loaded, MulticlassClassificationEvaluator)
    assert loaded.paramValues() == ev.paramValues()


def test_cross_validator_model_persists_spec(mesh8, binary_frame, tmp_path):
    from sntc_tpu.mlio import load_model, save_model

    grid = (
        ParamGridBuilder()
        .addGrid("regParam", [0.0, 0.1])
        .build()
    )
    cv = CrossValidator(
        estimator=LogisticRegression(mesh=mesh8, maxIter=15),
        estimatorParamMaps=grid,
        evaluator=BinaryClassificationEvaluator(),
        numFolds=2,
        seed=0,
    )
    cvm = cv.fit(binary_frame)
    loaded = load_model(save_model(cvm, str(tmp_path / "cvm")))
    assert isinstance(loaded, CrossValidatorModel)
    assert loaded.avgMetrics == pytest.approx(cvm.avgMetrics)
    assert loaded.bestIndex == cvm.bestIndex
    assert loaded.estimatorParamMaps == grid
    assert isinstance(loaded.estimator, LogisticRegression)
    assert isinstance(loaded.evaluator, BinaryClassificationEvaluator)
    # the restored spec is runnable: re-scoring the best model's transform
    # with the restored evaluator reproduces the recorded metric's scale
    out = loaded.transform(binary_frame)
    assert 0.5 < loaded.evaluator.evaluate(out) <= 1.0
    # and the loaded ESTIMATOR still fits
    refit = loaded.estimator.copy(
        loaded.estimatorParamMaps[loaded.bestIndex]
    ).fit(binary_frame)
    a = refit.transform(binary_frame)["prediction"]
    b = out["prediction"]
    assert np.mean(a == b) > 0.99


def test_cross_validator_estimator_save_load(mesh8, tmp_path):
    from sntc_tpu.mlio import load_model, save_model

    grid = ParamGridBuilder().addGrid("regParam", [0.0, 0.5]).build()
    cv = CrossValidator(
        estimator=LogisticRegression(mesh=mesh8, maxIter=10),
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2,
    )
    loaded = load_model(save_model(cv, str(tmp_path / "cv")))
    assert isinstance(loaded, CrossValidator)
    assert loaded.getNumFolds() == 2
    assert loaded.estimatorParamMaps == grid
    assert loaded.evaluator.getMetricName() == "accuracy"


def test_tvs_model_persists_spec(mesh8, binary_frame, tmp_path):
    from sntc_tpu.mlio import load_model, save_model

    grid = ParamGridBuilder().addGrid("maxIter", [5, 15]).build()
    tvs = TrainValidationSplit(
        estimator=LogisticRegression(mesh=mesh8),
        estimatorParamMaps=grid,
        evaluator=BinaryClassificationEvaluator(),
        trainRatio=0.7,
        seed=0,
    )
    m = tvs.fit(binary_frame)
    loaded = load_model(save_model(m, str(tmp_path / "tvsm")))
    assert isinstance(loaded, TrainValidationSplitModel)
    assert loaded.validationMetrics == pytest.approx(m.validationMetrics)
    assert loaded.estimatorParamMaps == grid
    assert isinstance(loaded.evaluator, BinaryClassificationEvaluator)
