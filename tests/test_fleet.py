"""Elastic serve fleet (r19): consistent-hash placement stability,
lease expiry → dead-worker recovery on an injectable wall clock,
first-class migration (bitwise vs an unmigrated reference), torn-ship
revert at the ``fleet.migrate`` fault site, coordinator
degrade-never-kill on a poisoned tenant spec, the fleet doctor, the
r19 request-drain race regression, and the fleet-flags drift check.
Everything in-process and steppable — the coordinator and workers are
plain objects with injectable clocks; the REAL multi-process kills
live in scripts/chaos_crash_matrix.py (FLEET_KILL_SITES)."""

import glob
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.obs import reset_registry
from sntc_tpu.serve import MemorySink, MemorySource, ServeDaemon, TenantSpec
from sntc_tpu.serve.fleet import (
    ConsistentHashRing,
    FleetCoordinator,
    FleetWorker,
    fsck_fleet,
    restore_retired,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()
    # fleet runs emit many distinct (event, tenant) series into the
    # process-global metrics registry; left behind, they exhaust the
    # 64-label-set cardinality cap for every later test file
    reset_registry()


class _Identity(Transformer):
    def transform(self, frame):
        return frame


class FakeWall:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _frames(n_batches, rows=4, base=0):
    return [
        Frame({"x": np.arange(rows, dtype=np.float64) + 100 * b + base})
        for b in range(n_batches)
    ]


def _specs(n_tenants, batches=3):
    specs, sinks = {}, {}
    for i in range(n_tenants):
        tid = f"t{i}"
        sinks[tid] = MemorySink()
        specs[tid] = TenantSpec(
            tenant_id=tid,
            model=_Identity(),
            source=MemorySource(_frames(batches, base=1000 * i)),
            sink=sinks[tid],
        )
    return specs, sinks


def _fleet(tmp_path, worker_ids, specs, wall, **kw):
    root = str(tmp_path / "fleet")
    coord = FleetCoordinator(root, worker_ids, specs, wall=wall, **kw)
    workers = {
        w: FleetWorker(w, root, specs, wall=wall) for w in worker_ids
    }
    return root, coord, workers


def _step(coord, workers, wall, rounds, dt=0.5):
    for _ in range(rounds):
        wall.t += dt
        for w in workers.values():
            w.tick()
        coord.tick()


def _sink_rows(sink):
    """(batch_id, value-tuple) pairs — the bitwise evidence."""
    return sorted(
        (bid, tuple(np.asarray(f["x"]).tolist()))
        for bid, f in sink.batches
    )


def _tenant_homes(root, tid):
    """Workers whose on-disk tree holds the tenant (single-homed
    invariant: exactly one, shipping partials count as homes)."""
    return sorted(
        p.split(os.sep)[-3] for p in glob.glob(
            os.path.join(root, "worker", "*", "tenant", tid)
        ) + glob.glob(
            os.path.join(root, "worker", "*", "tenant", tid + ".shipping")
        )
    )


# ---------------------------------------------------------------------------
# placement: the consistent-hash ring
# ---------------------------------------------------------------------------


def test_ring_assignment_deterministic_and_bounded_load():
    costs = {f"t{i}": 1.0 + (i % 3) for i in range(60)}
    ring = ConsistentHashRing(["w0", "w1", "w2", "w3"])
    a = ring.assign(costs)
    assert a == ring.assign(costs)  # fully deterministic
    assert set(a) == set(costs)
    cap = ring.capacity(costs)
    load = {}
    for tid, w in a.items():
        load[w] = load.get(w, 0.0) + costs[tid]
    assert all(l <= cap + 1e-9 for l in load.values())
    # every worker carries SOMETHING at 60 tenants / 4 workers
    assert set(load) == {"w0", "w1", "w2", "w3"}


def test_ring_join_leave_moves_a_bounded_share():
    costs = {f"t{i}": 1.0 for i in range(100)}
    before = ConsistentHashRing(["w0", "w1", "w2", "w3"]).assign(costs)
    after_join = ConsistentHashRing(
        ["w0", "w1", "w2", "w3", "w4"]
    ).assign(costs)
    moved = sum(1 for t in costs if before[t] != after_join[t])
    # the consistent-hashing property: a join claims roughly its own
    # share (1/5 here), never a full reshuffle
    assert 0 < moved <= 50
    after_leave = ConsistentHashRing(["w0", "w1", "w2"]).assign(costs)
    relocated = sum(
        1 for t in costs
        if before[t] != "w3" and before[t] != after_leave[t]
    )
    # w3's tenants MUST move; the survivors' mostly stay put
    assert relocated <= 40


def test_ring_pinned_tenant_stays_put():
    costs = {f"t{i}": 1.0 for i in range(20)}
    ring = ConsistentHashRing(["w0", "w1", "w2"])
    a = ring.assign(costs, pinned={"t7": "w2", "t11": "w0"})
    assert a["t7"] == "w2"
    assert a["t11"] == "w0"


# ---------------------------------------------------------------------------
# the fleet loop: bootstrap, lease expiry, recovery, rejoin
# ---------------------------------------------------------------------------


def test_fleet_bootstrap_serves_every_tenant(tmp_path):
    wall = FakeWall()
    specs, sinks = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall, lease_ttl_s=5.0
    )
    assert set(coord.assignments) == set(specs)
    _step(coord, workers, wall, 30)
    st = coord.status()
    assert all(w["state"] == "live" for w in st["workers"].values())
    for tid, sink in sinks.items():
        assert len(sink.batches) == 3, tid
        assert _tenant_homes(root, tid) == [
            coord.assignments[tid]["worker"]
        ]
    rep = fsck_fleet(root)
    assert rep["ok"], rep["errors"]
    for w in workers.values():
        w.drain()
        w.close()
    coord.close()


def test_lease_expiry_migrates_tenants_to_survivor(tmp_path):
    wall = FakeWall()
    specs, sinks = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall, lease_ttl_s=5.0
    )
    _step(coord, workers, wall, 4)  # everyone live, some rows served
    dead_tenants = [
        t for t, e in coord.assignments.items() if e["worker"] == "w1"
    ]
    assert dead_tenants  # the hash ring spreads 4 tenants over 2
    # w1 stops heartbeating; the injectable wall walks past the TTL
    for _ in range(20):
        wall.t += 1.0
        workers["w0"].tick()
        coord.tick()
    st = coord.status()
    assert st["workers"]["w1"]["state"] == "dead"
    for tid in dead_tenants:
        assert coord.assignments[tid] == {
            "worker": "w0", "phase": "serving",
        }
        assert _tenant_homes(root, tid) == ["w0"]
    # zero committed rows lost: EVERY tenant finishes on the survivor
    _step(coord, workers, wall, 10)
    for tid, sink in sinks.items():
        assert len(sink.batches) == 3, tid
    assert coord.migrations["completed"] >= len(dead_tenants)
    for w in workers.values():
        w.close()
    coord.close()


def test_dead_worker_rejoin_goes_live_again(tmp_path):
    wall = FakeWall()
    specs, _ = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall, lease_ttl_s=5.0
    )
    _step(coord, workers, wall, 4)
    for _ in range(15):  # kill w1's heartbeat past the TTL
        wall.t += 1.0
        workers["w0"].tick()
        coord.tick()
    assert coord.status()["workers"]["w1"]["state"] == "dead"
    _step(coord, workers, wall, 20)  # w1 heartbeats again → join
    st = coord.status()
    assert st["workers"]["w1"]["state"] == "live"
    for tid, e in coord.assignments.items():
        assert e["phase"] == "serving", (tid, e)
        assert _tenant_homes(root, tid) == [e["worker"]]
    for w in workers.values():
        w.close()
    coord.close()


# ---------------------------------------------------------------------------
# migration: first-class, bitwise, and safe to tear
# ---------------------------------------------------------------------------


def test_migration_bitwise_vs_unmigrated_reference(tmp_path):
    wall = FakeWall()
    ref_specs, ref_sinks = _specs(4, batches=4)
    _, ref_coord, ref_workers = _fleet(
        tmp_path / "ref", ["w0", "w1"], ref_specs, wall
    )
    _step(ref_coord, ref_workers, wall, 30)

    specs, sinks = _specs(4, batches=4)
    root, coord, workers = _fleet(
        tmp_path / "mig", ["w0", "w1"], specs, wall
    )
    _step(coord, workers, wall, 3)  # mid-stream, rows still flowing
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w0"
    )
    assert coord.migrate_tenant(tid, reason="rebalance")
    _step(coord, workers, wall, 30)
    assert coord.assignments[tid] == {"worker": "w1", "phase": "serving"}
    assert coord.migrations["completed"] == 1
    assert _tenant_homes(root, tid) == ["w1"]
    # a verified sealed manifest records the move
    manifest = json.load(open(
        os.path.join(root, "fleet", "migrations", f"{tid}.json")
    ))
    assert manifest["tenant"] == tid and manifest["dst"] == "w1"
    # the migrated fleet's sinks are bitwise the unmigrated fleet's
    for t in specs:
        assert _sink_rows(sinks[t]) == _sink_rows(ref_sinks[t]), t
    for w in list(workers.values()) + list(ref_workers.values()):
        w.close()
    coord.close()
    ref_coord.close()


def test_remigration_before_new_owner_applied_releases_ghost(tmp_path):
    """A tenant re-migrated AWAY from a worker before that worker ever
    applied the epoch that gave it the tenant: the named source holds
    nothing and must release immediately (a ``never_held`` marker) —
    not leave the coordinator waiting on a ghost forever."""
    wall = FakeWall()
    specs, sinks = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall
    )
    _step(coord, workers, wall, 2)  # both live, serving started
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w0"
    )
    assert coord.migrate_tenant(tid, "w1", reason="rebalance")
    # only the SOURCE ticks: the flip to serving@w1 completes without
    # w1 ever applying the epoch that hands it the tenant
    for _ in range(20):
        wall.t += 0.5
        workers["w0"].tick()
        coord.tick()
        if coord.assignments[tid] == {"worker": "w1",
                                      "phase": "serving"}:
            break
    assert coord.assignments[tid] == {"worker": "w1", "phase": "serving"}
    # ...and is immediately migrated BACK before w1 ticks once
    assert coord.migrate_tenant(tid, "w0", reason="rebalance")
    _step(coord, workers, wall, 30)
    assert coord.assignments[tid] == {"worker": "w0", "phase": "serving"}
    assert coord.migrations["completed"] == 2
    assert _tenant_homes(root, tid) == ["w0"]
    for t, sink in sinks.items():
        assert len(sink.batches) == 3, t  # zero committed rows lost
    for w in workers.values():
        w.close()
    coord.close()


def test_torn_ship_reverts_to_source_and_loses_nothing(tmp_path):
    wall = FakeWall()
    specs, sinks = _specs(4, batches=4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall
    )
    _step(coord, workers, wall, 3)
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w0"
    )
    assert coord.migrate_tenant(tid, reason="rebalance")
    R.arm("fleet.migrate", "io", times=1)  # tear the ship mid-copy
    _step(coord, workers, wall, 30)
    # the torn copy quarantined; the tenant re-resumed at the SOURCE
    assert coord.migrations["reverted"] == 1
    assert coord.assignments[tid] == {"worker": "w0", "phase": "serving"}
    assert _tenant_homes(root, tid) == ["w0"]
    for t, sink in sinks.items():
        assert len(sink.batches) == 4, t  # zero committed rows lost
    for w in workers.values():
        w.close()
    coord.close()


def test_poisoned_spec_degrades_tenant_never_kills_worker(tmp_path):
    wall = FakeWall()
    specs, sinks = _specs(3)
    specs["bad"] = TenantSpec(
        tenant_id="bad", model=_Identity(), sink=MemorySink(),
    )  # no source AND no watch dir: raises at build
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall
    )
    _step(coord, workers, wall, 25)
    st = coord.status()
    assert all(w["state"] == "live" for w in st["workers"].values())
    assert coord.assignments["bad"]["phase"] == "failed"
    for tid, sink in sinks.items():  # the healthy tenants all finish
        assert len(sink.batches) == 3, tid
    # parked means parked: further rounds never reassign it
    _step(coord, workers, wall, 5)
    assert coord.assignments["bad"]["phase"] == "failed"
    for w in workers.values():
        w.close()
    coord.close()


# ---------------------------------------------------------------------------
# the review-hardening regressions: fencing, torn request tails,
# stranded drains, ghost controller targets, reserved ids
# ---------------------------------------------------------------------------


def test_worker_id_fleet_is_reserved(tmp_path):
    wall = FakeWall()
    specs, _ = _specs(1)
    with pytest.raises(ValueError, match="reserved"):
        FleetWorker("fleet", str(tmp_path / "r"), specs, wall=wall)
    with pytest.raises(ValueError, match="reserved"):
        FleetCoordinator(
            str(tmp_path / "r"), ["w0", "fleet"], specs, wall=wall
        )
    root, coord, workers = _fleet(tmp_path, ["w0"], specs, wall)
    coord.add_worker("w1")  # a legal join still works
    with pytest.raises(ValueError, match="reserved"):
        coord.add_worker("fleet")
    for w in workers.values():
        w.close()
    coord.close()


def test_heartbeat_thread_renews_lease_without_ticks(tmp_path):
    """The r19 fix for slow-worker false death: the dedicated
    heartbeat thread keeps the lease fresh while the serving thread is
    parked (a minutes-long compile in real life)."""
    specs, _ = _specs(1)
    w = FleetWorker(
        "w0", str(tmp_path / "fleet"), specs,
        heartbeat_interval_s=0.02,
    )
    assert w.start_heartbeat()
    assert not w.start_heartbeat()  # idempotent: one thread only
    try:
        deadline = time.time() + 5.0
        lease_file = os.path.join(
            str(tmp_path / "fleet"), "fleet", "workers", "w0",
            "lease.json",
        )
        seq = -1
        while time.time() < deadline and seq < 3:
            time.sleep(0.01)
            try:
                seq = json.load(open(lease_file))["seq"]
            except (OSError, ValueError):
                pass
        # several renewals landed although tick() never ran
        assert seq >= 3
    finally:
        w.stop_heartbeat()
        w.close()


def test_dead_source_ship_fenced_by_grace_and_lease_recheck(tmp_path):
    """A worker declared dead off a 5s TTL must NOT have its tree
    shipped immediately: the ship waits the extra dead-grace, and a
    lease renewal inside that window aborts the ship entirely —
    split-brain fencing for the slow-but-alive worker."""
    wall = FakeWall()
    specs, sinks = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall,
        lease_ttl_s=5.0, dead_grace_s=10.0,
    )
    _step(coord, workers, wall, 4)
    w1_tenants = [
        t for t, e in coord.assignments.items() if e["worker"] == "w1"
    ]
    assert w1_tenants
    # w1 goes silent just past the TTL: declared dead, NOT shipped
    for _ in range(8):
        wall.t += 1.0
        workers["w0"].tick()
        coord.tick()
    assert coord.status()["workers"]["w1"]["state"] == "dead"
    for tid in w1_tenants:
        assert coord.assignments[tid]["phase"] == "draining"
        assert "w1" in _tenant_homes(root, tid)  # tree untouched
    assert coord.migrations["completed"] == 0
    # w1 renews INSIDE the grace window: the ship aborts, the worker
    # revives, and the tenants settle through the normal drain path
    _step(coord, workers, wall, 30)
    st = coord.status()
    assert st["workers"]["w1"]["state"] == "live"
    for tid, e in coord.assignments.items():
        assert e["phase"] == "serving", (tid, e)
        assert _tenant_homes(root, tid) == [e["worker"]]
    for tid, sink in sinks.items():
        assert len(sink.batches) == 3, tid  # zero committed rows lost
    for w in workers.values():
        w.close()
    coord.close()


def test_dead_source_tree_retires_instead_of_rmtree(tmp_path):
    """After a truly-dead source's tenants ship, its trees move to
    fleet/retired/ (evidence preserved for a zombie writer) instead of
    being deleted — and the serving namespace stays single-homed."""
    wall = FakeWall()
    specs, sinks = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall,
        lease_ttl_s=5.0, dead_grace_s=4.0,
    )
    _step(coord, workers, wall, 4)
    dead_tenants = [
        t for t, e in coord.assignments.items() if e["worker"] == "w1"
    ]
    for _ in range(20):
        wall.t += 1.0
        workers["w0"].tick()
        coord.tick()
    for tid in dead_tenants:
        assert coord.assignments[tid] == {
            "worker": "w0", "phase": "serving",
        }
        assert _tenant_homes(root, tid) == ["w0"]
        assert glob.glob(os.path.join(
            root, "fleet", "retired", f"{tid}.w1.*"
        )), tid
    _step(coord, workers, wall, 10)
    for tid, sink in sinks.items():
        assert len(sink.batches) == 3, tid
    for w in workers.values():
        w.close()
    coord.close()


def test_retired_trees_fsck_verified_and_restorable(tmp_path):
    """r23: retired dead-source trees are part of the fleet fsck
    surface — verified, not just parked — and ``restore_retired``
    recovers one into an explicit destination with a sealed restore
    manifest.  A retired tree that fails fsck refuses to restore."""
    wall = FakeWall()
    specs, _sinks = _specs(4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall,
        lease_ttl_s=5.0, dead_grace_s=4.0,
    )
    _step(coord, workers, wall, 4)
    for _ in range(20):  # w1 dies; its trees retire
        wall.t += 1.0
        workers["w0"].tick()
        coord.tick()
    retired = sorted(
        os.path.basename(p) for p in
        glob.glob(os.path.join(root, "fleet", "retired", "*"))
    )
    assert retired
    # 1. fsck covers every retired tree
    rep = fsck_fleet(root)
    assert rep["ok"], rep
    assert sorted(rep["retired"]) == retired
    assert all(r["ok"] for r in rep["retired"].values())
    # 2. restore a verified tree into an explicit destination
    dest = str(tmp_path / "restored")
    rr = restore_retired(root, retired[0], dest)
    assert rr["ok"] is True and rr["files"] > 0
    from sntc_tpu.resilience.storage import load_sealed_json

    man = load_sealed_json(os.path.join(dest, "restore_manifest.json"))
    assert man["retired"] == retired[0]
    for rel, size, _sha in man["files"]:
        assert os.path.getsize(os.path.join(dest, rel)) == size
    assert R.recent_events(event="fleet_retired_restored")
    # 3. a missing name refuses cleanly
    miss = restore_retired(root, "nope.w9.0", str(tmp_path / "x"))
    assert miss["ok"] is False and miss["error"] == "no such retired tree"
    # 4. a corrupted retired tree fails fleet fsck AND refuses restore
    victim_dir = os.path.join(root, "fleet", "retired", retired[0])
    victims = glob.glob(
        os.path.join(victim_dir, "**", "commits", "*.json"),
        recursive=True,
    ) or glob.glob(os.path.join(victim_dir, "**", "*.json"),
                   recursive=True)
    with open(victims[0], "w") as f:
        f.write('{"torn": ')
    rep2 = fsck_fleet(root, repair=False)
    assert rep2["ok"] is False
    assert rep2["retired"][retired[0]]["ok"] is False
    rr2 = restore_retired(
        root, retired[0], str(tmp_path / "y"), repair=False,
    )
    assert rr2["ok"] is False and rr2["error"] == "retired tree fails fsck"
    for w in workers.values():
        w.close()
    coord.close()


def test_dead_source_failed_ship_restores_from_warm_replica(tmp_path):
    """r23 tentpole wiring: when a dead worker's tenant tree cannot
    ship (every attempt tears) revert-to-source is impossible — the
    coordinator promotes the tenant's warm-standby replica into the
    destination tree instead of parking the tenant as failed, and the
    tenant finishes its arc with zero committed-row loss."""
    from sntc_tpu.obs.metrics import registry
    from sntc_tpu.resilience.replicate import ReplicationPlane
    from sntc_tpu.serve.fleet import tenant_tree

    wall = FakeWall()
    n_batches = 6
    specs, sinks = _specs(4, batches=n_batches)
    standby = str(tmp_path / "standby")
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall,
        lease_ttl_s=5.0, dead_grace_s=4.0, standby_root=standby,
    )
    _step(coord, workers, wall, 4)
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w1"
    )
    workers["w1"].close()  # dies mid-arc; tree quiescent on disk
    # the warm replica a live ReplicationPlane would have left behind:
    # ship the (still healthy) tree and seal a barrier at its last
    # durable commit, BEFORE the ship path is sabotaged below
    tree = tenant_tree(root, "w1", tid)
    commits = sorted(
        glob.glob(os.path.join(tree, "ckpt", "commits", "*.json"))
    )
    assert commits  # the tenant committed something before death
    with open(commits[-1]) as f:
        last = json.load(f)
    bid = int(os.path.splitext(os.path.basename(commits[-1]))[0])
    plane = ReplicationPlane(tree, standby, tenant=tid)
    plane.on_commit(bid, last, 0)
    plane.close()
    # every ship attempt of THIS tenant tears; the source is dead, so
    # the replica is the only way back
    R.arm(f"tenant/{tid}/fleet.migrate", "io", times=None)
    for _ in range(20):
        wall.t += 1.0
        workers["w0"].tick()
        coord.tick()
    R.disarm(f"tenant/{tid}/fleet.migrate")
    assert coord.assignments[tid] == {"worker": "w0", "phase": "serving"}
    assert os.path.isdir(
        os.path.join(tenant_tree(root, "w0", tid), "ckpt")
    )
    evs = R.recent_events(event="tenant_restored_from_replica")
    assert evs and evs[-1]["tenant"] == tid
    assert (registry().get(
        "sntc_fleet_migrations_total",
        reason="replica_restore", outcome="completed",
    ) or 0) == 1
    # the restored tenant resumes from the barrier and finishes the
    # arc — no committed batch lost, none duplicated
    _step(coord, workers, wall, 15)
    for t, sink in sinks.items():
        assert len(sink.batches) == n_batches, t
    for w in workers.values():
        w.close()
    coord.close()


def test_torn_request_tail_is_not_dropped(tmp_path):
    """A partially-appended fleet request (torn tail, non-ASCII reason
    included) must be consumed on the tick AFTER the line completes —
    these requests fire once per tenant per daemon lifetime, so a
    dropped line is never re-posted."""
    wall = FakeWall()
    specs, _ = _specs(4)
    root, coord, workers = _fleet(tmp_path, ["w0", "w1"], specs, wall)
    _step(coord, workers, wall, 3)
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w0"
    )
    line = json.dumps({
        "action": "migrate", "tenant": tid,
        "reason": "café-überload",  # non-ASCII: bytes ≠ chars
        "worker": "w0",
    }, ensure_ascii=False).encode()
    path = os.path.join(
        root, "fleet", "workers", "w0", "requests.jsonl"
    )
    with open(path, "ab") as f:  # torn mid-append: no newline yet
        f.write(line[:len(line) // 2])
    coord.tick()
    assert coord.assignments[tid]["phase"] == "serving"  # not consumed
    with open(path, "ab") as f:  # the append completes
        f.write(line[len(line) // 2:] + b"\n")
    coord.tick()
    assert coord.assignments[tid]["phase"] == "draining"
    _step(coord, workers, wall, 20)
    assert coord.assignments[tid]["phase"] == "serving"
    assert coord.migrations["completed"] == 1
    for w in workers.values():
        w.close()
    coord.close()


def test_draining_tenant_reverts_to_source_when_dst_dies(tmp_path):
    """Destination dies mid-migration with no other live worker: the
    draining tenant must revert to its intact source instead of being
    stranded in 'draining' forever."""
    wall = FakeWall()
    specs, sinks = _specs(4, batches=4)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall, lease_ttl_s=5.0
    )
    _step(coord, workers, wall, 3)
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w0"
    )
    assert coord.migrate_tenant(tid, "w1", reason="rebalance")
    # w1 (the destination) goes silent while the SOURCE is still
    # mid-drain (it heartbeats but never applies the draining epoch,
    # so it never releases) — the classic dst-death-mid-migration
    for _ in range(25):
        wall.t += 1.0
        workers["w0"].renew_lease()
        coord.tick()
    assert coord.status()["workers"]["w1"]["state"] == "dead"
    assert coord.assignments[tid] == {"worker": "w0", "phase": "serving"}
    assert coord.migrations["reverted"] >= 1
    # the source never even noticed: serving resumes there untouched
    _step(coord, {"w0": workers["w0"]}, wall, 30)
    for t, sink in sinks.items():
        assert len(sink.batches) == 4, t  # zero committed rows lost
    for w in workers.values():
        w.close()
    coord.close()


# ---------------------------------------------------------------------------
# the fleet doctor
# ---------------------------------------------------------------------------


def test_fsck_fleet_repairs_torn_journal_flags_broken_seal(tmp_path):
    wall = FakeWall()
    specs, _ = _specs(2)
    root, coord, workers = _fleet(
        tmp_path, ["w0", "w1"], specs, wall
    )
    _step(coord, workers, wall, 10)
    tid = next(
        t for t, e in coord.assignments.items() if e["worker"] == "w0"
    )
    assert coord.migrate_tenant(tid, reason="rebalance")
    _step(coord, workers, wall, 15)
    assert coord.migrations["completed"] == 1
    # tear the assignment journal mid-line (crash mid-append)
    journal = os.path.join(root, "fleet", "assignments.jsonl")
    with open(journal, "a") as f:
        f.write('{"epoch": 99, "torn')
    rep = fsck_fleet(root)
    assert rep["ok"], rep["errors"]
    assert len(rep["repaired"]) >= 1
    records = [
        json.loads(line)
        for line in open(journal) if line.strip()
    ]
    assert all("torn" not in json.dumps(r) for r in records)
    # a broken migration-manifest seal is UNREPAIRABLE: ok=False
    mpath = os.path.join(root, "fleet", "migrations", f"{tid}.json")
    doc = json.load(open(mpath))
    doc["dst"] = "attacker"
    with open(mpath, "w") as f:
        json.dump(doc, f)
    rep = fsck_fleet(root)
    assert not rep["ok"]
    assert any(
        e.get("artifact") == "fleet_migration_manifest"
        for e in rep["errors"]
    )
    for w in workers.values():
        w.close()
    coord.close()


# ---------------------------------------------------------------------------
# the r19 request-drain race regression (satellite 1): a drain
# requested from another thread mid-tick must WAIT for the in-flight
# scheduling round, and the markers must carry the mid-batch evidence
# ---------------------------------------------------------------------------


def test_request_drain_mid_tick_waits_for_round(tmp_path):
    entered, release = threading.Event(), threading.Event()

    class GateSink(MemorySink):
        def add_batch(self, batch_id, frame):
            entered.set()
            release.wait(10)
            return super().add_batch(batch_id, frame)

    spec = TenantSpec(
        tenant_id="t0", model=_Identity(),
        source=MemorySource(_frames(2)), sink=GateSink(),
    )
    d = ServeDaemon([spec], str(tmp_path / "root"))
    ticker = threading.Thread(target=d.tick)
    ticker.start()
    assert entered.wait(10)  # a batch is in flight inside tick()
    d.request_drain("race")
    drainer = threading.Thread(target=d.drain)
    drainer.start()
    drainer.join(0.3)
    # the fix: drain blocks on the scheduler mutex instead of racing
    # the in-flight round
    assert drainer.is_alive()
    release.set()
    ticker.join(10)
    drainer.join(10)
    assert not drainer.is_alive()
    marker = json.load(open(
        os.path.join(str(tmp_path / "root"), "daemon_drain_marker.json")
    ))
    assert marker["reason"] == "race"
    d.close()


def test_drain_marker_records_mid_batch_tenants(tmp_path):
    class DownSink(MemorySink):
        def add_batch(self, batch_id, frame):
            raise IOError("sink volume down")

    spec = TenantSpec(
        tenant_id="t0", model=_Identity(),
        source=MemorySource(_frames(1)), sink=DownSink(),
    )
    d = ServeDaemon([spec], str(tmp_path / "root"))
    d.tick()  # the batch defers into the WAL — in flight, uncommitted
    d.request_drain("evidence")
    d.drain()
    daemon_marker = json.load(open(
        os.path.join(str(tmp_path / "root"), "daemon_drain_marker.json")
    ))
    assert daemon_marker["mid_batch_tenants"] == ["t0"]
    tenant_marker = json.load(open(os.path.join(
        d.tenant_dir("t0"), "drain_marker.json"
    )))
    assert tenant_marker["was_mid_batch"] is True
    d.close()


# ---------------------------------------------------------------------------
# fleet-flags drift check (the tier-1 wiring of check_fleet_flags)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_flags_consistent_cli_coordinator_docs():
    checker = _load_script("check_fleet_flags")
    assert checker.check() == []
