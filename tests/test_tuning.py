import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.evaluation import MulticlassClassificationEvaluator
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import LogisticRegression
from sntc_tpu.tuning import (
    CrossValidator,
    ParamGridBuilder,
    TrainValidationSplit,
)


def test_param_grid_builder():
    grid = (
        ParamGridBuilder()
        .addGrid("regParam", [0.0, 0.1])
        .addGrid("maxIter", [10, 20, 30])
        .baseOn(tol=1e-4)
        .build()
    )
    assert len(grid) == 6
    assert all(g["tol"] == 1e-4 for g in grid)
    assert {(g["regParam"], g["maxIter"]) for g in grid} == {
        (r, m) for r in (0.0, 0.1) for m in (10, 20, 30)
    }
    assert ParamGridBuilder().build() == [{}]


def _data(n=1200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
    return Frame({"features": X, "label": y})


def test_cross_validator_picks_better_config(mesh8):
    f = _data()
    # regParam=10 cripples the model; CV must prefer the small one
    grid = ParamGridBuilder().addGrid("regParam", [1e-4, 10.0]).build()
    cv = CrossValidator(
        estimator=LogisticRegression(mesh=mesh8, maxIter=30),
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy", mesh=mesh8),
        numFolds=3,
        seed=1,
    )
    model = cv.fit(f)
    assert model.bestIndex == 0
    assert len(model.avgMetrics) == 2
    assert model.avgMetrics[0] > model.avgMetrics[1]
    out = model.transform(f)
    assert (out["prediction"] == f["label"]).mean() > 0.85


def test_cross_validator_collect_sub_models(mesh8):
    f = _data(400)
    cv = CrossValidator(
        estimator=LogisticRegression(mesh=mesh8, maxIter=10),
        estimatorParamMaps=[{}],
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy", mesh=mesh8),
        numFolds=2,
        collectSubModels=True,
    )
    model = cv.fit(f)
    assert len(model.subModels) == 1 and len(model.subModels[0]) == 2


def test_train_validation_split(mesh8, tmp_path):
    f = _data(seed=2)
    grid = ParamGridBuilder().addGrid("regParam", [1e-4, 10.0]).build()
    tvs = TrainValidationSplit(
        estimator=LogisticRegression(mesh=mesh8, maxIter=30),
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy", mesh=mesh8),
        trainRatio=0.7,
        seed=3,
    )
    model = tvs.fit(f)
    assert model.bestIndex == 0
    assert len(model.validationMetrics) == 2
    # best-model persistence through the generic sub-stage mechanism
    save_model(model, str(tmp_path / "tvs"))
    loaded = load_model(str(tmp_path / "tvs"))
    np.testing.assert_array_equal(
        loaded.transform(f)["prediction"], model.transform(f)["prediction"]
    )


def test_utils_metrics_logger(tmp_path):
    from sntc_tpu.obs import SpanTracer
    from sntc_tpu.utils import MetricsLogger

    log = MetricsLogger(str(tmp_path / "m.jsonl"))
    log.log(event="fit_start", model="lr")
    log.log(event="fit_end", loss=0.5)
    records = log.read_all()
    assert [r["step"] for r in records] == [0, 1]
    assert records[1]["loss"] == 0.5

    # phase timing lives on the obs span tracer now (the old StepTimer
    # was dormant telemetry and is gone)
    t = SpanTracer(capacity=8)
    with t.span("a"):
        pass
    with t.span("a"):
        pass
    assert [s["name"] for s in t.spans()] == ["a", "a"]


def test_cross_validator_fold_col(mesh8):
    f = _data(n=400, seed=3)
    folds = (np.arange(400) % 3).astype(np.float64)
    f = f.with_column("myfold", folds)
    cv = CrossValidator(
        estimator=LogisticRegression(mesh=mesh8, maxIter=20),
        estimatorParamMaps=ParamGridBuilder().addGrid("regParam", [0.0, 0.1]).build(),
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy", mesh=mesh8),
        numFolds=3, foldCol="myfold",
    ).fit(f)
    assert len(cv.avgMetrics) == 2
    with pytest.raises(ValueError, match="foldCol"):
        CrossValidator(
            estimator=LogisticRegression(mesh=mesh8),
            evaluator=MulticlassClassificationEvaluator(mesh=mesh8),
            numFolds=2, foldCol="myfold",
        ).fit(f)  # fold index 2 out of range for numFolds=2


def test_tvs_collect_sub_models(mesh8):
    f = _data(n=300, seed=4)
    tvs = TrainValidationSplit(
        estimator=LogisticRegression(mesh=mesh8, maxIter=20),
        estimatorParamMaps=ParamGridBuilder().addGrid("regParam", [0.0, 0.05]).build(),
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy", mesh=mesh8),
        collectSubModels=True,
    ).fit(f)
    assert tvs.subModels is not None and len(tvs.subModels) == 2


def test_cross_validator_fold_col_rejects_empty_and_fractional(mesh8):
    f = _data(n=90, seed=5)
    ev = MulticlassClassificationEvaluator(metricName="accuracy", mesh=mesh8)
    est = LogisticRegression(mesh=mesh8, maxIter=10)
    with pytest.raises(ValueError, match="empty"):
        CrossValidator(
            estimator=est, evaluator=ev, numFolds=3,
            foldCol="z",
        ).fit(f.with_column("z", np.zeros(90)))  # folds 1,2 empty
    with pytest.raises(ValueError, match="integers"):
        CrossValidator(
            estimator=est, evaluator=ev, numFolds=2, foldCol="z",
        ).fit(f.with_column("z", np.full(90, 0.5)))


# ---------------------------------------------------------------------------
# batched (vmapped) grid fits — SURVEY.md §2.5 task parallelism
# ---------------------------------------------------------------------------


def _data15(n=1500, seed=3, k=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    W = rng.normal(size=(6, k))
    y = np.argmax(X @ W + 0.3 * rng.normal(size=(n, k)), axis=1).astype(
        np.float64
    )
    return Frame({"features": X, "label": y})


def test_supports_batched_grid_rules(mesh8):
    lr = LogisticRegression(mesh=mesh8, maxIter=10)
    ok = [{"regParam": 0.0}, {"regParam": 0.1, "elasticNetParam": 0.5}]
    assert lr.supports_batched_grid(ok)
    # single point: nothing to batch
    assert not lr.supports_batched_grid([{"regParam": 0.1}])
    # non-uniform static knob
    assert not lr.supports_batched_grid(
        [{"maxIter": 5}, {"maxIter": 20}]
    )
    # uniform static knob is fine
    assert lr.supports_batched_grid(
        [{"maxIter": 5, "regParam": 0.0}, {"maxIter": 5, "regParam": 0.1}]
    )
    # unknown/unsupported key -> sequential fallback
    assert not lr.supports_batched_grid(
        [{"regParam": 0.0}, {"featuresCol": "other"}]
    )
    # bound constraints -> sequential fallback
    lb = np.full((1, 5), -1.0)
    bounded = LogisticRegression(
        mesh=mesh8, maxIter=10, lowerBoundsOnCoefficients=lb
    )
    assert not bounded.supports_batched_grid(ok)


def test_fit_grid_matches_individual_fits(mesh8):
    f = _data()
    lr = LogisticRegression(mesh=mesh8, maxIter=25)
    grid = (
        ParamGridBuilder()
        .addGrid("regParam", [0.0, 0.01, 0.1])
        .build()
    )
    batched = lr._fit_grid(f, grid)
    for params, bm in zip(grid, batched):
        sm = lr.copy(params).fit(f)
        np.testing.assert_allclose(
            bm.coefficientMatrix, sm.coefficientMatrix, atol=2e-3
        )
        np.testing.assert_allclose(
            bm.interceptVector, sm.interceptVector, atol=2e-3
        )
        # grid-point params land on the batched models too
        assert bm.getRegParam() == params["regParam"]


def test_fit_grid_mixed_l1_l2_groups(mesh8):
    """L1 (OWLQN) and L2 (LBFGS) points batch separately but return in
    grid order, matching their individual fits."""
    f = _data15()
    lr = LogisticRegression(mesh=mesh8, maxIter=20)
    grid = [
        {"regParam": 0.05, "elasticNetParam": 1.0},  # pure L1
        {"regParam": 0.0},                            # unregularized
        {"regParam": 0.05, "elasticNetParam": 0.0},   # pure L2
        {"regParam": 0.05, "elasticNetParam": 0.5},   # elastic net
    ]
    batched = lr._fit_grid(f, grid)
    assert len(batched) == 4
    for params, bm in zip(grid, batched):
        sm = lr.copy(params).fit(f)
        np.testing.assert_allclose(
            bm.coefficientMatrix, sm.coefficientMatrix, atol=5e-3
        )


def test_cross_validator_batched_matches_sequential(mesh8, monkeypatch):
    f = _data(800)
    grid = ParamGridBuilder().addGrid("regParam", [1e-4, 0.05, 5.0]).build()

    def run():
        cv = CrossValidator(
            estimator=LogisticRegression(mesh=mesh8, maxIter=20),
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(
                metricName="accuracy", mesh=mesh8
            ),
            numFolds=2,
            seed=5,
        )
        return cv.fit(f)

    monkeypatch.setenv("SNTC_TUNING_BATCH", "0")
    seq = run()
    monkeypatch.setenv("SNTC_TUNING_BATCH", "1")
    bat = run()
    assert bat.bestIndex == seq.bestIndex
    np.testing.assert_allclose(bat.avgMetrics, seq.avgMetrics, atol=1e-3)


def test_parallelism_noop_warns(mesh8, caplog):
    """Spark-ported code setting parallelism on a non-batchable estimator
    gets a warning, not silence (VERDICT weak item 7)."""
    import logging

    f = _data(300)
    grid = ParamGridBuilder().addGrid("maxIter", [5, 10]).build()  # static-varying
    cv = CrossValidator(
        estimator=LogisticRegression(mesh=mesh8),
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(
            metricName="accuracy", mesh=mesh8
        ),
        numFolds=2,
        parallelism=4,
    )
    with caplog.at_level(logging.WARNING, logger="sntc_tpu.tuning.cross_validator"):
        cv.fit(f)
    assert any("parallelism" in r.message for r in caplog.records)


def test_fit_grid_folds_matches_per_fold_fits(mesh8):
    """The one-program fold×grid sweep equals per-fold subset fits: a fold
    is a zero-weight mask, so coefficients must match fits on the actual
    row subsets (modulo f32 summation order)."""
    f = _data(900, seed=8)
    lr = LogisticRegression(mesh=mesh8, maxIter=20)
    grid = [{"regParam": 0.0}, {"regParam": 0.05, "elasticNetParam": 1.0}]
    rng = np.random.default_rng(3)
    fold_of = rng.integers(0, 3, size=f.num_rows)
    batched = lr._fit_grid_folds(f, grid, fold_of, 3)
    assert len(batched) == 3 and all(len(row) == 2 for row in batched)
    for fold in range(3):
        train = f.filter(fold_of != fold)
        for gi, params in enumerate(grid):
            ref = lr.copy(params).fit(train)
            np.testing.assert_allclose(
                batched[fold][gi].coefficientMatrix,
                ref.coefficientMatrix,
                atol=5e-3,
            )


def test_ovr_lr_vectorized_matches_sequential(mesh8):
    """OneVsRest(LogisticRegression) runs all K binary fits as one vmapped
    program; models must match the sequential per-class fits."""
    from sntc_tpu.models import OneVsRest

    f = _data15(1200, seed=6, k=4)
    base = LogisticRegression(mesh=mesh8, maxIter=25, regParam=1e-3)
    calls = []
    orig = LogisticRegression._fit_ovr_lanes

    def spy(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    LogisticRegression._fit_ovr_lanes = spy
    try:
        vec = OneVsRest(classifier=base, mesh=mesh8).fit(f)
    finally:
        LogisticRegression._fit_ovr_lanes = orig
    assert calls, "vectorized OvR path did not run (gate regressed?)"
    assert len(vec.models) == 4

    # sequential reference: force family=binomial-incompatible gate off
    seq_models = []
    y = np.asarray(f["label"])
    for c in range(4):
        sub = f.with_column("bin", (y == c).astype(np.float64))
        seq_models.append(
            base.copy({"labelCol": "bin"}).fit(sub)
        )
    for vm, sm in zip(vec.models, seq_models):
        np.testing.assert_allclose(
            vm.coefficientMatrix, sm.coefficientMatrix, atol=5e-3
        )
    out = vec.transform(f)
    assert (out["prediction"] == y).mean() > 0.8
