"""Live network front door (r20): WAL-at-ingress socket sources.

Covers the spool contract (atomic seals, max-index resume, keep-N
committed retention with tombstoned offsets), the loss-accounting law
(received == spooled + sum(dropped) EXACTLY, for every drop reason),
the backpressure/shed ladder (ring overflow, disk budget, injected
ENOSPC), torn-frame quarantine, both listeners end-to-end over real
loopback sockets, the TenantSpec/CLI wiring, the ingress-flags drift
checker, and the chaos kill/burst scenarios in real child processes.
"""

import glob
import importlib.util
import os
import socket
import threading
import time

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.native.netflow import make_datagram
from sntc_tpu.serve import (
    CsvSpoolSource,
    IngressSpool,
    NetFlowSpoolSource,
    ServeDaemon,
    TcpRowIngress,
    TenantSpec,
    UdpIngressListener,
    build_ingress,
    frame_rows,
)
from sntc_tpu.serve.ingress import FRAME_HEADER, QUARANTINE_DIR

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _dgram(n_records=2, dstport=80, seq=0):
    rec = (
        0xC0A80001, 0xC0A80002, 1234, dstport, 6, 0x12, 0,
        10, 1000, 1_000, 2_000, 0, 0, 0, 0,
    )
    return make_datagram([rec] * n_records, seq=seq)


def _wait(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _law(snap):
    """The conservation law, as an exact equality."""
    return snap["received"] == snap["spooled"] + sum(
        snap["dropped"].values()
    )


# ---------------------------------------------------------------------------
# UDP listener: loopback round-trip into the replayable spool
# ---------------------------------------------------------------------------


def test_udp_roundtrip_seals_and_replays(tmp_path):
    spool_dir = str(tmp_path / "spool")
    spool = IngressSpool(spool_dir)
    lst = UdpIngressListener(
        spool, ring_datagrams=64, seal_datagrams=2, seal_idle_s=0.1,
    ).start()
    try:
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for i in range(4):
            tx.sendto(_dgram(seq=i), ("127.0.0.1", lst.port))
        tx.close()
        assert _wait(lambda: spool.stats.received == 4), (
            spool.stats.snapshot()
        )
    finally:
        snap = lst.drain()
    assert snap["received"] == 4
    assert snap["spooled"] == 4
    assert snap["dropped"] == {}
    assert _law(snap)
    assert snap["drained"] is True
    # 4 datagrams / 2 per seal = 2 capture files, and the endpoint is
    # published in the durable stats for harnesses
    assert snap["sealed_files"] == 2
    stats = IngressSpool.read_stats(spool_dir)
    assert stats["port"] == lst.port and stats["proto"] == "udp"

    # the spool replays through the ordinary directory-source path
    src = NetFlowSpoolSource(spool_dir)
    assert src.latest_offset() == 2
    frame = src.get_batch(0, 2)
    assert frame.num_rows == 8  # 4 datagrams x 2 records
    assert np.all(frame["Destination Port"] == 80.0)
    src.close()


def test_udp_partial_group_idle_seals_without_drain(tmp_path):
    """A partial seal group must age toward the idle tail seal during
    STEADY STATE — not only at drain.  Regression: the spooler reset
    its idle clock on every wakeup while a partial group sat in buf,
    so a live listener held the tail in memory until SIGTERM (the CLI
    serve drive caught it: predictions only appeared at shutdown)."""
    spool_dir = str(tmp_path / "spool")
    spool = IngressSpool(spool_dir)
    lst = UdpIngressListener(
        spool, ring_datagrams=64, seal_datagrams=8, seal_idle_s=0.1,
    ).start()
    try:
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for i in range(3):  # 3 < seal_datagrams: never a full group
            tx.sendto(_dgram(seq=i), ("127.0.0.1", lst.port))
        tx.close()
        # sealed by the IDLE clock, with the listener still live
        assert _wait(lambda: spool.stats.spooled == 3, timeout=5.0), (
            spool.stats.snapshot()
        )
        assert spool.stats.snapshot()["sealed_files"] == 1
    finally:
        snap = lst.drain()
    assert snap["received"] == 3 and snap["dropped"] == {}
    assert _law(snap)


def test_udp_ring_overflow_conservation_exact(tmp_path):
    """Flood a stopped spooler: exactly ring_size datagrams survive,
    the rest are counted ring_overflow, and after a drain the law
    holds as an equality — sent == spooled + dropped."""
    spool = IngressSpool(str(tmp_path / "spool"))
    lst = UdpIngressListener(spool, ring_datagrams=4, seal_datagrams=30)
    # ingest with the spooler not yet running: the ring caps at 4
    for i in range(10):
        lst._ingest(_dgram(seq=i))
    assert spool.stats.received == 10
    assert spool.stats.dropped == {"ring_overflow": 6}
    lst.start()
    snap = lst.drain()
    assert snap["spooled"] == 4
    assert snap["received"] == snap["spooled"] + snap["dropped"][
        "ring_overflow"
    ]
    assert _law(snap)


def test_udp_recv_fault_drops_one_counted(tmp_path):
    """An injected receive fault (ingress.recv) drops ONE datagram —
    counted as received AND dropped so the law stays exact — and the
    listener survives to ingest the next one."""
    spool = IngressSpool(str(tmp_path / "spool"))
    lst = UdpIngressListener(
        spool, ring_datagrams=8, seal_datagrams=1, seal_idle_s=0.05,
    ).start()
    try:
        R.arm("ingress.recv", kind="exc", times=1)
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        tx.sendto(_dgram(seq=0), ("127.0.0.1", lst.port))
        assert _wait(lambda: spool.stats.dropped.get("recv_error") == 1)
        tx.sendto(_dgram(seq=1), ("127.0.0.1", lst.port))
        assert _wait(lambda: spool.stats.spooled == 1), (
            spool.stats.snapshot()
        )
        tx.close()
    finally:
        snap = lst.drain()
    assert snap["received"] == 2
    assert snap["dropped"] == {"recv_error": 1}
    assert _law(snap)


# ---------------------------------------------------------------------------
# TCP listener: framed rows, torn-frame quarantine, oversize shed
# ---------------------------------------------------------------------------


def test_tcp_roundtrip_torn_and_oversize(tmp_path):
    spool_dir = str(tmp_path / "spool")
    spool = IngressSpool(spool_dir, prefix="rows_", suffix=".csv")
    lst = TcpRowIngress(
        spool, host="127.0.0.1", columns=["x", "y"], seal_rows=2,
        seal_idle_s=0.1,
    ).start()
    try:
        # a well-behaved client: two framed rows -> one sealed file
        c = socket.create_connection(("127.0.0.1", lst.port))
        c.sendall(frame_rows(["1,2", "3,4"]))
        c.close()
        assert _wait(lambda: spool.stats.spooled == 2), (
            spool.stats.snapshot()
        )

        # a client that dies mid-frame: the torn tail is quarantined,
        # counted, and no other connection is disturbed
        c = socket.create_connection(("127.0.0.1", lst.port))
        c.sendall(FRAME_HEADER.pack(100) + b"torn!")
        c.close()
        assert _wait(lambda: spool.stats.quarantined == 1)

        # an absurd length prefix is shed (oversize_frame), counted
        c = socket.create_connection(("127.0.0.1", lst.port))
        c.sendall(FRAME_HEADER.pack(64 << 20))
        assert _wait(
            lambda: spool.stats.dropped.get("oversize_frame") == 1
        )
        c.close()
    finally:
        snap = lst.drain()
    # 2 rows + 1 torn tail + 1 oversize header = 4 received units
    assert snap["received"] == 4
    assert snap["spooled"] == 2
    assert snap["dropped"] == {"torn_frame": 1, "oversize_frame": 1}
    assert _law(snap)
    qfiles = glob.glob(os.path.join(spool_dir, QUARANTINE_DIR, "*.bin"))
    assert len(qfiles) == 1
    with open(qfiles[0], "rb") as f:
        # evidence preservation includes the length prefix
        assert f.read() == FRAME_HEADER.pack(100) + b"torn!"

    # sealed file carries the declared header and replays as a frame
    sealed = sorted(glob.glob(os.path.join(spool_dir, "rows_*.csv")))
    assert len(sealed) == 1
    with open(sealed[0], "rb") as f:
        assert f.read() == b"x,y\n1,2\n3,4\n"
    src = CsvSpoolSource(spool_dir)
    assert src.latest_offset() == 1
    frame = src.get_batch(0, 1)
    assert frame.num_rows == 2
    assert np.allclose(frame["x"], [1.0, 3.0])
    assert np.allclose(frame["y"], [2.0, 4.0])
    src.close()


# ---------------------------------------------------------------------------
# the spool itself: retention, offsets, resume, shed valves
# ---------------------------------------------------------------------------


def test_spool_retention_prunes_committed_only_offsets_stable(tmp_path):
    spool_dir = str(tmp_path / "spool")
    committed = {"v": 0}
    spool = IngressSpool(
        spool_dir, keep_files=3, committed_offset_fn=lambda: committed["v"],
    )
    payloads = [_dgram(seq=i) for i in range(11)]
    for p in payloads[:10]:
        assert spool.seal(p, units=1) is not None
    # nothing committed yet: retention must not touch replayable history
    assert len(glob.glob(os.path.join(spool_dir, "capture_*.nf5"))) == 10
    committed["v"] = 8
    assert spool.seal(payloads[10], units=1) is not None
    # of the 8 committed files (idx < 8) the newest 3 are kept; the 3
    # uncommitted files are untouchable
    live = sorted(glob.glob(os.path.join(spool_dir, "capture_*.nf5")))
    assert [os.path.basename(p) for p in live] == [
        f"capture_{i:06d}.nf5" for i in (5, 6, 7, 8, 9, 10)
    ]
    assert spool.stats.pruned_files == 5

    # offsets survive the prune: file i IS offset i forever
    src = NetFlowSpoolSource(spool_dir)
    assert src.latest_offset() == 11
    with pytest.raises(ValueError, match="retention horizon"):
        src.get_batch(2, 4)
    frame = src.get_batch(8, 11)
    assert frame.num_rows == 6  # 3 datagrams x 2 records
    src.close()

    # a restart resumes PAST everything ever sealed — a pruned spool
    # never reuses an index
    spool2 = IngressSpool(
        spool_dir, keep_files=3, committed_offset_fn=lambda: committed["v"],
    )
    path = spool2.seal(_dgram(seq=99), units=1)
    assert os.path.basename(path) == "capture_000011.nf5"
    assert spool2.stats.pruned_files == 5  # durable across restart


def test_spool_restart_resumes_index_bitwise(tmp_path):
    spool_dir = str(tmp_path / "spool")
    payloads = [_dgram(n_records=i + 1, seq=i) for i in range(3)]
    spool = IngressSpool(spool_dir)
    for p in payloads:
        spool.seal(p, units=1)
    spool2 = IngressSpool(spool_dir)
    spool2.seal(b"tail", units=1)
    files = sorted(glob.glob(os.path.join(spool_dir, "capture_*.nf5")))
    assert [os.path.basename(p) for p in files] == [
        f"capture_{i:06d}.nf5" for i in range(4)
    ]
    for p, want in zip(files[:3], payloads):
        with open(p, "rb") as f:
            assert f.read() == want


def test_spool_budget_shed_counted_never_enospc_death(tmp_path):
    spool = IngressSpool(
        str(tmp_path / "spool"), spool_budget_mb=10 / (1 << 20),  # 10 bytes
    )
    assert spool.seal(b"x" * 100, units=3) is None  # over budget: shed
    assert spool.stats.dropped == {"spool_over_budget": 3}
    assert spool.seal(b"ok", units=1) is not None  # within budget: sealed
    assert spool.stats.spooled == 1
    snap = spool.stats.snapshot()
    assert snap["received"] == 0  # seal-side drops don't touch received


def test_spool_io_fault_sheds_counted(tmp_path):
    spool = IngressSpool(str(tmp_path / "spool"))
    R.arm("ingress.spool", kind="enospc", times=1)
    assert spool.seal(b"doomed", units=2) is None
    assert spool.stats.dropped == {"spool_error": 2}
    assert spool.seal(b"fine", units=1) is not None
    assert not glob.glob(
        os.path.join(str(tmp_path / "spool"), "*doomed*")
    )


def test_listener_close_discards_counted(tmp_path):
    """close() without drain: ring contents are discarded but COUNTED
    (close_discard), keeping the law."""
    spool = IngressSpool(str(tmp_path / "spool"))
    lst = UdpIngressListener(spool, ring_datagrams=8, seal_datagrams=30)
    for i in range(3):
        lst._ingest(_dgram(seq=i))
    lst.start()
    lst.close()
    snap = spool.stats.snapshot()
    assert snap["dropped"] == {"close_discard": 3}
    assert snap["spooled"] == 0
    assert _law(snap)


# ---------------------------------------------------------------------------
# capture_udp (the polling exporter): durability fixes ride along
# ---------------------------------------------------------------------------


def test_capture_udp_resumes_past_existing_index(tmp_path):
    from sntc_tpu.serve.netflow_source import capture_udp

    out = tmp_path / "caps"
    out.mkdir()
    # a prior run left file 7: new captures must continue at 8, not
    # collide at len(glob) == 1
    (out / "capture_000007.nf5").write_bytes(_dgram(seq=0))
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    t = threading.Thread(
        target=capture_udp,
        args=(port, str(out), 2),
        kwargs=dict(timeout_s=5.0, datagrams_per_file=1, sock=sock),
    )
    t.start()
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    deadline = time.monotonic() + 5.0
    while t.is_alive() and time.monotonic() < deadline:
        tx.sendto(_dgram(seq=1), ("127.0.0.1", port))
        time.sleep(0.02)
    t.join(timeout=10.0)
    tx.close()
    names = sorted(os.path.basename(p) for p in out.glob("capture_*.nf5"))
    assert names[:3] == [
        "capture_000007.nf5", "capture_000008.nf5", "capture_000009.nf5",
    ]


# ---------------------------------------------------------------------------
# wiring: build_ingress, TenantSpec validation, daemon end-to-end
# ---------------------------------------------------------------------------


def test_build_ingress_requires_exactly_one_listener(tmp_path):
    with pytest.raises(ValueError, match="exactly one"):
        build_ingress(str(tmp_path / "s"))
    with pytest.raises(ValueError, match="exactly one"):
        build_ingress(str(tmp_path / "s"), listen_udp=0, listen_tcp=0)


def test_tenant_spec_ingress_validation():
    def spec(**ingress_kw):
        return TenantSpec(
            "t", model=_Identity(), watch="w/", out="o/",
            ingress=ingress_kw or None,
        )

    with pytest.raises(ValueError, match="exactly one"):
        spec(listen_udp=0, listen_tcp=0)
    with pytest.raises(ValueError, match="exactly one"):
        spec(spool_mb=8)
    with pytest.raises(ValueError):
        spec(listen_udp=0, bogus_knob=1)
    with pytest.raises(ValueError, match="watch"):
        TenantSpec(
            "t", model=_Identity(), out="o/", ingress={"listen_udp": 0},
        )
    with pytest.raises(ValueError, match="pcap"):
        TenantSpec(
            "t", model=_Identity(), watch="w/", out="o/",
            from_capture="pcap", ingress={"listen_udp": 0},
        )


def test_daemon_tcp_ingress_end_to_end(tmp_path):
    """A serve-daemon tenant with an ingress block: rows sent over a
    real TCP socket come out the tenant's sink, and close() drains the
    listener before settling the engine."""
    spool_dir = str(tmp_path / "spool")
    out_dir = str(tmp_path / "out")
    spec = TenantSpec(
        "net",
        model=_Identity(),
        watch=spool_dir,
        out=out_dir,
        out_columns=["x"],
        ingress={"listen_tcp": 0, "columns": ["x"], "seal_every": 2},
    )
    d = ServeDaemon([spec], str(tmp_path / "root"))
    try:
        assert _wait(
            lambda: (IngressSpool.read_stats(spool_dir) or {}).get(
                "tcp_port"
            )
        )
        port = IngressSpool.read_stats(spool_dir)["tcp_port"]
        c = socket.create_connection(("127.0.0.1", port))
        c.sendall(frame_rows(["5", "7"]))
        c.close()
        assert _wait(
            lambda: glob.glob(os.path.join(spool_dir, "rows_*.csv"))
        )
        assert _wait(lambda: d.process_available() >= 1)
    finally:
        d.close()
    stats = IngressSpool.read_stats(spool_dir)
    assert stats["drained"] is True
    assert stats["received"] == 2 and stats["spooled"] == 2
    batches = sorted(glob.glob(os.path.join(out_dir, "*.csv")))
    assert batches
    rows = []
    for b in batches:
        with open(b) as f:
            rows.extend(line.strip() for line in f.readlines()[1:] if line.strip())
    assert [float(r) for r in rows] == [5.0, 7.0]


# ---------------------------------------------------------------------------
# r23 regression: stats write-through near the retention horizon
# ---------------------------------------------------------------------------


def test_seal_near_horizon_writes_stats_through_throttle(tmp_path):
    """A seal landing within one file of the committed horizon must
    write ingress_stats.json THROUGH the fsync throttle: a kill inside
    the throttle window right before committed consumption removes the
    live files would otherwise leave no witness of the sealed index,
    and the restart would re-seal an index below the committed horizon
    (duplicate batch).  Regression for the throttled-stats bug."""
    spool_dir = str(tmp_path / "spool")
    committed = {"off": 0}
    sp = IngressSpool(
        spool_dir, committed_offset_fn=lambda: committed["off"],
        keep_files=1,
    )
    # park the throttle: within this test only write-THROUGHS can land
    sp.stats_interval_s = 3600.0
    sp._stats_written_at = time.monotonic()
    assert sp.seal(b"a" * 32, 1)
    committed["off"] = 1  # the engine commits file 0 immediately
    assert sp.seal(b"b" * 32, 1)
    st = IngressSpool.read_stats(spool_dir)
    assert st is not None and st["sealed_files"] == 2
    # the kill: no drain/flush — and committed consumption has pruned
    # every live capture file, so stats are the ONLY witness left
    for p in glob.glob(os.path.join(spool_dir, "capture_*.nf5")):
        os.unlink(p)
    sp2 = IngressSpool(
        spool_dir, committed_offset_fn=lambda: committed["off"],
        keep_files=1,
    )
    path = sp2.seal(b"c" * 32, 1)
    # never re-seals an index at/below the committed horizon
    assert path is not None and path.endswith("capture_000002.nf5")


# ---------------------------------------------------------------------------
# drift checker
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ingress_flags_consistent_across_layers():
    assert _load_script("check_ingress_flags").main() == 0


# ---------------------------------------------------------------------------
# chaos: kill-matrix + burst over real loopback traffic (child procs)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos():
    return _load_script("chaos_crash_matrix")


@pytest.fixture(scope="module")
def ingress_reference(chaos, tmp_path_factory):
    return chaos.run_ingress_reference(
        str(tmp_path_factory.mktemp("ingress_ref"))
    )


def test_chaos_ingress_spool_kill_bitwise(
    chaos, ingress_reference, tmp_path
):
    """SIGKILL inside the seal (before the atomic publish), restart,
    resend-until-sealed: committed state and sink bytes converge
    bitwise with the uninterrupted reference, sent == committed +
    journaled_drops exactly, and the final epoch satisfies the law."""
    v = chaos.run_ingress_kill_scenario(
        str(tmp_path), "ingress.spool", ingress_reference
    )
    assert v["ok"], v


@pytest.mark.slow
def test_chaos_ingress_recv_kill_bitwise(
    chaos, ingress_reference, tmp_path
):
    v = chaos.run_ingress_kill_scenario(
        str(tmp_path), "ingress.recv", ingress_reference
    )
    assert v["ok"], v


def test_chaos_ingress_burst_shed_ladder(chaos, tmp_path):
    """Flood a 4-slot ring through a slowed spool: the worker survives
    the burst (no OOM, exit 0 on drain), sheds are counted
    ring_overflow, and the law holds exactly over 150 datagrams."""
    v = chaos.run_ingress_burst_scenario(str(tmp_path))
    assert v["ok"], v
    assert v["dropped"].get("ring_overflow", 0) > 0
    assert v["law_exact"], v
