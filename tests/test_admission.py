"""Data-plane admission (r10): SchemaContract modes, clean_flows policy
unity, parser salvage with file+line attribution, the source.parse
fault grammar (DATA kinds), row-level dead-letter accounting, salvage ×
shape buckets × fusion bitwise parity with a flat compile ledger, and
the corrupt-corpus chaos harness in tier-1."""

import glob
import importlib.util
import json
import os

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Pipeline, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.data.ingest import clean_flows, load_csv, load_csv_dir
from sntc_tpu.data.schema import (
    CICIDS2017_CONTRACT,
    CICIDS2017_FEATURES,
    ColumnSpec,
    SchemaContract,
    SchemaViolation,
)
from sntc_tpu.data.synth import generate_frame
from sntc_tpu.feature import MinMaxScaler, VectorAssembler
from sntc_tpu.models import LogisticRegression, NaiveBayes
from sntc_tpu.resilience import HealthMonitor, HealthState
from sntc_tpu.serve.streaming import (
    FileStreamSource,
    MemorySink,
    MemorySource,
    StreamingQuery,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    yield
    R.clear()
    R.clear_events()


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Identity(Transformer):
    def transform(self, frame):
        return frame


# ---------------------------------------------------------------------------
# SchemaContract unit behavior
# ---------------------------------------------------------------------------


def _xy_contract(**kw):
    return SchemaContract(
        {"x": ColumnSpec(fill=0.0), "y": ColumnSpec(fill=0.0)}, **kw
    )


def test_strict_raises_with_reasons():
    f = Frame({"x": np.array([1.0, np.nan]), "y": np.array([1.0, 2.0])})
    with pytest.raises(SchemaViolation) as ei:
        _xy_contract().admit(f, mode="strict")
    assert ei.value.reasons == [
        {"column": "x", "reason": "non_finite", "count": 1}
    ]


def test_salvage_masks_and_sanitizes():
    f = Frame({
        "x": np.array([1.0, np.nan, 3.0, np.inf]),
        "y": np.array([1.0, 2.0, 3.0, 4.0]),
    })
    res = _xy_contract().admit(f, mode="salvage")
    np.testing.assert_array_equal(
        res.valid, [True, False, True, False]
    )
    # shape preserved; excised rows hold finite donor copies
    assert res.frame.num_rows == 4
    assert np.isfinite(res.frame["x"]).all()
    assert res.frame["x"].dtype == np.float32
    assert [r["row"] for r in res.rejects] == [1, 3]
    assert {r["reason"] for r in res.rejects} == {"non_finite"}


def test_permissive_coerces_then_salvages():
    f = Frame({
        "x": np.array(["1.5", "junk", "inf"], dtype=object),
        "y": np.array([np.nan, 2.0, -1.0]),
    })
    c = SchemaContract({
        "x": ColumnSpec(fill=0.0),
        "y": ColumnSpec(fill=0.0, min_value=0.0),
    })
    res = c.admit(f, mode="permissive")
    # "1.5" parses, "junk" takes the fill, "inf" is non-finite -> fill;
    # y NaN takes the fill, y=-1 is out of range -> row poison
    np.testing.assert_array_equal(res.valid, [True, True, False])
    np.testing.assert_array_equal(
        res.frame["x"][:2], np.array([1.5, 0.0], np.float32)
    )
    assert res.rejects[0]["reason"] == "out_of_range"
    assert res.coerced > 0


def test_range_domain_and_missing_column():
    c = SchemaContract({
        "x": ColumnSpec(min_value=0.0, max_value=10.0),
        "tag": ColumnSpec(dtype="str", domain=("a", "b")),
    })
    f = Frame({
        "x": np.array([5.0, 11.0, 2.0]),
        "tag": np.array(["a", "b", "z"], dtype=object),
    })
    res = c.admit(f, mode="salvage")
    np.testing.assert_array_equal(res.valid, [True, False, False])
    assert {r["reason"] for r in res.rejects} == {
        "out_of_range", "out_of_domain",
    }
    with pytest.raises(SchemaViolation) as ei:
        c.admit(Frame({"x": np.array([1.0])}), mode="salvage")
    assert ei.value.reasons[0]["reason"] == "missing_column"


def test_with_mode_and_validation():
    c = _xy_contract(mode="salvage")
    assert c.with_mode("salvage") is c
    assert c.with_mode("strict").mode == "strict"
    assert c.columns is c.with_mode("strict").columns
    with pytest.raises(ValueError):
        SchemaContract({"x": ColumnSpec()}, mode="wat")


def test_fill_invalid_rows_donor_semantics():
    f = Frame({
        "x": np.array([9.0, 1.0, 2.0, 3.0]),
        "v": np.arange(8.0).reshape(4, 2),
        "s": np.array(["a", "b", "c", "d"], dtype=object),
    })
    out = f.fill_invalid_rows(np.array([False, True, False, True]))
    # leading invalid row borrows the FIRST valid row; later ones the
    # nearest preceding valid row
    np.testing.assert_array_equal(out["x"], [1.0, 1.0, 1.0, 3.0])
    np.testing.assert_array_equal(out["v"][2], out["v"][1])
    assert list(out["s"]) == ["b", "b", "b", "d"]
    # no valid rows: zero/empty fill, shape kept
    out = f.fill_invalid_rows(np.zeros(4, bool))
    assert out.num_rows == 4 and (np.asarray(out["x"]) == 0).all()
    with pytest.raises(ValueError):
        f.fill_invalid_rows(np.ones(3, bool))


# ---------------------------------------------------------------------------
# clean_flows <-> CICIDS2017_CONTRACT policy unity (satellite)
# ---------------------------------------------------------------------------


def test_clean_flows_drop_equals_contract_salvage():
    f = generate_frame(1500, seed=4, dirty=True)
    dropped = clean_flows(f)  # handle_invalid="drop"
    res = CICIDS2017_CONTRACT.admit(f, mode="salvage")
    salvaged = res.frame.filter(res.valid)
    assert salvaged.num_rows == dropped.num_rows < f.num_rows
    for c in CICIDS2017_FEATURES:
        np.testing.assert_array_equal(
            salvaged[c], dropped[c], err_msg=c
        )


def test_clean_flows_zero_equals_contract_permissive():
    f = generate_frame(1500, seed=5, dirty=True)
    zeroed = clean_flows(f, handle_invalid="zero")
    res = CICIDS2017_CONTRACT.admit(f, mode="permissive")
    assert res.valid.all()  # fill=0.0 repairs every non-finite cell
    assert res.coerced > 0
    for c in CICIDS2017_FEATURES:
        np.testing.assert_array_equal(
            res.frame[c], zeroed[c], err_msg=c
        )


# ---------------------------------------------------------------------------
# CSV parser: file+line attribution and per-line salvage (satellite)
# ---------------------------------------------------------------------------


def _ragged_fixture(tmp_path, name="day.csv"):
    p = tmp_path / name
    p.write_text("x,y\n1.0,2.0\n3.0,4.0,5.0\n6.0,7.0\n")
    return str(p)


def test_load_csv_error_names_file_and_line(tmp_path):
    p = _ragged_fixture(tmp_path)
    with pytest.raises(ValueError) as ei:
        load_csv(p)
    msg = str(ei.value)
    assert p in msg and "line 3" in msg and "3,4,5" in msg.replace(
        "3.0,4.0,5.0", "3,4,5"
    )


def test_load_csv_dir_error_names_offending_file(tmp_path):
    d = tmp_path / "days"
    d.mkdir()
    (d / "a.csv").write_text("x,y\n1.0,2.0\n")
    bad = _ragged_fixture(d, name="b.csv")
    with pytest.raises(ValueError) as ei:
        load_csv_dir(str(d))
    assert bad in str(ei.value) and "line 3" in str(ei.value)


def test_load_csv_salvage_excises_with_location(tmp_path):
    p = _ragged_fixture(tmp_path)
    rejects = []
    f = load_csv(p, salvage=True, rejects=rejects)
    assert f.num_rows == 2
    np.testing.assert_array_equal(f["x"], [1.0, 6.0])
    assert rejects == [{
        "file": p, "line": 3, "raw": "3.0,4.0,5.0",
        "reason": "ragged_row", "detail": "3 fields, expected 2",
    }]


def test_pcap_truncation_emits_event():
    from sntc_tpu.native.pcap import (
        make_packet, make_pcap, parse_pcap, scan_truncation,
    )

    cap = make_pcap(
        [(1.0 + i, make_packet(1, 2, 10, 20, payload=40))
         for i in range(4)]
    )
    clean_len, dropped = scan_truncation(cap[:-10])
    assert dropped == (len(cap) - 10) - clean_len > 0
    got = parse_pcap(cap[:-10])
    assert got.shape[0] == 3  # valid prefix
    np.testing.assert_array_equal(got, parse_pcap(cap)[:3])
    ev = [e for e in R.recent_events()
          if e.get("event") == "parse_truncated"]
    assert ev and ev[-1]["format"] == "pcap"


# ---------------------------------------------------------------------------
# SNTC_FAULTS grammar: DATA kinds + fault_data
# ---------------------------------------------------------------------------


def test_grammar_accepts_data_kinds():
    specs = R.parse_faults_env(
        "source.parse:ragged:0.5:7,source.parse:corrupt_bytes,"
        "stream.read:exc"
    )
    assert specs[0] == {
        "site": "source.parse", "kind": "ragged", "prob": 0.5, "seed": 7,
    }
    assert specs[1]["kind"] == "corrupt_bytes"
    with pytest.raises(ValueError, match="unknown kind"):
        R.parse_faults_env("source.parse:shred")


def test_fault_data_deterministic_and_kind_scoped():
    payload = b"x,y\n1,2\n3,4\n5,6\n"
    R.arm("source.parse", kind="ragged", times=None)
    a = R.fault_data("source.parse", payload)
    assert a != payload and b"__sntc_ragged__" in a
    # header (line 0) is never the spliced line
    assert a.split(b"\n")[0] == b"x,y"
    R.arm("source.parse", kind="ragged", times=None)
    assert R.fault_data("source.parse", payload) == a  # same seed+call
    # truncate strictly shortens; corrupt_bytes preserves length
    R.arm("source.parse", kind="truncate", times=None)
    assert len(R.fault_data("source.parse", payload)) < len(payload)
    R.arm("source.parse", kind="corrupt_bytes", times=None)
    mutated = R.fault_data("source.parse", payload)
    assert len(mutated) == len(payload) and mutated != payload
    # a DATA kind is inert at a plain fault_point, and vice versa
    R.arm("source.parse", kind="ragged", times=None)
    R.fault_point("source.parse")  # must not raise
    R.arm("source.parse", kind="exc", times=None)
    assert R.fault_data("source.parse", payload) == payload


# ---------------------------------------------------------------------------
# engine admission: dead-letter accounting, events, health
# ---------------------------------------------------------------------------


def _poison_frames():
    return [
        Frame({"x": np.array([1.0, 2.0, np.nan, 4.0])}),
        Frame({"x": np.array([5.0, np.inf, 7.0, 8.0])}),
    ]


def test_engine_salvage_dead_letters_rows(tmp_path):
    contract = SchemaContract({"x": ColumnSpec()}, mode="salvage")
    monitor = HealthMonitor().attach()
    try:
        sink = MemorySink()
        q = StreamingQuery(
            _Identity(), MemorySource(_poison_frames()), sink,
            str(tmp_path / "ckpt"), max_batch_offsets=1,
            schema_contract=contract,
        )
        assert q.process_available() == 2
    finally:
        monitor.detach()
    np.testing.assert_array_equal(sink.frames[0]["x"], [1.0, 2.0, 4.0])
    np.testing.assert_array_equal(sink.frames[1]["x"], [5.0, 7.0, 8.0])
    rows = []
    for p in sorted(
        glob.glob(str(tmp_path / "ckpt" / "dead_letter_rows" / "*.jsonl"))
    ):
        with open(p) as f:
            rows += [json.loads(line) for line in f]
    assert len(rows) == 2
    assert rows[0]["batch_id"] == 0 and rows[0]["row"] == 2
    assert rows[0]["reason"] == "non_finite" and rows[0]["column"] == "x"
    assert rows[0]["raw"]  # best-effort raw rendering present
    stats = q.admission_stats()
    assert stats["rows_rejected"] == 2
    assert stats["batches_salvaged"] == 2
    events = [e for e in R.recent_events()
              if e.get("event") == "rows_rejected"]
    assert [e["count"] for e in events] == [1, 1]
    # rising rejects mark the SOURCE degraded through the event stream
    assert monitor.state_of("source.parse") == HealthState.DEGRADED


def test_engine_strict_mode_quarantines_batch(tmp_path):
    contract = SchemaContract({"x": ColumnSpec()})
    q = StreamingQuery(
        _Identity(), MemorySource(_poison_frames()), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        schema_contract=contract, row_policy="strict",
        max_batch_failures=1,
    )
    assert q.process_available() == 2
    assert all(p.get("quarantined") for p in q.recentProgress)
    # batch-level dead letter, not row-level
    assert os.path.isdir(str(tmp_path / "ckpt" / "dead_letter"))
    assert not os.path.isdir(str(tmp_path / "ckpt" / "dead_letter_rows"))


def test_row_policy_requires_contract(tmp_path):
    with pytest.raises(ValueError, match="schema_contract"):
        StreamingQuery(
            _Identity(), MemorySource([]), MemorySink(),
            str(tmp_path / "ckpt"), row_policy="salvage",
        )


def test_file_source_parse_salvage_attributes_file_and_line(tmp_path):
    watch = tmp_path / "in"
    watch.mkdir()
    (watch / "a.csv").write_text("x\n1.0\nbad,row\n3.0\n")
    contract = SchemaContract({"x": ColumnSpec()}, mode="salvage")
    sink = MemorySink()
    q = StreamingQuery(
        _Identity(),
        FileStreamSource(str(watch), parse_salvage=True),
        sink, str(tmp_path / "ckpt"),
        schema_contract=contract,
    )
    assert q.process_available() == 1
    np.testing.assert_array_equal(sink.frames[0]["x"], [1.0, 3.0])
    rows = []
    for p in glob.glob(
        str(tmp_path / "ckpt" / "dead_letter_rows" / "*.jsonl")
    ):
        with open(p) as f:
            rows += [json.loads(line) for line in f]
    assert len(rows) == 1
    assert rows[0]["file"].endswith("a.csv")
    assert rows[0]["line"] == 3 and rows[0]["raw"] == "bad,row"
    assert rows[0]["reason"] == "ragged_row"


def test_take_rejects_is_file_scoped(tmp_path):
    """A prefetch thread may parse (and reject lines from) a FUTURE
    batch's file before the current batch drains — the drain must only
    take the current batch's files' records and leave the rest."""
    watch = tmp_path / "in"
    watch.mkdir()
    (watch / "a.csv").write_text("x\n1.0\nbad,a\n")
    (watch / "b.csv").write_text("x\n2.0\nbad,b\n")
    src = FileStreamSource(str(watch), parse_salvage=True)
    src.latest_offset()
    src.get_batch(0, 2)  # parses both files, collects both rejects
    a = str(watch / "a.csv")
    b = str(watch / "b.csv")
    got = src.take_rejects([a])
    assert [r["file"] for r in got] == [a]
    got = src.take_rejects([b])
    assert [r["file"] for r in got] == [b]
    assert src.take_rejects() == []


def test_dead_letter_journal_merges_never_shrinks(tmp_path):
    """A rewrite of a batch's row journal (deferred-batch retry round,
    WAL replay) must merge with the prior records, never drop them."""
    contract = SchemaContract({"x": ColumnSpec()}, mode="salvage")
    q = StreamingQuery(
        _Identity(), MemorySource(_poison_frames()), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        schema_contract=contract,
    )
    stray = {"file": "elsewhere.csv", "line": 9, "raw": "bad",
             "reason": "ragged_row"}
    q._journal_rejected_rows(0, {"start": 0, "end": 1}, [stray], [])
    q._journal_rejected_rows(
        0, {"start": 0, "end": 1},
        [{"row": 2, "column": "x", "reason": "non_finite",
          "value": "nan", "raw": "nan"}], [],
    )
    p = tmp_path / "ckpt" / "dead_letter_rows" / "batch_000000.jsonl"
    recs = [json.loads(line) for line in p.read_text().splitlines()]
    assert len(recs) == 2  # the stray record survived the rewrite
    assert {r["reason"] for r in recs} == {"ragged_row", "non_finite"}


def test_coerced_counts_only_permissive_repairs():
    f = Frame({"x": np.array(["1.5", "2.5"], dtype=object),
               "y": np.array([1.0, 2.0])})
    c = _xy_contract()
    assert c.admit(f, mode="salvage").coerced == 0  # reading ≠ repair
    assert c.admit(f, mode="permissive").coerced == 2


def test_admit_shares_clean_columns():
    x = np.array([1.0, 2.0], np.float32)
    f = Frame({"x": x, "y": np.array([3.0, 4.0], np.float32)})
    res = _xy_contract().admit(f, mode="salvage")
    assert res.valid.all()
    assert res.frame["x"] is x  # clean column: zero copies, shared


# ---------------------------------------------------------------------------
# salvage × shape buckets × fusion: bitwise parity + flat compile ledger
# ---------------------------------------------------------------------------

D = 4


def _serve_pipeline(mesh, head_name):
    head = {
        "lr": LogisticRegression(mesh=mesh, featuresCol="scaled",
                                 maxIter=25),
        "nb": NaiveBayes(mesh=mesh, featuresCol="scaled",
                         modelType="multinomial"),
    }[head_name]
    rng = np.random.default_rng(0)
    X = np.abs(rng.normal(3.0, 2.0, size=(400, D))).astype(np.float32)
    train = Frame(
        {f"c{i}": X[:, i].copy() for i in range(D)}
        | {"label": (X[:, 0] > 3.0).astype(np.float64)}
    )
    pipe = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(D)],
                        outputCol="features"),
        MinMaxScaler(inputCol="features", outputCol="scaled"),
        head,
    ])
    return pipe.fit(train)


def _stream_frames(n_batches=3, rows=8, seed=9):
    """Per batch: (poisoned frame, valid mask). Poison = NaN in c1."""
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        X = np.abs(rng.normal(3.0, 2.0, size=(rows, D))).astype(np.float32)
        cols = {f"c{i}": X[:, i].copy() for i in range(D)}
        frame = Frame(cols)
        valid = np.ones(rows, bool)
        for r in rng.choice(rows, size=2, replace=False):
            cols["c1"][r] = np.nan
            valid[r] = False
        out.append((Frame(dict(cols)), valid))
    return out


@pytest.mark.parametrize("head_name", ["lr", "nb"])
def test_salvage_buckets_fusion_bitwise_flat_compiles(
    tmp_path, mesh8, head_name, monkeypatch
):
    """The acceptance contract: with shape buckets AND fusion on, row
    salvage yields sink output bitwise-equal (for the surviving rows)
    to serving the pre-cleaned stream, and the compile ledgers stay
    FLAT — excision never changes a dispatched shape."""
    from sntc_tpu.fuse import compile_pipeline, fusion_stats

    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")
    model = compile_pipeline(_serve_pipeline(mesh8, head_name))
    assert fusion_stats(model)["segments"] >= 1
    batches = _stream_frames()
    contract = SchemaContract(
        {f"c{i}": ColumnSpec() for i in range(D)}, mode="salvage"
    )

    def _run(frames, ckpt, with_contract):
        sink = MemorySink()
        q = StreamingQuery(
            model, MemorySource(frames), sink, str(tmp_path / ckpt),
            max_batch_offsets=1, shape_buckets=8,
            schema_contract=contract if with_contract else None,
        )
        assert q.process_available() == len(frames)
        return q, sink

    q_ref, sink_ref = _run(
        [f.filter(v) for f, v in batches], "ref", False
    )
    # the fused segments (and their compile ledgers) are SHARED by both
    # queries — the salvage run must add zero new program signatures
    fused_compiles_after_ref = fusion_stats(model)["compile_events"]
    q_sal, sink_sal = _run([f for f, _ in batches], "salvage", True)

    for (_, ref), (_, got) in zip(sink_ref.batches, sink_sal.batches):
        assert got.num_rows == ref.num_rows
        for c in ("rawPrediction", "probability", "prediction"):
            if c in ref and c in got:
                np.testing.assert_array_equal(
                    np.asarray(got[c]), np.asarray(ref[c]), err_msg=c
                )
    # salvage never changed a dispatched shape: every batch is 8 rows
    # -> ONE bucket -> one predictor compile event, and zero NEW fused
    # program signatures beyond the reference run's
    assert q_sal.predictor.compile_events == 1
    assert q_sal.pipeline_stats()["compile_events"] == 1
    assert (
        fusion_stats(model)["compile_events"] == fused_compiles_after_ref
    )
    assert q_sal.admission_stats()["rows_rejected"] == 6


# ---------------------------------------------------------------------------
# corrupt-corpus chaos in tier-1 (satellite)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos():
    return _load_script("chaos_corrupt_corpus")


def test_chaos_corrupt_csv_exact_accounting(chaos, tmp_path):
    verdict = chaos.scenario_csv_salvage(
        str(tmp_path), n_files=3, rows=8, n_corrupt=5, seed=0
    )
    assert verdict["ok"], verdict
    assert verdict["dead_letter_rows"] == 5
    assert verdict["sink_match"] and verdict["compile_events"] == 1


def test_chaos_fault_kind_conservation(chaos, tmp_path):
    verdict = chaos.scenario_csv_fault_kinds(
        str(tmp_path), n_files=4, rows=8, seed=7
    )
    assert verdict["ok"], verdict
    assert (
        verdict["sink_rows"] + verdict["dead_letter_rows"]
        == verdict["reference_rows"]
    )


def test_chaos_binary_captures(chaos, tmp_path):
    pcap = chaos.scenario_pcap(str(tmp_path), seed=3)
    assert pcap["ok"], pcap
    nf = chaos.scenario_netflow(str(tmp_path), seed=5)
    assert nf["ok"], nf
    assert nf["torn_rows"] == nf["expected_torn_rows"]
