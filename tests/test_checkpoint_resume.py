"""Fault injection (SURVEY.md §5.3): interrupt a fit, resume from the
mid-fit checkpoint, and assert the trajectory is identical to an
uninterrupted run — the restart-from-checkpoint recovery model that
replaces Spark's lineage recomputation."""

import os

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio.optimizer_checkpoint import load_state
from sntc_tpu.models import (
    GBTClassifier,
    LogisticRegression,
    MultilayerPerceptronClassifier,
)


def _data(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] ** 2 + 0.3 * rng.normal(size=n) > 0.5).astype(
        np.float64
    )
    return Frame({"features": X, "label": y})


def test_lr_interrupted_fit_resumes_bit_identical(mesh8, tmp_path):
    f = _data()
    ckpt = str(tmp_path / "lr_ckpt")
    common = dict(mesh=mesh8, regParam=1e-3, tol=1e-12)

    # uninterrupted run: 40 iterations
    full = LogisticRegression(maxIter=40, **common).fit(f)

    # "crashed" run: same config, but stop after 15 iterations by lying
    # about maxIter... instead simulate the crash by checkpointing every 5
    # and fitting with maxIter=15 (fingerprint uses maxIter, so keep 40 and
    # interrupt via a small interval + an induced exception-free partial run)
    # -> the honest simulation: run maxIter=40 with interval 15, capture the
    # state file mid-flight via a monkeypatched save that aborts after the
    # first segment.
    from sntc_tpu.mlio import optimizer_checkpoint as oc

    calls = {"n": 0}
    orig_save = oc.save_state

    class Boom(RuntimeError):
        pass

    def crashing_save(ckpt_dir, state, fp):
        orig_save(ckpt_dir, state, fp)
        calls["n"] += 1
        if calls["n"] == 1:
            raise Boom("injected crash after first checkpoint")

    oc.save_state = crashing_save
    try:
        with pytest.raises(Boom):
            LogisticRegression(
                maxIter=40, checkpointInterval=15, checkpointDir=ckpt, **common
            ).fit(f)
    finally:
        oc.save_state = orig_save

    # state survived the crash at iteration 15
    state = load_state(
        ckpt,
        fingerprint={
            "algo": "logistic_regression", "n_coef": 6, "n_int": 1,
            "num_classes": 2, "binomial": True, "regParam": 1e-3,
            "elasticNetParam": 0.0, "maxIter": 40, "tol": 1e-12,
            "standardization": True, "n_rows": 1500, "bounds": None,
        },
    )
    assert state is not None and 0 < int(state["k"]) <= 15

    # resume: same estimator config, same checkpoint dir
    resumed = LogisticRegression(
        maxIter=40, checkpointInterval=15, checkpointDir=ckpt, **common
    ).fit(f)

    np.testing.assert_array_equal(resumed.coefficients, full.coefficients)
    assert resumed.intercept == full.intercept
    # objective trajectory continuity: identical history
    np.testing.assert_array_equal(
        resumed.summary.objectiveHistory, full.summary.objectiveHistory
    )
    # checkpoint cleaned up after successful completion
    assert not os.path.exists(os.path.join(ckpt, "lbfgs_state.npz"))


def test_lr_stale_fingerprint_ignored(mesh8, tmp_path):
    f = _data(seed=1)
    ckpt = str(tmp_path / "ckpt")
    LogisticRegression(
        mesh=mesh8, maxIter=10, checkpointInterval=4, checkpointDir=ckpt
    ).fit(f)
    # different hyperparams -> old state must not be resumed
    m = LogisticRegression(
        mesh=mesh8, maxIter=10, regParam=0.5, checkpointInterval=4,
        checkpointDir=ckpt,
    ).fit(f)
    ref = LogisticRegression(mesh=mesh8, maxIter=10, regParam=0.5).fit(f)
    np.testing.assert_array_equal(m.coefficients, ref.coefficients)


def test_mlp_checkpointed_equals_straight(mesh8, tmp_path):
    f = _data(seed=2)
    kw = dict(mesh=mesh8, layers=[6, 8, 2], seed=4, tol=1e-12)
    full = MultilayerPerceptronClassifier(maxIter=30, **kw).fit(f)
    seg = MultilayerPerceptronClassifier(
        maxIter=30, checkpointInterval=7,
        checkpointDir=str(tmp_path / "mlp"), **kw,
    ).fit(f)
    np.testing.assert_array_equal(seg.weights, full.weights)


def test_gbt_resume_skips_completed_rounds(mesh8, tmp_path):
    f = _data(seed=3)
    ckpt = str(tmp_path / "gbt")
    kw = dict(mesh=mesh8, maxDepth=3, stepSize=0.3, seed=1)
    full = GBTClassifier(maxIter=8, **kw).fit(f)

    from sntc_tpu.mlio import optimizer_checkpoint as oc

    orig_save = oc.save_state
    calls = {"n": 0}

    class Boom(RuntimeError):
        pass

    def crashing_save(ckpt_dir, state, fp):
        orig_save(ckpt_dir, state, fp)
        calls["n"] += 1
        if calls["n"] == 2:  # crash after round 4's checkpoint
            raise Boom()

    oc.save_state = crashing_save
    try:
        with pytest.raises(Boom):
            GBTClassifier(
                maxIter=8, checkpointInterval=2, checkpointDir=ckpt, **kw
            ).fit(f)
    finally:
        oc.save_state = orig_save

    resumed = GBTClassifier(
        maxIter=8, checkpointInterval=2, checkpointDir=ckpt, **kw
    ).fit(f)
    np.testing.assert_array_equal(resumed.forest.feature, full.forest.feature)
    np.testing.assert_allclose(
        resumed.forest.leaf_stats, full.forest.leaf_stats, rtol=1e-6
    )
    np.testing.assert_array_equal(
        resumed.transform(f)["prediction"], full.transform(f)["prediction"]
    )


def test_gbt_regressor_round_checkpoint_resume(tmp_path, mesh8):
    """A CRASH mid-boosting resumes from the last saved round (only the
    missing rounds are grown) and matches an uninterrupted fit; a
    completed fit clears its checkpoint so a rerun regrows from scratch."""
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.models import GBTRegressor
    import sntc_tpu.models.tree.gbt_regressor as gbr

    rng = np.random.default_rng(23)
    X = rng.uniform(-2, 2, size=(1500, 4)).astype(np.float32)
    y = (X[:, 0] ** 2 + X[:, 2] + 0.1 * rng.normal(size=1500)).astype(
        np.float32
    )
    f = Frame({"features": X, "label": y})
    ck = str(tmp_path / "gbt_reg_ck")
    kw = dict(
        mesh=mesh8, maxIter=6, maxDepth=3, stepSize=0.3, maxBins=32, seed=0,
        checkpointDir=ck, checkpointInterval=2,
    )
    calls = []
    orig = gbr.grow_forest

    class Boom(RuntimeError):
        pass

    def crashing(*a, **k):
        calls.append(1)
        if len(calls) > 4:  # crash after round 4 (checkpoint at round 4)
            raise Boom()
        return orig(*a, **k)

    gbr.grow_forest = crashing
    try:
        with pytest.raises(Boom):
            GBTRegressor(**kw).fit(f)
    finally:
        gbr.grow_forest = orig

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    calls.clear()
    gbr.grow_forest = counting
    try:
        resumed = GBTRegressor(**kw).fit(f)  # only rounds 5-6 grow
        n_resumed = len(calls)
        calls.clear()
        rerun = GBTRegressor(**kw).fit(f)  # checkpoint cleared: full fit
        n_rerun = len(calls)
    finally:
        gbr.grow_forest = orig
    assert n_resumed == 2, n_resumed
    assert n_rerun == 6, n_rerun
    full = GBTRegressor(
        mesh=mesh8, maxIter=6, maxDepth=3, stepSize=0.3, maxBins=32, seed=0
    ).fit(f)
    np.testing.assert_allclose(resumed.forest.feature, full.forest.feature)
    np.testing.assert_allclose(
        np.asarray(resumed.transform(f)["prediction"]),
        np.asarray(full.transform(f)["prediction"]),
        atol=1e-5,
    )
