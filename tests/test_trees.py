"""Tree oracle tests (SURVEY.md §4.2): split-for-split vs sklearn on tiny
data with bins forced equal; behavioral (accuracy/AUC) parity on blobs."""

import numpy as np
import pytest
from sklearn.ensemble import GradientBoostingClassifier as SkGBT
from sklearn.tree import DecisionTreeClassifier as SkTree

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import (
    GBTClassifier,
    OneVsRest,
    RandomForestClassifier,
)
from sntc_tpu.models.tree.grower import resolve_feature_subset_k


def _blobs(n=4000, k=3, d=6, seed=0, scale=2.5):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * scale
    y = rng.integers(0, k, size=n)
    X = (centers[y] + rng.normal(size=(n, d))).astype(np.float32)
    return Frame({"features": X, "label": y.astype(np.float64)}), X, y


def test_feature_subset_strategy_resolution():
    assert resolve_feature_subset_k("auto", 78, 20, True) == 9  # ceil(sqrt(78))
    assert resolve_feature_subset_k("auto", 78, 1, True) == 78
    assert resolve_feature_subset_k("auto", 78, 20, False) == 26
    assert resolve_feature_subset_k("all", 78, 20, True) == 78
    assert resolve_feature_subset_k("log2", 78, 20, True) == 6
    assert resolve_feature_subset_k("0.5", 78, 20, True) == 39
    assert resolve_feature_subset_k(10, 78, 20, True) == 10
    with pytest.raises(ValueError):
        resolve_feature_subset_k("bogus", 78, 20, True)


def test_single_tree_matches_sklearn_splits(mesh8):
    """One tree, all features, no bagging, fine bins -> same structure as a
    depth-2 sklearn tree on well-separated data."""
    f, X, y = _blobs(n=800, k=2, d=3, seed=1, scale=4.0)
    rf = RandomForestClassifier(
        mesh=mesh8, numTrees=1, maxDepth=2, maxBins=128, bootstrap=False,
        featureSubsetStrategy="all", seed=0,
    ).fit(f)
    sk = SkTree(max_depth=2, criterion="gini").fit(X, y)
    # root split feature must agree
    assert rf.forest.feature[0, 0] == sk.tree_.feature[0]
    # both thresholds cut in the same inter-cluster gap: the row partitions
    # agree (exact threshold placement inside an empty gap is arbitrary)
    ours_left = X[:, rf.forest.feature[0, 0]] < rf.forest.threshold[0, 0]
    sk_left = X[:, sk.tree_.feature[0]] <= sk.tree_.threshold[0]
    assert (ours_left == sk_left).mean() > 0.99
    out = rf.transform(f)
    sk_acc = (sk.predict(X) == y).mean()
    our_acc = (out["prediction"] == y).mean()
    assert abs(our_acc - sk_acc) < 0.02


def test_rf_multiclass_accuracy(mesh8):
    f, X, y = _blobs(n=5000, k=4, d=8, seed=2)
    rf = RandomForestClassifier(
        mesh=mesh8, numTrees=10, maxDepth=5, seed=3
    ).fit(f)
    out = rf.transform(f)
    assert (out["prediction"] == y).mean() > 0.93
    prob = out["probability"]
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, rtol=1e-5)
    raw = out["rawPrediction"]
    # raw = summed per-tree votes: rows sum to numTrees
    np.testing.assert_allclose(raw.sum(axis=1), 10.0, rtol=1e-4)


def test_rf_determinism_and_bagging_variation(mesh8):
    f, X, y = _blobs(n=1000, k=3, seed=4)
    kw = dict(mesh=mesh8, numTrees=5, maxDepth=3, seed=9)
    m1 = RandomForestClassifier(**kw).fit(f)
    m2 = RandomForestClassifier(**kw).fit(f)
    np.testing.assert_array_equal(m1.forest.feature, m2.forest.feature)
    # bootstrap trees differ from each other (bagging works)
    assert not np.array_equal(m1.forest.feature[0], m1.forest.feature[1])


def test_min_instances_and_gain_pruning(mesh8):
    f, X, y = _blobs(n=300, k=2, d=3, seed=5)
    deep = RandomForestClassifier(
        mesh=mesh8, numTrees=1, maxDepth=6, bootstrap=False,
        featureSubsetStrategy="all", minInstancesPerNode=100, seed=0,
    ).fit(f)
    # severe min-instances -> shallow effective tree: most slots never created
    created = (deep.forest.feature[0] != -2).sum()
    assert created < 15


def test_gbt_binary_beats_baseline_and_matches_sklearn_behaviorally(mesh8):
    f, X, y = _blobs(n=3000, k=2, d=6, seed=6, scale=1.5)
    gbt = GBTClassifier(
        mesh=mesh8, maxIter=15, maxDepth=3, stepSize=0.3, seed=1
    ).fit(f)
    out = gbt.transform(f)
    our_acc = (out["prediction"] == y).mean()
    sk = SkGBT(n_estimators=15, max_depth=3, learning_rate=0.3).fit(X, y)
    sk_acc = (sk.predict(X) == y).mean()
    assert our_acc > 0.93
    assert abs(our_acc - sk_acc) < 0.03
    prob = out["probability"]
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, rtol=1e-5)


def test_gbt_rejects_multiclass(mesh8):
    f, _, _ = _blobs(n=200, k=3)
    with pytest.raises(ValueError, match="binary-only"):
        GBTClassifier(mesh=mesh8, maxIter=2).fit(f)


def test_ovr_gbt_multiclass(mesh8):
    f, X, y = _blobs(n=2500, k=3, d=6, seed=7)
    ovr = OneVsRest(
        classifier=GBTClassifier(mesh=mesh8, maxIter=8, maxDepth=3, stepSize=0.3),
    ).fit(f)
    out = ovr.transform(f)
    assert out["rawPrediction"].shape == (2500, 3)
    assert (out["prediction"] == y).mean() > 0.9


def test_feature_importances(mesh8):
    """Signal features dominate importances (Spark gain*count semantics)."""
    rng = np.random.default_rng(11)
    n = 3000
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = ((X[:, 2] > 0) ^ (X[:, 5] > 0.5)).astype(np.float64)
    f = Frame({"features": X, "label": y})
    rf = RandomForestClassifier(
        mesh=mesh8, numTrees=8, maxDepth=4, seed=0,
        featureSubsetStrategy="all", bootstrap=False,
    ).fit(f)
    imp = rf.featureImportances
    assert imp.shape == (8,)
    assert imp.sum() == pytest.approx(1.0)
    assert set(np.argsort(imp)[-2:]) == {2, 5}

    gbt = GBTClassifier(mesh=mesh8, maxIter=6, maxDepth=3, seed=0).fit(f)
    gimp = gbt.featureImportances
    # full training width even if some features are never split on
    assert gimp.shape == (8,)
    assert gimp.sum() == pytest.approx(1.0)
    assert set(np.argsort(gimp)[-2:]) == {2, 5}


def test_feature_importances_unavailable_without_stats():
    from sntc_tpu.models.tree.grower import Forest

    forest = Forest(
        feature=np.array([[0, -1, -1]], np.int32),
        threshold=np.zeros((1, 3), np.float32),
        leaf_stats=np.zeros((1, 3, 2), np.float32),
        max_depth=1,
    )
    with pytest.raises(ValueError, match="without per-node split"):
        forest.feature_importances(4)


def test_tree_models_save_load(tmp_path, mesh8):
    f, X, y = _blobs(n=600, k=3, seed=8)
    rf = RandomForestClassifier(mesh=mesh8, numTrees=3, maxDepth=3, seed=0).fit(f)
    save_model(rf, str(tmp_path / "rf"))
    rf2 = load_model(str(tmp_path / "rf"))
    np.testing.assert_array_equal(
        rf2.transform(f)["prediction"], rf.transform(f)["prediction"]
    )

    f2, _, _ = _blobs(n=600, k=2, seed=9)
    gbt = GBTClassifier(mesh=mesh8, maxIter=4, maxDepth=2, seed=0).fit(f2)
    save_model(gbt, str(tmp_path / "gbt"))
    gbt2 = load_model(str(tmp_path / "gbt"))
    np.testing.assert_array_equal(
        gbt2.transform(f2)["prediction"], gbt.transform(f2)["prediction"]
    )

    ovr = OneVsRest(
        classifier=GBTClassifier(mesh=mesh8, maxIter=3, maxDepth=2)
    ).fit(f)
    save_model(ovr, str(tmp_path / "ovr"))
    ovr2 = load_model(str(tmp_path / "ovr"))
    np.testing.assert_array_equal(
        ovr2.transform(f)["prediction"], ovr.transform(f)["prediction"]
    )


def test_ovr_gbt_vectorized_matches_sequential(mesh8):
    """The vectorized one-vs-rest GBT (class axis on the grower's tree
    axis) must reproduce the sequential per-class fits tree-for-tree when
    featureSubsetStrategy='all' (the default)."""
    f, X, y = _blobs(n=1200, k=3, d=5, seed=11)
    clf = GBTClassifier(mesh=mesh8, maxIter=4, maxDepth=3, stepSize=0.2, seed=3)
    ovr = OneVsRest(classifier=clf)
    vec = ovr.fit(f)  # dispatches to the vectorized path

    # sequential reference: force the fallback by requesting checkpointing
    # off AND calling the per-class loop directly
    seq_models = []
    for c in range(3):
        sub = f.with_column("b", (y == c).astype(np.float64))
        seq_models.append(clf.copy({"labelCol": "b"}).fit(sub))

    for c in range(3):
        mv, ms = vec.models[c], seq_models[c]
        np.testing.assert_array_equal(mv.forest.feature, ms.forest.feature)
        np.testing.assert_allclose(
            mv.forest.threshold, ms.forest.threshold, rtol=1e-6
        )
        np.testing.assert_allclose(
            mv.forest.leaf_stats, ms.forest.leaf_stats, rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(mv.treeWeights, ms.treeWeights)
    out = vec.transform(f)
    assert (out["prediction"] == y).mean() > 0.9


def test_ovr_gbt_vectorized_with_subsampling(mesh8):
    """Subsampling masks are shared across classes (sequential parity:
    every class copy carries the same seed) — still tree-for-tree equal."""
    f, X, y = _blobs(n=1000, k=3, d=5, seed=13)
    clf = GBTClassifier(
        mesh=mesh8, maxIter=3, maxDepth=2, subsamplingRate=0.7, seed=5
    )
    vec = OneVsRest(classifier=clf).fit(f)
    sub0 = f.with_column("b", (y == 0).astype(np.float64))
    seq0 = clf.copy({"labelCol": "b"}).fit(sub0)
    np.testing.assert_array_equal(
        vec.models[0].forest.feature, seq0.forest.feature
    )


def test_tree_serve_paths_agree(mesh8, monkeypatch):
    """Sync and fused-async serve paths agree for RF and GBT models."""
    from sntc_tpu.models import GBTClassifier, RandomForestClassifier

    rng = np.random.default_rng(11)
    X = rng.normal(size=(500, 8)).astype(np.float32)
    y3 = np.argmax(X[:, :3] + 0.5 * rng.normal(size=(500, 3)), axis=1).astype(
        np.float64
    )
    y2 = (X[:, 0] > 0).astype(np.float64)

    rf = RandomForestClassifier(
        mesh=mesh8, numTrees=5, maxDepth=3, seed=0
    ).fit(Frame({"features": X, "label": y3}))
    gbt = GBTClassifier(mesh=mesh8, maxIter=4, maxDepth=3, seed=0).fit(
        Frame({"features": X, "label": y2})
    )
    f3 = Frame({"features": X})
    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")  # force the device path
    for m in (rf, gbt):
        ref = m.transform(f3)
        out = m.transform_async(f3)()
        for col in ("rawPrediction", "probability"):
            np.testing.assert_allclose(out[col], ref[col], atol=1e-5)
        np.testing.assert_array_equal(out["prediction"], ref["prediction"])


def test_ovr_fused_raw_matches_per_model_loop(mesh8):
    """Fused OneVsRest serving (one pass over all classes) equals the
    per-sub-model loop for both LR and GBT sub-models."""
    from sntc_tpu.models import GBTClassifier, LogisticRegression, OneVsRest

    rng = np.random.default_rng(12)
    X = rng.normal(size=(800, 6)).astype(np.float32)
    y = np.argmax(X[:, :3] + 0.6 * rng.normal(size=(800, 3)), axis=1).astype(
        np.float64
    )
    f = Frame({"features": X, "label": y})
    for base in (
        LogisticRegression(mesh=mesh8, maxIter=15),
        GBTClassifier(mesh=mesh8, maxIter=3, maxDepth=3, seed=0),
    ):
        m = OneVsRest(classifier=base, mesh=mesh8).fit(f)
        fused = m._raw_predict(X)
        assert m._fused_raw() is not None
        loop = np.stack(
            [sub._raw_predict(X)[:, 1] for sub in m.models], axis=1
        )
        np.testing.assert_allclose(fused, loop, atol=1e-4)
        assert fused.shape == (800, 3)


def test_ovr_fused_cache_invalidates_on_model_mutation(mesh8):
    """Mutating the public ``models`` list after a predict must not serve
    the stale fused weight stack."""
    from sntc_tpu.models import LogisticRegression, OneVsRest

    rng = np.random.default_rng(21)
    X = rng.normal(size=(400, 5)).astype(np.float32)
    y = np.argmax(X[:, :3], axis=1).astype(np.float64)
    f = Frame({"features": X, "label": y})
    m = OneVsRest(
        classifier=LogisticRegression(mesh=mesh8, maxIter=10), mesh=mesh8
    ).fit(f)
    before = m._raw_predict(X)
    # swap class 0's sub-model for class 1's: column 0 must change
    m.models[0] = m.models[1]
    after = m._raw_predict(X)
    np.testing.assert_allclose(after[:, 0], before[:, 1], atol=1e-6)
    assert not np.allclose(after[:, 0], before[:, 0])


def test_quantile_edges_device_host_parity():
    """With sample_rows >= n both binning paths consume every row and must
    agree; the device branch (jitted jnp.quantile over a strided sample)
    otherwise has no small-data divergence from the host branch."""
    import jax.numpy as jnp

    from sntc_tpu.ops.binning import bin_features, quantile_bin_edges

    rng = np.random.default_rng(9)
    X = rng.normal(size=(4000, 7)).astype(np.float32)
    host = quantile_bin_edges(X, max_bins=16, sample_rows=10_000)
    dev = quantile_bin_edges(jnp.asarray(X), max_bins=16, sample_rows=10_000)
    assert isinstance(host, np.ndarray)
    assert host.shape == dev.shape == (7, 15)
    np.testing.assert_allclose(np.asarray(dev), host, atol=1e-4)
    # binned ids agree everywhere off the edge boundaries
    bh = np.asarray(bin_features(jnp.asarray(X), jnp.asarray(host)))
    bd = np.asarray(bin_features(jnp.asarray(X), dev))
    assert (bh != bd).mean() < 1e-3


def test_pcap_source_skips_permanently_bad_file(tmp_path):
    """A complete-but-undecodable capture must not wedge the stream: it
    decodes to 0 rows with a warning; a truncated header still raises
    (retry until the writer finishes)."""
    import warnings as _w

    from sntc_tpu.serve import PcapDirSource

    d = tmp_path / "caps"
    d.mkdir()
    (d / "bad.pcap").write_bytes(b"\x00" * 64)  # 64 bytes of junk
    src = PcapDirSource(str(d))
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        f = src.get_batch(0, 1)
    assert f.num_rows == 0
    assert any("skipping unreadable" in str(r.message) for r in rec)
    (d / "bad.pcap").write_bytes(b"\x01\x02")  # short header: partial write
    with pytest.raises(ValueError):
        src.get_batch(0, 1)


def test_decision_tree_classifier_matches_sklearn(mesh8):
    """Public single-tree estimator: behavioral parity with sklearn's
    DecisionTreeClassifier on separable blobs, plus the full classifier
    column contract and a save/load round trip."""
    import tempfile

    from sntc_tpu.models import (
        DecisionTreeClassificationModel,
        DecisionTreeClassifier,
    )

    f, X, y = _blobs(n=3000, k=3, seed=5)
    m = DecisionTreeClassifier(mesh=mesh8, maxDepth=5, maxBins=64, seed=0).fit(f)
    out = m.transform(f)
    acc = (np.asarray(out["prediction"]) == y).mean()
    sk = SkTree(max_depth=5, random_state=0).fit(X, y)
    sk_acc = (sk.predict(X) == y).mean()
    assert acc > 0.9
    assert abs(acc - sk_acc) < 0.03
    prob = np.asarray(out["probability"])
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-5)
    assert np.asarray(out["rawPrediction"]).shape == (3000, 3)
    imp = m.featureImportances
    assert imp.shape == (6,) and abs(imp.sum() - 1.0) < 1e-6
    with tempfile.TemporaryDirectory() as d:
        save_model(m, d + "/m")
        m2 = load_model(d + "/m")
        assert isinstance(m2, DecisionTreeClassificationModel)
        np.testing.assert_array_equal(
            np.asarray(m2.transform(f)["prediction"]),
            np.asarray(out["prediction"]),
        )


def test_decision_tree_regressor_fits_means(mesh8):
    """Regression tree: leaf predictions are segment means; matches
    sklearn's DecisionTreeRegressor closely on a piecewise-constant
    target, and round-trips through save/load."""
    import tempfile

    from sklearn.tree import DecisionTreeRegressor as SkReg

    from sntc_tpu.models import (
        DecisionTreeRegressionModel,
        DecisionTreeRegressor,
    )

    rng = np.random.default_rng(11)
    X = rng.uniform(-2, 2, size=(4000, 3)).astype(np.float32)
    y = (
        np.where(X[:, 0] > 0, 3.0, -1.0)
        + np.where(X[:, 1] > 0.5, 2.0, 0.0)
        + 0.05 * rng.normal(size=4000)
    )
    f = Frame({"features": X, "label": y})
    m = DecisionTreeRegressor(mesh=mesh8, maxDepth=3, maxBins=64).fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    sk = SkReg(max_depth=3, random_state=0).fit(X, y)
    rmse = np.sqrt(np.mean((pred - y) ** 2))
    sk_rmse = np.sqrt(np.mean((sk.predict(X) - y) ** 2))
    # histogram trees can't split inside a bin (Spark semantics): the step
    # at x0=0 sits inside a ~0.06-wide bin, costing a small mixed leaf vs
    # sklearn's exact split; everything else must match
    assert rmse < sk_rmse + 0.25
    assert rmse < 0.3 * y.std()  # >90% variance explained
    with tempfile.TemporaryDirectory() as d:
        save_model(m, d + "/m")
        m2 = load_model(d + "/m")
        assert isinstance(m2, DecisionTreeRegressionModel)
        np.testing.assert_allclose(
            np.asarray(m2.transform(f)["prediction"]), pred, atol=1e-6
        )


def test_decision_tree_depth_and_fused_serve(mesh8):
    """model.depth reports the realized depth (not heap capacity); the
    fused one-dispatch serve path equals the sync transform."""
    from sntc_tpu.models import DecisionTreeClassifier

    rng = np.random.default_rng(3)
    X = rng.normal(size=(2000, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)  # one clean split suffices
    f = Frame({"features": X, "label": y})
    m = DecisionTreeClassifier(mesh=mesh8, maxDepth=6, maxBins=64).fit(f)
    # growth stops before the heap capacity: realized depth, not maxDepth
    # (a few boundary-bin refinements may go past the single clean split)
    assert m.depth < 6
    assert not m.hasParam("subsamplingRate")  # Spark DTs have no bagging
    ref = m.transform(f)
    out = m.transform_async(f)()
    np.testing.assert_array_equal(out["prediction"], ref["prediction"])
    np.testing.assert_allclose(
        out["probability"], ref["probability"], atol=1e-5
    )


def test_random_forest_regressor_vs_sklearn(mesh8):
    """Averaged regression forest tracks sklearn's RandomForestRegressor
    behaviorally on a smooth target; save/load round-trips; importances
    find the signal features."""
    import tempfile

    from sklearn.ensemble import RandomForestRegressor as SkRF

    from sntc_tpu.models import (
        RandomForestRegressionModel,
        RandomForestRegressor,
    )

    rng = np.random.default_rng(17)
    n = 5000
    X = rng.uniform(-2, 2, size=(n, 6)).astype(np.float32)
    y = (
        2.0 * X[:, 1]
        + np.sin(2.0 * X[:, 4])
        + 0.1 * rng.normal(size=n)
    ).astype(np.float32)
    f = Frame({"features": X, "label": y})
    # featureSubsetStrategy="all" to match sklearn's regression default
    # (Spark's regression "auto" is onethird — sklearn at max_features=1/3
    # does WORSE than our onethird: 1.20 vs 0.66 rmse on this data)
    m = RandomForestRegressor(
        mesh=mesh8, numTrees=15, maxDepth=6, maxBins=64, seed=0,
        featureSubsetStrategy="all",
    ).fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    rmse = np.sqrt(np.mean((pred - y) ** 2))
    sk = SkRF(n_estimators=15, max_depth=6, random_state=0).fit(X, y)
    sk_rmse = np.sqrt(np.mean((sk.predict(X) - y) ** 2))
    assert rmse < sk_rmse + 0.05  # histogram splits vs exact splits
    assert rmse < 0.15 * y.std()
    imp = m.featureImportances
    assert set(np.argsort(imp)[-2:]) == {1, 4}
    with tempfile.TemporaryDirectory() as d:
        save_model(m, d + "/rfr")
        m2 = load_model(d + "/rfr")
        assert isinstance(m2, RandomForestRegressionModel)
        np.testing.assert_allclose(
            np.asarray(m2.transform(f)["prediction"]), pred, atol=1e-6
        )


def test_gbt_regressor_vs_sklearn(mesh8):
    """Boosted regression matches sklearn's GradientBoostingRegressor
    behaviorally; save/load round-trips; absolute loss works."""
    import tempfile

    from sklearn.ensemble import GradientBoostingRegressor as SkGBR

    from sntc_tpu.models import GBTRegressionModel, GBTRegressor

    rng = np.random.default_rng(19)
    n = 4000
    X = rng.uniform(-2, 2, size=(n, 5)).astype(np.float32)
    y = (X[:, 0] ** 2 + 2.0 * X[:, 3] + 0.1 * rng.normal(size=n)).astype(
        np.float32
    )
    f = Frame({"features": X, "label": y})
    m = GBTRegressor(
        mesh=mesh8, maxIter=25, maxDepth=3, stepSize=0.3, maxBins=64, seed=0
    ).fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    rmse = np.sqrt(np.mean((pred - y) ** 2))
    sk = SkGBR(n_estimators=25, max_depth=3, learning_rate=0.3).fit(X, y)
    sk_rmse = np.sqrt(np.mean((sk.predict(X) - y) ** 2))
    # histogram splits + Spark's weight-1.0 first tree (sklearn scales
    # every tree by the learning rate) cost a modest constant
    assert rmse < sk_rmse + 0.15
    assert rmse < 0.2 * y.std()
    ab = GBTRegressor(
        mesh=mesh8, maxIter=25, maxDepth=3, stepSize=0.3, maxBins=64,
        lossType="absolute", seed=0,
    ).fit(f)
    ab_rmse = np.sqrt(np.mean((np.asarray(ab.transform(f)["prediction"]) - y) ** 2))
    assert ab_rmse < 0.5 * y.std()
    with tempfile.TemporaryDirectory() as d:
        save_model(m, d + "/gbr")
        m2 = load_model(d + "/gbr")
        assert isinstance(m2, GBTRegressionModel)
        np.testing.assert_allclose(
            np.asarray(m2.transform(f)["prediction"]), pred, atol=1e-6
        )
        assert m2.numTrees == m.numTrees and m2.treeWeights == m.treeWeights


def test_gbt_regressor_validated_early_stop(mesh8):
    """A plateauing validation split halts boosting with numTrees <
    maxIter (runWithValidation semantics)."""
    from sntc_tpu.models import GBTRegressor

    rng = np.random.default_rng(20)
    n = 3000
    X = rng.uniform(-2, 2, size=(n, 4)).astype(np.float32)
    y = (X[:, 0] + 0.8 * rng.normal(size=n)).astype(np.float32)  # noisy
    is_val = np.zeros(n, bool)
    is_val[::3] = True
    f = Frame({
        "features": X, "label": y, "isVal": is_val.astype(np.float64)
    })
    m = GBTRegressor(
        mesh=mesh8, maxIter=60, maxDepth=4, stepSize=0.5, seed=0,
        validationIndicatorCol="isVal", validationTol=0.0,
    ).fit(f)
    assert m.numTrees < 60


@pytest.mark.parametrize("subset", ["all", "sqrt"])
def test_node_group_batching_identical_forest(mesh8, monkeypatch, subset):
    """The memory-bounded node-group path (Spark maxMemoryInMB analog)
    must produce EXACTLY the forest the single-pass path grows — the
    grouping is a pure execution-schedule choice.  ``group`` is resolved
    in grow_forest and passed as a STATIC jit arg, so the env override
    retraces rather than silently reusing the cached single-pass program
    (both branches — shared fmask=None and the per-group fmask slices of
    'sqrt' — are exercised)."""
    from sntc_tpu.models import RandomForestClassifier
    from sntc_tpu.models.tree.grower import node_group_size

    rng = np.random.default_rng(3)
    n = 4000
    X = rng.normal(size=(n, 12)).astype(np.float32)
    y = ((X[:, 0] > 0) * 2 + (X[:, 3] > 0.5)).astype(np.float64)
    f = Frame({"features": X, "label": y})

    def grow():
        m = RandomForestClassifier(
            mesh=mesh8, numTrees=4, maxDepth=6, seed=0,
            featureSubsetStrategy=subset,
        ).fit(f)
        fo = m.forest
        return fo.feature.copy(), fo.threshold.copy(), fo.leaf_stats.copy()

    monkeypatch.delenv("SNTC_TREE_NODE_GROUP_MB", raising=False)
    base = grow()
    assert node_group_size(4, 12, 32, 4) >= 32  # default: one group

    monkeypatch.setenv("SNTC_TREE_NODE_GROUP_MB", "0.2")
    assert node_group_size(4, 12, 32, 4) < 32  # forces multiple groups
    grouped = grow()
    for a, b in zip(base, grouped):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("subset", ["all", "sqrt"])
def test_sibling_subtraction_identical_forest(mesh8, monkeypatch, subset):
    """Sibling-histogram subtraction (right child = parent − left) must
    grow EXACTLY the forest the direct path grows: with integer-valued
    Poisson bagging weights every histogram cell is an exact small-int
    f32 sum, so the subtraction is exact and the forests are
    bit-identical — including under memory-bounded node grouping (the
    subtraction path slices the SAME parent histograms per group)."""
    from sntc_tpu.models import RandomForestClassifier
    from sntc_tpu.models.tree.grower import node_group_size

    rng = np.random.default_rng(5)
    n = 4000
    X = rng.normal(size=(n, 12)).astype(np.float32)
    y = ((X[:, 1] > 0) * 2 + (X[:, 4] > -0.3)).astype(np.float64)
    f = Frame({"features": X, "label": y})

    def grow():
        m = RandomForestClassifier(
            mesh=mesh8, numTrees=4, maxDepth=6, seed=0,
            featureSubsetStrategy=subset,
        ).fit(f)
        fo = m.forest
        return fo.feature.copy(), fo.threshold.copy(), fo.leaf_stats.copy()

    monkeypatch.setenv("SNTC_TREE_SIBLING", "0")
    direct = grow()
    monkeypatch.setenv("SNTC_TREE_SIBLING", "1")  # force (CPU default: off)
    sibling = grow()
    for a, b in zip(direct, sibling):
        np.testing.assert_array_equal(a, b)

    # grouping invariance on the subtraction path itself: the budget must
    # land group in [2, 32) — group=1 would disable sibling subtraction
    # entirely and make this leg vacuous (direct == direct)
    monkeypatch.setenv("SNTC_TREE_NODE_GROUP_MB", "0.5")
    assert 2 <= node_group_size(4, 12, 32, 4) < 32
    sibling_grouped = grow()
    for a, b in zip(sibling, sibling_grouped):
        np.testing.assert_array_equal(a, b)


def test_sibling_subtraction_regression_signed_stats(mesh8, monkeypatch):
    """Variance stats ([w, wy, wy²]) are signed in wy — the sibling path
    must NOT clamp derived siblings at zero (a clamp would zero negative
    residual sums and corrupt every TPU GBT/regressor fit).  Integer-
    valued targets keep all sums exact, so direct and sibling forests
    are bit-identical."""
    from sntc_tpu.models import RandomForestRegressor

    rng = np.random.default_rng(13)
    n = 3000
    X = rng.normal(size=(n, 8)).astype(np.float32)
    # integer-valued, centered targets: wy sums go genuinely negative
    y = (np.round(2 * X[:, 0]) - np.round(X[:, 3])).astype(np.float64)
    f = Frame({"features": X, "label": y})

    def grow():
        m = RandomForestRegressor(
            mesh=mesh8, numTrees=3, maxDepth=5, seed=0,
            featureSubsetStrategy="all",
        ).fit(f)
        fo = m.forest
        return fo.feature.copy(), fo.threshold.copy(), fo.leaf_stats.copy()

    monkeypatch.setenv("SNTC_TREE_SIBLING", "0")
    direct = grow()
    monkeypatch.setenv("SNTC_TREE_SIBLING", "1")
    sibling = grow()
    for a, b in zip(direct, sibling):
        np.testing.assert_array_equal(a, b)
    # the planted negative-mean leaves really exist (guards vacuity)
    leaf_wy = direct[2][..., 1][direct[0] == -1]
    assert (leaf_wy < 0).any(), "no negative wy leaf — test lost its teeth"


def test_label_fused_scatter_identical_forest(mesh8, monkeypatch):
    """The label-fused scalar scatter (default for classification) must
    produce EXACTLY the forest of the generic vector segment_sum path —
    both accumulate the same integer-valued weights in row order, so the
    comparison is bit-exact.  SNTC_TREE_LABEL_FUSED=0 is the field
    kill-switch that forces the generic path."""
    from sntc_tpu.models import RandomForestClassifier

    rng = np.random.default_rng(9)
    n = 3000
    X = rng.normal(size=(n, 9)).astype(np.float32)
    y = ((X[:, 0] > -0.5) * 2 + (X[:, 2] > 0.4)).astype(np.float64)
    f = Frame({"features": X, "label": y})

    def grow():
        m = RandomForestClassifier(
            mesh=mesh8, numTrees=3, maxDepth=5, seed=0
        ).fit(f)
        fo = m.forest
        return fo.feature.copy(), fo.threshold.copy(), fo.leaf_stats.copy()

    fused = grow()
    monkeypatch.setenv("SNTC_TREE_LABEL_FUSED", "0")
    generic = grow()
    for a, b in zip(fused, generic):
        np.testing.assert_array_equal(a, b)


def test_gbt_regressor_absolute_loss_wide_range_targets(mesh8):
    """Advisor r2 (medium): with lossType='absolute', the FIRST tree must
    fit the raw residuals with weight 1.0 (Spark boost()); the old
    sign-residual first tree bounded predictions to
    init ± ~maxIter·stepSize, which is grossly wrong when the target
    spread dwarfs that (y spanning [0, 1000] here)."""
    from sntc_tpu.models import GBTRegressor

    rng = np.random.default_rng(23)
    n = 3000
    X = rng.uniform(-2, 2, size=(n, 4)).astype(np.float32)
    y = (500.0 + 250.0 * X[:, 0] + 5.0 * rng.normal(size=n)).astype(
        np.float32
    )  # spread ~1000 >> maxIter * stepSize
    f = Frame({"features": X, "label": y})
    m = GBTRegressor(
        mesh=mesh8, maxIter=20, maxDepth=3, stepSize=0.3, seed=0,
        lossType="absolute",
    ).fit(f)
    pred = np.asarray(m.transform(f)["prediction"])
    # the first weight-1.0 raw-residual tree captures the bulk of the
    # spread; the old behavior left rmse ≈ y.std() (~250)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.25 * float(y.std()), rmse
    assert m.treeWeights[0] == 1.0
