"""pcap parser + flow meter tests (the CICFlowMeter-analog of [B:11]).

The pure-Python struct parser is the oracle for the native C++ one; the
flow meter is checked against hand-computed statistics on small crafted
captures.
"""

import numpy as np
import pytest

from sntc_tpu.data.schema import CICIDS2017_FEATURES
from sntc_tpu.native import pcap as pc
from sntc_tpu.native.pcap import (
    make_packet,
    make_pcap,
    packets_to_flow_frame,
    parse_pcap,
    pcap_to_flow_frame,
)

A, B = 0x0A000001, 0x0A000002  # 10.0.0.1 / 10.0.0.2


def _two_flow_capture():
    """Flow 1: TCP A:1234 <-> B:80 (3 fwd + 1 bwd).  Flow 2: UDP."""
    pkts = [
        (10.0, make_packet(A, B, 1234, 80, payload=100, flags=0x02, window=1000)),
        (10.1, make_packet(B, A, 80, 1234, payload=200, flags=0x12, window=2000)),
        (10.3, make_packet(A, B, 1234, 80, payload=50, flags=0x18)),
        (10.6, make_packet(A, B, 1234, 80, payload=0, flags=0x10)),
        (20.0, make_packet(A, B, 5555, 53, proto=17, payload=40)),
        (20.2, make_packet(B, A, 53, 5555, proto=17, payload=120)),
    ]
    return make_pcap(pkts)


def test_python_parser_fields():
    data = _two_flow_capture()
    rows = pc._parse_pcap_py(data)
    assert rows.shape == (6, pc.PCAP_FIELDS)
    np.testing.assert_allclose(
        rows[:, 0], [10.0, 10.1, 10.3, 10.6, 20.0, 20.2], atol=5e-7
    )
    assert rows[0, 1] == A and rows[0, 2] == B
    assert rows[0, 3] == 1234 and rows[0, 4] == 80
    assert rows[0, 5] == 6 and rows[4, 5] == 17
    assert rows[0, 7] == 100  # payload
    assert rows[0, 8] == 0x02  # SYN
    assert rows[0, 9] == 1000  # window
    assert rows[0, 10] == 40  # 20 IP + 20 TCP
    assert rows[4, 10] == 28  # 20 IP + 8 UDP


def test_native_matches_python_oracle():
    if not pc.using_native():
        pytest.skip("no C++ toolchain")
    data = _two_flow_capture()
    np.testing.assert_allclose(
        parse_pcap(data), pc._parse_pcap_py(data), atol=1e-9
    )


def test_parser_skips_non_ipv4_and_handles_truncation():
    import struct

    good = make_packet(A, B, 1, 2, payload=10)
    arp = b"\x02" * 12 + struct.pack(">H", 0x0806) + b"\x00" * 28
    data = make_pcap([(1.0, arp), (2.0, good)])
    rows = pc._parse_pcap_py(data)
    assert rows.shape[0] == 1 and rows[0, 0] == 2.0
    # truncated tail: drop the last 5 bytes of the capture
    rows2 = pc._parse_pcap_py(data[:-5])
    assert rows2.shape[0] == 0 or rows2.shape[0] == 1
    if pc.using_native():
        np.testing.assert_allclose(parse_pcap(data), rows)


def test_nanosecond_and_vlan_variants():
    pkt = make_packet(A, B, 9, 10, payload=5)
    data = make_pcap([(3.000000001, pkt)], nanos=True)
    rows = pc._parse_pcap_py(data)
    assert abs(rows[0, 0] - 3.000000001) < 1e-9
    # 802.1Q tag insertion
    import struct

    tagged = (
        pkt[:12]
        + struct.pack(">HH", 0x8100, 42)
        + pkt[12:]
    )
    data_v = make_pcap([(4.0, tagged)])
    rows_v = pc._parse_pcap_py(data_v)
    assert rows_v.shape[0] == 1 and rows_v[0, 3] == 9
    if pc.using_native():
        np.testing.assert_allclose(parse_pcap(data_v), rows_v)


def test_flow_meter_two_flows():
    f = pcap_to_flow_frame(_two_flow_capture())
    assert f.num_rows == 2
    assert set(f.columns) == set(CICIDS2017_FEATURES)
    i = int(np.argmax(f["Destination Port"]))  # TCP flow: dport 80
    j = 1 - i
    assert f["Destination Port"][i] == 80
    assert f["Destination Port"][j] == 53
    assert f["Total Fwd Packets"][i] == 3
    assert f["Total Backward Packets"][i] == 1
    assert f["Total Length of Fwd Packets"][i] == 150
    assert f["Total Length of Bwd Packets"][i] == 200
    np.testing.assert_allclose(f["Flow Duration"][i], 0.6e6, rtol=1e-5)
    np.testing.assert_allclose(
        f["Flow Bytes/s"][i], 350 / 0.6, rtol=1e-4
    )
    np.testing.assert_allclose(f["Flow Packets/s"][i], 4 / 0.6, rtol=1e-4)
    # fwd packet lengths: 100, 50, 0
    assert f["Fwd Packet Length Max"][i] == 100
    assert f["Fwd Packet Length Min"][i] == 0
    np.testing.assert_allclose(f["Fwd Packet Length Mean"][i], 50.0, rtol=1e-5)
    np.testing.assert_allclose(
        f["Fwd Packet Length Std"][i], np.std([100, 50, 0], ddof=1), rtol=1e-5
    )
    # flow IATs: 0.1, 0.2, 0.3 s in µs
    np.testing.assert_allclose(f["Flow IAT Mean"][i], 0.2e6, rtol=1e-4)
    np.testing.assert_allclose(f["Flow IAT Max"][i], 0.3e6, rtol=1e-4)
    np.testing.assert_allclose(f["Flow IAT Min"][i], 0.1e6, rtol=1e-4)
    # fwd IATs (ts 10.0, 10.3, 10.6): two gaps of 0.3
    np.testing.assert_allclose(f["Fwd IAT Total"][i], 0.6e6, rtol=1e-4)
    np.testing.assert_allclose(f["Fwd IAT Std"][i], 0.0, atol=1.0)
    assert f["SYN Flag Count"][i] == 2  # SYN + SYN/ACK
    assert f["ACK Flag Count"][i] == 3
    assert f["PSH Flag Count"][i] == 1
    assert f["Fwd PSH Flags"][i] == 1
    assert f["Init_Win_bytes_forward"][i] == 1000
    assert f["Init_Win_bytes_backward"][i] == 2000
    assert f["act_data_pkt_fwd"][i] == 2  # payload>0 fwd packets
    assert f["min_seg_size_forward"][i] == 40
    assert f["Fwd Header Length"][i] == 120  # 3 × 40
    assert f["Bwd Header Length"][i] == 40
    # UDP flow sanity
    assert f["Total Fwd Packets"][j] == 1
    assert f["Total Backward Packets"][j] == 1


def test_flow_timeout_splits_flows():
    pkts = [
        (0.0, make_packet(A, B, 1000, 80, payload=10)),
        (1.0, make_packet(A, B, 1000, 80, payload=10)),
        (200.0, make_packet(A, B, 1000, 80, payload=10)),  # > 120 s gap
    ]
    f = pcap_to_flow_frame(make_pcap(pkts))
    assert f.num_rows == 2
    counts = sorted(f["Total Fwd Packets"].tolist())
    assert counts == [1, 2]


def test_active_idle_split():
    # one flow with a 10 s quiet gap: two active spans, one idle period
    pkts = [
        (0.0, make_packet(A, B, 7, 80, payload=10)),
        (1.0, make_packet(A, B, 7, 80, payload=10)),
        (11.0, make_packet(A, B, 7, 80, payload=10)),
        (12.5, make_packet(A, B, 7, 80, payload=10)),
    ]
    f = pcap_to_flow_frame(make_pcap(pkts), activity_timeout=5.0)
    assert f.num_rows == 1
    np.testing.assert_allclose(f["Idle Mean"][0], 10e6, rtol=1e-5)
    np.testing.assert_allclose(f["Idle Max"][0], 10e6, rtol=1e-5)
    np.testing.assert_allclose(f["Active Max"][0], 1.5e6, rtol=1e-5)
    np.testing.assert_allclose(f["Active Min"][0], 1.0e6, rtol=1e-5)
    np.testing.assert_allclose(f["Active Mean"][0], 1.25e6, rtol=1e-5)


def test_direction_assignment_first_packet_wins():
    # first packet travels B->A, so forward = B->A even though A<B
    pkts = [
        (0.0, make_packet(B, A, 80, 1234, payload=300)),
        (0.1, make_packet(A, B, 1234, 80, payload=50)),
    ]
    f = pcap_to_flow_frame(make_pcap(pkts))
    assert f.num_rows == 1
    assert f["Total Fwd Packets"][0] == 1
    assert f["Total Length of Fwd Packets"][0] == 300
    assert f["Total Length of Bwd Packets"][0] == 50
    assert f["Destination Port"][0] == 1234


def test_empty_and_malformed():
    assert parse_pcap(b"notapcap") is None
    with pytest.raises(ValueError):
        pcap_to_flow_frame(b"junkjunkjunkjunkjunkjunkjunk")
    f = packets_to_flow_frame(np.zeros((0, pc.PCAP_FIELDS)))
    assert f.num_rows == 0


def test_pcap_dir_source_streams(tmp_path):
    from sntc_tpu.serve import MemorySink, PcapDirSource, StreamingQuery
    from sntc_tpu.serve.streaming import StreamSink

    d = tmp_path / "caps"
    d.mkdir()
    (d / "c0.pcap").write_bytes(_two_flow_capture())
    pkts = [(5.0, make_packet(A, B, 42, 443, payload=64))]
    (d / "c1.pcap").write_bytes(make_pcap(pkts))
    src = PcapDirSource(str(d))
    assert src.latest_offset() == 2
    batch = src.get_batch(0, 2)
    assert batch.num_rows == 3
    assert set(batch.columns) == set(CICIDS2017_FEATURES)

    class CollectSink(StreamSink):
        def __init__(self):
            self.rows = 0

        def add_batch(self, batch_id, frame):
            self.rows += frame.num_rows

    # identity "model": StreamingQuery needs a Transformer; use a passthrough
    from sntc_tpu.core.base import Transformer

    class Passthrough(Transformer):
        def transform(self, frame):
            return frame

    sink = CollectSink()
    q = StreamingQuery(
        Passthrough(), src, sink, str(tmp_path / "ckpt"), max_batch_offsets=1
    )
    assert q.process_available() == 2
    assert sink.rows == 3
