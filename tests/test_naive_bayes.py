"""NaiveBayes oracle tests: sklearn's four NB variants are the numeric
references (SURVEY.md §4.2 oracle strategy)."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import NaiveBayes


def _count_data(seed=0, n=2000, d=8, k=3):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, k, size=n)
    rates = rng.uniform(0.5, 4.0, size=(k, d))
    X = rng.poisson(rates[y]).astype(np.float32)
    return Frame({"features": X, "label": y.astype(np.float64)}), X, y


def test_multinomial_matches_sklearn(mesh8):
    from sklearn.naive_bayes import MultinomialNB

    f, X, y = _count_data()
    m = NaiveBayes(mesh=mesh8, smoothing=1.0).fit(f)
    sk = MultinomialNB(alpha=1.0).fit(X, y)
    np.testing.assert_allclose(m.theta, sk.feature_log_prob_, atol=1e-5)
    # priors are Spark's lambda-smoothed form, NOT sklearn's unsmoothed
    counts = np.bincount(y, minlength=3).astype(np.float64)
    spark_pi = np.log(counts + 1.0) - np.log(counts.sum() + 3.0)
    np.testing.assert_allclose(m.bias, spark_pi, atol=1e-6)
    out = m.transform(f)
    # the tiny prior delta leaves predictions essentially identical
    agree = (
        np.asarray(out["prediction"]) == sk.predict(X).astype(np.float64)
    ).mean()
    assert agree > 0.999


def test_bernoulli_matches_sklearn(mesh8):
    from sklearn.naive_bayes import BernoulliNB

    rng = np.random.default_rng(1)
    n, d, k = 1500, 10, 2
    y = rng.integers(0, k, size=n)
    p = rng.uniform(0.2, 0.8, size=(k, d))
    X = (rng.random((n, d)) < p[y]).astype(np.float32)
    f = Frame({"features": X, "label": y.astype(np.float64)})
    m = NaiveBayes(mesh=mesh8, modelType="bernoulli", smoothing=1.0).fit(f)
    sk = BernoulliNB(alpha=1.0).fit(X, y)
    out = m.transform(f)
    agree = (
        np.asarray(out["prediction"]) == sk.predict(X).astype(np.float64)
    ).mean()
    assert agree > 0.999
    with pytest.raises(ValueError, match="0/1"):
        NaiveBayes(mesh=mesh8, modelType="bernoulli").fit(
            Frame({"features": X + 0.5, "label": y.astype(np.float64)})
        )


def test_gaussian_matches_sklearn(mesh8):
    from sklearn.naive_bayes import GaussianNB

    rng = np.random.default_rng(2)
    n, d, k = 2000, 6, 3
    y = rng.integers(0, k, size=n)
    mu = rng.normal(size=(k, d)) * 3
    X = (mu[y] + rng.normal(size=(n, d))).astype(np.float32)
    f = Frame({"features": X, "label": y.astype(np.float64)})
    m = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(f)
    sk = GaussianNB().fit(X, y)
    out = m.transform(f)
    # smoothing differs slightly (Spark eps=0.1*max var vs sklearn 1e-9 *
    # max var) -> compare predictions, which are robust to it
    agree = (np.asarray(out["prediction"]) == sk.predict(X)).mean()
    assert agree > 0.995


def test_complement_matches_sklearn(mesh8):
    from sklearn.naive_bayes import ComplementNB

    f, X, y = _count_data(seed=3)
    m = NaiveBayes(mesh=mesh8, modelType="complement", smoothing=1.0).fit(f)
    sk = ComplementNB(alpha=1.0, norm=True).fit(X, y)
    out = m.transform(f)
    agree = (np.asarray(out["prediction"]) == sk.predict(X)).mean()
    assert agree > 0.97


def test_weights_and_negative_rejection(mesh8):
    f, X, y = _count_data(seed=4)
    w = np.ones(len(y), np.float32)
    fw = Frame({"features": X, "label": y.astype(np.float64), "w": w})
    m1 = NaiveBayes(mesh=mesh8).fit(f)
    m2 = NaiveBayes(mesh=mesh8, weightCol="w").fit(fw)
    np.testing.assert_allclose(m1.theta, m2.theta, atol=1e-6)
    with pytest.raises(ValueError, match="non-negative"):
        NaiveBayes(mesh=mesh8).fit(
            Frame({"features": X - 10.0, "label": y.astype(np.float64)})
        )


def test_save_load_and_fused_serve(mesh8, tmp_path):
    f, X, y = _count_data(seed=5)
    m = NaiveBayes(mesh=mesh8).fit(f)
    save_model(m, str(tmp_path / "nb"))
    m2 = load_model(str(tmp_path / "nb"))
    ref = m.transform(f)
    np.testing.assert_array_equal(
        np.asarray(m2.transform(f)["prediction"]), np.asarray(ref["prediction"])
    )
    out = m.transform_async(f)()
    np.testing.assert_array_equal(out["prediction"], ref["prediction"])
    np.testing.assert_allclose(out["probability"], ref["probability"], atol=1e-5)
    g = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(f)
    save_model(g, str(tmp_path / "gnb"))
    g2 = load_model(str(tmp_path / "gnb"))
    np.testing.assert_array_equal(
        np.asarray(g2.transform(f)["prediction"]),
        np.asarray(g.transform(f)["prediction"]),
    )


def test_gaussian_large_scale_features_agree_with_sklearn(mesh8):
    """Flow-like features whose variances span many decades: the
    pilot-shifted moments and 1e-9 smoothing must track sklearn closely
    (f32 raw-x^2 accumulation used to collapse agreement to ~48%)."""
    from sklearn.naive_bayes import GaussianNB

    rng = np.random.default_rng(6)
    n, k = 4000, 4
    y = rng.integers(0, k, size=n)
    # columns at wildly different scales, class signal in each
    scales = np.array([1e6, 1e3, 1.0, 1e-2], np.float64)
    mu = rng.normal(size=(k, 4)) * 2
    X = ((mu[y] + rng.normal(size=(n, 4))) * scales[None, :] + scales[None, :] * 50).astype(np.float32)
    f = Frame({"features": X, "label": y.astype(np.float64)})
    m = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(f)
    sk = GaussianNB().fit(X.astype(np.float64), y)
    ours = np.asarray(m.transform(f)["prediction"])
    agree = (ours == sk.predict(X.astype(np.float64))).mean()
    assert agree > 0.98


def test_gaussian_flow_schema_exact_sklearn_agreement(mesh8):
    """On the CICIDS2017-schema synthetic flows (variances spanning ~12
    decades, 15 imbalanced classes) the gaussian fit must agree with
    sklearn exactly: two-pass class variances, f64 likelihood, and the
    GLOBAL-variance smoothing floor were each required to get here."""
    from sklearn.naive_bayes import GaussianNB

    from sntc_tpu.data.ingest import clean_flows
    from sntc_tpu.data.synth import generate_frame

    df = clean_flows(generate_frame(8000, seed=13))
    feat = [c for c in df.columns if c != "Label"]
    X = np.stack([np.asarray(df[c], np.float32) for c in feat], axis=1)
    _, y = np.unique(np.asarray(df["Label"]), return_inverse=True)
    f = Frame({"features": X, "label": y.astype(np.float64)})
    m = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(f)
    sk = GaussianNB().fit(X, y)
    ours = np.asarray(m.transform(f)["prediction"])
    sk_pred = sk.predict(X)
    agree = ours == sk_pred
    # f32-vs-f64 knife edges: any disagreeing row must be a near-tie in
    # sklearn's OWN log-likelihoods (top-2 margin ~0), not a real miss
    if not agree.all():
        assert agree.mean() > 0.999
        jll = sk.predict_joint_log_proba(X[~agree])
        top2 = np.sort(jll, axis=1)[:, -2:]
        # relative tie margin: log-likelihoods are O(200), so f32
        # accumulation noise across 78 feature terms is O(1e-2)
        assert np.all(
            top2[:, 1] - top2[:, 0] < 1e-4 * np.abs(top2[:, 1]) + 1e-3
        )
