"""Compile-cache host partition (VERDICT r4 weak #4): an XLA:CPU
executable AOT-compiled on a differently-featured host must be a cache
MISS, not a served artifact that can SIGILL."""

import sntc_tpu.utils.compile_cache as cc


def test_host_signature_is_stable_and_flag_sensitive(monkeypatch):
    sig1 = cc.host_feature_signature()
    sig2 = cc.host_feature_signature()
    assert sig1 == sig2 and len(sig1) >= 4


def test_cache_dir_partitioned_by_host_signature(tmp_path, monkeypatch):
    monkeypatch.delenv("SNTC_NO_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("SNTC_CACHE_NO_HOST_KEY", raising=False)
    base = str(tmp_path / "xla")

    monkeypatch.setattr(cc, "host_feature_signature", lambda: "aaaa1111bbbb")
    dir_a = cc.resolve_cache_dir(base)
    # a foreign host wrote an artifact into ITS partition
    monkeypatch.setattr(cc, "host_feature_signature", lambda: "cccc2222dddd")
    dir_b = cc.resolve_cache_dir(base)

    assert dir_a != dir_b
    assert dir_a.startswith(base) and dir_b.startswith(base)
    # structural guarantee: nothing under dir_a is visible from dir_b,
    # so an entry written under another feature signature cannot be
    # served here — it is a clean miss
    import os

    os.makedirs(dir_a, exist_ok=True)
    open(os.path.join(dir_a, "foreign-entry"), "w").close()
    assert not os.path.exists(os.path.join(dir_b, "foreign-entry"))


def test_host_key_opt_out_and_disable(tmp_path, monkeypatch):
    base = str(tmp_path / "xla")
    monkeypatch.setenv("SNTC_CACHE_NO_HOST_KEY", "1")
    assert cc.resolve_cache_dir(base) == base
    monkeypatch.setenv("SNTC_NO_COMPILE_CACHE", "1")
    assert cc.resolve_cache_dir(base) is None


def test_enable_rewrites_env_to_partitioned_path(tmp_path, monkeypatch):
    """ADVICE r5: with JAX_COMPILATION_CACHE_DIR set, jax can enable the
    cache at the UNpartitIONED base before enable_persistent_cache()
    runs; the helper must rewrite the env var to the per-host path so no
    compile (here or in subprocesses) can touch the shared base."""
    import os

    base = str(tmp_path / "xla")
    monkeypatch.delenv("SNTC_NO_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("SNTC_CACHE_NO_HOST_KEY", raising=False)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", base)
    resolved = cc.enable_persistent_cache()
    assert resolved != base and resolved.startswith(base)
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == resolved
    # idempotent: re-enabling with the rewritten env must NOT nest a
    # second host-<sig> partition level
    assert cc.enable_persistent_cache() == resolved
    assert cc.resolve_cache_dir() == resolved
