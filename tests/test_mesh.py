"""r22 mesh substrate: sharded-vs-single-device equivalence matrix,
elastic resize / OOM split, transfer-ledger attribution, evidence
metrics, and the axis-registry drift check.

The matrix pins the tentpole claim: every collective call site produces
the SAME answer on a mesh of 1, 2, and 8 (faked CPU) devices — bitwise
for f64 / integer-valued payloads (psum of exact integers is
order-independent), ≤1e-5 relative for f32 iterative fits.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import sntc_tpu.resilience as R
from sntc_tpu.core.frame import Frame
from sntc_tpu.obs.metrics import registry
from sntc_tpu.parallel import (
    default_mesh,
    make_tree_aggregate,
    set_collective_domain,
    shard_batch,
)
from sntc_tpu.parallel.mesh import (
    DATA_AXIS,
    MESH_AXES,
    collective_wire_bytes,
    data_sharding,
    map_at,
    map_reduce_at,
    payload_nbytes,
    sharded_jit,
)

MESH_SIZES = (1, 2, 8)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    set_collective_domain(None)
    yield
    R.clear()
    R.clear_events()
    set_collective_domain(None)


def _get(name, **labels):
    return registry().get(name, **labels) or 0


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# substrate units
# ---------------------------------------------------------------------------


def test_mesh_axes_registry_sane():
    assert set(MESH_AXES) == {"data", "model"}
    assert DATA_AXIS in MESH_AXES
    for axis, meaning in MESH_AXES.items():
        assert isinstance(meaning, str) and len(meaning) > 10, axis


def test_map_at_reduce_at_matches_numpy(mesh8):
    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    out = map_reduce_at(
        mesh8,
        lambda xs: {"sum": xs.sum(axis=0), "sq": (xs * xs).sum()},
        in_specs=(P(DATA_AXIS, None),),
        jit=True,
    )(x)
    np.testing.assert_array_equal(np.asarray(out["sum"]), x.sum(axis=0))
    assert float(out["sq"]) == float((x * x).sum())


def test_map_at_row_sharded_output(mesh8):
    x = np.ones((16, 3), np.float32)
    fn = map_at(
        mesh8,
        lambda xs: xs * 2.0,
        in_specs=(P(DATA_AXIS, None),),
        out_specs=P(DATA_AXIS, None),
    )
    out = fn(jax.device_put(x, data_sharding(mesh8, 2)))
    np.testing.assert_array_equal(np.asarray(out), x * 2.0)


def test_sharded_jit_honors_annotations(mesh8):
    fn = sharded_jit(
        lambda x: x + 1.0,
        in_shardings=(data_sharding(mesh8, 2),),
        out_shardings=data_sharding(mesh8, 2),
    )
    out = fn(np.zeros((16, 2), np.float32))
    np.testing.assert_array_equal(np.asarray(out), np.ones((16, 2)))


def test_collective_wire_bytes_model():
    assert collective_wire_bytes(1, 1000) == 0  # one device moves nothing
    assert collective_wire_bytes(2, 1000) == 2000
    assert collective_wire_bytes(8, 1000) == 14000
    assert payload_nbytes({"a": np.zeros(4, np.float64)}) == 32


# ---------------------------------------------------------------------------
# equivalence matrix — the five call sites + the fused serve program
# ---------------------------------------------------------------------------


def _agg_over(size, x):
    """One tree_aggregate (sum + gram) over a mesh of ``size``."""
    mesh = default_mesh(size)

    def moments(xs, w):
        xw = xs * w[:, None]
        return {"sum": xw.sum(axis=0), "gram": xw.T @ xs}

    agg = make_tree_aggregate(moments, mesh)
    out = agg(*shard_batch(mesh, x))
    return {k: np.asarray(v) for k, v in out.items()}


def test_tree_aggregate_bitwise_f64_across_mesh_sizes():
    """f64 + integer-valued rows: the psum tree is EXACT, so every mesh
    size must agree bit for bit (jax.experimental.enable_x64 scopes the
    f64 leg to this test)."""
    rng = np.random.default_rng(7)
    x = rng.integers(-50, 50, size=(512, 6)).astype(np.float64)
    from jax.experimental import enable_x64

    with enable_x64():
        results = {s: _agg_over(s, x) for s in MESH_SIZES}
    base = results[1]
    assert base["sum"].dtype == np.float64
    np.testing.assert_array_equal(base["sum"], x.sum(axis=0))
    for s in MESH_SIZES[1:]:
        for k in base:
            np.testing.assert_array_equal(
                base[k], results[s][k],
                err_msg=f"mesh {s} leaf {k} not bitwise-equal to mesh 1",
            )


def test_tree_aggregate_f32_pinned_tolerance():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(512, 6)).astype(np.float32)
    results = {s: _agg_over(s, x) for s in MESH_SIZES}
    for s in MESH_SIZES[1:]:
        for k in results[1]:
            np.testing.assert_allclose(
                results[1][k], results[s][k], rtol=1e-5, atol=1e-5,
            )


def _blobs(seed=0, n=960, k=3, d=4, scale=8.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * scale
    y = rng.integers(0, k, size=n)
    X = (centers[y] + rng.normal(size=(n, d))).astype(np.float32)
    return X, y


def test_kmeans_equivalence_across_mesh_sizes():
    from sntc_tpu.models import KMeans

    X, _ = _blobs()
    f = Frame({"features": X})
    fits = {
        s: KMeans(mesh=default_mesh(s), k=3, seed=1, maxIter=15).fit(f)
        for s in MESH_SIZES
    }
    base = np.asarray(fits[1].clusterCenters, np.float64)
    base_pred = np.asarray(fits[1].transform(f)["prediction"])
    for s in MESH_SIZES[1:]:
        np.testing.assert_allclose(
            np.asarray(fits[s].clusterCenters, np.float64), base,
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_array_equal(
            np.asarray(fits[s].transform(f)["prediction"]), base_pred
        )


def test_lda_e_step_equivalence_across_mesh_sizes():
    from sntc_tpu.models.lda import _run_e_step

    rng = np.random.default_rng(5)
    counts = rng.integers(0, 6, size=(64, 40)).astype(np.float32)
    k = 5
    eeb = np.exp(rng.normal(size=(k, 40)).astype(np.float32) * 0.1)
    key = jax.random.PRNGKey(0)
    outs = {
        s: _run_e_step(default_mesh(s), counts, eeb, 0.1, key, 20)
        for s in MESH_SIZES
    }
    g1, s1 = (np.asarray(a) for a in outs[1])
    for s in MESH_SIZES[1:]:
        g, st = (np.asarray(a) for a in outs[s])
        np.testing.assert_allclose(st, s1, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(g, g1, rtol=1e-5, atol=1e-4)


def test_pic_equivalence_across_mesh_sizes():
    from sntc_tpu.models import PowerIterationClustering

    rng = np.random.default_rng(2)
    n = 40
    src, dst, w = [], [], []
    for i in range(n):
        for j in range(i + 1, n):
            same = (i < n // 2) == (j < n // 2)
            if rng.random() < (0.8 if same else 0.05):
                src.append(i)
                dst.append(j)
                w.append(1.0 if same else 0.1)
    f = Frame({
        "src": np.array(src, np.int64), "dst": np.array(dst, np.int64),
        "weight": np.array(w, np.float64),
    })
    labels = {}
    for s in MESH_SIZES:
        out = PowerIterationClustering(
            mesh=default_mesh(s), k=2, maxIter=25, weightCol="weight",
            seed=1,
        ).assignClusters(f)
        order = np.argsort(np.asarray(out["id"]))
        labels[s] = np.asarray(out["cluster"])[order]
    for s in MESH_SIZES[1:]:
        a, b = labels[1], labels[s]
        # identical partition, cluster ids may swap
        assert (
            np.array_equal(a, b) or np.array_equal(a, 1 - b)
        ), f"mesh {s} partition differs from mesh 1"


def test_tree_histogram_equivalence_across_mesh_sizes():
    from sntc_tpu.models import DecisionTreeClassifier

    X, _ = _blobs(seed=4)
    y = (X[:, 0] > X[:, 0].mean()).astype(np.float64)
    f = Frame({"features": X, "label": y})
    preds = {}
    for s in MESH_SIZES:
        m = DecisionTreeClassifier(
            mesh=default_mesh(s), maxDepth=3, seed=1
        ).fit(f)
        preds[s] = np.asarray(m.transform(f)["prediction"])
    for s in MESH_SIZES[1:]:
        np.testing.assert_array_equal(preds[1], preds[s])
    assert float((preds[1] == y).mean()) > 0.9


def test_fused_lr_serve_equivalence_serve_mesh(mesh8, monkeypatch):
    """The fused serve program answers identically with and without a
    serve mesh (shard the dispatch rows over 8 devices vs single-device
    placement) — predictions bitwise, probabilities ≤1e-5."""
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.feature import StandardScaler
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.parallel.context import reset_serve_mesh, set_serve_mesh
    from sntc_tpu.serve.fuse import compile_serving

    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")  # force device path
    rng = np.random.default_rng(0)
    X = rng.normal(3.0, 2.0, size=(1024, 6)).astype(np.float32)
    y = (X[:, 0] > 3.0).astype(np.float64)
    f = Frame({"features": X, "label": y})
    pm = Pipeline(stages=[
        StandardScaler(mesh=mesh8, inputCol="features",
                       outputCol="scaled", withMean=True),
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=30),
    ]).fit(f)
    fused = compile_serving(pm)
    try:
        set_serve_mesh(None)
        single = fused.transform(f)
        set_serve_mesh(default_mesh(8))
        sharded = fused.transform(f)
    finally:
        reset_serve_mesh()
    np.testing.assert_array_equal(
        np.asarray(single["prediction"]), np.asarray(sharded["prediction"])
    )
    np.testing.assert_allclose(
        np.asarray(single["probability"]),
        np.asarray(sharded["probability"]), rtol=1e-5, atol=1e-6,
    )


# ---------------------------------------------------------------------------
# elastic resize / OOM split
# ---------------------------------------------------------------------------


def _int_batch(n=512, d=6, seed=11):
    rng = np.random.default_rng(seed)
    return rng.integers(-20, 20, size=(n, d)).astype(np.float32)


def _sum_fn(xs, w):
    xw = xs * w[:, None]
    return {"sum": xw.sum(axis=0), "gram": xw.T @ xs}


def test_device_lost_resizes_mesh_and_result_is_bitwise(mesh8):
    from sntc_tpu.resilience.device import DeviceFaultDomain

    x = _int_batch()
    baseline = make_tree_aggregate(_sum_fn, mesh8)(*shard_batch(mesh8, x))
    dom = DeviceFaultDomain(probe_async=False)
    set_collective_domain(dom)
    agg = make_tree_aggregate(_sum_fn, mesh8)
    before = _get("sntc_collective_resizes_total")
    R.arm("collective.dispatch", kind="device_lost", times=1)
    out = agg(*shard_batch(mesh8, x))
    assert int(agg.mesh().shape[DATA_AXIS]) == 4  # 8 -> shrink to 4
    for k in ("sum", "gram"):
        np.testing.assert_array_equal(
            np.asarray(out[k]), np.asarray(baseline[k])
        )
    assert _get("sntc_collective_resizes_total") == before + 1
    assert _get("sntc_collective_mesh_devices", axis=DATA_AXIS) == 4
    decisions = [r.get("decision") for r in dom.journal]
    assert "mesh_resize" in decisions
    assert not dom.host_degraded
    # a batch sharded for the ORIGINAL mesh still dispatches (lazy
    # migration onto the survivors)
    out2 = agg(*shard_batch(mesh8, x))
    np.testing.assert_array_equal(
        np.asarray(out2["sum"]), np.asarray(baseline["sum"])
    )


def test_resize_disabled_env_propagates(mesh8, monkeypatch):
    monkeypatch.setenv("SNTC_MESH_RESIZE", "0")
    agg = make_tree_aggregate(_sum_fn, mesh8)
    x = _int_batch(n=64)
    R.arm("collective.dispatch", kind="device_lost", times=1)
    with pytest.raises(Exception) as ei:
        agg(*shard_batch(mesh8, x))
    assert "device" in str(ei.value).lower()


def test_single_device_mesh_never_resizes():
    mesh1 = default_mesh(1)
    agg = make_tree_aggregate(_sum_fn, mesh1)
    x = _int_batch(n=64)
    R.arm("collective.dispatch", kind="device_lost", times=1)
    with pytest.raises(Exception):
        agg(*shard_batch(mesh1, x))


def test_device_oom_splits_and_sums_bitwise(mesh8):
    from sntc_tpu.resilience.device import DeviceFaultDomain

    x = _int_batch(seed=13)
    baseline = make_tree_aggregate(_sum_fn, mesh8)(*shard_batch(mesh8, x))
    dom = DeviceFaultDomain(probe_async=False)
    set_collective_domain(dom)
    agg = make_tree_aggregate(_sum_fn, mesh8)
    R.arm("collective.dispatch", kind="device_oom", times=1)
    out = agg(*shard_batch(mesh8, x))
    for k in ("sum", "gram"):
        np.testing.assert_array_equal(
            np.asarray(out[k]), np.asarray(baseline[k])
        )
    assert dom.oom_splits == 1
    assert int(agg.mesh().shape[DATA_AXIS]) == 8  # no resize on OOM


def test_resize_mid_fit_converges_with_survivors(mesh8):
    """The chaos claim in miniature: a participant dies mid-ALS-fit
    (the one estimator whose loop dispatches the aggregate per
    iteration — LR/LinReg run their whole optimizer inside one XLA
    program), the fit resizes onto the survivors and still converges;
    the decision is journaled, the host never degrades."""
    from sntc_tpu.models import ALS
    from sntc_tpu.resilience.device import DeviceFaultDomain

    rng = np.random.default_rng(0)
    n_u, n_i, rank = 40, 30, 3
    U = rng.normal(size=(n_u, rank)) / np.sqrt(rank)
    V = rng.normal(size=(n_i, rank)) / np.sqrt(rank)
    full = U @ V.T + 2.0
    mask = rng.random((n_u, n_i)) < 0.6
    uu, ii = np.nonzero(mask)
    f = Frame({
        "user": uu.astype(np.int64), "item": ii.astype(np.int64),
        "rating": full[uu, ii].astype(np.float32),
    })
    dom = DeviceFaultDomain(probe_async=False)
    set_collective_domain(dom)
    # fire mid-fit: let the first iteration's dispatches succeed first
    R.arm("collective.dispatch", kind="device_lost", after=3, times=1)
    m = ALS(
        mesh=mesh8, rank=4, maxIter=10, regParam=0.02, seed=2
    ).fit(f)
    pred = np.asarray(
        m.transform(Frame({"user": uu, "item": ii}))["prediction"]
    )
    rmse = float(np.sqrt(np.mean((pred - full[uu, ii]) ** 2)))
    assert rmse < 0.1, rmse  # noiseless low-rank: survivors converged
    decisions = [r.get("decision") for r in dom.journal]
    assert "mesh_resize" in decisions
    assert not dom.host_degraded


# ---------------------------------------------------------------------------
# transfer-ledger attribution (satellite bugfix regression)
# ---------------------------------------------------------------------------


def test_shard_placement_lands_in_transfer_ledger(mesh8):
    from sntc_tpu.utils.profiling import TransferLedger, ledger_scope

    led = TransferLedger()
    x = np.random.default_rng(1).normal(size=(256, 4)).astype(np.float32)
    with ledger_scope(led):
        shard_batch(mesh8, x)
    snap = led.snapshot()
    # the batch array + the weights column both crossed the host link
    assert snap["uploads"] >= 2, snap
    assert snap["upload_bytes"] >= x.nbytes, snap
    # movement is NOT a fused dispatch — the dispatch series keeps
    # meaning "fused program calls"
    assert snap["dispatches"] == 0, snap


def test_resize_replacement_attributed_to_ledger(mesh8):
    from sntc_tpu.utils.profiling import TransferLedger, ledger_scope

    led = TransferLedger()
    x = _int_batch(n=128, seed=17)
    agg = make_tree_aggregate(_sum_fn, mesh8)
    with ledger_scope(led):
        args = shard_batch(mesh8, x)
        placed = led.snapshot()["upload_bytes"]
        R.arm("collective.dispatch", kind="device_lost", times=1)
        agg(*args)
    snap = led.snapshot()
    # the resize re-placed the batch on the survivors: strictly more
    # bytes than the initial placement, still zero dispatches
    assert snap["upload_bytes"] > placed, snap
    assert snap["dispatches"] == 0, snap


# ---------------------------------------------------------------------------
# evidence metrics
# ---------------------------------------------------------------------------


def test_collective_dispatch_metrics(mesh8):
    x = np.ones((64, 3), np.float32)
    d0 = _get("sntc_collective_dispatches_total",
              op="tree_aggregate", axis=DATA_AXIS)
    b0 = _get("sntc_collective_bytes_moved_total",
              op="tree_aggregate", axis=DATA_AXIS)
    agg = make_tree_aggregate(
        lambda xs, w: (xs * w[:, None]).sum(axis=0), mesh8
    )
    out = agg(*shard_batch(mesh8, x))
    assert _get("sntc_collective_dispatches_total",
                op="tree_aggregate", axis=DATA_AXIS) == d0 + 1
    wire = collective_wire_bytes(8, int(out.nbytes))
    assert _get("sntc_collective_bytes_moved_total",
                op="tree_aggregate", axis=DATA_AXIS) == b0 + wire
    assert _get("sntc_collective_mesh_devices", axis=DATA_AXIS) == 8


def test_model_op_metrics_emitted(mesh8):
    from sntc_tpu.models import KMeans

    X, _ = _blobs(seed=9, n=256)
    d0 = _get("sntc_collective_dispatches_total",
              op="kmeans.lloyd", axis=DATA_AXIS)
    KMeans(mesh=mesh8, k=2, seed=1, maxIter=5).fit(Frame({"features": X}))
    assert _get("sntc_collective_dispatches_total",
                op="kmeans.lloyd", axis=DATA_AXIS) > d0


# ---------------------------------------------------------------------------
# drift check wiring
# ---------------------------------------------------------------------------


def test_mesh_axes_consistent_code_registry_docs():
    checker = _load_script("check_mesh_axes")
    assert checker.check() == []
