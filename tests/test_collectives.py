import jax
import jax.numpy as jnp
import numpy as np

from sntc_tpu.parallel import (
    make_tree_aggregate,
    pad_rows,
    shard_batch,
)


def test_pad_rows():
    assert pad_rows(16, 8) == 16
    assert pad_rows(17, 8) == 24
    assert pad_rows(1, 8) == 8


def test_shard_batch_pads_with_zero_weights(mesh8):
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    (xs, w) = shard_batch(mesh8, x)
    assert xs.shape == (16, 1)
    assert w.shape == (16,)
    np.testing.assert_array_equal(np.asarray(w), [1] * 10 + [0] * 6)
    # padding replicates row 0, not garbage
    assert np.asarray(xs)[10:].tolist() == [[0.0]] * 6


def test_tree_aggregate_matches_numpy(mesh8):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(100, 4)).astype(np.float32)
    y = rng.normal(size=(100,)).astype(np.float32)
    xs, ys, w = shard_batch(mesh8, x, y)

    def weighted_moments(xs, ys, w):
        return {
            "sum_x": jnp.einsum("n,nd->d", w, xs),
            "sum_xy": jnp.einsum("n,nd,n->d", w, xs, ys),
            "count": jnp.sum(w),
        }

    agg = make_tree_aggregate(weighted_moments, mesh8)
    out = agg(xs, ys, w)
    np.testing.assert_allclose(np.asarray(out["sum_x"]), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out["sum_xy"]), (x * y[:, None]).sum(0), rtol=1e-4
    )
    assert float(out["count"]) == 100.0


def test_tree_aggregate_result_replicated(mesh8):
    x = np.ones((8, 2), dtype=np.float32)
    xs, w = shard_batch(mesh8, x)
    agg = make_tree_aggregate(lambda xs, w: jnp.sum(xs * w[:, None]), mesh8)
    out = agg(xs, w)
    assert float(out) == 16.0
    # replicated output: every device holds the full value
    assert out.sharding.is_fully_replicated
