import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import MultilayerPerceptronClassifier


def _xor_data(n=2000, seed=0):
    """Nonlinearly separable data a linear model cannot fit."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float64)
    return Frame({"features": X, "label": y})


def _multi_blobs(n=3000, k=4, seed=1):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, 6)) * 3
    y = rng.integers(0, k, size=n)
    X = (centers[y] + rng.normal(size=(n, 6))).astype(np.float32)
    return Frame({"features": X, "label": y.astype(np.float64)}), y


def test_mlp_learns_xor(mesh8):
    f = _xor_data()
    model = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[2, 16, 2], maxIter=200, seed=3
    ).fit(f)
    out = model.transform(f)
    acc = (out["prediction"] == f["label"]).mean()
    assert acc > 0.95, acc
    # objective decreased
    h = model.summary.objectiveHistory
    assert h[-1] < h[0] * 0.5


def test_mlp_multiclass_and_columns(mesh8):
    f, y = _multi_blobs()
    model = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[6, 12, 4], maxIter=150, seed=0
    ).fit(f)
    out = model.transform(f)
    assert out["probability"].shape == (3000, 4)
    np.testing.assert_allclose(out["probability"].sum(1), 1.0, rtol=1e-5)
    assert (out["prediction"] == y).mean() > 0.97


def test_mlp_seed_determinism(mesh8):
    f = _xor_data(500)
    kw = dict(mesh=mesh8, layers=[2, 8, 2], maxIter=30, seed=7)
    m1 = MultilayerPerceptronClassifier(**kw).fit(f)
    m2 = MultilayerPerceptronClassifier(**kw).fit(f)
    np.testing.assert_array_equal(m1.weights, m2.weights)


def test_mlp_initial_weights_and_validation(mesh8):
    f = _xor_data(200)
    with pytest.raises(ValueError, match="layers\\[0\\]"):
        MultilayerPerceptronClassifier(mesh=mesh8, layers=[3, 2], maxIter=1).fit(f)
    with pytest.raises(ValueError, match="output layer"):
        MultilayerPerceptronClassifier(mesh=mesh8, layers=[2, 1], maxIter=1).fit(f)
    n_w = 2 * 8 + 8 + 8 * 2 + 2
    m = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[2, 8, 2], maxIter=0,
        initialWeights=np.arange(n_w, dtype=np.float32) / n_w,
    ).fit(f)
    np.testing.assert_allclose(m.weights, np.arange(n_w) / n_w, rtol=1e-6)


def test_mlp_gd_solver(mesh8):
    f = _xor_data(800)
    model = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[2, 8, 2], maxIter=300, solver="gd", stepSize=0.5, seed=1
    ).fit(f)
    h = model.summary.objectiveHistory
    assert h[-1] < h[0]


def test_mlp_save_load(tmp_path, mesh8):
    f = _xor_data(300)
    m = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[2, 6, 2], maxIter=20
    ).fit(f)
    save_model(m, str(tmp_path / "mlp"))
    loaded = load_model(str(tmp_path / "mlp"))
    np.testing.assert_array_equal(loaded.weights, m.weights)
    np.testing.assert_array_equal(
        loaded.transform(f)["prediction"], m.transform(f)["prediction"]
    )


def test_bfloat16_compute_dtype_close_to_f32(mesh8):
    f, y = _multi_blobs(n=2000, k=3, seed=8)
    kw = dict(mesh=mesh8, layers=[6, 16, 3], maxIter=40, seed=0)
    m32 = MultilayerPerceptronClassifier(**kw).fit(f)
    m16 = MultilayerPerceptronClassifier(computeDtype="bfloat16", **kw).fit(f)
    acc32 = (m32.transform(f)["prediction"] == y).mean()
    acc16 = (m16.transform(f)["prediction"] == y).mean()
    assert acc16 > acc32 - 0.03, (acc16, acc32)
    with pytest.raises(ValueError):
        MultilayerPerceptronClassifier(computeDtype="float16", **kw)


def test_mlp_serve_paths_agree(mesh8, monkeypatch):
    """Host (numpy), sync device, and fused async device serve paths all
    produce the same columns (placement must never change results)."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(600, 6)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    f = Frame({"features": X, "label": y})
    m = MultilayerPerceptronClassifier(
        mesh=mesh8, layers=[6, 8, 2], maxIter=25, seed=0
    ).fit(f)

    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")  # force device
    dev = m.transform(f)
    dev_async = m.transform_async(f)()
    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "100000")  # force host
    host = m.transform(f)
    for col in ("rawPrediction", "probability"):
        np.testing.assert_allclose(dev[col], host[col], atol=1e-5)
        np.testing.assert_allclose(dev_async[col], dev[col], atol=1e-6)
    np.testing.assert_array_equal(dev["prediction"], host["prediction"])
    np.testing.assert_array_equal(dev_async["prediction"], dev["prediction"])
