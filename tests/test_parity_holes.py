"""Round-2 parity closures (VERDICT item 8): LR bound-constrained fit,
per-class ``thresholds``, multiclass-evaluator ``weightCol``."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.evaluation.multiclass import MulticlassClassificationEvaluator
from sntc_tpu.models.logistic_regression import LogisticRegression


def _binary(n=3000, d=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    true_w = np.array([1.5, -2.0, 0.8, 0.0, -0.5])
    p = 1.0 / (1.0 + np.exp(-(X @ true_w + 0.3)))
    y = (rng.random(n) < p).astype(np.float64)
    return Frame({"features": X, "label": y}), X, y


# ---------------------------------------------------------------------------
# bound-constrained LR
# ---------------------------------------------------------------------------


def test_lr_nonnegative_bounds_respected_and_optimal():
    frame, X, y = _binary()
    d = X.shape[1]
    lr = LogisticRegression(
        maxIter=200, regParam=0.0, tol=1e-9,
        lowerBoundsOnCoefficients=np.zeros((1, d)),
    )
    model = lr.fit(frame)
    coef = model.coefficients
    assert (coef >= -1e-5).all()
    # constrained optimum from scipy L-BFGS-B on the same objective
    from scipy.optimize import minimize

    def obj(theta):
        w, b = theta[:d], theta[d]
        z = X @ w + b
        return float(np.mean(np.logaddexp(0.0, z) - y * z))

    res = minimize(
        obj,
        np.zeros(d + 1),
        method="L-BFGS-B",
        bounds=[(0, None)] * d + [(None, None)],
    )
    ours = obj(np.concatenate([coef, [model.intercept]]))
    assert ours == pytest.approx(res.fun, abs=2e-4)


def test_lr_interval_bounds_multinomial():
    rng = np.random.default_rng(3)
    n, d, k = 2000, 4, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, k))
    y = np.argmax(X @ W + 0.3 * rng.normal(size=(n, k)), axis=1).astype(
        np.float64
    )
    frame = Frame({"features": X, "label": y})
    lb = np.full((k, d), -0.5)
    ub = np.full((k, d), 0.5)
    model = LogisticRegression(
        maxIter=100, family="multinomial",
        lowerBoundsOnCoefficients=lb, upperBoundsOnCoefficients=ub,
        lowerBoundsOnIntercepts=np.full(k, -0.1),
        upperBoundsOnIntercepts=np.full(k, 0.1),
    ).fit(frame)
    assert (model.coefficientMatrix >= -0.5 - 1e-5).all()
    assert (model.coefficientMatrix <= 0.5 + 1e-5).all()
    assert (np.abs(model.interceptVector) <= 0.1 + 1e-5).all()


def test_lr_bounds_reject_l1():
    frame, _, _ = _binary(n=200)
    lr = LogisticRegression(
        regParam=0.1, elasticNetParam=0.5,
        lowerBoundsOnCoefficients=np.zeros((1, 5)),
    )
    with pytest.raises(ValueError, match="L2"):
        lr.fit(frame)


def test_lr_bounds_shape_validation():
    frame, _, _ = _binary(n=200)
    lr = LogisticRegression(lowerBoundsOnCoefficients=np.zeros((2, 3)))
    with pytest.raises(ValueError, match="shape"):
        lr.fit(frame)


# ---------------------------------------------------------------------------
# per-class thresholds
# ---------------------------------------------------------------------------


def test_thresholds_scale_predictions():
    rng = np.random.default_rng(5)
    n, k = 500, 3
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = rng.integers(0, k, n).astype(np.float64)
    frame = Frame({"features": X, "label": y})
    model = LogisticRegression(maxIter=20, family="multinomial").fit(frame)
    base = model.transform(frame)["prediction"]
    # huge threshold on class 0 suppresses it entirely
    model.setThresholds([1e6, 1.0, 1.0])
    pred = model.transform(frame)["prediction"]
    assert not (pred == 0.0).any()
    # equal thresholds reproduce plain argmax
    model.setThresholds([1.0, 1.0, 1.0])
    np.testing.assert_array_equal(
        model.transform(frame)["prediction"], base
    )
    # zero threshold wins whenever its probability is positive
    model.setThresholds([0.0, 1.0, 1.0])
    assert (model.transform(frame)["prediction"] == 0.0).all()


def test_thresholds_validation():
    frame, _, _ = _binary(n=100)
    model = LogisticRegression(maxIter=5).fit(frame)
    model.setThresholds([0.5, 0.5, 0.5])
    with pytest.raises(ValueError, match="numClasses"):
        model.transform(frame)
    model.setThresholds([0.0, 0.0])
    with pytest.raises(ValueError, match="one zero"):
        model.transform(frame)


# ---------------------------------------------------------------------------
# evaluator weightCol
# ---------------------------------------------------------------------------


def test_multiclass_evaluator_weight_col():
    y = np.array([0, 0, 1, 1, 2], np.float64)
    p = np.array([0, 1, 1, 1, 0], np.float64)
    w = np.array([2.0, 1.0, 1.0, 3.0, 1.0])
    frame = Frame({"label": y, "prediction": p, "w": w})
    acc_w = MulticlassClassificationEvaluator(
        metricName="accuracy", weightCol="w"
    ).evaluate(frame)
    # weighted accuracy: correct rows weigh 2+1+3 of total 8
    assert acc_w == pytest.approx(6.0 / 8.0)
    acc = MulticlassClassificationEvaluator(metricName="accuracy").evaluate(
        frame
    )
    assert acc == pytest.approx(3.0 / 5.0)


def test_multiclass_evaluator_weighted_logloss():
    y = np.array([0, 1], np.float64)
    prob = np.array([[0.8, 0.2], [0.4, 0.6]])
    w = np.array([3.0, 1.0])
    frame = Frame({"label": y, "prediction": y, "probability": prob, "w": w})
    ev = MulticlassClassificationEvaluator(metricName="logLoss", weightCol="w")
    expect = (3.0 * -np.log(0.8) + 1.0 * -np.log(0.6)) / 4.0
    assert ev.evaluate(frame) == pytest.approx(expect)


# ---------------------------------------------------------------------------
# code-review regressions (round 2)
# ---------------------------------------------------------------------------


def test_string_indexer_nan_roundtrips_through_fit_vocab():
    from sntc_tpu.feature.string_indexer import StringIndexer

    vals = np.array(["a", "b", np.nan, "a", np.nan, np.nan], dtype=object)
    frame = Frame({"label": vals})
    model = StringIndexer(handleInvalid="error").fit(frame)
    assert "nan" in model.labels
    out = model.transform(frame)["labelIndex"]
    assert out[2] == out[4] == float(model.labels.index("nan"))


def test_lr_inf_bounds_with_constant_feature():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(500, 3)).astype(np.float32)
    X[:, 1] = 7.0  # zero-variance feature
    y = (X[:, 0] > 0).astype(np.float64)
    frame = Frame({"features": X, "label": y})
    lb = np.array([[-np.inf, -np.inf, 0.0]])
    model = LogisticRegression(
        maxIter=50, lowerBoundsOnCoefficients=lb
    ).fit(frame)
    assert model.coefficients[1] == 0.0  # constant feature -> 0
    assert model.coefficients[2] >= -1e-6


def test_gbt_binary_guard_sees_validation_rows():
    from sntc_tpu.models.tree.gbt import GBTClassifier

    X = np.random.default_rng(0).normal(size=(100, 3)).astype(np.float32)
    y = np.zeros(100)
    y[:10] = 2.0  # multiclass labels hidden in the validation split
    is_val = np.zeros(100, bool)
    is_val[:10] = True
    y[10:60] = 1.0
    frame = Frame({"features": X, "label": y, "isVal": is_val})
    gbt = GBTClassifier(maxIter=3, validationIndicatorCol="isVal")
    with pytest.raises(ValueError, match="binary-only"):
        gbt.fit(frame)


def test_string_indexer_none_roundtrips_through_fit_vocab():
    from sntc_tpu.feature.string_indexer import StringIndexer

    vals = np.array(["a", None, "a", np.nan], dtype=object)
    frame = Frame({"label": vals})
    model = StringIndexer(handleInvalid="error").fit(frame)
    out = model.transform(frame)["labelIndex"]
    assert out[1] == float(model.labels.index("None"))
    assert out[3] == float(model.labels.index("nan"))
