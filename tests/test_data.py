import numpy as np

from sntc_tpu.data import (
    CICIDS2017_FEATURES,
    CICIDS2017_LABELS,
    clean_flows,
    generate_frame,
    load_csv_dir,
    write_day_csvs,
)
from sntc_tpu.data.ingest import cache_parquet, load_parquet
from sntc_tpu.data.schema import LABEL_COLUMN, normalize_label


def test_schema_constants():
    assert len(CICIDS2017_FEATURES) == 78
    assert len(CICIDS2017_LABELS) == 15
    assert len(set(CICIDS2017_FEATURES)) == 78


def test_generate_frame_shape_and_labels():
    f = generate_frame(5000, seed=0)
    assert f.num_rows == 5000
    assert set(f.columns) == set(CICIDS2017_FEATURES) | {LABEL_COLUMN}
    present = set(np.unique(f[LABEL_COLUMN].astype(str)))
    assert "BENIGN" in present
    assert present <= set(CICIDS2017_LABELS)
    # benign-heavy imbalance
    benign_frac = (f[LABEL_COLUMN].astype(str) == "BENIGN").mean()
    assert 0.7 < benign_frac < 0.9


def test_dirty_values_injected_and_cleaned():
    f = generate_frame(2000, seed=1, dirty=True)
    stacked = np.stack([f[c] for c in CICIDS2017_FEATURES], axis=1)
    assert not np.isfinite(stacked).all()
    cleaned = clean_flows(f)
    assert cleaned.num_rows < f.num_rows
    stacked = np.stack([cleaned[c] for c in CICIDS2017_FEATURES], axis=1)
    assert np.isfinite(stacked).all()
    assert stacked.dtype == np.float32

    zeroed = clean_flows(f, handle_invalid="zero")
    assert zeroed.num_rows == f.num_rows


def test_label_normalization():
    assert normalize_label(" BENIGN ") == "BENIGN"
    assert normalize_label("Web Attack \x96 XSS") == "Web Attack - XSS"


def test_csv_roundtrip_dedups_duplicate_header(tmp_path):
    # raw day files contain 'Fwd Header Length' twice; ingest must map the
    # second occurrence to 'Fwd Header Length.1'
    write_day_csvs(str(tmp_path), n_rows_per_day=50, n_days=2, seed=3)
    header = open(tmp_path / "day0.csv").readline()
    assert header.count("Fwd Header Length") == 2
    assert "Fwd Header Length.1" not in header
    f = load_csv_dir(str(tmp_path))
    assert f.num_rows == 100
    assert set(f.columns) == set(CICIDS2017_FEATURES) | {LABEL_COLUMN}
    assert "Fwd Header Length.1" in f.columns
    cleaned = clean_flows(f)
    assert cleaned.num_rows <= 100


def test_parquet_cache_roundtrip(tmp_path):
    f = clean_flows(generate_frame(100, seed=2))
    path = cache_parquet(f, str(tmp_path / "cache.parquet"))
    g = load_parquet(path)
    assert g.num_rows == f.num_rows
    np.testing.assert_allclose(g["Flow Duration"], f["Flow Duration"])


def test_load_csv_dir_parallel_preserves_order(tmp_path):
    """r8 satellite: the threaded day-file reader must concatenate rows
    in sorted-filename order, identical to a serial read."""
    d = str(tmp_path / "days")
    write_day_csvs(d, n_rows_per_day=200, n_days=6, seed=9)
    parallel = load_csv_dir(d)
    serial = load_csv_dir(d, max_workers=1)
    assert parallel.num_rows == serial.num_rows == 1200
    assert parallel.columns == serial.columns
    for col in parallel.columns:
        np.testing.assert_array_equal(parallel[col], serial[col])
