"""Autotuned zero-copy ingest engine (r15): the declarative source
graph, the feedback autotuner (convergence + the no-oscillation
guarantee), the zero-copy columnar loader's bitwise contract against
the legacy ``load_csv``/``clean_flows`` path, and the
CLI ⇔ knobs ⇔ catalog ⇔ docs drift check."""

import os
import sys

import numpy as np
import pyarrow.csv as pacsv
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.data import (
    CICIDS2017_FEATURES,
    clean_flows,
    generate_frame,
    load_csv,
    load_csv_dir,
    write_day_csvs,
)
from sntc_tpu.data.autotune import (
    AutotunePolicy,
    IngestAutotuner,
    Signal,
    TuningBudget,
)
from sntc_tpu.data.ingest import cache_parquet, load_parquet
from sntc_tpu.data.pipeline import (
    DEFAULT_BOUNDS,
    KNOB_NAMES,
    STAGES,
    Knob,
    describe_graph,
    graph_knobs,
    load_flows_columnar,
    read_flows_columnar,
)
from sntc_tpu.data.schema import LABEL_COLUMN, normalize_label
from sntc_tpu.serve import (
    CsvDirSink,
    FileStreamSource,
    MemorySink,
    MemorySource,
    StreamingQuery,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the one-pass clean_flows + zero-copy loader bitwise contracts
# ---------------------------------------------------------------------------


def _legacy_clean(frame, label_col=LABEL_COLUMN, handle_invalid="drop"):
    """The pre-r15 clean_flows, verbatim — the bitwise reference."""
    feature_cols = [c for c in frame.columns if c != label_col]
    cleaned = {}
    bad = np.zeros(frame.num_rows, dtype=bool)
    for name in feature_cols:
        col = frame[name].astype(np.float32, copy=True)
        invalid = ~np.isfinite(col)
        if invalid.any():
            if handle_invalid == "drop":
                bad |= invalid
            else:
                col[invalid] = 0.0
        cleaned[name] = col
    if label_col in frame:
        cleaned[label_col] = np.array(
            [normalize_label(str(v)) for v in frame[label_col]],
            dtype=object,
        )
    out = Frame(cleaned)
    if handle_invalid == "drop" and bad.any():
        out = out.filter(~bad)
    return out


def _frames_bitwise(a, b):
    assert a.columns == b.columns
    assert a.num_rows == b.num_rows
    for c in a.columns:
        assert a[c].dtype == b[c].dtype, c
        assert np.array_equal(a[c], b[c]), c


@pytest.mark.parametrize("mode", ["drop", "zero"])
def test_clean_flows_one_pass_bitwise(mode):
    frame = generate_frame(4000, seed=11, dirty=True)
    _frames_bitwise(
        clean_flows(frame, handle_invalid=mode),
        _legacy_clean(frame, handle_invalid=mode),
    )


def test_clean_flows_single_contiguous_block():
    """The r15 layout claim: every scalar feature column is a view into
    ONE contiguous float32 block (no per-column materializations)."""
    frame = generate_frame(1000, seed=3, dirty=False)
    out = clean_flows(frame, handle_invalid="zero")
    feats = [c for c in out.columns if c != LABEL_COLUMN]
    assert len({id(out[c].base) for c in feats}) == 1
    first = out[feats[0]]
    assert first.base is not None and first.base.dtype == np.float32
    assert all(out[c].base is first.base for c in feats)
    assert all(out[c].flags.c_contiguous for c in feats)


@pytest.fixture(scope="module")
def day_csvs(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("days"))
    return out, write_day_csvs(out, n_rows_per_day=1500, n_days=2, seed=5)


@pytest.mark.parametrize("mode", ["drop", "zero"])
def test_columnar_loader_bitwise_vs_legacy(day_csvs, mode):
    _dir, paths = day_csvs
    _frames_bitwise(
        read_flows_columnar(paths[0], handle_invalid=mode),
        clean_flows(load_csv(paths[0]), handle_invalid=mode),
    )


def test_columnar_dir_loader_bitwise(day_csvs):
    csv_dir, _paths = day_csvs
    _frames_bitwise(
        load_flows_columnar(csv_dir),
        clean_flows(load_csv_dir(csv_dir)),
    )


def test_columnar_loader_zero_copy_views(day_csvs):
    """Feature columns come out as float32 views over Arrow buffers —
    no post-parse host materialization."""
    _dir, paths = day_csvs
    frame = read_flows_columnar(paths[0], handle_invalid=None)
    feats = [c for c in frame.columns if c != LABEL_COLUMN]
    assert feats  # sanity
    for c in feats:
        assert frame[c].dtype == np.float32
        assert not frame[c].flags.owndata  # a view, not a copy
    # serve face: row count untouched (admission owns row policy)
    assert frame.num_rows == load_csv(paths[0]).num_rows


def test_columnar_invalid_mode_rejected(day_csvs):
    _dir, paths = day_csvs
    with pytest.raises(ValueError, match="handle_invalid"):
        read_flows_columnar(paths[0], handle_invalid="impute")


def test_load_parquet_memory_map_roundtrip(tmp_path):
    frame = clean_flows(generate_frame(800, seed=9))
    path = cache_parquet(frame, str(tmp_path / "cache.parquet"))
    _frames_bitwise(load_parquet(path), frame)
    _frames_bitwise(load_parquet(path, memory_map=False), frame)


def test_columnar_frame_predicts_bitwise_with_legacy(
    day_csvs, mesh8, tmp_path
):
    """The upload-dtype claim: a fused program fed the columnar f32
    frame produces BITWISE the predictions of the legacy f64 frame
    (the upload-cast policy applies the same f64→f32 conversion the
    parse-time cast did)."""
    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import VectorAssembler
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import BatchPredictor, compile_serving

    frame = generate_frame(1200, seed=21, dirty=False)
    csv = str(tmp_path / "clean.csv")
    pacsv.write_csv(
        frame.select(CICIDS2017_FEATURES).to_arrow(), csv
    )
    cleaned = clean_flows(frame)
    assembler = VectorAssembler(
        inputCols=CICIDS2017_FEATURES, outputCol="features"
    )
    fit_frame = assembler.transform(cleaned).with_column(
        "label",
        (cleaned[LABEL_COLUMN].astype(str) == "BENIGN").astype(
            np.float64
        ),
    )
    lr = LogisticRegression(mesh=mesh8, maxIter=10).fit(fit_frame)
    served = compile_serving(PipelineModel(stages=[assembler, lr]))
    legacy64 = load_csv(csv)
    columnar32 = read_flows_columnar(csv, handle_invalid=None)
    # legacy keeps the parse dtypes (int64/float64); columnar is f32
    assert any(
        legacy64[c].dtype == np.float64 for c in CICIDS2017_FEATURES
    )
    assert all(
        columnar32[c].dtype == np.float32 for c in CICIDS2017_FEATURES
    )
    p64 = BatchPredictor(served).predict_frame(legacy64)
    p32 = BatchPredictor(served).predict_frame(columnar32)
    np.testing.assert_array_equal(
        p64["prediction"], p32["prediction"]
    )


# ---------------------------------------------------------------------------
# the source graph: meters + live knob resizing
# ---------------------------------------------------------------------------


def _stream_dir(tmp_path, n_files=8, rows=40, seed=0):
    rng = np.random.default_rng(seed)
    in_dir = str(tmp_path / "in")
    os.makedirs(in_dir, exist_ok=True)
    for i in range(n_files):
        chunk = Frame({
            k: rng.normal(size=rows) for k in ("a", "b", "c", "d")
        })
        pacsv.write_csv(
            chunk.to_arrow(), os.path.join(in_dir, f"p_{i:03d}.csv")
        )
    return in_dir


class _ColsModel:
    """Tiny duck-typed served model over the 4-column stream frames."""

    def transform(self, f):
        return f.with_column("prediction", f["a"] + f["b"])

    def transform_async(self, f):
        out = self.transform(f)
        return lambda: out

    def input_columns(self):
        return ["a", "b", "c", "d"]


def test_source_meters_and_graph_description(tmp_path):
    in_dir = _stream_dir(tmp_path)
    src = FileStreamSource(in_dir, prefetch_batches=2, read_workers=2)
    q = StreamingQuery(
        _ColsModel(), src, MemorySink(), str(tmp_path / "ckpt"),
        max_batch_offsets=2,
    )
    assert q.process_available() == 4
    stats = q.pipeline_stats()
    assert set(stats["ingest"]) == set(STAGES)
    assert stats["ingest"]["read"]["count"] == 4
    assert stats["ingest"]["parse"]["count"] == 8  # one per file
    assert stats["ingest"]["bucket"]["count"] == 4
    assert stats["ingest"]["parse"]["ewma_s"] > 0
    desc = describe_graph(q)
    assert list(desc) == list(STAGES)
    assert desc["parse"]["workers"] == 2
    assert desc["stage"]["queue_bound"] == 2
    src.close()


def test_live_knob_resize_mid_stream(tmp_path):
    in_dir = _stream_dir(tmp_path, n_files=10)
    src = FileStreamSource(in_dir, prefetch_batches=1, read_workers=1)
    sink = MemorySink()
    q = StreamingQuery(
        _ColsModel(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1,
    )
    q.process_available()
    knobs = graph_knobs(q)
    assert set(knobs) == set(KNOB_NAMES)
    knobs["read_workers"].set(3)
    knobs["prefetch_batches"].set(4)
    assert src.read_workers == 3 and src.prefetch_batches == 4
    # the resized-out staging pool is RETIRED (still usable by any
    # prefetch thread mid-submit), and close() drains the retirees
    assert src._retired_pools
    # resizing to the same value is a no-op (no pool churn)
    pool_before = src._read_pool
    src.set_read_workers(3)
    assert src._read_pool is pool_before
    # more files arrive; the resized source serves them correctly
    rng = np.random.default_rng(99)
    for i in range(10, 16):
        chunk = Frame({
            k: rng.normal(size=40) for k in ("a", "b", "c", "d")
        })
        pacsv.write_csv(
            chunk.to_arrow(), os.path.join(in_dir, f"p_{i:03d}.csv")
        )
    assert q.process_available() == 6
    assert len(sink.frames) == 16
    src.close()
    assert not src._retired_pools


def test_default_bounds_cover_every_knob():
    assert set(DEFAULT_BOUNDS) == set(KNOB_NAMES)
    for lo, hi in DEFAULT_BOUNDS.values():
        assert 1 <= lo <= hi


# ---------------------------------------------------------------------------
# the autotuner: convergence, hysteresis, no-oscillation, budget
# ---------------------------------------------------------------------------


def _fake_knobs(**spec):
    """name -> (initial, lo, hi) into live Knob objects over dicts."""
    knobs = {}
    for name, (val, lo, hi) in spec.items():
        box = {"v": val}
        knobs[name] = Knob(
            name,
            (lambda b=box: b["v"]),
            (lambda n, b=box: b.__setitem__("v", int(n))),
            lo, hi,
        )
    return knobs


SLOW_READ = Signal(backlog=6, miss_rate=0.9, queue_occupancy=0.3,
                   read_wait_s=0.4, parse_s=0.01, files_per_batch=1)
SLOW_PARSE = Signal(backlog=6, miss_rate=0.3, queue_occupancy=0.3,
                    read_wait_s=0.5, parse_s=0.45, files_per_batch=4)
SATURATED = Signal(backlog=9, miss_rate=0.1, queue_occupancy=1.0,
                   read_wait_s=0.05, parse_s=0.01, files_per_batch=2)
IDLE = Signal(backlog=0, miss_rate=0.0, queue_occupancy=0.0,
              read_wait_s=0.001, parse_s=0.001, files_per_batch=1)


def _drive(tuner, knobs, sig, windows):
    for _ in range(windows):
        tuner.observe(sig, knobs)


def test_autotuner_slow_read_widens_staging():
    """Skewed workload: engine waits on cold reads (single-file
    batches, high miss rate) → the tuner converges prefetch_batches to
    its ceiling and touches nothing else."""
    knobs = _fake_knobs(
        read_workers=(1, 1, 4), prefetch_batches=(1, 1, 6),
        pipeline_depth=(2, 1, 4),
    )
    tuner = IngestAutotuner(policy=AutotunePolicy(confirm=2, cooldown=1))
    _drive(tuner, knobs, SLOW_READ, 40)
    assert knobs["prefetch_batches"].get() == 6  # converged to hi
    assert knobs["read_workers"].get() == 1
    assert knobs["pipeline_depth"].get() == 2
    assert all(d["knob"] == "prefetch_batches" for d in tuner.applied())
    # converged: the last windows apply nothing further
    n = len(tuner.applied())
    _drive(tuner, knobs, SLOW_READ, 20)
    assert len(tuner.applied()) == n


def test_autotuner_slow_parse_adds_workers():
    """Skewed the other way: multi-file batches whose parse dominates
    the read wait → read_workers grows first."""
    knobs = _fake_knobs(
        read_workers=(1, 1, 4), prefetch_batches=(2, 1, 6),
        pipeline_depth=(2, 1, 4),
    )
    tuner = IngestAutotuner(policy=AutotunePolicy(confirm=2, cooldown=1))
    _drive(tuner, knobs, SLOW_PARSE, 12)
    assert knobs["read_workers"].get() > 1
    assert tuner.applied()[0]["knob"] == "read_workers"


def test_autotuner_saturated_staging_deepens_pipeline():
    knobs = _fake_knobs(
        read_workers=(4, 1, 4), prefetch_batches=(6, 1, 6),
        pipeline_depth=(2, 1, 4),
    )
    tuner = IngestAutotuner(policy=AutotunePolicy(confirm=2, cooldown=1))
    _drive(tuner, knobs, SATURATED, 12)
    assert knobs["pipeline_depth"].get() > 2
    assert tuner.applied()[0]["knob"] == "pipeline_depth"


def test_autotuner_idle_shrinks():
    knobs = _fake_knobs(
        read_workers=(4, 1, 4), prefetch_batches=(6, 1, 6),
        pipeline_depth=(2, 1, 4),
    )
    tuner = IngestAutotuner(policy=AutotunePolicy(confirm=2, cooldown=1))
    _drive(tuner, knobs, IDLE, 12)
    applied = tuner.applied()
    assert applied and applied[0]["direction"] == "down"
    assert knobs["prefetch_batches"].get() < 6


def test_autotuner_hysteresis_requires_confirmation():
    """A one-window blip never moves a knob: confirm=3 means two
    agreeing windows are not enough."""
    knobs = _fake_knobs(prefetch_batches=(1, 1, 6))
    tuner = IngestAutotuner(policy=AutotunePolicy(confirm=3, cooldown=0))
    tuner.observe(SLOW_READ, knobs)
    tuner.observe(IDLE, knobs)      # breaks the streak
    tuner.observe(SLOW_READ, knobs)
    tuner.observe(IDLE, knobs)
    assert knobs["prefetch_batches"].get() == 1
    assert not tuner.applied()


def test_no_oscillation_under_flapping_source():
    """THE guarantee: a source flapping between starved and idle (the
    chaos profile) produces a BOUNDED number of knob changes — the
    reversal limit freezes the contested knob and the tuner goes
    quiescent forever after."""
    policy = AutotunePolicy(confirm=2, cooldown=1, max_reversals=2)
    knobs = _fake_knobs(
        read_workers=(1, 1, 4), prefetch_batches=(2, 1, 8),
        pipeline_depth=(2, 1, 4),
    )
    tuner = IngestAutotuner(policy=policy)
    changes_at = []
    # flap with a period long enough to defeat pure confirm-hysteresis
    for w in range(600):
        sig = SLOW_READ if (w // 6) % 2 == 0 else IDLE
        rec = tuner.observe(sig, knobs)
        if rec is not None and rec["action"] == "applied":
            changes_at.append(w)
    # the analytic bound: Σ_knobs (max_reversals + 1) × (hi − lo)
    bound = sum(
        (policy.max_reversals + 1) * (k.hi - k.lo)
        for k in knobs.values()
    )
    assert len(changes_at) <= bound
    # and empirically FAR tighter: the contested knob froze
    assert "prefetch_batches" in tuner.frozen
    # quiescent: no change in the last 400 windows
    assert not changes_at or changes_at[-1] < 200
    frozen_decisions = [
        d for d in tuner.decisions if d["action"] == "frozen"
    ]
    assert frozen_decisions  # the freeze itself is journaled


def test_tuning_budget_shared_across_tenants():
    """Two tenants' tuners draw on ONE budget: total extra staged
    ranges across both never exceeds the cap, and the denied decision
    is journaled (not silently dropped)."""
    budget = TuningBudget(prefetch_batches=2)
    tuners = [
        IngestAutotuner(
            policy=AutotunePolicy(confirm=1, cooldown=0),
            budget=budget, tenant=t,
        )
        for t in ("a", "b")
    ]
    knobs = {
        t: _fake_knobs(prefetch_batches=(1, 1, 8)) for t in ("a", "b")
    }
    for _ in range(10):
        for t, tuner in zip(("a", "b"), tuners):
            tuner.observe(SLOW_READ, knobs[t])
    grown = sum(
        knobs[t]["prefetch_batches"].get() - 1 for t in ("a", "b")
    )
    assert grown == 2  # exactly the budget, split across tenants
    assert budget.snapshot()["prefetch_batches"]["used"] == 2
    denied = [
        d
        for tuner in tuners
        for d in tuner.decisions
        if d["action"] == "budget_denied"
    ]
    assert denied
    # a shrink refunds the budget
    idle_knobs = knobs["a"]
    t_a = tuners[0]
    for _ in range(6):
        t_a.observe(IDLE, idle_knobs)
    assert budget.snapshot()["prefetch_batches"]["used"] < 2


def test_budget_charges_only_above_cold_default():
    """Review regression: the budget charges EXTRA capacity above a
    knob's cold-start value.  Shrinking below the default refunds
    nothing (nothing was charged), and regrowing back to it costs
    nothing — an idle fleet that dipped under its defaults can always
    recover them even on an exhausted budget."""
    budget = TuningBudget(prefetch_batches=1)
    policy = AutotunePolicy(confirm=1, cooldown=0, max_reversals=50)
    tuner = IngestAutotuner(policy=policy, budget=budget)
    knobs = _fake_knobs(prefetch_batches=(4, 1, 8))
    # idle: shrink 4 -> 1; nothing was ever charged, nothing refunds
    for _ in range(8):
        tuner.observe(IDLE, knobs)
    assert knobs["prefetch_batches"].get() == 1
    assert budget.snapshot()["prefetch_batches"]["used"] == 0
    # starved again: regrowth back to the cold default of 4 is FREE
    for _ in range(8):
        tuner.observe(SLOW_READ, knobs)
    assert knobs["prefetch_batches"].get() >= 4
    used_at_4 = budget.snapshot()["prefetch_batches"]["used"]
    assert used_at_4 <= 1  # only growth PAST 4 charged
    # and growth beyond default+cap is denied, not silently applied
    for _ in range(12):
        tuner.observe(SLOW_READ, knobs)
    assert knobs["prefetch_batches"].get() == 5  # default 4 + cap 1
    assert budget.snapshot()["prefetch_batches"]["used"] == 1
    assert any(
        d["action"] == "budget_denied" for d in tuner.decisions
    )


def test_signal_full_miss_rate_when_staging_disabled(tmp_path):
    """Review regression: with prefetch disabled every read IS a cold
    read, but the source's miss counters are gated on prefetch being
    armed — the signal must report the honest 100% miss rate so the
    tuner can ARM staging instead of one-way ratcheting down."""
    in_dir = _stream_dir(tmp_path, n_files=4)
    src = FileStreamSource(in_dir, prefetch_batches=0)
    q = StreamingQuery(
        _ColsModel(), src, MemorySink(), str(tmp_path / "ckpt"),
        max_batch_offsets=1,
    )
    tuner = IngestAutotuner()
    q._tick_latest = src.latest_offset()  # what an engine round sets
    sig = tuner._signal(q)
    assert sig.backlog == 4 and sig.miss_rate == 1.0
    knobs = graph_knobs(q)
    assert tuner.propose(sig, knobs) == ("prefetch_batches", +1)
    # drained and idle: the synthetic miss rate must NOT block shrink
    assert q.process_available() == 4
    q._tick_latest = src.latest_offset()
    idle_sig = tuner._signal(q)
    assert idle_sig.backlog == 0 and idle_sig.miss_rate == 0.0
    src.close()


# ---------------------------------------------------------------------------
# live-engine integration
# ---------------------------------------------------------------------------


def test_engine_autotune_end_to_end(tmp_path):
    """Aggressive tuner on a real CSV stream: the engine serves
    correctly, knob changes land between batches, decisions ride the
    stats/metrics plane."""
    from sntc_tpu.obs.metrics import registry

    in_dir = _stream_dir(tmp_path, n_files=14)
    src = FileStreamSource(in_dir, prefetch_batches=1, read_workers=1)
    tuner = IngestAutotuner(
        policy=AutotunePolicy(interval_ticks=1, confirm=1, cooldown=0)
    )
    sink = MemorySink()
    q = StreamingQuery(
        _ColsModel(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, autotuner=tuner,
    )
    assert q.process_available() == 14
    assert sum(f.num_rows for f in sink.frames) == 14 * 40
    stats = q.pipeline_stats()
    assert stats["autotune"]["windows"] > 0
    assert stats["autotune"]["knobs"]["prefetch_batches"] >= 1
    if tuner.applied():  # knob gauge mirrors the last applied value
        d = tuner.applied()[-1]
        assert registry().get(
            "sntc_ingest_knob_value", knob=d["knob"]
        ) == d["to"]
    src.close()


def test_engine_autotune_failure_degrades_not_kills(tmp_path):
    """The degrade-never-kill contract: an exploding tuner emits
    autotune_error and the stream keeps serving."""
    from sntc_tpu.resilience import add_event_observer, remove_event_observer

    class Exploding:
        def on_tick(self, engine):
            raise RuntimeError("controller bug")

    seen = []

    def _obs(rec):
        if rec.get("event") == "autotune_error":
            seen.append(rec)

    add_event_observer(_obs)
    try:
        src = MemorySource([
            Frame({k: np.ones(5) for k in ("a", "b", "c", "d")})
        ])
        sink = MemorySink()
        q = StreamingQuery(
            _ColsModel(), src, sink, str(tmp_path / "ckpt"),
            autotuner=Exploding(),
        )
        assert q.process_available() == 1
        assert len(sink.frames) == 1
    finally:
        remove_event_observer(_obs)
    assert seen and "controller bug" in seen[0]["error"]


def test_daemon_shared_budget_autotune(tmp_path, mesh8):
    """serve-daemon wiring: per-tenant tuners share one TuningBudget,
    autotune evidence lands in status()."""
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import ServeDaemon, TenantSpec

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    model = LogisticRegression(mesh=mesh8, maxIter=5).fit(
        Frame({"features": X, "label": y})
    )
    specs = [
        TenantSpec(
            tenant_id=t, model=model,
            source=MemorySource([
                Frame({"features": rng.normal(size=(16, 4)).astype(
                    np.float32)})
            ]),
            sink=MemorySink(),
        )
        for t in ("a", "b")
    ]
    daemon = ServeDaemon(specs, str(tmp_path / "root"), autotune=True)
    try:
        daemon.process_available()
        stats = daemon.autotune_stats()
        assert set(stats["tenants"]) == {"a", "b"}
        assert "budget" in stats
        # both tuners share the SAME budget object
        tuners = [t.query.autotuner for t in daemon.tenants]
        assert tuners[0].budget is tuners[1].budget
        assert daemon.status()["autotune"] is not None
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# drift check
# ---------------------------------------------------------------------------


def test_check_ingest_flags_consistent():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import check_ingest_flags

    assert check_ingest_flags.check() == []
