"""Driver-contract smoke: every bench config runs and emits the agreed
JSON shape (the driver parses ONE line: metric/value/unit/vs_baseline).

Tiny rows — this is a wiring test, not a measurement."""

import json
import subprocess
import sys
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("cfg", ["1", "3", "5"])
def test_bench_config_emits_contract_line(cfg):
    env = dict(
        os.environ,
        BENCH_ROWS="2000",
        BENCH_PLATFORM="cpu",
        BENCH_PROBE_TIMEOUT_S="0",
        BENCH_NO_JOURNAL="1",  # committed journal holds real runs only
        # a toy forest: config 3's depth-10 × 20-tree compile dominates
        # the suite's wall clock and certifies nothing here (no quality
        # assertion below — real measurements use the defaults)
        BENCH_RF_TREES="4",
        BENCH_RF_DEPTH="5",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--config", cfg],
        env=env, capture_output=True, text=True, timeout=500,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "platform"):
        assert key in rec, rec
    assert rec["value"] > 0
    # r5 pairing contract: the ratio comes from a proxy measured in THIS
    # invocation, not the cache
    assert rec["paired"] is True, rec
    assert ("proxy_s" in rec) or ("proxy_rows_per_s" in rec), rec
    assert rec["vs_baseline"] is not None and rec["vs_baseline"] > 0


def test_bench_mfu_emits_contract_line():
    env = dict(
        os.environ,
        BENCH_ROWS="2000",
        BENCH_PLATFORM="cpu",
        BENCH_PROBE_TIMEOUT_S="0",
        BENCH_NO_JOURNAL="1",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mfu"],
        env=env, capture_output=True, text=True, timeout=500,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    for key in ("metric", "value", "mlp_f32_fit_s", "mlp_bf16_fit_s",
                "bf16_speedup_vs_f32", "platform"):
        assert key in rec, rec
    assert rec["mlp_f32_iters"] > 0
