"""Compute-plane fault domain (r18): DEVICE kind classification and
injection round-trips, OOM-adaptive dispatch (recursive split + bucket
floor step-down), per-(segment, signature) compile poisoning + the
wall-time watchdog, the HOST_DEGRADED state machine with probe-gated
recovery and a churn-free compile ledger on re-entry, the host-fallback
equivalence matrix across all five heads (buckets + row-validity masks
+ salvage), engine-level no-death/no-strike behavior under every DEVICE
kind at every site, delivery-thread error-context threading, the
compile-cache fsck, the controller's platform-fault escalate
suppression, and the kill-mid-fallback chaos scenario."""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Pipeline, PipelineModel, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import DCT, MinMaxScaler, VectorAssembler
from sntc_tpu.fuse import compile_pipeline, fused_segments
from sntc_tpu.models import (
    LinearSVC,
    LogisticRegression,
    MultilayerPerceptronClassifier,
    NaiveBayes,
    RandomForestClassifier,
)
from sntc_tpu.resilience import (
    DeviceExecError,
    DeviceFaultDomain,
    DevicePolicy,
    InjectedDeviceFault,
    classify_device_error,
)
from sntc_tpu.resilience.device import annotate_batch
from sntc_tpu.serve import (
    MemorySink,
    MemorySource,
    ServeController,
    ServeDaemon,
    StreamingQuery,
    TenantSpec,
)
from sntc_tpu.serve.controller import SloSignal
from sntc_tpu.serve.transform import BatchPredictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()


@pytest.fixture(autouse=True)
def _device_staged_path(monkeypatch):
    """Bitwise parity target: the eager fallback's staged transforms
    must run the DEVICE path (the f64 host-serve crossover is a
    different numerical path by design — the documented-tolerance case,
    not the bitwise one)."""
    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _domain(**kw):
    """A deterministic domain: synchronous always-healthy probe, zero
    probe interval (recovery on the first tick)."""
    policy = DevicePolicy(probe_interval_s=0.0, **kw)
    return DeviceFaultDomain(
        policy, probe_fn=lambda: True, probe_async=False
    )


def _frame(n=16):
    return Frame({"a": np.arange(float(n)), "b": np.arange(float(n)) * 2})


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def test_injected_kinds_classify_round_trip():
    for kind in R.DEVICE_KINDS:
        R.arm("x.y", kind, times=1)
        with pytest.raises(InjectedDeviceFault) as ei:
            R.fault_point("x.y")
        assert classify_device_error(ei.value) == kind
        R.clear()


def test_classifies_real_xla_shapes_and_rejects_others():
    class XlaRuntimeError(RuntimeError):
        pass

    assert classify_device_error(XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "4294967296 bytes."
    )) == "device_oom"
    assert classify_device_error(XlaRuntimeError(
        "INTERNAL: during XLA compilation: something broke"
    )) == "compile_error"
    assert classify_device_error(XlaRuntimeError(
        "UNAVAILABLE: device lost: tunnel dropped"
    )) == "device_lost"
    # the chain walks through wrappers
    try:
        try:
            raise XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory")
        except XlaRuntimeError as inner:
            raise RuntimeError("delivery failed") from inner
    except RuntimeError as outer:
        assert classify_device_error(outer) == "device_oom"
    # a non-XLA-shaped error never classifies, whatever its message
    assert classify_device_error(
        ValueError("compilation failed: out of memory")
    ) is None
    assert classify_device_error(None) is None


def test_device_kinds_inert_at_disk_and_data_hooks(tmp_path):
    R.arm("storage.wal", "device_oom")
    assert R.fault_disk("storage.wal") is None
    R.clear()
    R.arm("source.parse", "device_oom")
    assert R.fault_data("source.parse", b"abc") == b"abc"


# ---------------------------------------------------------------------------
# OOM-adaptive dispatch
# ---------------------------------------------------------------------------


def test_oom_split_bitwise_and_floor_step():
    f = _frame(16)
    ref = BatchPredictor(_Identity(), bucket_rows=4).predict_frame(f)
    dom = _domain()
    p = BatchPredictor(_Identity(), bucket_rows=4, device_domain=dom)
    R.arm("device.dispatch", "device_oom", times=1)
    out = p.predict_frame(f)
    for c in ref.columns:
        np.testing.assert_array_equal(np.asarray(out[c]),
                                      np.asarray(ref[c]))
    s = dom.stats()
    assert s["oom_splits"] == 1
    assert s["state"] == "DEVICE_OK"
    assert p.bucket_rows == 2  # floor stepped down under OOM pressure
    assert any(
        d["decision"] == "device_oom_split" for d in dom.journal
    )
    events = [e for e in R.recent_events()
              if e.get("event") == "device_oom_split"]
    assert events and events[0]["rows"] == 16


def test_oom_recursive_split_respects_depth_and_floor():
    """Persistent OOM splits to the floor, then counts the at-floor
    failure toward degradation and finishes on the host fallback —
    the dispatch NEVER dies."""
    dom = _domain(degrade_after=1)
    p = BatchPredictor(_Identity(), bucket_rows=4, device_domain=dom)
    R.arm("device.dispatch", "device_oom", times=None)
    out = p.predict_frame(_frame(16))
    np.testing.assert_array_equal(np.asarray(out["a"]),
                                  np.arange(16.0))
    assert dom.host_degraded
    assert dom.stats()["oom_splits"] >= 3  # halved all the way down
    assert dom.stats()["faults"]["device_oom"] >= 1
    # ONE floor step per top-level dispatch, not one per split level
    assert p.bucket_rows == 2
    # degraded serving skips the device fault surface entirely
    calls_before = R.call_count("device.dispatch")
    p.predict_frame(_frame(8))
    assert R.call_count("device.dispatch") == calls_before


# ---------------------------------------------------------------------------
# compile poisoning (+ watchdog)
# ---------------------------------------------------------------------------


def test_predict_compile_error_poisons_shape():
    f = _frame(16)
    ref = BatchPredictor(_Identity(), bucket_rows=4).predict_frame(f)
    dom = _domain()
    p = BatchPredictor(_Identity(), bucket_rows=4, device_domain=dom)
    R.arm("predict.compile", "compile_error", times=1)
    out = p.predict_frame(f)
    np.testing.assert_array_equal(np.asarray(out["a"]),
                                  np.asarray(ref["a"]))
    assert dom.stats()["poisoned_signatures"] == 1
    # the poisoned shape keeps serving the host path (no new compile
    # events), while a DIFFERENT shape still dispatches on device
    ce = p.compile_events
    p.predict_frame(f)
    assert p.compile_events == ce
    assert dom.stats()["fallback_batches"] >= 2
    p.predict_frame(_frame(64))
    assert p.compile_events == ce + 1


D = 4


def _fused_pipeline(mesh, head=None):
    """assembler (eager by the single-upload rule) + DCT + head → one
    real FusedSegment whose fuse.compile boundary genuinely fires."""
    rng = np.random.default_rng(0)
    X = np.abs(rng.normal(3.0, 2.0, size=(200, D))).astype(np.float32)
    cols = {f"c{i}": X[:, i].copy() for i in range(D)}
    cols["label"] = (X[:, 0] > 3.0).astype(np.float64)
    train = Frame(cols)
    head = head or LogisticRegression(
        mesh=mesh, featuresCol="dct", maxIter=20
    )
    pm = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(D)],
                        outputCol="features"),
        DCT(inputCol="features", outputCol="dct"),
        head,
    ]).fit(train)
    return pm, train.drop("label")


def test_fused_compile_error_poisons_exactly_that_signature(mesh8):
    pm, serve = _fused_pipeline(mesh8)
    ref = BatchPredictor(
        compile_pipeline(pm), bucket_rows=16
    ).predict_frame(serve.slice(0, 16))
    dom = _domain()
    fused = compile_pipeline(pm)
    p = BatchPredictor(fused, bucket_rows=16, device_domain=dom)
    seg = fused_segments(fused)[0]
    assert seg._domain is dom and seg.segment_index == 0
    R.arm("fuse.compile", "compile_error", times=1)
    out = p.predict_frame(serve.slice(0, 16))
    for c in ("rawPrediction", "probability", "prediction"):
        np.testing.assert_array_equal(
            np.asarray(out[c]), np.asarray(ref[c]), err_msg=c
        )
    assert len(seg._poisoned) == 1 and seg.compile_events == 0
    # same signature again: served poisoned, nothing compiles
    p.predict_frame(serve.slice(0, 16))
    assert seg.poisoned_served >= 1 and seg.compile_events == 0
    # a DIFFERENT signature compiles on device as usual
    p.predict_frame(serve.slice(0, 64))
    assert seg.compile_events == 1 and len(seg._poisoned) == 1
    ev = [e for e in R.recent_events()
          if e.get("event") == "signature_poisoned"]
    assert ev and ev[0]["segment"] == 0 and ev[0]["site"] == "fuse.compile"


def test_compile_watchdog_poisons_over_budget_signature(mesh8):
    pm, serve = _fused_pipeline(mesh8)
    ref = BatchPredictor(
        compile_pipeline(pm), bucket_rows=16
    ).predict_frame(serve.slice(0, 16))
    dom = DeviceFaultDomain(
        DevicePolicy(compile_budget_s=1e-9, probe_interval_s=0.0),
        probe_fn=lambda: True, probe_async=False,
    )
    fused = compile_pipeline(pm)
    p = BatchPredictor(fused, bucket_rows=16, device_domain=dom)
    seg = fused_segments(fused)[0]
    out = p.predict_frame(serve.slice(0, 16))
    for c in ("rawPrediction", "probability", "prediction"):
        np.testing.assert_array_equal(
            np.asarray(out[c]), np.asarray(ref[c]), err_msg=c
        )
    assert len(seg._poisoned) == 1
    assert any(
        d["decision"] == "signature_poisoned"
        and "watchdog" in d["reason"]
        for d in dom.journal
    )
    assert dom.state == "DEVICE_OK"  # poisoning is not degradation


# ---------------------------------------------------------------------------
# HOST_DEGRADED + probe-gated recovery
# ---------------------------------------------------------------------------


def test_device_lost_degrades_recovers_ledger_flat(mesh8):
    pm, serve = _fused_pipeline(mesh8)
    dom = _domain()
    fused = compile_pipeline(pm)
    p = BatchPredictor(fused, bucket_rows=16, device_domain=dom)
    seg = fused_segments(fused)[0]
    ref = p.predict_frame(serve.slice(0, 16))  # warm the device path
    ce_pred, ce_seg = p.compile_events, seg.compile_events
    from sntc_tpu.obs.metrics import registry

    R.arm("device.dispatch", "device_lost", times=1)
    out = p.predict_frame(serve.slice(0, 16))
    assert dom.host_degraded
    assert registry().get("sntc_device_state") == 1.0
    for c in ("prediction",):
        np.testing.assert_array_equal(np.asarray(out[c]),
                                      np.asarray(ref[c]))
    # degraded serving: host path, no compile churn
    p.predict_frame(serve.slice(0, 16))
    dom.tick()  # probe succeeds -> DEVICE_OK
    assert not dom.host_degraded
    assert registry().get("sntc_device_state") == 0.0
    assert dom.stats()["recoveries"] == 1
    assert dom.stats()["recovery_latency_s"] is not None
    # re-entry: the warm shapes/signatures reuse their programs
    p.predict_frame(serve.slice(0, 16))
    assert p.compile_events == ce_pred
    assert seg.compile_events == ce_seg
    ev = [e.get("event") for e in R.recent_events()]
    assert "device_degraded" in ev and "device_recovered" in ev


def test_health_maps_degrade_recover_pair():
    from sntc_tpu.resilience import HealthMonitor, HealthState

    h = HealthMonitor().attach()
    try:
        dom = _domain()
        dom.enter_host_degraded("test")
        assert h.state_of("model") == HealthState.DEGRADED
        dom.tick()
        assert h.state_of("model") == HealthState.OK
    finally:
        h.close()


def test_async_probe_never_blocks_tick():
    """The default probe path runs on a background thread; a hung probe
    leaves the domain degraded without wedging the tick."""
    import threading

    release = threading.Event()

    def slow_probe():
        release.wait(5.0)
        return True

    dom = DeviceFaultDomain(
        DevicePolicy(probe_interval_s=0.0), probe_fn=slow_probe,
        probe_async=True,
    )
    dom.enter_host_degraded("test")
    dom.tick()  # launches the probe; must return immediately
    assert dom.host_degraded
    release.set()
    deadline = 50
    import time as _t

    while dom.host_degraded and deadline:
        dom.tick()
        _t.sleep(0.02)
        deadline -= 1
    assert not dom.host_degraded


# ---------------------------------------------------------------------------
# host-fallback equivalence matrix (the tolerance contract's bitwise half)
# ---------------------------------------------------------------------------


def _heads(mesh):
    return {
        "lr": LogisticRegression(mesh=mesh, featuresCol="scaled",
                                 maxIter=20),
        "mlp": MultilayerPerceptronClassifier(
            mesh=mesh, featuresCol="scaled", layers=[D, 6, 2],
            maxIter=20,
        ),
        "nb": NaiveBayes(mesh=mesh, featuresCol="scaled",
                         modelType="multinomial"),
        "svc": LinearSVC(mesh=mesh, featuresCol="scaled", maxIter=20),
        "rf": RandomForestClassifier(mesh=mesh, featuresCol="scaled",
                                     numTrees=4, maxDepth=3, seed=0),
    }


@pytest.mark.parametrize("head_name", ["lr", "mlp", "nb", "svc", "rf"])
def test_host_fallback_equivalence_matrix(mesh8, head_name):
    """HOST_DEGRADED fallback vs the fused+bucketed device path for
    every head, with a row-validity (salvage admission) mask riding
    the dispatch: the f64 ``prediction`` column is BITWISE; the f32
    device-cast score columns hold the documented tolerance (XLA is
    free to fuse across the segment's stage boundary, so the device
    program's op order differs from the stage-by-stage host path by
    at most an ulp — docs/RESILIENCE.md tolerance table)."""
    rng = np.random.default_rng(1)
    X = np.abs(rng.normal(3.0, 2.0, size=(120, D))).astype(np.float32)
    cols = {f"c{i}": X[:, i].copy() for i in range(D)}
    cols["label"] = (X[:, 0] > 3.0).astype(np.float64)
    train = Frame(cols)
    pm = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(D)],
                        outputCol="features"),
        MinMaxScaler(inputCol="features", outputCol="scaled"),
        _heads(mesh8)[head_name],
    ]).fit(train)
    serve = train.drop("label").slice(0, 30)
    mask = np.ones(30, dtype=bool)
    mask[[3, 7, 21]] = False  # salvage-admission excisions
    device_out = BatchPredictor(
        compile_pipeline(pm), bucket_rows=16
    ).predict_frame(serve, row_valid=mask)
    dom = _domain()
    dom.enter_host_degraded("matrix")
    fallback_out = BatchPredictor(
        compile_pipeline(pm), bucket_rows=16, device_domain=dom
    ).predict_frame(serve, row_valid=mask)
    assert fallback_out.num_rows == device_out.num_rows == 27
    np.testing.assert_array_equal(
        np.asarray(fallback_out["prediction"]),
        np.asarray(device_out["prediction"]),
    )
    for c in ("rawPrediction", "probability"):
        if c in device_out and c in fallback_out:
            np.testing.assert_allclose(
                np.asarray(fallback_out[c]),
                np.asarray(device_out[c]),
                rtol=1e-5, atol=1e-6, err_msg=c,
            )
    assert dom.stats()["fallback_batches"] >= 1


# ---------------------------------------------------------------------------
# engine-level: no death, no strikes, exactly-once
# ---------------------------------------------------------------------------


def _engine_frames(n=6, rows=16):
    return [
        Frame({"a": np.arange(float(rows)) + 100 * i}) for i in range(n)
    ]


@pytest.mark.parametrize("site,kind", [
    ("device.dispatch", "device_oom"),
    ("device.dispatch", "device_lost"),
    ("predict.compile", "compile_error"),
    ("predict.compile", "device_lost"),
    ("fuse.compile", "compile_error"),
])
def test_engine_survives_device_kind_at_site(tmp_path, site, kind):
    """Each DEVICE kind armed at each site on a supervised stream:
    every batch commits, the engine never dies, and NOTHING
    quarantines or strikes (platform faults are not poison batches)."""
    frames = _engine_frames()
    dom = _domain(degrade_after=1)
    p = BatchPredictor(_Identity(), bucket_rows=8, device_domain=dom)
    q = StreamingQuery(
        p, MemorySource(frames), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        max_batch_failures=3,
    )
    R.arm(site, kind, times=2)
    done = 0
    for _ in range(12):
        done += q.process_available()
        if done >= len(frames):
            break
    assert done == len(frames)
    events = [e.get("event") for e in R.recent_events()]
    assert "quarantine" not in events
    assert "breaker_open" not in events
    assert "retry_exhausted" not in events
    if kind != "compile_error":
        assert dom.stats()["faults"].get(kind, 0) >= 1 or \
            dom.stats()["oom_splits"] >= 1


def test_engine_pipeline_stats_device_block(tmp_path):
    dom = _domain()
    p = BatchPredictor(_Identity(), bucket_rows=8, device_domain=dom)
    q = StreamingQuery(
        p, MemorySource(_engine_frames(2)), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
    )
    q.process_available()
    stats = q.pipeline_stats()
    assert stats["device"]["state"] == "DEVICE_OK"
    assert "fallback_batches" in stats["device"]


def test_daemon_shared_domain_no_tenant_strikes(tmp_path):
    """A bare-site device fault hits every tenant's dispatches; the
    shared domain absorbs it once and NO tenant is struck — the ladder
    stays OK across the whole arc (degrade -> recover)."""
    model = _Identity()
    specs = [
        TenantSpec(tenant_id=t, model=model,
                   source=MemorySource(_engine_frames(3)),
                   sink=MemorySink(), max_batch_failures=2)
        for t in ("a", "b")
    ]
    daemon = ServeDaemon(
        specs, str(tmp_path / "root"), shape_buckets=8,
        device_policy=DevicePolicy(probe_interval_s=0.0,
                                   degrade_after=1),
    )
    # deterministic recovery: synchronous always-healthy probe
    daemon.device_domain._probe_fn = lambda: True
    daemon.device_domain._probe_async = False
    try:
        R.arm("device.dispatch", "device_lost", times=1)
        for _ in range(20):
            daemon.tick()
        st = daemon.status()
        assert st["aggregate"]["batches_done"] == 6
        for tid in ("a", "b"):
            assert st["tenants"][tid]["state"] == "OK"
            assert st["tenants"][tid]["strikes"] == 0
        dev = st["device"]
        assert dev["degradations"] == 1 and dev["recoveries"] == 1
        assert daemon.device_degraded() is False
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# delivery-thread error context (the r18 bugfix)
# ---------------------------------------------------------------------------


def test_device_exec_error_carries_context():
    e = DeviceExecError(
        "device device_oom while finalizing fused segment 2",
        kind="device_oom", segment=2, signature="((8, 4), '<f4')",
    )
    assert classify_device_error(e) == "device_oom"
    assert e.segment == 2 and "((8, 4)" in e.signature
    e2 = annotate_batch(e, 7)
    assert e2.batch_id == 7
    notes = getattr(e2, "__notes__", None)
    if notes is not None:  # py3.11+
        assert any("batch 7" in n for n in notes)
    # idempotent: a second annotate never overwrites the first
    annotate_batch(e2, 9)
    assert e2.batch_id == 7


def test_fused_finalize_error_names_segment_and_signature(
    mesh8, monkeypatch
):
    """A device-shaped error surfacing at FINALIZE (the overlap-sink
    delivery thread's stage) is re-raised as DeviceExecError naming
    the segment and input signature — and the engine's delivery wrapper
    adds the batch id."""
    import sntc_tpu.fuse.planner as planner

    pm, serve = _fused_pipeline(mesh8)
    fused = compile_pipeline(pm)
    dom = _domain(degrade_after=1)
    p = BatchPredictor(fused, bucket_rows=16, device_domain=dom)
    seg = fused_segments(fused)[0]

    class XlaRuntimeError(RuntimeError):
        pass

    real_span = planner.span

    def exploding_span(name, **kw):
        if name == "fuse.finalize":
            raise XlaRuntimeError("UNAVAILABLE: device lost: poof")
        return real_span(name, **kw)

    # the assembler runs eagerly ahead of the segment in the plan —
    # feed the segment its real input
    assembled = fused.getStages()[0].transform(serve.slice(0, 16))
    fin = seg.transform_async(assembled)
    monkeypatch.setattr(planner, "span", exploding_span)
    with pytest.raises(DeviceExecError) as ei:
        fin()
    monkeypatch.setattr(planner, "span", real_span)
    err = ei.value
    assert err.device_kind == "device_lost"
    assert err.segment == 0 and err.signature is not None
    assert "signature" in str(err) and "segment" in str(err)
    assert classify_device_error(err) == "device_lost"


def test_delivery_thread_device_error_redispatches_and_commits(
    tmp_path, monkeypatch, mesh8
):
    """Overlap-sink engine: a device-classified finalize failure on the
    delivery thread re-dispatches the head batch through the response
    ladder (domain degrades, fallback serves) — the batch COMMITS, no
    quarantine, and the device_fault event carries the batch id."""
    import sntc_tpu.fuse.planner as planner

    class XlaRuntimeError(RuntimeError):
        pass

    real_span = planner.span
    armed = {"n": 1}  # the first fused finalize dies device-shaped

    def exploding_span(name, **kw):
        if name == "fuse.finalize" and armed["n"] > 0:
            armed["n"] -= 1
            raise XlaRuntimeError("UNAVAILABLE: device lost: poof")
        return real_span(name, **kw)

    pm, serve = _fused_pipeline(mesh8)
    fused = compile_pipeline(pm)
    dom = _domain(degrade_after=1)
    p = BatchPredictor(fused, bucket_rows=16, device_domain=dom)
    frames = [serve.slice(i * 16, (i + 1) * 16) for i in range(3)]
    q = StreamingQuery(
        p, MemorySource(frames), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        overlap_sink=True, pipeline_depth=2, max_batch_failures=3,
    )
    monkeypatch.setattr(planner, "span", exploding_span)
    done = 0
    for _ in range(10):
        done += q.process_available()
        if done >= 3:
            break
    monkeypatch.setattr(planner, "span", real_span)
    assert done == 3
    events = [e for e in R.recent_events()]
    names = [e.get("event") for e in events]
    assert "quarantine" not in names
    faults = [e for e in events if e.get("event") == "device_fault"]
    assert faults and any(e.get("batch_id") is not None for e in faults)
    assert dom.stats()["faults"].get("device_lost", 0) >= 1


def test_recovery_probe_bypasses_success_marker(tmp_path, monkeypatch):
    """probe_for_recovery must run a REAL probe: a success marker
    written minutes before the device died would otherwise answer the
    recovery question from stale evidence and flap the domain."""
    import sntc_tpu.utils.backend_probe as bp

    marker = tmp_path / "probe_ok"
    marker.write_text("")
    monkeypatch.setattr(bp, "_ok_marker", lambda: str(marker))
    # the cached path trusts the fresh marker without a subprocess
    assert bp.probe_default_backend(0.05) is True
    # the recovery path bypasses it: a 50 ms budget cannot complete a
    # real backend-init subprocess, so the honest answer is False
    assert bp.probe_for_recovery(0.05) is False


def test_consecutive_segment_compile_errors_degrade(mesh8):
    """Faults a fused segment ABSORBS (poison + eager fallback) still
    accumulate toward degrade_after: the enclosing dispatch's success
    must not reset the streak a fault it contains just started."""
    pm, serve = _fused_pipeline(mesh8)
    dom = _domain(degrade_after=2)
    p = BatchPredictor(compile_pipeline(pm), bucket_rows=0,
                       device_domain=dom)
    R.arm("fuse.compile", "compile_error", times=2)
    p.predict_frame(serve.slice(0, 16))  # fresh sig 1: poisons
    assert not dom.host_degraded
    assert dom.stats()["consecutive_faults"] == 1
    p.predict_frame(serve.slice(0, 32))  # fresh sig 2: poisons again
    assert dom.host_degraded  # 2 consecutive absorbed faults degrade


def test_half_open_breaker_slot_released_on_device_fault(tmp_path):
    """A device-classified dispatch failure must RELEASE the half-open
    probe slot allow() reserved (not record an outcome): a leaked slot
    would wedge the breaker half-open and deadlock the engine; a
    recorded failure would re-open it — a tenant-strike event — for a
    platform fault."""
    from sntc_tpu.resilience import CircuitBreaker

    clock = {"t": 0.0}
    br = CircuitBreaker(
        "predict.dispatch", window=4, min_calls=2,
        failure_threshold=0.5, cooldown_s=10.0,
        half_open_max_calls=1, clock=lambda: clock["t"],
    )
    br.record_failure()
    br.record_failure()  # -> OPEN
    assert br.state == "open"
    clock["t"] = 11.0  # cooldown elapsed -> HALF_OPEN on next touch
    dom = _domain(degrade_after=3)
    p = BatchPredictor(_Identity(), bucket_rows=0, device_domain=dom)
    q = StreamingQuery(
        p, MemorySource(_engine_frames(2, rows=1)), MemorySink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        max_batch_failures=3, breakers={"predict.dispatch": br},
    )
    # the probe dispatch dies device-shaped AT the bucket floor (1-row
    # batch: no split possible, not yet degraded → the terminal OOM
    # escapes to the engine): the engine defers WITHOUT scoring the
    # breaker
    R.arm("device.dispatch", "device_oom", times=1)
    q.process_available()
    assert br.state == "half_open"  # not re-opened by the platform fault
    assert br._probes_in_flight == 0  # the reserved slot was released
    # the next round's probe succeeds and closes the breaker — the
    # leak would have refused this call forever
    done = q.process_available()
    assert done == 2 and br.state == "closed"


def test_swap_model_clears_predictor_poisons():
    """A hot-swapped model earns a clean predictor-level plan cache:
    poisons belonged to the replaced model's programs."""
    dom = _domain()
    p = BatchPredictor(_Identity(), bucket_rows=4, device_domain=dom)
    R.arm("predict.compile", "compile_error", times=1)
    p.predict_frame(_frame(16))
    assert p._poisoned_shapes
    assert dom.stats()["poisoned_signatures"] == 1
    p.swap_model(_Identity())
    assert not p._poisoned_shapes
    # the LIVE gauge drops with the discarded programs
    assert dom.stats()["poisoned_signatures"] == 0
    fb = dom.stats()["fallback_batches"]
    p.predict_frame(_frame(16))  # back on the device path
    assert dom.stats()["fallback_batches"] == fb


def test_bucket_floor_restores_after_clean_streak():
    dom = DeviceFaultDomain(
        DevicePolicy(probe_interval_s=0.0, floor_restore_after=3),
        probe_fn=lambda: True, probe_async=False,
    )
    p = BatchPredictor(_Identity(), bucket_rows=8, device_domain=dom)
    R.arm("device.dispatch", "device_oom", times=1)
    p.predict_frame(_frame(16))
    assert p.bucket_rows == 4  # emergency step-down
    for _ in range(3):  # the pressure passed: clean streak restores
        p.predict_frame(_frame(16))
    assert p.bucket_rows == 8
    assert any(
        d["decision"] == "bucket_floor_restored" for d in dom.journal
    )


# ---------------------------------------------------------------------------
# controller: platform faults don't climb the tenant ladder
# ---------------------------------------------------------------------------


def test_controller_suppresses_escalate_while_platform_degraded(
    tmp_path,
):
    from sntc_tpu.resilience.control import ControlPolicy

    degraded = {"on": True}
    daemon = ServeDaemon(
        [
            TenantSpec(tenant_id="noisy", model=_Identity(),
                       source=MemorySource([]), sink=MemorySink(),
                       slo_max_shed_rate=0.05, quarantine_after=2),
            TenantSpec(tenant_id="quiet", model=_Identity(),
                       source=MemorySource([]), sink=MemorySink(),
                       slo_p99_ms=60_000.0),
        ],
        str(tmp_path / "root"),
    )
    ctl = ServeController.for_daemon(
        daemon, policy=ControlPolicy(confirm=1, cooldown=0),
        ingest=False, device_check=lambda: degraded["on"],
    )
    daemon.controller = ctl
    flooding = SloSignal(batches=2, rows=16, rows_per_s=16.0,
                         shed_offsets=20, shed_rate=0.9, backlog=30,
                         elapsed_s=1.0)
    try:
        seen = []
        for _ in range(24):
            rec = ctl.step({"noisy": flooding})
            if rec is not None and rec["action"] == "applied":
                seen.append(rec["knob"])
        # quota + shed rungs still steer; escalate NEVER fires
        assert "noisy/quota" in seen and "noisy/shed" in seen
        assert "noisy/escalate" not in seen
        assert ctl.escalations_total == 0
        assert ctl.platform_deferrals >= 1
        assert daemon._by_id["noisy"].strikes == 0
        assert ctl.stats()["platform_degraded"] is True
        # plane recovers -> the ladder is whole again
        degraded["on"] = False
        for _ in range(12):
            rec = ctl.step({"noisy": flooding})
            if rec is not None and rec["action"] == "applied":
                seen.append(rec["knob"])
        assert "noisy/escalate" in seen
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# compile-cache fsck
# ---------------------------------------------------------------------------


def test_fsck_compile_cache_quarantines_and_serving_recompiles(
    tmp_path,
):
    from sntc_tpu.utils.compile_cache import fsck_compile_cache

    cache = tmp_path / "xla_cache"
    cache.mkdir()
    (cache / "good_entry").write_bytes(b"\x28\xb5\x2f\xfd" + b"x" * 64)
    (cache / "torn_entry").write_bytes(b"")  # crash-mid-write shape
    (cache / "orphan.tmp").write_bytes(b"partial")
    report = fsck_compile_cache(str(cache))
    assert report["ok"]
    assert report["checked"] == 3
    assert [q["path"] for q in report["quarantined"]] == [
        str(cache / "torn_entry")
    ]
    assert os.path.exists(cache / ".corrupt" / "torn_entry")
    assert not os.path.exists(cache / "orphan.tmp")
    assert os.path.exists(cache / "good_entry")
    # idempotent: a second pass finds a clean cache
    again = fsck_compile_cache(str(cache))
    assert again["ok"] and not again["quarantined"]
    # report-only mode flags without moving
    (cache / "torn2").write_bytes(b"")
    ro = fsck_compile_cache(str(cache), repair=False)
    assert not ro["ok"] and not ro["quarantined"]
    # SEEDED POISONED-CACHE RECOVERY: serving over the doctored cache
    # dir recompiles cleanly (a fresh process with the cache armed)
    fsck_compile_cache(str(cache))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JAX_COMPILATION_CACHE_DIR=str(cache),
               SNTC_CACHE_NO_HOST_KEY="1")
    proc = subprocess.run(
        [sys.executable, "-c",
         "from sntc_tpu.utils.compile_cache import "
         "enable_persistent_cache\n"
         "import jax, jax.numpy as jnp\n"
         "d = enable_persistent_cache()\n"
         "out = jax.jit(lambda x: x * 2 + 1)(jnp.arange(8.0))\n"
         "print('served', float(out.sum()))\n"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "served" in proc.stdout


def test_fsck_cli_compile_cache_flag(tmp_path):
    cache = tmp_path / "cache"
    cache.mkdir()
    (cache / "dead").write_bytes(b"")
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "sntc_tpu", "fsck", str(ckpt),
         "--compile-cache-dir", str(cache), "--platform", "cpu"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["compile_cache"]["quarantined"]
    assert os.path.exists(cache / ".corrupt" / "dead")


# ---------------------------------------------------------------------------
# chaos: kill mid-fallback (device.dispatch) in a real child process
# ---------------------------------------------------------------------------


def test_chaos_kill_mid_fallback_converges_bitwise(tmp_path):
    cm = _load_script("chaos_crash_matrix")
    ref = cm.run_device_reference(str(tmp_path))
    verdict = cm.run_device_kill_scenario(
        str(tmp_path), "device.dispatch", ref
    )
    assert verdict["ok"], verdict
    assert verdict["mid_fallback"] and verdict["sink_bitwise"]


@pytest.mark.slow
@pytest.mark.parametrize("site", ["predict.compile", "fuse.compile"])
def test_chaos_device_compile_kills_converge(tmp_path, site):
    cm = _load_script("chaos_crash_matrix")
    ref = cm.run_device_reference(str(tmp_path))
    verdict = cm.run_device_kill_scenario(str(tmp_path), site, ref)
    assert verdict["ok"], verdict
