"""PowerIterationClustering: block-structured-graph recovery, id
preservation, init modes, input validation."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import PowerIterationClustering


def _two_block_graph(n_per=30, p_in=0.9, p_out=0.02, seed=0, id_offset=0):
    """Edges of a two-community random graph; ids offset to prove the
    result reports original ids."""
    rng = np.random.default_rng(seed)
    n = 2 * n_per
    src, dst, w = [], [], []
    for i in range(n):
        for j in range(i + 1, n):
            same = (i < n_per) == (j < n_per)
            if rng.random() < (p_in if same else p_out):
                src.append(i + id_offset)
                dst.append(j + id_offset)
                w.append(1.0 if same else 0.1)
    return (
        np.array(src, np.int64), np.array(dst, np.int64),
        np.array(w, np.float64), n_per,
    )


def test_recovers_two_blocks(mesh8):
    src, dst, w, n_per = _two_block_graph(id_offset=100)
    f = Frame({"src": src, "dst": dst, "weight": w})
    pic = PowerIterationClustering(
        k=2, maxIter=30, weightCol="weight", seed=1
    )
    out = pic.assignClusters(f)
    ids = np.asarray(out["id"])
    cl = np.asarray(out["cluster"])
    assert ids.min() == 100  # original ids preserved
    by_id = dict(zip(ids.tolist(), cl.tolist()))
    a = [by_id[100 + i] for i in range(n_per)]
    b = [by_id[100 + n_per + i] for i in range(n_per)]
    # each block lands (almost) entirely in one cluster, and the two
    # blocks differ
    a_major = max(set(a), key=a.count)
    b_major = max(set(b), key=b.count)
    assert a_major != b_major
    assert a.count(a_major) >= 0.9 * n_per
    assert b.count(b_major) >= 0.9 * n_per


def test_degree_init(mesh8):
    src, dst, w, n_per = _two_block_graph(seed=3)
    f = Frame({"src": src, "dst": dst, "weight": w})
    out = PowerIterationClustering(
        k=2, maxIter=30, weightCol="weight", initMode="degree", seed=0
    ).assignClusters(f)
    assert len(np.unique(out["cluster"])) == 2


def test_default_weight_is_one(mesh8):
    src = np.array([0, 1, 3, 4], np.int64)
    dst = np.array([1, 2, 4, 5], np.int64)
    out = PowerIterationClustering(k=2, maxIter=10).assignClusters(
        Frame({"src": src, "dst": dst})
    )
    assert out.num_rows == 6  # two 3-chains


def test_validation(mesh8):
    f_neg = Frame({
        "src": np.array([0]), "dst": np.array([1]),
        "weight": np.array([-1.0]),
    })
    with pytest.raises(ValueError, match="non-negative"):
        PowerIterationClustering(weightCol="weight").assignClusters(f_neg)
    f_loop = Frame({"src": np.array([2]), "dst": np.array([2])})
    with pytest.raises(ValueError, match="self-loop"):
        PowerIterationClustering().assignClusters(f_loop)
