"""Resilience layer (r6): retry policies with deterministic backoff,
fault injection at every wired site, streaming quarantine, checkpoint
corruption detection + fallback, CV fold tolerance, probe retries, and
the bench rendezvous-SIGABRT retry.  All tier-1 CPU — injected faults
stand in for real hardware failures."""

import json
import os
import sys

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Estimator, Evaluator, Model, Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.core.params import Param
from sntc_tpu.resilience import (
    InjectedFault,
    InjectedIOFault,
    RetryExhausted,
    RetryPolicy,
    with_retries,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    R.clear()
    R.clear_events()
    yield
    R.clear()
    R.clear_events()


# ---------------------------------------------------------------------------
# policy: deterministic backoff, executor semantics, events
# ---------------------------------------------------------------------------


def test_backoff_schedule_deterministic_and_exact():
    p = RetryPolicy(max_attempts=4, base_delay_s=0.1, multiplier=2.0,
                    max_delay_s=5.0, jitter=0.1, seed=3)
    sched = p.backoff_schedule()
    assert sched == p.backoff_schedule()  # pure function of the policy
    # asserted EXACTLY: base * mult^i * (1 + jitter * U[-1,1)) with the
    # policy's own seeded generator
    rng = np.random.default_rng(3)
    expected = [
        min(0.1 * 2.0**i, 5.0) * (1.0 + 0.1 * float(rng.uniform(-1, 1)))
        for i in range(3)
    ]
    assert sched == expected
    # zero jitter: the pure exponential ramp, capped
    flat = RetryPolicy(max_attempts=5, base_delay_s=1.0, multiplier=4.0,
                       max_delay_s=6.0, jitter=0.0).backoff_schedule()
    assert flat == [1.0, 4.0, 6.0, 6.0]


def test_with_retries_succeeds_and_sleeps_the_schedule():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.2, jitter=0.1, seed=9)
    slept, calls = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    out = with_retries(flaky, p, site="t.site", sleep=slept.append)
    assert out == "ok" and len(calls) == 3
    assert slept == p.backoff_schedule()[:2]  # exact deterministic sleeps
    events = [e["event"] for e in R.recent_events(site="t.site")]
    assert events == ["retry", "retry", "retry_success"]


def test_with_retries_exhaustion_and_classifier():
    p = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0,
                    retryable=(IOError,))
    with pytest.raises(RetryExhausted) as ei:
        with_retries(lambda: (_ for _ in ()).throw(IOError("x")), p,
                     site="t.ex", sleep=lambda d: None)
    assert isinstance(ei.value.last_exception, IOError)
    assert ei.value.attempts == 2
    assert [e["event"] for e in R.recent_events(site="t.ex")] == [
        "retry", "retry_exhausted"
    ]

    # non-retryable exceptions pass through unchanged, no events
    with pytest.raises(KeyError):
        with_retries(lambda: {}["k"], p, site="t.nr", sleep=lambda d: None)
    assert R.recent_events(site="t.nr") == []


def test_with_retries_deadline_clamps_final_sleep():
    """The 100s backoff cannot fit the 50s deadline: the final sleep is
    CLAMPED to exactly the remaining budget (never slept past the
    deadline, never given up with budget left) and the last attempt
    runs at the deadline."""
    p = RetryPolicy(max_attempts=10, base_delay_s=100.0,
                    max_delay_s=100.0, jitter=0.0, deadline_s=50.0)
    t = {"now": 0.0}
    slept = []

    def sleep(d):
        slept.append(d)
        t["now"] += d

    def fail():
        raise IOError("x")

    with pytest.raises(RetryExhausted):
        with_retries(fail, p, site="t.dl", sleep=sleep,
                     clock=lambda: t["now"])
    assert slept == [50.0]  # clamped to remaining deadline, not 100
    assert t["now"] == 50.0  # total elapsed never exceeds the deadline
    ex = R.recent_events(site="t.dl", event="retry_exhausted")
    assert len(ex) == 1 and ex[0]["attempts"] == 2 and ex[0]["deadline_hit"]


def test_events_jsonl_sink(tmp_path, monkeypatch):
    log = tmp_path / "resilience.jsonl"
    monkeypatch.setenv("SNTC_RESILIENCE_LOG", str(log))
    p = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    with pytest.raises(RetryExhausted):
        with_retries(lambda: 1 / 0, p, site="t.log", sleep=lambda d: None)
    records = [json.loads(ln) for ln in log.read_text().splitlines()]
    assert [r["event"] for r in records] == ["retry", "retry_exhausted"]
    assert all(r["site"] == "t.log" for r in records)


# ---------------------------------------------------------------------------
# faults: registry, schedules, env grammar
# ---------------------------------------------------------------------------


def test_fault_point_unarmed_is_noop():
    R.fault_point("sink.write")  # nothing armed: must not raise


def test_arm_nth_call_and_times():
    R.arm("sink.write", kind="io", after=1, times=1)
    R.fault_point("sink.write")  # call 1: let through
    with pytest.raises(InjectedIOFault):
        R.fault_point("sink.write")  # call 2: fires
    R.fault_point("sink.write")  # times=1 spent
    assert R.call_count("sink.write") == 3
    injected = R.recent_events(site="sink.write", event="fault_injected")
    assert len(injected) == 1 and injected[0]["call"] == 2


def test_env_grammar_parses_and_rejects():
    specs = R.parse_faults_env("sink.write:io:0.3:7, probe.init")
    assert specs == [
        {"site": "sink.write", "kind": "io", "prob": 0.3, "seed": 7},
        {"site": "probe.init"},
    ]
    with pytest.raises(ValueError, match="malformed"):
        R.parse_faults_env("a:b:c")
    with pytest.raises(ValueError, match="malformed"):
        R.parse_faults_env("a:exc:0.5:1:9")


def test_env_knob_arms_deterministically(monkeypatch):
    monkeypatch.setenv("SNTC_FAULTS", "stream.read:timeout:0.5:11")
    fired = []
    for _ in range(20):
        try:
            R.fault_point("stream.read")
            fired.append(0)
        except R.InjectedTimeoutFault:
            fired.append(1)
    # the same env string must reproduce the same fault sequence
    rng = np.random.default_rng(11)
    expected = [1 if float(rng.uniform()) < 0.5 else 0 for _ in range(20)]
    assert fired == expected
    # dropping the env disarms on the next call
    monkeypatch.delenv("SNTC_FAULTS")
    R.fault_point("stream.read")


# ---------------------------------------------------------------------------
# streaming: per-batch retry, dead-letter quarantine, atomic sink
# ---------------------------------------------------------------------------


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _frames(n_batches, rows=8):
    return [
        Frame({"x": np.arange(rows, dtype=np.float64) + 100 * b})
        for b in range(n_batches)
    ]


def _query(tmp_path, src_frames, sink=None, **kw):
    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    src = MemorySource(src_frames)
    sink = sink if sink is not None else MemorySink()
    q = StreamingQuery(
        _Identity(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, **kw,
    )
    return q, sink


def test_streaming_sink_retry_under_policy(tmp_path):
    R.arm("sink.write", after=1, times=2)  # batch 1 fails twice, then ok
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
    q, sink = _query(tmp_path, _frames(3), retry_policy=policy)
    assert q.process_available() == 3  # completes despite the faults
    assert [i for i, _ in sink.batches] == [0, 1, 2]
    assert len(R.recent_events(site="sink.write", event="retry")) == 2
    assert R.recent_events(site="sink.write", event="retry_success")


def test_streaming_source_read_retry_under_policy(tmp_path):
    R.arm("stream.read", times=1)
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    q, sink = _query(tmp_path, _frames(2), retry_policy=policy)
    assert q.process_available() == 2
    assert len(sink.frames) == 2
    assert R.recent_events(site="stream.read", event="retry")


def test_streaming_poison_batch_quarantined_query_continues(tmp_path):
    from sntc_tpu.serve import MemorySink

    class PoisonSink(MemorySink):
        def add_batch(self, batch_id, frame):
            if batch_id == 1:
                raise ValueError("poison batch")
            super().add_batch(batch_id, frame)

    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    q, sink = _query(
        tmp_path, _frames(4), sink=PoisonSink(),
        retry_policy=policy, max_batch_failures=1,
    )
    # the query drains ALL batches in one call — no exception escapes
    assert q.process_available() == 4
    assert [i for i, _ in sink.batches] == [0, 2, 3]
    assert q.last_committed() == 3

    # dead-letter journal holds the evidence
    dl = os.path.join(str(tmp_path / "ckpt"), "dead_letter")
    records = [
        json.loads(ln)
        for ln in open(os.path.join(dl, "dead_letter.jsonl"))
    ]
    assert len(records) == 1
    rec = records[0]
    assert rec["batch_id"] == 1 and "poison" in rec["error"]
    assert rec["intent"]["start"] == 1 and rec["intent"]["end"] == 2
    assert rec["rows_file"] and os.path.exists(
        os.path.join(dl, rec["rows_file"])
    )
    # progress marks the quarantined batch; quarantine event emitted
    quarantined = [p for p in q.recentProgress if p.get("quarantined")]
    assert [p["batchId"] for p in quarantined] == [1]
    assert R.recent_events(site="sink.write", event="quarantine")

    # a restarted query on the same checkpoint does NOT replay batch 1
    q2, sink2 = _query(tmp_path, _frames(4), retry_policy=policy,
                       max_batch_failures=1)
    assert q2.process_available() == 0


def test_streaming_quarantine_threshold_counts_rounds(tmp_path):
    """max_batch_failures=2: the first failed retirement round DEFERS
    (batch stays queued, engine loop stays alive — no exception), the
    second quarantines and the query continues."""
    from sntc_tpu.serve import MemorySink

    class AlwaysFail(MemorySink):
        def add_batch(self, batch_id, frame):
            if batch_id == 0:
                raise IOError("down")
            super().add_batch(batch_id, frame)

    q, sink = _query(tmp_path, _frames(2), sink=AlwaysFail(),
                     max_batch_failures=2)
    assert q.process_available() == 0  # round 1: fails, stays queued
    assert q.last_committed() == -1
    assert q.process_available() == 2  # round 2: quarantined + continue
    assert [i for i, _ in sink.batches] == [1]


def test_streaming_background_loop_survives_quarantine(tmp_path):
    """The start()/awaitTermination surface must DEGRADE, not die, when
    quarantine is armed: each poll tick is one retry round and the
    poison batch dead-letters without crashing the loop thread."""
    import time as _time

    from sntc_tpu.serve import MemorySink

    class PoisonSink(MemorySink):
        def add_batch(self, batch_id, frame):
            if batch_id == 1:
                raise ValueError("poison")
            super().add_batch(batch_id, frame)

    q, sink = _query(tmp_path, _frames(3), sink=PoisonSink(),
                     max_batch_failures=2)
    q.start(poll_interval=0.01)
    deadline = _time.time() + 30
    while _time.time() < deadline and q.last_committed() < 2:
        _time.sleep(0.01)
    assert q.last_committed() == 2
    assert q.isActive  # the loop thread survived the poison batch
    q.stop()
    assert [i for i, _ in sink.batches] == [0, 2]


def test_streaming_read_poison_batch_quarantined(tmp_path):
    """A batch whose SOURCE READ fails persistently quarantines too —
    the query must not wedge forever on a torn input file."""
    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    class PoisonSource(MemorySource):
        def get_batch(self, start, end):
            if start == 1:
                raise IOError("torn input file")
            return super().get_batch(start, end)

    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    src = PoisonSource(_frames(3))
    sink = MemorySink()
    q = StreamingQuery(
        _Identity(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, retry_policy=policy, max_batch_failures=1,
    )
    assert q.process_available() == 3  # all three batches commit
    assert [i for i, _ in sink.batches] == [0, 2]
    assert q.last_committed() == 2
    rec = json.loads(open(os.path.join(
        str(tmp_path / "ckpt"), "dead_letter", "dead_letter.jsonl"
    )).read().strip())
    assert rec["batch_id"] == 1 and rec["rows_file"] is None
    assert [
        p["batchId"] for p in q.recentProgress if p.get("quarantined")
    ] == [1]


def test_streaming_predict_poison_batch_quarantined(tmp_path):
    """A batch the MODEL cannot process (malformed rows) quarantines
    with its raw rows journaled — the most common poison-batch shape."""
    class PickyModel(Transformer):
        def transform(self, frame):
            if 100.0 <= float(np.asarray(frame["x"])[0]) < 200.0:
                raise ValueError("malformed features")  # batch 1 only
            return frame

    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    src = MemorySource(_frames(3))
    sink = MemorySink()
    q = StreamingQuery(
        PickyModel(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, max_batch_failures=1,
    )
    assert q.process_available() == 3
    assert [i for i, _ in sink.batches] == [0, 2]
    rec = json.loads(open(os.path.join(
        str(tmp_path / "ckpt"), "dead_letter", "dead_letter.jsonl"
    )).read().strip())
    assert rec["batch_id"] == 1
    # the poison rows themselves are preserved for repair tooling
    assert rec["rows_file"] and rec["num_rows"] == 8
    events = R.recent_events(site="predict.dispatch", event="quarantine")
    assert len(events) == 1


def test_streaming_failure_stages_count_separately(tmp_path):
    """A read flake and a sink flake on the same batch must not pool
    toward one quarantine threshold."""
    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    class FlakyBoth(MemorySource):
        def __init__(self, frames):
            super().__init__(frames)
            self.read_fails = 1

        def get_batch(self, start, end):
            if start == 0 and self.read_fails:
                self.read_fails -= 1
                raise IOError("read flake")
            return super().get_batch(start, end)

    class FlakySink(MemorySink):
        def __init__(self):
            super().__init__()
            self.sink_fails = 1

        def add_batch(self, batch_id, frame):
            if batch_id == 0 and self.sink_fails:
                self.sink_fails -= 1
                raise IOError("sink flake")
            super().add_batch(batch_id, frame)

    src = FlakyBoth(_frames(1))
    sink = FlakySink()
    q = StreamingQuery(
        _Identity(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, max_batch_failures=2,
    )
    # round 1: read fails (read=1/2, deferred); round 2: read ok, sink
    # fails (sink=1/2, deferred); round 3: delivered — NOT quarantined,
    # because neither stage reached its own threshold
    assert q.process_available() == 0
    assert q.process_available() == 0
    assert q.process_available() == 1
    assert [i for i, _ in sink.batches] == [0]
    assert not R.recent_events(event="quarantine")


def test_streaming_defaults_preserve_single_shot(tmp_path):
    """No retry_policy / max_batch_failures: an armed fault propagates
    exactly as a real failure did pre-resilience (r5 contract)."""
    R.arm("sink.write", times=1)
    q, sink = _query(tmp_path, _frames(2))
    with pytest.raises(InjectedFault):
        q.process_available()
    assert q.process_available() == 2  # WAL replay still exact


def test_csv_sink_atomic_no_tmp_left(tmp_path):
    from sntc_tpu.serve import CsvDirSink

    out = str(tmp_path / "out")
    sink = CsvDirSink(out, columns=["x"])
    sink.add_batch(0, Frame({"x": np.arange(4, dtype=np.float64)}))
    assert os.listdir(out) == ["batch_000000.csv"]  # no .tmp debris


# ---------------------------------------------------------------------------
# checkpointing: manifest, corruption detection, fallback
# ---------------------------------------------------------------------------


def _stage():
    from sntc_tpu.feature import IndexToString

    return IndexToString(inputCol="p", outputCol="s", labels=["x", "y"])


def test_save_writes_manifest_and_roundtrips(tmp_path):
    from sntc_tpu.mlio import load_model, save_model
    from sntc_tpu.mlio.save_load import verify_checkpoint

    path = save_model(_stage(), str(tmp_path / "m"))
    assert os.path.exists(os.path.join(path, "_manifest.json"))
    assert verify_checkpoint(path) is True
    loaded = load_model(path)
    assert loaded.getLabels() == ["x", "y"]


def test_corrupted_checkpoint_detected(tmp_path):
    from sntc_tpu.mlio import save_model
    from sntc_tpu.mlio.save_load import (
        CheckpointCorruptError,
        load_model,
    )

    path = save_model(_stage(), str(tmp_path / "m"))
    meta = os.path.join(path, "metadata.json")
    blob = open(meta, "rb").read()
    with open(meta, "wb") as f:  # same length, flipped bytes: torn write
        f.write(blob[:-4] + b"XXXX")
    with pytest.raises(CheckpointCorruptError, match="sha256 mismatch"):
        load_model(path, fallback=False)


def test_corrupted_checkpoint_falls_back_to_prev(tmp_path, capsys):
    from sntc_tpu.feature import IndexToString
    from sntc_tpu.mlio import load_model, save_model

    path = str(tmp_path / "m")
    save_model(
        IndexToString(inputCol="p", outputCol="s", labels=["old"]), path
    )
    save_model(
        IndexToString(inputCol="p", outputCol="s", labels=["new"]), path
    )
    assert os.path.isdir(path + ".prev")  # previous good snapshot kept
    assert load_model(path).getLabels() == ["new"]

    # corrupt the primary: load degrades to the .prev snapshot
    meta = os.path.join(path, "metadata.json")
    blob = open(meta, "rb").read()
    with open(meta, "wb") as f:
        f.write(blob[:-4] + b"XXXX")
    loaded = load_model(path)
    assert loaded.getLabels() == ["old"]
    assert "degraded to previous good snapshot" in capsys.readouterr().err
    assert R.recent_events(site="ckpt.load", event="ckpt_fallback")


def test_injected_load_fault_takes_fallback_path(tmp_path):
    """An armed ckpt.load fault must degrade to .prev exactly as a real
    load failure does (the fault simulates flaky checkpoint storage)."""
    from sntc_tpu.feature import IndexToString
    from sntc_tpu.mlio import load_model, save_model

    path = str(tmp_path / "m")
    save_model(
        IndexToString(inputCol="p", outputCol="s", labels=["old"]), path
    )
    save_model(
        IndexToString(inputCol="p", outputCol="s", labels=["new"]), path
    )
    R.arm("ckpt.load", times=1)
    assert load_model(path).getLabels() == ["old"]  # degraded to .prev
    assert R.recent_events(site="ckpt.load", event="ckpt_fallback")
    # without a .prev the fault propagates
    R.arm("ckpt.load", times=1)
    lone = save_model(_stage(), str(tmp_path / "lone"))
    with pytest.raises(InjectedFault):
        load_model(lone)


def test_injected_save_fault_leaves_old_checkpoint_intact(tmp_path):
    from sntc_tpu.feature import IndexToString
    from sntc_tpu.mlio import load_model, save_model

    path = str(tmp_path / "m")
    save_model(
        IndexToString(inputCol="p", outputCol="s", labels=["good"]), path
    )
    R.arm("ckpt.save", times=1)
    with pytest.raises(InjectedFault):
        save_model(
            IndexToString(inputCol="p", outputCol="s", labels=["bad"]),
            path,
        )
    # the atomic publish never happened: live checkpoint is untouched,
    # no staging debris remains
    assert load_model(path).getLabels() == ["good"]
    assert [d for d in os.listdir(tmp_path) if ".tmp-" in d] == []


def test_ckpt_save_retry_under_policy_completes(tmp_path):
    """Acceptance: with ckpt.save armed, a save under with_retries
    completes and the round-trip load succeeds."""
    from sntc_tpu.mlio import load_model, save_model

    R.arm("ckpt.save", times=1)
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    path = with_retries(
        lambda: save_model(_stage(), str(tmp_path / "m")),
        policy, site="ckpt.save",
    )
    assert load_model(path).getLabels() == ["x", "y"]
    assert R.recent_events(site="ckpt.save", event="retry_success")


def test_torn_write_size_mismatch_detected(tmp_path):
    from sntc_tpu.mlio import save_model
    from sntc_tpu.mlio.save_load import (
        CheckpointCorruptError,
        verify_checkpoint,
    )

    path = save_model(_stage(), str(tmp_path / "m"))
    meta = os.path.join(path, "metadata.json")
    with open(meta, "ab") as f:
        f.write(b"garbage")  # truncation/extension: size check catches
    with pytest.raises(CheckpointCorruptError, match="bytes"):
        verify_checkpoint(path)


def test_missing_manifest_loads_unverified(tmp_path):
    """Pre-resilience checkpoints (no manifest) still load."""
    from sntc_tpu.mlio import load_model, save_model
    from sntc_tpu.mlio.save_load import verify_checkpoint

    path = save_model(_stage(), str(tmp_path / "m"))
    os.remove(os.path.join(path, "_manifest.json"))
    assert verify_checkpoint(path) is False
    assert load_model(path).getLabels() == ["x", "y"]


# ---------------------------------------------------------------------------
# CrossValidator fold tolerance
# ---------------------------------------------------------------------------


class _ConstParams:
    value = Param("constant prediction", default=0.0)


class ConstModel(_ConstParams, Model):
    def __init__(self, value=0.0, **kw):
        super().__init__(**kw)
        self.value = float(value)

    def transform(self, frame):
        return frame.with_column(
            "prediction", np.full(frame.num_rows, self.value)
        )


class ConstEstimator(_ConstParams, Estimator):
    def _fit(self, frame):
        return ConstModel(value=float(self.getValue()))


class MeanEvaluator(Evaluator):
    def evaluate(self, frame):
        return float(np.mean(frame["prediction"]))


def _cv(fault_tolerant=True, retry_policy=None, folds=2):
    from sntc_tpu.tuning import CrossValidator

    return CrossValidator(
        estimator=ConstEstimator(),
        estimatorParamMaps=[{"value": 1.0}, {"value": 3.0}],
        evaluator=MeanEvaluator(),
        numFolds=folds,
        seed=0,
        faultTolerant=fault_tolerant,
        retryPolicy=retry_policy,
    )


def _cv_frame(n=40):
    return Frame({"x": np.arange(n, dtype=np.float64)})


def test_cv_cell_failure_records_nan_and_search_survives():
    R.arm("cv.fit", after=0, times=1)  # first cell (fold 0, grid 0) dies
    cv = _cv(retry_policy=RetryPolicy(max_attempts=1))
    model = cv.fit(_cv_frame())
    # grid point 1 (value=3.0) wins; point 0 averaged over its one
    # surviving fold
    assert model.bestIndex == 1
    assert model.avgMetrics == [1.0, 3.0]
    degraded = R.recent_events(site="cv.fit", event="cv_cell_degraded")
    assert len(degraded) == 1
    assert degraded[0]["fold"] == 0 and degraded[0]["grid_index"] == 0


def test_cv_cell_retry_heals_transient_failure():
    R.arm("cv.fit", times=1)
    cv = _cv(retry_policy=RetryPolicy(
        max_attempts=2, base_delay_s=0.0, jitter=0.0
    ))
    model = cv.fit(_cv_frame())
    assert model.avgMetrics == [1.0, 3.0]
    assert not R.recent_events(site="cv.fit", event="cv_cell_degraded")
    assert R.recent_events(site="cv.fit", event="retry_success")


def test_cv_all_cells_failing_raises():
    R.arm("cv.fit", prob=1.0, times=None)
    cv = _cv(retry_policy=RetryPolicy(max_attempts=1))
    with pytest.raises(RuntimeError, match="every .* cell failed"):
        cv.fit(_cv_frame())


def test_cv_not_fault_tolerant_propagates():
    R.arm("cv.fit", times=1)
    cv = _cv(fault_tolerant=False)
    # the sequential non-tolerant path never calls the fault point (it
    # predates the resilience layer) — but an estimator failure aborts
    class Boom(ConstEstimator):
        def _fit(self, frame):
            raise RuntimeError("fit boom")

    from sntc_tpu.tuning import CrossValidator

    cv = CrossValidator(
        estimator=Boom(), estimatorParamMaps=[{}],
        evaluator=MeanEvaluator(), numFolds=2,
    )
    with pytest.raises(RuntimeError, match="fit boom"):
        cv.fit(_cv_frame())


def test_cv_fault_tolerant_matches_clean_run_metrics():
    """No faults armed: the tolerant path computes the same grid."""
    model_ft = _cv(fault_tolerant=True).fit(_cv_frame())
    model_plain = _cv(fault_tolerant=False).fit(_cv_frame())
    assert model_ft.avgMetrics == model_plain.avgMetrics
    assert model_ft.bestIndex == model_plain.bestIndex


# ---------------------------------------------------------------------------
# acceptance: SNTC_FAULTS arming each wired site in turn — streaming,
# checkpoint round-trip, CV grid all complete (retry or degrade per
# policy) with structured events (ISSUE r6 criterion 3)
# ---------------------------------------------------------------------------

# seed 29 uniform draws: .050 .506 .519 .265 .129 .021 .394 ... — with
# prob 0.5 the fire/clear sequence below is fully deterministic


def test_env_faults_streaming_query_completes(monkeypatch, tmp_path):
    monkeypatch.setenv("SNTC_FAULTS", "sink.write:io:0.5:29")
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    q, sink = _query(tmp_path, _frames(3), retry_policy=policy,
                     max_batch_failures=1)
    # batch 0: fire, retry clears; batch 1: clears; batch 2: fire, fire
    # -> retry exhausted -> quarantined.  The query still drains fully.
    assert q.process_available() == 3
    assert [i for i, _ in sink.batches] == [0, 1]
    assert [
        p["batchId"] for p in q.recentProgress if p.get("quarantined")
    ] == [2]
    assert R.recent_events(site="sink.write", event="retry_success")
    assert R.recent_events(site="sink.write", event="quarantine")


def test_env_faults_checkpoint_roundtrip_completes(monkeypatch, tmp_path):
    from sntc_tpu.mlio import load_model, save_model

    monkeypatch.setenv("SNTC_FAULTS", "ckpt.save:exc:0.5:29")
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    # save attempt 1 draws .050 -> injected fault; retry draws .506 ->
    # clean save.  Round-trip load verifies the manifest.
    path = with_retries(
        lambda: save_model(_stage(), str(tmp_path / "m")),
        policy, site="ckpt.save",
    )
    assert load_model(path).getLabels() == ["x", "y"]
    assert R.recent_events(site="ckpt.save", event="fault_injected")
    assert R.recent_events(site="ckpt.save", event="retry_success")


def test_env_faults_cv_grid_completes(monkeypatch):
    monkeypatch.setenv("SNTC_FAULTS", "cv.fit:exc:0.5:29")
    cv = _cv(retry_policy=RetryPolicy(
        max_attempts=2, base_delay_s=0.0, jitter=0.0
    ))
    # cells in order: (f0,g0) fire+retry-ok, (f0,g1) ok, (f1,g0)
    # fire+fire -> NaN, (f1,g1) fire+fire -> NaN.  Fold-0 metrics alone
    # still rank the grid; the search completes.
    model = cv.fit(_cv_frame())
    assert model.avgMetrics == [1.0, 3.0]
    assert model.bestIndex == 1
    degraded = R.recent_events(site="cv.fit", event="cv_cell_degraded")
    assert [(d["fold"], d["grid_index"]) for d in degraded] == [
        (1, 0), (1, 1)
    ]
    assert R.recent_events(site="cv.fit", event="retry_success")


# ---------------------------------------------------------------------------
# backend probe retries
# ---------------------------------------------------------------------------


@pytest.fixture
def _probe_env(monkeypatch, tmp_path):
    import subprocess as sp

    import sntc_tpu.utils.backend_probe as bp

    calls = {"n": 0, "fail_first": 0}

    def fake_run(cmd, timeout=None, **kw):
        calls["n"] += 1
        rc = 1 if calls["n"] <= calls["fail_first"] else 0
        return sp.CompletedProcess(cmd, rc)

    monkeypatch.setattr(bp.subprocess, "run", fake_run)
    monkeypatch.setattr(bp, "_ok_marker", lambda: str(tmp_path / "marker"))
    monkeypatch.setattr(
        bp, "_probe_policy",
        lambda **kw: RetryPolicy(
            max_attempts=3, base_delay_s=0.0, jitter=0.0
        ),
    )
    return bp, calls


def test_probe_retries_transient_init_failure(_probe_env):
    bp, calls = _probe_env
    calls["fail_first"] = 2  # two bad handshakes, third succeeds
    assert bp.probe_default_backend(timeout_s=5) is True
    assert calls["n"] == 3
    assert R.recent_events(site="probe.init", event="retry_success")


def test_probe_exhaustion_returns_false_no_marker(_probe_env, tmp_path):
    bp, calls = _probe_env
    calls["fail_first"] = 99
    assert bp.probe_default_backend(timeout_s=5) is False
    assert calls["n"] == 3  # policy budget, not single-shot
    assert not os.path.exists(str(tmp_path / "marker"))
    assert R.recent_events(site="probe.init", event="retry_exhausted")


def test_probe_injected_fault_retried(_probe_env):
    bp, calls = _probe_env
    R.arm("probe.init", times=1)
    assert bp.probe_default_backend(timeout_s=5) is True
    assert R.recent_events(site="probe.init", event="fault_injected")


def test_probe_attempts_env_parse(monkeypatch):
    import sntc_tpu.utils.backend_probe as bp

    monkeypatch.setenv("SNTC_PROBE_ATTEMPTS", "5")
    assert bp._probe_policy().max_attempts == 5
    monkeypatch.setenv("SNTC_PROBE_ATTEMPTS", "garbage")
    assert bp._probe_policy().max_attempts == 2  # fallback, no crash


def test_probe_total_budget_split_across_attempts(monkeypatch, tmp_path):
    """SNTC_PROBE_TIMEOUT_S stays the TOTAL stall bound: per-attempt
    subprocess timeouts divide it, and the policy deadline caps the
    whole retry loop — more attempts never multiply the worst case."""
    import subprocess as sp

    import sntc_tpu.utils.backend_probe as bp

    seen = []

    def fake_run(cmd, timeout=None, **kw):
        seen.append(timeout)
        return sp.CompletedProcess(cmd, 1)  # always failing

    monkeypatch.setattr(bp.subprocess, "run", fake_run)
    monkeypatch.setattr(bp, "_ok_marker", lambda: str(tmp_path / "mk"))
    monkeypatch.setenv("SNTC_PROBE_ATTEMPTS", "4")
    assert bp.probe_default_backend(timeout_s=8.0) is False
    assert all(t == pytest.approx(2.0) for t in seen)  # 8s / 4 attempts
    policy = bp._probe_policy(deadline_s=8.0)
    assert policy.deadline_s == 8.0 and policy.max_attempts == 4


def test_malformed_faults_env_warns_not_raises(monkeypatch, capsys):
    """A typo'd SNTC_FAULTS must fail loud ONCE on stderr and arm
    nothing — raising from fault_point would be misclassified as a
    site failure by the retry/quarantine machinery."""
    monkeypatch.setenv("SNTC_FAULTS", "sink.write:oi:0.3")  # bad kind
    R.fault_point("sink.write")  # no raise
    R.fault_point("stream.read")
    assert "malformed SNTC_FAULTS" in capsys.readouterr().err
    # the warning is once per string, not per call
    R.fault_point("sink.write")
    assert "malformed" not in capsys.readouterr().err


# ---------------------------------------------------------------------------
# collective dispatch site
# ---------------------------------------------------------------------------


def test_collective_dispatch_fault_and_retry(monkeypatch):
    import sntc_tpu.parallel.collectives as col

    # stub the jit so the test exercises the dispatch wrapper, not XLA
    monkeypatch.setattr(col.jax, "jit", lambda f: (lambda *a: "ok"))

    agg = col.make_tree_aggregate(lambda x: x, mesh=None)
    R.arm("collective.dispatch", times=1)
    with pytest.raises(InjectedFault):
        agg(np.zeros(4))  # single-shot by default

    R.clear()
    R.arm("collective.dispatch", times=1)
    monkeypatch.setenv("SNTC_COLLECTIVE_RETRIES", "1")
    agg = col.make_tree_aggregate(lambda x: x, mesh=None)
    assert agg(np.zeros(4)) == "ok"  # retried through the fault
    assert R.recent_events(
        site="collective.dispatch", event="retry_success"
    )


# ---------------------------------------------------------------------------
# bench: rendezvous-SIGABRT retry (exactly once, journaled)
# ---------------------------------------------------------------------------


def _bench():
    sys.path.insert(0, REPO)
    import bench

    return bench


_RENDEZVOUS_STDERR = (
    "F0000 00:00 external/xla/xla/... Expected 8 threads to join the "
    "rendezvous, but only 5 of them arrived on time; aborted"
)


def test_is_rendezvous_abort_signature():
    bench = _bench()
    assert bench._is_rendezvous_abort(-6, _RENDEZVOUS_STDERR)
    assert bench._is_rendezvous_abort(134, _RENDEZVOUS_STDERR)
    assert not bench._is_rendezvous_abort(0, _RENDEZVOUS_STDERR)
    assert not bench._is_rendezvous_abort(-6, "segfault somewhere")
    assert not bench._is_rendezvous_abort(1, _RENDEZVOUS_STDERR)


class _Args:
    rows = 100
    no_pair = False
    platform = "cpu"


class _Proc:
    def __init__(self, returncode, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def test_bench_isolated_retries_rendezvous_once():
    bench = _bench()
    good = json.dumps({"metric": "m", "value": 1.0, "unit": "s"})
    procs = [_Proc(-6, stderr=_RENDEZVOUS_STDERR), _Proc(0, stdout=good)]
    calls = []

    def runner(cmd, **kw):
        calls.append(cmd)
        return procs[len(calls) - 1]

    line = bench.run_config_isolated("3", _Args(), runner=runner)
    assert len(calls) == 2  # exactly one retry
    assert line["retried"] is True  # journaled evidence of the flake
    assert line["value"] == 1.0
    # the child must not double-journal
    # (parent sets BENCH_NO_JOURNAL=1 in the child env)


def test_bench_isolated_no_retry_for_other_failures():
    bench = _bench()
    calls = []

    def runner(cmd, **kw):
        calls.append(cmd)
        return _Proc(1, stderr="real failure")

    with pytest.raises(RuntimeError, match="rc=1"):
        bench.run_config_isolated("3", _Args(), runner=runner)
    assert len(calls) == 1  # no retry for non-rendezvous failures


def test_bench_isolated_second_rendezvous_death_raises():
    bench = _bench()
    calls = []

    def runner(cmd, **kw):
        calls.append(cmd)
        return _Proc(-6, stderr=_RENDEZVOUS_STDERR)

    with pytest.raises(RuntimeError, match="after one rendezvous retry"):
        bench.run_config_isolated("3", _Args(), runner=runner)
    assert len(calls) == 2  # retried once, then gave up


def test_bench_isolated_success_has_no_retried_flag():
    bench = _bench()
    good = json.dumps({"metric": "m", "value": 2.0, "unit": "s"})

    line = bench.run_config_isolated(
        "3", _Args(), runner=lambda cmd, **kw: _Proc(0, stdout=good)
    )
    assert "retried" not in line
