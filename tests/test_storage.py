"""Durable-storage survival plane (r17): torn-tail WAL repair,
rotating journals, append-WAL compaction + files-WAL pruning with
restart equivalence, dead-letter retention, the ENOSPC/io_error sweep
over every registered durable write site, the fsck doctor, disk
accounting/budgets, and the durable-artifact drift check.  Chaos
(kill-mid-append via torn_write at storage.wal) rides the crash
matrix script, driven here in tier-1."""

import importlib.util
import json
import os

import numpy as np
import pytest

import sntc_tpu.resilience as R
from sntc_tpu.core.base import Transformer
from sntc_tpu.core.frame import Frame
from sntc_tpu.data.schema import ColumnSpec, SchemaContract
from sntc_tpu.obs.metrics import registry
from sntc_tpu.resilience import (
    InjectedDiskFault,
    QuerySupervisor,
    RetryPolicy,
    storage,
)
from sntc_tpu.serve import CsvDirSink, MemorySink, MemorySource, StreamingQuery

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    R.clear()
    R.clear_events()
    R.reset_breakers()
    storage.reset_degradation()
    yield
    R.clear()
    R.clear_events()
    R.reset_breakers()
    storage.reset_degradation()


def _get(name, **labels):
    return registry().get(name, **labels) or 0


class _Identity(Transformer):
    def transform(self, frame):
        return frame


def _frames(n, rows=6):
    return [
        Frame({"x": np.arange(rows, dtype=np.float64) + 100 * b})
        for b in range(n)
    ]


def _engine(tmp, name, frames, **kwargs):
    sink = MemorySink()
    q = StreamingQuery(
        _Identity(), MemorySource(frames), sink,
        os.path.join(str(tmp), name), max_batch_offsets=1, **kwargs,
    )
    return q, sink


# ---------------------------------------------------------------------------
# satellite 1: append-WAL torn-tail repair (the JSONDecodeError regression)
# ---------------------------------------------------------------------------


def test_append_wal_torn_tail_repaired_on_recovery(tmp_path):
    """A crash mid-append leaves a partial final line in offsets.log;
    construction used to die with JSONDecodeError — now it truncates
    the torn tail, journals the repair, and replays what is whole."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    intent0 = {"batch_id": 0, "start": 0, "end": 1}
    with open(ckpt / "offsets.log", "w") as f:
        f.write(json.dumps(intent0) + "\n")
        f.write('{"batch_id": 1, "sta')  # torn mid-append
    q, sink = _engine(
        tmp_path, "ckpt", _frames(2), wal_mode="append",
    )
    # the whole intent replays; the torn one is gone
    assert q._pending_intents == {0: intent0}
    with open(ckpt / "offsets.log") as f:
        assert f.read() == json.dumps(intent0) + "\n"
    repairs = [
        json.loads(line)
        for line in open(ckpt / "storage_repair.jsonl")
    ]
    assert repairs and repairs[0]["action"] == "truncate_torn_tail"
    assert repairs[0]["path"].endswith("offsets.log")
    assert _get("sntc_storage_repairs_total", artifact="wal_append") >= 1
    # and the engine serves normally from the repaired state
    assert q.process_available() == 2
    q.stop()


def test_append_wal_torn_commit_tail_replays_batch(tmp_path):
    """A torn commits.log tail = a commit that never landed: the batch
    replays (exactly-once comes from the sink dedupe, as in a crash)."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    intent = {"batch_id": 0, "start": 0, "end": 1}
    with open(ckpt / "offsets.log", "w") as f:
        f.write(json.dumps(intent) + "\n")
    with open(ckpt / "commits.log", "w") as f:
        f.write('{"batch_id": 0, "end"')  # commit tore mid-append
    q, sink = _engine(tmp_path, "ckpt", _frames(1), wal_mode="append")
    assert q.last_committed() == -1  # torn commit reads as absent
    assert q.process_available() == 1
    assert q.last_committed() == 0
    q.stop()


def test_mid_file_wal_corruption_is_loud(tmp_path):
    """Damage that is NOT the crash shape (a bad line with real records
    after it) must raise, not silently elide history."""
    path = tmp_path / "commits.log"
    with open(path, "w") as f:
        f.write('{"batch_id": 0, "end": 1}\n')
        f.write("GARBAGE\n")
        f.write('{"batch_id": 2, "end": 3}\n')
    with pytest.raises(storage.JsonlCorruptError, match="line 2"):
        storage.read_jsonl_tolerant(str(path), repair=True)


def test_files_wal_torn_records_tolerated(tmp_path):
    """Files mode: a torn commit record at recovery quarantines (the
    batch replays); a torn intent record reads as absent (replans)."""
    q, _ = _engine(tmp_path, "ckpt", _frames(3))
    assert q.process_available() == 3
    q.stop()
    ckpt = tmp_path / "ckpt"
    with open(ckpt / "commits" / "2.json", "w") as f:
        f.write('{"batch_id": 2, "e')  # torn
    q2, sink2 = _engine(tmp_path, "ckpt", _frames(3))
    assert q2.last_committed() == 1  # fell back past the torn record
    assert os.path.exists(ckpt / "commits" / ".corrupt" / "2.json")
    assert q2.process_available() == 1  # batch 2 replays
    assert q2.last_committed() == 2
    q2.stop()


# ---------------------------------------------------------------------------
# RotatingJsonlWriter: caps, rotation, degrade/recover
# ---------------------------------------------------------------------------


def test_rotating_writer_bounds_footprint(tmp_path):
    path = str(tmp_path / "j.jsonl")
    w = storage.RotatingJsonlWriter(path, max_bytes=400, keep=2)
    for i in range(200):
        assert w.write({"i": i, "pad": "x" * 20})
    files = sorted(os.listdir(tmp_path))
    assert files == ["j.jsonl", "j.jsonl.1", "j.jsonl.2"]
    for name in files:
        assert os.path.getsize(tmp_path / name) <= 400 + 64
    # newest record is present in the live segment
    last = [json.loads(line) for line in open(path)][-1]
    assert last["i"] == 199
    assert w.stats()["rotations"] > 0


def test_rotating_writer_degrades_and_recovers(tmp_path):
    from sntc_tpu.resilience import HealthMonitor, HealthState

    h = HealthMonitor().attach()
    try:
        w = storage.RotatingJsonlWriter(str(tmp_path / "j.jsonl"))
        R.arm("storage.journal", kind="enospc", times=2)
        assert w.write({"i": 0}) is False
        assert w.write({"i": 1}) is False
        assert h.state_of("storage.shed_journal") == HealthState.DEGRADED
        assert _get(
            "sntc_storage_write_errors_total", artifact="shed_journal"
        ) >= 2
        # disk recovers: the buffered backlog flushes IN ORDER first
        assert w.write({"i": 2}) is True
        assert [r["i"] for r in map(
            json.loads, open(tmp_path / "j.jsonl")
        )] == [0, 1, 2]
        assert h.state_of("storage.shed_journal") == HealthState.OK
        events = [e["event"] for e in R.recent_events()]
        assert events.count("storage_degraded") == 1  # once per episode
        assert "storage_recovered" in events
    finally:
        h.close()


def test_rotating_writer_torn_write_rolls_back(tmp_path):
    """A torn journal append must not leave a partial line that
    corrupts the middle of the file once later appends land."""
    w = storage.RotatingJsonlWriter(str(tmp_path / "j.jsonl"))
    R.arm("storage.journal", kind="torn_write", times=1)
    assert w.write({"x": "y" * 200}) is False
    assert w.write({"z": 1}) is True
    records = [json.loads(line) for line in open(tmp_path / "j.jsonl")]
    assert records == [{"x": "y" * 200}, {"z": 1}]


# ---------------------------------------------------------------------------
# satellite 4: restart equivalence — replay after compaction/rotation is
# bitwise-identical to replay from the uncompacted log
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("wal_mode,bounded_kwargs,unbounded_kwargs", [
    ("append", dict(wal_compact_every=3), dict(wal_compact_every=0)),
    ("files", dict(wal_keep_commits=4), dict(wal_keep_commits=0)),
])
def test_restart_equivalence_bounded_vs_unbounded_wal(
    tmp_path, wal_mode, bounded_kwargs, unbounded_kwargs,
):
    frames = _frames(11)
    more = frames + _frames(5, rows=4)
    results = {}
    for name, kwargs in (
        ("bounded", bounded_kwargs), ("unbounded", unbounded_kwargs),
    ):
        q, _ = _engine(
            tmp_path, name, frames, wal_mode=wal_mode, **kwargs
        )
        assert q.process_available() == 11
        q.stop()
        # a fresh engine on the same checkpoint + 5 more source frames:
        # recovery state and continued output must be IDENTICAL whether
        # the history was compacted/pruned or kept whole
        q2, sink2 = _engine(
            tmp_path, name, more, wal_mode=wal_mode, **kwargs
        )
        recovered = (q2.last_committed(), q2.committed_end())
        assert q2.process_available() == 5
        out = [
            (bid, {c: f[c].tolist() for c in f.columns})
            for bid, f in sink2.batches
        ]
        q2.stop()
        results[name] = (recovered, out)
    assert results["bounded"] == results["unbounded"]
    if wal_mode == "append":
        # the bound actually bit: a sealed checkpoint exists and the
        # live logs hold only the tail
        ckpt = tmp_path / "bounded"
        core = storage.load_sealed_json(
            str(ckpt / "wal_checkpoint.json")
        )
        assert core["last_committed"] >= 11
        n_lines = sum(
            1 for line in open(ckpt / "commits.log") if line.strip()
        )
        assert n_lines < 4
    else:
        kept = os.listdir(tmp_path / "bounded" / "commits")
        assert len(kept) <= 5  # keep=4 (+ the one just landed)
        full = os.listdir(tmp_path / "unbounded" / "commits")
        assert len(full) == 16


def test_flow_state_store_retention_equivalence(tmp_path):
    """Restore from the keep-2 pruned store equals restore from an
    unpruned one, byte for byte."""
    from sntc_tpu.flow.state import FlowStateStore

    payloads = {
        end: (b"state-%d" % end) * 17 for end in (2, 4, 6, 8, 10)
    }
    pruned = FlowStateStore(str(tmp_path / "pruned"), keep=2)
    full = FlowStateStore(str(tmp_path / "full"), keep=5)
    for end, payload in payloads.items():
        pruned.publish(end, payload)
        full.publish(end, payload)
    assert pruned.ends() == [8, 10]
    for end in pruned.ends():
        assert pruned.load(end) == full.load(end) == payloads[end]


# ---------------------------------------------------------------------------
# dead-letter retention (keep-N + counted drops)
# ---------------------------------------------------------------------------


def test_dead_letter_retention_bounds_and_counts(tmp_path):
    class FailSink:
        def add_batch(self, batch_id, frame):
            raise IOError(f"sink down for {batch_id}")

    q = StreamingQuery(
        _Identity(), MemorySource(_frames(6)), FailSink(),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        max_batch_failures=1, dead_letter_keep=3,
    )
    assert q.process_available() == 6  # all quarantined, all committed
    q.stop()
    dl = tmp_path / "ckpt" / "dead_letter"
    csvs = [n for n in os.listdir(dl) if n.endswith(".csv")]
    assert len(csvs) == 3  # newest three kept
    assert sorted(csvs)[-1] == "batch_000005.csv"
    # the record journal survives retention (protected) and holds all 6
    records = [
        json.loads(line) for line in open(dl / "dead_letter.jsonl")
    ]
    assert len(records) == 6
    assert _get(
        "sntc_dead_letter_dropped_total", artifact="dead_letter"
    ) >= 3
    events = [e for e in R.recent_events()
              if e["event"] == "dead_letter_dropped"]
    assert events and events[-1]["keep"] == 3


# ---------------------------------------------------------------------------
# acceptance: ENOSPC / io_error at every registered durable write site —
# follow the declared policy, never die, drain clean
# ---------------------------------------------------------------------------


ENGINE_SWEEP_SITES = (
    "stream.wal", "stream.commit", "sink.write",
    "storage.wal", "storage.journal", "storage.dead_letter",
)


@pytest.mark.parametrize("kind", ["enospc", "io_error"])
@pytest.mark.parametrize("site", ENGINE_SWEEP_SITES)
def test_disk_fault_sweep_engine_survives(tmp_path, site, kind):
    """Transient disk failure at each engine-reachable durable write
    site: the armed engine (retry + quarantine + salvage admission +
    shed) must keep serving, follow the artifact's declared policy,
    and drain with zero exceptions.  Deferred rounds re-run via
    repeated process_available calls — each call is one poll tick."""
    contract = SchemaContract(
        {"x": ColumnSpec(dtype="float64", allow_nan=False)},
        mode="salvage",
    )
    frames = _frames(6)
    # one poison row so the row-level dead-letter path genuinely writes
    frames[2]["x"][1] = np.nan
    q = StreamingQuery(
        _Identity(), MemorySource(frames),
        CsvDirSink(str(tmp_path / "out"), durable=False),
        str(tmp_path / "ckpt"), max_batch_offsets=1,
        wal_mode="append", wal_compact_every=2,
        retry_policy=RetryPolicy(
            max_attempts=2, base_delay_s=0.0, jitter=0.0
        ),
        max_batch_failures=3,
        schema_contract=contract,
    )
    R.arm(site, kind=kind, times=2)
    expected_last = 5
    if site == "storage.journal":
        # the shed journal is this site's durable write: shed under the
        # fault — the DECISION must stand even though the record could
        # only buffer (degrade, not die)
        record = q.shed_backlog(2, policy="oldest", latest=6)
        assert record is not None and record["offsets_shed"] == 4
        expected_last = 1  # 2 surviving offsets -> 2 one-frame batches
    for _ in range(12):  # deferred rounds retry, one per call
        q.process_available()
        if q.last_committed() == expected_last:
            break
    assert q.last_committed() == expected_last
    assert q.in_flight_count() == 0
    q.stop()
    assert R.call_count(site) > 0  # the site was actually exercised
    if site == "storage.journal":
        assert _get(
            "sntc_storage_write_errors_total", artifact="shed_journal"
        ) >= 1
    if site == "storage.dead_letter":
        assert _get(
            "sntc_storage_write_errors_total",
            artifact="dead_letter_rows",
        ) >= 1


def test_disk_fault_marker_degrades_supervisor(tmp_path):
    """storage.marker faults: health dumps + drain marker degrade
    (counted) and the supervised drain still exits clean."""
    q, _ = _engine(tmp_path, "ckpt", _frames(3))
    sup = QuerySupervisor(
        q, health_json=str(tmp_path / "ckpt" / "health.json")
    )
    try:
        R.arm("storage.marker", kind="enospc", times=10)
        sup.tick()
        assert not os.path.exists(tmp_path / "ckpt" / "health.json")
        assert _get(
            "sntc_storage_write_errors_total", artifact="markers"
        ) >= 1
        R.clear()
        status = sup.drain_now("test")
        assert status["drained"] is True
        assert os.path.exists(
            tmp_path / "ckpt" / "drain_marker.json"
        )
    finally:
        sup.close()


def test_disk_fault_flow_snapshot_fails_loud(tmp_path):
    """storage.state policy is FAIL: a snapshot publish under ENOSPC
    raises (the engine's commit hook owns the retry), leaves no torn
    blob behind, and the next publish succeeds."""
    from sntc_tpu.flow.state import FlowStateStore

    store = FlowStateStore(str(tmp_path / "fs"), keep=2)
    R.arm("storage.state", kind="enospc", times=1)
    with pytest.raises(OSError):
        store.publish(4, b"payload")
    assert store.ends() == []
    store.publish(4, b"payload")
    assert store.load(4) == b"payload"


def test_enospc_is_a_real_oserror():
    import errno

    R.arm("stream.wal", kind="enospc", times=1)
    with pytest.raises(OSError) as ei:
        R.fault_point("stream.wal")
    assert ei.value.errno == errno.ENOSPC
    assert isinstance(ei.value, InjectedDiskFault)
    # torn_write is inert at a plain fault_point site
    R.arm("stream.wal", kind="torn_write", times=1)
    R.fault_point("stream.wal")


# ---------------------------------------------------------------------------
# satellite 2: sink / journal write errors carry path + offset context
# ---------------------------------------------------------------------------


def test_sink_write_error_names_file_and_bytes(tmp_path, monkeypatch):
    sink = CsvDirSink(str(tmp_path / "out"), durable=False)
    frame = _frames(1)[0]

    def boom(src, dst):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError) as ei:
        sink.add_batch(7, frame)
    msg = str(ei.value)
    assert "batch 7" in msg
    assert "batch_000007.csv.tmp" in msg
    assert "bytes written" in msg
    assert ei.value.errno == 28


def test_wal_append_error_names_file_and_offset(tmp_path):
    q, _ = _engine(tmp_path, "ckpt", _frames(2), wal_mode="append")
    assert q.process_available() == 2

    class Dead:
        name = str(tmp_path / "ckpt" / "offsets.log")

        def tell(self):
            return 123

        def write(self, text):
            raise OSError(5, "Input/output error")

        def truncate(self, pos):
            pass

        def seek(self, pos):
            pass

    with pytest.raises(OSError) as ei:
        storage.append_line(Dead(), '{"x": 1}\n', site="storage.wal")
    assert "offsets.log" in str(ei.value)
    assert "offset 123" in str(ei.value)
    q.stop()


# ---------------------------------------------------------------------------
# fsck: the doctor
# ---------------------------------------------------------------------------


def _make_dirty_root(tmp_path):
    """A checkpoint root with one of every kind of damage."""
    root = tmp_path / "ckpt"
    q, _ = _engine(tmp_path, "ckpt", _frames(4), wal_mode="append")
    assert q.process_available() == 4
    q.stop()
    # torn journal tail
    with open(root / "shed.jsonl", "w") as f:
        f.write('{"ok": 1}\n{"torn')
    # corrupt flow snapshot
    from sntc_tpu.flow.state import FlowStateStore

    store = FlowStateStore(str(root / "flow_state"), keep=2)
    store.publish(2, b"good-state")
    snap = store._file(2)
    with open(snap, "r+b") as f:
        f.seek(-3, os.SEEK_END)
        f.write(b"XXX")
    # corrupt marker + a tmp orphan
    with open(root / "drain_marker.json", "w") as f:
        f.write('{"half": ')
    with open(root / "whatever.json.tmp-123", "w") as f:
        f.write("orphan")
    return root, snap


def test_fsck_repairs_quarantines_and_reports(tmp_path):
    root, snap = _make_dirty_root(tmp_path)
    report = storage.fsck(str(root), repair=True)
    assert report["ok"] is True
    repaired = {r["path"] for r in report["repaired"]}
    assert str(root / "shed.jsonl") in repaired
    quarantined = {
        (r["artifact"], os.path.basename(r["path"]))
        for r in report["quarantined"]
    }
    assert ("flow_state", os.path.basename(snap)) in quarantined
    assert ("markers", "drain_marker.json") in quarantined
    assert os.path.exists(
        root / "flow_state" / ".corrupt" / os.path.basename(snap)
    )
    assert report["cleaned"]  # tmp orphan swept
    assert not os.path.exists(root / "whatever.json.tmp-123")
    # the journal parses clean after repair; the repair journal records
    # every action
    records = [
        json.loads(line) for line in open(root / "storage_repair.jsonl")
    ]
    actions = {r["action"] for r in records}
    assert {"truncate_torn_tail", "quarantine_corrupt"} <= actions
    # idempotent: a second pass finds a clean tree
    again = storage.fsck(str(root), repair=True)
    assert again["ok"] and not again["repaired"]
    assert not again["quarantined"]


def test_fsck_no_repair_reports_without_touching(tmp_path):
    root, snap = _make_dirty_root(tmp_path)
    report = storage.fsck(str(root), repair=False)
    assert report["ok"] is False
    assert report["errors"]
    assert not report["repaired"] and not report["quarantined"]
    assert os.path.exists(snap)  # nothing moved
    with open(root / "shed.jsonl") as f:
        assert f.read().endswith('{"torn')  # nothing truncated


def test_fsck_corrupt_wal_checkpoint_is_unrepairable(tmp_path):
    q, _ = _engine(
        tmp_path, "ckpt", _frames(7), wal_mode="append",
        wal_compact_every=2,
    )
    assert q.process_available() == 7
    q.stop()
    path = tmp_path / "ckpt" / "wal_checkpoint.json"
    core = json.loads(open(path).read())
    core["last_committed"] = 999  # forged without resealing
    with open(path, "w") as f:
        json.dump(core, f)
    report = storage.fsck(str(tmp_path / "ckpt"), repair=True)
    assert report["ok"] is False
    assert any(
        "sha256 mismatch" in e["detail"] for e in report["errors"]
    )


def test_fsck_tenant_tree_and_cli(tmp_path):
    # daemon-shaped layout: a root plus two tenant checkpoints
    root = tmp_path / "droot"
    for tid in ("a", "b"):
        q, _ = _engine(
            root, os.path.join("tenant", tid, "ckpt"), _frames(2),
        )
        assert q.process_available() == 2
        q.stop()
    with open(root / "tenant" / "a" / "ckpt" / "shed.jsonl", "w") as f:
        f.write('{"torn')
    from sntc_tpu.app import main

    rc = main([
        "fsck", str(root), "--tenant-tree",
        "--report", str(tmp_path / "report.json"),
        "--platform", "cpu",
    ])
    assert rc == 0
    report = json.loads(open(tmp_path / "report.json").read())
    assert report["tenant_tree"] is True
    assert report["ok"] is True
    assert {r["tenant"] for r in report["roots"]} == {None, "a", "b"}
    tenant_a = [r for r in report["roots"] if r["tenant"] == "a"][0]
    assert tenant_a["repaired"]


def test_engine_quick_scan_heals_journals(tmp_path):
    """The construction-time auto-scan: a torn shed.jsonl tail from a
    crashed run heals before the new engine serves."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    with open(ckpt / "shed.jsonl", "w") as f:
        f.write('{"ok": 1}\n{"torn')
    q, _ = _engine(tmp_path, "ckpt", _frames(1))
    assert q.storage_scan is not None
    assert q.storage_scan["repaired"]
    with open(ckpt / "shed.jsonl") as f:
        assert f.read() == '{"ok": 1}\n'
    assert "startup_scan" in q.storage_stats()
    q.stop()


# ---------------------------------------------------------------------------
# disk accounting, budgets, status blocks
# ---------------------------------------------------------------------------


def test_storage_plane_usage_and_budget(tmp_path):
    q, _ = _engine(
        tmp_path, "ckpt", _frames(5), wal_mode="append",
    )
    assert q.process_available() == 5
    q.stop()
    plane = storage.StoragePlane(
        str(tmp_path / "ckpt"), budget_bytes=10, min_interval_s=0.0,
    )
    status = plane.status()
    assert status["over_budget"] is True
    assert status["total_bytes"] > 10
    assert "wal_append" in status["artifacts"]
    assert _get("sntc_disk_bytes", artifact="total") > 0
    assert _get("sntc_disk_budget_bytes") == 10
    events = [e["event"] for e in R.recent_events()]
    assert events.count("disk_budget_exceeded") == 1
    plane.status()  # same breach: no second event
    events = [e["event"] for e in R.recent_events()]
    assert events.count("disk_budget_exceeded") == 1
    plane.budget_bytes = 10**9
    assert plane.status()["over_budget"] is False


def test_supervisor_status_carries_storage_block(tmp_path):
    q, _ = _engine(
        tmp_path, "ckpt", _frames(3), wal_mode="append",
        wal_compact_every=2,
    )
    sup = QuerySupervisor(q, disk_budget_mb=1.0)
    try:
        sup.tick()
        sup.tick()
        sup.tick()
        st = sup.status()["storage"]
        assert st["wal_mode"] == "append"
        assert st["wal_compactions"] >= 1
        assert st["disk"]["budget_bytes"] == 1 << 20
        assert st["disk"]["total_bytes"] > 0
    finally:
        sup.close()
        q.stop()


def test_daemon_status_carries_storage_block(tmp_path):
    from sntc_tpu.serve.tenancy import ServeDaemon, TenantSpec

    frames = _frames(2)
    specs = [
        TenantSpec(
            tenant_id=tid, model=_Identity(),
            source=MemorySource(list(frames)), sink=MemorySink(),
            disk_budget_mb=0.000001 if tid == "a" else None,
        )
        for tid in ("a", "b")
    ]
    daemon = ServeDaemon(specs, str(tmp_path / "root"))
    try:
        daemon.process_available()
        st = daemon.status()["storage"]
        assert set(st["tenants"]) == {"a", "b"}
        assert st["tenants"]["a"]["over_budget"] is True
        assert st["tenants"]["b"]["budget_bytes"] is None
        assert st["engines"]["a"]["wal_mode"] == "files"
        assert st["global"]["total_bytes"] > 0
        # the budget breach degraded ONLY tenant a's namespace
        from sntc_tpu.resilience import HealthState

        assert daemon.health.worst_under(
            "tenant/a/"
        ) == HealthState.DEGRADED
        assert daemon.health.worst_under(
            "tenant/b/"
        ) == HealthState.OK
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# drift check + chaos wiring (tier-1)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_durable_artifacts_consistent():
    checker = _load_script("check_durable_artifacts")
    assert checker.check() == []


def test_chaos_wal_torn_scenarios(tmp_path):
    """Kill-mid-append (the worker os._exits with half of batch 2's
    intent / commit line flushed); the restart journals a
    truncate_torn_tail repair record and reconverges committed state +
    sink file CONTENTS bitwise with the uninterrupted compacting
    reference."""
    chaos = _load_script("chaos_crash_matrix")
    ref = chaos.run_wal_reference(str(tmp_path))
    for name, after in chaos.WAL_TORN_SCENARIOS:
        verdict = chaos.run_wal_torn_scenario(
            str(tmp_path), name, after, ref
        )
        assert verdict["ok"], verdict
        assert verdict["torn_tail_on_disk"] and verdict["repair_journaled"]


def test_chaos_disk_fault_drain(tmp_path):
    """ENOSPC/EIO armed at every serve-reachable durable write site at
    once: the supervised worker serves degraded and exits 0 on drain."""
    chaos = _load_script("chaos_crash_matrix")
    verdict = chaos.run_disk_fault_scenario(str(tmp_path))
    assert verdict["ok"], verdict
