"""GaussianMixture tests: blob recovery, posterior semantics, sklearn
log-likelihood comparison, anisotropic covariance capture, save/load."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.mlio import load_model, save_model
from sntc_tpu.models import GaussianMixture, GaussianMixtureModel


def _blobs(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[-4.0, 0.0, 2.0], [3.0, 3.0, -1.0], [0.0, -4.0, -3.0]])
    y = rng.integers(0, 3, size=n)
    X = (centers[y] + rng.normal(size=(n, 3))).astype(np.float32)
    return Frame({"features": X}), X, y, centers


def _match_rate(pred, y, k):
    """Best label-permutation agreement (clustering has no fixed ids)."""
    from itertools import permutations

    best = 0.0
    for perm in permutations(range(k)):
        mapped = np.array([perm[int(p)] for p in pred])
        best = max(best, (mapped == y).mean())
    return best


def test_gmm_recovers_blobs(mesh8):
    f, X, y, centers = _blobs()
    m = GaussianMixture(mesh=mesh8, k=3, seed=1).fit(f)
    out = m.transform(f)
    pred = np.asarray(out["prediction"])
    assert _match_rate(pred, y, 3) > 0.97
    # every true center has a recovered mean nearby
    d = np.linalg.norm(m.means[:, None, :] - centers[None], axis=2)
    assert d.min(axis=0).max() < 0.5
    prob = out["probability"]
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, rtol=1e-5)
    assert m.weights.sum() == pytest.approx(1.0)
    assert len(m.gaussians) == 3
    assert m.summary.totalIterations > 0


def test_gmm_loglik_comparable_to_sklearn(mesh8):
    from sklearn.mixture import GaussianMixture as SkGMM

    f, X, y, _ = _blobs(seed=2)
    m = GaussianMixture(mesh=mesh8, k=3, seed=0, tol=1e-4, maxIter=200).fit(f)
    sk = SkGMM(
        n_components=3, covariance_type="full", random_state=0,
        tol=1e-4, max_iter=200,
    ).fit(X)
    ours = m.summary.logLikelihood
    theirs = float(sk.score(X))  # mean log-likelihood
    assert ours == pytest.approx(theirs, abs=0.05)


def test_gmm_captures_anisotropic_covariance(mesh8):
    """Full covariance must capture a strongly correlated component —
    the capability diagonal/spherical mixtures lack."""
    rng = np.random.default_rng(3)
    n = 4000
    A = np.array([[2.0, 1.8], [1.8, 2.0]])  # corr ~0.9
    X1 = rng.multivariate_normal([0, 0], A, size=n // 2)
    X2 = rng.multivariate_normal([8, -8], np.eye(2) * 0.5, size=n // 2)
    X = np.concatenate([X1, X2]).astype(np.float32)
    f = Frame({"features": X})
    m = GaussianMixture(mesh=mesh8, k=2, seed=0, tol=1e-4).fit(f)
    # the component near the origin carries the correlated covariance
    i = int(np.argmin(np.linalg.norm(m.means, axis=1)))
    cov = m.covs[i]
    corr = cov[0, 1] / np.sqrt(cov[0, 0] * cov[1, 1])
    assert corr > 0.8


def test_gmm_save_load_and_validation(mesh8, tmp_path):
    f, X, y, _ = _blobs(n=900, seed=4)
    m = GaussianMixture(mesh=mesh8, k=3, seed=0).fit(f)
    m2 = load_model(save_model(m, str(tmp_path / "gmm")))
    assert isinstance(m2, GaussianMixtureModel)
    np.testing.assert_allclose(
        m2.predictProbability(X), m.predictProbability(X), rtol=1e-6
    )
    with pytest.raises(ValueError, match="at least k"):
        GaussianMixture(mesh=mesh8, k=5).fit(
            Frame({"features": X[:3]})
        )
