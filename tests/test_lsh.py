"""LSH family: formula oracles (numpy recomputation), exact-recovery
checks (bucket width → brute-force agreement), and the collision
property LSH exists to provide."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import (
    BucketedRandomProjectionLSH,
    MinHashLSH,
)
from sntc_tpu.feature.lsh import HASH_PRIME
from sntc_tpu.mlio.save_load import load_model, save_model


@pytest.fixture(scope="module")
def dense():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(500, 8)).astype(np.float32)
    return Frame({"features": X})


def test_brp_hash_formula(mesh8, dense):
    m = BucketedRandomProjectionLSH(
        numHashTables=4, bucketLength=2.0, seed=5
    ).fit(dense)
    H = m.transform(dense)["hashes"]
    X = dense["features"]
    ref = np.floor(
        X.astype(np.float64) @ m.randUnitVectors.astype(np.float64).T / 2.0
    )
    np.testing.assert_allclose(H, ref, atol=1e-4)
    np.testing.assert_allclose(
        np.linalg.norm(m.randUnitVectors, axis=1), 1.0, atol=1e-6
    )


def test_brp_ann_matches_bruteforce_on_candidates(mesh8, dense):
    # huge bucketLength still splits on projection SIGN (floor(±eps) is
    # -1 or 0), so compute the candidate set independently in numpy and
    # check the query returns exact kNN within it
    m = BucketedRandomProjectionLSH(
        numHashTables=2, bucketLength=1e6, seed=0
    ).fit(dense)
    X = dense["features"]
    key = X[7]
    out = m.approxNearestNeighbors(dense, key, 5)
    H = np.floor(X.astype(np.float64) @ m.randUnitVectors.T.astype(np.float64) / 1e6)
    hk = np.floor(key.astype(np.float64) @ m.randUnitVectors.T.astype(np.float64) / 1e6)
    cand = np.nonzero((H == hk[None, :]).any(axis=1))[0]
    d_all = np.linalg.norm(X[cand].astype(np.float64) - key, axis=1)
    ref = np.sort(d_all)[:5]
    np.testing.assert_allclose(np.sort(out["distCol"]), ref, atol=1e-4)
    assert out["distCol"][0] == pytest.approx(0.0, abs=1e-6)  # key itself
    assert out.num_rows == 5


def test_brp_join_exact_when_one_bucket(mesh8):
    rng = np.random.default_rng(3)
    Xa = rng.normal(size=(40, 4)).astype(np.float32)
    Xb = rng.normal(size=(30, 4)).astype(np.float32)
    fa, fb = Frame({"features": Xa}), Frame({"features": Xb})
    m = BucketedRandomProjectionLSH(
        numHashTables=1, bucketLength=1e6, seed=1
    ).fit(fa)
    out = m.approxSimilarityJoin(fa, fb, threshold=1.5)
    d = np.linalg.norm(
        Xa.astype(np.float64)[:, None, :] - Xb[None, :, :], axis=2
    )
    R = m.randUnitVectors.astype(np.float64)
    ha = np.floor(Xa.astype(np.float64) @ R.T / 1e6)
    hb = np.floor(Xb.astype(np.float64) @ R.T / 1e6)
    same_bucket = (ha[:, None, :] == hb[None, :, :]).any(axis=2)
    ia, ib = np.nonzero((d < 1.5) & same_bucket)
    got = set(zip(out["idA"].tolist(), out["idB"].tolist()))
    assert got == set(zip(ia.tolist(), ib.tolist()))
    for a, b, dist in zip(out["idA"], out["idB"], out["distCol"]):
        assert dist == pytest.approx(d[a, b], abs=1e-4)


def test_brp_collision_property(mesh8):
    # near pair collides in some table; far pair collides in none
    rng = np.random.default_rng(9)
    base = rng.normal(size=8).astype(np.float32)
    X = np.stack([base, base + 0.01, base + 50.0])
    m = BucketedRandomProjectionLSH(
        numHashTables=8, bucketLength=1.0, seed=2
    ).fit(Frame({"features": X}))
    H = m.transform(Frame({"features": X}))["hashes"]
    assert (H[0] == H[1]).sum() >= 6
    assert (H[0] == H[2]).sum() == 0


def test_minhash_formula_and_jaccard(mesh8):
    rng = np.random.default_rng(4)
    X = (rng.random(size=(60, 30)) < 0.3).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0  # no empty sets
    f = Frame({"features": X})
    m = MinHashLSH(numHashTables=3, seed=8).fit(f)
    H = m.transform(f)["hashes"]
    a = m.randCoefficients[:, 0]
    b = m.randCoefficients[:, 1]
    j = np.arange(1, 31, dtype=np.int64)
    table = (j[None, :] * a[:, None] + b[:, None]) % HASH_PRIME  # [L,F]
    for i in range(60):
        active = X[i] != 0
        ref = table[:, active].min(axis=1)
        np.testing.assert_array_equal(H[i], ref)
    # keyDistance = jaccard distance, both pairwise and paired forms
    d_pair = m.keyDistance(X[:5], X[5:10], paired=True)
    d_full = m.keyDistance(X[:5], X[5:10])
    for i in range(5):
        inter = np.sum((X[i] != 0) & (X[5 + i] != 0))
        union = np.sum((X[i] != 0) | (X[5 + i] != 0))
        assert d_pair[i] == pytest.approx(1 - inter / union)
        assert d_full[i, i] == pytest.approx(d_pair[i])


def test_minhash_validation(mesh8):
    m = MinHashLSH(numHashTables=2).fit(
        Frame({"features": np.eye(3, dtype=np.float32)})
    )
    with pytest.raises(ValueError, match="binary"):
        m.transform(Frame({"features": np.array([[0.5, 1.0]], np.float32)}))
    with pytest.raises(ValueError, match="nonzero"):
        m.transform(Frame({"features": np.zeros((1, 3), np.float32)}))


def test_minhash_ann(mesh8):
    rng = np.random.default_rng(6)
    X = (rng.random(size=(200, 40)) < 0.25).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    f = Frame({"features": X})
    m = MinHashLSH(numHashTables=12, seed=1).fit(f)
    key = X[3]
    out = m.approxNearestNeighbors(f, key, 3)
    assert out.num_rows >= 1
    assert out["distCol"][0] == pytest.approx(0.0)  # finds the key itself


def test_lsh_accepts_1d_column(mesh8):
    # fit accepts a scalar column; transform/queries must too
    x = np.linspace(-3, 3, 64).astype(np.float32)
    f = Frame({"features": x})
    m = BucketedRandomProjectionLSH(
        numHashTables=2, bucketLength=1.0, seed=0
    ).fit(f)
    H = m.transform(f)["hashes"]
    assert H.shape == (64, 2)
    out = m.approxNearestNeighbors(f, np.array([0.0]), 3)
    assert out.num_rows >= 1
    join = m.approxSimilarityJoin(f, f, threshold=0.05)
    assert (join["idA"] == join["idB"]).sum() == 64  # self-pairs at d=0


def test_lsh_save_load(mesh8, dense, tmp_path):
    brp = BucketedRandomProjectionLSH(
        numHashTables=3, bucketLength=2.5, seed=7
    ).fit(dense)
    save_model(brp, str(tmp_path / "brp"))
    brp2 = load_model(str(tmp_path / "brp"))
    np.testing.assert_allclose(brp2.randUnitVectors, brp.randUnitVectors)
    assert brp2.getBucketLength() == 2.5
    np.testing.assert_allclose(
        brp2.transform(dense)["hashes"], brp.transform(dense)["hashes"]
    )

    Xb = (np.random.default_rng(2).random((20, 10)) < 0.5).astype(np.float32)
    Xb[Xb.sum(axis=1) == 0, 0] = 1.0
    fb = Frame({"features": Xb})
    mh = MinHashLSH(numHashTables=2, seed=3).fit(fb)
    save_model(mh, str(tmp_path / "mh"))
    mh2 = load_model(str(tmp_path / "mh"))
    np.testing.assert_array_equal(mh2.randCoefficients, mh.randCoefficients)
    np.testing.assert_array_equal(
        mh2.transform(fb)["hashes"], mh.transform(fb)["hashes"]
    )
