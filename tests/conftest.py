"""Test harness: fake 8-device CPU mesh (SURVEY.md §4.1).

Must run before jax is imported anywhere: forces the CPU platform with 8
virtual devices — the ``local[2]``/``local-cluster`` analog — so all
pmap/psum/shard_map code paths run multi-device without TPU hardware.
"""

import os

# Force-override: the host environment pins JAX_PLATFORMS to the real TPU and
# its sitecustomize imports jax at interpreter startup, so the env var alone is
# ignored — XLA_FLAGS must land before first backend init, the platform via
# jax.config.update after import.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# tests invoke bench.py helpers (smoke tests); the committed run journal
# must hold only real bench invocations
os.environ["BENCH_NO_JOURNAL"] = "1"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# SURVEY.md §5.2: CICIDS2017's Inf/NaN values make silent NaN propagation a
# real hazard — fail tests at the op that produced the first NaN.
jax.config.update("jax_debug_nans", True)


@pytest.fixture(scope="session")
def mesh8():
    from sntc_tpu.parallel import default_mesh

    assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"
    return default_mesh()
