"""LDA: planted-topic recovery (the generative model's own oracle),
topic-distribution inference, perplexity ordering vs a mismatched model,
describeTopics shapes, save/load."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.models import LDA
from sntc_tpu.mlio.save_load import load_model, save_model

V = 30  # vocabulary
K = 3


def _planted_corpus(n_docs=300, doc_len=80, seed=0):
    """Three disjoint-support topics: recovery is unambiguous."""
    rng = np.random.default_rng(seed)
    beta = np.zeros((K, V))
    for t in range(K):
        beta[t, t * 10:(t + 1) * 10] = 1.0 / 10
    X = np.zeros((n_docs, V), np.float32)
    dominant = np.zeros(n_docs, np.int64)
    for d in range(n_docs):
        theta = rng.dirichlet([0.2] * K)
        dominant[d] = theta.argmax()
        words = rng.choice(V, size=doc_len, p=theta @ beta)
        X[d] = np.bincount(words, minlength=V)
    return X, beta, dominant


@pytest.fixture(scope="module")
def corpus():
    return _planted_corpus()


@pytest.fixture(scope="module")
def fitted(corpus):
    X, _, _ = corpus
    return LDA(
        k=K, maxIter=60, subsamplingRate=0.2, seed=1,
    ).fit(Frame({"features": X}))


def test_recovers_planted_topics(corpus, fitted):
    _, beta, _ = corpus
    topics = fitted.topicsMatrix().T  # [k, V]
    # match each true topic to its best learned topic: the 10-word
    # support must carry most of the mass
    used = set()
    for t in range(K):
        support = beta[t] > 0
        mass = topics[:, support].sum(axis=1)
        best = int(np.argmax(mass))
        assert mass[best] > 0.85
        used.add(best)
    assert len(used) == K  # distinct learned topic per true topic


def test_topic_distribution_follows_dominant_topic(corpus, fitted):
    X, beta, dominant = corpus
    out = fitted.transform(Frame({"features": X}))
    theta = out["topicDistribution"]
    assert theta.shape == (len(X), K)
    np.testing.assert_allclose(theta.sum(axis=1), 1.0, atol=1e-6)
    # learned topic index for each true topic
    topics = fitted.topicsMatrix().T
    t_map = [
        int(np.argmax(topics[:, beta[t] > 0].sum(axis=1))) for t in range(K)
    ]
    pred_dom = theta.argmax(axis=1)
    agree = (pred_dom == np.array(t_map)[dominant]).mean()
    assert agree > 0.8


def test_perplexity_beats_mismatched_model(corpus, fitted):
    X, _, _ = corpus
    f = Frame({"features": X})
    good = fitted.logPerplexity(f)
    bad = LDA(k=K, maxIter=1, subsamplingRate=0.05, seed=9).fit(f)
    assert good < bad.logPerplexity(f)
    assert fitted.logLikelihood(f) < 0


def test_describe_topics(fitted):
    d = fitted.describeTopics(5)
    assert d["termIndices"].shape == (K, 5)
    assert d["termWeights"].shape == (K, 5)
    # weights sorted descending within each topic
    w = d["termWeights"]
    assert (np.diff(w, axis=1) <= 1e-12).all()


def test_validation():
    with pytest.raises(ValueError, match="non-negative"):
        LDA(k=2).fit(
            Frame({"features": -np.ones((4, 5), np.float32)})
        )


def test_save_load(corpus, fitted, tmp_path):
    X, _, _ = corpus
    save_model(fitted, str(tmp_path / "lda"))
    m2 = load_model(str(tmp_path / "lda"))
    np.testing.assert_allclose(m2.lam, fitted.lam)
    f = Frame({"features": X[:20]})
    np.testing.assert_allclose(
        m2.transform(f)["topicDistribution"],
        fitted.transform(f)["topicDistribution"],
        atol=1e-6,
    )


def test_em_optimizer_recovers_topics_deterministically(corpus):
    """optimizer='em' (full-corpus batch VB-EM) must recover the planted
    topics, apply Spark's EM auto-defaults (α=(50/k)+1, η=1.1), and be
    deterministic (no minibatch sampling anywhere)."""
    X, beta, _ = corpus
    m = LDA(k=K, maxIter=15, optimizer="em", seed=1).fit(
        Frame({"features": X})
    )
    assert m.alpha == pytest.approx(50.0 / K + 1.0)
    assert m.eta == pytest.approx(1.1)
    topics = m.topicsMatrix().T
    used = set()
    for t in range(K):
        support = beta[t] > 0
        mass = topics[:, support].sum(axis=1)
        best = int(np.argmax(mass))
        assert mass[best] > 0.85
        used.add(best)
    assert len(used) == K
    m2 = LDA(k=K, maxIter=15, optimizer="em", seed=1).fit(
        Frame({"features": X})
    )
    np.testing.assert_allclose(m2.lam, m.lam)
