import os

import numpy as np

from sntc_tpu.parallel import global_mesh, initialize, process_info


def test_initialize_noop_single_host(monkeypatch):
    for m in (
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",
    ):
        monkeypatch.delenv(m, raising=False)
    assert initialize() is False  # no multi-host markers -> no-op


def test_global_mesh_covers_all_devices(mesh8):
    m = global_mesh()
    assert m.devices.size == 8
    assert m.axis_names == ("data",)
    m2 = global_mesh(model=2)
    assert dict(m2.shape) == {"data": 4, "model": 2}
    # the mesh drives a real reduction
    import jax.numpy as jnp

    from sntc_tpu.parallel import make_tree_aggregate, shard_batch

    x = np.ones((16, 2), np.float32)
    xs, w = shard_batch(m, x)
    out = make_tree_aggregate(lambda xs, w: jnp.sum(xs * w[:, None]), m)(xs, w)
    assert float(out) == 32.0


def test_process_info_single():
    info = process_info()
    assert info["process_count"] == 1 and info["process_index"] == 0


_WORKER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

# the host sitecustomize pins jax_platforms to the TPU tunnel at import
# time; the env var alone is ignored (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
import numpy as np
from sntc_tpu.parallel.distributed import (
    global_mesh, initialize, process_info,
)

pid, port = int(sys.argv[1]), sys.argv[2]
assert initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=2,
    process_id=pid,
)
info = process_info()
assert info["process_count"] == 2, info
assert info["process_index"] == pid, info
assert info["global_devices"] == 4, info
mesh = global_mesh()
assert mesh.devices.size == 4

# a REAL cross-process collective: allgather each process's scalar
from jax.experimental import multihost_utils

g = multihost_utils.process_allgather(np.array([float(pid + 1)]))
assert g.reshape(-1).tolist() == [1.0, 2.0], g
print("DIST_OK", flush=True)
"""


def test_two_process_initialize(tmp_path):
    """jax.distributed.initialize exercised for REAL: two coordinated
    processes (2 virtual CPU devices each), global mesh over all 4
    devices, one cross-process allgather (SURVEY.md §5.8)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert "DIST_OK" in out
