import os

import numpy as np
import pytest

from sntc_tpu.parallel import global_mesh, initialize, process_info

# this container's jax build cannot run coordinated multi-process
# computations on the CPU backend — the workers die with exactly this
# message.  The two-process tests detect that SIGNATURE at runtime and
# skip (environment limitation, not a regression); on a backend that
# supports multiprocess they still run and assert in full.
_MULTIPROCESS_UNSUPPORTED = "Multiprocess computations aren't implemented"


def _require_pair_ok(procs, outs, marker):
    if any(_MULTIPROCESS_UNSUPPORTED in out for out in outs) and any(
        p.returncode != 0 for p in procs
    ):
        pytest.skip(
            "Multiprocess computations aren't implemented on the CPU "
            "backend on this jax build"
        )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert marker in out


def test_initialize_noop_single_host(monkeypatch):
    for m in (
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",
    ):
        monkeypatch.delenv(m, raising=False)
    assert initialize() is False  # no multi-host markers -> no-op


def test_global_mesh_covers_all_devices(mesh8):
    m = global_mesh()
    assert m.devices.size == 8
    assert m.axis_names == ("data",)
    m2 = global_mesh(model=2)
    assert dict(m2.shape) == {"data": 4, "model": 2}
    # the mesh drives a real reduction
    import jax.numpy as jnp

    from sntc_tpu.parallel import make_tree_aggregate, shard_batch

    x = np.ones((16, 2), np.float32)
    xs, w = shard_batch(m, x)
    out = make_tree_aggregate(lambda xs, w: jnp.sum(xs * w[:, None]), m)(xs, w)
    assert float(out) == 32.0


def test_process_info_single():
    info = process_info()
    assert info["process_count"] == 1 and info["process_index"] == 0


# ---------------------------------------------------------------------------
# faked-device in-process legs (r22): the two-process legs below skip on
# this container's jax build, so tier-1 exercises the SAME estimator
# assertions over >1 device here — the faked 8-device CPU mesh and a
# 2-device subset (the smallest true multi-shard shape).  Only the
# cross-process coordination itself stays subprocess-gated.
# ---------------------------------------------------------------------------


def _planted_frame(n=2000, d=6, seed=0):
    from sntc_tpu.core.frame import Frame

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.0, -1.0, 0.5, 0.0, 0.0, 0.0])
    y = (X @ beta + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    return Frame({"features": X, "label": y}), beta


@pytest.mark.parametrize("n_devices", [2, 8])
def test_estimator_fit_over_faked_device_mesh(n_devices):
    """The _FIT_WORKER assertions, in-process: a REAL LogisticRegression
    fit SPMD over a multi-device mesh learns the planted direction, the
    tree path's histogram collective agrees, and a repeat fit is
    bit-identical (deterministic SPMD program, no device-order
    dependence)."""
    from sntc_tpu.models import DecisionTreeClassifier, LogisticRegression
    from sntc_tpu.parallel import default_mesh

    mesh = default_mesh(n_devices)
    f, beta = _planted_frame()
    m = LogisticRegression(mesh=mesh, maxIter=40).fit(f)
    coef = np.asarray(m.coefficients, np.float64)
    corr = float(
        coef[:3] @ beta[:3]
        / (np.linalg.norm(coef[:3]) * np.linalg.norm(beta[:3]))
    )
    assert corr > 0.95, corr
    y = np.asarray(f["label"])
    acc = float((np.asarray(m.transform(f)["prediction"]) == y).mean())
    assert acc > 0.9, acc
    m2 = LogisticRegression(mesh=mesh, maxIter=40).fit(f)
    np.testing.assert_array_equal(
        coef, np.asarray(m2.coefficients, np.float64)
    )

    dt = DecisionTreeClassifier(mesh=mesh, maxDepth=3).fit(f)
    dt_acc = float((np.asarray(dt.transform(f)["prediction"]) == y).mean())
    assert dt_acc > 0.8, dt_acc


_WORKER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

# the host sitecustomize pins jax_platforms to the TPU tunnel at import
# time; the env var alone is ignored (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
import numpy as np
from sntc_tpu.parallel.distributed import (
    global_mesh, initialize, process_info,
)

pid, port = int(sys.argv[1]), sys.argv[2]
assert initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=2,
    process_id=pid,
)
info = process_info()
assert info["process_count"] == 2, info
assert info["process_index"] == pid, info
assert info["global_devices"] == 4, info
mesh = global_mesh()
assert mesh.devices.size == 4

# a REAL cross-process collective: allgather each process's scalar
from jax.experimental import multihost_utils

g = multihost_utils.process_allgather(np.array([float(pid + 1)]))
assert g.reshape(-1).tolist() == [1.0, 2.0], g
print("DIST_OK", flush=True)
"""


_FIT_WORKER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np
from sntc_tpu.parallel.distributed import global_mesh, initialize

pid, port = int(sys.argv[1]), sys.argv[2]
assert initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=2,
    process_id=pid,
)
mesh = global_mesh()
assert mesh.devices.size == 4

# identical data on both processes (the single-host data plane,
# replicated): a REAL LogisticRegression fit over the 2-process mesh
from sntc_tpu.core.frame import Frame
from sntc_tpu.models import LogisticRegression

rng = np.random.default_rng(0)
X = rng.normal(size=(4000, 6)).astype(np.float32)
beta = np.array([1.0, -1.0, 0.5, 0.0, 0.0, 0.0])
y = (X @ beta + 0.1 * rng.normal(size=4000) > 0).astype(np.float64)
f = Frame({"features": X, "label": y})
m = LogisticRegression(mesh=mesh, maxIter=40).fit(f)
coef = np.asarray(m.coefficients, np.float64)

# both processes must agree bit-for-bit on the result (SPMD), and the
# fit must have learned the planted direction
from jax.experimental import multihost_utils

both = multihost_utils.process_allgather(coef.astype(np.float32))
assert np.array_equal(both[0], both[1]), (both[0] - both[1])
corr = float(
    coef[:3] @ beta[:3] / (np.linalg.norm(coef[:3]) * np.linalg.norm(beta[:3]))
)
assert corr > 0.95, corr
acc = float((m.transform(f)["prediction"] == y).mean())
assert acc > 0.9, acc

# the TREE path too (a different collective shape: binned histogram
# aggregation inside the grower, psum'd across processes)
from sntc_tpu.models import DecisionTreeClassifier

dt = DecisionTreeClassifier(mesh=mesh, maxDepth=3).fit(f)
pred_col = dt.transform(f)["prediction"]
dt_acc = float((pred_col == y).mean())
assert dt_acc > 0.8, dt_acc
dt_pred = np.asarray(pred_col, np.float32)[:64]
both_dt = multihost_utils.process_allgather(dt_pred)
assert np.array_equal(both_dt[0], both_dt[1])
print("FIT_OK", round(acc, 3), round(dt_acc, 3), flush=True)
"""


def _run_pair(tmp_path, script_text, timeout=300):
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    return procs, outs


def test_two_process_estimator_fit(tmp_path):
    """A REAL estimator fit across two coordinated processes: the
    mesh-sharded LBFGS program runs SPMD over 2×2 devices with
    cross-process collectives, both processes produce bit-identical
    coefficients, and the fit learns (SURVEY.md §5.8 beyond the
    allgather smoke — shard_batch builds true global arrays via
    make_array_from_callback when the mesh spans processes)."""
    procs, outs = _run_pair(tmp_path, _FIT_WORKER)
    _require_pair_ok(procs, outs, "FIT_OK")


def test_two_process_initialize(tmp_path):
    """jax.distributed.initialize exercised for REAL: two coordinated
    processes (2 virtual CPU devices each), global mesh over all 4
    devices, one cross-process allgather (SURVEY.md §5.8)."""
    procs, outs = _run_pair(tmp_path, _WORKER)
    _require_pair_ok(procs, outs, "DIST_OK")
