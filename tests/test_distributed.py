import numpy as np

from sntc_tpu.parallel import global_mesh, initialize, process_info


def test_initialize_noop_single_host(monkeypatch):
    for m in (
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",
    ):
        monkeypatch.delenv(m, raising=False)
    assert initialize() is False  # no multi-host markers -> no-op


def test_global_mesh_covers_all_devices(mesh8):
    m = global_mesh()
    assert m.devices.size == 8
    assert m.axis_names == ("data",)
    m2 = global_mesh(model=2)
    assert dict(m2.shape) == {"data": 4, "model": 2}
    # the mesh drives a real reduction
    import jax.numpy as jnp

    from sntc_tpu.parallel import make_tree_aggregate, shard_batch

    x = np.ones((16, 2), np.float32)
    xs, w = shard_batch(m, x)
    out = make_tree_aggregate(lambda xs, w: jnp.sum(xs * w[:, None]), m)(xs, w)
    assert float(out) == 32.0


def test_process_info_single():
    info = process_info()
    assert info["process_count"] == 1 and info["process_index"] == 0
