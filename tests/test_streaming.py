"""Streaming engine tests — the StreamTest/MemoryStream analog (SURVEY.md §4
item 4): deterministic stepping, stop/restart with the same checkpoint dir,
exactly-once delivery, crash-after-intent replay."""

import json
import os

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame
from sntc_tpu.data import generate_frame, write_day_csvs
from sntc_tpu.models import LogisticRegression
from sntc_tpu.serve import (
    BatchPredictor,
    CsvDirSink,
    FileStreamSource,
    MemorySink,
    MemorySource,
    StreamingQuery,
)


@pytest.fixture(scope="module")
def model(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(800, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    return LogisticRegression(mesh=mesh8, maxIter=30).fit(
        Frame({"features": X, "label": y})
    )


def _batch(n, seed):
    rng = np.random.default_rng(seed)
    return Frame({"features": rng.normal(size=(n, 4)).astype(np.float32)})


def test_batch_predictor_chunks(model):
    f = _batch(1000, 1)
    out = BatchPredictor(model, chunk_rows=128).predict_frame(f)
    ref = model.transform(f)
    np.testing.assert_array_equal(out["prediction"], ref["prediction"])
    # arrow roundtrip path
    table = BatchPredictor(model).predict_batch(f.to_arrow())
    assert "prediction" in table.column_names


def test_streaming_processes_available_batches(model, tmp_path):
    src = MemorySource([_batch(50, 1), _batch(60, 2)])
    sink = MemorySink()
    q = StreamingQuery(model, src, sink, str(tmp_path / "ckpt"))
    assert q.process_available() == 1  # both frames drained in one batch
    assert sink.frames[0].num_rows == 110
    # new data arrives -> next batch only covers the delta
    src.add(_batch(30, 3))
    assert q.process_available() == 1
    assert sink.frames[1].num_rows == 30
    assert q.process_available() == 0


def test_streaming_resume_no_duplicates(model, tmp_path):
    ckpt = str(tmp_path / "ckpt")
    src = MemorySource([_batch(40, 1)])
    sink1 = MemorySink()
    q1 = StreamingQuery(model, src, sink1, ckpt)
    q1.process_available()
    q1.stop()

    # restart with same checkpoint: already-committed data is NOT reprocessed
    sink2 = MemorySink()
    q2 = StreamingQuery(model, src, sink2, ckpt)
    assert q2.process_available() == 0
    src.add(_batch(25, 2))
    assert q2.process_available() == 1
    assert [f.num_rows for f in sink2.frames] == [25]


def test_streaming_crash_after_intent_replays_exact_range(model, tmp_path):
    """Intent logged but uncommitted (crash between WAL and commit) -> the
    restarted query replays EXACTLY the logged range, even though more data
    arrived meanwhile (Spark's OffsetSeqLog recovery contract)."""
    ckpt = str(tmp_path / "ckpt")
    src = MemorySource([_batch(10, 1), _batch(20, 2)])
    os.makedirs(os.path.join(ckpt, "offsets"))
    os.makedirs(os.path.join(ckpt, "commits"))
    with open(os.path.join(ckpt, "offsets", "0.json"), "w") as f:
        json.dump({"batch_id": 0, "start": 0, "end": 1}, f)
    src.add(_batch(30, 3))  # late arrival

    sink = MemorySink()
    q = StreamingQuery(model, src, sink, ckpt)
    assert q.process_available() == 2
    # batch 0 replayed with the OLD range (first frame only), batch 1 gets the rest
    assert [f.num_rows for f in sink.frames] == [10, 50]


def test_streaming_max_batch_offsets(model, tmp_path):
    src = MemorySource([_batch(5, i) for i in range(4)])
    sink = MemorySink()
    q = StreamingQuery(
        model, src, sink, str(tmp_path / "ckpt"), max_batch_offsets=1
    )
    assert q.process_available() == 4  # one source offset per micro-batch
    assert [f.num_rows for f in sink.frames] == [5, 5, 5, 5]


def test_file_source_and_csv_sink(model, tmp_path, mesh8):
    """End-to-end config-5: CSV files stream in, predictions stream out,
    with offset/commit resume across query restarts [B:11]."""
    from sntc_tpu.data import CICIDS2017_FEATURES, clean_flows
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.feature import StandardScaler, StringIndexer, VectorAssembler

    train = clean_flows(generate_frame(3000, seed=5))
    train = train.with_column(
        "binLabel",
        np.where(train["Label"].astype(str) == "BENIGN", "benign", "attack").astype(object),
    )
    pipe_model = Pipeline(stages=[
        StringIndexer(inputCol="binLabel", outputCol="label"),
        VectorAssembler(inputCols=CICIDS2017_FEATURES, outputCol="features",
                        handleInvalid="skip"),
        LogisticRegression(mesh=mesh8, maxIter=30),
    ]).fit(train)
    # serving pipeline: drop the indexer (no labels on live flows)
    from sntc_tpu.core.base import PipelineModel
    serve_model = PipelineModel(stages=pipe_model.getStages()[1:])

    in_dir, out_dir = str(tmp_path / "in"), str(tmp_path / "out")
    write_day_csvs(in_dir, n_rows_per_day=40, n_days=2, seed=6)
    q = StreamingQuery(
        serve_model,
        FileStreamSource(in_dir),
        CsvDirSink(out_dir, columns=["prediction"]),
        str(tmp_path / "ckpt"),
    )
    assert q.process_available() == 1
    # two more day files land -> one more batch after "restart"
    write_day_csvs(in_dir, n_rows_per_day=40, n_days=4, seed=6)
    q2 = StreamingQuery(
        serve_model, FileStreamSource(in_dir),
        CsvDirSink(out_dir, columns=["prediction"]), str(tmp_path / "ckpt"),
    )
    assert q2.process_available() == 1
    outs = sorted(os.listdir(out_dir))
    assert outs == ["batch_000000.csv", "batch_000001.csv"]


# ---------------------------------------------------------------------------
# pipelined (async-dispatch) engine — VERDICT r1 item 3 / config 5
# ---------------------------------------------------------------------------


def test_transform_async_matches_transform(model):
    f = _batch(500, 7)
    ref = model.transform(f)
    out = model.transform_async(f)()
    for col in ("rawPrediction", "probability", "prediction"):
        np.testing.assert_allclose(out[col], ref[col], rtol=1e-6)
    assert out["prediction"].dtype == ref["prediction"].dtype


def test_transform_async_honors_threshold_and_thresholds(model):
    f = _batch(400, 8)
    for params in ({"threshold": 0.9}, {"thresholds": [0.7, 0.3]}):
        m = model.copy(params)
        np.testing.assert_array_equal(
            m.transform_async(f)()["prediction"],
            m.transform(f)["prediction"],
        )


def test_pipelined_query_matches_depth1(model, tmp_path):
    batches = [_batch(40, s) for s in range(6)]
    outs = {}
    for depth in (1, 3):
        src = MemorySource(batches)
        sink = MemorySink()
        q = StreamingQuery(
            model, src, sink, str(tmp_path / f"ckpt_d{depth}"),
            max_batch_offsets=1, pipeline_depth=depth,
        )
        assert q.process_available() == 6
        outs[depth] = sink
    for (i1, f1), (i3, f3) in zip(outs[1].batches, outs[3].batches):
        assert i1 == i3
        np.testing.assert_array_equal(f1["prediction"], f3["prediction"])


def test_pipelined_crash_replays_inflight_intents(model, tmp_path):
    """A crash with several WAL'd-but-uncommitted intents must replay them
    with their logged ranges on restart (exactly-once, depth > 1)."""
    ckpt = str(tmp_path / "ckpt_crash")
    batches = [_batch(40, s) for s in range(5)]
    src = MemorySource(batches)
    sink = MemorySink()
    q = StreamingQuery(model, src, sink, ckpt, max_batch_offsets=1,
                       pipeline_depth=3)
    # dispatch 3 intents, commit only the first, then "crash"
    assert q._run_one_batch()
    assert q.last_committed() == 0
    assert len(q._in_flight) == 2
    pending = [t[1] for t in q._in_flight]
    del q  # crash: in-flight batches lost, intents remain in the WAL

    sink2 = MemorySink()
    q2 = StreamingQuery(model, src, sink2, ckpt, max_batch_offsets=1,
                        pipeline_depth=3)
    assert q2.last_committed() == 0
    assert q2.process_available() == 4  # replays 2 intents + 2 fresh
    committed = sorted(
        int(os.path.splitext(p)[0])
        for p in os.listdir(os.path.join(ckpt, "commits"))
    )
    assert committed == [0, 1, 2, 3, 4]
    # the replayed batches used the crashed run's logged ranges
    with open(os.path.join(ckpt, "commits", "1.json")) as f:
        assert json.load(f) == pending[0]
    with open(os.path.join(ckpt, "commits", "2.json")) as f:
        assert json.load(f) == pending[1]
    # every source batch delivered exactly once, in order
    assert [f.num_rows for f in sink2.frames] == [40, 40, 40, 40]


def test_pipelined_sink_failure_retries_not_skips(model, tmp_path):
    """A transient sink failure must leave the batch queued for retry —
    not skip it and shift later batch ids (exactly-once under depth>1)."""
    batches = [_batch(30, s) for s in range(4)]
    src = MemorySource(batches)

    class FlakySink(MemorySink):
        def __init__(self):
            super().__init__()
            self.fail_on = {1}

        def add_batch(self, batch_id, frame):
            if batch_id in self.fail_on:
                self.fail_on.discard(batch_id)
                raise IOError("transient sink outage")
            super().add_batch(batch_id, frame)

    sink = FlakySink()
    q = StreamingQuery(model, src, sink, str(tmp_path / "ckpt_flaky"),
                       max_batch_offsets=1, pipeline_depth=2)
    with pytest.raises(IOError):
        q.process_available()
    # retry drains the rest, including the failed batch, in order
    assert q.process_available() == 3
    assert [i for i, _ in sink.batches] == [0, 1, 2, 3]
    assert q.last_committed() == 3


def test_crash_between_sink_and_commit_replays_and_sink_dedupes(
    model, tmp_path
):
    """Crash injected at ``stream.commit`` (post-sink, pre-commit): the
    batch's output reached the sink but no commit landed.  On restart
    the batch is REPLAYED with its WAL-logged range and the CSV sink
    dedupes by rewriting ``batch_<id>.csv`` in place — row counts stay
    exactly-once, never doubled."""
    import sntc_tpu.resilience as R

    ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")
    src = MemorySource([_batch(40, 1), _batch(25, 2)])
    q = StreamingQuery(
        model, src, CsvDirSink(out, columns=["prediction"]), ckpt,
        max_batch_offsets=1,
    )
    R.arm("stream.commit", times=1)
    try:
        with pytest.raises(R.InjectedFault):
            q.process_available()
    finally:
        R.clear()
    # the sink saw batch 0; the offset log did not
    assert os.path.exists(os.path.join(out, "batch_000000.csv"))
    assert os.listdir(os.path.join(ckpt, "commits")) == []
    del q  # crash

    q2 = StreamingQuery(
        model, src, CsvDirSink(out, columns=["prediction"]), ckpt,
        max_batch_offsets=1,
    )
    assert q2.process_available() == 2  # batch 0 replayed + batch 1
    assert sorted(os.listdir(out)) == [
        "batch_000000.csv", "batch_000001.csv"
    ]
    with open(os.path.join(out, "batch_000000.csv")) as f:
        assert sum(1 for _ in f) - 1 == 40  # replayed rows, not doubled
    with open(os.path.join(ckpt, "commits", "0.json")) as f:
        assert json.load(f) == {"batch_id": 0, "start": 0, "end": 1}


def test_append_wal_resume_and_replay(model, tmp_path):
    """wal_mode='append': same exactly-once recovery contract as the
    per-file WAL — committed batches don't reprocess; a crash between
    intent and commit replays exactly the logged range."""
    ckpt = str(tmp_path / "ckpt")
    src = MemorySource([_batch(40, 1)])
    sink1 = MemorySink()
    q1 = StreamingQuery(model, src, sink1, ckpt, wal_mode="append")
    assert q1.process_available() == 1
    q1.stop()

    sink2 = MemorySink()
    q2 = StreamingQuery(model, src, sink2, ckpt, wal_mode="append")
    assert q2.process_available() == 0  # committed data not reprocessed
    src.add(_batch(25, 2))
    assert q2.process_available() == 1
    assert [f.num_rows for f in sink2.frames] == [25]
    q2.stop()

    # crash-after-intent: hand-write an uncommitted intent line
    ckpt2 = str(tmp_path / "ckpt2")
    os.makedirs(ckpt2)
    with open(os.path.join(ckpt2, "offsets.log"), "w") as f:
        f.write(json.dumps({"batch_id": 0, "start": 0, "end": 1}) + "\n")
    src3 = MemorySource([_batch(10, 1), _batch(20, 2)])
    sink3 = MemorySink()
    q3 = StreamingQuery(model, src3, sink3, ckpt2, wal_mode="append",
                        )
    assert q3.process_available() == 2
    assert [f.num_rows for f in sink3.frames] == [10, 20]


def test_append_wal_rejects_files_mode_dir(model, tmp_path):
    ckpt = str(tmp_path / "ckpt")
    src = MemorySource([_batch(10, 1)])
    q = StreamingQuery(model, src, MemorySink(), ckpt)  # files mode
    q.process_available()
    q.stop()
    with pytest.raises(ValueError, match="files"):
        StreamingQuery(model, src, MemorySink(), ckpt, wal_mode="append")


def test_recent_progress_records(model, tmp_path):
    src = MemorySource([_batch(5, i) for i in range(3)])
    sink = MemorySink()
    q = StreamingQuery(model, src, sink, str(tmp_path / "ckpt"),
                       max_batch_offsets=1)
    q.process_available()
    assert [p["batchId"] for p in q.recentProgress] == [0, 1, 2]
    for p in q.recentProgress:
        assert p["numInputRows"] == 5
        assert p["durationMs"] > 0
        assert p["processedRowsPerSecond"] > 0


def test_start_await_termination_lifecycle(model, tmp_path):
    """writeStream.start() analog: background loop drains arriving data;
    stop() joins the thread; lastProgress/isActive surface state."""
    import time as _time

    src = MemorySource([_batch(20, 1)])
    sink = MemorySink()
    q = StreamingQuery(model, src, sink, str(tmp_path / "ckpt"),
                       max_batch_offsets=1)
    q.start(poll_interval=0.02)
    assert q.isActive
    deadline = _time.time() + 30
    while _time.time() < deadline and len(sink.frames) < 1:
        _time.sleep(0.02)
    src.add(_batch(10, 2))  # arrives while running
    while _time.time() < deadline and len(sink.frames) < 2:
        _time.sleep(0.02)
    assert [f.num_rows for f in sink.frames] == [20, 10]
    assert not q.awaitTermination(timeout=0.05)  # still polling
    assert q.lastProgress["numInputRows"] == 10
    q.stop()
    assert not q.isActive
    assert q.awaitTermination(timeout=1.0)
    with pytest.raises(RuntimeError, match="stopped"):
        q.start()


def test_await_termination_reraises_loop_crash(model, tmp_path):
    class BoomSink(MemorySink):
        def add_batch(self, batch_id, frame):
            raise RuntimeError("sink boom")

    src = MemorySource([_batch(10, 1)])
    q = StreamingQuery(model, src, BoomSink(), str(tmp_path / "ckpt"))
    q.start(poll_interval=0.02)
    with pytest.raises(RuntimeError, match="sink boom"):
        q.awaitTermination(timeout=30)


# ---------------------------------------------------------------------------
# r8: shape-bucketed predict — padded+masked batches are bitwise-equal
# to unpadded ones, and the compile ledger stays flat after warmup
# ---------------------------------------------------------------------------


def _family_models(mesh8):
    """One fitted model per family the predictor serves (small fits)."""
    from sntc_tpu.models import (
        LinearSVC,
        LogisticRegression,
        MultilayerPerceptronClassifier,
        NaiveBayes,
        RandomForestClassifier,
    )

    rng = np.random.default_rng(3)
    X = rng.normal(size=(240, 4)).astype(np.float32)
    y3 = (np.abs(X[:, 0]) + X[:, 1] > 0.8).astype(np.float64) + (
        X[:, 2] > 0.5
    ).astype(np.float64)
    train3 = Frame({"features": X, "label": y3})
    ybin = (X[:, 0] > 0).astype(np.float64)
    train2 = Frame({"features": X, "label": ybin})
    # tiny fits: bucket correctness is about transform row-locality, not
    # model quality — keep the tier-1 bill small
    return {
        "lr": LogisticRegression(mesh=mesh8, maxIter=8).fit(train2),
        "mlp": MultilayerPerceptronClassifier(
            mesh=mesh8, layers=[4, 8, 3], maxIter=8, seed=0
        ).fit(train3),
        "rf": RandomForestClassifier(
            mesh=mesh8, numTrees=3, maxDepth=3, seed=0
        ).fit(train3),
        "nb": NaiveBayes(mesh=mesh8, modelType="gaussian").fit(train3),
        "svc": LinearSVC(mesh=mesh8, maxIter=8).fit(train2),
    }


def test_bucketed_predict_bitwise_equal_across_families(mesh8):
    """Satellite: padded+masked predictions == unpadded predictions for
    every model family, and compile_events stays flat after the bucket
    shapes are warm (varying batch sizes, same buckets)."""
    sizes_warm = (50, 100)  # buckets 64 and 128
    sizes_after = (49, 60, 63, 90, 127, 100)  # same two buckets
    for name, m in _family_models(mesh8).items():
        bp = BatchPredictor(m, bucket_rows=16)
        for n in sizes_warm:
            bp.predict_frame(_batch(n, n))
        warm_events = bp.compile_events
        assert warm_events == 2, (name, bp.compile_events)
        for n in sizes_after:
            f = _batch(n, n)
            out = bp.predict_frame(f)
            ref = m.transform(f)
            assert out.num_rows == n, name
            assert out.columns == ref.columns, name
            np.testing.assert_array_equal(
                out["prediction"], ref["prediction"], err_msg=name
            )
            if "probability" in ref:  # LinearSVC emits margins only
                np.testing.assert_allclose(
                    out["probability"], ref["probability"], rtol=1e-6,
                    err_msg=name,
                )
        assert bp.compile_events == warm_events, name  # zero recompiles
        assert bp.bucket_hits >= len(sizes_after), name
        assert bp.padded_rows_total > 0, name


def test_bucketed_predict_threads_mask_through_row_dropping_stage(mesh8):
    """The row-validity mask survives a row-DROPPING stage: a pipeline
    whose assembler skips invalid rows must yield exactly the surviving
    real rows — tail-slicing would return the wrong rows here."""
    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import VectorAssembler
    from sntc_tpu.models import LogisticRegression

    rng = np.random.default_rng(5)
    cols = {f"c{i}": rng.normal(size=300).astype(np.float32)
            for i in range(4)}
    train = Frame(dict(cols))
    train = train.with_column(
        "label", (train["c0"] > 0).astype(np.float64)
    )
    asm = VectorAssembler(
        inputCols=[f"c{i}" for i in range(4)], outputCol="features",
        handleInvalid="skip",
    )
    lr = LogisticRegression(mesh=mesh8, maxIter=10).fit(
        asm.transform(train)
    )
    pipe = PipelineModel(stages=[asm, lr])

    bad = {f"c{i}": rng.normal(size=70).astype(np.float32)
           for i in range(4)}
    bad["c1"] = bad["c1"].copy()
    bad["c1"][[3, 11, 42]] = np.nan  # 3 real rows get skipped
    f = Frame(bad)
    ref = pipe.transform(f)
    assert ref.num_rows == 67
    out = BatchPredictor(pipe, bucket_rows=64).predict_frame(f)
    assert out.num_rows == 67
    np.testing.assert_array_equal(out["prediction"], ref["prediction"])
    np.testing.assert_array_equal(out["c0"], ref["c0"])


def test_oversized_frame_chunked_async_dispatch(model):
    """predict_frame_async over a frame larger than chunk_rows: all
    chunks dispatch before finalize, one finalize concatenates, results
    match the one-shot transform (bucketed tail chunk included)."""
    f = _batch(1000, 9)
    ref = model.transform(f)
    for bucket in (0, 64):
        bp = BatchPredictor(model, chunk_rows=256, bucket_rows=bucket)
        fin = bp.predict_frame_async(f)
        out = fin()
        assert out.num_rows == 1000
        np.testing.assert_array_equal(out["prediction"], ref["prediction"])
        np.testing.assert_allclose(
            out["probability"], ref["probability"], rtol=1e-6
        )
    # bucketed: 3 full 256-row chunks share one shape, the 232-row tail
    # pads into the same 256 bucket — ONE compile event total
    assert bp.compile_events == 1


# ---------------------------------------------------------------------------
# r8: pipelined engine — prefetching source + overlapped sink delivery
# ---------------------------------------------------------------------------


def _write_stream(tmp_path, n_files=6, rows=30):
    from sntc_tpu.data import write_day_csvs

    in_dir = str(tmp_path / "in")
    write_day_csvs(in_dir, n_rows_per_day=rows, n_days=n_files, seed=4)
    return in_dir


def test_single_listing_serves_latest_offset_and_get_batch(
    tmp_path, monkeypatch
):
    """Satellite: one glob+sort per poll tick — latest_offset() caches
    the listing and the tick's get_batch() reuses it."""
    import sntc_tpu.serve.streaming as S

    in_dir = _write_stream(tmp_path, n_files=3)
    src = FileStreamSource(in_dir)
    calls = {"n": 0}
    real_glob = S.glob.glob

    def counting(*a, **k):
        calls["n"] += 1
        return real_glob(*a, **k)

    monkeypatch.setattr(S.glob, "glob", counting)
    off = src.latest_offset()
    assert off == 3 and calls["n"] == 1
    f = src.get_batch(0, off)
    assert f.num_rows == 90
    assert calls["n"] == 1  # reused the tick's listing
    # a range past the cached listing re-scans
    with pytest.raises(ValueError):
        src.get_batch(3, 5)
    assert calls["n"] == 2


def test_prefetch_stages_next_batch(tmp_path):
    """prefetch(start, end) stages a background read; get_batch with
    that exact range consumes it, other ranges fall through."""
    in_dir = _write_stream(tmp_path, n_files=4)
    src = FileStreamSource(in_dir, prefetch_batches=1)
    assert src.latest_offset() == 4
    assert src.prefetch(0, 1)
    assert not src.prefetch(0, 1)  # already staged
    assert not src.prefetch(0, 2)  # queue full (bound = 1)
    f = src.get_batch(0, 1)  # consumes the staged read
    assert f.num_rows == 30
    assert src.prefetch(1, 2)  # slot free again
    # a shed that skipped past offset 2 evicts the now-stale (1, 2)
    assert src.prefetch(2, 4)
    assert (1, 2) not in src._staged
    f2 = src.get_batch(2, 4)
    assert f2.num_rows == 60
    stats = src.prefetch_stats()
    assert stats["hits"] == 2 and stats["hwm"] == 1
    # staged contents identical to a cold synchronous read
    ref = FileStreamSource(in_dir).get_batch(0, 1)
    np.testing.assert_array_equal(ref["Flow Duration"], f["Flow Duration"])
    src.close()


@pytest.mark.parametrize("wal_mode", ["files", "append"])
def test_overlap_sink_query_matches_serial(model, tmp_path, wal_mode):
    """The full pipelined engine (overlap + prefetch + buckets) commits
    the same batches with the same contents as the serial engine."""
    batches = [_batch(40 + 11 * i, i) for i in range(6)]
    outs = {}
    for mode in ("serial", "pipe"):
        src = MemorySource(batches)
        sink = MemorySink()
        q = StreamingQuery(
            model, src, sink, str(tmp_path / f"ckpt_{wal_mode}_{mode}"),
            max_batch_offsets=1, wal_mode=wal_mode,
            pipeline_depth=1 if mode == "serial" else 3,
            overlap_sink=mode == "pipe",
            shape_buckets=0 if mode == "serial" else 32,
        )
        assert q.process_available() == 6
        assert q.in_flight_count() == 0
        assert q._delivery is None
        q.stop()
        outs[mode] = sink
    for (i1, f1), (i2, f2) in zip(
        outs["serial"].batches, outs["pipe"].batches
    ):
        assert i1 == i2
        assert f1.num_rows == f2.num_rows
        np.testing.assert_array_equal(f1["prediction"], f2["prediction"])


def test_overlap_sink_file_source_end_to_end(model, tmp_path):
    """Pipelined engine over a real prefetching file source and CSV
    sink: exactly-once output files, prefetch hits recorded."""
    from sntc_tpu.data import CICIDS2017_FEATURES  # noqa: F401 — schema sanity

    in_dir = _write_stream(tmp_path, n_files=5)
    src = FileStreamSource(in_dir, prefetch_batches=2)

    class Echo(MemorySink):
        pass

    sink = Echo()

    from sntc_tpu.core.base import Transformer

    class Identity(Transformer):
        def transform(self, frame):
            return frame

    q = StreamingQuery(
        Identity(), src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, pipeline_depth=3, overlap_sink=True,
        shape_buckets=16,
    )
    assert q.process_available() == 5
    q.stop()
    assert [i for i, _ in sink.batches] == [0, 1, 2, 3, 4]
    assert all(f.num_rows == 30 for f in sink.frames)
    stats = q.pipeline_stats()
    assert stats["prefetch"]["hits"] >= 1
    assert stats["delivered_batches"] == 5
    src.close()


def test_overlap_sink_failure_defers_not_skips(model, tmp_path):
    """Serial-contract parity under overlap: a transient sink failure
    leaves the batch queued (ids never shift); unarmed quarantine
    re-raises from process_available."""
    batches = [_batch(30, s) for s in range(4)]
    src = MemorySource(batches)

    class FlakySink(MemorySink):
        def __init__(self):
            super().__init__()
            self.fail_on = {1}

        def add_batch(self, batch_id, frame):
            if batch_id in self.fail_on:
                self.fail_on.discard(batch_id)
                raise IOError("transient sink outage")
            super().add_batch(batch_id, frame)

    sink = FlakySink()
    q = StreamingQuery(model, src, sink, str(tmp_path / "ckpt_flaky"),
                       max_batch_offsets=1, pipeline_depth=2,
                       overlap_sink=True)
    with pytest.raises(IOError):
        q.process_available()
    assert q.process_available() == 3
    assert [i for i, _ in sink.batches] == [0, 1, 2, 3]
    assert q.last_committed() == 3
    q.stop()


def test_overlap_crash_between_sink_and_commit_replays(model, tmp_path):
    """stream.commit crash in overlap mode: the delivery reached the
    sink, the commit never landed; a restarted (pipelined) query
    replays the batch and the sink dedupes — exactly-once preserved."""
    import sntc_tpu.resilience as R

    ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")
    src = MemorySource([_batch(40, 1), _batch(25, 2)])
    q = StreamingQuery(
        model, src, CsvDirSink(out, columns=["prediction"]), ckpt,
        max_batch_offsets=1, pipeline_depth=2, overlap_sink=True,
    )
    R.arm("stream.commit", times=1)
    try:
        with pytest.raises(R.InjectedFault):
            q.process_available()
    finally:
        R.clear()
    assert os.path.exists(os.path.join(out, "batch_000000.csv"))
    assert os.listdir(os.path.join(ckpt, "commits")) == []
    q.stop()
    del q  # crash

    q2 = StreamingQuery(
        model, src, CsvDirSink(out, columns=["prediction"]), ckpt,
        max_batch_offsets=1, pipeline_depth=2, overlap_sink=True,
    )
    assert q2.process_available() == 2
    q2.stop()
    with open(os.path.join(out, "batch_000000.csv")) as f:
        assert sum(1 for _ in f) - 1 == 40  # replayed, not doubled
    with open(os.path.join(ckpt, "commits", "0.json")) as f:
        assert json.load(f) == {"batch_id": 0, "start": 0, "end": 1}


def test_overlap_drain_settles_in_air_delivery(model, tmp_path):
    """drain() in overlap mode joins the delivery thread's in-air batch
    and commits everything in flight — the preemption contract."""
    import time as _time

    class SlowSink(MemorySink):
        def add_batch(self, batch_id, frame):
            _time.sleep(0.05)
            super().add_batch(batch_id, frame)

    batches = [_batch(20, s) for s in range(4)]
    sink = SlowSink()
    q = StreamingQuery(
        model, MemorySource(batches), sink,
        str(tmp_path / "ckpt"), max_batch_offsets=1, pipeline_depth=3,
        overlap_sink=True,
    )
    # fill the pipeline and put one delivery in the air, then drain
    q._run_one_batch()
    assert q.in_flight_count() >= 1
    q.drain()
    assert q.in_flight_count() == 0
    assert q._delivery is None
    # every dispatched batch was sunk exactly once, in order, and the
    # commit log agrees with the sink
    ids = [i for i, _ in sink.batches]
    assert ids == list(range(len(ids))) and len(ids) >= 1
    assert q.last_committed() == ids[-1]
    q.stop()


def test_perf_flags_drift_check():
    """CLI flags ⇔ engine kwargs ⇔ docs must agree (tier-1 wiring of
    scripts/check_perf_flags.py)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_perf_flags",
        os.path.join(repo, "scripts", "check_perf_flags.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []


def test_hot_swap_never_lands_mid_delivery(tmp_path):
    """r11 hot-swap safety under overlap_sink: ``swap_model`` settles
    the in-air delivery FIRST (the head batch commits under the old
    generation on this thread) and only then flips the predictor — a
    swap can never land while a delivery is in the air."""
    import threading

    import numpy as np

    from sntc_tpu.models.logistic_regression import (
        LogisticRegressionModel,
    )

    def const_model(positive):
        # zero coefficients + a pinned intercept: predicts ONE class
        # everywhere, so the sink rows prove which model served them
        return LogisticRegressionModel(
            coefficient_matrix=np.zeros((2, 4), np.float32),
            intercepts=np.asarray(
                [0.0, 50.0 if positive else -50.0], np.float32
            ),
            is_binomial=True,
        )

    incumbent, candidate = const_model(False), const_model(True)
    entered, release = threading.Event(), threading.Event()
    holder = {}
    events = []

    class GatedSink(MemorySink):
        def add_batch(self, batch_id, frame):
            entered.set()
            assert release.wait(timeout=10), "swap should release us"
            # the engine predictor must still wrap the OLD model while
            # this delivery is in the air — the swap waits for us
            events.append(
                ("sunk", batch_id,
                 holder["q"].predictor.model is incumbent)
            )
            super().add_batch(batch_id, frame)

    sink = GatedSink()
    src = MemorySource([_batch(20, s) for s in range(2)])
    q = StreamingQuery(
        incumbent, src, sink, str(tmp_path / "ckpt"),
        max_batch_offsets=1, pipeline_depth=2, overlap_sink=True,
    )
    holder["q"] = q
    q._run_one_batch()  # batch 0's delivery is now in the air
    assert entered.wait(timeout=10)
    assert q._delivery is not None
    # release the gated sink shortly AFTER swap_model starts waiting on
    # the in-air head; the swap must join it, not overtake it
    threading.Timer(0.2, release.set).start()
    old = q.swap_model(candidate)
    events.append(("swapped",))
    assert old is incumbent
    assert q._delivery is None  # the head settled before the flip
    assert q.models_swapped == 1
    # ordering evidence: the in-air delivery completed (under the old
    # model) strictly before the swap was applied
    assert events[0] == ("sunk", 0, True)
    assert events[-1] == ("swapped",)
    # drain the rest: batches dispatched after the swap serve class 1
    src.add(_batch(15, 9))
    q.process_available()
    q.stop()
    first = np.asarray(sink.frames[0]["prediction"])
    last = np.asarray(sink.frames[-1]["prediction"])
    np.testing.assert_array_equal(first, np.zeros_like(first))
    np.testing.assert_array_equal(last, np.ones_like(last))
    assert q.last_committed() == len(sink.frames) - 1


# ---------------------------------------------------------------------------
# concurrent engines on ONE shared BatchPredictor (r12): the serve
# daemon hands tenants sharing a pipeline one predictor, so two engines
# dispatching through it from separate threads must (a) produce the
# exact sink output a serial run produces and (b) never widen the
# shared compile ledger past the union of their bucket shapes — the
# thread-safety contract the daemon's shared program cache depends on
# ---------------------------------------------------------------------------


def test_concurrent_queries_shared_predictor_bitwise_vs_serial(
    mesh8, tmp_path, monkeypatch
):
    import threading

    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.feature import MinMaxScaler, VectorAssembler
    from sntc_tpu.fuse import compile_pipeline, fused_segments

    # fused serving always runs on device; pin the staged host-serve
    # crossover off so serial and concurrent hit one numerical path
    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")

    def scalar_frame(n, seed):
        rng = np.random.default_rng(seed)
        cols = {
            f"c{i}": rng.normal(3.0, 2.0, size=n).astype(np.float32)
            for i in range(4)
        }
        return Frame(cols)

    train = scalar_frame(400, 99)
    train = Frame(
        {
            **{c: train[c] for c in train.columns},
            "label": (np.asarray(train["c0"]) > 3.0).astype(np.float64),
        }
    )
    pm = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(4)],
                        outputCol="features"),
        MinMaxScaler(inputCol="features", outputCol="scaled"),
        LogisticRegression(mesh=mesh8, featuresCol="scaled", maxIter=20),
    ]).fit(train)
    fused = compile_pipeline(pm)
    assert fused_segments(fused), "pipeline should fuse"

    # per-tenant streams with DIFFERENT row counts that land in two
    # buckets (5,7 -> 8; 11,13 -> 16): the shared ledger must hold
    # exactly those two shapes however the threads interleave
    frames = {
        "a": [scalar_frame(5, 10 + i) for i in range(4)]
        + [scalar_frame(11, 20 + i) for i in range(4)],
        "b": [scalar_frame(7, 30 + i) for i in range(4)]
        + [scalar_frame(13, 40 + i) for i in range(4)],
    }

    def run(pred, tid, ckpt_tag):
        sink = MemorySink()
        q = StreamingQuery(
            pred, MemorySource(frames[tid]), sink,
            str(tmp_path / f"{ckpt_tag}-{tid}"), max_batch_offsets=1,
        )
        q.process_available()
        q.stop()
        return sink

    # serial reference: each tenant alone on its OWN predictor
    serial = {
        tid: run(BatchPredictor(fused, bucket_rows=8), tid, "serial")
        for tid in ("a", "b")
    }

    shared = BatchPredictor(fused, bucket_rows=8)
    results, errs = {}, []

    def worker(tid):
        try:
            results[tid] = run(shared, tid, "conc")
        except Exception as e:  # pragma: no cover - failure evidence
            errs.append(e)

    threads = [
        threading.Thread(target=worker, args=(tid,)) for tid in ("a", "b")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs

    # (a) bitwise: every tenant's concurrent sink == its serial sink
    for tid in ("a", "b"):
        assert len(results[tid].frames) == len(serial[tid].frames)
        for got, want in zip(results[tid].frames, serial[tid].frames):
            assert got.num_rows == want.num_rows
            for col in ("rawPrediction", "probability", "prediction"):
                if col in want:
                    np.testing.assert_array_equal(
                        np.asarray(got[col]), np.asarray(want[col]),
                        err_msg=f"{tid}:{col}",
                    )

    # (b) flat shared ledger: exactly the two bucket shapes, however
    # the threads raced; every later dispatch was a bucket hit
    assert shared.compile_events == 2
    assert shared.bucket_hits == sum(len(v) for v in frames.values()) - 2
