"""Whole-pipeline fusion compiler (sntc_tpu.fuse): bitwise parity of the
fused path against the staged serving path across classifier heads, with
and without shape buckets; fallback partitioning around non-fusible
stages; the transfer-ledger single-upload/single-download contract; the
CrossValidator pipeline-grid hoist; and the registry⇔docs drift check."""

import importlib.util
import os

import numpy as np
import pytest

from sntc_tpu.core.base import Pipeline, PipelineModel
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import (
    DCT,
    MinMaxScaler,
    PCA,
    PolynomialExpansion,
    StandardScaler,
    VectorAssembler,
)
from sntc_tpu.fuse import (
    FusedSegment,
    compile_pipeline,
    fused_segments,
    fusion_stats,
)
from sntc_tpu.models import (
    LinearSVC,
    LogisticRegression,
    MultilayerPerceptronClassifier,
    NaiveBayes,
    RandomForestClassifier,
)
from sntc_tpu.serve.transform import BatchPredictor
from sntc_tpu.utils.profiling import transfer_ledger


@pytest.fixture(autouse=True)
def _device_staged_path(monkeypatch):
    """Parity target is the staged DEVICE path: the host-serve crossover
    (SNTC_SERVE_HOST_ROWS) would route small staged batches through the
    float64 numpy predict, which is a different numerical path by
    design — fused serving always runs on device."""
    monkeypatch.setenv("SNTC_SERVE_HOST_ROWS", "0")


D = 6


def _scalar_frame(n=300, seed=0, nan_rows=0):
    """Raw scalar columns c0..c5 + label (the CSV-shaped serving input);
    ``nan_rows`` poisons the first rows of c1 for handleInvalid tests."""
    rng = np.random.default_rng(seed)
    X = np.abs(rng.normal(3.0, 2.0, size=(n, D))).astype(np.float32)
    X[:, D - 1] = 5.0  # constant feature exercises span/std == 0 paths
    cols = {f"c{i}": X[:, i].copy() for i in range(D)}
    if nan_rows:
        c1 = cols["c1"]
        c1[:nan_rows] = np.nan
    cols["label"] = (X[:, 0] > 3.0).astype(np.float64)
    return Frame(cols)


def _head_pipeline(head, handle_invalid="error"):
    return Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(D)],
                        outputCol="features",
                        handleInvalid=handle_invalid),
        MinMaxScaler(inputCol="features", outputCol="scaled"),
        head,
    ])


def _heads(mesh):
    return {
        "lr": LogisticRegression(mesh=mesh, featuresCol="scaled",
                                 maxIter=30),
        "mlp": MultilayerPerceptronClassifier(
            mesh=mesh, featuresCol="scaled", layers=[D, 8, 2], maxIter=30
        ),
        "nb": NaiveBayes(mesh=mesh, featuresCol="scaled",
                         modelType="multinomial"),
        "svc": LinearSVC(mesh=mesh, featuresCol="scaled", maxIter=30),
        "rf": RandomForestClassifier(mesh=mesh, featuresCol="scaled",
                                     numTrees=5, maxDepth=4, seed=0),
    }


def _assert_bitwise(a: Frame, b: Frame):
    cols = [c for c in ("rawPrediction", "probability", "prediction")
            if c in a and c in b]
    assert cols, "no prediction columns to compare"
    assert a.num_rows == b.num_rows
    for c in cols:
        np.testing.assert_array_equal(
            np.asarray(a[c]), np.asarray(b[c]), err_msg=c
        )


@pytest.mark.parametrize("head_name", ["lr", "mlp", "nb", "svc", "rf"])
def test_fused_bitwise_parity(mesh8, head_name):
    f = _scalar_frame()
    pm = _head_pipeline(_heads(mesh8)[head_name]).fit(f)
    serve = f.drop("label")
    fused = compile_pipeline(pm)
    assert fused_segments(fused), "pipeline produced no fused segment"
    staged_out = BatchPredictor(pm).predict_frame(serve)
    fused_out = BatchPredictor(fused).predict_frame(serve)
    _assert_bitwise(staged_out, fused_out)


@pytest.mark.parametrize("head_name", ["lr", "mlp", "nb", "svc", "rf"])
def test_fused_bitwise_parity_shape_buckets(mesh8, head_name):
    """--shape-buckets analog: padded rows + the row-validity mask flow
    through a row-dropping handleInvalid='skip' assembler identically on
    the fused and staged paths (the skip stage is never fused — it runs
    eagerly ahead of the segment and filters the mask in lockstep)."""
    f = _scalar_frame(n=300, nan_rows=7)
    pm = _head_pipeline(
        _heads(mesh8)[head_name], handle_invalid="skip"
    ).fit(f)
    serve = f.drop("label")
    fused = compile_pipeline(pm)
    staged_out = BatchPredictor(pm, bucket_rows=64).predict_frame(serve)
    fused_out = BatchPredictor(fused, bucket_rows=64).predict_frame(serve)
    assert staged_out.num_rows == 300 - 7  # NaN rows dropped, pad stripped
    _assert_bitwise(staged_out, fused_out)


def test_fallback_partition_two_segments(mesh8):
    """A non-fusible stage mid-pipeline splits the plan into two fused
    segments bridged by the eager stage — results identical to staged."""
    f = _scalar_frame(n=200, seed=3)
    pm = Pipeline(stages=[
        VectorAssembler(inputCols=[f"c{i}" for i in range(D)],
                        outputCol="features", handleInvalid="error"),
        MinMaxScaler(inputCol="features", outputCol="s1"),
        # float64 host math: non-fusible without jax_enable_x64
        PolynomialExpansion(inputCol="s1", outputCol="poly", degree=2),
        MinMaxScaler(inputCol="poly", outputCol="s2"),
        LogisticRegression(mesh=mesh8, featuresCol="s2", maxIter=20),
    ]).fit(f)
    fused = compile_pipeline(pm)
    segs = fused_segments(fused)
    assert len(segs) == 2  # [mm1] and [mm2 + lr head]
    kinds = [type(s).__name__ for s in fused.getStages()]
    assert kinds == [
        "VectorAssembler", "FusedSegment", "PolynomialExpansion",
        "FusedSegment",
    ]
    assert segs[-1]._head is not None or segs[0]._head is not None
    serve = f.drop("label")
    _assert_bitwise(
        BatchPredictor(pm).predict_frame(serve),
        BatchPredictor(fused).predict_frame(serve),
    )


def _vector_frame(n=256, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(0.0, 1.0, size=(n, D)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    return Frame({"features": X, "label": y})


def _deep_fused(mesh, frame):
    """A fully-fusible 3-feature-stage + head pipeline compiled into ONE
    segment (scaler can't fold through DCT, so it fuses instead)."""
    pm = Pipeline(stages=[
        StandardScaler(mesh=mesh, inputCol="features", outputCol="sc",
                       withMean=True),
        DCT(inputCol="sc", outputCol="dct"),
        PCA(mesh=mesh, inputCol="dct", outputCol="pca", k=4),
        LogisticRegression(mesh=mesh, featuresCol="pca", maxIter=20),
    ]).fit(frame)
    fused = compile_pipeline(pm)
    segs = fused_segments(fused)
    assert len(fused.getStages()) == 1 and len(segs) == 1
    assert len(segs[0].fused_stages) == 4  # 3 feature stages + head
    return pm, fused, segs[0]


def test_single_upload_single_download_per_batch(mesh8):
    f = _vector_frame()
    pm, fused, seg = _deep_fused(mesh8, f)
    serve = f.drop("label")
    _assert_bitwise(
        BatchPredictor(pm).predict_frame(serve),
        BatchPredictor(fused).predict_frame(serve),
    )
    ledger = transfer_ledger()
    before = ledger.snapshot()
    seg_before = (seg.invocations, seg.uploads, seg.downloads)
    out = fused.transform(serve)
    after = ledger.snapshot()
    assert after["dispatches"] - before["dispatches"] == 1
    assert after["uploads"] - before["uploads"] == 1
    assert after["downloads"] - before["downloads"] == 1
    # per-segment counters carry the same evidence, isolated per model
    assert (seg.invocations, seg.uploads, seg.downloads) == tuple(
        v + 1 for v in seg_before
    )
    # intermediates (sc/dct/pca) live only on device — never materialized
    for col in ("sc", "dct", "pca"):
        assert col not in out
    stats = fusion_stats(fused)
    assert stats["segments"] == 1 and stats["fallbacks"] == 0
    assert stats["uploads"] == seg.uploads
    assert stats["downloads"] == seg.downloads


def test_compile_ledger_flat_across_buckets(mesh8):
    """Shape-bucketed serving keys the fused program per bucket: ragged
    micro-batches that pad to one bucket share ONE compile."""
    f = _vector_frame(n=64)
    _pm, fused, seg = _deep_fused(mesh8, f)
    predictor = BatchPredictor(fused, bucket_rows=64)
    for n in (50, 57, 64, 41):
        predictor.predict_frame(f.slice(0, n).drop("label"))
    assert seg.compile_events == 1
    assert predictor.compile_events == 1


def test_shared_column_policy_conflict_splits_segment():
    """Two fused stages reading ONE external column under different
    upload policies (a casting scaler vs a dtype-preserving
    ElementwiseProduct) must not share a segment: the first reader's
    f32 cast would bypass the second's dtype guard.  The planner splits
    them, the guard falls back eagerly on float64 input, and the fused
    output stays bitwise-equal to the staged path."""
    from sntc_tpu.feature import ElementwiseProduct
    from sntc_tpu.parallel.context import get_default_mesh

    rng = np.random.default_rng(2)
    X64 = rng.normal(3.0, 2.0, size=(100, D))  # float64, as load_csv yields
    f = Frame({"x": X64})
    pm = Pipeline(stages=[
        MinMaxScaler(inputCol="x", outputCol="a"),
        ElementwiseProduct(inputCol="x", outputCol="b",
                           scalingVec=[2.0] * D),
    ]).fit(f)
    fused = compile_pipeline(pm)
    segs = fused_segments(fused)
    assert len(segs) == 2  # conflict split, never one shared upload
    staged_out = pm.transform(f)
    fused_out = fused.transform(f)
    for col in ("a", "b"):
        np.testing.assert_array_equal(
            np.asarray(staged_out[col]), np.asarray(fused_out[col]),
            err_msg=col,
        )
    assert fused_out["b"].dtype == staged_out["b"].dtype
    # the dtype-preserving segment fell back eagerly on the f64 column
    assert sum(s.fallbacks for s in segs) == 1


def test_keep_retains_intermediate_and_eager_fallback(mesh8):
    f = _vector_frame(n=128)
    pm = Pipeline(stages=[
        StandardScaler(mesh=mesh8, inputCol="features", outputCol="sc"),
        DCT(inputCol="sc", outputCol="dct"),
    ]).fit(f)
    fused = compile_pipeline(pm, keep=("sc",))
    out = fused.transform(f)
    staged = pm.transform(f)
    np.testing.assert_array_equal(out["sc"], np.asarray(staged["sc"]))
    np.testing.assert_array_equal(out["dct"], np.asarray(staged["dct"]))
    # empty frames take the eager fallback and stay correct
    seg = fused_segments(fused)[0]
    before = seg.fallbacks
    empty = fused.transform(f.slice(0, 0))
    assert empty.num_rows == 0 and "dct" in empty
    assert seg.fallbacks == before + 1


def test_streaming_pipeline_stats_fusion(mesh8):
    """The engine journals fusion evidence: fused segments dispatch per
    micro-batch (bucket-padded batches included — the validity-mask
    column passes through the segment untouched) and pipeline_stats()
    exposes the compile + transfer ledgers bench config 6 reads."""
    import tempfile

    from sntc_tpu.serve import MemorySink, MemorySource, StreamingQuery

    f = _vector_frame(n=64)
    _pm, fused, seg = _deep_fused(mesh8, f)
    serve = f.drop("label")
    src = MemorySource([serve.slice(0, 32), serve.slice(32, 50)])
    sink = MemorySink()
    with tempfile.TemporaryDirectory() as tmp:
        q = StreamingQuery(
            fused, src, sink, tmp, max_batch_offsets=1, shape_buckets=32
        )
        assert q.process_available() == 2
        stats = q.pipeline_stats()
    fusion = stats["fusion"]
    assert fusion["segments"] == 1
    assert fusion["invocations"] >= 2
    assert fusion["fallbacks"] == 0
    assert seg.compile_events == 1  # both batches pad to the 32 bucket
    assert [fr.num_rows for fr in sink.frames] == [32, 18]


def test_cv_pipeline_grid_reuses_prefix(mesh8, monkeypatch):
    """CrossValidator over a Pipeline with a head-only grid: the feature
    prefix fits once per fold and both splits flow through the fused
    prefix once; metrics match the naive whole-pipeline-per-cell sweep."""
    from sntc_tpu.evaluation import MulticlassClassificationEvaluator
    from sntc_tpu.tuning import CrossValidator, ParamGridBuilder

    monkeypatch.setenv("SNTC_TUNING_BATCH", "0")  # sequential head fits
    f = _vector_frame(n=400, seed=5)
    grid = ParamGridBuilder().addGrid("regParam", [1e-4, 10.0]).build()

    def make_pipe(reg=0.0):
        return Pipeline(stages=[
            MinMaxScaler(inputCol="features", outputCol="scaled"),
            LogisticRegression(mesh=mesh8, featuresCol="scaled",
                               maxIter=20, regParam=reg),
        ])

    cv = CrossValidator(
        estimator=make_pipe(),
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(
            metricName="accuracy", mesh=mesh8
        ),
        numFolds=2,
        seed=7,
    )
    model = cv.fit(f)
    assert isinstance(model.bestModel, PipelineModel)
    assert model.bestIndex == 0  # regParam=10 cripples the model

    # the naive sweep: whole pipeline fit per (fold, grid point)
    rng = np.random.default_rng(7)
    fold_of = rng.integers(0, 2, size=f.num_rows)
    expected = np.zeros((len(grid), 2))
    ev = MulticlassClassificationEvaluator(metricName="accuracy",
                                           mesh=mesh8)
    for fold in range(2):
        train = f.filter(fold_of != fold)
        valid = f.filter(fold_of == fold)
        for gi, params in enumerate(grid):
            m = make_pipe(params["regParam"]).fit(train)
            expected[gi, fold] = ev.evaluate(m.transform(valid))
    np.testing.assert_allclose(
        model.avgMetrics, expected.mean(axis=1), rtol=1e-6
    )


def test_tvs_pipeline_grid(mesh8):
    from sntc_tpu.evaluation import MulticlassClassificationEvaluator
    from sntc_tpu.tuning import ParamGridBuilder, TrainValidationSplit

    f = _vector_frame(n=400, seed=6)
    tvs = TrainValidationSplit(
        estimator=Pipeline(stages=[
            MinMaxScaler(inputCol="features", outputCol="scaled"),
            LogisticRegression(mesh=mesh8, featuresCol="scaled",
                               maxIter=20),
        ]),
        estimatorParamMaps=ParamGridBuilder()
        .addGrid("regParam", [1e-4, 10.0]).build(),
        evaluator=MulticlassClassificationEvaluator(
            metricName="accuracy", mesh=mesh8
        ),
    )
    model = tvs.fit(f)
    assert isinstance(model.bestModel, PipelineModel)
    assert model.bestIndex == 0
    assert len(model.validationMetrics) == 2


# registry ⇔ docs drift check (the tier-1 wiring of
# scripts/check_fusible_stages.py, mirroring check_perf_flags)


def _load_script(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_feature_transformer_registered_or_documented():
    checker = _load_script("check_fusible_stages")
    problems = checker.check()
    assert not problems, "\n".join(problems)
