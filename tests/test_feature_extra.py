"""FeatureHasher / VectorIndexer / VectorSizeHint / DCT / RFormula:
scipy + hand-computed oracles."""

import numpy as np
import pytest

from sntc_tpu.core.frame import Frame, object_column
from sntc_tpu.feature import (
    DCT,
    FeatureHasher,
    RFormula,
    VectorIndexer,
    VectorSizeHint,
)
from sntc_tpu.feature.text import _spark_bucket
from sntc_tpu.mlio.save_load import load_model, save_model


def test_feature_hasher_buckets():
    f = Frame({
        "pkts": np.array([3.0, 5.0]),
        "proto": object_column(["tcp", "udp"]),
        "port": np.array([80, 80]),
    })
    h = FeatureHasher(
        inputCols=("pkts", "proto", "port"), numFeatures=64,
        categoricalCols=("port",),
    )
    out = h.transform(f)["features"]
    assert out.shape == (2, 64)
    # numeric: value lands at hash(colName)
    assert out[0, _spark_bucket("pkts", 64)] == 3.0
    assert out[1, _spark_bucket("pkts", 64)] == 5.0
    # categorical: 1.0 at hash("col=value")
    assert out[0, _spark_bucket("proto=tcp", 64)] == 1.0
    assert out[1, _spark_bucket("proto=udp", 64)] == 1.0
    # forced-categorical numeric column
    assert out[0, _spark_bucket("port=80", 64)] == 1.0
    with pytest.raises(ValueError, match="inputCols"):
        FeatureHasher(numFeatures=8).transform(f)
    # boolean columns hash the Scala lowercase rendering
    fb = Frame({"flag": np.array([True, False])})
    ob = FeatureHasher(inputCols=("flag",), numFeatures=64).transform(fb)
    assert ob["features"][0, _spark_bucket("flag=true", 64)] == 1.0
    assert ob["features"][1, _spark_bucket("flag=false", 64)] == 1.0


def test_rformula_removal_validation():
    f = Frame({"y": np.array([1.0]), "a": np.array([2.0])})
    with pytest.raises(ValueError, match="fitIntercept"):
        RFormula(formula="y ~ . - 1").fit(f)
    with pytest.raises(ValueError, match="not among"):
        RFormula(formula="y ~ a - nope").fit(f)


def test_vector_indexer_semantics():
    X = np.array([
        [0.0, -1.0, 2.5],
        [1.0, 0.0, 3.5],
        [0.0, 1.0, 4.5],
        [1.0, 0.0, 5.5],
    ], np.float32)
    f = Frame({"features": X})
    m = VectorIndexer(maxCategories=3).fit(f)
    # features 0 (2 values) and 1 (3 values) are categorical; 2 is not
    assert set(m.categoryMaps) == {0, 1}
    out = m.transform(f)["indexed"]
    np.testing.assert_array_equal(out[:, 0], [0, 1, 0, 1])
    # Spark pins 0.0 to index 0 (scaladoc: {-1.0, 0.0} -> {0.0: 0,
    # -1.0: 1}); remaining values ascend: -1.0 -> 1, 1.0 -> 2
    np.testing.assert_array_equal(out[:, 1], [1, 0, 2, 0])
    np.testing.assert_allclose(out[:, 2], X[:, 2])  # passthrough
    # unseen value handling
    f_bad = Frame({"features": np.array([[2.0, 0.0, 9.9]], np.float32)})
    with pytest.raises(ValueError, match="unseen"):
        m.transform(f_bad)
    m_keep = m.copy({"handleInvalid": "keep"})
    assert m_keep.transform(f_bad)["indexed"][0, 0] == 2.0  # extra bucket
    m_skip = m.copy({"handleInvalid": "skip"})
    assert m_skip.transform(f_bad).num_rows == 0


def test_vector_indexer_save_load(tmp_path):
    X = np.array([[0.0, 7.5], [1.0, 8.5], [0.0, 9.5]], np.float32)
    f = Frame({"features": X})
    m = VectorIndexer(maxCategories=2).fit(f)
    save_model(m, str(tmp_path / "vi"))
    m2 = load_model(str(tmp_path / "vi"))
    np.testing.assert_array_equal(
        m2.transform(f)["indexed"], m.transform(f)["indexed"]
    )


def test_vector_size_hint():
    f = Frame({"features": np.ones((3, 4), np.float32)})
    assert VectorSizeHint(size=4).transform(f) is f
    with pytest.raises(ValueError, match="width"):
        VectorSizeHint(size=5).transform(f)
    assert VectorSizeHint(
        size=5, handleInvalid="skip"
    ).transform(f).num_rows == 0
    assert VectorSizeHint(
        size=5, handleInvalid="optimistic"
    ).transform(f) is f


def test_dct_matches_scipy():
    from scipy.fft import dct as scipy_dct

    rng = np.random.default_rng(0)
    X = rng.normal(size=(50, 17)).astype(np.float32)
    f = Frame({"features": X})
    out = DCT().transform(f)["dct"]
    ref = scipy_dct(X.astype(np.float64), type=2, norm="ortho", axis=1)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    # inverse round-trips
    back = DCT(inputCol="dct", outputCol="back", inverse=True).transform(
        Frame({"dct": out})
    )["back"]
    np.testing.assert_allclose(back, X, atol=1e-5)


def test_rformula_numeric_and_dot():
    f = Frame({
        "y": np.array([1.0, 2.0, 3.0]),
        "a": np.array([0.5, 1.5, 2.5]),
        "b": np.array([1.0, 0.0, 1.0]),
    })
    m = RFormula(formula="y ~ .").fit(f)
    out = m.transform(f)
    np.testing.assert_allclose(
        out["features"], np.stack([f["a"], f["b"]], axis=1)
    )
    np.testing.assert_allclose(out["label"], f["y"])
    # term removal
    m2 = RFormula(formula="y ~ . - b").fit(f)
    assert m2.transform(f)["features"].shape == (3, 1)


def test_rformula_string_dummies_and_interaction():
    f = Frame({
        "y": object_column(["pos", "neg", "pos", "pos"]),
        "proto": object_column(["tcp", "udp", "tcp", "icmp"]),
        "x": np.array([1.0, 2.0, 3.0, 4.0]),
    })
    m = RFormula(formula="y ~ proto + x + proto:x").fit(f)
    out = m.transform(f)
    X = out["features"]
    # proto levels by frequency desc: tcp(2), icmp(1), udp(1) — ties by
    # value; last level dropped -> 2 dummy cols; + x + 2 interaction cols
    assert X.shape == (4, 5)
    # string label indexed: pos (freq 3) -> 0, neg -> 1
    np.testing.assert_array_equal(out["label"], [0, 1, 0, 0])
    # interaction = dummies * x, row-wise
    np.testing.assert_allclose(X[:, 3:], X[:, :2] * f["x"][:, None])
    with pytest.raises(ValueError, match="unknown column"):
        RFormula(formula="y ~ nope").fit(f)
    with pytest.raises(ValueError, match="~"):
        RFormula(formula="y + x").fit(f)


def test_sql_transformer():
    from sntc_tpu.feature import SQLTransformer

    f = Frame({
        "v1": np.array([1.0, 3.0, 5.0]),
        "v2": np.array([10.0, 20.0, 30.0]),
        "vec": np.ones((3, 2), np.float32),
    })
    # the Spark doc example shape: SELECT *, expr AS name ... WHERE
    out = SQLTransformer(
        statement="SELECT *, (v1 + v2) AS v3, (v1 * v2) AS v4 "
                  "FROM __THIS__ WHERE v1 > 2"
    ).transform(f)
    assert out.num_rows == 2
    np.testing.assert_allclose(out["v3"], [23.0, 35.0])
    np.testing.assert_allclose(out["v4"], [60.0, 150.0])
    assert out["vec"].shape == (2, 2)  # '*' carries vector columns too
    # projection without WHERE
    out2 = SQLTransformer(
        statement="SELECT v2, (v1 > 2) AS big FROM __THIS__"
    ).transform(f)
    assert out2.columns == ["v2", "big"]
    np.testing.assert_array_equal(out2["big"], [False, True, True])
    # SQL operator spellings: =, <>, AND/OR/NOT, plus literal columns
    out3 = SQLTransformer(
        statement="SELECT v1, 1 AS one FROM __THIS__ "
                  "WHERE v1 = 3 OR (NOT v2 <> 30 AND v1 > 4)"
    ).transform(f)
    np.testing.assert_allclose(out3["v1"], [3.0, 5.0])
    np.testing.assert_array_equal(out3["one"], [1, 1])
    # backtick quoting for the space-laden flow schema (Spark's quoting)
    fsp = Frame({"Destination Port": np.array([80.0, 0.0]),
                 "x": np.array([1.0, 2.0])})
    osp = SQLTransformer(
        statement="SELECT x, (`Destination Port` + 1) AS dp "
                  "FROM __THIS__ WHERE `Destination Port` > 0"
    ).transform(fsp)
    np.testing.assert_allclose(osp["dp"], [81.0])
    # bare backticked projection (no alias needed, Spark semantics)
    osp2 = SQLTransformer(
        statement="SELECT `Destination Port` FROM __THIS__"
    ).transform(fsp)
    assert osp2.columns == ["Destination Port"]
    # rewriting never touches string literals or backticked names:
    # '=' inside a literal survives; a column named with AND works
    fstr = Frame({
        "name": object_column(["a=b", "c"]),
        "Fwd AND Bwd": np.array([1.0, 2.0]),
    })
    ostr = SQLTransformer(
        statement="SELECT `Fwd AND Bwd` FROM __THIS__ WHERE name = 'a=b'"
    ).transform(fstr)
    assert ostr.num_rows == 1 and ostr["Fwd AND Bwd"][0] == 1.0
    # commas inside literals don't split the select list
    oc = SQLTransformer(
        statement="SELECT (name == 'a,b') AS m, x FROM __THIS__"
    ).transform(Frame({"name": object_column(["a,b", "z"]),
                       "x": np.array([5.0, 6.0])}))
    assert oc["m"].tolist() == [True, False]
    # SQL escaped quote '' stays inside the literal (matches "it's")
    oq = SQLTransformer(
        statement="SELECT x FROM __THIS__ WHERE name = 'it''s'"
    ).transform(Frame({"name": object_column(["it's", "its"]),
                       "x": np.array([1.0, 2.0])}))
    assert oq["x"].tolist() == [1.0]
    # a column legitimately named like a SQL keyword is fine
    f2 = Frame({"limit": np.array([1.0, 2.0])})
    out4 = SQLTransformer(
        statement="SELECT limit, (limit * 2) AS d FROM __THIS__"
    ).transform(f2)
    np.testing.assert_allclose(out4["d"], [2.0, 4.0])
    for bad in (
        "SELECT * FROM other",
        "SELECT a FROM __THIS__ JOIN b",
        "SELECT v1 + v2 FROM __THIS__",   # bare expression, no AS
        "SELECT nope FROM __THIS__",
        "SELECT COUNT(v1) AS c FROM __THIS__",  # aggregates don't eval
    ):
        with pytest.raises(ValueError):
            SQLTransformer(statement=bad).transform(f)


def test_variance_threshold_selector(mesh8, tmp_path):
    from sntc_tpu.feature import VarianceThresholdSelector
    from sklearn.feature_selection import VarianceThreshold as SkVT

    rng = np.random.default_rng(4)
    X = rng.normal(size=(500, 5)).astype(np.float32)
    X[:, 1] = 3.0           # constant
    X[:, 3] *= 0.01         # tiny variance
    f = Frame({"features": X})
    m = VarianceThresholdSelector(varianceThreshold=0.001).fit(f)
    sk = SkVT(threshold=0.001).fit(X.astype(np.float64))
    assert m.selectedFeatures == list(np.nonzero(sk.get_support())[0])
    out = m.transform(f)["selectedFeatures"]
    np.testing.assert_allclose(out, X[:, m.selectedFeatures])
    # default threshold 0: drops exactly the constant column
    m0 = VarianceThresholdSelector().fit(f)
    assert 1 not in m0.selectedFeatures and len(m0.selectedFeatures) == 4
    save_model(m, str(tmp_path / "vts"))
    m2 = load_model(str(tmp_path / "vts"))
    assert m2.selectedFeatures == m.selectedFeatures


def test_string_indexer_multi_column(mesh8, tmp_path):
    from sntc_tpu.feature import StringIndexer

    proto = np.array(["tcp", "udp", "tcp", "icmp"], dtype=object)
    flag = np.array(["S", "S", "A", "R"], dtype=object)
    f = Frame({"proto": proto, "flag": flag})
    m = StringIndexer(
        inputCols=("proto", "flag"), outputCols=("pi", "fi")
    ).fit(f)
    out = m.transform(f)
    # frequencyDesc per column: tcp->0; S->0
    np.testing.assert_array_equal(out["pi"], [0, 2, 0, 1])
    assert out["fi"][0] == 0 and out["fi"][1] == 0
    assert len(m.labelsArray) == 2
    # skip drops the ROW when any column is unseen
    f_bad = Frame({
        "proto": np.array(["tcp", "gre"], dtype=object),
        "flag": np.array(["S", "S"], dtype=object),
    })
    m_skip = m.copy({"handleInvalid": "skip"})
    assert m_skip.transform(f_bad).num_rows == 1
    with pytest.raises(ValueError, match="unseen"):
        m.transform(f_bad)
    # persistence round-trips the multi-column labels
    save_model(m, str(tmp_path / "si_multi"))
    m2 = load_model(str(tmp_path / "si_multi"))
    assert m2.labelsArray == m.labelsArray
    np.testing.assert_array_equal(
        m2.transform(f)["pi"], out["pi"]
    )
    # outputCols validation
    with pytest.raises(ValueError, match="outputCols"):
        StringIndexer(inputCols=("proto",)).fit(f)


def test_bucketizer_multi_column(mesh8):
    from sntc_tpu.feature import Bucketizer, QuantileDiscretizer

    f = Frame({
        "a": np.array([0.1, 0.5, 0.9, np.nan]),
        "b": np.array([10.0, 20.0, 30.0, 40.0]),
    })
    bk = Bucketizer(
        inputCols=("a", "b"), outputCols=("ab", "bb"),
        splitsArray=[[-np.inf, 0.4, np.inf], [-np.inf, 15.0, 25.0, np.inf]],
        handleInvalid="keep",
    )
    out = bk.transform(f)
    np.testing.assert_array_equal(out["ab"], [0, 1, 1, 2])  # NaN -> extra
    np.testing.assert_array_equal(out["bb"], [0, 1, 2, 2])
    # skip drops the ROW when any column is NaN
    out2 = bk.copy({"handleInvalid": "skip"}).transform(f)
    assert out2.num_rows == 3
    # multi-column QuantileDiscretizer returns a multi-column Bucketizer
    qd = QuantileDiscretizer(
        inputCols=("a", "b"), outputCols=("qa", "qb"), numBuckets=2,
        handleInvalid="keep",
    ).fit(f)
    out3 = qd.transform(f)
    assert set(np.unique(out3["qb"])) == {0.0, 1.0}
    with pytest.raises(ValueError, match="splitsArray"):
        Bucketizer(inputCols=("a",), outputCols=("x",)).transform(f)


def test_strip_label_indexer_multi_column(mesh8):
    """Serving prep keeps FEATURE-column indexing when the label shares
    a multi-column StringIndexerModel with features."""
    from sntc_tpu.app import strip_label_indexer
    from sntc_tpu.core.base import PipelineModel
    from sntc_tpu.feature import StringIndexer

    f = Frame({
        "proto": np.array(["tcp", "udp", "tcp"], dtype=object),
        "Label": np.array(["BENIGN", "DDoS", "BENIGN"], dtype=object),
    })
    m = StringIndexer(
        inputCols=("proto", "Label"), outputCols=("pi", "label")
    ).fit(f)
    stages, labels = strip_label_indexer(
        PipelineModel(stages=[m]), "label"
    )
    assert labels == m.labelsArray[1]  # the LABEL vocabulary, not proto's
    assert len(stages) == 1  # proto indexing survives
    out = stages[0].transform(Frame({
        "proto": np.array(["udp"], dtype=object)
    }))
    assert out["pi"][0] == 1.0 and "label" not in out
    # single-column label indexer drops whole, nothing else kept
    m1 = StringIndexer(inputCol="Label", outputCol="label").fit(f)
    stages1, labels1 = strip_label_indexer(
        PipelineModel(stages=[m1]), "label"
    )
    assert stages1 == [] and labels1 == m1.labels
    # no label indexer at all -> untouched, labels None
    stages2, labels2 = strip_label_indexer(
        PipelineModel(stages=[m1]), "other_col"
    )
    assert len(stages2) == 1 and labels2 is None


def test_imputer_mode_strategy():
    from sntc_tpu.feature import Imputer

    f = Frame({
        "a": np.array([1.0, 2.0, 2.0, 7.0, 7.0, np.nan]),
    })
    m = Imputer(inputCols=("a",), strategy="mode").fit(f)
    # ties between 2.0 and 7.0 (2 each) -> smallest wins (Spark 3.1)
    assert m.surrogates[0] == 2.0
    out = m.transform(f)["a"]
    assert out[-1] == 2.0


def test_rformula_save_load(tmp_path):
    f = Frame({
        "y": np.array([1.0, 0.0, 1.0]),
        "proto": object_column(["tcp", "udp", "tcp"]),
    })
    m = RFormula(formula="y ~ proto").fit(f)
    save_model(m, str(tmp_path / "rf"))
    m2 = load_model(str(tmp_path / "rf"))
    np.testing.assert_allclose(
        m2.transform(f)["features"], m.transform(f)["features"]
    )
