"""Model-lifecycle tests (r11) — the four layers of
``sntc_tpu/lifecycle/`` and their engine wiring:

* ``partial_fit`` shard equivalence for NaiveBayes (all four model
  types) and LogisticRegression (both families) against a batch fit on
  the concatenated shards, at the tolerances documented in
  docs/RESILIENCE.md "Model lifecycle";
* drift detection with a DETERMINISTIC latency on the synthetic
  two-day CICIDS-style drift stream (``generate_drift_frames``);
* shadow promotion + hot-swap under shape buckets AND whole-pipeline
  fusion with the compile ledger staying flat for the feature prefix
  (zero recompiles from shadowing or swapping);
* rollback restoring the incumbent's predictions bitwise;
* the end-to-end engine loops (gated promotion; ``partial_fit``
  online learning) and the lifecycle-flag drift check
  (``scripts/check_lifecycle_flags.py``).
"""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from sntc_tpu.core.base import Pipeline
from sntc_tpu.core.frame import Frame
from sntc_tpu.feature import PCA, StandardScaler, VectorAssembler
from sntc_tpu.lifecycle import (
    DriftMonitor,
    LifecycleManager,
    ModelPromoter,
    batch_score_stats,
    graft_head,
    js_divergence,
    macro_f1,
    read_model_marker,
    terminal_head,
)
from sntc_tpu.models import LogisticRegression, NaiveBayes
from sntc_tpu.serve import (
    BatchPredictor,
    MemorySink,
    MemorySource,
    StreamingQuery,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

K_SHARDS = 4


# ---------------------------------------------------------------------------
# synthetic concepts
# ---------------------------------------------------------------------------


def _gauss(n, seed, k=3, d=6):
    """Gaussian blobs: class c centered at c*1.5 along every feature."""
    r = np.random.default_rng(seed)
    y = r.integers(0, k, n)
    X = (y[:, None] * 1.5 + r.normal(size=(n, d))).astype(np.float32)
    return Frame({"features": X, "label": y.astype(np.float64)})


def _counts(n, seed, k=3, d=6):
    """Poisson count features (multinomial/complement-NB friendly)."""
    r = np.random.default_rng(seed)
    y = r.integers(0, k, n)
    rates = 1.0 + 3.0 * ((y[:, None] + np.arange(d)[None, :]) % k)
    X = r.poisson(rates).astype(np.float32)
    return Frame({"features": X, "label": y.astype(np.float64)})


def _binary(n, seed, k=3, d=6):
    r = np.random.default_rng(seed)
    y = r.integers(0, k, n)
    p = 0.2 + 0.6 * ((y[:, None] + np.arange(d)[None, :]) % k == 0)
    X = (r.random((n, d)) < p).astype(np.float32)
    return Frame({"features": X, "label": y.astype(np.float64)})


def _blobs3(n, seed, flip=False):
    """3-column named-feature frame for the fused-pipeline tests; the
    flipped concept swaps the class means so a candidate fit on it
    genuinely disagrees with the incumbent."""
    r = np.random.default_rng(seed)
    y = r.integers(0, 2, n)
    mu = np.where(y[:, None] == 1, 2.0, -2.0)
    if flip:
        mu = -mu
    X = (mu + r.normal(size=(n, 3))).astype(np.float32)
    return Frame({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "label": y.astype(np.float64),
    })


def _shards(frame):
    per = frame.num_rows // K_SHARDS
    return [
        frame.slice(i * per, (i + 1) * per) for i in range(K_SHARDS)
    ]


# ---------------------------------------------------------------------------
# partial_fit shard equivalence (the documented tolerance contract)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "model_type,gen",
    [
        ("multinomial", _counts),
        ("complement", _counts),
        ("bernoulli", _binary),
        ("gaussian", _gauss),
    ],
)
def test_nb_partial_fit_matches_batch_fit(model_type, gen, mesh8):
    train = gen(1200, 7)
    est = NaiveBayes(mesh=mesh8, modelType=model_type)
    batch = est.fit(train)
    state = None
    for shard in _shards(train):
        inc, state = est.partial_fit(shard, state)
    assert state.batches_seen == K_SHARDS
    assert state.rows_seen == 1200
    if model_type == "gaussian":
        # one-pass shifted moments vs the batch fit's second pass:
        # same statistic, different rounding (documented tolerance)
        np.testing.assert_allclose(
            inc.gaussian_mu, batch.gaussian_mu, rtol=1e-4
        )
        np.testing.assert_allclose(
            inc.gaussian_var, batch.gaussian_var, rtol=1e-2
        )
    else:
        # additive sufficient statistics: θ within f32 device
        # summation order of the batch fit
        np.testing.assert_allclose(inc.theta, batch.theta, rtol=1e-5)
        np.testing.assert_allclose(inc.bias, batch.bias, rtol=1e-5)
    test = gen(500, 77)
    agree = float(np.mean(
        np.asarray(batch.transform(test)["prediction"])
        == np.asarray(inc.transform(test)["prediction"])
    ))
    assert agree >= 0.99, f"{model_type}: agreement {agree}"


def test_nb_partial_fit_state_contracts(mesh8):
    est = NaiveBayes(mesh=mesh8)
    f = _counts(40, 0)
    _, state = est.partial_fit(f, None)
    with pytest.raises(ValueError, match="feature width"):
        est.partial_fit(
            Frame({"features": np.ones((5, 3), np.float32),
                   "label": np.zeros(5)}),
            state,
        )
    with pytest.raises(ValueError, match="outside the class set"):
        est.partial_fit(
            Frame({"features": np.ones((5, 6), np.float32),
                   "label": np.full(5, 7.0)}),
            state,
        )
    with pytest.raises(ValueError, match="decay"):
        est.partial_fit(f, state, decay=0.0)


def test_nb_partial_fit_decay_downweights_history(mesh8):
    """decay=γ multiplies every accumulated statistic before the new
    shard folds in — the streaming forgetfulness knob."""
    est = NaiveBayes(mesh=mesh8)
    a, b = _counts(200, 1), _counts(200, 2)
    _, s_plain = est.partial_fit(a, None)
    cw_a = s_plain.cw.copy()
    _, s_plain = est.partial_fit(b, s_plain)
    _, s_decay = est.partial_fit(a, None)
    _, s_decay = est.partial_fit(b, s_decay, decay=0.25)
    np.testing.assert_allclose(
        s_decay.cw, s_plain.cw - 0.75 * cw_a, rtol=1e-12
    )


@pytest.mark.parametrize("k", [2, 3])
def test_lr_partial_fit_matches_batch_fit(k, mesh8):
    """No finite sufficient statistic exists for the logistic loss, so
    the LR contract is behavioral: ≥95% held-out prediction agreement
    with the batch fit over iid shards (warm-started LBFGS on exactly
    accumulated standardization moments)."""
    train = _gauss(1200, 5, k=k)
    est = LogisticRegression(mesh=mesh8, maxIter=30)
    batch = est.fit(train)
    state = None
    for shard in _shards(train):
        inc, state = est.partial_fit(shard, state)
    assert state.binomial == (k == 2)
    assert state.rows_seen == 1200
    # the standardization moments are additive and accumulate EXACTLY
    X = np.asarray(train["features"], np.float64)
    np.testing.assert_allclose(state.s1, X.sum(axis=0), rtol=1e-5)
    np.testing.assert_allclose(state.s2, (X**2).sum(axis=0), rtol=1e-5)
    test = _gauss(600, 88, k=k)
    agree = float(np.mean(
        np.asarray(batch.transform(test)["prediction"])
        == np.asarray(inc.transform(test)["prediction"])
    ))
    assert agree >= 0.95, f"k={k}: agreement {agree}"


def test_lr_partial_fit_rejects_unsupported(mesh8):
    est = LogisticRegression(
        mesh=mesh8, lowerBoundsOnCoefficients=np.zeros((1, 6))
    )
    with pytest.raises(ValueError, match="bound constraints"):
        est.partial_fit(_gauss(40, 0, k=2), None)


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------


def test_js_divergence_properties():
    assert js_divergence([1, 2, 3], [1, 2, 3]) == pytest.approx(0.0)
    assert js_divergence([1, 0], [0, 1]) == pytest.approx(
        np.log(2.0), rel=1e-9
    )
    p, q = [0.7, 0.2, 0.1], [0.2, 0.3, 0.5]
    assert js_divergence(p, q) == pytest.approx(js_divergence(q, p))
    assert 0.0 < js_divergence(p, q) < np.log(2.0)


def test_drift_monitor_event_stream_once_per_episode():
    """Attached monitor folds ``batch_scored`` events; the breach emits
    ``drift_detected`` exactly once per episode and reset() re-arms."""
    from sntc_tpu.resilience import (
        add_event_observer,
        emit_event,
        remove_event_observer,
    )

    seen = []
    obs = lambda rec: seen.append(rec) if (  # noqa: E731
        rec.get("event") == "drift_detected"
    ) else None
    add_event_observer(obs)
    mon = DriftMonitor(window=2, threshold=0.2).attach()
    try:
        ref = {"prediction_mix": [100, 0], "score_hist": [50, 50]}
        shifted = {"prediction_mix": [0, 100], "score_hist": [50, 50]}
        for i in range(4):  # 2 reference + 2 current (no drift)
            emit_event(event="batch_scored", batch_id=i, **ref)
        assert not mon.detected
        for i in range(4, 6):
            emit_event(event="batch_scored", batch_id=i, **shifted)
        # the half-shifted window [3, 4] already crosses the threshold
        assert mon.detected and mon.detected_batch == 4
        for i in range(6, 9):  # still breached: no repeat emission
            emit_event(event="batch_scored", batch_id=i, **shifted)
        assert len(seen) == 1
        assert seen[0]["divergence"] > 0.2
        mon.reset()
        assert not mon.detected and mon.stats()["batches_seen"] == 9
    finally:
        mon.detach()
        remove_event_observer(obs)


def test_drift_detection_latency_on_synthetic_shift(mesh8):
    """The drift-replay fixture: a two-day CICIDS-style stream with the
    mix+concept shift at batch 6.  Detection latency is DETERMINISTIC —
    window 3 freezes batches 0-2 as the reference and the divergence
    crosses the threshold exactly 2 batches after the shift."""
    from sntc_tpu.data import clean_flows, generate_drift_frames
    from sntc_tpu.resilience.health import HealthMonitor

    frames = generate_drift_frames(
        12, rows_per_batch=256, shift_at=6, seed=0, n_classes=8
    )
    assert len(frames) == 12
    train = clean_flows(Frame.concat_all(frames[:6]))
    feat_cols = [c for c in train.columns if c != "Label"]
    from sntc_tpu.feature import StringIndexer

    model = Pipeline(stages=[
        StringIndexer(inputCol="Label", outputCol="label"),
        VectorAssembler(inputCols=feat_cols, outputCol="features"),
        NaiveBayes(mesh=mesh8, modelType="gaussian"),
    ]).fit(train)

    health = HealthMonitor()
    mon = DriftMonitor(window=3, threshold=0.04, health=health)
    for i, f in enumerate(frames):
        stats = batch_score_stats(model.transform(clean_flows(f)), 8)
        stats["batch_id"] = i
        mon.observe(stats)
        if i < 6:  # phase A: healthy baseline, no false positive
            assert not mon.detected, f"false positive at batch {i}"
    assert mon.detected
    assert mon.detected_batch == 8  # latency: 2 batches past the shift
    assert mon.detected_batch - 6 == 2
    snap = health.snapshot()["components"]["model"]
    assert snap["state"] == "DEGRADED"


def test_write_drift_stream_is_deterministic(tmp_path):
    from sntc_tpu.data import write_drift_stream

    d1, d2 = str(tmp_path / "s1"), str(tmp_path / "s2")
    p1 = write_drift_stream(d1, 4, rows_per_batch=16, shift_at=2)
    p2 = write_drift_stream(d2, 4, rows_per_batch=16, shift_at=2)
    assert [os.path.basename(p) for p in p1] == [
        f"part_{i:04d}.csv" for i in range(4)
    ]
    for a, b in zip(p1, p2):
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert fa.read() == fb.read()
    # the shifted day genuinely differs from the first
    with open(p1[0], "rb") as fa, open(p1[2], "rb") as fb:
        assert fa.read() != fb.read()


# ---------------------------------------------------------------------------
# shadow promotion + hot-swap under buckets and fusion
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fused_pair(mesh8):
    """(incumbent serving pipeline compiled with a fused feature prefix
    and a PLAIN swappable head, raw fitted incumbent, candidate head
    fit on the flipped concept)."""
    from sntc_tpu.fuse import compile_pipeline

    pipe = Pipeline(stages=[
        VectorAssembler(inputCols=["a", "b", "c"], outputCol="features"),
        StandardScaler(inputCol="features", outputCol="scaled"),
        PCA(inputCol="scaled", outputCol="pca", k=2),
        LogisticRegression(mesh=mesh8, featuresCol="pca", maxIter=25),
    ])
    fitted = pipe.fit(_blobs3(600, 1))
    candidate = terminal_head(pipe.fit(_blobs3(600, 2, flip=True)))
    serving = compile_pipeline(fitted, fuse_heads=False)
    return serving, fitted, candidate


def test_fused_head_is_not_swappable(fused_pair, mesh8):
    """A head fused INTO a segment cannot be located/swapped — the
    lifecycle serving path compiles with fuse_heads=False and the
    guard names that fix."""
    from sntc_tpu.fuse import compile_pipeline

    _, fitted, _ = fused_pair
    fully_fused = compile_pipeline(fitted, fuse_heads=True)
    with pytest.raises(ValueError, match="fuse_heads=False"):
        terminal_head(fully_fused)


def test_hot_swap_adds_zero_prefix_recompiles(fused_pair):
    """Shadow scoring AND the hot-swap reuse the incumbent's compiled
    feature-prefix programs: the fused segment's compile ledger and the
    predictor's shape ledger both stay flat, and only because
    graft_head reuses the very same fitted stage objects."""
    from sntc_tpu.fuse import fused_segments

    serving, _, candidate = fused_pair
    segs = fused_segments(serving)
    assert len(segs) == 1
    bp = BatchPredictor(serving, bucket_rows=32)
    for n, s in ((20, 10), (40, 11), (25, 12), (37, 13)):
        bp.predict_frame(_blobs3(n, s))
    seg_warm = [s.compile_events for s in segs]
    bp_warm = bp.compile_events
    assert bp_warm == 2  # two pow2 buckets: 32 and 64

    cand_serving = graft_head(serving, candidate)
    # the prefix is the SAME object, not an equivalent recompile
    assert fused_segments(cand_serving)[0] is segs[0]

    # shadow scoring through a second predictor with the same buckets
    shadow = BatchPredictor(cand_serving, bucket_rows=32)
    for n, s in ((20, 20), (40, 21)):
        shadow.predict_frame(_blobs3(n, s))
    assert [s.compile_events for s in segs] == seg_warm

    # hot-swap, then the same shapes again: zero new compile events
    old = bp.swap_model(cand_serving)
    for n, s in ((20, 30), (40, 31), (25, 32)):
        bp.predict_frame(_blobs3(n, s))
    assert [s.compile_events for s in segs] == seg_warm
    assert bp.compile_events == bp_warm

    # the swap genuinely changed the served model...
    probe = _blobs3(64, 99)
    ref_old = old.transform(probe)
    assert not np.array_equal(
        np.asarray(bp.predict_frame(probe)["prediction"]),
        np.asarray(ref_old["prediction"]),
    )
    # ...and swapping back restores incumbent predictions BITWISE
    bp.swap_model(old)
    out = bp.predict_frame(probe)
    np.testing.assert_array_equal(
        np.asarray(out["prediction"]),
        np.asarray(ref_old["prediction"]),
    )
    np.testing.assert_array_equal(
        np.asarray(out["probability"]),
        np.asarray(ref_old["probability"]),
    )


def test_promoter_gate_and_engine_swap(fused_pair, tmp_path):
    """End-to-end gated promotion on the engine: shadow-score the
    candidate over the window, publish atomically (marker + journal +
    ``.prev`` retained), hot-swap between micro-batches, and keep the
    WAL/commit contract."""
    from sntc_tpu.mlio import load_model, prev_checkpoint_path, save_model

    serving, fitted, candidate = fused_pair
    serving_path = str(tmp_path / "model")
    ckpt = str(tmp_path / "ckpt")
    save_model(fitted, serving_path)

    # stream labels follow the FLIPPED concept: the incumbent loses
    # the gate, the candidate (fit on it) wins
    batches = [_blobs3(64, 100 + i, flip=True) for i in range(8)]
    sink = MemorySink()
    promoter = ModelPromoter(
        serving, incumbent_raw=fitted, serving_path=serving_path,
        checkpoint_dir=ckpt, window=3, probation_batches=2,
    )
    promoter.set_candidate(candidate)
    q = StreamingQuery(
        serving, MemorySource(batches), sink, ckpt,
        max_batch_offsets=1,
        lifecycle=LifecycleManager(promoter=promoter),
    )
    assert q.process_available() == 8
    assert q.models_swapped == 1
    assert promoter.promotions == 1 and promoter.rollbacks == 0
    assert promoter.state == "idle"  # probation passed
    # WAL/commit contract: all 8 batches committed exactly once
    assert q.last_committed() == 7
    # the sink flips from incumbent predictions to candidate ones:
    # window fills at batch 2, the swap lands at the next safe point
    y0 = np.asarray(batches[0]["label"], np.int64)
    f1_first = macro_f1(y0, np.asarray(sink.frames[0]["prediction"]))
    y7 = np.asarray(batches[7]["label"], np.int64)
    f1_last = macro_f1(y7, np.asarray(sink.frames[7]["prediction"]))
    assert f1_first < 0.2 < 0.9 < f1_last
    # durable state: marker generation, journal verdicts, .prev
    marker = read_model_marker(ckpt)
    assert marker["generation"] == 1 and marker["action"] == "promoted"
    with open(os.path.join(ckpt, "promotion.jsonl")) as f:
        journal = [json.loads(line) for line in f]
    actions = [r["action"] for r in journal]
    assert "promote" in actions and "probation_passed" in actions
    assert any(
        r["action"] == "shadow_score" and r["decision"] == "promote"
        for r in journal
    )
    assert os.path.isdir(prev_checkpoint_path(serving_path))
    # the published checkpoint serves the candidate after a restart
    republished = terminal_head(load_model(serving_path))
    np.testing.assert_array_equal(
        np.asarray(republished.coefficientMatrix),
        np.asarray(candidate.coefficientMatrix),
    )
    lc = q.pipeline_stats()["lifecycle"]
    assert lc["models_swapped"] == 1
    assert lc["promoter"]["generation"] == 1
    q.stop()


def test_probation_breach_rolls_back_bitwise(fused_pair, tmp_path):
    """An OPEN predict.dispatch breaker during post-swap probation
    triggers rollback: the engine swaps the EXACT retained incumbent
    back (bitwise-identical predictions) and republishes it."""
    from sntc_tpu.mlio import load_model, save_model

    serving, fitted, candidate = fused_pair
    serving_path = str(tmp_path / "model")
    ckpt = str(tmp_path / "ckpt")
    save_model(fitted, serving_path)

    class OpenableBreaker:
        state = "closed"

    breaker = OpenableBreaker()
    promoter = ModelPromoter(
        serving, incumbent_raw=fitted, serving_path=serving_path,
        checkpoint_dir=ckpt, window=2, probation_batches=4,
        breaker=breaker,
    )
    promoter.set_candidate(candidate)
    batches = [_blobs3(64, 200 + i, flip=True) for i in range(3)]
    sink = MemorySink()
    q = StreamingQuery(
        serving, MemorySource(batches), sink, ckpt,
        max_batch_offsets=1,
        lifecycle=LifecycleManager(promoter=promoter),
    )
    probe = _blobs3(64, 999)
    ref_incumbent = serving.transform(probe)
    assert q.process_available() == 3
    assert q.models_swapped == 1 and promoter.state == "probation"

    # the breaker opens mid-probation; more stream data arrives
    breaker.state = "open"
    src2 = q.source
    src2.add(_blobs3(64, 300))
    assert q.process_available() == 1
    assert promoter.rollbacks == 1
    assert q.models_swapped == 2  # promote swap + rollback swap
    assert promoter.state == "rolled_back"
    # the served model is the EXACT incumbent object again: bitwise
    out = q.predictor.model.transform(probe)
    np.testing.assert_array_equal(
        np.asarray(out["prediction"]),
        np.asarray(ref_incumbent["prediction"]),
    )
    np.testing.assert_array_equal(
        np.asarray(out["probability"]),
        np.asarray(ref_incumbent["probability"]),
    )
    # durable: the marker records the rollback, the serving path loads
    # the incumbent head again
    marker = read_model_marker(ckpt)
    assert marker["action"] == "rolled_back"
    restored = terminal_head(load_model(serving_path))
    np.testing.assert_array_equal(
        np.asarray(restored.coefficientMatrix),
        np.asarray(terminal_head(fitted).coefficientMatrix),
    )
    q.stop()


def test_rollback_from_prev_checkpoint_without_memory(
    fused_pair, tmp_path
):
    """A promoter that never promoted in-process (fresh restart) rolls
    back from the durable ``<serving_path>.prev`` snapshot."""
    from sntc_tpu.mlio import save_model

    serving, fitted, candidate = fused_pair
    serving_path = str(tmp_path / "model")
    save_model(fitted, serving_path)  # generation 0
    save_model(
        graft_head(fitted, candidate), serving_path
    )  # candidate published; incumbent retained at .prev

    promoter = ModelPromoter(
        graft_head(serving, candidate),
        incumbent_raw=graft_head(fitted, candidate),
        serving_path=serving_path,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    promoter.rollback("operator-forced")
    restored = promoter.take_pending_swap()
    assert restored is not None
    probe = _blobs3(32, 5)
    np.testing.assert_array_equal(
        np.asarray(restored.transform(probe)["prediction"]),
        np.asarray(fitted.transform(probe)["prediction"]),
    )


def test_candidate_scaler_fold_normalization(mesh8):
    """The default serve path folds a scaler directly feeding the head
    into the head's weights, so the incumbent head reads the PRE-scaler
    column; an external candidate checkpoint arrives UNfolded.  The
    promoter must apply the same fold to the candidate (baking the
    candidate's OWN scaler into its head) before grafting — the r11
    serve-CLI regression."""
    from sntc_tpu.fuse import compile_pipeline

    pipe = Pipeline(stages=[
        VectorAssembler(inputCols=["a", "b", "c"], outputCol="raw"),
        StandardScaler(inputCol="raw", outputCol="features"),
        LogisticRegression(mesh=mesh8, maxIter=20),
    ])
    incumbent_raw = pipe.fit(_blobs3(400, 1))
    candidate_raw = pipe.fit(_blobs3(400, 2, flip=True))
    serving = compile_pipeline(incumbent_raw, fuse_heads=False)
    # the fold happened: the serving head reads the assembler output
    assert terminal_head(serving).getFeaturesCol() == "raw"
    promoter = ModelPromoter(serving, incumbent_raw=incumbent_raw)
    promoter.set_candidate(candidate_raw)  # must not raise
    assert promoter.candidate_head.getFeaturesCol() == "raw"
    # the folded graft serves the candidate's EXACT decision function
    probe = _blobs3(64, 9)
    np.testing.assert_array_equal(
        np.asarray(promoter.candidate.transform(probe)["prediction"]),
        np.asarray(candidate_raw.transform(probe)["prediction"]),
    )


def test_promote_and_prev_rollback_with_scaler_fold(mesh8, tmp_path):
    """``promote()`` on the default serve path (scaler folded into the
    head, so the candidate head reads the PRE-scaler column) must
    publish a restart-servable checkpoint — the raw prefix is folded
    the same way before the graft — and a post-restart rollback (no
    in-memory ``_previous``) must normalize the ``.prev`` head back
    onto the compiled prefix instead of raising."""
    from sntc_tpu.fuse import compile_pipeline
    from sntc_tpu.mlio import load_model, save_model

    pipe = Pipeline(stages=[
        VectorAssembler(inputCols=["a", "b", "c"], outputCol="raw"),
        StandardScaler(inputCol="raw", outputCol="features"),
        LogisticRegression(mesh=mesh8, maxIter=20),
    ])
    incumbent_raw = pipe.fit(_blobs3(400, 1))
    candidate_raw = pipe.fit(_blobs3(400, 2, flip=True))
    serving = compile_pipeline(incumbent_raw, fuse_heads=False)
    assert terminal_head(serving).getFeaturesCol() == "raw"
    serving_path = str(tmp_path / "model")
    save_model(incumbent_raw, serving_path)
    promoter = ModelPromoter(
        serving, incumbent_raw=incumbent_raw, serving_path=serving_path,
        checkpoint_dir=str(tmp_path / "ckpt"), window=1,
        probation_batches=1,
    )
    promoter.set_candidate(candidate_raw)
    promoter.promote()  # must not raise on the folded mismatch
    probe = _blobs3(64, 9)
    want = np.asarray(candidate_raw.transform(probe)["prediction"])
    # the published checkpoint transforms RAW flow columns on restart
    np.testing.assert_array_equal(
        np.asarray(load_model(serving_path).transform(probe)["prediction"]),
        want,
    )
    # land the swap, then simulate a restart: the retained in-memory
    # previous generation is gone, rollback must go through .prev
    promoter.on_swap_applied(serving)
    promoter._previous = None
    promoter.rollback("probation breach")
    inc_want = np.asarray(incumbent_raw.transform(probe)["prediction"])
    np.testing.assert_array_equal(
        np.asarray(promoter.incumbent.transform(probe)["prediction"]),
        inc_want,
    )
    # ...and republish the restored model for the next restart
    np.testing.assert_array_equal(
        np.asarray(load_model(serving_path).transform(probe)["prediction"]),
        inc_want,
    )


def test_promote_gate_disarmed_until_swap_applies(fused_pair, tmp_path):
    """A labeled batch settled between publish and the engine's swap
    safe point (overlap mode settles one during the swap itself) must
    NOT re-promote: ``promote()`` moves the machine to ``promoting``,
    and a stale duplicate ``on_swap_applied`` is a no-op instead of
    clobbering the incumbent with the cleared candidate."""
    from sntc_tpu.mlio import save_model

    serving, fitted, candidate = fused_pair
    serving_path = str(tmp_path / "model")
    save_model(fitted, serving_path)
    promoter = ModelPromoter(
        serving, incumbent_raw=fitted, serving_path=serving_path,
        checkpoint_dir=str(tmp_path / "ckpt"), window=1,
        probation_batches=2,
    )
    promoter.set_candidate(candidate)
    # head-only shadow: scoring must not re-run the feature prefix
    assert promoter._shadow.model is promoter.candidate_head

    batch = _blobs3(64, 100, flip=True)
    out = BatchPredictor(serving).predict_frame(batch)
    promoter.on_batch(0, batch, out)  # window=1: gate fires
    assert promoter.state == "promoting" and promoter.promotions == 1
    # the in-between batch: gate disarmed, no second publish
    promoter.on_batch(1, batch, out)
    assert promoter.promotions == 1 and promoter.generation == 1
    # ...and the partial-fit refit loop cannot reset the machine either
    promoter.update_candidate(candidate)
    assert promoter.state == "promoting"

    swap = promoter.take_pending_swap()
    assert swap is not None
    promoter.on_swap_applied(serving)
    assert promoter.state == "probation"
    assert promoter.incumbent is not None
    # stale duplicate apply (nothing armed): a no-op
    promoter.on_swap_applied(serving)
    assert promoter.state == "probation"
    assert promoter.incumbent is not None


def test_rollback_republishes_bare_head_incumbent(tmp_path, mesh8):
    """A bare classifier-head incumbent has no ``incumbent_raw``; after
    a rollback the restored head itself must be republished to
    ``serving_path`` — otherwise a restart loads the rolled-back
    candidate the marker claims was replaced."""
    from sntc_tpu.mlio import load_model, save_model

    incumbent = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(
        _gauss(300, 0)
    )
    candidate = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(
        _gauss(300, 1)
    )
    serving_path = str(tmp_path / "model")
    save_model(incumbent, serving_path)
    promoter = ModelPromoter(
        incumbent, serving_path=serving_path,
        checkpoint_dir=str(tmp_path / "ckpt"), window=1,
        probation_batches=2,
    )
    promoter.set_candidate(candidate)
    promoter.promote()
    promoter.take_pending_swap()
    promoter.on_swap_applied(incumbent)
    promoter.rollback("probation breach")
    probe = _gauss(200, 9)
    np.testing.assert_array_equal(
        np.asarray(load_model(serving_path).transform(probe)["prediction"]),
        np.asarray(incumbent.transform(probe)["prediction"]),
    )


def test_lifecycle_tick_rearms_swap_when_safe_point_fails(tmp_path, mesh8):
    """A failure BEFORE the predictor flip (e.g. settling the in-air
    delivery raises) must put the taken swap back for the next tick —
    dropping it would wedge a rollback in ``rolling_back`` while the
    disk checkpoint already names the restored model."""
    from sntc_tpu.resilience import add_event_observer, remove_event_observer

    incumbent = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(
        _gauss(200, 0)
    )
    replacement = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(
        _gauss(200, 1)
    )

    class OneSwap:
        def __init__(self, model):
            self.pending = model
            self.rearmed = 0
            self.applied = 0

        def on_batch(self, batch_id, frame, finalize):
            pass

        def take_pending_swap(self):
            pending, self.pending = self.pending, None
            return pending

        def rearm_pending_swap(self, model):
            self.pending = model
            self.rearmed += 1

        def on_swap_applied(self, old):
            self.applied += 1

    class FlakySwap(StreamingQuery):
        def swap_model(self, model):
            if getattr(self, "_fail_once", True):
                self._fail_once = False
                raise RuntimeError("delivery settle failed")
            return super().swap_model(model)

    lc = OneSwap(replacement)
    errors = []
    obs = lambda r: errors.append(r) if (  # noqa: E731
        r.get("event") == "lifecycle_error"
    ) else None
    add_event_observer(obs)
    try:
        q = FlakySwap(
            incumbent, MemorySource([_gauss(32, 2), _gauss(32, 3)]),
            MemorySink(), str(tmp_path / "ckpt"),
            max_batch_offsets=1, lifecycle=lc,
        )
        assert q.process_available() == 2
        assert lc.rearmed == 1 and lc.applied == 1
        assert q.predictor.model is replacement
        assert len(errors) == 1
        q.stop()
    finally:
        remove_event_observer(obs)


def test_online_partial_fit_loop_recovers_f1(tmp_path, mesh8):
    """The full online-learning arc on the engine: the incumbent is
    blind to the shifted concept, ``partial_fit`` refits a candidate
    from live labeled batches, the gate promotes it, and post-swap
    macro-F1 recovers."""
    from sntc_tpu.mlio import save_model

    def shifted(n, seed, shift=False, k=3, d=4):
        r = np.random.default_rng(seed)
        y = r.integers(0, k, n)
        centers = ((y[:, None] + 1) % k if shift else y[:, None]) * 2.0
        X = (centers + r.normal(size=(n, d))).astype(np.float32)
        return Frame({"features": X, "label": y.astype(np.float64)})

    incumbent = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(
        shifted(900, 0)
    )
    serving_path = str(tmp_path / "model")
    ckpt = str(tmp_path / "ckpt")
    save_model(incumbent, serving_path)
    batches = [shifted(128, 100 + i, shift=True) for i in range(10)]
    promoter = ModelPromoter(
        incumbent, incumbent_raw=incumbent, serving_path=serving_path,
        checkpoint_dir=ckpt, window=3, probation_batches=2,
    )
    mgr = LifecycleManager(promoter=promoter, partial_fit=True)
    q = StreamingQuery(
        incumbent, MemorySource(batches), MemorySink(), ckpt,
        max_batch_offsets=1, lifecycle=mgr,
    )
    assert q.process_available() == 10
    stats = q.pipeline_stats()["lifecycle"]
    assert stats["partial_fit_batches"] == 10
    assert stats["models_swapped"] >= 1
    assert promoter.promotions >= 1 and promoter.rollbacks == 0
    probe = shifted(400, 999, shift=True)
    y = np.asarray(probe["label"], np.int64)
    f1_inc = macro_f1(
        y, np.asarray(incumbent.transform(probe)["prediction"])
    )
    f1_live = macro_f1(
        y, np.asarray(q.predictor.model.transform(probe)["prediction"])
    )
    assert f1_inc < 0.2, f"incumbent unexpectedly survives: {f1_inc}"
    assert f1_live > 0.9, f"refit candidate did not recover: {f1_live}"
    q.stop()


def test_incremental_estimator_for_unsupported_head_raises(mesh8):
    from sntc_tpu.lifecycle import incremental_estimator_for
    from sntc_tpu.models import LinearSVC

    svc = LinearSVC(mesh=mesh8, maxIter=5).fit(_gauss(80, 0, k=2))
    with pytest.raises(ValueError, match="no incremental estimator"):
        incremental_estimator_for(svc)


def test_lifecycle_hook_failure_degrades_not_kills(tmp_path, mesh8):
    """A raising lifecycle hook must never kill the serving loop: the
    engine emits ``lifecycle_error`` and keeps committing."""
    from sntc_tpu.resilience import add_event_observer, remove_event_observer

    incumbent = NaiveBayes(mesh=mesh8, modelType="gaussian").fit(
        _gauss(200, 0)
    )

    class Exploding:
        def on_batch(self, batch_id, frame, finalize):
            raise RuntimeError("boom")

    seen = []
    obs = lambda rec: seen.append(rec) if (  # noqa: E731
        rec.get("event") == "lifecycle_error"
    ) else None
    add_event_observer(obs)
    try:
        q = StreamingQuery(
            incumbent,
            MemorySource([_gauss(32, 1), _gauss(32, 2)]),
            MemorySink(), str(tmp_path / "ckpt"),
            max_batch_offsets=1, lifecycle=Exploding(),
        )
        assert q.process_available() == 2
        assert q.last_committed() == 1
        assert len(seen) == 2
        q.stop()
    finally:
        remove_event_observer(obs)


# ---------------------------------------------------------------------------
# lifecycle-flag drift check (the tier-1 wiring of
# scripts/check_lifecycle_flags.py, mirroring check_perf_flags)
# ---------------------------------------------------------------------------


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lifecycle_flags_consistent():
    checker = _load_script("check_lifecycle_flags")
    assert checker.check() == []
