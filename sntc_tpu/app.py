"""Application entry points — the reference's L0 script layer.

Behavioral spec: SURVEY.md §2.1: the reference app is a set of driver
scripts over CICIDS2017 day CSVs — per-estimator train/eval scripts
(`[R]`, capability fixed by [B:6-12]) and a streaming-inference script
([B:11]).  This module is their CLI equivalent:

    python -m sntc_tpu synth    --out data/ --rows 100000
    python -m sntc_tpu train    --data data/ --estimator mlp --model-out m/
    python -m sntc_tpu evaluate --data data/ --model m/ --metric macroF1
    python -m sntc_tpu serve    --model m/ --watch data/in --out data/out \
                                --checkpoint data/ckpt

``train`` assembles the same pipeline shapes the five bench configs use
(StringIndexer → VectorAssembler → [StandardScaler] → estimator);
``serve`` runs the micro-batch engine over a watched CSV directory with
offset/commit resume.  Real "MachineLearningCVE" day CSVs drop in
unchanged; ``synth`` writes schema-identical synthetic days.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

TRAIN_DEFAULT_LAYERS = "78,64,15"


def _obs_start(args) -> None:
    """Arm the telemetry surfaces a command requested (before any
    work): ``--trace-out`` enables the span tracer for the process."""
    if getattr(args, "trace_out", None):
        from sntc_tpu.obs import enable_tracing

        enable_tracing()


def _obs_finish(args) -> None:
    """Publish the telemetry a command requested: the Prometheus text
    snapshot (``--metrics-out``, atomic) and the Chrome-trace/Perfetto
    span export (``--trace-out``)."""
    if getattr(args, "metrics_out", None):
        from sntc_tpu.obs import registry

        registry().write_prometheus(args.metrics_out)
    if getattr(args, "trace_out", None):
        from sntc_tpu.obs import tracer

        t = tracer()
        if t is not None:
            t.export_chrome_trace(args.trace_out)


def _device_trace_ctx(args):
    """``--device-trace DIR``: a jax.profiler capture around the run
    (XLA op timeline for Perfetto/TensorBoard) — device time next to
    the host spans.  A no-op context when the flag is unset."""
    if getattr(args, "device_trace", None):
        from sntc_tpu.obs import device_trace

        return device_trace(args.device_trace)
    return contextlib.nullcontext()


def _add_obs_flags(p, device: bool = True):
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write the process metrics registry as a "
                   "Prometheus text snapshot here (atomic; "
                   "serve-daemon republishes it every scheduling "
                   "round, other commands at exit) — see "
                   "docs/OBSERVABILITY.md")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="arm the span tracer and export the host-stage "
                   "timeline as Chrome-trace JSON here at exit "
                   "(loadable in chrome://tracing / ui.perfetto.dev)")
    if device:
        p.add_argument("--device-trace", default=None, metavar="DIR",
                       help="additionally capture a jax.profiler "
                       "(XLA op-level) trace of the run into DIR "
                       "for TensorBoard/Perfetto")


def _build_estimator(name: str, mesh, args):
    from sntc_tpu.models import (
        DecisionTreeClassifier,
        GBTClassifier,
        LinearSVC,
        LogisticRegression,
        MultilayerPerceptronClassifier,
        NaiveBayes,
        OneVsRest,
        RandomForestClassifier,
    )

    if name == "lr":
        return LogisticRegression(
            mesh=mesh, maxIter=args.max_iter, regParam=args.reg_param
        )
    if name == "mlp":
        layers = [int(v) for v in args.layers.split(",")]
        return MultilayerPerceptronClassifier(
            mesh=mesh, layers=layers, maxIter=args.max_iter, seed=args.seed
        )
    if name == "rf":
        return RandomForestClassifier(
            mesh=mesh, numTrees=args.num_trees, maxDepth=args.max_depth,
            seed=args.seed,
        )
    if name == "gbt":
        return OneVsRest(
            classifier=GBTClassifier(
                mesh=mesh, maxIter=args.max_iter, maxDepth=args.max_depth,
                stepSize=args.step_size, seed=args.seed,
                maxBins=args.max_bins,
            ),
            featuresCol=args.features_col,
        )
    if name == "dt":
        return DecisionTreeClassifier(
            mesh=mesh, maxDepth=args.max_depth, maxBins=args.max_bins,
            seed=args.seed,
        )
    if name == "nb":
        return NaiveBayes(mesh=mesh, modelType="gaussian")
    if name == "svc":
        return OneVsRest(
            classifier=LinearSVC(
                mesh=mesh, maxIter=args.max_iter, regParam=args.reg_param
            ),
            featuresCol=args.features_col,
        )
    raise SystemExit(
        f"unknown estimator {name!r} (lr|mlp|rf|gbt|dt|nb|svc)"
    )


def _feature_stages(mesh, args, with_scaler: bool):
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.feature import (
        ChiSqSelector,
        StandardScaler,
        StringIndexer,
        VectorAssembler,
    )

    stages = [
        StringIndexer(inputCol=args.label_col, outputCol="label",
                      handleInvalid="skip"),
        VectorAssembler(inputCols=CICIDS2017_FEATURES,
                        outputCol="rawFeatures", handleInvalid="skip"),
    ]
    if args.chisq_top:
        stages.append(ChiSqSelector(
            mesh=mesh, numTopFeatures=args.chisq_top,
            featuresCol="rawFeatures", labelCol="label",
            outputCol=args.features_col,
        ))
    elif with_scaler:
        stages.append(StandardScaler(
            mesh=mesh, inputCol="rawFeatures", outputCol=args.features_col,
            withMean=True,
        ))
    return stages


def _load_data(args):
    from sntc_tpu.data import clean_flows, load_csv_dir

    df = clean_flows(load_csv_dir(args.data))
    if args.binary:
        import numpy as np

        df = df.with_column(
            args.label_col,
            np.where(
                df[args.label_col].astype(str) == "BENIGN", "benign", "attack"
            ).astype(object),
        )
    return df


def cmd_train(args) -> int:
    from sntc_tpu.parallel.context import get_default_mesh

    _obs_start(args)
    mesh = get_default_mesh()
    # telemetry publishes in finally: a crashed fit is exactly the run
    # whose partial metrics/spans the operator armed --metrics-out /
    # --trace-out to see (same contract as the serve/daemon paths)
    try:
        return _cmd_train_body(args, mesh)
    finally:
        _obs_finish(args)


def _cmd_train_body(args, mesh) -> int:
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.evaluation import MulticlassClassificationEvaluator
    from sntc_tpu.mlio import save_model
    from sntc_tpu.obs import span

    with span("train.load_data"):
        df = _load_data(args)
    train, test = df.random_split(
        [1 - args.test_fraction, args.test_fraction], seed=args.seed
    )
    with_scaler = args.estimator in ("lr", "mlp", "svc")
    # the column the estimator reads = whatever the LAST feature stage
    # writes: chisq/scaler write --features-col, a bare assembler leaves
    # "rawFeatures" (trees consume unscaled features, as the reference does)
    if not args.chisq_top and not with_scaler:
        args.features_col = "rawFeatures"
    n_features = args.chisq_top or len(CICIDS2017_FEATURES)
    if args.estimator == "mlp":
        import numpy as np

        n_classes = int(np.unique(train[args.label_col].astype(str)).size)
        layers = [int(v) for v in args.layers.split(",")]
        is_default = args.layers == TRAIN_DEFAULT_LAYERS
        for pos, want, what in (
            (0, n_features, "input width / feature count"),
            (-1, n_classes, "output width / class count"),
        ):
            if layers[pos] != want:
                if is_default:
                    layers[pos] = want  # default layers track the data
                else:
                    raise SystemExit(
                        f"--layers {what} mismatch: {layers[pos]} != {want}"
                    )
        args.layers = ",".join(str(v) for v in layers)
    est = _build_estimator(args.estimator, mesh, args)
    if est.hasParam("featuresCol"):
        est.set("featuresCol", args.features_col)
    pipe = Pipeline(stages=_feature_stages(mesh, args, with_scaler) + [est])
    t0 = time.perf_counter()
    with _device_trace_ctx(args), span(
        "train.fit", estimator=args.estimator
    ):
        model = pipe.fit(train)
    fit_s = time.perf_counter() - t0
    with span("train.evaluate"):
        f1 = MulticlassClassificationEvaluator(
            metricName=args.metric, mesh=mesh
        ).evaluate(model.transform(test))
    if args.model_out:
        save_model(model, args.model_out)
    print(json.dumps({
        "estimator": args.estimator, "train_rows": train.num_rows,
        "fit_wall_clock_s": round(fit_s, 3), args.metric: f1,
        "model_out": args.model_out,
    }))
    return 0


def cmd_evaluate(args) -> int:
    from sntc_tpu.evaluation import MulticlassClassificationEvaluator
    from sntc_tpu.mlio import load_model
    from sntc_tpu.parallel.context import get_default_mesh

    mesh = get_default_mesh()
    model = load_model(args.model)
    df = _load_data(args)
    value = MulticlassClassificationEvaluator(
        metricName=args.metric, mesh=mesh
    ).evaluate(model.transform(df))
    print(json.dumps({"rows": df.num_rows, args.metric: value}))
    return 0


def strip_label_indexer(model, label_index_col: str):
    """Serving prep: remove the LABEL indexing (live flows carry no
    label column) while KEEPING any feature-column indexing, and return
    the label vocabulary for mapping predictions back to strings.

    Handles both indexer modes: a single-column StringIndexerModel
    writing ``label_index_col`` is dropped whole; a multi-column one is
    reduced to its non-label columns.  Returns ``(stages, labels)``
    where ``labels`` is None when no label indexer was found."""
    from sntc_tpu.feature.string_indexer import (
        StringIndexerModel,
        _resolve_cols,
    )

    stages, labels = [], None
    for s in model.getStages():
        if isinstance(s, StringIndexerModel):
            ins, outs = _resolve_cols(s)
            if label_index_col in outs:
                j = outs.index(label_index_col)
                labels = s.labelsArray[j]
                keep = [k for k in range(len(outs)) if k != j]
                if keep:
                    reduced = StringIndexerModel(
                        labelsArray=[s.labelsArray[k] for k in keep],
                    )
                    reduced.setParams(
                        inputCols=[ins[k] for k in keep],
                        outputCols=[outs[k] for k in keep],
                        handleInvalid=s.getHandleInvalid(),
                        stringOrderType=s.getStringOrderType(),
                    )
                    stages.append(reduced)
                continue
        stages.append(s)
    return stages, labels


def _serving_form(model, label_index_col: str, fuse: bool,
                  fuse_heads: bool = True):
    """One checkpoint → its servable form, shared by ``serve`` and
    ``serve-daemon``: drop the LABEL indexer (live flows carry no
    label; feature-column indexers are kept), map predictions back to
    label strings with its vocabulary, and — with ``fuse`` — compile
    through the whole-pipeline fusion compiler
    (docs/PERFORMANCE.md "Whole-pipeline fusion"; ``fuse_heads=False``
    keeps the head a plain swappable stage for lifecycle hot-swap).
    Returns ``(model, labels, out_cols)``."""
    from sntc_tpu.core.base import PipelineModel

    out_cols = ["prediction"]
    labels = None
    if isinstance(model, PipelineModel):
        from sntc_tpu.feature import IndexToString
        from sntc_tpu.serve import compile_serving

        stages, labels = strip_label_indexer(model, label_index_col)
        tail = (
            [IndexToString(
                inputCol="prediction", outputCol="predictedLabel",
                labels=labels,
            )]
            if labels is not None else []
        )
        model = PipelineModel(stages=stages + tail)
        if fuse:
            model = compile_serving(model, fuse_heads=fuse_heads)
        if tail:
            out_cols = ["prediction", "predictedLabel"]
    return model, labels, out_cols


def cmd_serve(args) -> int:
    from sntc_tpu.mlio import load_model
    from sntc_tpu.resilience import (
        QuerySupervisor,
        RetryPolicy,
        default_breakers,
    )
    from sntc_tpu.serve import (
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
    )

    _obs_start(args)
    # kernel tier selection must land before any serving compile reads
    # it (the registry re-resolves per dispatch, but the journaled run
    # config should reflect one consistent mode end-to-end)
    if getattr(args, "serve_kernels", None):
        os.environ["SNTC_SERVE_KERNELS"] = args.serve_kernels
    model = load_model(args.model)
    raw_model = model  # persistable form: the lifecycle publish target
    # model lifecycle (r11): any of the drift / shadow-promotion /
    # incremental-fit flags arms the LifecycleManager on the engine
    lifecycle_armed = bool(
        args.partial_fit or args.drift_window > 0 or args.promote_from
    )
    # only a config that can SWAP models needs the head kept out of the
    # fused segments; drift-only monitoring keeps full head fusion
    swap_armed = bool(args.partial_fit or args.promote_from)
    # no labels on live flows: the label indexer comes off and
    # predictions map back to label STRINGS — the reference app's
    # output shape.  --fuse (default) compiles through the whole-
    # pipeline fusion compiler; with promotion or partial-fit armed
    # the HEAD stays a plain stage (fuse_heads=False): a fused head's
    # weights are constants of the segment's program, so hot-swapping
    # it would recompile the whole prefix — plain heads swap with zero
    # prefix recompiles while the feature prefix still fuses.
    # Drift-only monitoring never swaps, so it keeps full fusion.
    model, labels, out_cols = _serving_form(
        model, args.label_index_col, args.fuse,
        fuse_heads=not swap_armed,
    )
    # a SERVED query degrades instead of dying: transient read/sink
    # errors retry in place, a batch that keeps failing quarantines to
    # the dead-letter journal after --max-batch-failures rounds, and
    # the breakers get enough outcomes to actually open — without these
    # the first IOError would kill the process and the supervision
    # layer below would never see a second chance
    retries = max(1, args.batch_retry_attempts)
    # pipelined serving (docs/PERFORMANCE.md): depth > 1 arms the
    # overlapped retire stage (sink delivery on its own thread) and the
    # source's background prefetch; --shape-buckets pads micro-batches
    # to power-of-two row buckets so predict compiles once per bucket
    pipelined = args.pipeline_depth > 1
    # --row-policy salvage|permissive arms the data-plane admission
    # layer against the canonical CICIDS2017 contract: poison ROWS are
    # excised (and journaled to <checkpoint>/dead_letter_rows/ with
    # file/line/raw/reason) while the clean rows keep serving — and the
    # CSV parser itself salvages ragged lines instead of failing the
    # batch.  "strict" keeps today's trust-the-input behavior: the
    # whole batch fails and the poison-batch machinery owns it.
    contract = None
    if args.row_policy != "strict":
        from sntc_tpu.data import CICIDS2017_CONTRACT

        contract = CICIDS2017_CONTRACT.with_mode(args.row_policy)
    # live-model lifecycle: --drift-window arms the divergence monitor
    # (drift_detected events, model DEGRADED); --promote-from shadow-
    # scores a candidate checkpoint and promotes it through the atomic
    # publish + between-batches hot-swap; --partial-fit incrementally
    # refits the candidate head from live labeled batches (LR/NB)
    lifecycle = None
    if lifecycle_armed:
        from sntc_tpu.lifecycle import (
            DriftMonitor,
            LifecycleManager,
            ModelPromoter,
        )

        drift = None
        if args.drift_window > 0:
            drift = DriftMonitor(
                window=args.drift_window,
                threshold=args.drift_threshold,
            ).attach()
        promoter = None
        if args.promote_from or args.partial_fit:
            promoter = ModelPromoter(
                model,
                incumbent_raw=raw_model,
                serving_path=args.model,
                checkpoint_dir=args.checkpoint,
                window=args.shadow_window,
                margin=args.promote_margin,
                label_col="Label",
                labels=labels,
                bucket_rows=args.shape_buckets,
            )
            if args.partial_fit:
                from sntc_tpu.lifecycle import (
                    incremental_estimator_for,
                    terminal_head,
                )

                try:  # fail fast on a head with no partial_fit path
                    incremental_estimator_for(terminal_head(model))
                except ValueError as e:
                    raise SystemExit(f"--partial-fit: {e}")
            if args.promote_from:
                promoter.load_candidate(args.promote_from)
        lifecycle = LifecycleManager(
            drift=drift,
            promoter=promoter,
            partial_fit=args.partial_fit,
            n_classes=len(labels) if labels is not None else None,
        )
    # --from-capture (flow subsystem): the watch directory holds RAW
    # pcap/NetFlow capture files; a stateful keyed-window operator
    # computes the CICIDS2017 flow features live (watermark-driven
    # windows, crash-safe snapshot-at-commit state under
    # <checkpoint>/flow_state) and the emitted feature rows ride the
    # SAME admission → predict → sink path the CSV mode serves.  See
    # docs/RESILIENCE.md "Stateful flow windows".
    # --listen-udp / --listen-tcp (r20): the live network front door.
    # The watch directory becomes the ingress SPOOL: a supervised
    # listener seals socket payloads (NetFlow v5 datagrams over UDP,
    # length-prefixed CSV rows over TCP) into replayable capture files
    # there, and the engine serves the sealed files through the
    # ordinary directory-source machinery — WAL replay, admission, the
    # autotuner and the SLO controller all compose unchanged.  See
    # docs/RESILIENCE.md "Network ingress".
    ingress_listeners = []
    if args.listen_udp is not None or args.listen_tcp is not None:
        from sntc_tpu.serve import ingress as _ingress

        if args.from_capture:
            raise SystemExit(
                "--listen-udp/--listen-tcp spool their own capture "
                "format; drop --from-capture (UDP serves NetFlow v5 "
                "directly)"
            )
        ingress_columns = None
        if args.listen_tcp is not None:
            # framed TCP rows carry VALUES only; the sealed CSV files
            # need a header naming them — the admission contract's
            # column order is the wire contract
            from sntc_tpu.data import CICIDS2017_CONTRACT

            ingress_columns = list(
                (contract or CICIDS2017_CONTRACT).columns
            )
        source, ingress_listeners = _ingress.build_ingress(
            args.watch,
            listen_udp=args.listen_udp,
            listen_tcp=args.listen_tcp,
            spool_mb=args.ingress_spool_mb,
            columns=ingress_columns,
            source_kwargs=dict(
                prefetch_batches=(
                    args.prefetch_batches if pipelined else 0
                ),
                read_workers=args.read_workers,
                parse_salvage=contract is not None,
            ),
        )
    elif args.from_capture:
        from sntc_tpu.flow import FlowCaptureSource

        source = FlowCaptureSource(
            args.watch,
            format=args.from_capture,
            flow_timeout=args.flow_timeout,
            activity_timeout=args.flow_activity_timeout,
            allowed_lateness=args.flow_lateness,
            max_state_packets=args.flow_max_packets,
            state_dir=os.path.join(args.checkpoint, "flow_state"),
            prefetch_batches=(args.prefetch_batches if pipelined else 0),
            read_workers=args.read_workers,
        )
    else:
        source = FileStreamSource(
            args.watch,
            prefetch_batches=(args.prefetch_batches if pipelined else 0),
            read_workers=args.read_workers,
            parse_salvage=contract is not None,
        )
    # closed-loop SLO control (r16): any --slo-* flag declares a
    # setpoint and arms the ServeController over this engine via the
    # supervisor below (--no-controller keeps the knobs at their flag
    # values).  Resolved HERE because the controller OWNS the ingest
    # tuner — one owner per knob, exactly the daemon rule.
    slo = None
    if args.controller and (
        args.slo_p99_ms or args.slo_min_rows_per_sec
        or args.slo_max_shed_rate
    ):
        from sntc_tpu.serve import SloPolicy

        slo = SloPolicy(
            slo_p99_ms=args.slo_p99_ms,
            slo_min_rows_per_sec=args.slo_min_rows_per_sec,
            slo_max_shed_rate=args.slo_max_shed_rate,
        )
    # --autotune: the ingest source graph tunes its own pools/queues
    # (read_workers, prefetch width, pipeline depth) from observed
    # stage latencies, with hysteresis and journaled decisions —
    # tf.data AUTOTUNE for this serve path (docs/PERFORMANCE.md
    # "Autotuned ingest"); the flags above become the cold-start
    # values.  With SLOs declared the CONTROLLER owns the tuner (and
    # pipeline_depth) — an engine-owned tuner alongside it would
    # double-steer the same knobs with two direction histories and
    # defeat the no-oscillation bound.
    autotuner = None
    if args.autotune and slo is None:
        from sntc_tpu.data.autotune import IngestAutotuner

        autotuner = IngestAutotuner()
    # compute-plane fault domain (r18, default armed): device/XLA
    # errors classify and respond per kind — OOM splits the
    # micro-batch, a failed/over-budget compile poisons its signature
    # onto the host fallback, a lost device flips HOST_DEGRADED with
    # probe-gated recovery — instead of riding the generic poison-batch
    # machinery.  The pre-built predictor carries the domain into the
    # engine (and every fused segment).
    if args.device_faults:
        from sntc_tpu.resilience.device import (
            DeviceFaultDomain,
            DevicePolicy,
        )
        from sntc_tpu.serve import BatchPredictor

        model = BatchPredictor(
            model,
            bucket_rows=args.shape_buckets,
            device_domain=DeviceFaultDomain(DevicePolicy(
                compile_budget_s=args.compile_budget_s or None,
            )),
        )
    q = StreamingQuery(
        model,
        source,
        CsvDirSink(args.out, columns=out_cols),
        args.checkpoint,
        max_batch_offsets=args.max_files_per_batch,
        pipeline_depth=args.pipeline_depth,
        shape_buckets=args.shape_buckets,
        overlap_sink=pipelined,
        breakers=default_breakers(),
        retry_policy=(
            RetryPolicy(max_attempts=retries, base_delay_s=0.2, jitter=0.1)
            if retries > 1 else None
        ),
        max_batch_failures=(
            args.max_batch_failures if args.max_batch_failures > 0 else None
        ),
        schema_contract=contract,
        row_dead_letter_dir=args.row_dead_letter,
        lifecycle=lifecycle,
        autotuner=autotuner,
        wal_mode=args.wal_mode,
        wal_compact_every=args.wal_compact_every,
        wal_keep_commits=args.wal_keep_commits,
        dead_letter_keep=args.dead_letter_keep,
    )
    repl_plane = None
    if args.standby_root:
        # warm-standby disaster recovery (r23): ship the checkpoint's
        # durable tree + the sink to the standby root and seal a
        # commit barrier every --repl-barrier-every commits
        from sntc_tpu.resilience.replicate import ReplicationPlane

        repl_plane = ReplicationPlane(
            args.checkpoint, args.standby_root,
            barrier_every=args.repl_barrier_every,
            sink_dir=args.out,
        )
        q.commit_listener = repl_plane.on_commit
    if ingress_listeners:
        from sntc_tpu.serve import ingress as _ingress

        # retention prunes only BELOW the committed horizon, and the
        # listeners go live only once the engine that replays their
        # spool exists
        _ingress.wire_committed_offset(source, q.committed_end)
        for l in ingress_listeners:
            l.start()
    if args.once:
        try:
            with _device_trace_ctx(args):
                n = q.process_available()
                if ingress_listeners:
                    # settle the front door (intake stops, tail seals),
                    # then serve what it sealed — '--once' means the
                    # spool is drained too
                    for l in ingress_listeners:
                        l.drain()
                    n += q.process_available()
                if repl_plane is not None:
                    repl_plane.close()
        finally:
            # publish even when the drain crashed — the partial
            # metrics/trace are the debugging evidence
            _obs_finish(args)
        print(json.dumps({"batches": n}))
        return 0
    # supervised loop: SIGTERM (and Ctrl-C) drains — finish in-flight
    # batches, commit, write drain_marker.json — and exits 0; a restart
    # on the same checkpoint resumes exactly-once from the offset log
    # the controller (slo resolved above, before the autotuner) steers
    # --pipeline-depth / --shape-buckets / the shed knob live and
    # journals every decision to <checkpoint>/controller.jsonl — see
    # docs/RESILIENCE.md "Closed-loop SLO control"
    sup = QuerySupervisor(
        q,
        max_pending_batches=args.max_pending_batches,
        shed_policy=args.shed_policy,
        max_batch_wall_time=args.max_batch_wall_time,
        health_json=args.health_json,
        slo=slo,
        disk_budget_mb=args.disk_budget_mb,
    )
    sup.install_signal_handlers()
    if ingress_listeners:
        # SIGTERM settles the FRONT DOOR first — intake stops and the
        # ring tail seals durably — and only then requests the engine
        # drain, so nothing a sender was acked (the sealed file) can
        # die in listener memory
        import signal as _signal

        def _drain_ingress_then_engine(signum, frame):
            for l in ingress_listeners:
                try:
                    l.drain()
                except Exception:
                    pass
            sup.request_drain("SIGTERM")

        _signal.signal(_signal.SIGTERM, _drain_ingress_then_engine)
    print(f"serving: watching {args.watch} -> {args.out} "
          f"(checkpoint {args.checkpoint}); SIGTERM/Ctrl-C drains",
          file=sys.stderr)
    try:
        with _device_trace_ctx(args):
            status = sup.run(poll_interval=args.poll_interval)
    except KeyboardInterrupt:
        status = sup.drain_now("KeyboardInterrupt")
    finally:
        for l in ingress_listeners:
            try:
                l.close()
            except Exception:
                pass
        if repl_plane is not None:
            repl_plane.close()
        sup.close()  # unsubscribe the health monitor from the event bus
        _obs_finish(args)
    print(json.dumps({
        "batches": status["engine"]["batches_done"],
        "drained": status["drained"],
        "health": status["health"]["overall"],
    }))
    return 0


def cmd_serve_daemon(args) -> int:
    """Multi-tenant serving: N tenant streams (pipeline + source +
    sink + checkpoint + row policy each) multiplexed over one shared
    device program cache with fair scheduling and per-tenant fault
    isolation — see docs/RESILIENCE.md "Multi-tenant serving".

    The tenant file (``--tenants``) is JSON: ``{"tenants": [{"id":
    ..., "model": <checkpoint>, "watch": <in dir>, "out": <out dir>,
    ...}]}`` where every entry may override the daemon-level default
    flags (``weight``, ``max_rows_per_sec``, ``max_pending_batches``,
    ``shed_policy``, ``quarantine_after``, ``quarantine_cooldown_s``,
    ``stop_after``, ``row_policy``, ...).  Tenants naming the SAME
    model checkpoint share one predictor — and therefore one set of
    compiled device programs."""
    from sntc_tpu.serve import ServeDaemon

    _obs_start(args)
    specs = _load_tenant_specs(args)
    daemon = ServeDaemon(
        specs, args.root,
        shape_buckets=args.shape_buckets,
        pipeline_depth=args.pipeline_depth,
        health_json=args.health_json,
        metrics_out=args.metrics_out,
        autotune=args.autotune,
        controller=args.controller,
        disk_budget_mb=args.root_disk_budget_mb,
        dead_letter_keep=args.dead_letter_keep,
        device_faults=args.device_faults,
        compile_budget_s=args.compile_budget_s or None,
        standby_root=args.standby_root,
        repl_barrier_every=args.repl_barrier_every,
    )
    try:
        if args.once:
            with _device_trace_ctx(args):
                n = daemon.process_available()
            # the --once pass IS the warmup; the drain that follows
            # must not compile anything new on the shared cache
            daemon.mark_warm()
            daemon.drain()
            status = daemon.status()
        else:
            daemon.install_signal_handlers()
            print(
                f"serve-daemon: {len(specs)} tenants -> {args.root}; "
                "SIGTERM/Ctrl-C drains every tenant",
                file=sys.stderr,
            )
            try:
                with _device_trace_ctx(args):
                    status = daemon.run(
                        poll_interval=args.poll_interval
                    )
            except KeyboardInterrupt:
                daemon.request_drain("KeyboardInterrupt")
                daemon.drain()
                status = daemon.status()
            n = status["aggregate"]["batches_done"]
    finally:
        daemon.close()
        _obs_finish(args)
    print(json.dumps({
        "batches": n,
        "tenants": {
            tid: row["state"] for tid, row in status["tenants"].items()
        },
        "recompiles_after_warmup": status["recompiles_after_warmup"],
        "drained": status["drained"],
        "health": status["health"]["overall"],
    }))
    return 0


def _load_tenant_specs(args) -> list:
    """The serve-daemon / fleet-serve tenant catalog: parse the
    ``--tenants`` JSON, load + compile each DISTINCT model checkpoint
    once, apply the flag-level defaults, and return the TenantSpec
    list."""
    from sntc_tpu.mlio import load_model
    from sntc_tpu.resilience import RetryPolicy
    from sntc_tpu.serve import TenantSpec

    with open(args.tenants) as f:
        doc = json.load(f)
    entries = doc["tenants"] if isinstance(doc, dict) else doc
    if not entries:
        raise SystemExit(f"{args.tenants}: no tenants declared")
    retries = max(1, args.batch_retry_attempts)
    defaults = {
        "weight": args.tenant_weight,
        "max_rows_per_sec": args.max_rows_per_sec,
        "max_pending_batches": args.max_pending_batches,
        "shed_policy": args.shed_policy,
        "quarantine_after": args.quarantine_after,
        "quarantine_cooldown_s": args.quarantine_cooldown,
        "stop_after": args.stop_after,
        "from_capture": args.from_capture,
        "slo_p99_ms": args.slo_p99_ms,
        "slo_min_rows_per_sec": args.slo_min_rows_per_sec,
        "slo_max_shed_rate": args.slo_max_shed_rate,
        "disk_budget_mb": args.disk_budget_mb,
        "max_batch_offsets": args.max_files_per_batch,
        "max_batch_failures": (
            args.max_batch_failures if args.max_batch_failures > 0
            else None
        ),
        "retry_policy": (
            RetryPolicy(max_attempts=retries, base_delay_s=0.2,
                        jitter=0.1)
            if retries > 1 else None
        ),
        # live network front door (r20): daemon-level listener flags
        # become the default per-tenant ingress block (a tenant's own
        # 'ingress' JSON block replaces it wholesale); port 0 gives
        # every tenant its own ephemeral port, published in its
        # <watch>/ingress_stats.json
        "ingress": (
            {
                "listen_udp": args.listen_udp,
                "listen_tcp": args.listen_tcp,
                "spool_mb": args.ingress_spool_mb,
            }
            if (args.listen_udp is not None
                or args.listen_tcp is not None)
            else None
        ),
    }
    # each distinct checkpoint path loads and compiles ONCE; tenants
    # sharing a path receive the SAME served-model object, which is
    # what makes the daemon share their predictor + compiled programs
    served_by_path = {}

    def _served(path):
        if path not in served_by_path:
            model, _labels, out_cols = _serving_form(
                load_model(path), args.label_index_col, args.fuse
            )
            served_by_path[path] = (model, out_cols)
        return served_by_path[path]

    specs = []
    for entry in entries:
        e = dict(entry)
        path = e.get("model")
        if not isinstance(path, str):
            raise SystemExit(
                f"tenant {e.get('id')!r}: 'model' must be a checkpoint "
                "path"
            )
        model, out_cols = _served(path)
        e["model"] = model
        e.setdefault("out_columns", out_cols)
        policy = e.get("row_policy", None if args.row_policy == "strict"
                       else args.row_policy)
        if policy is not None and policy != "strict":
            from sntc_tpu.data import CICIDS2017_CONTRACT

            e["row_policy"] = policy
            e["schema_contract"] = CICIDS2017_CONTRACT.with_mode(policy)
        else:
            e.pop("row_policy", None)
        specs.append(TenantSpec.from_dict(e, defaults))
    return specs


def cmd_fleet_serve(args) -> int:
    """Elastic serve fleet (r19): ONE coordinator process supervising
    N worker processes, each a plain ServeDaemon over its assigned
    tenant slice.  Placement is consistent hashing over tenant ids
    with the DRR weights as costs; liveness is a filesystem
    lease + heartbeat; a worker whose lease expires is declared dead
    and its tenants migrate (drain -> ship the fsck-verifiable state
    tree -> resume) to the survivors — the SAME first-class migration
    path rebalancing and the controller's ``migrate`` rung use.
    SIGTERM/Ctrl-C raises the fleet drain marker and fans SIGTERM out
    to every worker.  See docs/RESILIENCE.md "Elastic serve fleet".

    Internally re-invoked with ``--fleet-worker-id`` for each worker
    child (same flags, one worker identity)."""
    import itertools
    import signal as _signal
    import subprocess

    from sntc_tpu.serve.fleet import FleetCoordinator, FleetWorker

    if args.fleet_worker_id:
        # ---- worker mode (spawned by the coordinator) ----
        specs = {s.tenant_id: s for s in _load_tenant_specs(args)}
        worker = FleetWorker(
            args.fleet_worker_id, args.root, specs,
            daemon_kwargs=dict(
                shape_buckets=args.shape_buckets,
                pipeline_depth=args.pipeline_depth,
                autotune=args.autotune,
                dead_letter_keep=args.dead_letter_keep,
                device_faults=args.device_faults,
                compile_budget_s=args.compile_budget_s or None,
                standby_root=args.standby_root,
                repl_barrier_every=args.repl_barrier_every,
            ),
            controller=args.controller,
        )
        status = worker.run(poll_interval=args.poll_interval)
        print(json.dumps({
            "worker": args.fleet_worker_id,
            "tenants": {
                tid: row["state"]
                for tid, row in status.get("tenants", {}).items()
            },
        }))
        return 0

    # ---- coordinator mode ----
    _obs_start(args)
    with open(args.tenants) as f:
        doc = json.load(f)
    entries = doc["tenants"] if isinstance(doc, dict) else doc
    if not entries:
        raise SystemExit(f"{args.tenants}: no tenants declared")

    class _PlacementSpec:
        """The coordinator needs placement facts only — it never
        loads a model checkpoint (the workers do)."""

        def __init__(self, entry):
            self.placement_cost = entry.get("placement_cost")
            self.weight = float(entry.get("weight",
                                          args.tenant_weight))
            self.pinned_worker = entry.get("pinned_worker")

    specs = {e["id"]: _PlacementSpec(e) for e in entries}
    worker_ids = (
        args.worker_ids.split(",") if args.worker_ids
        else [f"w{i}" for i in range(args.workers)]
    )
    procs = {}
    child_argv = [sys.executable, "-m", "sntc_tpu"] + sys.argv[1:]

    def _spawn(wid):
        procs[wid] = subprocess.Popen(
            child_argv + ["--fleet-worker-id", wid]
        )

    fresh_ids = itertools.count(len(worker_ids))

    def _scale_out(reason):
        wid = f"w{next(fresh_ids)}"
        _spawn(wid)
        return wid

    coord = FleetCoordinator(
        args.root, worker_ids, specs,
        lease_ttl_s=args.lease_ttl, boot_grace_s=args.boot_grace,
        dead_grace_s=args.dead_grace,
        vnodes=args.vnodes, slack=args.slack,
        scale_out_hook=_scale_out,
        standby_root=args.standby_root,
    )
    stop = {"sig": None}

    def _term(signum, frame):
        stop["sig"] = signum

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            _signal.signal(sig, _term)
        except ValueError:
            pass
    for wid in worker_ids:
        _spawn(wid)
    print(
        f"fleet-serve: coordinator over {len(worker_ids)} workers x "
        f"{len(specs)} tenants -> {args.root}; SIGTERM/Ctrl-C drains "
        "the fleet",
        file=sys.stderr,
    )
    try:
        while stop["sig"] is None:
            coord.tick()
            time.sleep(args.poll_interval)
    finally:
        # the fan-out: raise the fleet drain marker (the workers'
        # loops watch it), then SIGTERM every child and wait
        coord.drain_fleet(f"signal {stop['sig']}")
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + args.drain_timeout
        for p in procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        coord.tick()
        coord.close()
        _obs_finish(args)
    print(json.dumps(coord.status()))
    return 0


def cmd_fsck(args) -> int:
    """The storage doctor (r17): walk a checkpoint root — or a whole
    serve-daemon tenant tree — verify every registered durable
    artifact (WAL logs + sealed compaction checkpoints, JSONL
    journals, flow-state snapshot seals, markers, model-checkpoint
    manifests), repair what is safe (torn JSONL tails truncate with a
    journaled repair record; tmp orphans sweep), quarantine corrupt
    blobs to ``.corrupt/``, and print one machine-readable JSON
    report.  Exit 0 when the tree is (now) clean, 1 when unrepairable
    damage remains.  See docs/RESILIENCE.md "Durable storage
    lifecycle"."""
    from sntc_tpu.resilience.storage import fsck

    if args.fleet_root:
        from sntc_tpu.serve.fleet import fsck_fleet

        report = fsck_fleet(args.root, repair=not args.no_repair)
    else:
        report = fsck(
            args.root,
            repair=not args.no_repair,
            tenant_tree=args.tenant_tree,
        )
    if args.standby:
        # anti-entropy (r23): cross-verify every tenant replica under
        # the standby root against its sealed manifest AND against the
        # primary tree under ROOT; each mismatch journals a
        # replica_diverged and fails the exit code
        from sntc_tpu.resilience.replicate import fsck_standby

        standby_report = fsck_standby(
            args.standby,
            primary_root=args.root,
            repair=not args.no_repair,
        )
        report["standby"] = standby_report
        report["ok"] = report["ok"] and standby_report["ok"]
    if args.compile_cache or args.compile_cache_dir:
        # the persistent XLA compilation cache (r18): quarantine
        # unreadable/zero-length entries to .corrupt/ so serving
        # RECOMPILES a clean miss instead of crashing on a torn
        # executable; rides the same report + exit-code contract
        from sntc_tpu.utils.compile_cache import fsck_compile_cache

        cache_report = fsck_compile_cache(
            args.compile_cache_dir, repair=not args.no_repair,
        )
        report["compile_cache"] = cache_report
        report["ok"] = report["ok"] and cache_report["ok"]
    text = json.dumps(report, indent=1)
    if args.report:
        with open(args.report, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0 if report["ok"] else 1


def cmd_fleet_restore_retired(args) -> int:
    """Recover a retired dead-source tenant tree (r23): fsck-verify
    ``<root>/fleet/retired/<name>`` and copy it into an explicit
    destination directory with a sealed restore manifest — never back
    into the serving namespace.  With no NAME, list what is
    restorable.  Exit 1 when the tree fails verification."""
    from sntc_tpu.serve.fleet import (
        RETIRED_DIR,
        fleet_meta_dir,
        restore_retired,
    )

    rdir = os.path.join(fleet_meta_dir(args.root), RETIRED_DIR)
    if not args.name:
        names = sorted(
            d for d in (os.listdir(rdir) if os.path.isdir(rdir) else [])
            if not d.startswith(".")
        )
        print(json.dumps({"root": args.root, "retired": names}))
        return 0
    if not args.dest:
        raise SystemExit("--dest is required to restore a tree")
    report = restore_retired(
        args.root, args.name, args.dest, repair=not args.no_repair,
    )
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


def cmd_synth(args) -> int:
    from sntc_tpu.data import write_day_csvs

    paths = write_day_csvs(
        args.out, n_rows_per_day=args.rows // args.days, n_days=args.days,
        seed=args.seed,
    )
    print(json.dumps({"files": paths}))
    return 0


def main(argv=None) -> int:
    from sntc_tpu.utils.backend_probe import add_platform_arg

    ap = argparse.ArgumentParser(
        prog="python -m sntc_tpu",
        description=__doc__.split("\n\n")[1],
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--data", required=True,
                       help="directory of CICIDS2017-schema day CSVs")
        p.add_argument("--label-col", default="Label")
        p.add_argument("--binary", action="store_true",
                       help="benign-vs-attack relabel (config 1 [B:7])")
        p.add_argument("--metric", default="macroF1")
        p.add_argument("--seed", type=int, default=0)
        add_platform_arg(p)

    p = sub.add_parser("train", help="fit a pipeline, report held-out metric")
    common(p)
    p.add_argument("--estimator", default="mlp", choices=["lr", "mlp", "rf", "gbt", "dt", "nb", "svc"])
    p.add_argument("--model-out", default=None)
    p.add_argument("--test-fraction", type=float, default=0.2)
    p.add_argument("--max-iter", type=int, default=100)
    p.add_argument("--reg-param", type=float, default=1e-4)
    p.add_argument("--layers", default=TRAIN_DEFAULT_LAYERS)
    p.add_argument("--num-trees", type=int, default=20)
    p.add_argument("--max-depth", type=int, default=5)
    p.add_argument("--step-size", type=float, default=0.1)
    p.add_argument("--max-bins", type=int, default=128)
    p.add_argument("--chisq-top", type=int, default=0,
                   help="if > 0, use ChiSqSelector(k) instead of the scaler")
    p.add_argument("--features-col", default="features")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("evaluate", help="evaluate a saved model on CSVs")
    common(p)
    p.add_argument("--model", required=True)
    p.set_defaults(fn=cmd_evaluate)

    p = sub.add_parser("serve", help="micro-batch streaming inference [B:11]")
    p.add_argument("--model", required=True)
    p.add_argument("--watch", required=True, help="input CSV directory")
    p.add_argument("--out", required=True, help="output CSV directory")
    p.add_argument("--checkpoint", required=True,
                   help="offset/commit WAL directory (exactly-once resume)")
    p.add_argument("--label-index-col", default="label",
                   help="outputCol of the LABEL StringIndexer to strip "
                   "(feature-column indexers are kept)")
    p.add_argument("--max-files-per-batch", type=int, default=None)
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="in-flight micro-batches; > 1 also arms the "
                   "pipelined engine (overlapped sink delivery + source "
                   "prefetch); 1 = fully serial")
    p.add_argument("--shape-buckets", type=int, default=0,
                   help="pad micro-batches up to power-of-two row "
                   "buckets with this floor so the jitted predict "
                   "compiles once per bucket, not once per batch "
                   "shape; 0 = off")
    p.add_argument("--read-workers", type=int, default=4,
                   help="per-file read/parse pool width for multi-file "
                   "micro-batches (the ingest graph's parse-stage "
                   "workers; --autotune resizes it live)")
    p.add_argument("--autotune", action="store_true", dest="autotune",
                   default=False,
                   help="arm the ingest autotuner: resize "
                   "--read-workers / --prefetch-batches / "
                   "--pipeline-depth live from observed stage "
                   "latencies (hysteresis-guarded; every decision "
                   "journaled as autotune_decision events and "
                   "sntc_ingest_* metrics)")
    p.add_argument("--no-autotune", action="store_false", dest="autotune",
                   help="keep the ingest pools at their flag values")
    p.add_argument("--prefetch-batches", type=int, default=2,
                   help="background source reads staged ahead of the "
                   "engine (pipelined mode only); 0 = off")
    p.add_argument("--fuse", action="store_true", dest="fuse", default=True,
                   help="compile the serving pipeline with the whole-"
                   "pipeline fusion compiler: fold the scaler into the "
                   "model and jit each fusible stage run into ONE device "
                   "program (default)")
    p.add_argument("--no-fuse", action="store_false", dest="fuse",
                   help="serve the staged pipeline unfused (stage-by-"
                   "stage transforms; debugging/verification)")
    p.add_argument("--serve-kernels", default=None,
                   choices=["auto", "pallas", "interpret", "off"],
                   help="serving kernel tier (r21): hand-written Pallas "
                   "kernels for the fused hot path behind per-kernel "
                   "fit-guards — auto (pallas on TPU, off elsewhere), "
                   "pallas, interpret (CPU debugging twin), or off "
                   "(pure XLA).  Sets SNTC_SERVE_KERNELS before the "
                   "serving pipeline compiles; unset leaves the "
                   "environment's value in force")
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="drain available files and exit")
    p.add_argument("--health-json", default=None, metavar="PATH",
                   help="atomically rewrite a health/breaker/engine "
                   "status dump here every engine tick")
    p.add_argument("--max-pending-batches", type=int, default=None,
                   help="load-shed when the source backlog exceeds this "
                   "many micro-batches (default: never shed)")
    p.add_argument("--shed-policy", default="oldest",
                   choices=["oldest", "sample"],
                   help="shed the oldest surplus offsets, or process the "
                   "whole backlog row-subsampled (journaled either way)")
    p.add_argument("--max-batch-wall-time", type=float, default=None,
                   metavar="S", help="watchdog: flag a batch running "
                   "longer than this as UNHEALTHY (watchdog_stall event)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="declared p99 batch-latency SLO: arms the "
                   "closed-loop controller, which steers the serving "
                   "knobs (pipeline depth, shape-bucket floor, shed, "
                   "ingest pools) toward it with hysteresis-guarded "
                   "journaled decisions; 0/unset = undeclared")
    p.add_argument("--slo-min-rows-per-sec", type=float, default=None,
                   help="declared throughput-floor SLO (binds while "
                   "the source has backlog); arms the controller "
                   "like --slo-p99-ms; 0/unset = undeclared")
    p.add_argument("--slo-max-shed-rate", type=float, default=None,
                   help="declared bound on the per-window fraction of "
                   "offsets load shedding may drop; arms the "
                   "controller; 0/unset = undeclared")
    p.add_argument("--controller", action="store_true",
                   dest="controller", default=True,
                   help="allow the closed-loop SLO controller (armed "
                   "by any --slo-* flag; decisions journaled to "
                   "<checkpoint>/controller.jsonl) — default")
    p.add_argument("--no-controller", action="store_false",
                   dest="controller",
                   help="keep every serving knob at its flag value "
                   "even when SLOs are declared")
    p.add_argument("--row-policy", default="strict",
                   choices=["strict", "salvage", "permissive"],
                   help="data-plane admission against the canonical "
                   "CICIDS2017 contract: strict = a poison batch fails "
                   "whole (today's behavior); salvage = poison ROWS are "
                   "excised to the row dead-letter and clean rows keep "
                   "serving; permissive = coerce what's coercible "
                   "(numeric strings, non-finite -> 0), then salvage")
    p.add_argument("--row-dead-letter", default=None, metavar="DIR",
                   help="row-level dead-letter directory (default: "
                   "<checkpoint>/dead_letter_rows): one JSONL per "
                   "batch with file/line/raw text/reason per excised "
                   "row")
    p.add_argument("--partial-fit", action="store_true",
                   help="incrementally refit a candidate head (LR/NB "
                   "sufficient-statistic partial_fit) from live "
                   "labeled batches and shadow it for promotion")
    p.add_argument("--drift-window", type=int, default=0, metavar="N",
                   help="arm the drift monitor: Jensen-Shannon "
                   "divergence of the last N committed batches' "
                   "prediction-mix/score histograms against the first "
                   "N (drift_detected event + model DEGRADED on "
                   "breach); 0 = off")
    p.add_argument("--drift-threshold", type=float, default=0.25,
                   help="divergence breach level for --drift-window")
    p.add_argument("--promote-from", default=None, metavar="DIR",
                   help="candidate model checkpoint to shadow-score on "
                   "live batches; promoted (atomic publish over "
                   "--model, incumbent retained at .prev, "
                   "between-batches hot-swap) when its macro-F1 beats "
                   "the incumbent over --shadow-window batches")
    p.add_argument("--shadow-window", type=int, default=8, metavar="N",
                   help="labeled batches the promotion gate averages "
                   "macro-F1 over")
    p.add_argument("--promote-margin", type=float, default=0.05,
                   help="macro-F1 lead the candidate must hold over "
                   "the incumbent to promote; with --partial-fit the "
                   "candidate is a refit of the incumbent, so refit "
                   "jitter re-promotes every window at margin 0")
    p.add_argument("--wal-mode", default="files",
                   choices=["files", "append"],
                   help="WAL format under --checkpoint: 'files' (one "
                   "json per intent/commit) or 'append' (one flushed "
                   "JSONL log per side — the high-throughput WAL, "
                   "compacted per --wal-compact-every)")
    p.add_argument("--wal-compact-every", type=int, default=256,
                   metavar="N",
                   help="append-WAL compaction interval in commits: "
                   "seal a wal_checkpoint.json and truncate the logs "
                   "every N commits (replay = checkpoint + tail); "
                   "0 = never compact")
    p.add_argument("--wal-keep-commits", type=int, default=64,
                   metavar="N",
                   help="files-WAL retention: committed intent/commit "
                   "pairs older than the last N are pruned; 0 = keep "
                   "forever")
    p.add_argument("--dead-letter-keep", type=int, default=200,
                   metavar="N",
                   help="dead-letter retention: keep the newest N "
                   "evidence files per dead-letter dir, drop the "
                   "oldest with a counted dead_letter_dropped; "
                   "0 = unbounded")
    p.add_argument("--disk-budget-mb", type=float, default=None,
                   metavar="MB",
                   help="byte budget for the checkpoint root: usage "
                   "is measured into sntc_disk_* gauges each tick and "
                   "a breach emits disk_budget_exceeded (DEGRADED "
                   "health); unset = measure only")
    p.add_argument("--batch-retry-attempts", type=int, default=2,
                   help="in-place attempts per read/sink stage before a "
                   "round counts as failed (1 = no retry)")
    p.add_argument("--max-batch-failures", type=int, default=3,
                   help="failed rounds before a poison batch is "
                   "dead-lettered and committed; 0 = first failure "
                   "kills the query (pre-r6 semantics)")
    p.add_argument("--from-capture", default=None,
                   choices=["pcap", "netflow"],
                   help="serve RAW captures: --watch holds pcap/.nf5 "
                   "capture files and a stateful keyed-window operator "
                   "computes the CICIDS2017 flow features live "
                   "(crash-safe state under <checkpoint>/flow_state); "
                   "unset = the default precomputed-CSV mode")
    p.add_argument("--flow-timeout", type=float, default=120.0,
                   metavar="S",
                   help="session-window quiet gap: a flow idle longer "
                   "than this (behind the watermark) is COMPLETE and "
                   "its feature row emits (CICFlowMeter's flow "
                   "timeout)")
    p.add_argument("--flow-activity-timeout", type=float, default=5.0,
                   metavar="S",
                   help="Active/Idle split gap inside a flow window "
                   "(CICFlowMeter's activity timeout; pcap only)")
    p.add_argument("--flow-lateness", type=float, default=5.0,
                   metavar="S",
                   help="allowed event-time lateness: the watermark "
                   "trails the max seen timestamp by this much; "
                   "records behind the watermark drop with reason "
                   "late_record (journaled, counted)")
    p.add_argument("--flow-max-packets", type=int, default=500_000,
                   help="hard cap on buffered records across all open "
                   "windows: beyond it the oldest flows force-evict "
                   "early (reason state_cap) so operator state stays "
                   "bounded under any replay")
    p.add_argument("--device-faults", action="store_true",
                   dest="device_faults", default=True,
                   help="arm the compute-plane fault domain: classify "
                   "device/XLA errors (OOM / compile / device lost) "
                   "and respond per kind — OOM-adaptive batch "
                   "splitting, per-signature compile poisoning with "
                   "host fallback, HOST_DEGRADED with probe-gated "
                   "recovery (default)")
    p.add_argument("--no-device-faults", action="store_false",
                   dest="device_faults",
                   help="pre-r18 behavior: device errors raise through "
                   "the generic retry/quarantine machinery")
    p.add_argument("--compile-budget-s", type=float, default=30.0,
                   metavar="S",
                   help="per-signature compile wall-time watchdog: a "
                   "fused-program compile exceeding this poisons that "
                   "(segment, signature) and serves it through the "
                   "eager host fallback; 0 = unarmed")
    p.add_argument("--listen-udp", type=int, default=None, metavar="PORT",
                   help="live network front door: bind a supervised "
                   "UDP listener for NetFlow v5 datagrams; --watch "
                   "becomes the ingress SPOOL the listener seals "
                   "replayable capture files into (0 = ephemeral "
                   "port, published in <watch>/ingress_stats.json); "
                   "loss is counted, never silent — see "
                   "docs/RESILIENCE.md 'Network ingress'")
    p.add_argument("--listen-tcp", type=int, default=None, metavar="PORT",
                   help="live network front door: bind a framed TCP "
                   "row listener (4-byte big-endian length + one CSV "
                   "row per frame); --watch becomes the ingress "
                   "spool; torn frames quarantine, over-budget spool "
                   "pauses reads (sender backpressure)")
    p.add_argument("--ingress-spool-mb", type=float, default=None,
                   metavar="MB",
                   help="ingress spool byte budget: TCP pauses reads "
                   "over it, UDP sheds at ingress (counted "
                   "spool_over_budget) after a committed-file prune "
                   "— bounded disk instead of ENOSPC death; unset = "
                   "unbudgeted")
    p.add_argument("--standby-root", default=None, metavar="DIR",
                   help="warm-standby disaster recovery (r23): "
                   "continuously replicate the checkpoint's durable "
                   "artifact tree (+ the sink) to <DIR>/default/ with "
                   "sealed manifests and commit barriers, so a lost "
                   "primary disk promotes from the replica with "
                   "measured RPO/RTO — see docs/RESILIENCE.md "
                   "'Disaster recovery'; unset = no replication")
    p.add_argument("--repl-barrier-every", type=int, default=1,
                   metavar="N",
                   help="seal a replication commit barrier every N "
                   "engine commits (ReplicationPlane barrier_every): "
                   "1 = every commit (tightest RPO), larger trades "
                   "barrier lag for ship amortization")
    _add_obs_flags(p)
    add_platform_arg(p)
    p.set_defaults(fn=cmd_serve)

    # flags shared by serve-daemon and fleet-serve (the fleet workers
    # are plain serve daemons, so the whole daemon surface forwards)
    p = daemon_flags = argparse.ArgumentParser(add_help=False)
    p.add_argument("--tenants", required=True, metavar="JSON",
                   help="tenant spec file: {\"tenants\": [{\"id\", "
                   "\"model\", \"watch\", \"out\", ...per-tenant "
                   "overrides}]}")
    p.add_argument("--root", required=True,
                   help="daemon root: per-tenant checkpoints/WALs/"
                   "dead-letters land under <root>/tenant/<id>/")
    p.add_argument("--label-index-col", default="label")
    p.add_argument("--max-files-per-batch", type=int, default=1,
                   help="micro-batch size in source files, per tenant "
                   "(TenantSpec max_batch_offsets)")
    p.add_argument("--pipeline-depth", type=int, default=1,
                   help="per-tenant in-flight micro-batches; > 1 arms "
                   "each tenant's overlapped sink delivery")
    p.add_argument("--shape-buckets", type=int, default=0,
                   help="power-of-two row bucketing for the SHARED "
                   "predictors (compile once per bucket across all "
                   "tenants of a pipeline); 0 = off")
    p.add_argument("--fuse", action="store_true", dest="fuse",
                   default=True,
                   help="compile each distinct tenant pipeline with the "
                   "whole-pipeline fusion compiler (default)")
    p.add_argument("--no-fuse", action="store_false", dest="fuse")
    p.add_argument("--autotune", action="store_true", dest="autotune",
                   default=False,
                   help="arm per-tenant ingest autotuners drawing from "
                   "ONE shared tuning budget (total extra parse "
                   "threads / staged ranges / pipeline slots capped "
                   "across the fleet)")
    p.add_argument("--no-autotune", action="store_false",
                   dest="autotune")
    p.add_argument("--tenant-weight", type=float, default=1.0,
                   help="default fair-share weight (TenantSpec weight): "
                   "deficit round-robin credits per scheduling round")
    p.add_argument("--max-rows-per-sec", type=float, default=None,
                   help="default per-tenant admission rate quota "
                   "(TenantSpec max_rows_per_sec): a token bucket "
                   "charged at commit throttles a flooding tenant at "
                   "its own edge; unset = unlimited")
    p.add_argument("--max-pending-batches", type=int, default=None,
                   help="default per-tenant backlog cap (TenantSpec "
                   "max_pending_batches): surplus is shed through the "
                   "tenant's own journaled shed path")
    p.add_argument("--shed-policy", default="oldest",
                   choices=["oldest", "sample"],
                   help="default per-tenant shed policy (TenantSpec "
                   "shed_policy)")
    p.add_argument("--quarantine-after", type=int, default=3,
                   help="unhealthy strikes (quarantine/retry_exhausted/"
                   "breaker_open events tagged with the tenant) before "
                   "the tenant is QUARANTINED (TenantSpec "
                   "quarantine_after)")
    p.add_argument("--quarantine-cooldown", type=float, default=30.0,
                   metavar="S",
                   help="seconds a QUARANTINED tenant holds before "
                   "probation back to OK (TenantSpec "
                   "quarantine_cooldown_s)")
    p.add_argument("--stop-after", type=int, default=3,
                   help="quarantine episodes before the tenant is "
                   "STOPPED and its breakers evicted (TenantSpec "
                   "stop_after)")
    p.add_argument("--row-policy", default="strict",
                   choices=["strict", "salvage", "permissive"],
                   help="default per-tenant data-plane admission "
                   "(TenantSpec row_policy) against the canonical "
                   "CICIDS2017 contract")
    p.add_argument("--from-capture", default=None,
                   choices=["pcap", "netflow"],
                   help="default per-tenant raw-capture mode "
                   "(TenantSpec from_capture): tenants' watch dirs "
                   "hold capture files and each tenant runs its own "
                   "stateful flow-window operator (state under "
                   "tenant/<id>/ckpt/flow_state); per-tenant "
                   "'flow_options' in the tenants JSON tunes the "
                   "window knobs")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="default per-tenant p99 latency SLO "
                   "(TenantSpec slo_p99_ms; per-tenant JSON "
                   "overrides); the --controller setpoint; "
                   "0/unset = undeclared")
    p.add_argument("--slo-min-rows-per-sec", type=float, default=None,
                   help="default per-tenant throughput-floor SLO "
                   "(TenantSpec slo_min_rows_per_sec); 0/unset = "
                   "undeclared")
    p.add_argument("--slo-max-shed-rate", type=float, default=None,
                   help="default per-tenant shed-rate SLO bound "
                   "(TenantSpec slo_max_shed_rate, a fraction in "
                   "(0, 1]); 0/unset = undeclared")
    p.add_argument("--controller", action="store_true",
                   dest="controller", default=False,
                   help="arm the closed-loop SLO controller: one "
                   "guarded knob step per window toward the declared "
                   "per-tenant SLOs (protect compliant tenants, "
                   "degrade the violator throttle->shed->escalate), "
                   "owning the per-tenant ingest tuners; decisions "
                   "journaled to <root>/controller.jsonl")
    p.add_argument("--no-controller", action="store_false",
                   dest="controller",
                   help="keep every serving knob at its flag value")
    p.add_argument("--batch-retry-attempts", type=int, default=2)
    p.add_argument("--max-batch-failures", type=int, default=3,
                   help="default per-tenant poison-batch threshold "
                   "(TenantSpec max_batch_failures); 0 = first failure "
                   "surfaces (and strikes the tenant)")
    p.add_argument("--disk-budget-mb", type=float, default=None,
                   metavar="MB",
                   help="default per-tenant disk byte budget "
                   "(TenantSpec disk_budget_mb): the tenant/<id>/ "
                   "subtree is measured into sntc_disk_bytes{tenant=} "
                   "each round and a breach degrades THAT tenant's "
                   "health; 0/unset = measure only")
    p.add_argument("--root-disk-budget-mb", type=float, default=None,
                   metavar="MB",
                   help="global disk byte budget for the whole daemon "
                   "root (all tenants + shared journals)")
    p.add_argument("--dead-letter-keep", type=int, default=200,
                   metavar="N",
                   help="per-tenant dead-letter retention: keep the "
                   "newest N evidence files per dead-letter dir "
                   "(counted dead_letter_dropped); 0 = unbounded")
    p.add_argument("--device-faults", action="store_true",
                   dest="device_faults", default=True,
                   help="arm ONE compute-plane fault domain shared by "
                   "every tenant's predictor (tenants share the "
                   "physical device): device/XLA errors respond per "
                   "kind and never strike a tenant's ladder (default)")
    p.add_argument("--no-device-faults", action="store_false",
                   dest="device_faults",
                   help="pre-r18 behavior: device errors ride the "
                   "generic per-tenant retry/quarantine machinery")
    p.add_argument("--compile-budget-s", type=float, default=30.0,
                   metavar="S",
                   help="per-signature compile wall-time watchdog for "
                   "the shared predictors (see serve --compile-"
                   "budget-s); 0 = unarmed")
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="drain available files across all tenants and "
                   "exit")
    p.add_argument("--health-json", default=None, metavar="PATH",
                   help="atomically rewrite the daemon status dump "
                   "(per-tenant states, compile ledger, health, "
                   "breakers) here every scheduling round")
    p.add_argument("--listen-udp", type=int, default=None, metavar="PORT",
                   help="default per-tenant UDP ingress (TenantSpec "
                   "ingress): each tenant's watch dir becomes its own "
                   "ingress spool behind a supervised NetFlow v5 "
                   "listener — use 0 (ephemeral, published in "
                   "<watch>/ingress_stats.json) so tenants never "
                   "collide on a port; per-tenant 'ingress' JSON "
                   "blocks override")
    p.add_argument("--listen-tcp", type=int, default=None, metavar="PORT",
                   help="default per-tenant framed-TCP row ingress "
                   "(TenantSpec ingress); 0 = ephemeral per tenant, "
                   "published in the tenant's ingress_stats.json")
    p.add_argument("--ingress-spool-mb", type=float, default=None,
                   metavar="MB",
                   help="default per-tenant ingress spool byte budget "
                   "(TenantSpec ingress spool_mb): over it TCP pauses "
                   "reads and UDP sheds at ingress, counted — never "
                   "ENOSPC death")
    p.add_argument("--standby-root", default=None, metavar="DIR",
                   help="warm-standby disaster recovery (r23): every "
                   "tenant's durable tree (+ sink) replicates to "
                   "<DIR>/<tenant>/ with sealed manifests and commit "
                   "barriers; a fleet coordinator also prefers "
                   "replica-restore when a dead worker's primary tree "
                   "cannot ship — see docs/RESILIENCE.md 'Disaster "
                   "recovery'")
    p.add_argument("--repl-barrier-every", type=int, default=1,
                   metavar="N",
                   help="seal a replication commit barrier every N "
                   "commits per tenant (ReplicationPlane "
                   "barrier_every); 1 = tightest RPO")
    _add_obs_flags(p)
    add_platform_arg(p)

    p = sub.add_parser(
        "serve-daemon",
        parents=[daemon_flags],
        help="multi-tenant streaming inference: N tenant streams, one "
        "shared device program cache, fair scheduling, per-tenant "
        "isolation (docs/RESILIENCE.md)",
    )
    p.set_defaults(fn=cmd_serve_daemon)

    p = sub.add_parser(
        "fleet-serve",
        parents=[daemon_flags],
        help="elastic serve fleet: one coordinator process supervising "
        "N serve-daemon workers with leases, consistent-hash "
        "placement, worker-death recovery, and first-class tenant "
        "migration (docs/RESILIENCE.md)",
    )
    p.add_argument("--workers", type=int, default=2, metavar="N",
                   help="worker processes to spawn (ids w0..wN-1); "
                   "each runs a plain ServeDaemon over its assigned "
                   "tenant slice under <root>/worker/<id>/")
    p.add_argument("--worker-ids", default=None, metavar="IDS",
                   help="explicit comma-separated worker ids "
                   "(overrides --workers; the ids TenantSpec "
                   "pinned_worker entries must name)")
    p.add_argument("--lease-ttl", type=float, default=5.0, metavar="S",
                   help="worker lease TTL (FleetCoordinator "
                   "lease_ttl_s): a worker whose heartbeat marker is "
                   "older is declared DEAD and its tenants migrate to "
                   "the survivors")
    p.add_argument("--boot-grace", type=float, default=30.0,
                   metavar="S",
                   help="first-heartbeat grace (FleetCoordinator "
                   "boot_grace_s): how long a spawned worker may take "
                   "to come up before it counts as dead")
    p.add_argument("--dead-grace", type=float, default=None,
                   metavar="S",
                   help="ship fence (FleetCoordinator dead_grace_s): "
                   "a dead worker's tenant trees only ship after its "
                   "lease stays expired this much LONGER, with a final "
                   "lease re-read — a slow-but-alive worker gets the "
                   "window to renew (default: 2 x lease TTL)")
    p.add_argument("--vnodes", type=int, default=64,
                   help="virtual nodes per worker on the consistent-"
                   "hash ring (FleetCoordinator vnodes)")
    p.add_argument("--slack", type=float, default=1.25,
                   help="bounded-load placement slack (FleetCoordinator "
                   "slack): per-worker capacity = slack x total "
                   "placement cost / workers")
    p.add_argument("--drain-timeout", type=float, default=60.0,
                   metavar="S",
                   help="seconds to wait for workers to settle after "
                   "the SIGTERM fan-out before killing them")
    p.add_argument("--fleet-worker-id", default=None,
                   help="internal: run as the named fleet WORKER "
                   "instead of the coordinator (the coordinator "
                   "re-invokes itself with this flag per worker)")
    p.set_defaults(fn=cmd_fleet_serve)

    p = sub.add_parser(
        "fsck",
        help="verify + repair every durable artifact under a "
        "checkpoint root (WAL seals/tails, journals, flow-state "
        "snapshots, markers, model manifests); machine-readable "
        "report; exit 1 when unrepairable damage remains",
    )
    p.add_argument("root", help="checkpoint root to doctor (a serve "
                   "--checkpoint dir, or a serve-daemon --root with "
                   "--tenant-tree)")
    p.add_argument("--tenant-tree", action="store_true",
                   help="also walk every <root>/tenant/<id>/ckpt "
                   "(the serve-daemon layout)")
    p.add_argument("--fleet-root", action="store_true",
                   help="treat ROOT as an elastic-fleet coordinator "
                   "root: doctor the fleet metadata (assignment "
                   "marker + journal, leases, request journals, "
                   "sealed migration manifests, torn mid-ship "
                   "copies) plus every <root>/worker/<id>/ daemon "
                   "tree; an unrepairable migration manifest exits 1")
    p.add_argument("--no-repair", action="store_true",
                   help="report only: no truncations, no quarantines, "
                   "no tmp sweeps")
    p.add_argument("--compile-cache", action="store_true",
                   help="also doctor the persistent XLA compilation "
                   "cache (the dir enable_persistent_cache manages, "
                   "from JAX_COMPILATION_CACHE_DIR / the default "
                   "base): zero-length/unreadable entries quarantine "
                   "to .corrupt/ so serving recompiles instead of "
                   "crashing; tmp orphans sweep")
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="explicit compilation-cache directory to "
                   "doctor (implies --compile-cache)")
    p.add_argument("--standby", default=None, metavar="DIR",
                   help="anti-entropy (r23): also cross-verify every "
                   "tenant replica under this warm-standby root — "
                   "sealed manifest, replica content hashes, and "
                   "primary-vs-replica for files both sides hold; "
                   "each divergence journals replica_diverged and "
                   "exits 1 (with repair, the diverged replica copy "
                   "quarantines so the next ship re-seeds it)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="also write the JSON report here")
    add_platform_arg(p)
    p.set_defaults(fn=cmd_fsck)

    p = sub.add_parser(
        "fleet-restore-retired",
        help="recover a retired dead-source tenant tree "
        "(fleet/retired/<tid>.<wid>.<epoch>): fsck-verify and copy it "
        "into an explicit --dest with a sealed restore manifest; no "
        "NAME lists what is restorable",
    )
    p.add_argument("root", help="fleet coordinator root")
    p.add_argument("name", nargs="?", default=None,
                   help="retired tree name (<tid>.<wid>.<epoch>); "
                   "omit to list")
    p.add_argument("--dest", default=None, metavar="DIR",
                   help="destination directory for the verified copy "
                   "(required with NAME; never the serving namespace)")
    p.add_argument("--no-repair", action="store_true",
                   help="verify only: no torn-tail truncations inside "
                   "the retired tree")
    add_platform_arg(p)
    p.set_defaults(fn=cmd_fleet_restore_retired)

    p = sub.add_parser("synth", help="write schema-identical synthetic day CSVs")
    p.add_argument("--out", required=True)
    p.add_argument("--rows", type=int, default=80_000)
    p.add_argument("--days", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_synth)

    args = ap.parse_args(argv)
    # backend liveness: the default platform is a remote TPU tunnel
    # that can hang forever inside jax.devices() when down — probe it
    # from a killable subprocess and fall back to CPU rather than hang
    # the user's terminal (--platform skips the probe; synth is
    # numpy-only and needs neither)
    if args.cmd != "synth":
        from sntc_tpu.utils.backend_probe import resolve_platform

        platform = resolve_platform(getattr(args, "platform", None))
        if platform:
            import jax

            jax.config.update("jax_platforms", platform)
    # Spark pays no per-process compile; neither should a CLI user on
    # their second run (SURVEY.md §3.5 cold-start — docs/PARITY.md)
    from sntc_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    # every CLI gets the metrics plane: the event→metrics bridge folds
    # whatever the command emits (engines install it themselves, but
    # train/evaluate emit too — CV retries, checkpoint fallbacks)
    from sntc_tpu.obs import install_event_metrics

    install_event_metrics()
    return args.fn(args)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
