"""Event-stream → metrics bridge: the consolidation glue.

Every resilience/lifecycle/tenancy subsystem already narrates itself
through ``sntc_tpu.resilience.emit_event`` — retries, breaker
transitions, quarantines, load sheds, rejected rows, drift episodes,
health changes, fault injections, tenant ladder moves.  Instead of
teaching each emitter about the registry, ONE observer folds the whole
stream into named metrics:

* every event counts into ``sntc_events_total{event, site, tenant}``
  (tenant-namespaced sites are split: ``tenant/a/sink.write`` becomes
  ``site="sink.write", tenant="a"`` so series stay low-cardinality and
  tenant-aggregable);
* events carrying quantities get dedicated counters — ``rows_rejected``
  reasons, ``load_shed`` offsets, ``quarantine`` batches.

The observer NEVER raises (``emit_event`` evicts raising observers, and
losing the metrics plane to one malformed record would be worse than
missing the record): internal failures are counted on the bridge and
inspectable via :func:`bridge_errors`.
"""

from __future__ import annotations

import threading
from typing import Any, Dict

from sntc_tpu.obs.metrics import inc

_installed = False
_install_lock = threading.Lock()
_errors = 0


def split_tenant_site(record: Dict[str, Any]):
    """(site, tenant) for one event record: the explicit ``tenant``
    field wins; a ``tenant/<id>/<site>`` site is split so the bare site
    name and the tenant label stay separately aggregable."""
    site = record.get("site") or ""
    tenant = record.get("tenant") or ""
    if isinstance(site, str) and site.startswith("tenant/"):
        parts = site.split("/", 2)
        if len(parts) == 3:
            tenant = tenant or parts[1]
            site = parts[2]
    return site, tenant


def _observe(record: Dict[str, Any]) -> None:
    global _errors
    try:
        event = record.get("event")
        if not event:
            return
        site, tenant = split_tenant_site(record)
        labels: Dict[str, str] = {"event": str(event)}
        if site:
            labels["site"] = str(site)
        if tenant:
            labels["tenant"] = str(tenant)
        inc("sntc_events_total", 1, **labels)
        tlabel = {"tenant": str(tenant)} if tenant else {}
        if event == "rows_rejected":
            reasons = record.get("reasons")
            if isinstance(reasons, dict) and reasons:
                for reason, n in reasons.items():
                    inc(
                        "sntc_rows_rejected_total", int(n),
                        reason=str(reason), **tlabel,
                    )
            else:
                inc(
                    "sntc_rows_rejected_total",
                    int(record.get("count") or 0),
                    reason="unknown", **tlabel,
                )
        elif event == "load_shed":
            inc(
                "sntc_shed_offsets_total",
                int(record.get("offsets_shed") or 0), **tlabel,
            )
        elif event == "quarantine":
            inc("sntc_batches_quarantined_total", 1, **tlabel)
    except Exception:
        _errors += 1


def bridge_errors() -> int:
    """Records the bridge failed to fold (malformed payloads) — the
    bridge swallows them so ``emit_event`` never evicts it."""
    return _errors


def install_event_metrics() -> bool:
    """Subscribe the bridge to the process event stream (idempotent;
    returns True when this call did the install).  Called by every
    entry point that starts emitting — engine/daemon construction, the
    CLIs, bench — so ad-hoc embedders get the metrics plane without
    asking for it."""
    global _installed
    with _install_lock:
        if _installed:
            return False
        from sntc_tpu.resilience.policy import add_event_observer

        add_event_observer(_observe)
        _installed = True
        return True
