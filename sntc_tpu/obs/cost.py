"""MFU/roofline evidence plane (r21).

PR 4's fusion planner grew an opt-in ``SNTC_OBS_COST_ANALYSIS`` hook
that stashed XLA's own per-program FLOPs/bytes estimate next to each
compiled signature.  This module promotes that hook into a shared
plane: :func:`extract` pulls the cost estimate from any compiled jit
program, and :func:`roofline` combines it with measured wall time and
the probed peaks (``utils.backend_probe.probed_peaks``) into
achieved-vs-peak numbers —

    achieved FLOP/s  = flops x invocations / seconds
    MFU              = achieved FLOP/s / peak FLOP/s
    BW utilization   = achieved bytes/s / peak bytes/s
    arithmetic intensity = flops / bytes accessed

surfaced three ways: the catalogued ``sntc_mfu_*`` gauges (per serving
segment), the ``roofline`` block of ``fuse.fusion_stats()``, and
``bench.py --mfu`` / bench config 16's per-segment evidence.  Every
number carries the peaks' ``peak_source`` (datasheet / estimate / env)
so a CPU MFU is never mistaken for a measured-chip figure.

The hook stays opt-in: extraction forces an eager compile and the
dispatch timing adds a clock read per batch, so the planner only pays
for either when ``SNTC_OBS_COST_ANALYSIS`` is set.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

#: the cost_analysis() keys worth keeping (XLA emits dozens)
_KEYS = ("flops", "bytes accessed", "transcendentals")


def enabled() -> bool:
    """True when the opt-in cost/roofline plane is armed."""
    return bool(os.environ.get("SNTC_OBS_COST_ANALYSIS"))


def extract(prog, args) -> Optional[Dict[str, float]]:
    """XLA's FLOPs/bytes estimate for ``prog`` lowered at ``args`` —
    the planner hook's body, shared.  Returns ``None`` when the
    backend offers no cost analysis (some platforms don't)."""
    try:
        cost = prog.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return {
            k: float(v)
            for k, v in dict(cost or {}).items()
            if isinstance(v, (int, float)) and k in _KEYS
        }
    except Exception:
        return None


def roofline(
    cost: Optional[Dict[str, float]],
    seconds: float = 0.0,
    invocations: int = 0,
    platform: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """Achieved-vs-peak accounting for one compiled program.

    ``cost`` is an :func:`extract` result; ``seconds`` is total
    measured wall time across ``invocations`` dispatches of it.  With
    no timing yet (warmup) the static quantities — FLOPs, bytes,
    arithmetic intensity, peaks — still report; the achieved/MFU
    fields appear once there is a nonzero measurement."""
    if not cost:
        return None
    from sntc_tpu.utils.backend_probe import probed_peaks

    peaks = probed_peaks(platform)
    flops = float(cost.get("flops", 0.0))
    nbytes = float(cost.get("bytes accessed", 0.0))
    out: Dict[str, Any] = {
        "flops": flops,
        "bytes_accessed": nbytes,
        "arithmetic_intensity": (flops / nbytes) if nbytes else None,
        "peak_flops": peaks["flops"],
        "peak_bw": peaks["bw"],
        "peak_source": peaks["peak_source"],
        "platform": peaks["platform"],
        "invocations": int(invocations),
        "seconds": float(seconds),
    }
    if seconds > 0 and invocations > 0:
        achieved_flops = flops * invocations / seconds
        achieved_bw = nbytes * invocations / seconds
        out["achieved_flops"] = achieved_flops
        out["achieved_bw"] = achieved_bw
        out["mfu"] = achieved_flops / peaks["flops"]
        out["bw_util"] = achieved_bw / peaks["bw"]
    return out


def emit_mfu(segment: int, roof: Optional[Dict[str, Any]]) -> None:
    """Publish one segment's roofline onto the catalogued gauges
    (``sntc_mfu_ratio`` / ``sntc_mfu_bw_ratio``, labeled by segment)."""
    if not roof or "mfu" not in roof:
        return
    from sntc_tpu.obs.metrics import set_gauge

    seg = str(segment)
    set_gauge("sntc_mfu_ratio", roof["mfu"], segment=seg)
    set_gauge("sntc_mfu_bw_ratio", roof["bw_util"], segment=seg)
