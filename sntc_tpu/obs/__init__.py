"""Unified telemetry substrate — one metrics/trace/event plane.

Seven PRs of infrastructure each grew an ad-hoc ledger: the engine's
``_emit`` event stream, the compile ledger (``compile_events`` /
``recompiles_after_warmup``), the process ``TransferLedger``,
``prefetch_stats()``, the shed journal, and ``HealthMonitor.snapshot``.
This package is the single plane they all land on:

* :mod:`sntc_tpu.obs.metrics` — a process-wide :class:`MetricsRegistry`
  (counters, gauges, fixed-bucket histograms; label support including
  ``tenant=<id>``) with lock-free-on-read snapshots, Prometheus-text
  and JSONL exposition, and injectable clocks for deterministic tests.
* :mod:`sntc_tpu.obs.trace` — a span tracer (``obs.span("stage",
  **attrs)``) recording wall+monotonic intervals on a ring buffer and
  exporting Chrome-trace/Perfetto JSON; ``jax.profiler`` /
  compiled-program cost-analysis hooks behind flags so device time can
  be correlated with the host spans.
* :mod:`sntc_tpu.obs.bridge` — the consolidation glue: an event-stream
  observer folding every structured resilience event (retry, breaker,
  shed, quarantine, drift, health transitions, fault injections) into
  named registry metrics, so the EXISTING emitters need no changes and
  the existing APIs (``transfer_ledger()``, ``recompiles_after_
  warmup()``, ``events_dropped()``) remain thin views over the same
  numbers.

Metric names, label conventions, and the trace-viewer howto live in
``docs/OBSERVABILITY.md``; ``scripts/check_metric_names.py`` pins the
code ⇔ catalog ⇔ docs mapping in tier-1.

This package imports only the standard library at import time, so every
layer (resilience, serve, fuse, utils) can depend on it without cycles.
"""

from sntc_tpu.obs.bridge import install_event_metrics
from sntc_tpu.obs.metrics import (
    CATALOG,
    MetricsRegistry,
    inc,
    observe,
    registry,
    reset_registry,
    set_gauge,
    set_registry,
)
from sntc_tpu.obs.trace import (
    SpanTracer,
    device_trace,
    disable_tracing,
    enable_tracing,
    span,
    tracer,
    tracing_enabled,
)

__all__ = [
    "CATALOG",
    "MetricsRegistry",
    "registry",
    "set_registry",
    "reset_registry",
    "inc",
    "set_gauge",
    "observe",
    "SpanTracer",
    "span",
    "tracer",
    "enable_tracing",
    "disable_tracing",
    "tracing_enabled",
    "device_trace",
    "install_event_metrics",
]
