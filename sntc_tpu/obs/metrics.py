"""Process-wide metrics registry: counters, gauges, fixed-bucket
histograms, labels, Prometheus/JSONL exposition.

Design constraints, in priority order:

1. **Hot-path writes are cheap** — one dict lookup + one small lock per
   increment.  The streaming engine calls :func:`inc` per micro-batch
   (not per row), so the registry never shows up in a profile; bench
   config 5 pins the whole substrate's overhead at ≤ 5% rows/s
   (docs/OBSERVABILITY.md has the measured numbers).
2. **Snapshots never block writers** — :meth:`MetricsRegistry.snapshot`
   reads live series values without taking the write locks (CPython
   makes each individual read atomic); a snapshot taken mid-increment
   may be one tick stale on one series, never torn across the registry.
3. **Bounded cardinality** — every metric holds at most
   ``max_label_sets`` distinct label sets; beyond the cap, writes to
   any further label set collapse into a reserved ``overflow="true"``
   series and each such write is counted (:meth:`label_overflows`),
   never silent.  A misbehaving label (a batch id, a file path)
   degrades the one metric, not the process.
4. **Deterministic in tests** — wall/monotonic clocks are injectable
   per registry, so JSONL exposition records are assertable exactly.

Every metric this codebase emits is declared in :data:`CATALOG` (name →
type/help/labels/buckets) — the single source of truth that
``docs/OBSERVABILITY.md`` documents and ``scripts/check_metric_names
.py`` drift-checks against the code in tier-1.  Undeclared names are
rejected: an unregistered metric is exactly the ad-hoc-ledger drift
this package exists to end.
"""

from __future__ import annotations

import json
import os
import threading
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# seconds; covers sub-ms device dispatches through multi-second batches
LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0,
)

#: THE metric catalog: every name the codebase may emit, with its type,
#: allowed labels, and help text.  ``scripts/check_metric_names.py``
#: pins code ⇔ CATALOG ⇔ docs/OBSERVABILITY.md in tier-1.
CATALOG: Dict[str, Dict[str, Any]] = {
    # -- the structured event stream (obs.bridge) -------------------------
    "sntc_events_total": dict(
        type=COUNTER, labels=("event", "site", "tenant"),
        help="Structured resilience/lifecycle events by name, site, "
        "and tenant (the _emit/emit_event stream, consolidated).",
    ),
    "sntc_events_dropped_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Event-ring evictions (legacy view: events_dropped()).",
    ),
    "sntc_rows_rejected_total": dict(
        type=COUNTER, labels=("reason", "tenant"),
        help="Rows excised by data-plane admission, by reason code.",
    ),
    "sntc_shed_offsets_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Source offsets dropped by load shedding (shed journal).",
    ),
    "sntc_batches_quarantined_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Poison batches journaled to the dead-letter sink.",
    ),
    "sntc_faults_injected_total": dict(
        type=COUNTER, labels=("site", "kind"),
        help="Deterministic fault injections fired (SNTC_FAULTS).",
    ),
    # -- the serving engine -----------------------------------------------
    "sntc_batches_committed_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Micro-batches committed to the WAL (incl. quarantined).",
    ),
    "sntc_rows_committed_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Input rows across committed micro-batches.",
    ),
    "sntc_batch_duration_seconds": dict(
        type=HISTOGRAM, labels=("tenant",), buckets=LATENCY_BUCKETS,
        help="WAL-intent→commit latency per micro-batch (the "
        "recentProgress durationMs distribution).",
    ),
    "sntc_source_prefetch_hits_total": dict(
        type=COUNTER, labels=(),
        help="get_batch calls served from a staged prefetch read.",
    ),
    "sntc_source_prefetch_misses_total": dict(
        type=COUNTER, labels=(),
        help="get_batch calls that fell through to a synchronous read "
        "while prefetch was armed.",
    ),
    # -- ingest -------------------------------------------------------------
    "sntc_ingest_files_parsed_total": dict(
        type=COUNTER, labels=(),
        help="Source files parsed by load_csv.",
    ),
    "sntc_ingest_rows_parsed_total": dict(
        type=COUNTER, labels=(),
        help="Rows parsed out of source files by load_csv.",
    ),
    "sntc_ingest_bytes_read_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Raw source bytes read by ingest (CSV parse, capture "
        "decode).",
    ),
    # -- the ingest source graph + autotuner (data/pipeline, data/autotune) --
    "sntc_ingest_stage_seconds": dict(
        type=HISTOGRAM, labels=("stage", "tenant"),
        buckets=LATENCY_BUCKETS,
        help="Per-item latency of each ingest source-graph stage "
        "(read/parse/admit/bucket/stage) — the autotuner's feedback "
        "signal.",
    ),
    "sntc_ingest_queue_depth": dict(
        type=GAUGE, labels=("stage", "tenant"),
        help="Current occupancy of a source-graph stage queue (the "
        "prefetch staging queue).",
    ),
    "sntc_ingest_autotune_decisions_total": dict(
        type=COUNTER, labels=("knob", "direction", "tenant"),
        help="Applied ingest-autotuner knob changes, by knob and "
        "direction.",
    ),
    "sntc_ingest_knob_value": dict(
        type=GAUGE, labels=("knob", "tenant"),
        help="Current value of each autotuned ingest knob "
        "(read_workers / prefetch_batches / pipeline_depth).",
    ),
    # -- live network ingress (serve/ingress, r20) --------------------------
    "sntc_ingress_datagrams_total": dict(
        type=COUNTER, labels=("tenant",),
        help="UDP datagrams accepted at the ingress receive boundary "
        "(pre-spool; the conservation law's 'received' side).",
    ),
    "sntc_ingress_frames_total": dict(
        type=COUNTER, labels=("tenant",),
        help="TCP length-prefixed frames accepted at the ingress "
        "receive boundary.",
    ),
    "sntc_ingress_bytes_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Payload bytes accepted at the ingress receive boundary.",
    ),
    "sntc_ingress_dropped_total": dict(
        type=COUNTER, labels=("reason", "tenant"),
        help="Ingress payloads shed, by reason (ring_overflow / "
        "spool_over_budget / spool_error / torn_frame / oversize_frame "
        "/ recv_error / close_discard) — counted shed, never silent "
        "loss: received == spooled + dropped after a drain.",
    ),
    "sntc_ingress_sealed_files_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Capture files sealed (fsynced atomic rename) into the "
        "ingress spool.",
    ),
    "sntc_ingress_pruned_files_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Committed capture files pruned by spool retention "
        "(keep-N / disk budget).",
    ),
    "sntc_ingress_spool_bytes": dict(
        type=GAUGE, labels=("tenant",),
        help="Live bytes in the ingress spool directory.",
    ),
    "sntc_ingress_ring_depth": dict(
        type=GAUGE, labels=("tenant",),
        help="Payloads waiting in the bounded ingress ring.",
    ),
    "sntc_ingress_backpressure_state": dict(
        type=GAUGE, labels=("tenant",),
        help="1 while TCP ingress is pausing reads (spool over "
        "budget), 0 otherwise.",
    ),
    "sntc_ingress_connections": dict(
        type=GAUGE, labels=("tenant",),
        help="Live TCP ingress connections.",
    ),
    # -- predict / compile ledgers ------------------------------------------
    "sntc_predict_compile_events_total": dict(
        type=COUNTER, labels=(),
        help="Distinct dispatched row shapes across BatchPredictors "
        "(each costs at most one XLA compile; legacy view: "
        "BatchPredictor.compile_events).",
    ),
    "sntc_predict_bucket_hits_total": dict(
        type=COUNTER, labels=(),
        help="Dispatches that reused an already-seen row shape.",
    ),
    "sntc_predict_padded_rows_total": dict(
        type=COUNTER, labels=(),
        help="Wasted rows shape-bucket padding cost.",
    ),
    "sntc_fuse_compile_events_total": dict(
        type=COUNTER, labels=(),
        help="Distinct input signatures compiled across FusedSegments.",
    ),
    "sntc_fuse_fallbacks_total": dict(
        type=COUNTER, labels=(),
        help="FusedSegment eager fallbacks (empty frame / dtype gate).",
    ),
    # -- host↔device transfers (utils.profiling.TransferLedger mirror) ------
    "sntc_transfer_dispatches_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Fused-program dispatches (unlabeled series = the "
        "process-global TransferLedger; tenant series = the "
        "per-engine ledgers).",
    ),
    "sntc_transfer_uploads_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Host→device array uploads by fused dispatches.",
    ),
    "sntc_transfer_downloads_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Device→host output materializations by fused finalizes.",
    ),
    "sntc_transfer_upload_bytes_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Bytes uploaded host→device by fused dispatches.",
    ),
    "sntc_transfer_download_bytes_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Bytes materialized device→host by fused finalizes.",
    ),
    # -- collective layer over the mesh substrate (parallel/mesh, r22) ------
    "sntc_collective_dispatches_total": dict(
        type=COUNTER, labels=("op", "axis"),
        help="SPMD collective dispatches over a mesh axis, by "
        "aggregate op (tree_aggregate / kmeans.lloyd / lda.e_step / "
        "pic.power / tree.histogram).",
    ),
    "sntc_collective_bytes_moved_total": dict(
        type=COUNTER, labels=("op", "axis"),
        help="Ring-allreduce wire bytes (2·(n-1)·payload) moved by "
        "collective dispatches — the SparCML baseline a compressed "
        "reduction must beat; loop-carried psums count once per "
        "dispatch (documented lower bound).",
    ),
    "sntc_collective_mesh_devices": dict(
        type=GAUGE, labels=("axis",),
        help="Live mesh shape: devices along each declared axis "
        "(shrinks on a journaled mesh_resize).",
    ),
    "sntc_collective_resizes_total": dict(
        type=COUNTER, labels=(),
        help="Elastic mesh resizes — a device_lost answered by "
        "shrinking the data axis onto the survivors instead of "
        "flipping HOST_DEGRADED.",
    ),
    # -- health / breakers / drift -------------------------------------------
    "sntc_health_state": dict(
        type=GAUGE, labels=("component",),
        help="Component health (0=OK, 1=DEGRADED, 2=UNHEALTHY).",
    ),
    "sntc_breaker_state": dict(
        type=GAUGE, labels=("site",),
        help="Circuit-breaker state (0=closed, 1=half_open, 2=open).",
    ),
    "sntc_drift_divergence": dict(
        type=GAUGE, labels=("component",),
        help="Latest Jensen-Shannon divergence the drift monitor saw.",
    ),
    # -- multi-tenant scheduler ----------------------------------------------
    "sntc_daemon_ticks_total": dict(
        type=COUNTER, labels=(),
        help="ServeDaemon scheduling rounds.",
    ),
    "sntc_tenant_state": dict(
        type=GAUGE, labels=("tenant",),
        help="Tenant ladder state (0=OK, 1=THROTTLED, 2=QUARANTINED, "
        "3=STOPPED).",
    ),
    "sntc_tenant_deficit": dict(
        type=GAUGE, labels=("tenant",),
        help="DRR scheduler deficit after the last round.",
    ),
    "sntc_tenant_strikes_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Unhealthy strikes counted against the tenant ladder.",
    ),
    # -- the stateful flow-feature engine (sntc_tpu/flow) --------------------
    "sntc_flow_records_consumed_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Parser records (packets/datagram rows) accepted into "
        "keyed window state.",
    ),
    "sntc_flow_late_records_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Records dropped behind the watermark (reason code "
        "late_record).",
    ),
    "sntc_flow_out_of_order_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Accepted records that arrived behind the stream head "
        "but inside the lateness bound.",
    ),
    "sntc_flow_windows_emitted_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Completed flow windows emitted as feature rows.",
    ),
    "sntc_flow_evictions_total": dict(
        type=COUNTER, labels=("reason", "tenant"),
        help="Flows evicted from keyed state, by reason (watermark / "
        "state_cap / flush).",
    ),
    "sntc_flow_snapshots_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Operator-state snapshots published at commit.",
    ),
    "sntc_flow_active_flows": dict(
        type=GAUGE, labels=("tenant",),
        help="Open (uncompleted) flow windows held in keyed state.",
    ),
    "sntc_flow_state_packets": dict(
        type=GAUGE, labels=("tenant",),
        help="Buffered parser records across all open windows (the "
        "watermark-bounded state size).",
    ),
    "sntc_flow_state_bytes": dict(
        type=GAUGE, labels=("tenant",),
        help="Size of the last published operator-state snapshot.",
    ),
    # -- the closed-loop SLO controller (serve/controller) -------------------
    "sntc_ctl_windows_total": dict(
        type=COUNTER, labels=(),
        help="SLO-controller observation windows closed.",
    ),
    "sntc_ctl_decisions_total": dict(
        type=COUNTER, labels=("action", "knob", "tenant"),
        help="SLO-controller decisions (applied / budget_denied / "
        "frozen / delegated / escalated), by knob and tenant.",
    ),
    "sntc_ctl_knob_value": dict(
        type=GAUGE, labels=("knob", "tenant"),
        help="Current value of each controller-steered serving knob "
        "(pipeline_depth / shape_buckets / weight / quota / shed / "
        "escalate / migrate / scale_out; ladder knobs report their "
        "ladder index).",
    ),
    "sntc_ctl_slo_compliant": dict(
        type=GAUGE, labels=("slo", "tenant"),
        help="Per-window SLO compliance verdict (1 = compliant, 0 = "
        "violating) for each declared SLO axis (p99 / throughput / "
        "shed).",
    ),
    "sntc_ctl_window_p99_seconds": dict(
        type=GAUGE, labels=("tenant",),
        help="Windowed p99 batch latency the controller computed from "
        "the sntc_batch_duration_seconds bucket deltas.",
    ),
    # -- the tracer's own accounting -----------------------------------------
    "sntc_spans_dropped_total": dict(
        type=COUNTER, labels=(),
        help="Spans evicted from the trace ring buffer.",
    ),
    # -- the durable-storage survival plane (resilience/storage, r17) --------
    "sntc_disk_bytes": dict(
        type=GAUGE, labels=("artifact", "tenant"),
        help="On-disk bytes per registered durable artifact under a "
        "checkpoint root (artifact=total is the whole tree).",
    ),
    "sntc_disk_files": dict(
        type=GAUGE, labels=("artifact", "tenant"),
        help="On-disk file count per registered durable artifact "
        "(artifact=total is the whole tree).",
    ),
    "sntc_disk_budget_bytes": dict(
        type=GAUGE, labels=("tenant",),
        help="Declared disk byte budget for a checkpoint root "
        "(global when unlabeled, per-tenant when labeled).",
    ),
    "sntc_storage_write_errors_total": dict(
        type=COUNTER, labels=("artifact", "tenant"),
        help="Failed durable writes (ENOSPC/EIO, real or injected), "
        "by artifact.",
    ),
    "sntc_storage_degraded_state": dict(
        type=GAUGE, labels=("artifact", "tenant"),
        help="1 while an artifact is in a storage_degraded episode "
        "(records buffering in memory), 0 after recovery.",
    ),
    "sntc_storage_repairs_total": dict(
        type=COUNTER, labels=("artifact", "tenant"),
        help="Automatic storage repairs (torn-tail truncations, "
        "corrupt-blob quarantines), journaled to "
        "storage_repair.jsonl.",
    ),
    "sntc_dead_letter_dropped_total": dict(
        type=COUNTER, labels=("artifact", "tenant"),
        help="Dead-letter evidence files dropped by the keep-N/"
        "size-cap retention policy.",
    ),
    "sntc_wal_compactions_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Append-WAL compactions (sealed checkpoint written, "
        "offsets/commits logs truncated).",
    ),
    # -- the compute-plane fault domain (resilience/device, r18) --------------
    "sntc_device_state": dict(
        type=GAUGE, labels=(),
        help="Device serving state of the process's fault domain "
        "(0=DEVICE_OK, 1=HOST_DEGRADED — every dispatch on the eager "
        "host fallback until the recovery probe succeeds).",
    ),
    "sntc_device_faults_total": dict(
        type=COUNTER, labels=("kind", "site"),
        help="Classified device/XLA runtime failures (device_oom / "
        "compile_error / device_lost), by fault site.",
    ),
    "sntc_device_oom_splits_total": dict(
        type=COUNTER, labels=(),
        help="Micro-batch halvings the OOM responder performed "
        "(device_oom_split decisions; retried on device at the "
        "smaller shape).",
    ),
    "sntc_device_poisoned_signatures": dict(
        type=GAUGE, labels=(),
        help="(segment, signature) pairs poisoned out of the device "
        "plan cache after a compile failure or watchdog breach — each "
        "serves through the eager host fallback.",
    ),
    "sntc_device_fallback_batches_total": dict(
        type=COUNTER, labels=(),
        help="Dispatches served through the eager host fallback "
        "(poisoned signature or HOST_DEGRADED).",
    ),
    # -- serving-kernel forge + MFU/roofline plane (r21) ----------------
    "sntc_kernel_dispatch_total": dict(
        type=COUNTER, labels=("kernel", "impl"),
        help="Hand-written kernel executions by kernel name and "
        "implementation (pallas on hardware, interpret on CPU "
        "tier-1); the registered twin paths count under "
        "sntc_kernel_fallback_total instead.",
    ),
    "sntc_kernel_fallback_total": dict(
        type=COUNTER, labels=("kernel", "reason"),
        help="Kernel-tier calls served on the lowered-jnp/numpy twin "
        "path, by reason (off / guard / poisoned / compile_error / "
        "segment).",
    ),
    "sntc_kernel_poisoned_signatures": dict(
        type=GAUGE, labels=(),
        help="(kernel, signature) pairs poisoned onto the XLA twin "
        "path after a kernel compile failure — each serves bitwise "
        "on the twin, never striking a tenant.",
    ),
    "sntc_mfu_ratio": dict(
        type=GAUGE, labels=("segment",),
        help="Achieved FLOP/s over probed peak FLOP/s per fused "
        "serving segment (XLA cost_analysis x measured dispatch "
        "time; only under SNTC_OBS_COST_ANALYSIS=1 — see "
        "obs/cost.py and the peak_source caveat).",
    ),
    "sntc_mfu_bw_ratio": dict(
        type=GAUGE, labels=("segment",),
        help="Achieved memory bandwidth over probed peak bandwidth "
        "per fused serving segment (same hook and caveats as "
        "sntc_mfu_ratio).",
    ),
    "sntc_device_recoveries_total": dict(
        type=COUNTER, labels=(),
        help="HOST_DEGRADED -> DEVICE_OK transitions (the probe-gated "
        "recovery tick restored device serving).",
    ),
    # -- the elastic serve fleet (serve/fleet, r19) ---------------------------
    "sntc_fleet_worker_state": dict(
        type=GAUGE, labels=("worker",),
        help="Coordinator's liveness verdict per worker (1 = lease "
        "current, 0 = lease expired / declared dead).",
    ),
    "sntc_fleet_leases_renewed_total": dict(
        type=COUNTER, labels=("worker",),
        help="Worker lease/heartbeat renewals observed by the "
        "coordinator.",
    ),
    "sntc_fleet_leases_expired_total": dict(
        type=COUNTER, labels=("worker",),
        help="Lease expiries — a worker missed its TTL and was "
        "declared dead; its tenants were redistributed.",
    ),
    "sntc_fleet_migrations_total": dict(
        type=COUNTER, labels=("reason", "outcome"),
        help="Tenant migrations by reason (rebalance / worker_dead / "
        "controller / join) and outcome (completed / reverted).",
    ),
    "sntc_fleet_tenants_value": dict(
        type=GAUGE, labels=("worker",),
        help="Tenants currently assigned to each worker (the "
        "coordinator's placement view).",
    ),
    "sntc_fleet_rows_value": dict(
        type=GAUGE, labels=("worker",),
        help="Rows committed as reported by each worker's last "
        "heartbeat (worker=fleet is the aggregate across live "
        "workers).",
    ),
    # -- warm-standby replication (resilience/replicate, r23) -----------------
    "sntc_repl_ships_total": dict(
        type=COUNTER, labels=("tenant", "outcome"),
        help="Replication ship passes by outcome (completed / error). "
        "An error pass degraded — it was journaled and retries at the "
        "next commit; the serving engine never notices.",
    ),
    "sntc_repl_ship_files_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Artifact files copied into the standby replica tree "
        "(changed-content files only; unchanged files are skipped by "
        "stamp/sha).",
    ),
    "sntc_repl_ship_bytes_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Bytes shipped into the standby replica tree.",
    ),
    "sntc_repl_barriers_sealed_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Commit-barrier records sealed into the replicated "
        "barrier log — each one is a provably consistent promotion "
        "point (the replica holds everything through its batch_id).",
    ),
    "sntc_repl_lag_batches": dict(
        type=GAUGE, labels=("tenant",),
        help="Committed batches not yet covered by a sealed barrier "
        "(the batch component of RPO; 0 right after each barrier).",
    ),
    "sntc_repl_lag_seconds": dict(
        type=GAUGE, labels=("tenant",),
        help="Seconds since the last sealed barrier (the time "
        "component of RPO).",
    ),
    "sntc_repl_lag_bytes": dict(
        type=GAUGE, labels=("tenant",),
        help="Estimated un-replicated primary bytes (what a primary "
        "loss right now would cost; stat-only estimate, refreshed on "
        "degraded ships and zeroed at each barrier).",
    ),
    "sntc_repl_divergence_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Replica-vs-manifest or replica-vs-primary divergences "
        "found by promotion or anti-entropy fsck (each one also "
        "journals a replica_diverged event).",
    ),
    "sntc_repl_promotions_total": dict(
        type=COUNTER, labels=("outcome",),
        help="Standby promotions by outcome (completed / failed). A "
        "failed promotion never leaves a partially promoted tree.",
    ),
    "sntc_repl_tail_loss_rows_total": dict(
        type=COUNTER, labels=("tenant",),
        help="Rows counted lost beyond the last sealed barrier at "
        "promotion (the counted_tail_loss term of the loss-accounting "
        "law: committed == replicated_through_barrier + "
        "counted_tail_loss).",
    ),
}

_OVERFLOW_KEY: Tuple[Tuple[str, str], ...] = (("overflow", "true"),)


class _Series:
    """One label set of one metric.  Counters/gauges keep ``value``;
    histograms keep per-bucket counts plus sum/count."""

    __slots__ = ("labels", "value", "bucket_counts", "sum", "count")

    def __init__(self, labels: Tuple[Tuple[str, str], ...],
                 n_buckets: int = 0):
        self.labels = labels
        self.value = 0.0
        self.bucket_counts = [0] * n_buckets if n_buckets else None
        self.sum = 0.0
        self.count = 0


class MetricsRegistry:
    """Registry of cataloged metrics (module docstring has the design).

    ``clock``/``mono`` are the wall/monotonic time sources used by the
    JSONL exposition — inject constants for deterministic test output.
    ``max_label_sets`` caps per-metric label cardinality.
    """

    def __init__(
        self,
        *,
        clock=time.time,
        mono=time.monotonic,
        max_label_sets: int = 64,
    ):
        self._clock = clock
        self._mono = mono
        self.max_label_sets = int(max_label_sets)
        self._lock = threading.Lock()  # series creation only
        # name -> (spec, {labelkey: _Series}, write lock)
        self._metrics: Dict[str, Tuple[dict, Dict, threading.Lock]] = {}
        self._label_overflows = 0
        self._jsonl_records = 0

    # -- series resolution ---------------------------------------------------

    def _series(self, name: str, labels: Dict[str, str]) -> _Series:
        entry = self._metrics.get(name)
        if entry is None:
            spec = CATALOG.get(name)
            if spec is None:
                raise KeyError(
                    f"metric {name!r} is not declared in obs.metrics."
                    "CATALOG — add it there (and to docs/OBSERVABILITY"
                    ".md; scripts/check_metric_names.py enforces both)"
                )
            with self._lock:
                entry = self._metrics.get(name)
                if entry is None:
                    entry = (spec, {}, threading.Lock())
                    self._metrics[name] = entry
        spec, series, lock = entry
        if labels:
            allowed = spec["labels"]
            for k in labels:
                if k not in allowed:
                    raise KeyError(
                        f"label {k!r} not declared for metric {name!r} "
                        f"(allowed: {allowed})"
                    )
            key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        else:
            key = ()
        s = series.get(key)
        if s is None:
            with lock:
                s = series.get(key)
                if s is None:
                    if key and len(series) >= self.max_label_sets:
                        # cardinality cap: collapse into the reserved
                        # overflow series (created on first breach).
                        # The counter is registry-wide, so guard it
                        # with the registry lock — two metrics
                        # overflowing concurrently hold DIFFERENT
                        # series locks (lock order metric→registry is
                        # safe: creation never takes them nested the
                        # other way)
                        with self._lock:
                            self._label_overflows += 1
                        s = series.get(_OVERFLOW_KEY)
                        if s is None:
                            s = series[_OVERFLOW_KEY] = _Series(
                                _OVERFLOW_KEY,
                                len(spec.get("buckets", ())) + 1
                                if spec["type"] == HISTOGRAM else 0,
                            )
                        return s
                    s = series[key] = _Series(
                        key,
                        len(spec.get("buckets", ())) + 1
                        if spec["type"] == HISTOGRAM else 0,
                    )
        return s

    # -- write surface -------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        s = self._series(name, labels)
        with self._metrics[name][2]:
            s.value += value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        s = self._series(name, labels)
        lock = self._metrics[name][2]
        with lock:
            s.value = float(value)

    def observe(self, name: str, value: float, **labels: str) -> None:
        spec = CATALOG.get(name)
        if spec is None or spec["type"] != HISTOGRAM:
            raise KeyError(f"{name!r} is not a cataloged histogram")
        s = self._series(name, labels)
        lock = self._metrics[name][2]
        buckets = spec["buckets"]
        # bisect_left = first bound >= value, i.e. Prometheus le
        # semantics; index len(buckets) is the +Inf bucket
        i = bisect_left(buckets, value)
        with lock:
            s.bucket_counts[i] += 1
            s.sum += value
            s.count += 1

    # -- read surface (lock-free) --------------------------------------------

    def get(self, name: str, **labels: str) -> Optional[float]:
        """Current value of one counter/gauge series (None when the
        series does not exist yet)."""
        entry = self._metrics.get(name)
        if entry is None:
            return None
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        s = entry[1].get(key)
        return s.value if s is not None else None

    def get_histogram(self, name: str, **labels: str) -> Optional[dict]:
        """Live view of one histogram series (None when the series
        does not exist yet): bucket bounds, per-bucket counts, sum,
        count.  Lock-free like :meth:`get` — a read racing an observe
        may be one tick stale on one bucket, never torn across the
        registry.  The SLO controller diffs two of these to get a
        WINDOWED latency distribution."""
        entry = self._metrics.get(name)
        if entry is None:
            return None
        spec = entry[0]
        if spec["type"] != HISTOGRAM:
            raise KeyError(f"{name!r} is not a cataloged histogram")
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        s = entry[1].get(key)
        if s is None:
            return None
        return {
            "bounds": list(spec["buckets"]),
            "buckets": list(s.bucket_counts),
            "sum": s.sum,
            "count": s.count,
        }

    def label_overflows(self) -> int:
        """WRITES that landed on an overflow series (not distinct
        evicted label sets — telling those apart would require storing
        exactly the keys the cap exists to bound).  Nonzero means some
        metric's labels exceeded ``max_label_sets``; the rate says how
        hot the overflowing series are, not how many there were."""
        return self._label_overflows

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy of every live series — readers never take
        the write locks (see module docstring, constraint 2)."""
        out: Dict[str, Any] = {}
        for name, (spec, series, _lock) in list(self._metrics.items()):
            rows = []
            for s in list(series.values()):
                row: Dict[str, Any] = {"labels": dict(s.labels)}
                if spec["type"] == HISTOGRAM:
                    row["buckets"] = list(s.bucket_counts)
                    row["sum"] = s.sum
                    row["count"] = s.count
                else:
                    row["value"] = s.value
                rows.append(row)
            out[name] = {
                "type": spec["type"],
                "help": spec["help"],
                "series": rows,
            }
            if spec["type"] == HISTOGRAM:
                out[name]["bucket_bounds"] = list(spec["buckets"])
        return out

    # -- exposition ----------------------------------------------------------

    @staticmethod
    def _fmt_labels(labels, extra: str = "") -> str:
        parts = [
            '%s="%s"' % (
                k,
                str(v).replace("\\", r"\\").replace('"', r"\"")
                .replace("\n", r"\n"),
            )
            for k, v in labels
        ]
        if extra:
            parts.append(extra)
        return "{%s}" % ",".join(parts) if parts else ""

    @staticmethod
    def _fmt_value(v: float) -> str:
        return repr(int(v)) if float(v).is_integer() else repr(v)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every live
        series, metrics sorted by name for diffable output."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            spec, series, _lock = self._metrics[name]
            lines.append(f"# HELP {name} {spec['help']}")
            lines.append(f"# TYPE {name} {spec['type']}")
            for s in sorted(
                list(series.values()), key=lambda s: s.labels
            ):
                if spec["type"] == HISTOGRAM:
                    # snapshot the counts once so the cumulative sums
                    # below cannot tear against concurrent observes
                    counts = list(s.bucket_counts)
                    acc = 0
                    for bound, n in zip(spec["buckets"], counts):
                        acc += n
                        lines.append(
                            f"{name}_bucket"
                            + self._fmt_labels(
                                s.labels, f'le="{bound}"'
                            )
                            + f" {acc}"
                        )
                    acc += counts[-1]
                    lines.append(
                        f"{name}_bucket"
                        + self._fmt_labels(s.labels, 'le="+Inf"')
                        + f" {acc}"
                    )
                    lines.append(
                        f"{name}_sum" + self._fmt_labels(s.labels)
                        + f" {self._fmt_value(s.sum)}"
                    )
                    lines.append(
                        f"{name}_count" + self._fmt_labels(s.labels)
                        + f" {acc}"
                    )
                else:
                    lines.append(
                        name + self._fmt_labels(s.labels)
                        + f" {self._fmt_value(s.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str) -> str:
        """Atomically (tmp + rename) publish the Prometheus text dump —
        a scraper/tailer never reads a torn snapshot."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)  # storage: telemetry
        return path

    def write_jsonl(self, path: str) -> Dict[str, Any]:
        """Append one snapshot record (wall + monotonic timestamps from
        the injectable clocks) to a JSONL file and return it."""
        record = {
            "ts": self._clock(),
            "mono": self._mono(),
            "seq": self._jsonl_records,
            "metrics": self.snapshot(),
        }
        self._jsonl_records += 1
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:  # storage: unbounded(caller-owned JSONL export path)
            f.write(json.dumps(record) + "\n")
        return record


# ---------------------------------------------------------------------------
# the process default registry + module-level write helpers (hot paths
# call these; swap the default out with set_registry for test isolation)
# ---------------------------------------------------------------------------

_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _default


def set_registry(r: MetricsRegistry) -> MetricsRegistry:
    """Replace the process default registry; returns the previous one."""
    global _default
    prev, _default = _default, r
    return prev


def reset_registry() -> MetricsRegistry:
    """Fresh default registry (test isolation); returns the new one."""
    set_registry(MetricsRegistry())
    return _default


def inc(name: str, value: float = 1.0, **labels: str) -> None:
    _default.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels: str) -> None:
    _default.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels: str) -> None:
    _default.observe(name, value, **labels)
