"""Span tracer — host-side stage timing on a ring buffer, exported as
Chrome-trace/Perfetto JSON.

``obs.span("stage.name", **attrs)`` wraps a hot-path stage; each closed
span records (name, monotonic start, duration, wall start, thread id,
attrs) onto a bounded ring.  Tracing is OFF by default and the disabled
path is one attribute read + one dict build — the engine's hot paths
carry the calls permanently without measurable cost (bench config 5
pins the ≤ 5% overhead budget, docs/OBSERVABILITY.md has the numbers).

:meth:`SpanTracer.export_chrome_trace` writes the ring as Chrome
``traceEvents`` JSON, loadable in ``chrome://tracing`` and
https://ui.perfetto.dev — every span a complete ("X") event on its
thread's track.  Ring overflow drops the OLDEST spans and counts them
(``sntc_spans_dropped_total``), never silently.

Device-side correlation hooks (both opt-in — they cost real time):

* :func:`device_trace` — a ``jax.profiler`` trace context (XLA op-level
  timeline for TensorBoard/Perfetto) around any region; the serve CLIs
  expose it as ``--device-trace DIR``.
* ``SNTC_OBS_COST_ANALYSIS=1`` — the fusion planner additionally runs
  XLA's compiled-program ``cost_analysis()`` per compiled signature and
  keeps the FLOPs/bytes estimates on the segment
  (``fusion_stats()["cost_analysis"]``), so host spans can be compared
  against what the program *should* cost on the device.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from sntc_tpu.obs.metrics import inc


class _NullSpan:
    """Shared no-op context manager for the tracing-disabled path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records itself on exit (exceptions included —
    a failing stage's time is exactly the time worth seeing)."""

    __slots__ = ("_tracer", "name", "attrs", "_t0", "_wall0")

    def __init__(self, tracer: "SpanTracer", name: str, attrs):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._wall0 = self._tracer._wall()
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._record(
            self.name,
            self._t0,
            self._tracer._clock() - self._t0,
            self._wall0,
            threading.get_ident(),
            self.attrs,
        )
        return False


class SpanTracer:
    """Bounded ring of closed spans (thread-safe; injectable clocks).

    ``capacity`` bounds memory for the life of the process; overflow
    evicts oldest and counts ``dropped``.
    """

    def __init__(
        self,
        capacity: int = 65_536,
        *,
        clock=time.perf_counter,
        wall=time.time,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self.dropped = 0

    def span(self, name: str, **attrs: Any) -> _Span:
        return _Span(self, name, attrs or None)

    def _record(self, name, t0, dur, wall0, tid, attrs) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
                try:
                    inc("sntc_spans_dropped_total")
                except Exception:
                    pass
            self._ring.append((name, t0, dur, wall0, tid, attrs))

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            ring = list(self._ring)
        return [
            {
                "name": name, "t0": t0, "dur_s": dur, "wall": wall0,
                "tid": tid, "attrs": attrs or {},
            }
            for name, t0, dur, wall0, tid, attrs in ring
        ]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "spans": len(self._ring),
                "capacity": self.capacity,
                "dropped": self.dropped,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def export_chrome_trace(self, path: str) -> str:
        """Write the ring as Chrome trace-event JSON (``ph: "X"``
        complete events, µs timestamps) — loadable in chrome://tracing
        and ui.perfetto.dev.  Atomic publish (tmp + rename)."""
        with self._lock:
            ring = list(self._ring)
        pid = os.getpid()
        thread_names = {
            t.ident: t.name for t in threading.enumerate()
            if t.ident is not None
        }
        events: List[Dict[str, Any]] = []
        for tid, tname in sorted(thread_names.items()):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": tname},
            })
        for name, t0, dur, wall0, tid, attrs in ring:
            ev: Dict[str, Any] = {
                "name": name, "cat": "host", "ph": "X",
                "ts": round(t0 * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": pid, "tid": tid,
            }
            args = dict(attrs) if attrs else {}
            args["wall_ts"] = wall0
            ev["args"] = args
            events.append(ev)
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "sntc_tpu.obs",
                "dropped_spans": self.dropped,
            },
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # storage: telemetry
        return path


# ---------------------------------------------------------------------------
# the process tracer: disabled (None) by default; span() is the
# permanent hot-path call site
# ---------------------------------------------------------------------------

_tracer: Optional[SpanTracer] = None


def span(name: str, **attrs: Any):
    """``with obs.span("stream.read", batch=3): ...`` — records onto
    the process tracer when enabled, a shared no-op otherwise."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def tracer() -> Optional[SpanTracer]:
    return _tracer


def tracing_enabled() -> bool:
    return _tracer is not None


def enable_tracing(capacity: int = 65_536, **kwargs: Any) -> SpanTracer:
    """Arm the process tracer (idempotent: an already-armed tracer is
    returned unchanged unless a new capacity is requested)."""
    global _tracer
    if _tracer is None or _tracer.capacity != capacity:
        _tracer = SpanTracer(capacity, **kwargs)
    return _tracer


def disable_tracing() -> Optional[SpanTracer]:
    """Disarm and return the tracer (its ring stays readable)."""
    global _tracer
    t, _tracer = _tracer, None
    return t


# ---------------------------------------------------------------------------
# device-side correlation (opt-in)
# ---------------------------------------------------------------------------


class device_trace:
    """``with device_trace(log_dir):`` — a ``jax.profiler`` capture
    (XLA op-level Perfetto/TensorBoard timeline) around the block, so
    device time lines up with the host spans recorded inside it.
    Expensive; the serve CLIs gate it behind ``--device-trace DIR``."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir

    def __enter__(self):
        import jax

        jax.profiler.start_trace(self.log_dir)
        return self

    def __exit__(self, *exc):
        import jax

        jax.profiler.stop_trace()
        return False
