from sntc_tpu.mlio.save_load import load_model, save_model

__all__ = ["save_model", "load_model"]
