from sntc_tpu.mlio.save_load import (
    load_model,
    prev_checkpoint_path,
    save_model,
)

__all__ = ["save_model", "load_model", "prev_checkpoint_path"]
