"""Mid-fit optimizer checkpointing — beyond-parity recovery (SURVEY.md
§5.3/§5.4).

Spark's aggregation jobs are stateless, so its failure recovery is lineage
recomputation — a crashed ``fit`` restarts from iteration 0.  Here the
LBFGS/OWLQN state (position, gradient, curvature memory, counters,
objective history) is a small pytree, so estimators with
``checkpointInterval > 0`` persist it every N iterations and a re-run
``fit`` with the same ``checkpointDir`` resumes EXACTLY where the crash
left off — bit-identical to an uninterrupted run on the same hardware
(asserted by the fault-injection test, SURVEY.md §5.3).

Layout: ``<dir>/lbfgs_state.npz`` + ``<dir>/lbfgs_meta.json``; the meta
fingerprint (problem shape + hyperparams) guards against resuming a stale
state into a different problem.  The state is deleted when the fit
completes, so finished runs never leak into later ones.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_STATE_FILE = "lbfgs_state.npz"
_META_FILE = "lbfgs_meta.json"


def _paths(ckpt_dir: str) -> Tuple[str, str]:
    return (
        os.path.join(ckpt_dir, _STATE_FILE),
        os.path.join(ckpt_dir, _META_FILE),
    )


def save_state(ckpt_dir: str, state: Dict, fingerprint: Dict) -> None:
    from sntc_tpu.mlio.save_load import _orbax_save, payload_format

    os.makedirs(ckpt_dir, exist_ok=True)
    state_path, meta_path = _paths(ckpt_dir)
    host_state = {k2: np.asarray(v) for k2, v in state.items()}
    if payload_format() == "orbax":
        # SNTC_CHECKPOINT_FORMAT covers MID-FIT optimizer state too, not
        # just model payloads (same env var, same meaning everywhere)
        _orbax_save(state_path + ".orbax", host_state)
        if os.path.exists(state_path):
            os.remove(state_path)
    else:
        np.savez(state_path, **host_state)
        # a stale orbax payload would shadow this save at load time
        import shutil

        if os.path.isdir(state_path + ".orbax"):
            shutil.rmtree(state_path + ".orbax")
    with open(meta_path, "w") as f:
        json.dump(fingerprint, f)


def load_state(ckpt_dir: str, fingerprint: Dict) -> Optional[Dict]:
    from sntc_tpu.mlio.save_load import _orbax_load

    state_path, meta_path = _paths(ckpt_dir)
    orbax_path = state_path + ".orbax"
    has_state = os.path.exists(state_path) or os.path.isdir(orbax_path)
    if not (has_state and os.path.exists(meta_path)):
        return None
    with open(meta_path) as f:
        stored = json.load(f)
    if stored != fingerprint:
        return None  # different problem/hyperparams: ignore stale state
    if os.path.isdir(orbax_path):
        return _orbax_load(orbax_path)
    with np.load(state_path) as z:
        return {k2: z[k2] for k2 in z.files}


def clear_state(ckpt_dir: str) -> None:
    import shutil

    for p in _paths(ckpt_dir):
        if os.path.exists(p):
            os.remove(p)
    orbax_path = _paths(ckpt_dir)[0] + ".orbax"
    if os.path.isdir(orbax_path):
        shutil.rmtree(orbax_path)


def run_segmented(
    opt_call: Callable,
    target_iters: int,
    interval: int,
    ckpt_dir: Optional[str],
    fingerprint: Dict,
):
    """Drive a resumable optimizer in checkpointed segments.

    ``opt_call(init_state, resume, iter_limit) -> (LbfgsResult, state)``
    must stop at ``iter_limit`` (absolute); segments all reuse one compiled
    program because only the traced ``iter_limit`` changes.
    With ``interval <= 0`` or no ``ckpt_dir``: a single uncheckpointed call.
    """
    if not ckpt_dir or interval <= 0:
        res, _ = opt_call(None, False, target_iters)
        return res

    loaded = load_state(ckpt_dir, fingerprint)
    state = None
    k_done = 0
    if loaded is not None:
        k_done = int(loaded["k"])
        if bool(loaded.get("done", False)) or k_done >= target_iters:
            # finished previously; re-run the final no-op segment to
            # materialize the result from the stored state
            res, _ = opt_call(loaded, True, k_done)
            clear_state(ckpt_dir)
            return res
        state = loaded

    res = None
    while k_done < target_iters:
        limit = min(k_done + interval, target_iters)
        res, dev_state = opt_call(state, state is not None, limit)
        state = {k2: np.asarray(v) for k2, v in dev_state.items()}
        k_done = int(res.n_iters)
        save_state(ckpt_dir, state, fingerprint)
        if bool(res.converged):
            break
        if k_done < limit:  # line-search stall: no further progress
            break
    clear_state(ckpt_dir)
    return res
