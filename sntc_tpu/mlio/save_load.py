"""ML persistence — the ``PipelineModel.save/load`` analog.

Behavioral spec: SURVEY.md §5.4 mechanism 2 (upstream
``ml/util/ReadWrite.scala`` [U]): each stage persists to its own directory
with JSON metadata (class, uid, params) plus a binary payload; ``load``
reconstructs the stage reflectively; pipelines recurse over per-stage
subdirectories.  Payloads here are ``.npz`` (numpy) instead of Parquet —
the params are small (coefficients, trees, scaler moments), and npz
round-trips exactly.

Contract (tested per SURVEY.md §4 item 3, the ``DefaultReadWriteTest``
analog): ``load_model(save_model(m, p))`` produces a stage with identical
params and identical transform behavior.

Stages opt in by implementing ``_save_extra() -> (json_dict, arrays_dict)``
and ``_load_from(params, extra, arrays) -> instance``; pure-params stages
need neither.

Durability (resilience layer): :func:`save_model` writes the WHOLE stage
tree into a staging directory, seals it with a sha256 manifest
(``_manifest.json``), and publishes with directory renames — the live
checkpoint is never a partially-written tree, and the previous good
snapshot is retained at ``<path>.prev``.  :func:`load_model` verifies
the manifest (``SNTC_VERIFY_CHECKPOINT=0`` skips the hash pass) and, on
a torn/corrupted primary, falls back to ``<path>.prev`` with a
structured event instead of dying.  Both ends expose fault-injection
sites (``ckpt.save`` / ``ckpt.load``).
"""

from __future__ import annotations

import hashlib
import importlib
import json
import os
import shutil
import sys
from typing import Any, Dict, Optional

import numpy as np

from sntc_tpu.core.base import Pipeline, PipelineModel, PipelineStage
from sntc_tpu.resilience import emit_event, fault_point

_FORMAT_VERSION = 1
_MANIFEST = "_manifest.json"


class CheckpointCorruptError(RuntimeError):
    """The checkpoint tree fails manifest verification (torn write,
    bit-rot, or partial copy) — names the first offending file."""


class _NpEncoder(json.JSONEncoder):
    def default(self, o: Any) -> Any:
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        return super().default(o)


def _qualname(obj: Any) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _resolve(qualname: str) -> type:
    module, _, name = qualname.rpartition(".")
    if not module.startswith("sntc_tpu."):
        raise ValueError(
            f"refusing to load class {qualname!r} from outside sntc_tpu"
        )
    cls = getattr(importlib.import_module(module), name)
    if not issubclass(cls, PipelineStage):
        raise ValueError(f"{qualname} is not a PipelineStage")
    return cls


def _save_stage(stage: PipelineStage, path: str) -> str:
    """One stage directory (recursing over sub-stages) — the pre-r6
    ``save_model`` body, now always writing into a staging tree."""
    os.makedirs(path, exist_ok=True)
    params = dict(stage.paramValues())
    meta: Dict[str, Any] = {
        "format_version": _FORMAT_VERSION,
        "class": _qualname(stage),
        "uid": stage.uid,
    }

    sub_stages = None
    if isinstance(stage, (Pipeline, PipelineModel)):
        sub_stages = params.pop("stages", [])
    elif hasattr(stage, "_sub_stages"):
        sub_stages = stage._sub_stages()
    if sub_stages is not None:
        meta["stage_dirs"] = []
        for i, sub in enumerate(sub_stages):
            sub_dir = f"stage_{i:03d}"
            _save_stage(sub, os.path.join(path, sub_dir))
            meta["stage_dirs"].append(sub_dir)
    extra, arrays = (
        stage._save_extra() if hasattr(stage, "_save_extra") else ({}, {})
    )
    # optional payloads (e.g. a Forest loaded from an old save without
    # gain/count) come through as None — omit rather than corrupt the npz
    arrays = {k: v for k, v in arrays.items() if v is not None}

    meta["params"] = params
    meta["extra"] = extra
    payload = payload_format()
    if arrays:
        meta["payload"] = payload
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, cls=_NpEncoder, indent=1)
    # no stale-payload sweep needed: save_model always stages into a
    # fresh directory and publishes by rename, so the other format's
    # leftover file cannot exist here
    if arrays:
        if payload == "orbax":
            _orbax_save(os.path.join(path, "data.orbax"), arrays)
        else:
            np.savez(os.path.join(path, "data.npz"), **arrays)
    return path


# ---------------------------------------------------------------------------
# manifest: sha256 over every file of the staged tree
# ---------------------------------------------------------------------------


def _tree_files(root: str):
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            if rel != _MANIFEST:
                yield rel, full


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_manifest(root: str) -> None:
    files = {
        rel: {"sha256": _sha256(full), "bytes": os.path.getsize(full)}
        for rel, full in _tree_files(root)
    }
    tmp = os.path.join(root, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"manifest_version": 1, "files": files}, f, indent=1)
    os.replace(tmp, os.path.join(root, _MANIFEST))  # storage: checkpoint


def verify_checkpoint(path: str) -> bool:
    """Verify ``path`` against its manifest; True when verified, False
    when no manifest exists (pre-resilience checkpoints load unchecked).
    Raises :class:`CheckpointCorruptError` on the first mismatch."""
    mpath = os.path.join(path, _MANIFEST)
    if not os.path.exists(mpath):
        return False
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (json.JSONDecodeError, KeyError, OSError) as e:
        raise CheckpointCorruptError(
            f"unreadable checkpoint manifest {mpath}: {e!r}"
        ) from e
    for rel, want in files.items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            raise CheckpointCorruptError(
                f"checkpoint {path}: manifest file {rel!r} is missing"
            )
        if os.path.getsize(full) != want["bytes"]:
            raise CheckpointCorruptError(
                f"checkpoint {path}: {rel!r} is "
                f"{os.path.getsize(full)} bytes, manifest says "
                f"{want['bytes']} (torn write)"
            )
        if os.environ.get("SNTC_VERIFY_CHECKPOINT", "1") != "0":
            got = _sha256(full)
            if got != want["sha256"]:
                raise CheckpointCorruptError(
                    f"checkpoint {path}: {rel!r} sha256 mismatch "
                    f"(expected {want['sha256'][:12]}…, got {got[:12]}…)"
                )
    # files present on disk but absent from the manifest are tolerated
    # (a stranger file beside the tree is not corruption of the tree)
    return True


def _prev_path(path: str) -> str:
    return os.path.normpath(path) + ".prev"


def prev_checkpoint_path(path: str) -> str:
    """The retained previous-snapshot location for a checkpoint at
    ``path`` (written by :func:`save_model`'s atomic publish; the
    lifecycle layer rolls a failed promotion back to it)."""
    return _prev_path(path)


def save_model(stage: PipelineStage, path: str) -> str:
    """Persist a stage (or whole Pipeline/PipelineModel) to ``path``.

    Atomic publish: the tree is staged at ``<path>.tmp-<pid>``, sealed
    with a manifest, then swapped in by rename; an existing checkpoint
    at ``path`` is retained as ``<path>.prev`` (the fallback snapshot
    :func:`load_model` degrades to).  A crash — or an armed
    ``ckpt.save`` fault — before the swap leaves the previous
    checkpoint fully intact."""
    path = os.path.normpath(path)
    staging = f"{path}.tmp-{os.getpid()}"
    if os.path.isdir(staging):
        shutil.rmtree(staging)
    moved_aside = False
    prev = _prev_path(path)
    try:
        _save_stage(stage, staging)
        # injected faults land here: after the expensive tree write,
        # BEFORE anything touches the live checkpoint
        fault_point("ckpt.save")
        _write_manifest(staging)
        if os.path.isdir(path):
            if os.path.isdir(prev):
                shutil.rmtree(prev)
            os.replace(path, prev)  # storage: checkpoint
            moved_aside = True
        os.replace(staging, path)  # storage: checkpoint
    except BaseException:
        # if the old checkpoint was already moved aside and the final
        # publish failed, put it back — a failed save must never leave
        # ``path`` empty while the only good tree sits at .prev
        if (
            moved_aside
            and not os.path.isdir(path)
            and os.path.isdir(prev)
        ):
            os.replace(prev, path)  # storage: checkpoint
        if os.path.isdir(staging):
            shutil.rmtree(staging, ignore_errors=True)
        raise
    return path


def payload_format() -> str:
    """Array-payload backend: ``npz`` (default — one portable file) or
    ``orbax`` (``SNTC_CHECKPOINT_FORMAT=orbax`` — the JAX-ecosystem
    checkpointer SURVEY.md §5.4 names; async-capable, sharding-aware,
    the right base for multi-host model dumps).  Loads auto-detect, so
    repos can mix formats freely."""
    fmt = os.environ.get("SNTC_CHECKPOINT_FORMAT", "npz")
    if fmt not in ("npz", "orbax"):
        raise ValueError(
            f"SNTC_CHECKPOINT_FORMAT={fmt!r}: expected 'npz' or 'orbax'"
        )
    return fmt


def _orbax_save(path: str, arrays: Dict[str, np.ndarray]) -> None:
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            os.path.abspath(path), dict(arrays), force=True
        )


def _orbax_load(path: str) -> Dict[str, np.ndarray]:
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        out = ckptr.restore(os.path.abspath(path))
    return {k: np.asarray(v) for k, v in out.items()}


def _load_stage(path: str) -> PipelineStage:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"unsupported model format {meta.get('format_version')}")
    cls = _resolve(meta["class"])
    params = meta.get("params", {})
    extra = meta.get("extra", {})
    npz_path = os.path.join(path, "data.npz")
    orbax_path = os.path.join(path, "data.orbax")
    arrays: Dict[str, np.ndarray] = {}
    payload = meta.get("payload")  # absent in pre-orbax saves: sniff
    if payload == "orbax" or (payload is None and os.path.isdir(orbax_path)):
        if os.path.isdir(orbax_path):
            arrays = _orbax_load(orbax_path)
    elif os.path.exists(npz_path):
        with np.load(npz_path) as z:
            arrays = {k: z[k] for k in z.files}

    if issubclass(cls, (Pipeline, PipelineModel)):
        stages = [
            load_model(os.path.join(path, d), fallback=False)
            for d in meta.get("stage_dirs", [])
        ]
        obj = cls(stages=stages)
        obj.setParams(**params)
    elif hasattr(cls, "_from_sub_stages"):
        stages = [
            load_model(os.path.join(path, d), fallback=False)
            for d in meta.get("stage_dirs", [])
        ]
        obj = cls._from_sub_stages(stages, params, extra)
    elif hasattr(cls, "_load_from"):
        obj = cls._load_from(params, extra, arrays)
    else:
        obj = cls()
        obj.setParams(**params)
    obj.uid = meta.get("uid", obj.uid)
    return obj


def load_model(path: str, fallback: bool = True) -> PipelineStage:
    """Load a stage tree, verifying its manifest when present.

    On a corrupted/torn primary (manifest mismatch, unreadable
    metadata, bad payload), a verified ``<path>.prev`` snapshot — kept
    by :func:`save_model`'s atomic publish — is loaded instead, with a
    structured ``ckpt_fallback`` event and a stderr warning; without
    one the original error propagates.  ``fallback=False`` (and every
    recursive sub-stage load) disables degradation."""
    path = os.path.normpath(path)
    try:
        # inside the try: an injected ckpt.load fault must take the same
        # degradation path a real load failure does
        fault_point("ckpt.load")
        verify_checkpoint(path)
        return _load_stage(path)
    except Exception as primary_err:
        prev = _prev_path(path)
        if not fallback or not os.path.isdir(prev):
            raise
        try:
            verify_checkpoint(prev)
            obj = _load_stage(prev)
        except Exception:
            raise primary_err  # both bad: report the primary failure
        emit_event(
            event="ckpt_fallback", site="ckpt.load", path=path,
            fallback_path=prev, error=repr(primary_err),
        )
        print(
            f"sntc_tpu: checkpoint {path!r} failed to load "
            f"({primary_err!r}); degraded to previous good snapshot "
            f"{prev!r}",
            file=sys.stderr,
        )
        return obj
