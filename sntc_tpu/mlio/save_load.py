"""ML persistence — the ``PipelineModel.save/load`` analog.

Behavioral spec: SURVEY.md §5.4 mechanism 2 (upstream
``ml/util/ReadWrite.scala`` [U]): each stage persists to its own directory
with JSON metadata (class, uid, params) plus a binary payload; ``load``
reconstructs the stage reflectively; pipelines recurse over per-stage
subdirectories.  Payloads here are ``.npz`` (numpy) instead of Parquet —
the params are small (coefficients, trees, scaler moments), and npz
round-trips exactly.

Contract (tested per SURVEY.md §4 item 3, the ``DefaultReadWriteTest``
analog): ``load_model(save_model(m, p))`` produces a stage with identical
params and identical transform behavior.

Stages opt in by implementing ``_save_extra() -> (json_dict, arrays_dict)``
and ``_load_from(params, extra, arrays) -> instance``; pure-params stages
need neither.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, Tuple

import numpy as np

from sntc_tpu.core.base import Pipeline, PipelineModel, PipelineStage

_FORMAT_VERSION = 1


class _NpEncoder(json.JSONEncoder):
    def default(self, o: Any) -> Any:
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        return super().default(o)


def _qualname(obj: Any) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _resolve(qualname: str) -> type:
    module, _, name = qualname.rpartition(".")
    if not module.startswith("sntc_tpu."):
        raise ValueError(
            f"refusing to load class {qualname!r} from outside sntc_tpu"
        )
    cls = getattr(importlib.import_module(module), name)
    if not issubclass(cls, PipelineStage):
        raise ValueError(f"{qualname} is not a PipelineStage")
    return cls


def save_model(stage: PipelineStage, path: str) -> str:
    """Persist a stage (or whole Pipeline/PipelineModel) to ``path``."""
    os.makedirs(path, exist_ok=True)
    params = dict(stage.paramValues())
    meta: Dict[str, Any] = {
        "format_version": _FORMAT_VERSION,
        "class": _qualname(stage),
        "uid": stage.uid,
    }

    sub_stages = None
    if isinstance(stage, (Pipeline, PipelineModel)):
        sub_stages = params.pop("stages", [])
    elif hasattr(stage, "_sub_stages"):
        sub_stages = stage._sub_stages()
    if sub_stages is not None:
        meta["stage_dirs"] = []
        for i, sub in enumerate(sub_stages):
            sub_dir = f"stage_{i:03d}"
            save_model(sub, os.path.join(path, sub_dir))
            meta["stage_dirs"].append(sub_dir)
    extra, arrays = (
        stage._save_extra() if hasattr(stage, "_save_extra") else ({}, {})
    )
    # optional payloads (e.g. a Forest loaded from an old save without
    # gain/count) come through as None — omit rather than corrupt the npz
    arrays = {k: v for k, v in arrays.items() if v is not None}

    meta["params"] = params
    meta["extra"] = extra
    payload = payload_format()
    if arrays:
        meta["payload"] = payload
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, cls=_NpEncoder, indent=1)
    # re-saving over an old path must not leave the OTHER format's
    # payload behind (load follows meta["payload"], but a stale file is
    # still wrong on disk)
    import shutil

    npz_path = os.path.join(path, "data.npz")
    orbax_path = os.path.join(path, "data.orbax")
    if os.path.exists(npz_path) and not (arrays and payload == "npz"):
        os.remove(npz_path)
    if os.path.isdir(orbax_path) and not (arrays and payload == "orbax"):
        shutil.rmtree(orbax_path)
    if arrays:
        if payload == "orbax":
            _orbax_save(orbax_path, arrays)
        else:
            np.savez(npz_path, **arrays)
    return path


def payload_format() -> str:
    """Array-payload backend: ``npz`` (default — one portable file) or
    ``orbax`` (``SNTC_CHECKPOINT_FORMAT=orbax`` — the JAX-ecosystem
    checkpointer SURVEY.md §5.4 names; async-capable, sharding-aware,
    the right base for multi-host model dumps).  Loads auto-detect, so
    repos can mix formats freely."""
    fmt = os.environ.get("SNTC_CHECKPOINT_FORMAT", "npz")
    if fmt not in ("npz", "orbax"):
        raise ValueError(
            f"SNTC_CHECKPOINT_FORMAT={fmt!r}: expected 'npz' or 'orbax'"
        )
    return fmt


def _orbax_save(path: str, arrays: Dict[str, np.ndarray]) -> None:
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            os.path.abspath(path), dict(arrays), force=True
        )


def _orbax_load(path: str) -> Dict[str, np.ndarray]:
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        out = ckptr.restore(os.path.abspath(path))
    return {k: np.asarray(v) for k, v in out.items()}


def load_model(path: str) -> PipelineStage:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"unsupported model format {meta.get('format_version')}")
    cls = _resolve(meta["class"])
    params = meta.get("params", {})
    extra = meta.get("extra", {})
    npz_path = os.path.join(path, "data.npz")
    orbax_path = os.path.join(path, "data.orbax")
    arrays: Dict[str, np.ndarray] = {}
    payload = meta.get("payload")  # absent in pre-orbax saves: sniff
    if payload == "orbax" or (payload is None and os.path.isdir(orbax_path)):
        if os.path.isdir(orbax_path):
            arrays = _orbax_load(orbax_path)
    elif os.path.exists(npz_path):
        with np.load(npz_path) as z:
            arrays = {k: z[k] for k in z.files}

    if issubclass(cls, (Pipeline, PipelineModel)):
        stages = [
            load_model(os.path.join(path, d)) for d in meta.get("stage_dirs", [])
        ]
        obj = cls(stages=stages)
        obj.setParams(**params)
    elif hasattr(cls, "_from_sub_stages"):
        stages = [
            load_model(os.path.join(path, d)) for d in meta.get("stage_dirs", [])
        ]
        obj = cls._from_sub_stages(stages, params, extra)
    elif hasattr(cls, "_load_from"):
        obj = cls._load_from(params, extra, arrays)
    else:
        obj = cls()
        obj.setParams(**params)
    obj.uid = meta.get("uid", obj.uid)
    return obj
