"""Raw-capture stream source: capture files → keyed windows → feature
rows, with snapshot-at-commit crash safety.

:class:`FlowCaptureSource` plugs the stateful
:class:`~sntc_tpu.flow.engine.FlowFeatureEngine` into the micro-batch
engine as an ordinary :class:`~sntc_tpu.serve.streaming.StreamSource`:
the offset model is the capture-file count (exactly the
``NetFlowDirSource``/``PcapDirSource`` model), ``get_batch`` parses the
range's raw bytes (through the ``source.parse`` fault/corruption site),
feeds the records into the window operator, and returns the batch of
COMPLETED windows' CICIDS2017 feature rows — which then flow through
the unchanged serve path (admission → bucketed/fused predict → sink).

**The state contract.**  A stateful source must replay exactly: the
engine's WAL recovery re-issues uncommitted intents with their logged
ranges, so operator state must rewind to "as of the last commit".
Three hooks implement snapshot-at-commit:

* ``get_batch`` stages a post-consume state serialization keyed by the
  range's end offset (staging at READ time matters: in pipelined mode
  later batches may consume before this one commits);
* ``on_batch_committed`` (called by ``StreamingQuery`` BEFORE the WAL
  commit record is written) publishes the staged snapshot through
  :class:`~sntc_tpu.flow.state.FlowStateStore` — publish-then-commit
  means the retained snapshots always bracket the committed offset;
* ``on_restore`` (called at query construction with the recovered
  committed end) loads the exact-offset snapshot and rewinds the
  operator, after which WAL replay reconverges **bitwise** (emission
  is a pure function of state + consumed range).

Consumption is strictly ordered (ranges advance monotonically; a
same-range re-read — the engine's read-retry path — returns the
memoized batch without re-consuming).  A range skipped by load
shedding is allowed through: those packets are lost by the journaled
shed decision, not silently.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.flow.engine import (
    FlowFeatureEngine,
    NetFlowMeter,
    PcapFlowMeter,
)
from sntc_tpu.flow.state import FlowStateError, FlowStateStore
from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.obs.trace import span
from sntc_tpu.resilience import fault_point
from sntc_tpu.serve.netflow_source import (
    _CaptureDirSource,
    decode_pcap_packets,
)

#: capture format → default filename pattern
FORMATS = {"pcap": "*.pcap", "netflow": "*.nf5"}

_PKTS = "__records__"


class FlowCaptureSource(_CaptureDirSource):
    """Directory of capture files served as completed-window feature
    batches (module docstring has the protocol).  Parsing is stateless
    and rides the inherited listing-cache / parallel-read / prefetch /
    ``source.parse`` fault machinery (``_CaptureDirSource``); only
    consumption is ordered and stateful."""

    def __init__(
        self,
        path: str,
        format: str = "pcap",
        pattern: Optional[str] = None,
        flow_timeout: float = 120.0,
        activity_timeout: float = 5.0,
        allowed_lateness: float = 5.0,
        max_state_packets: int = 500_000,
        state_dir: Optional[str] = None,
        tenant: Optional[str] = None,
        **kwargs,
    ):
        if format not in FORMATS:
            raise ValueError(
                f"unknown capture format {format!r}; expected one of "
                f"{sorted(FORMATS)}"
            )
        # tenant forwards into DirStreamSource so the source-graph
        # meters (read/parse/stage) and the autotuner's knob gauges
        # carry this tenant's label from construction
        kwargs.setdefault("tenant", tenant)
        super().__init__(path, pattern or FORMATS[format], **kwargs)
        self.format = format
        meter = (
            PcapFlowMeter(flow_timeout=flow_timeout,
                          activity_timeout=activity_timeout)
            if format == "pcap"
            else NetFlowMeter(flow_timeout=flow_timeout)
        )
        self.engine = FlowFeatureEngine(
            meter,
            allowed_lateness=allowed_lateness,
            max_state_packets=max_state_packets,
            tenant=tenant,
        )
        self.tenant = tenant
        self._mlabels = {} if tenant is None else {"tenant": tenant}
        self.store = (
            FlowStateStore(state_dir, tenant=tenant)
            if state_dir is not None else None
        )
        self._consumed_end = 0
        self._memo: Optional[Tuple[Tuple[int, int], Frame]] = None
        # range whose records are folded into state but whose emission
        # has not completed yet: a failure between consume and the memo
        # (an eviction-pass fault, a transient in the meter emit) makes
        # the engine's retry re-enter — it must resume at the POLL,
        # never re-consume
        self._pending: Optional[Tuple[int, int]] = None
        self._staged_state: Dict[int, bytes] = {}
        # end offset of the last consumed-but-unpublished range: its
        # state serializes lazily — at commit when nothing was consumed
        # after it, or just-in-time before the NEXT consume overwrites
        # it (the pipelined read-ahead case)
        self._snapshot_due: Optional[int] = None
        self.snapshots_published = 0

    # -- parse (stateless; runs on reader/prefetch threads) ------------------

    def _decode_file(self, data: bytes) -> Frame:
        """Raw capture bytes → a packets Frame (one 2-D record-matrix
        column): decode policy shared with the per-file serving
        sources, metering deferred to the stateful engine."""
        if self.format == "netflow":
            from sntc_tpu.native import parse_stream

            return Frame({_PKTS: parse_stream(data)})
        return Frame({_PKTS: decode_pcap_packets(data)})

    # -- ordered stateful consumption ---------------------------------------

    def get_batch(self, start: int, end: int) -> Frame:
        if self._memo is not None and self._memo[0] == (start, end):
            # engine read-retry / deferred re-dispatch of the SAME
            # range: the records are already in state — hand back the
            # memoized emission instead of double-consuming
            return self._memo[1]
        if self._pending == (start, end):
            # the range's records are already folded in; the first
            # pass died between consume and the memo (eviction-pass
            # fault, meter transient): resume at the poll — never
            # re-consume.  poll() itself mutates nothing until the
            # meter emit succeeds, so re-polling is idempotent.
            emitted = self.engine.poll()
        else:
            if start < self._consumed_end:
                raise ValueError(
                    f"flow source consumed through offset "
                    f"{self._consumed_end} but was asked to re-read "
                    f"[{start}, {end}): stateful windows replay only "
                    "through the checkpoint's snapshot-at-commit "
                    "protocol"
                )
            frame = super().get_batch(start, end)
            records = np.asarray(frame[_PKTS])
            if self.store is not None and self._snapshot_due is not None:
                # the previous consumed range is still uncommitted
                # (pipelined read-ahead): capture its state before
                # this consume overwrites it; the serial path never
                # pays this — its snapshot serializes at commit from
                # the live state
                self._staged_state[self._snapshot_due] = (
                    self.engine.snapshot()
                )
                self._snapshot_due = None
            with span("flow.consume", records=int(records.shape[0])):
                self.engine.consume(records)
                # consumed, not yet emitted: a failure from here to
                # the memo re-enters through the _pending branch above
                self._pending = (start, end)
                emitted = self.engine.poll()
        # emission bookkeeping lands BEFORE the fault point: a raising
        # flow.emit fault — or the engine's read retry after any
        # later failure — re-enters through the memo and can never
        # double-consume; a KILL here still loses only in-memory
        # state (nothing durable yet)
        self._consumed_end = end
        if self.store is not None:
            self._snapshot_due = end
        self._memo = ((start, end), emitted)
        self._pending = None
        # kill point: windows emitted in memory, nothing durable yet
        # (chaos matrix "flow.emit" scenario)
        fault_point("flow.emit", tenant=self.tenant)
        return emitted

    # -- StreamingQuery state hooks -----------------------------------------

    def on_restore(self, committed_end: int) -> None:
        """Rewind operator state to the snapshot matching the WAL's
        committed end offset (query construction calls this before any
        replay)."""
        self._staged_state.clear()
        self._memo = None
        self._pending = None
        self._snapshot_due = None
        if self.store is None:
            if committed_end:
                raise FlowStateError(
                    f"checkpoint committed through offset "
                    f"{committed_end} but this FlowCaptureSource has "
                    "no state_dir: the operator state of the consumed "
                    "captures is unrecoverable (arm state_dir, or "
                    "start a fresh checkpoint)"
                )
            return
        payload = self.store.load(committed_end)
        if payload is None:
            if committed_end == 0:
                self._consumed_end = 0
                return
            raise FlowStateError(
                f"no flow-state snapshot for committed offset "
                f"{committed_end} under {self.store.path!r} (have "
                f"{self.store.ends()}): state and WAL have diverged"
            )
        self.engine.restore(payload)
        self._consumed_end = committed_end

    def on_batch_committed(self, batch_id: int, intent: dict) -> None:
        """Publish the committed batch's staged snapshot (called by
        the engine BEFORE the WAL commit record lands — the retained
        snapshots then always bracket the committed offset).  A range
        that quarantined mid-emission is first EXCISED from state."""
        end = int(intent["end"])
        if self._pending is not None and self._pending[1] <= end:
            # the batch is being committed with its records folded in
            # but its windows never emitted — a read-stage quarantine
            # after persistent poll failures.  Roll its consume back:
            # the dead letter owns the poison batch, keyed state must
            # not keep its packets (they would cascade the same
            # failing eviction set into every later batch's poll, and
            # the published snapshot must really be "state untouched
            # by the quarantined batch").
            self.engine.rollback_last_consume()
            self._pending = None
        if self.store is None:
            return
        payload = self._staged_state.pop(end, None)
        for stale in [k for k in self._staged_state if k <= end]:
            del self._staged_state[stale]
        if payload is None and self._snapshot_due == end:
            # nothing was consumed after this range (the serial-engine
            # common case): the live state IS its post-consume state
            payload = self.engine.snapshot()
        if self._snapshot_due is not None and self._snapshot_due <= end:
            self._snapshot_due = None
        if payload is None:
            # a batch that never completed get_batch (read-stage
            # quarantine) commits with state untouched by it; the
            # quarantine path only runs with nothing else in flight,
            # so the live state IS the committed state
            payload = self.engine.snapshot()
        with span("flow.snapshot", batch=batch_id):
            self.store.publish(end, payload)
        self.snapshots_published += 1
        inc("sntc_flow_snapshots_total", **self._mlabels)
        set_gauge("sntc_flow_state_bytes", len(payload), **self._mlabels)

    # -- operational surface -------------------------------------------------

    def flush_windows(self) -> Frame:
        """Force-emit every open window (end-of-stream flush for batch
        jobs/tests; a serving loop should NOT call this — open windows
        belong in state across restarts)."""
        return self.engine.poll(force=True)

    def flow_stats(self) -> dict:
        """Operator evidence (state size, watermark, eviction/late
        counters, snapshots) for status dumps and bench journals."""
        return dict(
            self.engine.stats(),
            snapshots_published=self.snapshots_published,
            consumed_end=self._consumed_end,
        )
