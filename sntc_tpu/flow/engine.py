"""Stateful keyed-window flow metering — the Structured-Streaming
stateful-operator analog over raw captures [B:11].

The batch path (``native/pcap.py``/``native/netflow.py``) meters a
WHOLE capture at once, so a flow split across two micro-batch files
would emit as two half-windows.  :class:`FlowFeatureEngine` closes that
gap: it buffers the packet/datagram records the native parsers produce
per bidirectional 5-tuple key, advances an event-time **watermark**
(``max event ts seen − allowed_lateness``), and emits a flow's
CICIDS2017 feature row only once the watermark proves the window
COMPLETE — no admissible future record can extend it (a session window
closes when ``watermark − last_ts > flow_timeout``: any record the
lateness policy would still accept has ``ts ≥ watermark``, whose gap to
the window exceeds the session timeout and therefore starts a NEW
window).  Records inside the lateness bound may arrive out of order
(counted, reordered at emit); records behind the watermark are dropped
with the reason code ``late_record`` — the PR-5 admission discipline
applied to event time.

Feature math is NOT reimplemented here: emission concatenates the
completed windows' raw records and defers to the same hardened meters
the batch path uses (``packets_to_flow_frame`` — one lexsort +
segment-reduction pass, the sufficient-statistic discipline
``partial_fit`` shares — and a record-merge + ``netflow_to_flow_frame``
for NetFlow), so windowed serving and whole-capture metering can never
drift apart and golden tests pin one implementation.

State is a first-class crash-safety citizen: :meth:`snapshot` /
:meth:`restore` round-trip the operator state bytes the
:class:`~sntc_tpu.flow.state.FlowStateStore` persists at every engine
commit (see ``sntc_tpu/flow/source.py`` for the snapshot-at-commit
protocol), and emission is a pure function of (state, consumed range) —
WAL replay from the last checkpoint reproduces the same windows
**bitwise**.  Watermark eviction plus the ``max_state_packets`` cap
bound state size under arbitrary out-of-order replay (``state_cap``
evictions force the oldest flows out early; documented best-effort
splits).  Everything surfaces as catalogued ``sntc_flow_*`` metrics
(docs/OBSERVABILITY.md) and structured events.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from sntc_tpu.core.frame import Frame
from sntc_tpu.native import (
    NF5_FIELDS,
    PCAP_FIELDS,
    netflow_to_flow_frame,
    packets_to_flow_frame,
)
from sntc_tpu.obs.metrics import inc, set_gauge
from sntc_tpu.resilience import emit_event, fault_point

# NF5 column indexes the NetFlow meter reads (sntc_tpu/native/netflow.py
# NF5_FIELD_NAMES order)
_NF_SRC, _NF_DST, _NF_SPORT, _NF_DPORT, _NF_PROTO = 0, 1, 2, 3, 4
_NF_FLAGS, _NF_PKTS, _NF_OCTETS = 5, 7, 8
_NF_FIRST, _NF_LAST, _NF_DUR = 9, 10, 15


class PcapFlowMeter:
    """Keying + emission over pcap packet rows (``[n, PCAP_FIELDS]``).

    The key is the bidirectional (order-free) 5-tuple — the same
    ``lo/hi`` endpoint canonicalization ``packets_to_flow_frame`` sorts
    by — and emission IS ``packets_to_flow_frame``, so a window the
    engine completes carries byte-for-byte the features the batch
    meter would compute from the same packets."""

    n_fields = PCAP_FIELDS

    def __init__(self, flow_timeout: float = 120.0,
                 activity_timeout: float = 5.0):
        self.flow_timeout = float(flow_timeout)
        self.activity_timeout = float(activity_timeout)

    def key_columns(self, records: np.ndarray) -> np.ndarray:
        src = records[:, 1].astype(np.int64)
        dst = records[:, 2].astype(np.int64)
        sport = records[:, 3].astype(np.int64)
        dport = records[:, 4].astype(np.int64)
        proto = records[:, 5].astype(np.int64)
        ep_a = src * 65536 + sport
        ep_b = dst * 65536 + dport
        return np.stack(
            [np.minimum(ep_a, ep_b), np.maximum(ep_a, ep_b), proto],
            axis=1,
        )

    def event_ts(self, records: np.ndarray) -> np.ndarray:
        return records[:, 0].astype(np.float64)

    def emit(self, records: np.ndarray) -> Frame:
        return packets_to_flow_frame(
            records,
            flow_timeout=self.flow_timeout,
            activity_timeout=self.activity_timeout,
        )


class NetFlowMeter:
    """Keying + emission over NetFlow v5 records (``[n, NF5_FIELDS]``).

    NetFlow is unidirectional, so the key is the DIRECTIONAL 5-tuple
    (as the exporter reports it).  Emission merges each completed
    window's records — packets/octets sum, flags OR, first/last
    min/max — into one record and lifts it through
    ``netflow_to_flow_frame``, i.e. a long flow the exporter cut into
    several records re-aggregates into one feature row.  Event time is
    the record's ``first`` timestamp (exporter sysuptime clock, ms →
    seconds); ``flow_timeout``/lateness are read on that clock."""

    n_fields = NF5_FIELDS

    def __init__(self, flow_timeout: float = 120.0):
        self.flow_timeout = float(flow_timeout)

    def key_columns(self, records: np.ndarray) -> np.ndarray:
        src = records[:, _NF_SRC].astype(np.int64)
        dst = records[:, _NF_DST].astype(np.int64)
        sport = records[:, _NF_SPORT].astype(np.int64)
        dport = records[:, _NF_DPORT].astype(np.int64)
        proto = records[:, _NF_PROTO].astype(np.int64)
        return np.stack(
            [src * 65536 + sport, dst * 65536 + dport, proto], axis=1
        )

    def event_ts(self, records: np.ndarray) -> np.ndarray:
        return records[:, _NF_FIRST].astype(np.float64) / 1000.0

    def emit(self, records: np.ndarray) -> Frame:
        n = records.shape[0]
        if n == 0:
            return netflow_to_flow_frame(
                np.zeros((0, NF5_FIELDS), np.float64)
            )
        ts = self.event_ts(records)
        keys = self.key_columns(records)
        order = np.lexsort((ts, keys[:, 2], keys[:, 1], keys[:, 0]))
        r = records[order]
        k = keys[order]
        t = ts[order]
        new_key = np.empty(n, bool)
        new_key[0] = True
        new_key[1:] = (k[1:] != k[:-1]).any(axis=1)
        gap = np.empty(n, np.float64)
        gap[0] = 0.0
        gap[1:] = t[1:] - t[:-1]
        new_flow = new_key | (gap > self.flow_timeout)
        starts = np.flatnonzero(new_flow)
        merged = r[starts].copy()  # first record carries the identity
        iflags = r[:, _NF_FLAGS].astype(np.int64)
        merged[:, _NF_PKTS] = np.add.reduceat(r[:, _NF_PKTS], starts)
        merged[:, _NF_OCTETS] = np.add.reduceat(r[:, _NF_OCTETS], starts)
        merged[:, _NF_FLAGS] = np.bitwise_or.reduceat(iflags, starts)
        merged[:, _NF_FIRST] = np.minimum.reduceat(r[:, _NF_FIRST], starts)
        merged[:, _NF_LAST] = np.maximum.reduceat(r[:, _NF_LAST], starts)
        merged[:, _NF_DUR] = np.maximum(
            merged[:, _NF_LAST] - merged[:, _NF_FIRST], 0
        )
        return netflow_to_flow_frame(merged)


class _FlowState:
    """One key's buffered window: record chunks in arrival order plus
    the first/last event timestamps (the eviction clock)."""

    __slots__ = ("chunks", "first_ts", "last_ts", "n")

    def __init__(self):
        self.chunks: List[np.ndarray] = []
        self.first_ts = float("inf")
        self.last_ts = float("-inf")
        self.n = 0


class FlowFeatureEngine:
    """The keyed session-window operator (module docstring has the
    semantics).  ``consume`` is atomic w.r.t. exceptions — grouping is
    computed before any state mutates — so an engine-level read retry
    that re-enters with the same records can never double-count."""

    def __init__(
        self,
        meter,
        allowed_lateness: float = 5.0,
        max_state_packets: int = 500_000,
        tenant: Optional[str] = None,
    ):
        if allowed_lateness < 0:
            raise ValueError("allowed_lateness must be >= 0")
        if max_state_packets < 1:
            raise ValueError("max_state_packets must be >= 1")
        self.meter = meter
        self.allowed_lateness = float(allowed_lateness)
        self.max_state_packets = int(max_state_packets)
        self.tenant = tenant
        self._mlabels = {} if tenant is None else {"tenant": tenant}
        self._flows: Dict[Tuple[int, ...], _FlowState] = {}
        self._max_ts: Optional[float] = None
        self._packets = 0  # buffered records across all flows
        self.records_consumed = 0
        self.late_records = 0
        self.out_of_order = 0
        self.windows_emitted = 0
        self.evictions: Dict[str, int] = {}
        # undo record for the MOST RECENT consume (per-key previous
        # first/last timestamps, prior clock + counters): lets the
        # source excise a quarantined batch whose records folded in
        # but whose windows never emitted (rollback_last_consume)
        self._last_undo: Optional[dict] = None

    # -- event-time bookkeeping ---------------------------------------------

    def watermark(self) -> Optional[float]:
        """``max event ts seen − allowed_lateness`` (None before any
        record): records behind it are late, windows idle more than
        ``flow_timeout`` behind it are complete."""
        if self._max_ts is None:
            return None
        return self._max_ts - self.allowed_lateness

    def state_size(self) -> Dict[str, int]:
        return {"flows": len(self._flows), "packets": self._packets}

    def _publish_gauges(self) -> None:
        set_gauge("sntc_flow_active_flows", len(self._flows),
                  **self._mlabels)
        set_gauge("sntc_flow_state_packets", self._packets,
                  **self._mlabels)

    # -- consume -------------------------------------------------------------

    def consume(self, records: np.ndarray) -> Dict[str, int]:
        """Fold one micro-batch of parser records into the keyed state;
        returns ``{accepted, late, out_of_order}`` for the batch."""
        records = np.asarray(records, np.float64)
        stats = {"accepted": 0, "late": 0, "out_of_order": 0}
        undo = {
            "max_ts": self._max_ts,
            "counters": (self.records_consumed, self.late_records,
                         self.out_of_order),
            "keys": [],
        }
        if records.shape[0]:
            ts = self.meter.event_ts(records)
            wm = self.watermark()
            if wm is not None:
                late = ts < wm
            else:
                late = np.zeros(records.shape[0], bool)
            keep = ~late
            n_late = int(np.count_nonzero(late))
            n_ooo = (
                0 if self._max_ts is None
                else int(np.count_nonzero(keep & (ts < self._max_ts)))
            )
            records, ts = records[keep], ts[keep]
            stats["late"] = n_late
            stats["out_of_order"] = n_ooo
            if n_late:
                self.late_records += n_late
                inc("sntc_flow_late_records_total", n_late,
                    **self._mlabels)
                emit_event(
                    event="flow_late_records", site="flow.emit",
                    reason="late_record", count=n_late,
                    watermark=wm,
                    **({} if self.tenant is None
                       else {"tenant": self.tenant}),
                )
            if n_ooo:
                self.out_of_order += n_ooo
                inc("sntc_flow_out_of_order_total", n_ooo,
                    **self._mlabels)
        if records.shape[0]:
            # grouping is computed in FULL before any mutation, then
            # applied in a plain append pass that cannot realistically
            # raise — a retry that re-enters after a downstream failure
            # must never find half a batch folded in
            keys = self.meter.key_columns(records)
            uniq, inv = np.unique(keys, axis=0, return_inverse=True)
            order = np.argsort(inv, kind="stable")
            bounds = np.searchsorted(inv[order], np.arange(len(uniq)))
            bounds = np.append(bounds, len(order))
            staged = []
            for j in range(len(uniq)):
                sel = order[bounds[j]:bounds[j + 1]]
                seg_ts = ts[sel]
                staged.append((
                    tuple(int(v) for v in uniq[j]), records[sel],
                    float(seg_ts.min()), float(seg_ts.max()),
                ))
            self._max_ts = (
                float(ts.max()) if self._max_ts is None
                else max(self._max_ts, float(ts.max()))
            )
            for key, chunk, tmin, tmax in staged:
                st = self._flows.get(key)
                undo["keys"].append((
                    key,
                    None if st is None else (st.first_ts, st.last_ts),
                ))
                if st is None:
                    st = self._flows[key] = _FlowState()
                st.chunks.append(chunk)
                st.n += len(chunk)
                st.first_ts = min(st.first_ts, tmin)
                st.last_ts = max(st.last_ts, tmax)
            self._packets += records.shape[0]
            stats["accepted"] = int(records.shape[0])
            self.records_consumed += stats["accepted"]
            inc("sntc_flow_records_consumed_total", stats["accepted"],
                **self._mlabels)
        self._last_undo = undo
        self._publish_gauges()
        return stats

    def rollback_last_consume(self) -> bool:
        """Excise the most recent consume's records from keyed state
        (a quarantined batch whose windows never emitted must not
        poison later batches' polls or the committed snapshot).  Only
        valid while no poll has SUCCEEDED since that consume — poll
        mutates nothing on failure, so the state delta is exactly the
        consume's per-key appends.  Returns True when rolled back."""
        undo = self._last_undo
        if undo is None:
            return False
        for key, prev in undo["keys"]:
            st = self._flows.get(key)
            if st is None or not st.chunks:  # pragma: no cover
                continue
            chunk = st.chunks.pop()
            st.n -= len(chunk)
            self._packets -= len(chunk)
            if prev is None or st.n == 0:
                del self._flows[key]
            else:
                st.first_ts, st.last_ts = prev
        self._max_ts = undo["max_ts"]
        (self.records_consumed, self.late_records,
         self.out_of_order) = undo["counters"]
        self._last_undo = None
        self._publish_gauges()
        return True

    # -- eviction / emission -------------------------------------------------

    def _complete_keys(self) -> List[Tuple[int, ...]]:
        wm = self.watermark()
        if wm is None:
            return []
        bound = wm - self.meter.flow_timeout
        return [k for k, st in self._flows.items() if st.last_ts < bound]

    def poll(self, force: bool = False) -> Frame:
        """Evict every completed window (plus the oldest flows while
        the ``max_state_packets`` cap is exceeded; everything with
        ``force=True`` — the explicit flush) and emit their feature
        rows as one Frame.  Deterministic given (state, watermark):
        the WAL-replay convergence contract rests on it."""
        evicted: List[Tuple[Tuple[int, ...], str]] = []
        if force:
            evicted = [(k, "flush") for k in self._flows]
        else:
            evicted = [(k, "watermark") for k in self._complete_keys()]
            remaining = self._packets - sum(
                self._flows[k].n for k, _ in evicted
            )
            if remaining > self.max_state_packets:
                # cap pressure: force the oldest still-open flows out
                # early (their window splits — documented best-effort)
                open_keys = sorted(
                    (k for k in self._flows
                     if k not in {e[0] for e in evicted}),
                    key=lambda k: (self._flows[k].last_ts, k),
                )
                for k in open_keys:
                    if remaining <= self.max_state_packets:
                        break
                    remaining -= self._flows[k].n
                    evicted.append((k, "state_cap"))
        if not evicted:
            self._publish_gauges()
            return self.meter.emit(
                np.zeros((0, self.meter.n_fields), np.float64)
            )
        # kill point: state selected for eviction but nothing removed
        # or emitted yet (chaos matrix "flow.evict" scenario)
        fault_point("flow.evict", tenant=self.tenant)
        # deterministic emission order: windows sorted by (first_ts,
        # key); the meter's own lexsort is stable on top of this
        evicted.sort(key=lambda e: (self._flows[e[0]].first_ts, e[0]))
        parts: List[np.ndarray] = []
        for key, _reason in evicted:
            parts.extend(self._flows[key].chunks)
        # emit BEFORE any state mutates: a failure here (injected or
        # real) leaves the engine exactly as it was, so the caller's
        # retry re-polls the same eviction set instead of losing it
        frame = self.meter.emit(np.concatenate(parts, axis=0))
        reasons: Dict[str, int] = {}
        for key, reason in evicted:
            st = self._flows.pop(key)
            self._packets -= st.n
            reasons[reason] = reasons.get(reason, 0) + 1
        self.windows_emitted += frame.num_rows
        inc("sntc_flow_windows_emitted_total", frame.num_rows,
            **self._mlabels)
        for reason, count in sorted(reasons.items()):
            self.evictions[reason] = self.evictions.get(reason, 0) + count
            inc("sntc_flow_evictions_total", count, reason=reason,
                **self._mlabels)
        emit_event(
            event="flow_windows_emitted", site="flow.evict",
            windows=frame.num_rows, flows_evicted=len(evicted),
            reasons=reasons, watermark=self.watermark(),
            **({} if self.tenant is None else {"tenant": self.tenant}),
        )
        self._publish_gauges()
        return frame

    # -- snapshot / restore --------------------------------------------------

    def snapshot(self) -> bytes:
        """Serialize the operator state (flows in sorted-key order,
        each flow's records in arrival order, plus the watermark clock
        and counters).  ``restore(snapshot())`` followed by the same
        consume/poll sequence reproduces the same emissions bitwise —
        the property the snapshot-at-commit protocol needs."""
        import io
        import json

        keys = sorted(self._flows)
        counts = np.asarray(
            [self._flows[k].n for k in keys], np.int64
        ).reshape(-1)
        key_arr = (
            np.asarray(keys, np.int64)
            if keys else np.zeros((0, 3), np.int64)
        )
        if keys:
            records = np.concatenate(
                [c for k in keys for c in self._flows[k].chunks], axis=0
            )
        else:
            records = np.zeros((0, self.meter.n_fields), np.float64)
        buf = io.BytesIO()
        np.savez(buf, keys=key_arr, counts=counts, records=records)
        header = {
            "version": 1,
            "max_ts": self._max_ts,
            "records_consumed": self.records_consumed,
            "late_records": self.late_records,
            "out_of_order": self.out_of_order,
            "windows_emitted": self.windows_emitted,
            "evictions": self.evictions,
            "n_flows": len(keys),
        }
        return json.dumps(header).encode() + b"\n" + buf.getvalue()

    def restore(self, payload: bytes) -> None:
        import io
        import json

        head, _, body = payload.partition(b"\n")
        header = json.loads(head.decode())
        if header.get("version") != 1:
            raise ValueError(
                f"unsupported flow-state version {header.get('version')}"
            )
        with np.load(io.BytesIO(body)) as z:
            keys, counts, records = z["keys"], z["counts"], z["records"]
        self._flows = {}
        self._packets = 0
        off = 0
        for i in range(keys.shape[0]):
            n = int(counts[i])
            st = _FlowState()
            chunk = records[off:off + n].copy()
            st.chunks = [chunk]
            st.n = n
            seg_ts = self.meter.event_ts(chunk)
            st.first_ts = float(seg_ts.min())
            st.last_ts = float(seg_ts.max())
            self._flows[tuple(int(v) for v in keys[i])] = st
            self._packets += n
            off += n
        self._max_ts = header["max_ts"]
        self._last_undo = None
        self.records_consumed = header["records_consumed"]
        self.late_records = header["late_records"]
        self.out_of_order = header["out_of_order"]
        self.windows_emitted = header["windows_emitted"]
        self.evictions = dict(header["evictions"])
        self._publish_gauges()

    def stats(self) -> Dict[str, object]:
        """Operator evidence for status dumps / bench journals."""
        return {
            "records_consumed": self.records_consumed,
            "late_records": self.late_records,
            "out_of_order": self.out_of_order,
            "windows_emitted": self.windows_emitted,
            "evictions": dict(self.evictions),
            "watermark": self.watermark(),
            **self.state_size(),
        }
