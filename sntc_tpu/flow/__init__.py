"""Stateful flow-feature engine: crash-safe keyed session windows from
raw captures to CICIDS2017 feature rows (ROADMAP item 4, [B:11]).

- :class:`FlowFeatureEngine` — the keyed window operator (watermarks,
  late/out-of-order policy, bounded state, snapshot/restore);
- :class:`PcapFlowMeter` / :class:`NetFlowMeter` — keying + emission
  over the native parsers' record matrices (emission defers to the
  hardened batch meters, so windowed and whole-capture features can
  never drift);
- :class:`FlowCaptureSource` — the ``StreamSource`` adapter opening
  end-to-end raw-capture → features → classify serving
  (``python -m sntc_tpu serve --from-capture pcap ...``);
- :class:`FlowStateStore` — snapshot-at-commit persistence under the
  PR-1 atomic-publish + sha256 discipline.

See docs/RESILIENCE.md "Stateful flow windows".
"""

from sntc_tpu.flow.engine import (
    FlowFeatureEngine,
    NetFlowMeter,
    PcapFlowMeter,
)
from sntc_tpu.flow.source import FORMATS, FlowCaptureSource
from sntc_tpu.flow.state import (
    FlowStateCorruptError,
    FlowStateError,
    FlowStateStore,
)

__all__ = [
    "FlowFeatureEngine",
    "PcapFlowMeter",
    "NetFlowMeter",
    "FlowCaptureSource",
    "FORMATS",
    "FlowStateStore",
    "FlowStateError",
    "FlowStateCorruptError",
]
