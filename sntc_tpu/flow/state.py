"""Crash-safe persistence for flow-operator state snapshots.

The snapshot-at-commit protocol (``sntc_tpu/flow/source.py``) publishes
one state blob per committed micro-batch, named by the batch's END
offset.  Each publish follows the PR-1 ``save_model`` discipline:
write to a temp file, fsync, rename into place, fsync the directory —
so a crash (or an armed ``flow.state_snapshot`` kill) never leaves a
torn snapshot visible — and every blob seals its payload with a sha256
digest verified on load.  The store retains the last ``keep``
snapshots, which is what makes restore unambiguous: publishes happen
in commit order, exactly one publish can land between two commits, so
the retained snapshots always bracket the engine's committed offset
and ``load(committed_end)`` finds an exact match (or offset 0, the
fresh-state case).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
from typing import List, Optional

from sntc_tpu.resilience import fault_point
from sntc_tpu.resilience.storage import atomic_write_bytes

_MAGIC = b"SNTCFLOW1\n"
_NAME_RE = re.compile(r"state-(\d{12})\.bin$")


def verify_snapshot(path: str, end: Optional[int] = None) -> bytes:
    """Verify one snapshot blob's integrity (magic, header, payload
    length, sha256) and return the payload.  ``end`` additionally pins
    the header's offset against the expected one.  Shared by
    :meth:`FlowStateStore.load` and the ``sntc fsck`` doctor, so the
    two can never disagree about what 'corrupt' means."""
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MAGIC):
        raise FlowStateCorruptError(
            f"flow-state snapshot {path}: bad magic"
        )
    head, _, payload = blob[len(_MAGIC):].partition(b"\n")
    try:
        header = json.loads(head.decode())
    except ValueError as e:
        raise FlowStateCorruptError(
            f"flow-state snapshot {path}: unreadable header ({e})"
        ) from e
    if end is not None and header.get("end") != int(end):
        raise FlowStateCorruptError(
            f"flow-state snapshot {path}: header names offset "
            f"{header.get('end')}, file names {end}"
        )
    if len(payload) != header.get("bytes"):
        raise FlowStateCorruptError(
            f"flow-state snapshot {path}: {len(payload)} payload "
            f"bytes, header says {header.get('bytes')} (torn write)"
        )
    got = hashlib.sha256(payload).hexdigest()
    if got != header.get("sha256"):
        raise FlowStateCorruptError(
            f"flow-state snapshot {path}: sha256 mismatch "
            f"(expected {str(header.get('sha256'))[:12]}…, got "
            f"{got[:12]}…)"
        )
    return payload


class FlowStateError(RuntimeError):
    """Operator state cannot be reconciled with the checkpoint's
    committed offset (missing snapshot for a nonzero offset)."""


class FlowStateCorruptError(FlowStateError):
    """A snapshot file fails its integrity check (bad magic, torn
    payload, sha256 mismatch) — names the offending file."""


class FlowStateStore:
    """One directory of ``state-<end>.bin`` snapshot blobs.

    ``tenant`` namespaces the ``flow.state_snapshot`` fault point
    (``tenant/<id>/flow.state_snapshot``) so multi-tenant chaos can
    kill one tenant's snapshot publish without touching neighbors."""

    def __init__(self, path: str, keep: int = 2,
                 tenant: Optional[str] = None):
        if keep < 2:
            # fewer than 2 breaks the publish/commit bracketing: a
            # crash between snapshot publish and WAL commit must still
            # find the previous offset's snapshot on restart
            raise ValueError("FlowStateStore keep must be >= 2")
        self.path = path
        self.keep = int(keep)
        self.tenant = tenant
        os.makedirs(path, exist_ok=True)

    def _file(self, end: int) -> str:
        return os.path.join(self.path, f"state-{end:012d}.bin")

    def ends(self) -> List[int]:
        out = []
        for p in glob.glob(os.path.join(self.path, "state-*.bin")):
            m = _NAME_RE.search(p)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def publish(self, end: int, payload: bytes) -> str:
        """Atomically publish the snapshot for committed offset
        ``end`` (idempotent: a WAL replay republishes byte-equivalent
        state over the same name), then prune beyond ``keep``."""
        # kill point: the snapshot is serialized but nothing reached
        # disk (chaos matrix "flow.state_snapshot" scenario)
        fault_point("flow.state_snapshot", tenant=self.tenant)
        header = json.dumps({
            "version": 1,
            "end": int(end),
            "bytes": len(payload),
            "sha256": hashlib.sha256(payload).hexdigest(),
        }).encode()
        final = self._file(end)
        # the physical write routes through the storage plane's atomic
        # publish: the ``storage.state`` fault_disk site injects
        # ENOSPC/torn-write there, and the failure POLICY is "fail" —
        # the error propagates into the engine's commit hook, whose
        # retry/quarantine machinery owns the consequence (a snapshot
        # that silently degraded would break restore bracketing)
        atomic_write_bytes(
            final, _MAGIC + header + b"\n" + payload,
            site="storage.state", tenant=self.tenant,
        )
        for old in self.ends()[:-self.keep]:
            try:
                os.unlink(self._file(old))
            except OSError:
                pass
        return final

    def load(self, end: int) -> Optional[bytes]:
        """The verified payload for offset ``end``, or None when no
        snapshot with that offset exists."""
        path = self._file(end)
        if not os.path.exists(path):
            return None
        return verify_snapshot(path, end)
